"""Cluster runner: coordinator scheduling fragments onto worker nodes.

The coordinator half of the multi-host runtime (reference
presto-main/.../execution/scheduler/SqlQueryScheduler.java:112,281,533
stage tree + task launch; server/remotetask/HttpRemoteTask.java:100
task lifecycle over HTTP; execution/SqlStageExecution.java). The SPMD
mesh path (exec/distributed.py) is the ICI story — one process, XLA
collectives; this is the DCN story — independent worker processes, each
owning a device, exchanging pages over HTTP.

Scheduling model (reference NodeScheduler/UniformNodeSelector
simplified to uniform assignment):

- ``source`` fragments: splits round-robin over ACTIVE workers, one
  task per worker that received splits;
- ``fixed`` fragments: one task on every active worker, input pages
  hash-routed by the producer (buffer index = consumer partition);
- ``single`` fragments: one task on the least-loaded worker.

Failure handling (reference failuredetector/HeartbeatFailureDetector +
execution/scheduler retry; Presto's fault-tolerant execution spooled
the same way our ``retain=True`` output buffers do):

- a background heartbeat pings ``/v1/info``; nodes failing
  ``max_consecutive`` pings are excluded from scheduling;
- ``retry_policy=TASK`` (default): a FAILED task or a task lost with
  its worker is re-created (same deterministic fragment + splits, new
  attempt id) on a healthy node with exponential backoff, bounded by
  ``task_retry_attempts``; every transitive downstream consumer is
  re-created too, re-reading retained upstream buffers from token 0 —
  so one socket blip or one dead host costs a partial re-run, not the
  query;
- ``retry_policy=QUERY``: any task failure re-plans and re-runs the
  whole query (``query_retry_attempts`` times);
- ``retry_policy=NONE``: fail fast (the pre-fault-tolerance behavior);
- speculative execution: a task the ``StageMonitor`` flags as a
  straggler gets a duplicate attempt on another node;
  first-finished-wins and the loser is aborted (attempt-id-versioned
  buffers make duplicate rows impossible by construction);
- drain-aware scheduling: nodes reporting ``SHUTTING_DOWN`` (worker
  graceful shutdown, ``PUT /v1/info/state``) finish their running
  tasks but receive no new ones;
- ``query_max_run_time``: a coordinator-side deadline that DELETE-
  aborts every task of the query on expiry.
"""
from __future__ import annotations

import json
import re
import statistics
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Set, Tuple

from ..connectors.spi import Split
from ..obs.log import LOG
from ..obs.metrics import NODES, REGISTRY, TASKS
from ..obs.trace import TRACER
from ..planner import codec
from ..planner.fragmenter import (
    FragmentedPlan, OutputSpec, PlanFragment, fragment_plan,
)
from ..planner.plan import PlanNode, RemoteSourceNode, TableScanNode
from .failpoints import FAILPOINTS
from .local import QueryResult
from .runner import LocalRunner


class QueryFailedError(RuntimeError):
    pass


class _QueryRetry(Exception):
    """Internal: ``retry_policy=QUERY`` requested a whole-query rerun."""


#: duration strings accepted by ``query_max_run_time`` (reference
#: io.airlift.units.Duration): bare numbers are seconds
_DURATION_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(ms|s|m|h)?\s*$")
_DURATION_UNITS = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, None: 1.0}


def parse_duration_s(value) -> Optional[float]:
    """'500ms' | '30s' | '5m' | '2h' | 12.5 -> seconds; None/'' -> None."""
    if value is None or value == "":
        return None
    if isinstance(value, (int, float)):
        return float(value)
    m = _DURATION_RE.match(str(value))
    if m is None:
        raise ValueError(f"bad duration {value!r} (want e.g. 30s, 500ms)")
    return float(m.group(1)) * _DURATION_UNITS[m.group(2)]


def _retry_policy(session) -> str:
    p = str(session.properties.get("retry_policy", "TASK")).upper()
    if p not in ("TASK", "QUERY", "NONE"):
        raise ValueError(
            f"retry_policy must be TASK, QUERY or NONE, got {p!r}")
    return p


class HeartbeatFailureDetector:
    """Marks workers dead after consecutive failed pings (reference
    failuredetector/HeartbeatFailureDetector.java:77,360 — the
    exponential-decay rate collapsed to a consecutive-failure budget)."""

    def __init__(self, urls, interval_s: float = 5.0,
                 max_consecutive: int = 3, on_info=None):
        # ``urls`` may be a static list or a zero-arg callable returning
        # the current membership (discovery-fed, reference
        # DiscoveryNodeManager feeding the failure detector)
        self._source = urls if callable(urls) else (lambda: list(urls))
        self.interval_s = interval_s
        self.max_consecutive = max_consecutive
        self.failures: Dict[str, int] = {}
        #: optional ``(url, info_doc)`` callback on every successful
        #: ping — the heartbeat doubles as the node-state federator feed
        self.on_info = on_info
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    @property
    def urls(self) -> List[str]:
        return list(self._source())

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # bounded join (the loop notices _stop within one interval;
        # the in-flight ping holds it at most its 5s timeout): a
        # heartbeat that outlives its runner keeps writing the node
        # registry through teardown (locks/unjoined-thread)
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)

    def ping(self, url: str) -> Optional[dict]:
        """The worker's ``/v1/info`` doc on success (always truthy),
        None on failure."""
        try:
            # failpoint: simulate a missed heartbeat (FailpointError
            # falls into the generic failure path below)
            FAILPOINTS.hit("heartbeat.ping", key=url)
            with urllib.request.urlopen(f"{url}/v1/info",
                                        timeout=5) as resp:
                return json.loads(resp.read()) or {"state": "ACTIVE"}
        except Exception:
            return None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            for u in self.urls:
                info = self.ping(u)
                if info is not None:
                    self.failures[u] = 0
                    if self.on_info is not None:
                        self.on_info(u, info)
                else:
                    self.failures[u] = self.failures.get(u, 0) + 1

    def active(self) -> List[str]:
        return [u for u in self.urls
                if self.failures.get(u, 0) < self.max_consecutive]


class ClusterMemoryManager:
    """Coordinator-side memory guard (reference
    memory/ClusterMemoryManager.java + TotalReservationLowMemoryKiller):
    polls workers' heartbeat memory payloads; while the cluster-wide
    reservation exceeds ``limit_bytes``, kills the query holding the
    most memory (DELETE /v1/query/{id} on every worker)."""

    def __init__(self, runner: "ClusterRunner", limit_bytes: int,
                 interval_s: float = 0.5):
        self.runner = runner
        self.limit = limit_bytes
        self.interval_s = interval_s
        self.killed: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # join like the failure detector: the kill loop must not issue
        # DELETEs against a runner that already tore down its workers
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)

    def poll_once(self) -> Dict[str, int]:
        totals: Dict[str, int] = {}
        for url in self.runner.detector.active():
            try:
                # single attempt, short timeout: the next 0.5s poll is
                # the retry, and enforcement must not stall on a worker
                # the failure detector hasn't evicted yet
                info = self.runner._request(f"{url}/v1/info",
                                            retries=0, timeout=5)
            except Exception:
                continue
            for qid, b in info.get("queryMemory", {}).items():
                totals[qid] = totals.get(qid, 0) + int(b)
        return totals

    def enforce(self, totals: Dict[str, int]) -> None:
        live = {q: b for q, b in totals.items() if q not in self.killed}
        if not live or sum(live.values()) <= self.limit:
            return
        victim = max(live, key=live.get)
        self.killed[victim] = live[victim]
        LOG.log("query_killed_low_memory", query_id=victim,
                reserved_bytes=live[victim], limit_bytes=self.limit)
        for url in list(self.runner.worker_urls):
            try:
                self.runner._request(f"{url}/v1/query/{victim}",
                                     method="DELETE", retries=0,
                                     timeout=5)
            except Exception:
                continue

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.enforce(self.poll_once())


_STRAGGLERS_DETECTED = REGISTRY.counter("straggler_detected_total")
_SKEWED_STAGES = REGISTRY.counter("skewed_stage_total")
_TASK_RETRIES = REGISTRY.counter("task_retry_total")
_QUERY_RETRIES = REGISTRY.counter("query_retry_total")
_SPEC_LAUNCHED = REGISTRY.counter("speculative_launched_total")
_SPEC_WON = REGISTRY.counter("speculative_won_total")
_NODES_DRAINED = REGISTRY.counter("node_drained_total")
_NODES_JOINED = REGISTRY.counter("node_joined_total")
_SPOOL_REPLAYED = REGISTRY.counter("spool_replayed_task_total")


class StageMonitor:
    """Coordinator-side progress + straggler/skew detection over task
    status docs (the role of the reference's SqlStageExecution task
    stats aggregation feeding the low-memory killer and the webapp's
    stage timelines; see tf.data's production straggler story for why
    this must be always-on, not a profiling mode).

    Fed by the status polls the collector already makes: per stage it
    tracks completion progress, flags a task as a straggler when its
    elapsed time exceeds ``straggler_ratio`` x the median of the
    stage's OTHER tasks (median-of-others keeps a 2-task stage
    flaggable), and flags a stage as skewed when its max per-partition
    output row count exceeds ``skew_ratio`` x the stage median (the
    mean is useless here: max/mean is bounded by the task count, so a
    3-task stage could never cross a 4x threshold). Findings
    land in the shared TaskRegistry (``system.runtime.tasks`` columns
    ``straggler``/``skew_ratio``), in counters
    (``straggler_detected_total``/``skewed_stage_total``) so tests can
    assert regressions, and in the structured log."""

    straggler_ratio = 3.0
    min_elapsed_ms = 25.0
    skew_ratio = 4.0
    min_stage_rows = 256

    def __init__(self, query_id: str):
        self.query_id = query_id
        self._stragglers: set = set()
        self._skew: Dict[int, float] = {}
        self.progress: Dict[int, float] = {}
        self.last_statuses: List[dict] = []

    @staticmethod
    def _stage_of(task_id: str) -> int:
        parts = task_id.split(".")
        return int(parts[1]) if len(parts) > 2 and parts[1].isdigit() \
            else 0

    def _by_stage(self, statuses: List[dict]) -> Dict[int, List[dict]]:
        out: Dict[int, List[dict]] = {}
        for st in statuses:
            tid = st.get("taskId")
            if tid:
                out.setdefault(self._stage_of(tid), []).append(st)
        return out

    def observe(self, statuses: List[dict]) -> None:
        self.last_statuses = statuses
        for fid, sts in self._by_stage(statuses).items():
            done = sum(1 for s in sts if s.get("state") == "FINISHED")
            self.progress[fid] = round(100.0 * done / len(sts), 1)
            for st in sts:
                # mirror worker status into the coordinator's registry:
                # system.runtime.tasks works against remote workers too
                TASKS.update(
                    st["taskId"], query_id=self.query_id, stage_id=fid,
                    state=st.get("state", ""),
                    elapsed_ms=float(st.get("elapsedMs") or 0.0),
                    output_rows=int(st.get("rowsOut") or 0),
                    output_bytes=int(st.get("bytesOut") or 0))
            elapsed = [float(s.get("elapsedMs") or 0.0) for s in sts]
            if len(elapsed) < 2:
                continue
            for i, st in enumerate(sts):
                tid = st["taskId"]
                if tid in self._stragglers:
                    continue
                others = elapsed[:i] + elapsed[i + 1:]
                med = statistics.median(others)
                if med >= self.min_elapsed_ms \
                        and elapsed[i] > self.straggler_ratio * med:
                    self._stragglers.add(tid)
                    _STRAGGLERS_DETECTED.inc()
                    TASKS.update(tid, straggler=True)
                    LOG.log("straggler_detected",
                            query_id=self.query_id, task_id=tid,
                            stage_id=fid,
                            elapsed_ms=round(elapsed[i], 1),
                            stage_median_ms=round(med, 1))

    def finalize(self, statuses: List[dict]) -> Dict[str, object]:
        """Final pass once every task reached a terminal state: one
        more straggler sweep over frozen elapsed values (a query that
        finished within one long-poll never hit ``observe``), then
        per-stage output-row skew. Returns the summary that rides the
        query-history record."""
        if statuses:
            self.observe(statuses)
        for fid, sts in self._by_stage(self.last_statuses).items():
            if fid in self._skew or len(sts) < 2:
                continue
            rows = [float(s.get("rowsOut") or 0.0) for s in sts]
            total = sum(rows)
            if total < self.min_stage_rows:
                continue
            # floor the median at one row: an all-in-one-partition
            # stage must flag with a FINITE ratio (inf would leak
            # non-strict "Infinity" tokens into the JSONL history sink
            # and the structured log)
            ratio = max(rows) / max(statistics.median(rows), 1.0)
            if ratio >= self.skew_ratio:
                self._skew[fid] = round(ratio, 2)
                _SKEWED_STAGES.inc()
                for st in sts:
                    TASKS.update(st["taskId"], skew_ratio=round(ratio, 2))
                LOG.log("stage_skew_detected", query_id=self.query_id,
                        stage_id=fid, skew_ratio=round(ratio, 2),
                        rows=[int(r) for r in rows])
        return self.summary()

    @property
    def stragglers(self) -> Set[str]:
        """Task ids flagged as stragglers so far — the speculative
        execution layer's launch feed."""
        return set(self._stragglers)

    def summary(self) -> Dict[str, object]:
        return {"progress": dict(sorted(self.progress.items())),
                "stragglers": sorted(self._stragglers),
                "skewed_stages": dict(sorted(self._skew.items()))}


#: matches the upstream-task reference an ExchangeFailedError embeds in
#: a failed consumer's error string (server/worker.py) — the retry
#: layer's pointer to WHICH attempt to replace
_UPSTREAM_RE = re.compile(r"upstream task (\S+?)[\s:]")


class _TaskAttempt:
    """One live attempt of one logical task (a (fragment, partition)
    slot). Attempt ids are versioned into the task id — every attempt
    owns its own worker-side output buffer, so consumers can never
    interleave pages from two attempts."""

    __slots__ = ("key", "attempt", "worker", "url", "task_id",
                 "speculative")

    def __init__(self, key, attempt, worker, url, task_id,
                 speculative=False):
        self.key = key                  # (fragment_id, partition)
        self.attempt = attempt
        self.worker = worker
        self.url = url
        self.task_id = task_id
        self.speculative = speculative


class _QueryExecution:
    """One cluster query's task graph with fault tolerance: scheduling,
    status-poll driven retry/rescheduling, speculative straggler
    attempts, drain-aware worker choice, and the query deadline. The
    coordinator-side core of the reference's SqlQueryScheduler +
    SqlStageExecution retry machinery, collapsed onto the deterministic
    re-executable task docs this engine already ships."""

    def __init__(self, runner: "ClusterRunner", fp: FragmentedPlan,
                 init_values: List[object], workers: List[str],
                 exec_id: str, monitor: StageMonitor,
                 deadline: Optional[float] = None, session=None):
        self.runner = runner
        self.fp = fp
        self.init_values = init_values
        self.workers = list(workers)
        self.exec_id = exec_id
        self.monitor = monitor
        self.deadline = deadline        # time.monotonic() cutoff
        session = session if session is not None else runner.session
        self.session = session
        self.policy = _retry_policy(session)
        self.max_task_retries = int(
            session.properties.get("task_retry_attempts", 2))
        self.backoff_s = float(
            session.properties.get("task_retry_backoff_s", 0.05))
        from ..planner.planner import bool_property
        self.spec_enabled = self.policy == "TASK" and bool_property(
            session, "speculative_execution", True)
        # spooled exchange (exec/spool.py, default on): non-root tasks
        # write every output page through to the durable page-
        # addressed spool, so consumers replay by token (retries and
        # speculative attempts never re-run healthy upstreams), a
        # drained worker exits without lingering, and shuffle size is
        # no longer capped by worker RAM. spool_exchange=false falls
        # back to PR 5's retained in-memory buffers.
        self.spool = self.policy == "TASK" and bool_property(
            session, "spool_exchange", True)
        self.retain = self.policy == "TASK" and not self.spool
        #: keys whose lost-but-spool-complete attempt was preserved
        #: instead of re-created (the replay-not-rerun ledger)
        self.spool_preserved: Set[Tuple[int, int]] = set()
        # -- graph ------------------------------------------------------------
        self.frag_of: Dict[int, PlanFragment] = {
            f.id: f for f in fp.fragments}
        self.consumer_fid: Dict[int, int] = {}
        for f in fp.fragments:
            for node in _walk(f.root):
                if isinstance(node, RemoteSourceNode):
                    for fid in node.fragment_ids:
                        self.consumer_fid[fid] = f.id
        self.task_count: Dict[int, int] = {}
        self.splits_of: Dict[Tuple[int, int], List[Split]] = {}
        self.parts: Dict[int, List[Tuple[int, int]]] = {}
        self.n_buffers_of: Dict[int, int] = {}
        #: initial placement mirrors the pre-fault-tolerance scheduler:
        #: source tasks follow their split assignment, fixed stages put
        #: one task per worker, single stages take the first worker
        self.placement: Dict[Tuple[int, int], str] = {}
        for f in fp.fragments:
            if f.partitioning == "source":
                keys = []
                part = 0
                for w, splits in zip(self.workers,
                                     runner._assign_splits(
                                         f, self.workers)):
                    if not splits:
                        continue
                    key = (f.id, part)
                    self.splits_of[key] = splits
                    self.placement[key] = w
                    keys.append(key)
                    part += 1
                self.parts[f.id] = keys
            elif f.partitioning == "fixed":
                self.parts[f.id] = [(f.id, p)
                                    for p in range(len(self.workers))]
                for p, w in enumerate(self.workers):
                    self.placement[(f.id, p)] = w
            else:
                self.parts[f.id] = [(f.id, 0)]
                self.placement[(f.id, 0)] = self.workers[0]
            self.task_count[f.id] = len(self.parts[f.id])
        for f in fp.fragments:
            self.n_buffers_of[f.id] = self.task_count.get(
                self.consumer_fid.get(f.id, -1), 1)
        self.root_fid = fp.root.id
        # -- live state -------------------------------------------------------
        self.tasks: Dict[Tuple[int, int], _TaskAttempt] = {}
        self.spec: Dict[Tuple[int, int], _TaskAttempt] = {}
        self.spec_done: Set[Tuple[int, int]] = set()
        self.attempt_no: Dict[Tuple[int, int], int] = {}
        self.retries_used: Dict[Tuple[int, int], int] = {}
        self.bad_workers: Set[str] = set()
        self._sched: Optional[List[str]] = None
        self.retries = 0
        self.spec_launched = 0
        self.spec_won = 0
        self.events: List[Dict[str, object]] = []

    # -- scheduling -----------------------------------------------------------
    def schedule_all(self) -> None:
        """Create every task, upstream-first (the fragments list is in
        dependency order: children were cut before their consumers)."""
        self._sched = None
        for f in self.fp.fragments:
            with TRACER.span("stage", query_id=self.exec_id,
                             stage_id=f.id,
                             partitioning=f.partitioning):
                for key in self.parts[f.id]:
                    self._launch(key,
                                 preferred=self.placement.get(key))

    def _task_id(self, key: Tuple[int, int], attempt: int) -> str:
        base = f"{self.exec_id}.{key[0]}.{key[1]}"
        return base if attempt == 0 else f"{base}.a{attempt}"

    def _sources_for(self, f: PlanFragment) -> Dict[int, List[str]]:
        out: Dict[int, List[str]] = {}
        for node in _walk(f.root):
            if isinstance(node, RemoteSourceNode):
                for fid in node.fragment_ids:
                    out[fid] = [self.tasks[k].url
                                for k in self.parts[fid]]
        return out

    def _schedulable(self) -> List[str]:
        """The runner's schedulable set, swept at most once per
        scheduling burst / recovery round (``schedule_all`` and
        ``poll`` invalidate). With heartbeat off the runner sweep
        probes every worker synchronously (~5s per unreachable host),
        so per-launch sweeps would serialize exactly the dead-worker
        recovery they serve."""
        if self._sched is None:
            self._sched = self.runner._schedulable_workers()
        return self._sched

    def _pick_worker(self, exclude: Set[str] = frozenset()) -> str:
        """A schedulable worker for a (re)launch: heartbeat-alive, not
        draining, not observed bad this query; prefer workers outside
        ``exclude`` (the failed attempt's host), least-loaded first."""
        cands = [w for w in self._schedulable()
                 if w not in self.bad_workers]
        if not cands:
            cands = [w for w in self.runner.detector.active()
                     if w not in self.bad_workers]
        if not cands:
            raise QueryFailedError(
                "no active workers to (re)schedule task")
        load: Dict[str, int] = {}
        for at in self.tasks.values():
            load[at.worker] = load.get(at.worker, 0) + 1
        preferred = [w for w in cands if w not in exclude] or cands
        return min(preferred, key=lambda w: (load.get(w, 0),
                                             cands.index(w)))

    def _launch(self, key: Tuple[int, int],
                preferred: Optional[str] = None,
                exclude: Set[str] = frozenset(),
                speculative: bool = False) -> _TaskAttempt:
        """Create one attempt of ``key`` on a healthy worker; workers
        that refuse the create are marked bad and another is tried."""
        f = self.frag_of[key[0]]
        tried: Set[str] = set()
        while True:
            worker = preferred if preferred is not None \
                and preferred not in tried \
                and preferred not in self.bad_workers \
                else self._pick_worker(exclude | tried)
            attempt = self.attempt_no.get(key, -1) + 1
            self.attempt_no[key] = attempt
            task_id = self._task_id(key, attempt)
            retain = self.retain and key[0] != self.root_fid
            spool = self.spool and key[0] != self.root_fid
            try:
                url = self.runner._create_task(
                    worker, self.exec_id, f, key[1],
                    self.n_buffers_of[f.id],
                    self.splits_of.get(key, []),
                    self._sources_for(f), self.init_values,
                    task_id=task_id, retain=retain, spool=spool,
                    session=self.session)
            except QueryFailedError:
                # the chosen worker is unreachable: exclude it and try
                # the next one (its running tasks are recovered by the
                # status-poll path, not here)
                tried.add(worker)
                self.bad_workers.add(worker)
                continue
            except urllib.error.HTTPError as e:
                # HTTP-level refusal that survived _request's 5xx retry
                # budget — e.g. a 503 from a worker that began draining
                # between the schedulable sweep and this create: treat
                # the worker as bad and pick another. 4xx refusals are
                # deterministic (a malformed doc would fail everywhere)
                # so they fail the query with the worker's verdict.
                if e.code >= 500:
                    tried.add(worker)
                    self.bad_workers.add(worker)
                    continue
                detail = e.read().decode(errors="replace")
                raise QueryFailedError(
                    f"worker refused task create "
                    f"({e.code}): {detail}") from None
            at = _TaskAttempt(key, attempt, worker, url, task_id,
                              speculative=speculative)
            if speculative:
                self.spec[key] = at
            else:
                self.tasks[key] = at
            return at

    # -- views ----------------------------------------------------------------
    def root_url(self) -> str:
        return self.tasks[(self.root_fid, 0)].url

    def all_urls(self) -> List[str]:
        return [at.url for at in self.tasks.values()] + \
               [at.url for at in self.spec.values()]

    def summary(self) -> Dict[str, object]:
        return {"policy": self.policy, "retries": self.retries,
                "speculative_launched": self.spec_launched,
                "speculative_won": self.spec_won,
                "events": list(self.events)}

    # -- recovery -------------------------------------------------------------
    def _delete(self, at: _TaskAttempt) -> None:
        try:
            self.runner._request(at.url, method="DELETE", retries=0,
                                 timeout=5)
        except Exception:
            pass

    def abort_all(self) -> None:
        """Query-level abort: DELETE /v1/query/{id} on every worker —
        the cancellation-propagation path (deadline, QUERY retry)."""
        for url in set(list(self.runner.worker_urls)
                       + [at.worker for at in self.tasks.values()]):
            try:
                self.runner._request(
                    f"{url}/v1/query/{self.exec_id}", method="DELETE",
                    retries=0, timeout=5)
            except Exception:
                continue

    def check_deadline(self) -> None:
        if self.deadline is not None \
                and time.monotonic() > self.deadline:
            self.abort_all()
            raise QueryFailedError(
                "query exceeded query_max_run_time "
                f"({self.session.properties.get('query_max_run_time')})"
            )

    def _spool_complete(self, at: _TaskAttempt) -> bool:
        """True when this attempt committed its full output to the
        durable spool (its ``.done`` marker exists): consumers replay
        its pages from storage, so losing the worker does NOT require
        re-running the task."""
        if not self.spool:
            return False
        from .spool import SPOOL
        return SPOOL.finished_tokens(self.exec_id,
                                     at.task_id) is not None

    def _probe(self):
        """One status sweep over current attempts. Returns
        ``(statuses, failed, spec_status)`` where ``failed`` maps key ->
        human reason for FAILED/ABORTED/lost primaries and
        ``spec_status`` maps key -> status doc or None (lost)."""
        statuses: List[dict] = []
        failed: Dict[Tuple[int, int], str] = {}
        spec_status: Dict[Tuple[int, int], Optional[dict]] = {}
        dead: Set[str] = set()

        def fetch(at: _TaskAttempt) -> Tuple[Optional[dict], str]:
            if at.worker in dead:
                return None, f"worker {at.worker} unreachable"
            try:
                return self.runner._request(at.url, retries=1,
                                            timeout=5), ""
            except urllib.error.HTTPError as e:
                # the worker ANSWERED: the task is unknown there
                # (tombstone evicted, worker restarted) — the TASK is
                # lost, the worker is not; don't poison bad_workers
                return None, (f"task {at.task_id} unknown to "
                              f"{at.worker} (HTTP {e.code})")
            except Exception as e:
                dead.add(at.worker)
                self.bad_workers.add(at.worker)
                return None, f"worker {at.worker} unreachable: {e}"

        for key, at in list(self.tasks.items()):
            if key in self.spool_preserved:
                # this attempt's worker is gone but its complete
                # output lives in the spool — report it FINISHED
                # without probing the dead host again
                statuses.append({"taskId": at.task_id,
                                 "state": "FINISHED", "elapsedMs": 0,
                                 "rowsOut": 0, "bytesOut": 0})
                continue
            st, why = fetch(at)
            if st is None:
                if self._spool_complete(at):
                    # the task finished and committed its spool before
                    # its worker vanished (drain exit, crash after
                    # FINISH): replay, don't re-run — the whole point
                    # of the spooled exchange
                    self.spool_preserved.add(key)
                    _SPOOL_REPLAYED.inc()
                    self.events.append(
                        {"kind": "spool_replay", "task": at.task_id,
                         "worker": at.worker})
                    LOG.log("spool_replayed", query_id=self.exec_id,
                            task_id=at.task_id, worker=at.worker)
                    statuses.append({"taskId": at.task_id,
                                     "state": "FINISHED",
                                     "elapsedMs": 0, "rowsOut": 0,
                                     "bytesOut": 0})
                    continue
                failed[key] = f"lost task {at.task_id} ({why})"
                continue
            statuses.append(st)
            if st.get("state") in ("FAILED", "ABORTED"):
                failed[key] = (f"task {at.task_id} "
                               f"{st.get('state', '').lower()}: "
                               f"{st.get('error')}")
        for key, at in list(self.spec.items()):
            spec_status[key] = fetch(at)[0]
        return statuses, failed, spec_status

    def _resolve_speculation(self, statuses: List[dict],
                             failed: Dict[Tuple[int, int], str],
                             spec_status) -> None:
        """First-finished-wins between a primary and its speculative
        duplicate; the loser is aborted. A winner's downstream
        consumers are re-created against its buffer."""
        by_id = {st.get("taskId"): st for st in statuses}
        for key, sst in list(spec_status.items()):
            spec = self.spec.get(key)
            if spec is None:
                continue
            primary = self.tasks[key]
            pst = by_id.get(primary.task_id)
            if sst is None or (sst.get("state")
                               in ("FAILED", "ABORTED")):
                # the duplicate died: drop it, the primary carries on
                del self.spec[key]
                self._delete(spec)
                continue
            if pst is not None and pst.get("state") == "FINISHED" \
                    and key not in failed:
                del self.spec[key]
                self._delete(spec)
                LOG.log("speculative_lost", query_id=self.exec_id,
                        task_id=spec.task_id)
                continue
            if sst.get("state") == "FINISHED":
                # speculative win: promote the duplicate, rewire every
                # downstream consumer to its buffer, abort the loser
                del self.spec[key]
                self.tasks[key] = spec
                failed.pop(key, None)
                self.spec_won += 1
                _SPEC_WON.inc()
                self.events.append(
                    {"kind": "speculative_won", "task": spec.task_id,
                     "worker": spec.worker})
                LOG.log("speculative_won", query_id=self.exec_id,
                        task_id=spec.task_id, loser=primary.task_id)
                self._recreate_downstream({key[0]})
                self._delete(primary)

    def _downstream_fids(self, fids: Set[int]) -> List[int]:
        out: Set[int] = set()
        frontier = set(fids)
        while frontier:
            nxt = {self.consumer_fid[f] for f in frontier
                   if f in self.consumer_fid}
            nxt -= out
            out |= nxt
            frontier = nxt
        return [f.id for f in self.fp.fragments if f.id in out]

    def _recreate_downstream(self, fids: Set[int]) -> None:
        """Re-create every task transitively downstream of ``fids`` (in
        dependency order) so their exchange clients re-read the current
        upstream attempts' retained buffers from token 0."""
        for fid in self._downstream_fids(fids):
            for key in self.parts[fid]:
                # the fresh attempt is live again: a stale spool
                # preservation would make _probe fabricate FINISHED
                # for it forever and blind lost-task detection
                self.spool_preserved.discard(key)
                old = self.tasks[key]
                sp = self.spec.pop(key, None)
                if sp is not None:
                    self._delete(sp)
                self._delete(old)
                self._launch(key, preferred=old.worker)

    def _recover(self, failed: Dict[Tuple[int, int], str]) -> None:
        """Apply the retry policy to this round's failures."""
        if not failed:
            return
        qid = self.exec_id.split("r")[0]
        mm = self.runner.memory_manager
        if mm is not None and (self.exec_id in mm.killed
                               or qid in mm.killed):
            # the cluster memory manager killed this query on purpose —
            # resurrecting it would fight the OOM killer
            raise QueryFailedError(
                "Query killed: exceeded cluster memory limit "
                f"({next(iter(failed.values()))})")
        reason = next(iter(failed.values()))
        if self.policy == "NONE":
            raise QueryFailedError(reason)
        if self.policy == "QUERY":
            raise _QueryRetry(reason)
        self.check_deadline()
        # an ExchangeFailedError names the upstream attempt that died:
        # the real fault is THERE; its consumer is collateral and is
        # re-created by the cascade without burning its own budget
        by_id = {at.task_id: key for key, at in self.tasks.items()}
        extra: Dict[Tuple[int, int], str] = {}
        for key, why in failed.items():
            m = _UPSTREAM_RE.search(why or "")
            if not m:
                continue
            tid = m.group(1)
            ukey = by_id.get(tid)
            if ukey is None:
                parts = tid.split(".")
                if len(parts) >= 3 and parts[1].isdigit() \
                        and parts[2].isdigit():
                    ukey = (int(parts[1]), int(parts[2]))
            if ukey is not None and ukey in self.tasks:
                extra[ukey] = why
        failed = dict(failed)
        failed.update(extra)
        collateral = set()
        failed_fids = {k[0] for k in failed}
        for fid in self._downstream_fids(failed_fids):
            for key in self.parts[fid]:
                collateral.add(key)
        billed = {k: v for k, v in failed.items()
                  if k not in collateral}
        if not billed:       # pure collateral (stale consumer errors)
            billed = dict(failed)
        max_used = 0
        for key, why in billed.items():
            used = self.retries_used.get(key, 0) + 1
            self.retries_used[key] = used
            max_used = max(max_used, used)
            if used > self.max_task_retries:
                raise QueryFailedError(
                    f"task {self.tasks[key].task_id} failed after "
                    f"{used} attempts: {why}")
        from .backoff import jittered
        time.sleep(jittered(min(self.backoff_s * (2 ** (max_used - 1)),
                                2.0)))
        # replace failed attempts upstream-first, then cascade to every
        # transitive consumer (they re-read spooled/retained output
        # from token 0)
        replace = {k for k in failed if k not in collateral} \
            or set(failed)
        for f in self.fp.fragments:
            for key in self.parts[f.id]:
                if key not in replace:
                    continue
                # an explicitly-billed upstream (e.g. its spool copy
                # came back corrupt) must actually re-run: drop the
                # preservation so _probe stops reporting the dead
                # attempt FINISHED
                self.spool_preserved.discard(key)
                old = self.tasks[key]
                sp = self.spec.pop(key, None)
                self._delete(old)
                self.retries += 1
                _TASK_RETRIES.inc()
                if sp is not None:
                    # the straggler hedge outlived its primary: promote
                    # the duplicate (probed healthy this round —
                    # _resolve_speculation already dropped dead ones)
                    # instead of restarting the work from zero
                    self.tasks[key] = at = sp
                else:
                    at = self._launch(key, exclude={old.worker})
                self.events.append(
                    {"kind": "task_retry", "task": at.task_id,
                     "from": old.worker, "to": at.worker,
                     "attempt": at.attempt,
                     "reason": failed.get(key, "")})
                LOG.log("task_retried", query_id=self.exec_id,
                        task_id=old.task_id, new_task_id=at.task_id,
                        from_worker=old.worker, to_worker=at.worker,
                        attempt=at.attempt,
                        reason=failed.get(key, ""))
        self._recreate_downstream({k[0] for k in replace})

    def _maybe_speculate(self, statuses: List[dict]) -> None:
        if not self.spec_enabled:
            return
        stragglers = self.monitor.stragglers
        if not stragglers:
            return
        by_id = {at.task_id: (key, at)
                 for key, at in self.tasks.items()}
        states = {st.get("taskId"): st.get("state") for st in statuses}
        for tid in stragglers:
            ent = by_id.get(tid)
            if ent is None:
                continue
            key, at = ent
            if key in self.spec or key in self.spec_done \
                    or states.get(tid) != "RUNNING":
                continue
            if not any(w != at.worker and w not in self.bad_workers
                       for w in self._schedulable()):
                # no second host right now: don't create a duplicate
                # that _launch would land on the straggler's own
                # already-slow worker; re-check next round (a node
                # may finish draining or rejoin)
                continue
            try:
                dup = self._launch(key, exclude={at.worker},
                                   speculative=True)
            except QueryFailedError:
                continue          # no second host available: skip
            if dup.worker == at.worker:
                # a one-node cluster cannot speculate usefully; mark
                # the key done so the next poll round doesn't land
                # another create/abort churn on the already-slow host
                self.spec.pop(key, None)
                self._delete(dup)
                self.spec_done.add(key)
                continue
            self.spec_done.add(key)
            self.spec_launched += 1
            _SPEC_LAUNCHED.inc()
            self.events.append(
                {"kind": "speculative_launched", "task": dup.task_id,
                 "straggler": tid, "worker": dup.worker})
            LOG.log("speculative_launched", query_id=self.exec_id,
                    straggler_task_id=tid, task_id=dup.task_id,
                    worker=dup.worker)

    def poll(self) -> int:
        """One recovery round: deadline, status sweep, speculation
        resolution/launch, failure recovery. Returns the number of
        recovery actions taken (retries + speculation changes)."""
        self.check_deadline()
        self._sched = None
        before = self.retries + self.spec_launched + self.spec_won
        statuses, failed, spec_status = self._probe()
        self.monitor.observe(statuses)
        self._resolve_speculation(statuses, failed, spec_status)
        self._recover(failed)
        self._maybe_speculate(statuses)
        return (self.retries + self.spec_launched + self.spec_won) \
            - before

    def cleanup(self) -> None:
        for at in list(self.tasks.values()) + list(self.spec.values()):
            self._delete(at)


class ClusterRunner:
    """Executes SELECT queries across worker processes; everything else
    (DDL, SET, EXPLAIN) falls through to the embedded LocalRunner."""

    def __init__(self, worker_urls: Optional[List[str]] = None,
                 catalogs=None,
                 catalog: str = "tpch", schema: str = "default",
                 tpch_sf: float = 0.01, rows_per_batch: int = 1 << 17,
                 heartbeat: bool = True, discovery=None):
        # static URL list OR discovery-fed dynamic membership (reference
        # DiscoveryNodeManager: workers join by announcing, any time)
        self.discovery = discovery
        self._static_urls = list(worker_urls or ())
        self.local = LocalRunner(catalogs=catalogs, catalog=catalog,
                                 schema=schema, tpch_sf=tpch_sf,
                                 rows_per_batch=rows_per_batch)
        self.session = self.local.session
        self.rows_per_batch = rows_per_batch
        self._seq = 0
        #: worker url -> node id learned from /v1/info (node federator)
        self._node_ids: Dict[str, str] = {}
        #: worker url -> last seen /v1/info state — the drain-aware
        #: scheduling feed (SHUTTING_DOWN nodes finish their running
        #: tasks but are never assigned new ones)
        self._node_states: Dict[str, str] = {}
        #: monitor/recovery info of the last _run_fragments call (the
        #: cluster EXPLAIN ANALYZE feed)
        self._last_run_info: Dict[str, object] = {}
        NODES.update("coordinator", state="ACTIVE", coordinator=True,
                     uri="", active_tasks=0, mem_pool_peak_bytes=0)
        self.detector = HeartbeatFailureDetector(
            self._current_urls, on_info=self._note_node_info)
        self._heartbeat_on = bool(heartbeat)
        if heartbeat:
            self.detector.start()
        self.memory_manager: Optional[ClusterMemoryManager] = None
        limit = self.session.properties.get("cluster_memory_limit")
        if limit:
            self.enable_memory_manager(int(limit))

    def enable_memory_manager(self, limit_bytes: int,
                              interval_s: float = 0.5) -> None:
        self.memory_manager = ClusterMemoryManager(self, limit_bytes,
                                                   interval_s)
        self.memory_manager.start()

    def _current_urls(self) -> List[str]:
        if self.discovery is not None:
            return self.discovery.active_urls()
        return list(self._static_urls)

    @property
    def worker_urls(self) -> List[str]:
        return self._current_urls()

    # -- node-state federation (system.runtime.nodes) ------------------------
    def _note_node_info(self, url: str, info: dict) -> None:
        """Fold one worker's ``/v1/info`` doc into the process-wide
        node registry — the feed of ``system.runtime.nodes`` and of the
        node-labeled series on the coordinator's ``/v1/metrics``."""
        nid = str(info.get("nodeId") or url)
        if url not in self._node_ids:
            # first contact with this worker — covers boot-time
            # membership AND mid-query elastic joins (a worker that
            # announced while queries were running)
            _NODES_JOINED.inc()
            LOG.log("node_joined", node_id=nid, uri=url)
        self._node_ids[url] = nid
        state = str(info.get("state", "ACTIVE"))
        if state == "SHUTTING_DOWN" \
                and self._node_states.get(url) != "SHUTTING_DOWN":
            # ACTIVE -> SHUTTING_DOWN transition: the node entered its
            # drain window; the scheduler stops assigning to it
            _NODES_DRAINED.inc()
            LOG.log("node_draining", node_id=nid, uri=url)
        self._node_states[url] = state
        tasks = info.get("tasks") or {}
        fields = dict(
            state=state, coordinator=False, uri=url,
            active_tasks=int(tasks.get("RUNNING", 0) or 0),
            mem_pool_peak_bytes=int(
                info.get("memPoolPeakBytes", 0) or 0))
        # worker-sampled device.memory_stats() riding the heartbeat —
        # the feed of system.runtime.nodes' HBM columns and the
        # node_hbm_* series on the coordinator /v1/metrics scrape.
        # Only nodes whose backend actually reported stats get the
        # fields: a stats-less (CPU) node must stay absent from the
        # node_hbm_* series, not publish zeros
        hbm = info.get("hbm") or {}
        drop = ()
        if int(hbm.get("devices", 0) or 0) > 0:
            fields["hbm_in_use_bytes"] = int(hbm.get("bytesInUse", 0)
                                             or 0)
            fields["hbm_peak_bytes"] = int(hbm.get("peakBytes", 0) or 0)
        else:
            # a node that stops reporting device stats (restarted under
            # the same id on a stats-less backend) must not keep serving
            # its previous incarnation's sample
            drop = ("hbm_in_use_bytes", "hbm_peak_bytes")
        NODES.update(nid, drop=drop, **fields)
        # federate the heartbeat sample into the coordinator's
        # time-series store: per-node history becomes range-readable on
        # the coordinator's /v1/metrics/history and
        # system.runtime.timeseries without re-polling the worker
        from ..obs.timeseries import TIMESERIES
        TIMESERIES.record(f"node_active_tasks.{nid}",
                          fields["active_tasks"])
        TIMESERIES.record(f"node_mem_pool_peak_bytes.{nid}",
                          fields["mem_pool_peak_bytes"])
        if "hbm_in_use_bytes" in fields:
            TIMESERIES.record(f"node_hbm_in_use_bytes.{nid}",
                              fields["hbm_in_use_bytes"])
            TIMESERIES.record(f"node_hbm_peak_bytes.{nid}",
                              fields["hbm_peak_bytes"])

    def poll_nodes(self, urls: Optional[List[str]] = None) -> None:
        """One synchronous federation sweep (the background heartbeat
        does the same continuously when enabled); unreachable workers
        keep their last heartbeat timestamp so their age grows."""
        for url in (urls if urls is not None else self.worker_urls):
            try:
                info = self._request(f"{url}/v1/info", retries=0,
                                     timeout=5)
            except Exception:
                self._node_states[url] = "UNREACHABLE"
                nid = self._node_ids.get(url)
                if nid:
                    NODES.update(nid, seen=False, state="UNREACHABLE")
                continue
            self._note_node_info(url, info)
        # coordinator-role discovery entries (the serving fleet's
        # peers) surface in system.runtime.nodes too, flagged
        # coordinator=True — they are membership, never task targets
        # (active_urls() filters them out of scheduling)
        if self.discovery is not None:
            for n in self.discovery.nodes():
                if n.get("role") == "coordinator" and n.get("active"):
                    NODES.update(n["nodeId"], state=n.get(
                        "state", "ACTIVE"), coordinator=True,
                        uri=n.get("uri", ""))

    def _mesh_route(self, properties: Optional[Dict[str, object]] = None
                    ) -> bool:
        """Should this query run on the local device mesh instead of
        remote worker tasks? ``mesh_execution=on`` always; ``auto``
        (the default) only when >1 device is effective AND no remote
        worker is schedulable — a cluster that HAS healthy workers
        keeps the task/exchange path (spool, retries, speculation),
        while a worker-less multi-chip coordinator gets the SPMD
        substrate instead of failing with no nodes."""
        import dataclasses as _dc

        from ..config import validate_session_property
        from .distributed import mesh_device_count, mesh_mode
        session = self.session
        if properties:
            # only the two routing props matter here, and they must go
            # through the registry gate NOW: a malformed mesh_devices
            # raises the declared SessionPropertyError instead of a
            # bare int() crash before the overlay's own validation
            overlay = {k: validate_session_property(k, properties[k])
                       for k in ("mesh_execution", "mesh_devices")
                       if k in properties}
            if overlay:
                session = _dc.replace(
                    session,
                    properties={**session.properties, **overlay})
        mode = mesh_mode(session)
        if mode == "off":
            return False
        if mode == "on":
            return True
        if mesh_device_count(session) < 2:
            return False
        return not self._schedulable_workers()

    def _schedulable_workers(self) -> List[str]:
        """Workers eligible for NEW task assignment: heartbeat-alive and
        not draining (reference NodeScheduler skips nodes the
        GracefulShutdownHandler flagged SHUTTING_DOWN). Drain state
        merges two feeds: the ``/v1/info`` heartbeat sweep and the
        discovery announcements (a draining worker pushes
        SHUTTING_DOWN immediately, ahead of the next sweep)."""
        urls = self.detector.active()
        if not self._heartbeat_on:
            # no background federator: one synchronous sweep so drain
            # state and system.runtime.nodes are fresh for this query
            self.poll_nodes(urls)
        draining = {u for u, s in
                    (self.discovery.states() if self.discovery
                     is not None else {}).items()
                    if s == "SHUTTING_DOWN"}
        return [u for u in urls
                if u not in draining
                and self._node_states.get(u)
                not in ("SHUTTING_DOWN", "UNREACHABLE")]

    # -- HTTP helpers --------------------------------------------------------
    #: transient-failure budget for one remote-task call (reference
    #: server/remotetask/RequestErrorTracker.java wraps every remote-task
    #: request in retry-with-backoff; one socket blip must not fail a
    #: query with healthy workers)
    REQUEST_RETRIES = 4
    REQUEST_BACKOFF_S = 0.1

    def _request(self, url: str, method: str = "GET",
                 body: Optional[dict] = None,
                 retries: Optional[int] = None,
                 timeout: float = 10) -> dict:
        """Remote-task HTTP with retry/backoff. Retrying is safe because
        every mutating endpoint is idempotent (task PUT is an upsert on
        the worker, DELETE/abort tolerate repeats). Latency-sensitive
        callers (the memory manager's poll/kill loop) pass retries=0 —
        their next poll IS the retry. These are small-JSON control-plane
        calls (create/status/delete): the 10s timeout bounds a
        black-holed worker at ~a minute across the whole retry budget,
        not 5 minutes (result pages stream through a separate client)."""
        data = json.dumps(body).encode() if body is not None else None
        budget = self.REQUEST_RETRIES if retries is None else retries
        last: Optional[Exception] = None
        for attempt in range(budget + 1):
            if attempt:
                # jittered exponential backoff: N clients retrying a
                # recovering worker must not synchronize into bursts
                from .backoff import jittered
                time.sleep(jittered(
                    self.REQUEST_BACKOFF_S * (2 ** (attempt - 1))))
            req = urllib.request.Request(url, data=data, method=method)
            if data is not None:
                req.add_header("Content-Type", "application/json")
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                if e.code >= 500 and attempt < budget:
                    last = e
                    continue
                raise
            except (urllib.error.URLError, ConnectionError,
                    TimeoutError) as e:
                # transport-level failure: retry with backoff; the
                # heartbeat failure detector owns the
                # permanently-dead-worker verdict
                last = e
                if attempt >= budget:
                    break
                continue
        raise QueryFailedError(
            f"remote task request failed after "
            f"{budget + 1} attempts: {url}: {last}")

    # -- public API ----------------------------------------------------------
    def execute(self, sql: str,
                properties: Optional[Dict[str, object]] = None,
                user: str = "", cancel_event=None,
                serving=None) -> QueryResult:
        """Run one statement across the cluster. The keyword surface
        matches LocalRunner.execute, so the statement protocol serves
        a ClusterRunner through the SAME resource-group admission,
        per-query session overlay, cancellation, and serving handoff —
        multi-worker deployments get the PR 8 limits too. SELECTs ride
        the compiled-plan cache (serving/plancache.py): a repeated
        statement skips parse/plan/optimize straight to fragmenting."""
        import dataclasses as _dc
        from ..serving.plancache import cached_plan, parse_cached
        from ..sql import ast as A
        stmt = parse_cached(sql)
        analyze = isinstance(stmt, A.Explain) and stmt.analyze \
            and isinstance(stmt.statement, A.Query) \
            and stmt.type == "logical" and stmt.format == "text"
        if not isinstance(stmt, A.Query) and not analyze:
            return self.local.execute(sql, properties=properties,
                                      user=user,
                                      cancel_event=cancel_event,
                                      serving=serving)
        if self._mesh_route(properties):
            # mesh-native execution: with multiple chips on this host
            # the device mesh IS the cluster substrate — shards of one
            # SPMD program replace worker tasks. Route through the
            # embedded LocalRunner (same admission/serving/security
            # surface), whose execute_plan picks the SPMD executor.
            # Under ``auto`` remote workers still win when any are
            # schedulable; ``on`` forces the mesh.
            return self.local.execute(sql, properties=properties,
                                      user=user,
                                      cancel_event=cancel_event,
                                      serving=serving)
        session = self.session
        secured = bool(self.local.access_control.catalog_rules)
        if properties or secured or serving is not None:
            catalogs = session.catalogs
            if secured:
                from ..server.security import SecuredCatalogs
                catalogs = SecuredCatalogs(catalogs, user,
                                           self.local.access_control)
            session = _dc.replace(
                session, catalogs=catalogs, serving=serving,
                properties={**session.properties, **(properties or {})})
        if analyze:
            # EXPLAIN ANALYZE runs the inner query: it goes through
            # the SAME secured session overlay, privilege checks, and
            # cancellation as a plain SELECT — analyzing a statement
            # must never be a way around running it
            return self._explain_analyze(stmt.statement, sql,
                                         session=session, user=user,
                                         cancel_event=cancel_event)
        from ..planner.planner import bool_property
        sec = secured or self.local.roles.enforce
        use_template = bool_property(session, "plan_template_cache",
                                     False)
        use_results = bool_property(session, "result_cache", False)
        bindings = bound_key = None
        if use_template:
            from ..serving.template import template_plan
            plan, bindings, bound_key = template_plan(
                stmt, session, user=user, secured=sec)
        else:
            plan = cached_plan(stmt, session, user=user, secured=sec)
        if secured:
            self.local._check_catalog_access(plan, user)
        if self.local.roles.enforce:
            self.local._check_select_privileges(plan, user)
        if bindings:
            # remote fragments ship over the codec and trace literals
            # as constants — materialize this query's bindings (the
            # coordinator still skipped parse/plan/optimize on the hit)
            from ..expr.params import bind_plan
            plan = bind_plan(plan, bindings)
        rc_token = None
        if use_results:
            # the SAME begin/commit contract as LocalRunner: keying,
            # pre-execution dep/epoch stamps, and the mid-run write
            # veto must agree across execution modes
            from ..serving import resultcache as RC
            from ..serving.plancache import bound_fingerprint
            if bound_key is None:
                bound_key = bound_fingerprint(stmt, session, user=user,
                                              secured=sec)
            served, rc_token = RC.begin(
                bound_key, plan, session, self.rows_per_batch,
                cancel_event=cancel_event)
            if served is not None:
                return served
        # init plans (uncorrelated scalar subqueries) run on the
        # coordinator; their values ship inside every task update
        from .local import run_init_plans, _Executor
        ex = _Executor(session, self.rows_per_batch)
        run_init_plans(ex, plan)
        init_values = ex.init_values
        fragmented = fragment_plan(plan.root)
        out = self._run_fragments(fragmented, init_values, sql,
                                  session=session,
                                  cancel_event=cancel_event,
                                  user=user)
        if rc_token is not None:
            from ..serving import resultcache as RC
            RC.commit(rc_token, session, out)
        return out

    def _explain_analyze(self, query_stmt, sql: str, session=None,
                         user: str = "",
                         cancel_event=None) -> QueryResult:
        """Cluster EXPLAIN ANALYZE: run the inner query on the cluster,
        then render the plan plus the stage summary and the
        fault-tolerance section (retries/speculation/spool replays) —
        the cluster analogue of the local runner's trace/skew/scan-cache
        sections. ``session`` is the caller's (possibly secured)
        per-query overlay; planning against its catalogs enforces the
        same access control as a plain SELECT."""
        from .. import types as T
        from ..planner.planner import plan_query
        from ..planner.optimizer import optimize
        from ..planner.printer import format_retry_summary, print_plan
        from .local import run_init_plans, _Executor
        session = session if session is not None else self.session
        t0 = time.perf_counter()
        plan = optimize(plan_query(query_stmt, session), session)
        if self.local.roles.enforce:
            self.local._check_select_privileges(plan, user)
        ex = _Executor(session, self.rows_per_batch)
        run_init_plans(ex, plan)
        fragmented = fragment_plan(plan.root)
        out = self._run_fragments(fragmented, ex.init_values, sql,
                                  session=session,
                                  cancel_event=cancel_event,
                                  user=user)
        wall_ms = (time.perf_counter() - t0) * 1e3
        text = print_plan(plan)
        info = dict(self._last_run_info)
        text += (f"\nCluster: {len(fragmented.fragments)} stages, "
                 f"{len(out.rows):,} rows, total {wall_ms:,.0f}ms")
        retry = format_retry_summary(info)
        if retry:
            text += "\n" + retry
        from ..planner.planner import bool_property
        if bool_property(session, "profile", False):
            # in-process workers share this process's EXECUTABLES
            # registry, so the section shows the run's compiled
            # kernels; remote workers keep theirs queryable on their
            # own system.runtime.executables table
            from ..planner.printer import format_executables_registry
            exes = format_executables_registry()
            if exes:
                text += "\n" + exes
        return QueryResult(["Query Plan"], [T.VARCHAR],
                           [(line,) for line in text.split("\n")])

    # -- scheduling ----------------------------------------------------------
    def _schedulable_or_raise(self) -> List[str]:
        if not self.detector.active():
            raise QueryFailedError("no active workers")
        workers = self._schedulable_workers()
        if not workers:
            raise QueryFailedError(
                "no schedulable workers (all draining)")
        return workers

    def _run_fragments(self, fp: FragmentedPlan,
                       init_values: List[object],
                       sql: str = "", session=None,
                       cancel_event=None, user: str = "") -> QueryResult:
        session = session if session is not None else self.session
        workers = self._schedulable_or_raise()
        self._seq += 1
        qid = f"cq_{self._seq:06d}"
        REGISTRY.counter("cluster_queries_total").inc()
        from ..connectors.system import QueryLogEntry
        from ..events import QueryCompletedEvent
        # validate session properties BEFORE the RUNNING log entry is
        # appended: a bad value must raise without leaving a phantom
        # forever-RUNNING row in system.runtime.queries
        policy = _retry_policy(session)
        q_budget = int(session.properties.get(
            "query_retry_attempts", 1)) if policy == "QUERY" else 0
        max_run = parse_duration_s(
            session.properties.get("query_max_run_time"))
        deadline = (time.monotonic() + max_run) if max_run else None
        entry = QueryLogEntry(qid, "RUNNING", sql.strip(), 0.0,
                              user=user, create_time=time.time())
        with self.local._state_lock:
            self.local.query_log.append(entry)
            # same bound LocalRunner.execute applies: a cluster-only
            # coordinator must not grow the log without limit
            if len(self.local.query_log) > 1000:
                del self.local.query_log[:-500]
        monitor = StageMonitor(qid)
        total_retries = 0
        t0 = time.perf_counter()
        error: Optional[str] = None
        try:
            with TRACER.span("query", query_id=qid, mode="cluster",
                             workers=len(workers)):
                for qtry in range(q_budget + 1):
                    # QUERY-policy reruns use a distinct exec id so the
                    # rerun's tasks never share worker-side query state
                    # (device-scheduler handles, query-level aborts)
                    # with still-draining tasks of the aborted attempt
                    exec_id = qid if qtry == 0 else f"{qid}r{qtry}"
                    monitor = StageMonitor(qid)
                    run = _QueryExecution(self, fp, init_values,
                                          workers, exec_id, monitor,
                                          deadline=deadline,
                                          session=session)
                    try:
                        run.schedule_all()
                        out = self._collect(fp, run,
                                            cancel_event=cancel_event)
                        break
                    except _QueryRetry as e:
                        run.abort_all()
                        if qtry >= q_budget:
                            raise QueryFailedError(
                                f"query failed after {qtry + 1} "
                                f"attempts: {e}") from None
                        _QUERY_RETRIES.inc()
                        LOG.log("query_retried", query_id=qid,
                                attempt=qtry + 1, reason=str(e))
                        time.sleep(min(
                            float(session.properties.get(
                                "task_retry_backoff_s", 0.05))
                            * (2 ** qtry), 2.0))
                        workers = self._schedulable_or_raise()
                    finally:
                        # final status sweep BEFORE the task DELETEs:
                        # frozen elapsed/rows feed the last straggler
                        # pass, the skew pass, and the query-history
                        # operator records
                        monitor.finalize(
                            self._task_statuses(run.all_urls()))
                        self._harvest_spans(run.all_urls())
                        run.cleanup()
                        # spool GC: this exec attempt's pages can
                        # never be read again once its tasks are gone
                        # (success, failure and abort all pass here) —
                        # no orphaned per-query spool directories.
                        # Spool-less runs (NONE policy,
                        # spool_exchange=false) skip the per-worker
                        # DELETE round trips entirely.
                        if run.spool:
                            self._release_spool(exec_id)
                        total_retries += run.retries
                        self._last_run_info = {
                            **run.summary(), "retries": total_retries,
                            "query_retries": qtry}
            entry.state = "FINISHED"
            return out
        except Exception as e:
            entry.state = "FAILED"
            error = str(e)
            raise
        finally:
            entry.elapsed_ms = (time.perf_counter() - t0) * 1e3
            entry.error = error
            summary = monitor.summary()
            history = {
                "query_id": qid, "query": entry.query, "user": user,
                "state": entry.state, "error": error,
                "error_code": None, "create_time": entry.create_time,
                "elapsed_ms": round(entry.elapsed_ms, 3),
                "mode": "cluster", "plan_summary": " | ".join(
                    f"stage{f.id}[{f.partitioning}]"
                    for f in fp.fragments),
                "stages": summary,
                "retries": total_retries,
                "operators": [
                    {"operator": "task " + str(st.get("taskId", "")),
                     "rows": int(st.get("rowsOut") or 0),
                     "bytes": int(st.get("bytesOut") or 0),
                     "batches": 0,
                     "wall_ms": float(st.get("elapsedMs") or 0.0)}
                    for st in monitor.last_statuses],
            }
            self.local.events.query_completed(QueryCompletedEvent(
                query_id=qid, query=entry.query, user=user,
                state=entry.state, elapsed_ms=entry.elapsed_ms,
                error=error, create_time=entry.create_time,
                history=history))
            if LOG.enabled:
                LOG.log("query_completed", query_id=qid, mode="cluster",
                        state=entry.state,
                        elapsed_ms=round(entry.elapsed_ms, 3),
                        error=error, retries=total_retries, **summary)

    def _task_statuses(self, all_tasks: List[str]) -> List[dict]:
        """Best-effort status fetch for every task (single attempt —
        this runs on the completion path, including after a failure, so
        a dead worker must cost ONE timeout, not one per task: the
        first unreachable task skips the rest of that worker)."""
        out: List[dict] = []
        dead: set = set()
        for u in all_tasks:
            base = u.split("/v1/task/")[0]
            if base in dead:
                continue
            try:
                out.append(self._request(u, retries=0, timeout=2))
            except Exception:
                dead.add(base)
        return out

    def _harvest_spans(self, all_tasks: List[str]) -> None:
        """Pull each task's spans (its share of this query's trace) back
        to the coordinator so distributed traces stitch; the tracer
        dedupes by span id, so in-process workers sharing the ring are
        harmless."""
        if not TRACER.enabled:
            return
        # one fetch per distinct WORKER: a task's span export is the
        # worker's whole share of the trace, so per-task fetches would
        # download K duplicate copies for import_spans to throw away
        by_worker: Dict[str, str] = {}
        for u in all_tasks:
            by_worker.setdefault(u.split("/v1/task/")[0], u)
        for u in by_worker.values():
            try:
                st = self._request(f"{u}?spans=1", retries=0, timeout=5)
            except Exception:
                continue
            TRACER.import_spans(st.get("spans") or [])

    def _assign_splits(self, f: PlanFragment,
                       workers: List[str]) -> List[List[Split]]:
        scan = next(n for n in _walk(f.root)
                    if isinstance(n, TableScanNode))
        conn = self.session.catalogs.get(scan.catalog)
        splits = conn.split_manager.splits(scan.table, len(workers))
        out: List[List[Split]] = [[] for _ in workers]
        for i, s in enumerate(splits):
            out[i % len(workers)].append(s)
        return out

    def _create_task(self, worker: str, qid: str, f: PlanFragment,
                     partition: int, n_buffers: int,
                     splits: List[Split], sources: Dict[int, List[str]],
                     init_values: List[object],
                     task_id: Optional[str] = None,
                     retain: bool = False, spool: bool = False,
                     session=None) -> str:
        if task_id is None:
            task_id = f"{qid}.{f.id}.{partition}"
        session = session if session is not None else self.session
        doc = {
            "fragment": codec.encode(f.root),
            "output": {
                "kind": f.output.kind if f.output else "single",
                "keys": list(f.output.keys) if f.output else [],
                "n_buffers": n_buffers,
                # retain=True: acked pages survive in memory so a
                # re-created consumer attempt can re-read from token 0
                # (the spool_exchange=false fallback)
                "retain": bool(retain),
                # spool=True: every page writes through to the durable
                # page-addressed spool (exec/spool.py) — replay
                # storage that outlives this worker process
                "spool": bool(spool),
            },
            "splits": [codec.encode(s) for s in splits],
            "sources": {str(k): v for k, v in sources.items()},
            "partition": partition,
            "session": {
                "catalog": session.catalog,
                "schema": session.schema,
                "properties": {
                    k: v for k, v in session.properties.items()
                    if isinstance(v, (str, int, float, bool))
                },
            },
            "init_values": codec.encode(list(init_values)),
            "rows_per_batch": self.rows_per_batch,
        }
        serving = getattr(session, "serving", None)
        if serving is not None:
            # admitted-query handoff: the worker registers the query's
            # device-scheduler handle under the admitting group's
            # stride share, so cluster queries obey the same group
            # weights as LocalRunner queries (serving/groups.py)
            doc["serving"] = {"group": serving.scheduler_group,
                              "weight": serving.weight,
                              "label": serving.group_path}
        ctx = TRACER.context()
        if ctx is not None:
            # span context over the wire (the stage span is current):
            # the worker's task span joins this trace
            doc["trace"] = ctx
        self._request(f"{worker}/v1/task/{task_id}", method="PUT",
                      body=doc)
        return f"{worker}/v1/task/{task_id}"

    def _release_spool(self, exec_id: str) -> None:
        """Per-query spool GC, everywhere: the coordinator's local
        store (shared with in-process workers) plus a DELETE to every
        worker for node-local spool directories."""
        from .spool import SPOOL
        SPOOL.release_query(exec_id)
        for url in list(self.worker_urls):
            try:
                self._request(f"{url}/v1/spool/{exec_id}",
                              method="DELETE", retries=0, timeout=5)
            except Exception:
                continue

    # -- result collection ---------------------------------------------------
    def _collect(self, fp: FragmentedPlan, run: _QueryExecution,
                 cancel_event=None) -> QueryResult:
        from .pages import deserialize_page
        from ..server.worker import unframe_pages
        out_node = fp.root.root
        names = [f.name for f in out_node.fields]
        types = [f.type for f in out_node.fields]
        rows: List[tuple] = []
        token = 0
        cur = run.root_url()
        while True:
            if cancel_event is not None and cancel_event.is_set():
                # client-side cancel (protocol DELETE): abort every
                # task everywhere and surface the cancellation
                run.abort_all()
                from ..errors import QueryCancelledError
                raise QueryCancelledError("query cancelled")
            run.check_deadline()
            if run.root_url() != cur:
                # the root task was re-created (retry cascade or a
                # speculative win): restart collection from token 0 —
                # every attempt owns its own buffer, so discarding the
                # old attempt's rows makes duplicates impossible
                cur = run.root_url()
                token = 0
                rows = []
            req = urllib.request.Request(
                f"{cur}/results/0/{token}?max_wait=2")
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    body = resp.read()
                    complete = resp.headers.get(
                        "X-Buffer-Complete") == "true"
                    token = int(resp.headers.get("X-Next-Token", token))
            except urllib.error.HTTPError as e:
                # the root answered with a failure (its buffer failed or
                # the task is gone): one recovery round decides between
                # retry and propagating the real error
                detail = e.read().decode(errors="replace")
                if not run.poll() and run.root_url() == cur:
                    raise QueryFailedError(detail) from None
                continue
            except Exception as e:
                # transport error: the root's worker may be gone; the
                # recovery round reschedules its tasks elsewhere
                if not run.poll() and run.root_url() == cur:
                    raise QueryFailedError(str(e)) from None
                continue
            for page in unframe_pages(body):
                rows.extend(deserialize_page(page).to_pylist())
            if complete:
                break
            run.poll()
        return QueryResult(names=names, types=types, rows=rows)


def _walk(node: PlanNode):
    yield node
    for c in node.children:
        yield from _walk(c)

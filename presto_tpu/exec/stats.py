"""Per-operator execution statistics.

The role of the reference's stats objects — OperatorStats/DriverStats
recorded by OperationTimer inside the Driver loop (reference
operator/Driver.java:380-385, operator/OperatorStats.java) and surfaced
through EXPLAIN ANALYZE (operator/ExplainAnalyzeOperator.java): every
plan-node iterator is wrapped to record wall time, batches, and (in
analyze mode, where a device sync per batch is acceptable) live rows.

Wall time is inclusive — a node's clock runs while it waits on its
children — so the printer reports exclusive time by subtracting child
inclusive times, mirroring how the reference separates operator wall
from blocked time.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, Iterator, List, Optional


@dataclasses.dataclass
class NodeStats:
    wall_s: float = 0.0          # inclusive iterator time
    batches: int = 0
    rows: int = 0                # live rows (analyze mode only)
    capacity: int = 0            # total batch capacity emitted


class StatsCollector:
    """Collects NodeStats keyed by plan node (structural equality, the
    same keying as the executor's shared-subplan cache, so a replayed
    duplicate subtree reports the stats of its one real execution)."""

    def __init__(self, count_rows: bool = False):
        self.count_rows = count_rows
        self.by_node: Dict[object, NodeStats] = {}
        self.total_wall_s: float = 0.0
        self.planning_s: float = 0.0
        #: per-split completion records from table scans (the reference's
        #: event/SplitMonitor.java split-completion events): dicts with
        #: table, split, wall_ms, batches, started_at
        self.splits: List[Dict] = []
        #: device scan-cache outcome per split (exec/scancache.py) and
        #: cumulative consumer-side prefetch stall — the EXPLAIN ANALYZE
        #: scan-cache line's feed
        self.cache_hits = 0
        self.cache_misses = 0
        self.prefetch_stall_s = 0.0
        import threading
        # record_cache fires from concurrent prefetch worker threads;
        # an unsynchronized += would drop increments
        self._cache_lock = threading.Lock()

    def record_cache(self, hit: bool) -> None:
        with self._cache_lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1

    def record_split(self, table: str, split_no: int, started_at: float,
                     wall_s: float, batches: int) -> None:
        self.splits.append({
            "table": table, "split": split_no,
            "startMs": round(started_at * 1e3, 1),
            "wallMs": round(wall_s * 1e3, 1), "batches": batches})

    def snapshot(self) -> List[Dict]:
        """JSON-able per-node stats, root-last plan order — the live
        per-stage surface behind GET /v1/query/{id} (reference
        server/QueryResource.java per-stage stats)."""
        out = []
        # copy: the executor thread grows by_node while the live REST
        # endpoint snapshots it
        for node, st in list(self.by_node.items()):
            out.append({
                "node": type(node).__name__.replace("Node", ""),
                "wallMs": round(st.wall_s * 1e3, 1),
                "batches": st.batches,
                "rows": st.rows if self.count_rows else None,
                "capacity": st.capacity,
            })
        return out

    def stats_for(self, node) -> Optional[NodeStats]:
        return self.by_node.get(node)

    def wrap(self, node, it: Iterator) -> Iterator:
        st = self.by_node.setdefault(node, NodeStats())

        def timed():
            while True:
                t0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    st.wall_s += time.perf_counter() - t0
                    return
                st.wall_s += time.perf_counter() - t0
                st.batches += 1
                st.capacity += b.capacity
                if self.count_rows:
                    st.rows += b.host_count()
                yield b
        return timed()

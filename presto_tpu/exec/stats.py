"""Per-operator execution statistics.

The role of the reference's stats objects — OperatorStats/DriverStats
recorded by OperationTimer inside the Driver loop (reference
operator/Driver.java:380-385, operator/OperatorStats.java) and surfaced
through EXPLAIN ANALYZE (operator/ExplainAnalyzeOperator.java): every
plan-node iterator is wrapped to record wall time, batches, and (in
analyze mode, where a device sync per batch is acceptable) live rows.

Wall time is inclusive — a node's clock runs while it waits on its
children — so the printer reports exclusive time by subtracting child
inclusive times, mirroring how the reference separates operator wall
from blocked time.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, Iterator, Optional


@dataclasses.dataclass
class NodeStats:
    wall_s: float = 0.0          # inclusive iterator time
    batches: int = 0
    rows: int = 0                # live rows (analyze mode only)
    capacity: int = 0            # total batch capacity emitted


class StatsCollector:
    """Collects NodeStats keyed by plan node (structural equality, the
    same keying as the executor's shared-subplan cache, so a replayed
    duplicate subtree reports the stats of its one real execution)."""

    def __init__(self, count_rows: bool = False):
        self.count_rows = count_rows
        self.by_node: Dict[object, NodeStats] = {}
        self.total_wall_s: float = 0.0
        self.planning_s: float = 0.0

    def stats_for(self, node) -> Optional[NodeStats]:
        return self.by_node.get(node)

    def wrap(self, node, it: Iterator) -> Iterator:
        st = self.by_node.setdefault(node, NodeStats())

        def timed():
            while True:
                t0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    st.wall_s += time.perf_counter() - t0
                    return
                st.wall_s += time.perf_counter() - t0
                st.batches += 1
                st.capacity += b.capacity
                if self.count_rows:
                    st.rows += b.host_count()
                yield b
        return timed()

"""Per-operator execution statistics.

The role of the reference's stats objects — OperatorStats/DriverStats
recorded by OperationTimer inside the Driver loop (reference
operator/Driver.java:380-385, operator/OperatorStats.java) and surfaced
through EXPLAIN ANALYZE (operator/ExplainAnalyzeOperator.java): every
plan-node iterator is wrapped to record wall time, batches, and (in
analyze mode, where a device sync per batch is acceptable) live rows.

Wall time is inclusive — a node's clock runs while it waits on its
children — so the printer reports exclusive time by subtracting child
inclusive times, mirroring how the reference separates operator wall
from blocked time.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, Iterator, List, Optional


@dataclasses.dataclass
class NodeStats:
    wall_s: float = 0.0          # inclusive iterator time
    batches: int = 0
    rows: int = 0                # live rows (analyze mode only)
    capacity: int = 0            # total batch capacity emitted
    #: device seconds attributed by the profiler (profile mode only:
    #: jit dispatches made in this operator's frame, bracketed with
    #: block_until_ready — obs/profiler.py)
    device_time_s: float = 0.0


class StatsCollector:
    """Collects NodeStats keyed by plan node (structural equality, the
    same keying as the executor's shared-subplan cache, so a replayed
    duplicate subtree reports the stats of its one real execution)."""

    def __init__(self, count_rows: bool = False):
        self.count_rows = count_rows
        self.by_node: Dict[object, NodeStats] = {}
        self.total_wall_s: float = 0.0
        self.planning_s: float = 0.0
        #: per-split completion records from table scans (the reference's
        #: event/SplitMonitor.java split-completion events): dicts with
        #: table, split, wall_ms, batches, started_at
        self.splits: List[Dict] = []
        #: device scan-cache outcome per split (exec/scancache.py) and
        #: cumulative consumer-side prefetch stall — the EXPLAIN ANALYZE
        #: scan-cache line's feed
        self.cache_hits = 0
        self.cache_misses = 0
        self.prefetch_stall_s = 0.0
        #: plan node -> {ExecutableRecord: [invocations, device_s]} —
        #: which executables each operator dispatched while profiled;
        #: FLOPs/HBM bytes derive at render time (record.analyze() is
        #: lazy XLA introspection, never paid per call)
        self.exe_by_node: Dict[object, Dict[object, list]] = {}
        #: plan node -> (strategy, distribution) the join dispatch
        #: actually executed (direct/sorted/expand x replicated/
        #: partitioned) — the EXPLAIN ANALYZE join-row annotation and
        #: the per-query view of join_strategy_selected_total
        self.join_strategy: Dict[object, tuple] = {}
        import threading
        # record_cache fires from concurrent prefetch worker threads;
        # an unsynchronized += would drop increments
        self._cache_lock = threading.Lock()

    def record_cache(self, hit: bool) -> None:
        with self._cache_lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1

    def record_device(self, node, seconds: float, record) -> None:
        """Charge one profiled jit dispatch to a plan operator
        (obs/profiler.profiled_call's attribution sink)."""
        with self._cache_lock:
            st = self.by_node.setdefault(node, NodeStats())
            st.device_time_s += seconds
            ent = self.exe_by_node.setdefault(node, {}).setdefault(
                record, [0, 0.0])
            ent[0] += 1
            ent[1] += seconds

    def device_for(self, node) -> Optional[Dict]:
        """Per-operator device truth for the printer/history:
        ``device_time_s`` plus FLOPs / bytes-accessed estimates
        (per-invocation cost analysis x invocation count). None when
        the operator dispatched nothing under a profile context."""
        st = self.by_node.get(node)
        counts = self.exe_by_node.get(node)
        if (st is None or st.device_time_s <= 0.0) and not counts:
            return None
        flops = 0.0
        hbm = 0.0
        for rec, (n, _secs) in list((counts or {}).items()):
            a = rec.analyze()
            flops += (a.get("flops") or 0.0) * n
            hbm += (a.get("bytes_accessed") or 0.0) * n
        return {"device_time_s": st.device_time_s if st else 0.0,
                "flops": flops, "hbm_bytes": hbm}

    def executables_used(self) -> List[Dict]:
        """This query's executables, aggregated across operators —
        the EXPLAIN ANALYZE "Executables" section feed (the
        ``system.runtime.executables`` table is the process-lifetime
        view of the same records)."""
        agg: Dict[object, list] = {}
        for per_node in list(self.exe_by_node.values()):
            for rec, (n, secs) in list(per_node.items()):
                ent = agg.setdefault(rec, [0, 0.0])
                ent[0] += n
                ent[1] += secs
        out = []
        for rec, (n, secs) in agg.items():
            a = rec.analyze()
            out.append({
                "name": rec.name, "static_key": rec.static_key,
                "invocations": n, "device_time_s": secs,
                "compile_seconds": rec.compile_seconds,
                "flops": a.get("flops"),
                "bytes_accessed": a.get("bytes_accessed"),
            })
        out.sort(key=lambda d: -d["device_time_s"])
        return out

    def record_join_strategy(self, node, strategy: str,
                             distribution: str) -> None:
        """Executed join-dispatch verdict for one join/semi-join
        operator (exec/local._Executor._note_join_strategy's sink)."""
        self.join_strategy[node] = (strategy, distribution)

    def join_strategy_for(self, node) -> Optional[tuple]:
        return self.join_strategy.get(node)

    def record_split(self, table: str, split_no: int, started_at: float,
                     wall_s: float, batches: int) -> None:
        self.splits.append({
            "table": table, "split": split_no,
            "startMs": round(started_at * 1e3, 1),
            "wallMs": round(wall_s * 1e3, 1), "batches": batches})

    def snapshot(self) -> List[Dict]:
        """JSON-able per-node stats, root-last plan order — the live
        per-stage surface behind GET /v1/query/{id} (reference
        server/QueryResource.java per-stage stats)."""
        out = []
        # copy: the executor thread grows by_node while the live REST
        # endpoint snapshots it
        for node, st in list(self.by_node.items()):
            out.append({
                "node": type(node).__name__.replace("Node", ""),
                "wallMs": round(st.wall_s * 1e3, 1),
                "batches": st.batches,
                "rows": st.rows if self.count_rows else None,
                "capacity": st.capacity,
            })
        return out

    def stats_for(self, node) -> Optional[NodeStats]:
        return self.by_node.get(node)

    def wrap(self, node, it: Iterator) -> Iterator:
        st = self.by_node.setdefault(node, NodeStats())
        from ..obs.profiler import operator_scope

        def timed():
            while True:
                t0 = time.perf_counter()
                try:
                    # operator attribution: jit dispatches made while
                    # THIS node's generator frame runs charge to it;
                    # nested child iterators re-set the scope around
                    # their own frames (innermost wins), so a join's
                    # kernels bill the join, its child scan's staging
                    # bills the scan
                    with operator_scope(self, node):
                        b = next(it)
                except StopIteration:
                    st.wall_s += time.perf_counter() - t0
                    return
                st.wall_s += time.perf_counter() - t0
                st.batches += 1
                st.capacity += b.capacity
                if self.count_rows:
                    st.rows += b.host_count()
                yield b
        return timed()

"""Whole-pipeline fusion: one jitted program per join probe pipeline.

The reference compiles each operator to bytecode but still moves data
between operators one Page at a time through the Driver loop (reference
operator/Driver.java:367-400). On this backend the equivalent
per-operator dispatch is far more expensive: every operator boundary is
a separate XLA executable whose outputs MATERIALIZE in HBM — a chain of
N unique-build dimension joins re-writes the full fact-table width N
times and pays N kernel-launch round trips per batch (the "~15 gather
passes" q27 diagnosis in docs/perf.md).

This module fuses a probe pipeline — a chain of unique-build lookup
joins, filters, and projections over one streaming source — into ONE
jitted function. XLA then keeps intermediate columns in registers/HBM
exactly once, dead columns are eliminated end-to-end, and a probe batch
pays one dispatch for the whole chain. The analogue in spirit of the
reference's ScanFilterAndProjectOperator fusion (reference
operator/ScanFilterAndProjectOperator.java:62), generalized to join
chains.

Fusion is semantics-preserving: each stage applies the SAME kernel the
standalone operator would (lookup_join / eval_expr), so results are
identical; only materialization boundaries change. The executor decides
WHAT to fuse (exec/local.py _try_fused_chain) and keeps the generic
per-operator path for everything else (skewed builds, residual filters,
outer tails, shared subtrees).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..batch import Batch, Column, Schema
from ..expr import ir
from ..expr.compiler import Val, eval_expr, merge_err
from .. import types as T  # noqa: F401  (type objects live in stage fields)
from ..ops.join import lookup_join, semi_join_mask


@dataclasses.dataclass(frozen=True)
class JoinStage:
    """One unique-build lookup join. ``dyn_keys`` are probe-schema column
    indices with runtime [lo, hi] bounds from the build summary (inner
    joins only) — values arrive as traced scalars so changing bounds
    never recompiles. ``pallas`` routes this stage's probe through the
    fused Pallas ragged-gather kernel (ops/pallas_join) — the executor
    sets it only for direct-address prepared builds within the VMEM
    budget, and strips it (strip_pallas) if the kernel fails to lower."""
    lkeys: Tuple[int, ...]
    rkeys: Tuple[int, ...]
    payload: Tuple[int, ...]
    names: Tuple[str, ...]
    join_type: str                        # inner | left
    out_fields: Tuple[Tuple[str, object], ...]
    dyn_keys: Tuple[int, ...] = ()
    pallas: bool = False


def strip_pallas(stages: Tuple[object, ...]) -> Tuple[object, ...]:
    """The same chain with every JoinStage forced onto the XLA gather
    path — the fused-pipeline fallback after a kernel compile failure."""
    return tuple(dataclasses.replace(st, pallas=False)
                 if isinstance(st, JoinStage) and st.pallas else st
                 for st in stages)


@dataclasses.dataclass(frozen=True)
class FilterStage:
    pred: ir.Expr


@dataclasses.dataclass(frozen=True)
class ProjectStage:
    exprs: Tuple[ir.Expr, ...]
    out_names: Tuple[str, ...]


def _vals(batch: Batch):
    inputs = [Val(c.data, c.validity, c.type, c.dictionary)
              for c in batch.columns]
    if not inputs:
        inputs = [Val(batch.row_mask, batch.row_mask, T.BOOLEAN)]
    return inputs


def _apply_stages(cur: Batch, stages, preps, builds, dyns, errs):
    """Apply stages in order over a traced batch; joins consume
    preps/builds/dyns positionally. Appends per-stage error scalars to
    ``errs``; returns the resulting batch."""
    ji = 0
    for st in stages:
        if isinstance(st, JoinStage):
            if st.dyn_keys:
                keep = cur.row_mask
                b = dyns[ji]
                for j, ki in enumerate(st.dyn_keys):
                    c = cur.columns[ki]
                    keep = keep & c.validity & (c.data >= b[j, 0]) \
                        & (c.data <= b[j, 1])
                cur = Batch(cur.schema, cur.columns, keep)
            if st.pallas:
                from ..ops.pallas_join import lookup_join_direct
                out = lookup_join_direct(cur, builds[ji], st.lkeys,
                                         st.rkeys, st.payload, st.names,
                                         st.join_type, preps[ji])
            else:
                out = lookup_join(cur, builds[ji], st.lkeys, st.rkeys,
                                  st.payload, st.names, st.join_type,
                                  prepared=preps[ji])
            cur = Batch(Schema(list(st.out_fields)), out.columns,
                        out.row_mask)
            ji += 1
        elif isinstance(st, FilterStage):
            p = eval_expr(st.pred, _vals(cur))
            keep = cur.row_mask & p.valid & p.data
            if p.err is not None:
                errs.append(jnp.max(jnp.where(cur.row_mask, p.err,
                                              jnp.int32(0))))
            cur = Batch(cur.schema, cur.columns, keep)
        else:  # ProjectStage
            outs = [eval_expr(e, _vals(cur)) for e in st.exprs]
            cols = [Column(o.type, o.data, o.valid & cur.row_mask,
                           o.dictionary) for o in outs]
            row_errs = merge_err(*[o.err for o in outs])
            if row_errs is not None:
                errs.append(jnp.max(jnp.where(cur.row_mask, row_errs,
                                              jnp.int32(0))))
            cur = Batch(Schema([(n, e.type) for n, e in
                                zip(st.out_names, st.exprs)]),
                        cols, cur.row_mask)
    return cur


def _merge_errs(errs) -> Optional[jnp.ndarray]:
    if not errs:
        return None
    err = errs[0]
    for e in errs[1:]:
        err = jnp.maximum(err, e)
    return err


@functools.lru_cache(maxsize=None)
def fused_pipeline(stages: Tuple[object, ...]):
    """jitted fn(probe, preps, builds, dyns) -> (Batch, err_or_None).

    ``preps``/``builds``/``dyns`` are tuples with one entry per JoinStage
    (bottom-up order); ``dyns[i]`` is an [n_bounds, 2] i64 array aligned
    with that stage's dyn_keys. Capacity/schema specialization happens
    inside jax.jit (pytree structure + shapes are the dispatch key), so
    one cache entry serves every batch size bucket of the chain.
    """

    def run(probe: Batch, preps, builds, dyns):
        errs = []
        cur = _apply_stages(probe, stages, preps, builds, dyns, errs)
        return cur, _merge_errs(errs)

    # _TimedEntry: the fused chain is an executable like any jitcache
    # entry — compile time, invocations, and (under a profile context)
    # device time land in obs.profiler.EXECUTABLES, attributed to the
    # join node whose frame dispatches the chain
    from ..ops.jitcache import _TimedEntry
    return _TimedEntry("fused_pipeline", jax.jit(run), stages)


@functools.lru_cache(maxsize=None)
def fused_prefilter(stages: Tuple[object, ...],
                    pre_keys: Tuple[int, ...],
                    semi_keys: Optional[Tuple[Tuple[int, ...],
                                              Tuple[int, ...]]]):
    """jitted fn(probe, pre_bounds, semi_build, semi_prep)
    -> (Batch, err_or_None, live_count).

    The selectivity-first head of a fused join chain: ALL the chain's
    hoistable dynamic-filter key bounds (``pre_keys`` index the SOURCE
    schema; ``pre_bounds`` is the aligned [m, 2] i64 traced array) are
    evaluated on the raw source batch, then the source-side
    filter/project stages run, then — when the first join is inner —
    its key-membership mask (``semi_keys`` = (lkeys, rkeys)) gates the
    lanes WITHOUT gathering any payload. Payload gathers happen in the
    tail pipeline, after the executor compacts the surviving lanes — so
    a selective first join no longer gathers its build columns for all
    2^20 lanes per batch.

    ``live_count`` is a TRACED scalar (no readback here): the executor
    stacks a window of counts and syncs them in one RTT
    (exec/local.py:_run_fused_chain), amortizing the per-batch
    compaction liveness readback."""

    def run(probe: Batch, pre_bounds, semi_build, semi_prep):
        keep = probe.row_mask
        for j, ki in enumerate(pre_keys):
            c = probe.columns[ki]
            keep = keep & c.validity & (c.data >= pre_bounds[j, 0]) \
                & (c.data <= pre_bounds[j, 1])
        cur = Batch(probe.schema, probe.columns, keep)
        errs = []
        cur = _apply_stages(cur, stages, (), (), (), errs)
        if semi_keys is not None:
            lkeys, rkeys = semi_keys
            m = semi_join_mask(cur, semi_build, list(lkeys), list(rkeys),
                               negated=False, null_aware=False,
                               prepared=semi_prep)
            cur = Batch(cur.schema, cur.columns, cur.row_mask & m)
        count = jnp.sum(cur.row_mask.astype(jnp.int32))
        return cur, _merge_errs(errs), count

    from ..ops.jitcache import _TimedEntry
    return _TimedEntry("fused_prefilter", jax.jit(run),
                       (stages, pre_keys, semi_keys))

"""Driver: the batch-moving hot loop.

Conceptual parity with Presto's Driver (reference
presto-main/.../operator/Driver.java:262 processFor / :347 processInternal,
page-move loop :367-400): repeatedly move output batches between adjacent
operators, propagate finish() upstream-to-downstream, and yield after a time
quantum so a task scheduler can interleave drivers (reference
execution/executor/PrioritizedSplitRunner.java SPLIT_RUN_QUANTA).
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence

from ..batch import Batch
from .operators import Operator


class Pipeline:
    """A linear chain of operators, source first (reference DriverFactory)."""

    def __init__(self, operators: Sequence[Operator]):
        assert operators, "empty pipeline"
        self.operators = list(operators)


class Driver:
    """Executes one pipeline instance (one 'driver' per split in Presto)."""

    def __init__(self, pipeline: Pipeline, sink):
        self.ops = pipeline.operators
        self.sink = sink  # callable(batch)
        self._finish_sent = [False] * len(self.ops)
        self._done = False

    def is_finished(self) -> bool:
        return self._done

    def process_for(self, quantum_seconds: float = 1.0) -> None:
        """Run until the quantum expires or the pipeline finishes
        (reference Driver.processFor:262)."""
        deadline = time.monotonic() + quantum_seconds
        while not self._done and time.monotonic() < deadline:
            if not self._step():
                break

    def close(self) -> None:
        """Release operator resources; safe to call repeatedly. Runs on
        normal completion and on abandonment/failure alike."""
        for op in self.ops:
            op.close()

    def run_to_completion(self) -> None:
        try:
            while not self._done:
                if not self._step():
                    # no progress and not done: pipeline is stuck
                    if not self._done:
                        raise RuntimeError("pipeline made no progress")
        finally:
            self.close()

    def _step(self) -> bool:
        """One pass over adjacent operator pairs; returns progress.

        Moves at most ONE batch per pair per pass (like processInternal's
        page-move loop) so process_for's quantum stays meaningful — a
        greedy drain here would run a whole scan before the deadline check.
        """
        ops = self.ops
        progress = False
        for i in range(len(ops) - 1):
            cur, nxt = ops[i], ops[i + 1]
            if nxt.needs_input():
                out = cur.get_output()
                if out is not None:
                    nxt.add_input(out)
                    progress = True
            if cur.is_finished() and not self._finish_sent[i + 1]:
                nxt.finish()
                self._finish_sent[i + 1] = True
                progress = True
        # drain the last operator into the sink
        last = ops[-1]
        while True:
            out = last.get_output()
            if out is None:
                break
            self.sink(out)
            progress = True
        if last.is_finished():
            self._done = True
            self.close()
        return progress


def run_pipeline(operators: Sequence[Operator]) -> List[Batch]:
    """Convenience: run a pipeline to completion, collecting output batches."""
    results: List[Batch] = []
    d = Driver(Pipeline(operators), results.append)
    # sources need their finish() too when they self-report finished
    d.run_to_completion()
    return results

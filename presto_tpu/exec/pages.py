"""Page wire format: Batch <-> bytes for exchange, spill, and clients.

The role of the reference's PagesSerde (reference
presto-main/.../execution/buffer/PagesSerde.java:42-60 length-prefixed
block encodings + optional LZ4, marker byte PageCodecMarker;
SerializedPage.java) re-designed for the device-columnar batch:

- live rows are compacted host-side before encoding (wire carries no
  padding or dead rows);
- per column: packed validity bitmap + raw little-endian storage array
  (bool stored as u8) + the dictionary vocabulary for string columns;
- one marker byte selects compression (zlib level 1 — stdlib; the
  reference's LZ4 role of cheap-but-real wire compression);
- schema travels as a compact JSON header (names + type displays
  round-trip through types.parse_type).

The format is self-describing: deserialize_page needs no side channel.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np

from .. import types as T
from ..batch import Batch, Schema

MAGIC = b"PTPG"
_VERSION = 1
_MARKER_ZLIB = 1


def _header(batch_schema: Schema, n: int,
            dicts: List[Optional[Tuple[str, ...]]]) -> bytes:
    doc = {
        "names": batch_schema.names,
        "types": [t.display() for t in batch_schema.types],
        "n": n,
        "dicts": [list(d) if d is not None else None for d in dicts],
    }
    return json.dumps(doc, separators=(",", ":")).encode("utf-8")


def _encode(schema: Schema, arrays: List[np.ndarray],
            valids: List[np.ndarray],
            dicts: List[Optional[Tuple[str, ...]]],
            compress: bool) -> bytes:
    n = len(arrays[0]) if arrays else 0
    parts: List[bytes] = []
    for data, valid in zip(arrays, valids):
        parts.append(np.packbits(valid, bitorder="little").tobytes())
        if data.dtype == np.bool_:
            data = data.astype(np.uint8)
        parts.append(np.ascontiguousarray(data).tobytes())
    header = _header(schema, n, dicts)
    payload = struct.pack("<I", len(header)) + header + b"".join(parts)
    marker = 0
    if compress and len(payload) > 256:
        squeezed = zlib.compress(payload, level=1)
        if len(squeezed) < len(payload):
            payload, marker = squeezed, _MARKER_ZLIB
    return MAGIC + struct.pack("<BB", _VERSION, marker) + payload


def _host_columns(batch: Batch):
    mask = np.asarray(batch.row_mask)
    arrays = [np.asarray(c.data)[mask] for c in batch.columns]
    valids = [np.asarray(c.validity)[mask] for c in batch.columns]
    dicts = [c.dictionary if c.type.is_string else None
             for c in batch.columns]
    return mask, arrays, valids, dicts


def serialize_page(batch: Batch, compress: bool = True) -> bytes:
    """Encode a batch's live rows. Host-syncs the batch (device -> host)."""
    _, arrays, valids, dicts = _host_columns(batch)
    return _encode(batch.schema, arrays, valids, dicts, compress)


def serialize_partitioned(batch: Batch, key_indices: List[int],
                          n_parts: int,
                          compress: bool = True) -> List[Optional[bytes]]:
    """Hash-partition live rows by key columns (value-deterministic, so
    both join sides land matching rows in the same bucket) and encode one
    page per non-empty partition — the producer half of the exchange
    (reference operator/PartitionedOutputOperator.java:48)."""
    from ..parallel.exchange import hash_partition_ids
    pid = np.asarray(hash_partition_ids(batch, key_indices, n_parts))
    mask, arrays, valids, dicts = _host_columns(batch)
    pid = pid[mask]
    out: List[Optional[bytes]] = []
    for p in range(n_parts):
        sel = pid == p
        if not sel.any():
            out.append(None)
            continue
        out.append(_encode(batch.schema,
                           [a[sel] for a in arrays],
                           [v[sel] for v in valids], dicts, compress))
    return out


def deserialize_arrays(data: bytes):
    """Decode a page to host numpy: (schema, arrays, validities, dicts, n)
    — the spill readback path, which concatenates before device upload."""
    if data[:4] != MAGIC:
        raise ValueError("bad page magic")
    version, marker = struct.unpack_from("<BB", data, 4)
    if version != _VERSION:
        raise ValueError(f"unsupported page version {version}")
    payload = data[6:]
    if marker & _MARKER_ZLIB:
        payload = zlib.decompress(payload)
    (hlen,) = struct.unpack_from("<I", payload, 0)
    doc = json.loads(payload[4:4 + hlen].decode("utf-8"))
    n = doc["n"]
    schema = Schema(list(zip(doc["names"],
                             [T.parse_type(t) for t in doc["types"]])))
    dicts = [tuple(d) if d is not None else None for d in doc["dicts"]]
    off = 4 + hlen
    vbytes = (n + 7) // 8
    arrays: List[np.ndarray] = []
    validities: List[np.ndarray] = []
    for typ in schema.types:
        valid = np.unpackbits(
            np.frombuffer(payload, dtype=np.uint8, count=vbytes, offset=off),
            bitorder="little")[:n].astype(bool)
        off += vbytes
        dt = np.dtype(typ.storage_dtype)
        wire_dt = np.dtype(np.uint8) if dt == np.bool_ else dt
        # fixed-width vector columns (HLL register states) carry
        # width values per row; the type's display round-trips the width
        width = getattr(typ, "storage_width", None) or 1
        arr = np.frombuffer(payload, dtype=wire_dt, count=n * width,
                            offset=off)
        off += n * width * wire_dt.itemsize
        if width > 1:
            arr = arr.reshape(n, width)
        if dt == np.bool_:
            arr = arr.astype(bool)
        arrays.append(arr)
        validities.append(valid)
    return schema, arrays, validities, dicts, n


def deserialize_page(data: bytes) -> Batch:
    """Decode one serialized page back into a device batch."""
    schema, arrays, validities, dicts, n = deserialize_arrays(data)
    return Batch.from_arrays(schema, arrays, validities, dicts, num_rows=n)

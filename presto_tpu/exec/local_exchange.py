"""Local exchange: batch redistribution between pipelines in one process.

The role of the reference's intra-task exchange (reference
presto-main/.../operator/exchange/LocalExchange.java:105-125 dispatching
SINGLE / FIXED_BROADCAST / FIXED_ARBITRARY / FIXED_HASH /
FIXED_PASSTHROUGH partitioning, LocalPartitionGenerator): producers push
device batches into bounded per-consumer queues and N consumer iterators
drain them. On a single TPU chip the device serializes kernels, so the
parallelism this buys is HOST-side: overlapping host staging/decode with
device dispatch, and letting independent pipeline stages (join build vs
probe scan) run concurrently — the same reason the reference runs
multiple drivers per task (execution/executor/TaskExecutor.java).

Modes:
- single:      every batch to consumer 0
- broadcast:   every batch to every consumer (by reference — batches are
               immutable device values)
- round_robin: batch i to consumer i % n (FIXED_ARBITRARY's role)
- hash:        rows split by key hash; consumer c gets the sub-batch
               whose rows hash to c (FIXED_HASH; same splitmix64 row
               hash as the distributed exchange, so colocation
               agreements hold)
- passthrough: producer p feeds consumer p 1:1 (FIXED_PASSTHROUGH)
"""
from __future__ import annotations

import contextvars
import queue as _queue
import threading
import time
from typing import Callable, Iterator, List, Optional, Sequence

from ..batch import Batch

_DONE = object()

MODES = ("single", "broadcast", "round_robin", "hash", "passthrough")


class LocalExchange:
    def __init__(self, mode: str, n_consumers: int,
                 key_cols: Optional[Sequence[int]] = None,
                 buffer_batches: int = 4):
        assert mode in MODES, mode
        if mode == "hash" and not key_cols:
            raise ValueError("hash mode needs key columns")
        self.mode = mode
        self.n = n_consumers
        self.key_cols = list(key_cols or ())
        self._queues = [_queue.Queue(maxsize=buffer_batches)
                        for _ in range(n_consumers)]
        self._rr = 0
        self._failed: Optional[BaseException] = None
        self._closed = threading.Event()
        #: producer thread (exchange_source) — joined by close() so the
        #: subplan driver can't outlive the consumer that aborted it
        self._producer: Optional[threading.Thread] = None

    # -- producer side -------------------------------------------------------
    def push(self, batch: Batch, producer: int = 0) -> None:
        if self.mode == "single":
            self._put(0, batch)
        elif self.mode == "broadcast":
            for c in range(self.n):
                self._put(c, batch)
        elif self.mode == "round_robin":
            self._put(self._rr % self.n, batch)
            self._rr += 1
        elif self.mode == "passthrough":
            self._put(producer % self.n, batch)
        else:    # hash
            from ..parallel.exchange import hash_partition_ids
            pid = hash_partition_ids(batch, self.key_cols, self.n)
            for c in range(self.n):
                keep = batch.row_mask & (pid == c)
                self._put(c, Batch(batch.schema, batch.columns, keep))

    def finish(self, error: Optional[BaseException] = None) -> None:
        """Signal end-of-stream (or failure) to every consumer."""
        if error is not None:
            self._failed = error
        for c in range(self.n):
            self._put(c, _DONE, force=True)

    def close(self) -> None:
        """Consumer-side abort: unblock producers (e.g. LIMIT satisfied)
        AND any consumer still blocked in ``get`` (each queue gets a
        terminal DONE after the drain), then join the producer thread —
        an orphaned producer keeps driving the upstream subplan and
        touching shared state through teardown."""
        self._closed.set()
        for q in self._queues:
            try:
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass
            try:
                q.put_nowait(_DONE)
            except _queue.Full:
                pass
        if self._producer is not None and self._producer.is_alive():
            # bounded: the producer notices _closed within one 0.1s put
            # timeout; anything longer is upstream compute finishing
            self._producer.join(timeout=5.0)

    def start_producer(self, produce: Callable[[], None]) -> None:
        """Run ``produce`` on an owned daemon thread; close() joins it.
        Runs in a copy of the caller's context: the profile flag
        (obs/profiler._ACTIVE) and trace parentage must follow the
        pipeline onto its producer thread — a profiled query's join
        kernels run HERE, and losing the contextvar would silently drop
        their device-time attribution (per-operator scopes still re-set
        themselves inside this thread via StatsCollector.wrap)."""
        ctx = contextvars.copy_context()
        self._producer = threading.Thread(target=ctx.run,
                                          args=(produce,), daemon=True)
        self._producer.start()

    def _put(self, c: int, item, force: bool = False) -> None:
        while not self._closed.is_set():
            try:
                self._queues[c].put(item, timeout=0.1)
                return
            except _queue.Full:
                if force:
                    continue
        if force:    # DONE must always land so consumers terminate
            try:
                self._queues[c].put_nowait(item)
            except _queue.Full:
                pass

    # -- consumer side -------------------------------------------------------
    def consumer(self, c: int) -> Iterator[Batch]:
        q = self._queues[c]
        while True:
            item = q.get()
            if item is _DONE:
                if self._failed is not None:
                    raise self._failed
                return
            yield item

    def consumers(self) -> List[Iterator[Batch]]:
        return [self.consumer(c) for c in range(self.n)]


def exchange_source(batches: Iterator[Batch], mode: str, n_consumers: int,
                    key_cols: Optional[Sequence[int]] = None,
                    buffer_batches: int = 4) -> LocalExchange:
    """Spawn a producer thread draining ``batches`` into a LocalExchange —
    the driver-decoupling shape of LocalExchangeSourceOperator."""
    ex = LocalExchange(mode, n_consumers, key_cols, buffer_batches)

    def produce() -> None:
        try:
            for b in batches:
                if ex._closed.is_set():
                    # consumer aborted (LIMIT satisfied / query failed):
                    # stop driving the upstream subplan, don't just drop
                    # its batches
                    break
                ex.push(b)
        except BaseException as e:   # surfaced on the consumer side
            ex.finish(e)
            return
        finally:
            close = getattr(batches, "close", None)
            if close is not None:
                close()
        ex.finish()

    ex.start_producer(produce)
    return ex


def parallel_drivers(batches: Iterator[Batch],
                     driver_fn: Callable[[Batch], Batch],
                     concurrency: int,
                     buffer_batches: int = 4) -> Iterator[Batch]:
    """Fan ``batches`` over N driver threads each applying ``driver_fn``,
    yielding results as they complete (unordered) — the multi-driver
    pipeline of reference SqlTaskExecution (one driver per split,
    TaskExecutor time-slicing). Device kernels still serialize on the
    chip; the win is overlapping the drivers' host-side work."""
    if concurrency <= 1:
        for b in batches:
            yield driver_fn(b)
        return
    ex = exchange_source(batches, "round_robin", concurrency,
                         buffer_batches=buffer_batches)
    out: _queue.Queue = _queue.Queue(maxsize=concurrency * 2)
    errors: List[BaseException] = []

    def drive(c: int) -> None:
        try:
            for b in ex.consumer(c):
                out.put(("row", driver_fn(b)))
        except BaseException as e:
            errors.append(e)
        finally:
            out.put(("done", None))

    drivers: List[threading.Thread] = []
    for c in range(concurrency):
        # one context copy per driver (a Context can't be entered twice
        # concurrently) — same propagation contract as exchange_source
        ctx = contextvars.copy_context()
        t = threading.Thread(target=ctx.run, args=(drive, c),
                             daemon=True)
        drivers.append(t)
        t.start()
    done = 0
    try:
        while done < concurrency:
            kind, item = out.get()
            if kind == "done":
                done += 1
                continue
            yield item
    finally:
        ex.close()
        # early exit (LIMIT / generator closed): drivers may be blocked
        # on a full ``out`` — drain it until they notice the closed
        # exchange, then join (bounded; normal path joins immediately)
        deadline = time.monotonic() + 5.0
        while any(t.is_alive() for t in drivers) \
                and time.monotonic() < deadline:
            try:
                out.get_nowait()
            except _queue.Empty:
                time.sleep(0.01)
        for t in drivers:
            t.join(timeout=1.0)
    if errors:
        raise errors[0]
"""Two-tier spill: host DRAM first, then compressed pages on disk.

The TPU reshape of the reference's spill stack (reference
presto-main/.../spiller/GenericPartitioningSpiller.java for partitioned
join spill, operator/aggregation/builder/SpillableHashAggregationBuilder.java
for agg state, OrderByOperator.java + FileSingleStreamSpiller.java for
sort): the first "disk" is host DRAM (device_get), the natural spill tier
on a TPU host; when staged host bytes cross the pool's disk threshold,
chunks flush as compressed wire pages (exec/pages.py serde — the
reference's PagesSerde+LZ4 role) to a per-store temp file, partition-
sliced so readback is ranged reads. Partition ids are computed ON DEVICE
with the same value-based splitmix64 row hash the exchange uses — so a
spilled build partition and its probe partition agree by construction,
including for dictionary-encoded strings (hashed by VALUE, not per-chunk
code).

Buffers accumulate device batches against an OperatorMemoryContext; when
the pool can't fit the next batch (or another operator revokes them) they
stage everything to host numpy arrays and keep accepting input host-side.
Each staged chunk is bucketed once at staging time (argsort of partition
ids), so per-partition readback is slicing, not a rescan.
"""
from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..batch import (
    Batch, Schema, apply_remap_np, bucket_capacity, concat_batches,
    unify_dictionaries, vocab_column,
)
from ..memory import QueryMemoryPool, batch_device_bytes
from ..obs.metrics import REGISTRY
from ..obs.trace import TRACER
from ..ops.aggregation import AggSpec
from ..ops.jitcache import grouped_aggregate_jit as grouped_aggregate
from ..ops.sort import SortKey, sort_batch
from ..parallel.exchange import hash_partition_ids

#: process-wide spill metrics (per-query figures live on the pool's
#: MemoryStats; these are the fleet view behind system.runtime.metrics)
_SPILL_DEVICE_BYTES = REGISTRY.counter("spill_device_bytes_total")
_SPILL_HOST_BYTES = REGISTRY.counter("spill_host_staged_bytes_total")
_SPILL_DISK_BYTES = REGISTRY.counter("spill_disk_bytes_total")
_SPILL_REVOCATIONS = REGISTRY.counter("spill_revocations_total")


@dataclasses.dataclass
class _StagedChunk:
    datas: List[np.ndarray]
    valids: List[np.ndarray]
    dicts: List[Optional[Tuple[str, ...]]]
    part_rows: np.ndarray              # live row indices, partition-sorted
    bounds: Optional[np.ndarray]       # partition p = part_rows[b[p]:b[p+1]]

    def rows_of(self, p: Optional[int]) -> np.ndarray:
        if p is None or self.bounds is None:
            return self.part_rows
        return self.part_rows[self.bounds[p]:self.bounds[p + 1]]


def _stage_chunk(batch: Batch, pid=None,
                 n_partitions: Optional[int] = None) -> _StagedChunk:
    mask = np.asarray(batch.row_mask)
    live = np.nonzero(mask)[0]
    if pid is None:
        part_rows, bounds = live, None
    else:
        p = np.asarray(pid)[live]
        order = np.argsort(p, kind="stable")
        part_rows = live[order]
        bounds = np.searchsorted(p[order], np.arange(n_partitions + 1))
    return _StagedChunk(
        datas=[np.asarray(c.data) for c in batch.columns],
        valids=[np.asarray(c.validity) for c in batch.columns],
        dicts=[c.dictionary for c in batch.columns],
        part_rows=part_rows, bounds=bounds)


def _gather_chunks(schema: Schema,
                   selections: Iterable[Tuple[_StagedChunk, np.ndarray]]):
    """Concatenate selected rows across staged chunks, unifying string
    dictionaries incrementally. Returns (arrays, validity, vocabs) or
    None when no rows are selected."""
    ncols = len(schema)
    datas: List[List[np.ndarray]] = [[] for _ in range(ncols)]
    valids: List[List[np.ndarray]] = [[] for _ in range(ncols)]
    vocabs: List[Optional[Tuple[str, ...]]] = [None] * ncols
    any_rows = False
    for ch, rows in selections:
        if rows.size == 0:
            continue
        any_rows = True
        for ci in range(ncols):
            d = ch.datas[ci][rows]
            v = ch.valids[ci][rows]
            if ch.dicts[ci] is not None:
                if vocabs[ci] is None:
                    vocabs[ci] = ch.dicts[ci]
                elif vocabs[ci] != ch.dicts[ci]:
                    merged, remaps = unify_dictionaries(
                        [vocab_column(vocabs[ci]),
                         vocab_column(ch.dicts[ci])])
                    vocabs[ci] = merged
                    datas[ci] = [apply_remap_np(a, remaps[0])
                                 for a in datas[ci]]
                    d = apply_remap_np(d, remaps[1])
            datas[ci].append(d)
            valids[ci].append(v)
    if not any_rows:
        return None
    arrays = [np.concatenate(datas[ci]) for ci in range(ncols)]
    valid_arr = [np.concatenate(valids[ci]) for ci in range(ncols)]
    return arrays, valid_arr, vocabs


class SpillFile:
    """Append-only spill file of compressed wire pages (the role of
    reference spiller/FileSingleStreamSpiller.java's async file IO,
    synchronous here — staging already decoupled the device).

    Two construction modes share one read/append surface:

    - anonymous (default): a mkstemp'd scratch file unlinked on close —
      the spill tier's lifetime is the operator's;
    - named (``path=``, ``delete=False``): a durable file at a caller-
      chosen location that SURVIVES close — the exchange spool
      (exec/spool.py) builds its page logs on this, where another
      process (or a consumer that outlives the writer) reads the bytes
      back after the writing task is gone. ``flush()`` makes appended
      bytes visible to those foreign readers.
    """

    def __init__(self, directory: Optional[str] = None,
                 path: Optional[str] = None, delete: bool = True):
        self.delete = delete
        if path is not None:
            self.path = path
            self._f = open(path, "a+b")
        else:
            fd, self.path = tempfile.mkstemp(
                prefix="presto-tpu-spill-", suffix=".bin", dir=directory)
            self._f = os.fdopen(fd, "w+b")

    def append(self, data: bytes) -> Tuple[int, int]:
        off = self._f.seek(0, os.SEEK_END)
        self._f.write(data)
        return off, len(data)

    def flush(self) -> None:
        """Push appended bytes to the OS so concurrent readers (spool
        consumers in another process) observe complete frames."""
        self._f.flush()

    def read(self, off: int, length: int) -> bytes:
        self._f.seek(off)
        return self._f.read(length)

    def close(self) -> None:
        try:
            self._f.close()
        finally:
            if self.delete:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass


def _chunk_host_bytes(ch: _StagedChunk) -> int:
    return sum(a.nbytes for a in ch.datas) + sum(v.nbytes for v in ch.valids)


class HostPartitionStore:
    """Rows staged to host DRAM, hash-partitioned by key columns; beyond
    ``disk_threshold`` staged bytes, chunks flush to a SpillFile as one
    compressed page per (chunk, partition)."""

    def __init__(self, schema: Schema, n_partitions: int,
                 pool: Optional[QueryMemoryPool] = None):
        self.schema = schema
        self.n = n_partitions
        self.chunks: List[_StagedChunk] = []
        self.pool = pool
        self.host_bytes = 0
        self._file: Optional[SpillFile] = None
        # per partition: [(offset, length)] fragments in the spill file
        self._frags: List[List[Tuple[int, int]]] = [
            [] for _ in range(n_partitions)]

    def add(self, batch: Batch, key_cols: Sequence[int]) -> int:
        """Stage a device batch; returns the device bytes it occupied."""
        if self.n == 1:
            ch = _stage_chunk(batch)        # single partition: no hashing
        elif not key_cols:
            # bounds=None would alias every row into all n partitions
            raise ValueError(
                "multi-partition staging requires key columns")
        else:
            pid = hash_partition_ids(batch, list(key_cols), self.n)
            ch = _stage_chunk(batch, pid, self.n)
        if self._file is not None:
            self._flush_chunk(ch)
        else:
            self.chunks.append(ch)
            nb = _chunk_host_bytes(ch)
            self.host_bytes += nb
            _SPILL_HOST_BYTES.inc(nb)
            pool = self.pool
            if pool is not None:
                # the staging budget is QUERY-wide (reference
                # NodeSpillConfig.maxSpillPerNode): all stores share the
                # pool counter, so N concurrent buffers can't each claim
                # the full threshold
                pool.host_staged_bytes += nb
                if (pool.disk_threshold is not None
                        and pool.host_staged_bytes > pool.disk_threshold):
                    self._flush_to_disk()
        nb_dev = batch_device_bytes(batch)
        _SPILL_DEVICE_BYTES.inc(nb_dev)
        return nb_dev

    def _flush_to_disk(self) -> None:
        with TRACER.span("spill-to-disk", chunks=len(self.chunks),
                         host_bytes=self.host_bytes):
            self._file = SpillFile(
                None if self.pool is None else self.pool.spill_dir)
            for ch in self.chunks:
                self._flush_chunk(ch)
            self.chunks = []
        if self.pool is not None:
            self.pool.host_staged_bytes -= self.host_bytes
        self.host_bytes = 0

    def _flush_chunk(self, ch: _StagedChunk) -> None:
        from .pages import _encode
        for p in range(self.n):
            rows = ch.rows_of(p)
            if rows.size == 0:
                continue
            page = _encode(self.schema,
                           [d[rows] for d in ch.datas],
                           [v[rows] for v in ch.valids],
                           ch.dicts, compress=True)
            self._frags[p].append(self._file.append(page))
            _SPILL_DISK_BYTES.inc(len(page))
            if self.pool is not None:
                self.pool.stats.disk_spilled_bytes += len(page)

    def _disk_chunks(self, p: int) -> Iterator[Tuple[_StagedChunk, np.ndarray]]:
        from .pages import deserialize_arrays
        for off, length in self._frags[p]:
            _, arrays, valids, dicts, n = deserialize_arrays(
                self._file.read(off, length))
            ch = _StagedChunk(datas=arrays, valids=valids, dicts=dicts,
                              part_rows=np.arange(n), bounds=None)
            yield ch, ch.part_rows

    def _partition_arrays(self, p: int):
        selections = [(ch, ch.rows_of(p)) for ch in self.chunks]
        if self._file is not None:
            selections.extend(self._disk_chunks(p))
        return _gather_chunks(self.schema, selections)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        if self.pool is not None and self.host_bytes:
            self.pool.host_staged_bytes -= self.host_bytes
            self.host_bytes = 0

    def partition_batch(self, p: int) -> Optional[Batch]:
        """The whole partition as one device batch (build sides)."""
        got = self._partition_arrays(p)
        if got is None:
            return None
        arrays, valids, vocabs = got
        n = len(arrays[0]) if arrays else 0
        if n == 0:
            return None
        return Batch.from_arrays(self.schema, arrays, valids, vocabs,
                                 num_rows=n)

    def partition_batches(self, p: int,
                          rows_per_batch: int) -> Iterator[Batch]:
        """The partition streamed in bounded device chunks (probe sides)."""
        got = self._partition_arrays(p)
        if got is None:
            return
        arrays, valids, vocabs = got
        n = len(arrays[0]) if arrays else 0
        for lo in range(0, n, rows_per_batch):
            hi = min(lo + rows_per_batch, n)
            yield Batch.from_arrays(
                self.schema, [a[lo:hi] for a in arrays],
                [v[lo:hi] for v in valids], vocabs, num_rows=hi - lo)


class SpillableBuildBuffer:
    """Join-build-side accumulator: device-resident until the pool forces
    host staging (reference HashBuilderOperator spill states :155-180).
    finish() returns None (empty), a device Batch, or a
    HostPartitionStore for partitioned probing."""

    def __init__(self, pool: QueryMemoryPool, name: str,
                 key_cols: Sequence[int], n_partitions: int):
        self.ctx = pool.context(name, revoke_cb=self._spill_all)
        self.key_cols = list(key_cols)
        self.n_partitions = n_partitions
        self.device: List[Batch] = []
        self.store: Optional[HostPartitionStore] = None
        self.spilled = False

    def add(self, b: Batch) -> None:
        # pool lock: the pool's revoke path calls _spill_all from OTHER
        # threads (build drain on the main thread vs probe-prefetch); an
        # unsynchronized revoke both stages and leaves batches visible to
        # a concurrent consumer — duplicated rows
        with self.ctx.pool.lock:
            if self.spilled:
                self._stage(b)
                return
            nb = batch_device_bytes(b)
            if self.ctx.pool.try_reserve(nb, self.ctx):
                self.device.append(b)
            else:
                self.ctx.revoke()  # spills everything accumulated so far
                self._stage(b)

    def _stage(self, b: Batch) -> int:
        if self.store is None:
            self.store = HostPartitionStore(b.schema, self.n_partitions,
                                            pool=self.ctx.pool)
        n = self.store.add(b, self.key_cols)
        self.ctx.pool.stats.spilled_bytes += n
        return n

    def _spill_all(self) -> int:
        _SPILL_REVOCATIONS.inc()
        with TRACER.span("spill-revoke", buffer="join-build",
                         batches=len(self.device)):
            freed = 0
            for b in self.device:
                freed += self._stage(b)
            self.device = []
            self.spilled = True
            return freed

    def finish(self):
        # once the build is handed to the prober, revoking can no longer
        # free its device memory — keep the reservation, end revocability
        with self.ctx.pool.lock:
            self.ctx.pin()
            if self.spilled:
                return self.store
            if not self.device:
                return None
            return (self.device[0] if len(self.device) == 1
                    else concat_batches(self.device))

    def close(self) -> None:
        self.ctx.close()
        if self.store is not None:
            self.store.close()


class AggSpillBuffer:
    """Grouped-aggregation state accumulator: merges partial-state batches
    on device; under memory pressure stages states to host partitioned by
    group-key hash, finalizing partition-serially (reference
    SpillableHashAggregationBuilder.java + MergingHashAggregationBuilder).
    Group keys are disjoint across hash partitions, so per-partition FINAL
    results concatenate to the global answer."""

    def __init__(self, pool: QueryMemoryPool, name: str,
                 key_idx: Sequence[int], aggs: Sequence[AggSpec],
                 n_partitions: int, merge_every: int = 16,
                 key_bounds=None, allow_dense: bool = True,
                 error_sink=None):
        self.ctx = pool.context(name, revoke_cb=self._spill_all)
        self.key_idx = list(key_idx)
        self.aggs = list(aggs)
        # stats-derived static key bounds (AggregationNode.key_bounds):
        # merges and finals over state rows keep the dense scatter path;
        # allow_dense=False (session dense_grouping=false) pins the sort
        # path end to end
        self.key_bounds = tuple(key_bounds) if key_bounds else None
        self.allow_dense = allow_dense
        # receives device error scalars (executor error_flags.append):
        # a merge/final whose LARGER concatenated capacity flips the
        # dense gate on must still flag out-of-bounds keys, even when
        # the per-batch partials sorted (and so appended no flag)
        self.error_sink = error_sink
        self.n_partitions = n_partitions
        self.merge_every = merge_every
        self.device: List[Batch] = []
        self.store: Optional[HostPartitionStore] = None
        self.spilled = False

    def add_partial(self, partial: Batch) -> None:
        # pool lock: revoke callbacks (_spill_all) arrive from other
        # threads mid-merge; see SpillableBuildBuffer.add
        with self.ctx.pool.lock:
            if self.spilled:
                self._stage(partial)
                return
            nb = batch_device_bytes(partial)
            if self.ctx.pool.try_reserve(nb, self.ctx):
                self.device.append(partial)
                if len(self.device) < self.merge_every:
                    return
                # snapshot-and-clear under the lock; the merge itself
                # (which host-syncs for the compaction size) runs
                # outside so other operators' reserves aren't blocked
                # behind device compute. A revoke landing mid-merge
                # sees an empty device list and just flips `spilled`.
                snapshot = self.device
                self.device = []
            else:
                self.ctx.revoke()
                self._stage(partial)
                return
        states = concat_batches(snapshot)
        self._flag_bounds(states)
        merged = grouped_aggregate(states,
                                   self.key_idx, self.aggs, mode="merge",
                                   key_bounds=self.key_bounds,
                                   allow_dense=self.allow_dense)
        state = merged.compact(
            bucket_capacity(max(merged.host_count(), 1)))
        with self.ctx.pool.lock:
            self.ctx.release_all()
            if not self.spilled and self.ctx.pool.try_reserve(
                    batch_device_bytes(state), self.ctx):
                self.device.append(state)
            else:
                self._stage(state)
                self.spilled = True

    def _flag_bounds(self, states: Batch) -> None:
        """Mirror of this merge/final call's kernel dispatch: when the
        dense (clamping) path engages for THIS batch, emit the
        bounds-violation scalar — state batches keep raw key values, so
        out-of-bounds keys from a sort-path partial are still visible
        here (exec/local.py owns the per-partial-batch flags)."""
        if self.key_bounds is None or not self.allow_dense \
                or self.error_sink is None:
            return
        from ..ops.aggregation import dense_path_selected
        from ..ops.jitcache import key_bounds_violation_jit
        if dense_path_selected(states, self.key_idx, self.aggs,
                               key_bounds=self.key_bounds):
            self.error_sink(key_bounds_violation_jit(
                states, self.key_idx, self.key_bounds))

    def _stage(self, b: Batch) -> int:
        if self.store is None:
            self.store = HostPartitionStore(b.schema, self.n_partitions,
                                            pool=self.ctx.pool)
        n = self.store.add(b, self.key_idx)
        self.ctx.pool.stats.spilled_bytes += n
        return n

    def _spill_all(self) -> int:
        _SPILL_REVOCATIONS.inc()
        with TRACER.span("spill-revoke", buffer="hash-agg",
                         batches=len(self.device)):
            freed = 0
            for b in self.device:
                freed += self._stage(b)
            self.device = []
            self.spilled = True
            return freed

    def results(self, final: bool = True) -> Iterator[Batch]:
        """Final rows (default) or merged partial states (``final=False``,
        the PARTIAL-step output shipped to a downstream exchange)."""
        mode = "final" if final else "merge"
        with self.ctx.pool.lock:
            # consumers hold the yielded state from here on; snapshot the
            # device list under the lock so a late revoke can't re-stage
            # what we are about to yield
            self.ctx.pin()
            spilled, device = self.spilled, list(self.device)
        if not spilled:
            if not device:
                return
            states = (device[0] if len(device) == 1
                      else concat_batches(device))
            self._flag_bounds(states)
            yield grouped_aggregate(states, self.key_idx, self.aggs,
                                    mode=mode, key_bounds=self.key_bounds,
                                    allow_dense=self.allow_dense)
            return
        for p in range(self.n_partitions):
            part = None if self.store is None else \
                self.store.partition_batch(p)
            if part is None:
                continue
            self._flag_bounds(part)
            yield grouped_aggregate(part, self.key_idx, self.aggs,
                                    mode=mode, key_bounds=self.key_bounds,
                                    allow_dense=self.allow_dense)

    def close(self) -> None:
        self.ctx.close()
        if self.store is not None:
            self.store.close()


class SortSpillBuffer:
    """ORDER BY accumulator: device sort when everything fits; otherwise
    raw chunks stage to host and the final ordering is one np.lexsort over
    sortable operands replicating ops.sort._sortable's transforms
    (reference OrderByOperator spill; the host takes the role of
    FileSingleStreamSpiller's disk)."""

    def __init__(self, pool: QueryMemoryPool, name: str,
                 keys: Sequence[SortKey]):
        self.ctx = pool.context(name, revoke_cb=self._spill_all)
        self.keys = list(keys)
        self.device: List[Batch] = []
        self.store: Optional[HostPartitionStore] = None
        self.schema: Optional[Schema] = None
        self.spilled = False

    def add(self, b: Batch) -> None:
        # pool lock: cross-thread revoke callbacks; see
        # SpillableBuildBuffer.add
        with self.ctx.pool.lock:
            self.schema = b.schema
            if self.spilled:
                self._stage(b)
                return
            nb = batch_device_bytes(b)
            if self.ctx.pool.try_reserve(nb, self.ctx):
                self.device.append(b)
            else:
                self.ctx.revoke()
                self._stage(b)

    def _stage(self, b: Batch) -> int:
        if self.store is None:
            # one partition: sort wants everything back in one readback,
            # but still rides the two-tier (DRAM -> disk) staging
            self.store = HostPartitionStore(b.schema, 1,
                                            pool=self.ctx.pool)
        n = self.store.add(b, [])
        self.ctx.pool.stats.spilled_bytes += n
        return n

    def _spill_all(self) -> int:
        _SPILL_REVOCATIONS.inc()
        with TRACER.span("spill-revoke", buffer="order-by",
                         batches=len(self.device)):
            freed = 0
            for b in self.device:
                freed += self._stage(b)
            self.device = []
            self.spilled = True
            return freed

    def results(self, rows_per_batch: int) -> Iterator[Batch]:
        with self.ctx.pool.lock:
            self.ctx.pin()
            spilled, device = self.spilled, list(self.device)
        if not spilled:
            if not device:
                return
            merged = (device[0] if len(device) == 1
                      else concat_batches(device))
            yield sort_batch(merged, self.keys)
            return
        yield from self._host_sorted(rows_per_batch)

    def _host_sorted(self, rows_per_batch: int) -> Iterator[Batch]:
        schema = self.schema
        got = None if self.store is None \
            else self.store._partition_arrays(0)
        if got is None:
            return
        arrays, valid_arr, vocabs = got
        operands: List[np.ndarray] = []
        for k in self.keys:
            operands.extend(_np_sortable(
                arrays[k.column], valid_arr[k.column], vocabs[k.column],
                schema.types[k.column], k))
        # lexsort: last key is primary -> reverse; stable like lax.sort
        perm = np.lexsort(tuple(reversed(operands)))
        n = len(perm)
        for lo in range(0, n, rows_per_batch):
            idx = perm[lo:min(lo + rows_per_batch, n)]
            yield Batch.from_arrays(
                schema, [a[idx] for a in arrays],
                [v[idx] for v in valid_arr], vocabs, num_rows=len(idx))

    def close(self) -> None:
        self.ctx.close()
        if self.store is not None:
            self.store.close()


def _np_sortable(data: np.ndarray, valid: np.ndarray,
                 vocab: Optional[Tuple[str, ...]], typ,
                 key: SortKey) -> List[np.ndarray]:
    """Host replica of ops.sort._sortable: [null_rank, data'] ascending."""
    if typ.is_string:
        v = np.asarray(vocab or ("",), dtype=object)
        rank = np.argsort(np.argsort(v))
        data = rank[np.where(data >= 0, data, 0)]
    if data.dtype == np.bool_:
        data = data.astype(np.int32)
    if not key.ascending:
        data = -data if np.issubdtype(data.dtype, np.floating) else ~data
    nulls_first = key.effective_nulls_first()
    null_rank = (np.where(valid, 1, 0) if nulls_first
                 else np.where(valid, 0, 1)).astype(np.int32)
    return [null_rank, data]

"""Retry backoff helpers shared by coordinator and worker planes.

Lives in ``exec/`` so the coordinator's retry path (exec/cluster.py)
does not have to import the worker HTTP module for a six-line helper.
"""
from __future__ import annotations

import random


def jittered(seconds: float) -> float:
    """Retry backoff with +/-50% uniform jitter: deterministic
    exponential backoff synchronizes N consumers' retries into bursts
    that hammer a recovering worker; jitter spreads them (reference
    airlift Backoff adds the same randomization)."""
    return seconds * random.uniform(0.5, 1.5)

"""Deterministic fault-injection harness (named failpoints).

The role of the reference's ``@Failpoint``-style fault hooks and of
kernel failpoint frameworks: production code calls
``FAILPOINTS.hit("site.name", key=...)`` at interesting seams — worker
task run, exchange pull, heartbeat ping, scan decode — and the call is
a dictionary miss (near-zero cost) unless a test, the
``PRESTO_TPU_FAILPOINTS`` environment variable, or a
``failpoints=`` line in ``etc/config.properties`` armed that site.

Armed sites trigger deterministically:

- ``times``/``skip`` — trigger on hits ``skip+1 .. skip+times``
  (``times=None`` = unlimited), so "fail the first task, then recover"
  is one line of config;
- ``probability`` + ``seed`` — a per-rule ``random.Random(seed)``
  makes probabilistic chaos runs replayable bit-for-bit given the same
  hit sequence;
- ``match`` — a regex applied to the hit's ``key`` (task id, url,
  split) so a rule can target one partition (``\\.0\\.0$``) or one
  node (``@worker-2$``).

Actions: ``error`` (raise :class:`FailpointError`), ``sleep`` (inject
latency — the straggler story), and ``callback`` (test API only — run
arbitrary harness code, e.g. kill a worker's HTTP server mid-query).
Multiple rules may be armed on one site; every matching rule fires in
configuration order.

Spec grammar (env var / config value), ``;``-separated entries::

    site.name=action[:arg][,times:N][,skip:N][,prob:P][,seed:S][,match:RE]

    PRESTO_TPU_FAILPOINTS='worker.task_run=error:boom,times:1;\
exchange.pull=sleep:0.5,prob:0.1,seed:7'

Every recovery path in exec/cluster.py is CI-testable against this
harness without a real multi-host TPU cluster (tools/chaos_smoke.py).
"""
from __future__ import annotations

import os
import random
import re
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["FailpointError", "FailpointRegistry", "FAILPOINTS", "SITES"]

#: declared failpoint sites: every production ``FAILPOINTS.hit(...)``
#: call names one of these, and arming a site outside this table via
#: the spec grammar raises at parse time instead of silently never
#: firing (a typo'd chaos config that injects nothing "passes" every
#: recovery test it was meant to exercise). The static registry lint
#: (tools/analyze/registries.py) cross-checks this table against the
#: hit() call sites and the docs/robustness.md catalog two-way.
SITES: Dict[str, str] = {
    "worker.task_run": "worker begins executing a task attempt "
                       "(server/worker.py)",
    "exchange.pull": "exchange client pulls a page from an upstream "
                     "task (server/worker.py)",
    "heartbeat.ping": "coordinator failure-detector pings a worker "
                      "/v1/info (exec/cluster.py)",
    "scan.decode": "scan pipeline decodes one split batch, before "
                   "staging (exec/scancache.py)",
    "spool.write": "exchange spool appends one output-buffer page "
                   "(exec/spool.py); error fails the producing task",
    "spool.read": "exchange spool reads one page back "
                  "(exec/spool.py); error loses the spool copy",
    "spool.corrupt": "error action flips one byte of the page being "
                     "spooled while keeping the original checksum — "
                     "plants an on-disk corruption for the read path "
                     "to detect (exec/spool.py)",
    "spool.object_put": "object-store spool uploads one blob/manifest "
                        "(exec/spool.py ObjectSpoolStore); error fails "
                        "the writing task before the object lands",
    "spool.object_get": "object-store spool downloads one page blob "
                        "(exec/spool.py ObjectSpoolStore); error loses "
                        "the object copy",
    "exchange.spec_live": "speculative exchange read: the live-pull "
                          "arm is about to issue one HTTP pull "
                          "(server/worker.py); an error rule forces "
                          "the spool-replay arm to win the race",
    "exchange.spec_replay": "speculative exchange read: the "
                            "spool-replay arm is about to start "
                            "(server/worker.py); a sleep/error rule "
                            "forces the live arm to win the race",
    "mesh.repartition": "mesh executor ships one hash-exchange batch "
                        "over ICI (exec/distributed.py); error fails "
                        "the query before the collective dispatches",
    "protocol.serve": "statement producer granted its resource-group "
                      "slot, about to execute (server/protocol.py); "
                      "key = group path — a sleep rule injects "
                      "user-visible serving latency, error injects "
                      "availability failures (SLO chaos drills)",
    "plancache.plan": "plan/template cache captured its write epoch "
                      "and is about to plan+optimize (serving/"
                      "plancache.py, serving/template.py) — the PR 8 "
                      "TOCTOU window; the interleaving explorer "
                      "deschedules here to land a write mid-plan",
    "resultcache.stamp": "result cache captured its write epoch and "
                         "is about to stamp plan deps (serving/"
                         "resultcache.py begin()) — the PR 12 "
                         "round-2 epoch-before-deps window",
    "resultcache.partial": "result cache resolved a partial hit and "
                           "is about to recompute the delta "
                           "(serving/resultcache.py serve()) — the "
                           "PR 12 double-apply window",
    "fleet.broadcast": "fleet member about to POST one write bump to "
                       "one peer (serving/fleet.py); key = "
                       "connector/table@peer — an error rule DROPS the "
                       "broadcast, leaving that peer to the hit-time "
                       "data_version revalidation backstop (coherence "
                       "chaos drills)",
}


class FailpointError(RuntimeError):
    """An injected failure (never raised by real engine conditions)."""


class _Rule:
    __slots__ = ("site", "action", "message", "sleep_s", "times", "skip",
                 "probability", "pattern", "rng", "callback", "hits",
                 "triggers")

    def __init__(self, site: str, action: str, message: Optional[str],
                 sleep_s: float, times: Optional[int], skip: int,
                 probability: Optional[float], match: Optional[str],
                 seed: int, callback: Optional[Callable]):
        if action not in ("error", "sleep", "callback"):
            raise ValueError(f"unknown failpoint action {action!r}")
        if action == "callback" and callback is None:
            raise ValueError("callback action requires callback=")
        self.site = site
        self.action = action
        self.message = message or f"injected failure at {site}"
        self.sleep_s = float(sleep_s)
        self.times = times            # None = unlimited triggers
        self.skip = int(skip)
        self.probability = probability
        self.pattern = re.compile(match) if match else None
        # seeded per-rule RNG: probabilistic runs replay exactly given
        # the same hit sequence (the determinism contract of the harness)
        self.rng = random.Random(seed)
        self.callback = callback
        self.hits = 0                 # matching hits seen
        self.triggers = 0             # times the action actually fired

    def _should_trigger(self, key: str) -> bool:
        if self.pattern is not None and not self.pattern.search(key):
            return False
        self.hits += 1
        if self.hits <= self.skip:
            return False
        if self.times is not None and self.triggers >= self.times:
            return False
        if self.probability is not None \
                and self.rng.random() >= self.probability:
            return False
        self.triggers += 1
        return True


class FailpointRegistry:
    """Process-wide named-failpoint table. ``hit`` is the production
    call site; everything else is the test/config API."""

    def __init__(self, sites: Optional[Dict[str, str]] = None):
        self._lock = threading.Lock()
        self._rules: Dict[str, List[_Rule]] = {}
        #: when set, configure() rejects sites outside this table (the
        #: process-wide registry passes SITES; unit-test registries that
        #: exercise the rule machinery on synthetic names pass None)
        self._sites = sites

    # -- configuration (test API) --------------------------------------------
    def configure(self, site: str, action: str = "error",
                  message: Optional[str] = None, sleep_s: float = 0.0,
                  times: Optional[int] = 1, skip: int = 0,
                  probability: Optional[float] = None,
                  match: Optional[str] = None, seed: int = 0,
                  callback: Optional[Callable] = None) -> None:
        """Arm one rule on ``site`` (appends — multiple rules per site
        evaluate in configuration order)."""
        if self._sites is not None and site not in self._sites:
            raise ValueError(
                f"unknown failpoint site {site!r} — it would never "
                f"fire (registered: {sorted(self._sites)})")
        rule = _Rule(site, action, message, sleep_s, times, skip,
                     probability, match, seed, callback)
        with self._lock:
            self._rules.setdefault(site, []).append(rule)

    def configure_from_spec(self, spec: str) -> None:
        """Parse the ``;``-separated spec grammar (env var / config)."""
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if "=" not in entry:
                raise ValueError(f"malformed failpoint entry {entry!r}")
            site, value = entry.split("=", 1)
            parts = value.split(",")
            action, _, arg = parts[0].partition(":")
            kw: Dict = {}
            if action == "sleep":
                kw["sleep_s"] = float(arg or "0")
            elif action == "error":
                if arg:
                    kw["message"] = arg
            else:
                raise ValueError(
                    f"failpoint spec only supports error/sleep "
                    f"actions, got {action!r} (callback is test-only)")
            for opt in parts[1:]:
                k, _, v = opt.partition(":")
                k = k.strip()
                if k == "times":
                    kw["times"] = None if v == "inf" else int(v)
                elif k == "skip":
                    kw["skip"] = int(v)
                elif k == "prob":
                    kw["probability"] = float(v)
                elif k == "seed":
                    kw["seed"] = int(v)
                elif k == "match":
                    kw["match"] = v
                else:
                    raise ValueError(f"unknown failpoint option {k!r}")
            self.configure(site.strip(), action=action, **kw)

    def clear(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._rules.clear()
            else:
                self._rules.pop(site, None)

    # -- introspection -------------------------------------------------------
    def hits(self, site: str) -> int:
        with self._lock:
            return sum(r.hits for r in self._rules.get(site, ()))

    def triggers(self, site: str) -> int:
        with self._lock:
            return sum(r.triggers for r in self._rules.get(site, ()))

    def active(self) -> bool:
        return bool(self._rules)

    # -- the production call site --------------------------------------------
    def hit(self, site: str, key: str = "", **ctx) -> None:
        """Evaluate ``site``'s rules against ``key``. No rules armed
        anywhere = one falsy check; no rules on this site = one dict
        miss. May raise :class:`FailpointError`, sleep, or run a test
        callback (callbacks run outside the lock and receive
        ``key=...`` plus the caller's context kwargs)."""
        if not self._rules:
            return
        fired: List[_Rule] = []
        with self._lock:
            rules = self._rules.get(site)
            if not rules:
                return
            for r in rules:
                if r._should_trigger(key):
                    fired.append(r)
        for r in fired:
            if r.action == "sleep":
                time.sleep(r.sleep_s)
            elif r.action == "callback":
                r.callback(key=key, **ctx)
            else:
                raise FailpointError(f"failpoint {site}: {r.message}")


#: the process-wide registry (site names validated against SITES)
FAILPOINTS = FailpointRegistry(sites=SITES)

_env_spec = os.environ.get("PRESTO_TPU_FAILPOINTS")
if _env_spec:
    FAILPOINTS.configure_from_spec(_env_spec)

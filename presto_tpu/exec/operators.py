"""Pull-based batch operators.

Conceptual parity with Presto's operator framework (reference
presto-main/.../operator/Operator.java:20-92: needsInput/addInput/getOutput/
finish/isFinished), with device batches instead of Pages. Each operator owns
its jitted kernels; the Driver moves batches between adjacent operators
(reference operator/Driver.java:367-400).

Blocking is synchronous in v1 (single-host pipelines); the exchange layer
introduces real async sources.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp

from ..batch import Batch, Column, Schema, bucket_capacity, concat_batches
from ..connectors.spi import Connector, PageSource, Split
from ..expr import compile_filter, compile_projection
from ..expr.ir import Expr
from ..ops.aggregation import AggSpec, global_aggregate, grouped_aggregate
from ..ops.join import lookup_join
from ..ops.sort import SortKey, limit as limit_kernel, sort_batch, top_n


class Operator:
    """Base operator (reference operator/Operator.java:20)."""

    def needs_input(self) -> bool:
        return not self._finishing

    def add_input(self, batch: Batch) -> None:
        raise NotImplementedError

    def get_output(self) -> Optional[Batch]:
        return None

    def finish(self) -> None:
        self._finishing = True

    def is_finished(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        """Release held resources; called by the Driver when the pipeline
        ends, including early termination (reference Operator.close())."""

    def __init__(self):
        self._finishing = False


class TableScanOperator(Operator):
    """Source operator over a connector PageSource (reference
    operator/TableScanOperator.java)."""

    def __init__(self, connector: Connector, split: Split,
                 columns: Sequence[str], rows_per_batch: int = 1 << 17):
        super().__init__()
        self._source = connector.page_source(
            split, columns, rows_per_batch=rows_per_batch)
        self._iter = self._source.batches()
        self._done = False

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[Batch]:
        if self._done:
            return None
        try:
            return next(self._iter)
        except StopIteration:
            self._done = True
            self._source.close()
            return None

    def is_finished(self) -> bool:
        return self._done

    def close(self) -> None:
        self._source.close()


class ValuesOperator(Operator):
    """Emits pre-built batches (reference operator/ValuesOperator.java)."""

    def __init__(self, batches: Sequence[Batch]):
        super().__init__()
        self._batches = list(batches)
        self._pos = 0

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[Batch]:
        if self._pos < len(self._batches):
            b = self._batches[self._pos]
            self._pos += 1
            return b
        return None

    def is_finished(self) -> bool:
        return self._pos >= len(self._batches)


class FilterProjectOperator(Operator):
    """Fused filter + projection via compiled expressions (reference
    operator/FilterAndProjectOperator.java + project/PageProcessor.java)."""

    def __init__(self, input_schema: Schema,
                 predicate: Optional[Expr],
                 projections: Optional[Sequence[Expr]] = None,
                 output_names: Optional[Sequence[str]] = None):
        super().__init__()
        self._filter = compile_filter(predicate, input_schema) if predicate is not None else None
        self._project = (
            compile_projection(list(projections), list(output_names), input_schema)
            if projections is not None else None
        )
        self._pending: Optional[Batch] = None

    def needs_input(self) -> bool:
        return self._pending is None and not self._finishing

    def add_input(self, batch: Batch) -> None:
        if self._filter is not None:
            batch = self._filter(batch)
        if self._project is not None:
            batch = self._project(batch)
        self._pending = batch

    def get_output(self) -> Optional[Batch]:
        out, self._pending = self._pending, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._pending is None


class AggregationOperator(Operator):
    """Grouped / global aggregation with incremental partial merging
    (reference operator/HashAggregationOperator.java:48 and
    AggregationOperator.java). step: 'single' | 'partial' | 'final'.

    Strategy: aggregate each input batch to partial states; eagerly merge
    into the running state while it stays small (Q1-style low cardinality),
    otherwise buffer partials and do a hierarchical merge at finish
    (Q3-style high cardinality) — the duality Presto gets from
    InMemoryHashAggregationBuilder vs MergingHashAggregationBuilder.
    """

    def __init__(self, input_schema: Schema, group_indices: Sequence[int],
                 aggs: Sequence[AggSpec], step: str = "single"):
        super().__init__()
        self._input_schema = input_schema
        self._group = list(group_indices)
        self._aggs = list(aggs)
        self._step = step
        self._state: Optional[Batch] = None
        self._buffered: List[Batch] = []
        self._emitted = False

    def add_input(self, batch: Batch) -> None:
        if not self._group:
            mode = "merge" if self._step == "final" else "partial"
            partial = global_aggregate(batch, self._aggs, mode=mode)
            self._buffered.append(partial)
            if len(self._buffered) >= 64:
                merged = concat_batches(self._buffered)
                self._buffered = [
                    global_aggregate(merged, self._aggs, mode="merge")]
            return
        if self._step == "final":
            partial = batch  # inputs are states already
        else:
            partial = grouped_aggregate(batch, self._group, self._aggs,
                                        mode="partial")
        if self._state is None:
            self._state = partial
        elif self._state.capacity <= 4 * partial.capacity:
            # low-cardinality fast path: fold into the running state
            merged = concat_batches([self._state, partial])
            state = grouped_aggregate(
                merged, list(range(len(self._group))), self._aggs, mode="merge")
            if state.capacity > 4 * partial.capacity:
                # merge output keeps its input's (concatenated) capacity, so
                # the state grows each fold; periodically compact back down
                # to the live group count (one host sync), and if it really
                # is high-cardinality, stop eager merging for good.
                state = state.compact(bucket_capacity(state.host_count()))
            self._state = state
        else:
            self._buffered.append(partial)

    def get_output(self) -> Optional[Batch]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        if not self._group:
            if self._buffered:
                states = (concat_batches(self._buffered)
                          if len(self._buffered) > 1 else self._buffered[0])
            else:
                # SQL: global aggregate over empty input still emits one row
                empty = Batch.from_arrays(self._input_schema,
                                          [[] for _ in self._input_schema.fields])
                if self._step == "final":
                    return None
                states = global_aggregate(empty, self._aggs, mode="partial")
            if self._step == "partial":
                return global_aggregate(states, self._aggs, mode="merge")
            return global_aggregate(states, self._aggs, mode="final")
        parts = ([self._state] if self._state is not None else []) + self._buffered
        if not parts:
            return None
        states = concat_batches(parts) if len(parts) > 1 else parts[0]
        key_idx = list(range(len(self._group)))
        if self._step == "partial":
            return (grouped_aggregate(states, key_idx, self._aggs, mode="merge")
                    if len(parts) > 1 else states)
        return grouped_aggregate(states, key_idx, self._aggs, mode="final")

    def is_finished(self) -> bool:
        return self._finishing and self._emitted


class OrderByOperator(Operator):
    """Full sort: buffer all input, sort at finish (reference
    operator/OrderByOperator.java)."""

    def __init__(self, keys: Sequence[SortKey]):
        super().__init__()
        self._keys = list(keys)
        self._buffered: List[Batch] = []
        self._emitted = False

    def add_input(self, batch: Batch) -> None:
        self._buffered.append(batch)

    def get_output(self) -> Optional[Batch]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        if not self._buffered:
            return None
        merged = concat_batches(self._buffered) if len(self._buffered) > 1 else self._buffered[0]
        return sort_batch(merged, self._keys)

    def is_finished(self) -> bool:
        return self._finishing and self._emitted


class TopNOperator(Operator):
    """Memory-bounded top-N: fold each batch into the running top-N
    (reference operator/TopNOperator.java)."""

    def __init__(self, keys: Sequence[SortKey], n: int):
        super().__init__()
        self._keys = list(keys)
        self._n = n
        self._state: Optional[Batch] = None
        self._emitted = False

    def add_input(self, batch: Batch) -> None:
        candidate = top_n(batch, self._keys, self._n).compact(
            bucket_capacity(self._n))
        if self._state is None:
            self._state = candidate
        else:
            merged = concat_batches([self._state, candidate])
            self._state = top_n(merged, self._keys, self._n).compact(
                bucket_capacity(self._n))

    def get_output(self) -> Optional[Batch]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        return self._state

    def is_finished(self) -> bool:
        return self._finishing and self._emitted


class LimitOperator(Operator):
    """Streaming LIMIT (reference operator/LimitOperator.java)."""

    def __init__(self, n: int):
        super().__init__()
        self._remaining = n
        self._pending: Optional[Batch] = None

    def needs_input(self) -> bool:
        return self._pending is None and self._remaining > 0 and not self._finishing

    def add_input(self, batch: Batch) -> None:
        out = limit_kernel(batch, self._remaining)
        self._remaining -= out.host_count()
        self._pending = out

    def get_output(self) -> Optional[Batch]:
        out, self._pending = self._pending, None
        return out

    def is_finished(self) -> bool:
        return (self._finishing or self._remaining <= 0) and self._pending is None


class HashBuildOperator(Operator):
    """Join build side: buffers and prepares the lookup structure (reference
    operator/HashBuilderOperator.java:51). The 'hash table' is a sorted key
    array probed by binary search."""

    def __init__(self):
        super().__init__()
        self._buffered: List[Batch] = []
        self.build_batch: Optional[Batch] = None

    def add_input(self, batch: Batch) -> None:
        self._buffered.append(batch)

    def finish(self) -> None:
        super().finish()
        if self.build_batch is None and self._buffered:
            self.build_batch = (
                concat_batches(self._buffered)
                if len(self._buffered) > 1 else self._buffered[0]
            )

    def is_finished(self) -> bool:
        return self._finishing


class LookupJoinOperator(Operator):
    """Probe side of the join (reference operator/LookupJoinOperator.java).
    Streams probe batches against the finished build side."""

    def __init__(self, build: HashBuildOperator,
                 probe_keys: Sequence[int], build_keys: Sequence[int],
                 payload: Sequence[int], payload_names: Sequence[str],
                 join_type: str = "inner",
                 build_schema: Optional[Schema] = None):
        super().__init__()
        self._build_op = build
        self._probe_keys = list(probe_keys)
        self._build_keys = list(build_keys)
        self._payload = list(payload)
        self._payload_names = list(payload_names)
        self._join_type = join_type
        self._build_schema = build_schema
        self._pending: Optional[Batch] = None

    def needs_input(self) -> bool:
        return self._pending is None and not self._finishing

    def _empty_build_output(self, batch: Batch) -> Batch:
        """Empty build side: keep the joined schema contract — probe columns
        plus (all-null) payload columns; inner join masks every row out."""
        if self._build_schema is None and self._payload:
            raise ValueError(
                "join build side produced no rows and no build_schema was "
                "given to emit the joined schema")
        fields = list(zip(batch.schema.names, batch.schema.types))
        cols = list(batch.columns)
        no_valid = jnp.zeros_like(batch.row_mask)
        for ci, name in zip(self._payload, self._payload_names):
            typ = self._build_schema.types[ci]
            fields.append((name, typ))
            cols.append(Column(
                typ, jnp.zeros(batch.capacity, dtype=typ.storage_dtype),
                no_valid, () if typ.is_string else None))
        mask = (jnp.zeros_like(batch.row_mask) if self._join_type == "inner"
                else batch.row_mask)
        return Batch(Schema(fields), cols, mask)

    def add_input(self, batch: Batch) -> None:
        build = self._build_op.build_batch
        if build is None:
            self._pending = self._empty_build_output(batch)
            return
        self._pending = lookup_join(
            batch, build, self._probe_keys, self._build_keys,
            self._payload, self._payload_names, self._join_type)

    def get_output(self) -> Optional[Batch]:
        out, self._pending = self._pending, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._pending is None

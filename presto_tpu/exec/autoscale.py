"""The elasticity control loop: ClusterSignals in, scale actions out.

PR 9 built the mechanisms (join-mid-query, drain-and-exit, spool
replay) and PR 16 built the sensor (`obs/signals.py` ClusterSignals);
this module is the actuator that closes the loop. Three layers:

- **Rules** (:data:`RULES` / :func:`decide`): the ONE rule registry —
  a pure function from a frozen snapshot to recommendations
  (``scale_up`` / ``scale_down`` / ``replace_node`` / ``grow_cache``
  / ``scale_coordinator``). ``tools/autoscale_watch.py`` is a thin
  shim over exactly this registry, so the reference watcher and the
  controller cannot drift (tests/test_autoscale.py pins the parity).

- **Providers** (:class:`NodeProvider`): the pluggable boundary to
  whatever actually owns worker capacity. Shipped:
  :class:`LocalProcessProvider` (spawns real
  ``python -m presto_tpu.server.worker`` subprocesses — the interface
  is the point; a cloud provider slots in behind the same four
  methods) and :class:`InProcessProvider` (WorkerServer objects in
  this process, the chaos/test substrate).

- **Controller** (:class:`AutoscaleController`): the coordinator-side
  loop. Consumes the signals feed on a cadence and applies confirmed
  decisions with *hysteresis* (a decision must repeat for
  ``confirm_evals`` consecutive evaluations before it acts — one noisy
  snapshot moves nothing), *cooldowns* (``cooldown_s`` between applied
  scale actions), *bounded steps* (``scale_step`` workers per action,
  clamped to ``[min_workers, max_workers]``), and the PR 16 invariant
  re-checked at apply time: while ANY group's SLO alert is PAGE, the
  cluster never scales down. Scale-down always takes the drain path —
  ``PUT /v1/info/state SHUTTING_DOWN`` → active tasks finish and
  commit their spool → the worker's final GONE announcement
  deregisters it explicitly — never a kill. When a group is
  admission-bound (queue deep while every device sits idle — more
  workers cannot help), the controller scales the *coordinator* tier
  instead through an injected scaler (``tools/fleet.py``'s
  FleetHandle adapts onto it).

Everything is observable: ``autoscale_evaluations_total``,
``autoscale_decision_total.<action>``, ``autoscale_actions_total.
<action>``, ``autoscale_blocked_total.<reason>`` (hysteresis /
cooldown / page-held / bounds / no-scaler / drain-failed).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs.metrics import REGISTRY
from ..obs.signals import (CacheSignals, ClusterSignals, GroupSignals,
                           NodeSignals, cluster_signals)

_EVALS = REGISTRY.counter("autoscale_evaluations_total")

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# -- the rule registry --------------------------------------------------------
# One registry for the reference watcher AND the controller. Every
# rule is a pure function (signals, cfg) -> [decision], each decision
# ``{"action", "target", "reason", "signals": {...}}`` carrying the
# raw values it fired on, so an operator (or a test) can audit the
# decision against the feed.

DEFAULT_RULE_CONFIG: Dict[str, float] = {
    "queue_ratio": 2.0,
    "idle_ratio": 0.25,
    "stale_heartbeat_s": 30.0,
    "cache_pressure": 0.9,
    "min_budget": 0.5,
    "coordinator_queue_ratio": 4.0,
}


def _wants_scale_up(g: GroupSignals, cfg: Dict[str, float]) -> bool:
    limit = max(1, g.hard_concurrency_limit)
    return g.queued >= cfg["queue_ratio"] * limit \
        or g.alert_state == "PAGE"


def _rule_scale_up(signals: ClusterSignals,
                   cfg: Dict[str, float]) -> List[Dict]:
    out: List[Dict] = []
    for g in signals.groups:
        limit = max(1, g.hard_concurrency_limit)
        if _wants_scale_up(g, cfg):
            why = (f"alert {g.alert_state}" if g.alert_state == "PAGE"
                   else f"queue {g.queued} >= {cfg['queue_ratio']:g}x "
                        f"limit {limit}")
            out.append({"action": "scale_up", "target": g.group,
                        "reason": why,
                        "signals": {"queued": g.queued,
                                    "running": g.running,
                                    "limit": limit,
                                    "alert_state": g.alert_state,
                                    "burn_short": g.burn_short,
                                    "p95_s": g.p95_s}})
    return out


def _rule_scale_down(signals: ClusterSignals,
                     cfg: Dict[str, float]) -> List[Dict]:
    out: List[Dict] = []
    for g in signals.groups:
        limit = max(1, g.hard_concurrency_limit)
        if (not _wants_scale_up(g, cfg)
                and g.queued == 0
                and g.running < cfg["idle_ratio"] * limit
                and g.alert_state == "OK"
                and (g.error_budget_remaining is None
                     or g.error_budget_remaining >= cfg["min_budget"])):
            out.append({"action": "scale_down", "target": g.group,
                        "reason": f"idle: running {g.running} < "
                                  f"{cfg['idle_ratio']:g}x limit "
                                  f"{limit}, no queue, alert OK",
                        "signals": {"running": g.running,
                                    "limit": limit,
                                    "budget":
                                        g.error_budget_remaining}})
    return out


def _rule_replace_node(signals: ClusterSignals,
                       cfg: Dict[str, float]) -> List[Dict]:
    out: List[Dict] = []
    for n in signals.nodes:
        if n.heartbeat_age_s > cfg["stale_heartbeat_s"]:
            out.append({"action": "replace_node", "target": n.node_id,
                        "reason": f"heartbeat {n.heartbeat_age_s:.1f}s"
                                  f" > {cfg['stale_heartbeat_s']:g}s "
                                  "stale threshold",
                        "signals": {"state": n.state,
                                    "heartbeat_age_s":
                                        n.heartbeat_age_s}})
    return out


def _rule_grow_cache(signals: ClusterSignals,
                     cfg: Dict[str, float]) -> List[Dict]:
    out: List[Dict] = []
    caches = signals.caches
    for name, pressure in (("scan", caches.scan_cache_pressure),
                           ("plan", caches.plan_cache_pressure),
                           ("result", caches.result_cache_pressure)):
        if pressure > cfg["cache_pressure"]:
            out.append({"action": "grow_cache",
                        "target": f"{name}_cache",
                        "reason": f"fill {pressure:.0%} > "
                                  f"{cfg['cache_pressure']:.0%} "
                                  "pressure threshold",
                        "signals": {"pressure": round(pressure, 4)}})
    return out


def _rule_scale_coordinator(signals: ClusterSignals,
                            cfg: Dict[str, float]) -> List[Dict]:
    """Admission-bound detection: a group's queue is deep while every
    device sits idle — the hard concurrency limit (admission), not
    worker capacity, is the bottleneck, so adding workers cannot help.
    The fix is more *coordinators*: each fleet member brings its own
    admission slots, federated with bounded staleness (PR 19)."""
    out: List[Dict] = []
    if not signals.nodes:
        return out                   # device idleness unknown: hold
    active = sum(n.active_tasks for n in signals.nodes)
    if active > len(signals.nodes):
        return out                   # devices busy: worker-bound
    for g in signals.groups:
        limit = max(1, g.hard_concurrency_limit)
        if g.queued >= cfg["coordinator_queue_ratio"] * limit \
                and g.running >= limit:
            out.append({"action": "scale_coordinator",
                        "target": g.group,
                        "reason": f"admission-bound: queue {g.queued} "
                                  f">= {cfg['coordinator_queue_ratio']:g}"
                                  f"x limit {limit} with "
                                  f"{active} active tasks across "
                                  f"{len(signals.nodes)} idle nodes",
                        "signals": {"queued": g.queued,
                                    "running": g.running,
                                    "limit": limit,
                                    "active_tasks": active,
                                    "nodes": len(signals.nodes)}})
    return out


#: evaluation order matters only for output ordering; each rule is
#: independent (scale_down re-checks the scale_up predicate itself)
RULES: "Dict[str, Callable[[ClusterSignals, Dict[str, float]], List[Dict]]]" = {
    "scale_up": _rule_scale_up,
    "scale_down": _rule_scale_down,
    "replace_node": _rule_replace_node,
    "grow_cache": _rule_grow_cache,
    "scale_coordinator": _rule_scale_coordinator,
}


def decide(signals: ClusterSignals, *,
           queue_ratio: float = 2.0,
           idle_ratio: float = 0.25,
           stale_heartbeat_s: float = 30.0,
           cache_pressure: float = 0.9,
           min_budget: float = 0.5,
           coordinator_queue_ratio: float = 4.0) -> List[Dict]:
    """Map one frozen snapshot to scaling recommendations by running
    every registered rule. Pure and deterministic: same snapshot,
    same decisions."""
    cfg = {"queue_ratio": queue_ratio, "idle_ratio": idle_ratio,
           "stale_heartbeat_s": stale_heartbeat_s,
           "cache_pressure": cache_pressure, "min_budget": min_budget,
           "coordinator_queue_ratio": coordinator_queue_ratio}
    out: List[Dict] = []
    for rule in RULES.values():
        out.extend(rule(signals, cfg))
    return out


def demo_signals() -> ClusterSignals:
    """A synthetic busy cluster exercising every classic rule: one
    backed-up group, one paging group, one idle group, one stale node,
    one hot cache (the ``--demo`` watcher input and the feed's
    contract-test fixture)."""
    return ClusterSignals(
        ts=0.0,
        groups=(
            GroupSignals(group="serving.dash", state="FULL",
                         running=8, queued=20,
                         hard_concurrency_limit=8,
                         p95_s=0.45, burn_short=1.2, burn_long=0.8,
                         error_budget_remaining=0.6,
                         alert_state="OK"),
            GroupSignals(group="serving.adhoc", state="CAN_RUN",
                         running=3, queued=1,
                         hard_concurrency_limit=8,
                         p95_s=2.1, burn_short=14.0, burn_long=11.0,
                         error_budget_remaining=0.0,
                         alert_state="PAGE"),
            GroupSignals(group="batch", state="CAN_RUN",
                         running=0, queued=0,
                         hard_concurrency_limit=16,
                         error_budget_remaining=1.0,
                         alert_state="OK"),
        ),
        nodes=(
            NodeSignals(node_id="w0", state="active",
                        heartbeat_age_s=1.5, active_tasks=4),
            NodeSignals(node_id="w1", state="active",
                        heartbeat_age_s=95.0, active_tasks=0),
        ),
        caches=CacheSignals(scan_cache_resident_bytes=950,
                            scan_cache_limit_bytes=1000,
                            plan_cache_entries=10,
                            plan_cache_capacity=64,
                            result_cache_resident_bytes=100,
                            result_cache_limit_bytes=1000),
    )


# -- the drain path -----------------------------------------------------------

def drain_node(url: str, timeout_s: float = 30.0,
               poll_s: float = 0.1) -> bool:
    """THE scale-down primitive: ask the node to drain
    (``PUT /v1/info/state SHUTTING_DOWN`` — active tasks finish and
    commit their spool, the node deregisters itself with a final GONE
    announcement) and wait until its socket refuses. Returns False if
    the node never confirmed the drain or outlived ``timeout_s`` —
    the caller decides what a stuck drain means; this function never
    kills anything."""
    req = urllib.request.Request(
        f"{url}/v1/info/state", data=b'"SHUTTING_DOWN"', method="PUT",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()
    except Exception:
        return False
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/v1/info",
                                        timeout=2) as resp:
                resp.read()
        except urllib.error.HTTPError:
            pass                      # still answering: keep waiting
        except Exception:
            return True               # socket refused: drained + gone
        time.sleep(poll_s)
    return False


# -- providers ----------------------------------------------------------------

class NodeHandle:
    """One worker the provider owns."""

    __slots__ = ("node_id", "url", "proc", "server")

    def __init__(self, node_id: str, url: str, proc=None, server=None):
        self.node_id = node_id
        self.url = url
        self.proc = proc              # LocalProcessProvider
        self.server = server          # InProcessProvider

    def __repr__(self) -> str:
        return f"NodeHandle({self.node_id} @ {self.url})"


class NodeProvider:
    """The pluggable capacity boundary. The controller only ever calls
    these four methods; a cloud provider implements the same surface
    against real instance APIs. ``terminate`` exists for replacing
    nodes that no longer answer their drain — the controller NEVER
    calls it for scale-down."""

    def launch(self) -> NodeHandle:
        raise NotImplementedError

    def nodes(self) -> List[NodeHandle]:
        raise NotImplementedError

    def drain(self, handle: NodeHandle,
              timeout_s: float = 30.0) -> bool:
        raise NotImplementedError

    def terminate(self, handle: NodeHandle) -> None:
        raise NotImplementedError


class LocalProcessProvider(NodeProvider):
    """Workers as real subprocesses (``python -m
    presto_tpu.server.worker``), announcing to the coordinator(s) over
    HTTP — the closest local stand-in for cloud instances: separate
    address spaces, real process exit on drain, SIGKILL preemption."""

    def __init__(self, coordinator_urls: Sequence[str],
                 tpch_sf: float = 0.01, host: str = "127.0.0.1",
                 spool_dir: Optional[str] = None,
                 etc_dir: Optional[str] = None,
                 ready_timeout_s: float = 180.0,
                 extra_env: Optional[Dict[str, str]] = None):
        self.coordinator_urls = list(coordinator_urls)
        self.tpch_sf = float(tpch_sf)
        self.host = host
        self.spool_dir = spool_dir
        self.etc_dir = etc_dir
        self.ready_timeout_s = float(ready_timeout_s)
        #: worker-process environment overlay (e.g. the elasticity
        #: bench's PRESTO_TPU_DEVICE_FLOOR_MS device model)
        self.extra_env = dict(extra_env or {})
        self._handles: List[NodeHandle] = []
        self._seq = 0

    def launch(self) -> NodeHandle:
        self._seq += 1
        argv = [sys.executable, "-m", "presto_tpu.server.worker",
                "--host", self.host, "--port", "0",
                "--tpch-sf", str(self.tpch_sf)]
        if self.coordinator_urls:
            argv += ["--coordinator", ",".join(self.coordinator_urls)]
        if self.spool_dir:
            argv += ["--spool-dir", self.spool_dir]
        if self.etc_dir:
            argv += ["--etc-dir", self.etc_dir]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self.extra_env)
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, cwd=_REPO_ROOT, env=env,
            start_new_session=True)
        ready: List[Optional[bytes]] = [None]

        def read_line():
            ready[0] = proc.stdout.readline()
        t = threading.Thread(target=read_line, daemon=True)
        t.start()
        t.join(self.ready_timeout_s)
        if ready[0] is None or not ready[0].strip():
            proc.kill()
            raise RuntimeError(
                f"worker subprocess not ready in "
                f"{self.ready_timeout_s:.0f}s")
        doc = json.loads(ready[0])
        handle = NodeHandle(doc["nodeId"],
                            f"http://{self.host}:{doc['port']}",
                            proc=proc)
        self._handles.append(handle)
        return handle

    def nodes(self) -> List[NodeHandle]:
        self._handles = [h for h in self._handles
                         if h.proc.poll() is None]
        return list(self._handles)

    def drain(self, handle: NodeHandle,
              timeout_s: float = 30.0) -> bool:
        ok = drain_node(handle.url, timeout_s=timeout_s)
        if ok:
            try:
                handle.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                ok = False
        if ok and handle in self._handles:
            self._handles.remove(handle)
        return ok

    def terminate(self, handle: NodeHandle) -> None:
        handle.proc.kill()
        try:
            handle.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        if handle in self._handles:
            self._handles.remove(handle)

    def stop_all(self) -> None:
        """Provider teardown (harness cleanup, not controller policy)."""
        for h in list(self._handles):
            self.terminate(h)


class InProcessProvider(NodeProvider):
    """WorkerServer objects inside this process, announcing into an
    in-process DiscoveryNodeManager — the chaos/test substrate. Drain
    still goes over real HTTP (the same bytes a cloud worker would
    see); the explicit deregister is a final GONE announcement."""

    def __init__(self, discovery, tpch_sf: float = 0.01,
                 catalogs=None, drain_grace_s: float = 2.0):
        self.discovery = discovery
        self.tpch_sf = float(tpch_sf)
        self.catalogs = catalogs
        self.drain_grace_s = float(drain_grace_s)
        self._handles: List[NodeHandle] = []

    def launch(self) -> NodeHandle:
        from ..server.worker import WorkerServer
        w = WorkerServer(catalogs=self.catalogs, tpch_sf=self.tpch_sf,
                         drain_grace_s=self.drain_grace_s)
        w.start()
        url = f"http://127.0.0.1:{w.port}"
        self.discovery.announce(w.node_id, url)
        handle = NodeHandle(w.node_id, url, server=w)
        self._handles.append(handle)
        return handle

    def nodes(self) -> List[NodeHandle]:
        self._handles = [
            h for h in self._handles
            if h.server.httpd.socket.fileno() != -1]
        return list(self._handles)

    def drain(self, handle: NodeHandle,
              timeout_s: float = 30.0) -> bool:
        ok = drain_node(handle.url, timeout_s=timeout_s)
        if ok:
            ok = handle.server.stopped.wait(timeout=timeout_s)
        if ok:
            # in-process workers announce through the provider, so the
            # provider issues their explicit deregister too
            self.discovery.announce(handle.node_id, handle.url,
                                    state="GONE")
            if handle in self._handles:
                self._handles.remove(handle)
        return ok

    def terminate(self, handle: NodeHandle) -> None:
        w = handle.server
        try:
            w.httpd.shutdown()
            w.httpd.server_close()
        except Exception:
            pass
        for t in list(w.tasks.values()):
            t.abort()
        self.discovery.announce(handle.node_id, handle.url,
                                state="GONE")
        if handle in self._handles:
            self._handles.remove(handle)

    def stop_all(self) -> None:
        for h in list(self._handles):
            self.terminate(h)


# -- the controller -----------------------------------------------------------

@dataclass
class AutoscalePolicy:
    """Everything the controller needs to stay stable: floor/ceiling,
    bounded steps, cooldown between applied actions, and the
    consecutive-evaluation confirmation count (hysteresis). The rule
    thresholds ride along so one object configures the whole loop."""
    min_workers: int = 1
    max_workers: int = 8
    scale_step: int = 1
    cooldown_s: float = 30.0
    confirm_evals: int = 2
    interval_s: float = 5.0
    rule_config: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_RULE_CONFIG))


class AutoscaleController:
    """The coordinator-side control loop (see module docstring)."""

    def __init__(self, provider: NodeProvider,
                 policy: Optional[AutoscalePolicy] = None,
                 signals_fn: Callable[[], ClusterSignals]
                 = cluster_signals,
                 coordinator_scaler=None,
                 on_grow_cache: Optional[Callable[[str], None]] = None,
                 drain_timeout_s: float = 30.0):
        from .._devtools.lockcheck import checked_lock
        self.provider = provider
        self.policy = policy or AutoscalePolicy()
        self.signals_fn = signals_fn
        #: duck-typed coordinator-tier scaler: ``scale_up(reason)`` /
        #: ``scale_down(reason)`` (tools/fleet.py FleetHandle adapts)
        self.coordinator_scaler = coordinator_scaler
        self.on_grow_cache = on_grow_cache
        self.drain_timeout_s = float(drain_timeout_s)
        self._lock = checked_lock("autoscale.controller")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: (action, target) -> consecutive evaluations recommending it
        self._streaks: Dict[Tuple[str, str], int] = {}
        self._last_action_t: Optional[float] = None
        self._last_report: Dict = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscale-controller")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.policy.interval_s):
            try:
                self.evaluate()
            except Exception:
                # the control loop must outlive a bad snapshot or a
                # provider hiccup; the next tick retries
                REGISTRY.counter("autoscale_loop_errors_total").inc()

    # -- one control tick ----------------------------------------------------
    def evaluate(self, signals: Optional[ClusterSignals] = None,
                 now: Optional[float] = None) -> Dict:
        """One control tick: snapshot → rules → hysteresis/cooldown/
        bounds gates → applied actions. Injectable ``signals``/``now``
        make the loop unit-testable tick by tick."""
        with self._lock:
            return self._evaluate_locked(signals, now)

    def _evaluate_locked(self, signals, now) -> Dict:
        now = time.monotonic() if now is None else now
        signals = self.signals_fn() if signals is None else signals
        _EVALS.inc()
        decisions = decide(signals, **self.policy.rule_config)
        for d in decisions:
            REGISTRY.counter(
                f"autoscale_decision_total.{d['action']}").inc()

        seen = {(d["action"], d["target"]) for d in decisions}
        self._streaks = {k: v + 1 for k, v in self._streaks.items()
                         if k in seen}
        for k in seen:
            self._streaks.setdefault(k, 1)

        applied: List[Dict] = []
        blocked: List[Dict] = []

        def block(d: Dict, why: str) -> None:
            REGISTRY.counter(f"autoscale_blocked_total.{why}").inc()
            blocked.append({**d, "blocked": why})

        paged = any(g.alert_state == "PAGE" for g in signals.groups)
        for d in decisions:
            action, target = d["action"], d["target"]
            if self._streaks.get((action, target), 0) \
                    < self.policy.confirm_evals:
                block(d, "hysteresis")
                continue
            if action == "grow_cache":
                # advisory unless a grower is injected: cache sizing
                # is a config decision, not a capacity one
                if self.on_grow_cache is not None:
                    self.on_grow_cache(target)
                    self._applied(d, applied)
                continue
            if self._last_action_t is not None \
                    and now - self._last_action_t \
                    < self.policy.cooldown_s:
                block(d, "cooldown")
                continue
            if action == "scale_up":
                n = min(self.policy.scale_step,
                        self.policy.max_workers
                        - len(self.provider.nodes()))
                if n <= 0:
                    block(d, "bounds")
                    continue
                for _ in range(n):
                    self.provider.launch()
                self._applied(d, applied, now, count=n)
            elif action == "scale_down":
                if paged:
                    # the PR 16 invariant, re-checked at apply time:
                    # a paging cluster never shrinks — not even a
                    # group the rules judged idle
                    block(d, "page-held")
                    continue
                nodes = self.provider.nodes()
                n = min(self.policy.scale_step,
                        len(nodes) - self.policy.min_workers)
                if n <= 0:
                    block(d, "bounds")
                    continue
                victims = self._pick_victims(nodes, signals, n)
                ok = all(self.provider.drain(
                    v, timeout_s=self.drain_timeout_s)
                    for v in victims)
                if ok:
                    self._applied(d, applied, now, count=len(victims))
                else:
                    # a stuck drain is NOT escalated to a kill: the
                    # node keeps serving, the next tick retries
                    block(d, "drain-failed")
            elif action == "replace_node":
                handle = next(
                    (h for h in self.provider.nodes()
                     if h.node_id == target), None)
                if handle is None:
                    block(d, "unknown-node")
                    continue
                self.provider.launch()   # capacity first
                if not self.provider.drain(
                        handle, timeout_s=self.drain_timeout_s):
                    # a node too dead to drain is exactly what
                    # terminate exists for — this is replacement of a
                    # corpse, not scale-down
                    self.provider.terminate(handle)
                self._applied(d, applied, now)
            elif action == "scale_coordinator":
                if self.coordinator_scaler is None:
                    block(d, "no-scaler")
                    continue
                if self.coordinator_scaler.scale_up(d["reason"]):
                    self._applied(d, applied, now)
                else:
                    block(d, "scaler-refused")

        self._last_report = {
            "ts": signals.ts, "now": now,
            "workers": len(self.provider.nodes()),
            "decisions": decisions, "applied": applied,
            "blocked": blocked,
        }
        return self._last_report

    def _applied(self, d: Dict, applied: List[Dict],
                 now: Optional[float] = None, count: int = 1) -> None:
        REGISTRY.counter(
            f"autoscale_actions_total.{d['action']}").inc()
        applied.append({**d, "count": count})
        if now is not None:
            self._last_action_t = now

    @staticmethod
    def _pick_victims(nodes: List[NodeHandle],
                      signals: ClusterSignals,
                      n: int) -> List[NodeHandle]:
        """Idle-most first, judged by the feed's per-node active-task
        counts (unknown nodes sort last-launched-first-drained)."""
        active = {ns.node_id: ns.active_tasks for ns in signals.nodes}
        order = sorted(
            enumerate(nodes),
            key=lambda iv: (active.get(iv[1].node_id, 0), -iv[0]))
        return [h for _i, h in order[:n]]

    # -- observability -------------------------------------------------------
    def status(self) -> Dict:
        """The ``/v1/autoscale`` surface."""
        return {
            "running": self._thread is not None,
            "policy": {
                "minWorkers": self.policy.min_workers,
                "maxWorkers": self.policy.max_workers,
                "scaleStep": self.policy.scale_step,
                "cooldownS": self.policy.cooldown_s,
                "confirmEvals": self.policy.confirm_evals,
                "intervalS": self.policy.interval_s,
            },
            "workers": [
                {"nodeId": h.node_id, "url": h.url}
                for h in self.provider.nodes()],
            "streaks": {f"{a}:{t}": c
                        for (a, t), c in self._streaks.items()},
            "lastReport": self._last_report,
        }

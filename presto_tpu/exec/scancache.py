"""Device-resident scan cache + asynchronous prefetching scan pipeline.

The input side of the engine, shared by the local executor
(exec/local.py) and the cluster worker task path (server/worker.py):

- **ScanCache** — a memory-accounted, LRU, cross-query cache of decoded
  device column sets keyed by (connector instance, catalog, table,
  split, column set, pushdown, table data-version). tf.data (PAPERS.md)
  and "Accelerating Presto with GPUs" both found that the accelerator
  starves unless decoded input is cached and pipelined; here a warm
  re-run of a scan-heavy query replays device-resident batches instead
  of re-generating/decoding/transferring every split. Entries are
  accounted against a dedicated ``memory.QueryMemoryPool`` (so the
  resident set is bounded and observable) and invalidated on connector
  writes through ``connectors.spi.notify_data_change`` — the same write
  path that already invalidates the sqlite connector's TableStats
  cache. Connectors that cannot attest a data version
  (``Connector.data_version`` returns None, e.g. the live
  system.runtime tables) are never cached.

- **Prefetching pipeline** — bounded per-split reorder queues filled by
  background threads: split N+1 decodes and stages to the device
  (``jax.device_put``) while the consumer's kernels chew on split N.
  Delivery stays in deterministic split order (physical row order feeds
  order-sensitive downstream semantics). Consumer-side waits are
  recorded as prefetch stalls — the histogram that says whether a query
  is input-bound — and credited back to the fair device scheduler
  (exec/taskexec.py) so stalled queries aren't billed device time they
  never used.

- **Bucketed capacity padding** — the ragged final chunk of a split
  pads up to the scan stream's standard power-of-two bucket, so the
  jit caches (ops/jitcache.py) reuse one executable per operator
  instead of recompiling per residual size.

Observability: ``scan_cache_{hit,miss,insert}_total``,
``scan_cache_evicted_bytes_total``, ``scan_cache_resident_bytes``,
``scan_prefetch_stall_seconds``, ``scan_prefetch_batches_total`` — all
flowing through the shared registry into ``system.runtime.metrics``,
``/v1/metrics``, and the EXPLAIN ANALYZE scan-cache line
(planner/printer.format_scan_cache_summary).

Session knobs (docs/perf.md): ``scan_cache`` (default true; the escape
hatch), ``scan_prefetch``, ``scan_prefetch_depth``,
``scan_pad_batches``, ``scan_threads``. The resident LIMIT is
process-wide on purpose — ``scan-cache.max-bytes`` in
config.properties or ``CACHE.set_limit`` — never a session property
(one session must not evict every other session's cache).
"""
from __future__ import annotations

import dataclasses
import queue as _queue
import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import jax

from .._devtools.lockcheck import checked_lock, checked_rlock, guarded_by
from ..batch import Batch, bucket_capacity
from ..connectors import spi
from ..memory import QueryMemoryPool, batch_device_bytes
from ..obs import flight as _flight
from ..obs.metrics import REGISTRY
from .failpoints import FAILPOINTS

_HITS = REGISTRY.counter("scan_cache_hit_total")
_MISSES = REGISTRY.counter("scan_cache_miss_total")
_INSERTS = REGISTRY.counter("scan_cache_insert_total")
_INVALIDATED = REGISTRY.counter("scan_cache_invalidated_total")
_EVICTED_BYTES = REGISTRY.counter("scan_cache_evicted_bytes_total")
_RESIDENT = REGISTRY.gauge("scan_cache_resident_bytes")
_STALL = REGISTRY.histogram("scan_prefetch_stall_seconds")
_PREFETCH_BATCHES = REGISTRY.counter("scan_prefetch_batches_total")
_SHARED_ATTACH = REGISTRY.counter("scan_shared_attach_total")

#: longest a query waits on another query's in-flight decode before
#: giving up and decoding solo (robustness: a wedged producer must not
#: wedge its attached consumers)
SHARED_WAIT_S = 30.0

#: default resident-set bound for the process-wide cache; overridable
#: via config.properties ``scan-cache.max-bytes`` or CACHE.set_limit
DEFAULT_CACHE_BYTES = 2 << 30


def _freeze(v):
    """Recursively hashable form of split/pushdown payloads (connector
    split info is opaque and may carry lists)."""
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


class _Entry:
    __slots__ = ("batches", "nbytes", "ctx", "conn_ref")

    def __init__(self, batches, nbytes, ctx, conn_ref):
        self.batches = batches
        self.nbytes = nbytes
        self.ctx = ctx
        self.conn_ref = conn_ref


class _InFlight:
    """One split decode in progress: attached queries wait on ``event``
    and read ``batches`` (None = the producer failed or abandoned —
    waiters retry, possibly becoming the producer themselves)."""

    __slots__ = ("event", "batches")

    def __init__(self):
        self.event = threading.Event()
        self.batches: Optional[List[Batch]] = None


class ScanCache:
    """Cross-query LRU of decoded device split data, accounted against
    its own memory pool (the reference has no analogue — Presto re-reads
    the source per query; the closest cousins are Alluxio-style local
    caches and tf.data's ``cache()``, which this is, device-resident).

    Serving plane: the cache additionally brokers **shared-scan
    batching** — N concurrent queries missing on the same (table,
    split, columns, pushdown, version) key attach to ONE in-flight
    decode (``join_inflight``/``finish_inflight``) instead of racing N
    duplicate decodes, the "shared work across concurrent consumers of
    the same table" idea from 'Efficient Tabular Data Preprocessing of
    ML Pipelines' (PAPERS.md)."""

    #: guarded-field contracts (lockcheck): entry map and in-flight
    #: decode table only under the cache lock
    _entries = guarded_by(attr="_lock")
    _inflight = guarded_by(attr="_lock")

    def __init__(self, limit_bytes: int = DEFAULT_CACHE_BYTES):
        self.pool = QueryMemoryPool(limit_bytes)
        self._entries: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        self._inflight: dict = {}
        self._lock = checked_rlock("scancache.entries")

    # -- keying ---------------------------------------------------------------
    @staticmethod
    def key(conn, catalog: str, split, columns, pushdown, version,
            rows_per_batch: int = 0):
        """Raises TypeError when split info / pushdown are unhashable —
        callers treat that split as uncacheable. ``rows_per_batch`` is
        part of the key: a consumer with a different batch-size setting
        must miss (and re-decode at its own granularity), not replay
        another runner's 32x-larger batches into operators sized for
        small ones."""
        k = (id(conn), catalog, split.table.schema, split.table.table,
             _freeze(split.info), tuple(columns), _freeze(pushdown),
             _freeze(version), int(rows_per_batch))
        hash(k)
        return k

    # -- lookup / insert ------------------------------------------------------
    def get(self, key, conn) -> Optional[List[Batch]]:
        return self.get_any([key], conn)

    def get_any(self, keys, conn,
                count_miss: bool = True) -> Optional[List[Batch]]:
        """First hit among ``keys`` (one hit/miss accounted for the
        whole probe — callers pass [effective-pushdown key,
        static-pushdown key]: an entry produced WITHOUT dynamic bounds
        is a superset the engine re-filters anyway, so it serves a
        bounds-carrying consumer correctly). ``count_miss=False`` for
        speculative probes that will be retried with accounting."""
        with self._lock:
            for key in keys:
                e = self._entries.get(key)
                if e is None:
                    continue
                if e.conn_ref() is not conn:
                    # id() reuse after a connector was collected: never
                    # serve another connector's data for a recycled
                    # address
                    self._drop(key, e)
                    continue
                self._entries.move_to_end(key)
                _HITS.inc()
                return e.batches
            if count_miss:
                _MISSES.inc()
            return None

    def put(self, key, conn, batches: List[Batch]) -> bool:
        nbytes = sum(batch_device_bytes(b) for b in batches)
        with self._lock:
            if key in self._entries:
                return True          # first writer won; identical data
            # version re-check under the lock: a write that landed while
            # this scan was decoding already bumped data_version (and
            # its invalidate found nothing to drop) — inserting under
            # the stale version key would leave an unreachable entry
            # squatting on reserved bytes until LRU pressure clears it
            ver_fn = getattr(conn, "data_version", None)
            if ver_fn is not None and _freeze(ver_fn(key[3])) != key[7]:
                return False
            if nbytes > self.pool.limit:
                return False         # can never fit: don't flush the LRU
            self._sweep_dead()
            ctx = self.pool.context("scan-cache-entry")
            while not self.pool.try_reserve(nbytes, ctx):
                if not self._entries:
                    ctx.close()
                    return False
                self._evict_lru()
            self._entries[key] = _Entry(batches, nbytes, ctx,
                                        weakref.ref(conn))
            _INSERTS.inc()
            _RESIDENT.set(self.pool.reserved)
            return True

    # -- shared-scan batching -------------------------------------------------
    def join_inflight(self, key) -> Tuple[_InFlight, bool]:
        """(record, is_owner): the first caller per key becomes the
        owner (it decodes and MUST call :meth:`finish_inflight` on every
        exit path); later callers attach and wait on ``record.event``."""
        with self._lock:
            fl = self._inflight.get(key)
            if fl is not None:
                return fl, False
            fl = self._inflight[key] = _InFlight()
            return fl, True

    def finish_inflight(self, key, batches: Optional[List[Batch]]) -> None:
        """Publish the owner's outcome: the complete staged batch list,
        or None when the decode failed/was abandoned (waiters retry)."""
        with self._lock:
            fl = self._inflight.pop(key, None)
        if fl is not None:
            fl.batches = batches
            fl.event.set()

    # -- eviction / invalidation ---------------------------------------------
    def _drop(self, key, e: _Entry) -> None:
        del self._entries[key]
        e.ctx.close()
        _RESIDENT.set(self.pool.reserved)

    def _evict_lru(self) -> None:
        key, e = next(iter(self._entries.items()))
        _EVICTED_BYTES.inc(e.nbytes)
        self._drop(key, e)

    def _sweep_dead(self) -> None:
        """Drop entries whose connector was garbage-collected (their
        weakref is dead): long-lived processes churn through short-lived
        runners, and dead entries are pure resident-set waste."""
        for key in [k for k, e in self._entries.items()
                    if e.conn_ref() is None]:
            self._drop(key, self._entries[key])

    def invalidate(self, conn=None, table: Optional[str] = None) -> None:
        """Drop entries for a connector (and optionally one table). Part
        of the connector write path via spi.notify_data_change — the
        same path that invalidates per-connector stats caches."""
        with self._lock:
            victims = []
            for key, e in self._entries.items():
                ref = e.conn_ref()
                if ref is None:
                    victims.append(key)   # dead connector: always drop
                    continue
                if conn is not None and ref is not conn:
                    continue
                if table is not None and key[3] != table:
                    continue
                victims.append(key)
            for key in victims:
                self._drop(key, self._entries[key])
            if victims:
                _INVALIDATED.inc(len(victims))

    def clear(self) -> None:
        with self._lock:
            for key in list(self._entries):
                self._drop(key, self._entries[key])

    def set_limit(self, limit_bytes: int) -> None:
        with self._lock:
            self.pool.limit = int(limit_bytes)
            while self._entries and self.pool.reserved > self.pool.limit:
                self._evict_lru()

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return int(self.pool.reserved)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: the process-wide cache (one device per process, like taskexec.GLOBAL)
CACHE = ScanCache()

# connector writes invalidate through the shared SPI notification hook
spi.on_data_change(lambda conn, table: CACHE.invalidate(conn, table))


# -- scan options -------------------------------------------------------------

@dataclasses.dataclass
class ScanOptions:
    cache: bool = True
    prefetch: bool = True
    pad: bool = True
    threads: int = 2
    depth: int = 4
    #: attach concurrent identical-split misses to one in-flight decode
    shared: bool = True


def options_from_session(session) -> ScanOptions:
    # the resident LIMIT is deliberately NOT a session property: the
    # cache is process-wide, and one session's knob must not resize
    # (and evict from) every other session's cache — size it via
    # config.properties scan-cache.max-bytes or CACHE.set_limit
    from ..planner.planner import bool_property
    props = session.properties
    return ScanOptions(
        cache=bool_property(session, "scan_cache", True),
        prefetch=bool_property(session, "scan_prefetch", True),
        pad=bool_property(session, "scan_pad_batches", True),
        threads=int(props.get("scan_threads", 2)),
        depth=int(props.get("scan_prefetch_depth", 4)),
        shared=bool_property(session, "shared_scan", True))


class _PadTracker:
    """Max-capacity-so-far tracker for one scan stream: a batch smaller
    than the stream's established bucket (the ragged final chunk) pads
    up to it, bounded by the rows_per_batch bucket, so one executable
    per operator serves the whole stream."""

    __slots__ = ("_lock", "_max", "ceiling")

    def __init__(self, ceiling: int):
        self._lock = checked_lock("scancache.pad")
        self._max = 0
        self.ceiling = ceiling

    def target(self, capacity: int) -> int:
        with self._lock:
            if capacity > self._max:
                self._max = capacity
                return capacity
            return min(self._max, self.ceiling)


# -- the scan pipeline --------------------------------------------------------

def scan_splits(conn, catalog: str, columns: Sequence[str],
                splits: Sequence, pushdown_fn: Callable[[], object],
                rows_per_batch: int, opts: ScanOptions,
                record_split=None, check_cancel=None,
                stats=None, static_pushdown=None) -> Iterator[Batch]:
    """Stream a table scan's batches: per-split cache lookup, background
    decode+stage prefetch, deterministic split-order delivery, bucketed
    capacity padding. ``pushdown_fn`` is re-evaluated when each split
    starts (dynamic join bounds may arrive while earlier splits stream —
    the bounds in force become part of that split's cache key).
    ``static_pushdown`` (the plan-time bounds, sans dynamic-filter
    additions) keys a FALLBACK lookup: a cached entry produced without
    the dynamic bounds is a superset the join machinery re-filters, so
    it may serve a bounds-carrying re-run — warm hits stay deterministic
    even when dynamic bounds race the scan."""
    if not splits:
        return
    columns = tuple(columns)
    version = None
    cacheable = opts.cache
    if cacheable:
        # getattr: duck-typed connector doubles predate the SPI method
        ver_fn = getattr(conn, "data_version", None)
        version = ver_fn(splits[0].table.table) if ver_fn else None
        cacheable = version is not None
    pad = _PadTracker(bucket_capacity(max(int(rows_per_batch), 1))) \
        if opts.pad else None
    # inline (no prefetch threads): split_batches runs inside the
    # consumer's device-scheduler quantum — attach-waiting there would
    # hold the device while the owner may need quanta to finish its own
    # inline decode (whole-device stall). Inline scans therefore never
    # ATTACH; they still register ownership and publish, so threaded
    # peers (which wait on background threads, outside any quantum) can
    # ride their decode.
    inline_scan = not opts.prefetch or opts.threads <= 1

    def split_keys(split, pushdown):
        """[effective key, static-pushdown fallback key] (deduped);
        empty when uncacheable."""
        if not cacheable:
            return []
        try:
            keys = [ScanCache.key(conn, catalog, split, columns,
                                  pushdown, version, rows_per_batch)]
            if _freeze(static_pushdown) != _freeze(pushdown):
                keys.append(ScanCache.key(conn, catalog, split, columns,
                                          static_pushdown, version,
                                          rows_per_batch))
            return keys
        except TypeError:
            return []            # unhashable connector payload

    def stage(b: Batch) -> Batch:
        if pad is not None:
            tgt = pad.target(b.capacity)
            if tgt > b.capacity:
                from ..ops.jitcache import pad_capacity_jit
                b = pad_capacity_jit(b, tgt)
        # start the host->device transfer from the producing thread so
        # it overlaps the consumer's kernels (no-op for resident arrays)
        b = jax.device_put(b)
        if opts.prefetch:
            # only batches the background pipeline actually staged
            # count — the serial path must not inflate the A/B metric
            _PREFETCH_BATCHES.inc()
        return b

    def replay(i: int, split, cached, t0: float) -> Iterator[Batch]:
        if stats is not None:
            stats.record_cache(True)
        for b in cached:
            if pad is not None:
                pad.target(b.capacity)
            yield b
        if record_split is not None:
            record_split(i, t0, len(cached))

    def attach_wait(fl: "_InFlight") -> bool:
        """Wait on another query's in-flight decode of this split
        (shared-scan batching). True when its batches are usable. The
        wait is an input stall: observed and credited back to the fair
        scheduler like a prefetch stall."""
        from . import taskexec
        _SHARED_ATTACH.inc()
        t_stall = time.perf_counter()
        deadline = t_stall + SHARED_WAIT_S
        done = True
        while not fl.event.wait(0.1):
            if check_cancel is not None:
                check_cancel()
            if time.perf_counter() > deadline:
                done = False      # wedged producer: decode solo
                break
        dt = time.perf_counter() - t_stall
        _STALL.observe(dt)
        taskexec.GLOBAL.note_stall(dt)
        if stats is not None:
            stats.prefetch_stall_s += dt
        mfl = _flight.current_flight()
        if mfl is not None:
            mfl.record("stall", wall=dt)
        return done and fl.batches is not None

    def split_batches(i: int, split) -> Iterator[Batch]:
        t0 = time.perf_counter()
        pushdown = pushdown_fn()
        keys = split_keys(split, pushdown)
        owner_key = None
        solo = False
        while keys:
            cached = CACHE.get_any(keys, conn)
            if cached is not None:
                yield from replay(i, split, cached, t0)
                return
            if not opts.shared or solo:
                break
            fl, owner = CACHE.join_inflight(keys[0])
            if not owner and inline_scan:
                # another query owns the decode but THIS scan runs
                # inside its quantum: waiting would hold the device —
                # decode solo instead (duplicate work beats a stall)
                break
            if owner:
                # close the probe->register gap: a decode that started
                # and FINISHED between this query's miss and its
                # registration already inserted the entry — serve it
                # instead of decoding again
                cached = CACHE.get_any(keys, conn, count_miss=False)
                if cached is not None:
                    CACHE.finish_inflight(keys[0], cached)
                    yield from replay(i, split, cached, t0)
                    return
                owner_key = keys[0]
                break
            if attach_wait(fl):
                # ride the other query's decode: its staged batches
                # serve this consumer directly (put() may have been
                # refused by the memory limit — the list is live
                # either way)
                yield from replay(i, split, fl.batches, t0)
                return
            # producer failed/abandoned (event set, no batches): retry
            # the probe — this query may now become the owner. Producer
            # wedged past the wait budget (event unset): decode solo,
            # unregistered, so one stuck query cannot wedge its peers.
            solo = not fl.event.is_set()
        if keys and stats is not None:
            stats.record_cache(False)
        complete = None
        try:
            src = conn.page_source(split, list(columns),
                                   pushdown=pushdown,
                                   rows_per_batch=rows_per_batch)
            acc = [] if keys else None
            nb = 0
            for b in src.batches():
                # failpoint: abort mid-decode (chaos tests prove a
                # failed/aborted scan never reaches the put() below — a
                # partial column set must not become a resident cache
                # entry)
                FAILPOINTS.hit("scan.decode",
                               key=f"{catalog}.{split.table.table}.{i}",
                               split=i, batch=nb)
                b = stage(b)
                nb += 1
                if acc is not None:
                    acc.append(b)
                yield b
            if record_split is not None:
                record_split(i, t0, nb)
            if acc is not None:
                # only complete split streams insert: every early exit
                # above (decode error, failpoint, abort/GeneratorExit
                # from the consumer) skips this line by construction
                complete = acc
                CACHE.put(keys[0], conn, acc)
        finally:
            if owner_key is not None:
                # publish to attached queries on EVERY exit path: a
                # complete batch list serves them directly; None sends
                # them back to decode for themselves
                CACHE.finish_inflight(owner_key, complete)

    # serial warm fast path: splits already resident replay in order
    # with no thread/queue machinery at all; the pipeline spins up only
    # from the first cold split on (fully-warm queries — the repeated-
    # traffic case the cache exists for — never pay prefetch overhead)
    start = 0
    if cacheable:
        for i, split in enumerate(splits):
            t0 = time.perf_counter()
            keys = split_keys(split, pushdown_fn())
            cached = CACHE.get_any(keys, conn, count_miss=False) \
                if keys else None
            if cached is None:
                break                # split_batches re-probes, counted
            for b in replay(i, split, cached, t0):
                if check_cancel is not None:
                    check_cancel()
                yield b
            start = i + 1
        if start == len(splits):
            return
        splits = list(splits)[start:]

    if not opts.prefetch or opts.threads <= 1:
        for i, split in enumerate(splits, start):
            for b in split_batches(i, split):
                if check_cancel is not None:
                    check_cancel()
                yield b
        return

    # background prefetch: one bounded queue per split; the consumer
    # drains them in split order while workers decode+stage ahead of it
    DONE = object()
    stop = threading.Event()     # consumer gone (e.g. LIMIT satisfied)
    queues = [_queue.Queue(maxsize=max(1, opts.depth)) for _ in splits]
    pending: "_queue.Queue[int]" = _queue.Queue()
    for i in range(len(splits)):
        pending.put(i)

    def put(q, item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def worker() -> None:
        while not stop.is_set():
            try:
                i = pending.get_nowait()
            except _queue.Empty:
                return
            try:
                # ``start + i``: split numbering in stats stays global
                # even when the warm fast path served a prefix
                for b in split_batches(start + i, splits[i]):
                    if not put(queues[i], b):
                        return
            except BaseException as e:  # surfaced on the consumer side
                put(queues[i], e)
                return
            put(queues[i], DONE)

    n_workers = max(1, min(int(opts.threads), len(splits)))
    workers = [threading.Thread(target=worker, daemon=True,
                                name=f"scan-prefetch-{j}")
               for j in range(n_workers)]
    for w in workers:
        w.start()
    from . import taskexec
    try:
        for q in queues:
            while True:
                try:
                    item = q.get_nowait()
                except _queue.Empty:
                    # consumer outran the prefetcher: the wait is an
                    # input stall — observable, and credited back to
                    # the device scheduler (stalled != computing)
                    t_stall = time.perf_counter()
                    item = q.get()
                    dt = time.perf_counter() - t_stall
                    _STALL.observe(dt)
                    taskexec.GLOBAL.note_stall(dt)
                    if stats is not None:
                        stats.prefetch_stall_s += dt
                    mfl = _flight.current_flight()
                    if mfl is not None:
                        mfl.record("stall", wall=dt)
                if item is DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                if check_cancel is not None:
                    check_cancel()
                yield item
    finally:
        stop.set()
        for w in workers:
            # bounded join: workers notice ``stop`` within one 0.1s put
            # timeout; tests assert no scan-prefetch threads leak
            w.join(timeout=2.0)

"""Resource groups: admission, queueing, weighted-fair dispatch
(reference execution/resourcegroups/InternalResourceGroup.java)."""
import threading
import time

import pytest

from presto_tpu.server.resource_groups import (
    QueryQueueFullError, ResourceGroupManager,
)


def test_serial_default():
    m = ResourceGroupManager()
    a = m.submit(user="alice")
    b = m.submit(user="bob")
    assert a.granted and not b.granted
    a.release()
    assert b.granted
    b.release()


def test_concurrency_limit_and_queue():
    m = ResourceGroupManager({
        "rootGroups": [{"name": "g", "hardConcurrencyLimit": 2,
                        "maxQueued": 2}],
        "selectors": [{"group": "g"}]})
    adms = [m.submit() for _ in range(4)]
    assert [a.granted for a in adms] == [True, True, False, False]
    with pytest.raises(QueryQueueFullError):
        m.submit()
    adms[0].release()
    assert adms[2].granted and not adms[3].granted
    for a in adms[1:3]:
        a.release()
    assert adms[3].granted
    adms[3].release()


def test_parent_limit_gates_children():
    m = ResourceGroupManager({
        "rootGroups": [{"name": "root", "hardConcurrencyLimit": 1,
                        "maxQueued": 10,
                        "subGroups": [
                            {"name": "a", "hardConcurrencyLimit": 5},
                            {"name": "b", "hardConcurrencyLimit": 5}]}],
        "selectors": [{"user": "a.*", "group": "root.a"},
                      {"group": "root.b"}]})
    a1 = m.submit(user="alice")
    b1 = m.submit(user="bob")
    assert a1.granted and not b1.granted   # root caps total at 1
    a1.release()
    assert b1.granted
    b1.release()


def test_weighted_fair_prefers_underweighted():
    m = ResourceGroupManager({
        "rootGroups": [{"name": "root", "hardConcurrencyLimit": 2,
                        "maxQueued": 10,
                        "subGroups": [
                            {"name": "small", "hardConcurrencyLimit": 2,
                             "schedulingWeight": 1},
                            {"name": "big", "hardConcurrencyLimit": 2,
                             "schedulingWeight": 3}]}],
        "selectors": [{"source": "s", "group": "root.small"},
                      {"group": "root.big"}]})
    s1 = m.submit(source="s")
    g1 = m.submit()
    assert s1.granted and g1.granted
    s2 = m.submit(source="s")
    g2 = m.submit()
    # small releases -> small has 0 running (ratio 0/1), big has 1
    # (ratio 1/3): small is further below its fair share, so its queued
    # query gets the freed slot
    s1.release()
    assert s2.granted and not g2.granted
    # big releases -> ratios small 1/1 vs big 0/3: big goes next
    g1.release()
    assert g2.granted
    for a in (s2, g2):
        a.release()


def test_selector_matching():
    m = ResourceGroupManager({
        "rootGroups": [{"name": "r", "hardConcurrencyLimit": 10,
                        "subGroups": [
                            {"name": "etl", "hardConcurrencyLimit": 5},
                            {"name": "adhoc", "hardConcurrencyLimit": 5}]}],
        "selectors": [{"user": "etl-.*", "group": "r.etl"},
                      {"group": "r.adhoc"}]})
    a = m.submit(user="etl-nightly")
    b = m.submit(user="jane")
    assert a.group.path == "r.etl"
    assert b.group.path == "r.adhoc"
    a.release(); b.release()


def test_release_of_queued_admission_frees_no_slot():
    """Cancelling a QUEUED query must remove it from the queue without
    granting (and leaking) a run slot."""
    m = ResourceGroupManager()      # concurrency 1
    a = m.submit()
    b = m.submit()
    assert a.granted and not b.granted
    b.release()                     # cancel while queued
    a.release()
    c = m.submit()                  # the slot is free, not leaked
    assert c.granted
    c.release()
    info = m.info()[0]
    assert info["numRunning"] == 0 and info["numQueued"] == 0


def test_group_config_parses_serving_keys():
    """softMemoryLimit / hardMemoryLimit / queryQueuedTimeout parse from
    the JSON config and surface in info() (docs/serving.md schema)."""
    m = ResourceGroupManager({
        "rootGroups": [{"name": "g", "hardConcurrencyLimit": 4,
                        "softMemoryLimit": 1 << 30,
                        "hardMemoryLimit": 2 << 30,
                        "queryQueuedTimeout": "1.5s"}],
        "selectors": [{"group": "g"}]})
    g = m.roots["g"]
    assert g.soft_memory_limit == 1 << 30
    assert g.hard_memory_limit == 2 << 30
    assert g.query_queued_timeout == 1.5
    info = m.info()[0]
    assert info["softMemoryLimitBytes"] == 1 << 30
    assert info["memoryReservedBytes"] == 0
    assert info["state"] == "CAN_RUN"


def test_over_soft_memory_queues_until_release():
    """A group past softMemoryLimit stops admitting (kill-or-queue);
    the queued query starts the moment memory returns."""
    m = ResourceGroupManager({
        "rootGroups": [{"name": "g", "hardConcurrencyLimit": 8,
                        "softMemoryLimit": 100}],
        "selectors": [{"group": "g"}]})
    g = m.roots["g"]
    a = m.submit()
    assert a.granted
    with m.memory_lock:
        g.memory_reserved = 150          # over the soft limit
    assert m.info()[0]["state"] == "OVER_SOFT_MEMORY_LIMIT"
    b = m.submit()
    assert not b.granted                 # queued, not started
    with m.memory_lock:
        g.memory_reserved = 0
    m._dispatch()
    assert b.granted
    b.release()
    a.release()


def test_server_queues_second_query():
    """Server-level: with the default serial group, a second statement
    stays QUEUED until the first finishes."""
    from presto_tpu.server.protocol import PrestoTpuServer

    class SlowRunner:
        def __init__(self):
            self.gate = threading.Event()
            from presto_tpu.exec.local import QueryResult
            self._result = QueryResult(["x"], [], [(1,)])

        def execute(self, sql, properties=None, user="",
                    cancel_event=None):
            if sql == "slow":
                self.gate.wait(20)
            return self._result

    runner = SlowRunner()
    srv = PrestoTpuServer(runner=runner)
    q1 = srv.create_query("slow", {})
    q2 = srv.create_query("fast", {})
    deadline = time.time() + 10
    while q1.state != "RUNNING" and time.time() < deadline:
        time.sleep(0.02)
    assert q1.state == "RUNNING"
    time.sleep(0.3)
    assert q2.state == "QUEUED"
    runner.gate.set()
    deadline = time.time() + 10
    while q2.state != "FINISHED" and time.time() < deadline:
        time.sleep(0.02)
    assert q1.state == "FINISHED" and q2.state == "FINISHED"
    info = srv.resource_groups.info()
    assert info[0]["numRunning"] == 0 and info[0]["numQueued"] == 0

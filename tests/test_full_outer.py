"""FULL OUTER JOIN + arbitrary-arity join keys.

Reference: operator/LookupJoinOperator.java probes all join types against
the same lookup source, with LookupOuterOperator emitting the
unmatched-build tail from a visited-positions bitmap; join keys are
arbitrary channel tuples (sql/gen/JoinCompiler.java). The TPU engine
mirrors both: build_match_mask tracks matched build rows across probe
batches, and key tuples compare lexicographically at any arity/width.
"""
import pytest

# tier-1 budget: excluded from `pytest -m 'not slow'` — FULL OUTER matrix is compile-bound
# (see tools/check_tier1_time.py; ~55s)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.01)


@pytest.fixture(scope="module")
def dist(runner):
    from presto_tpu.exec.distributed import DistributedRunner
    return DistributedRunner(catalogs=runner.session.catalogs,
                             n_devices=8, rows_per_batch=1 << 12)


FULL_BASIC = """
SELECT a.x, a.v, b.x, b.w FROM
 (VALUES (1, 'a1'), (2, 'a2'), (4, 'a4')) a(x, v)
 FULL OUTER JOIN (VALUES (2, 'b2'), (3, 'b3'), (4, 'b4')) b(x, w)
 ON a.x = b.x
ORDER BY coalesce(a.x, b.x), a.v NULLS LAST
"""

FULL_EXPECT = [
    (1, "a1", None, None),
    (2, "a2", 2, "b2"),
    (None, None, 3, "b3"),
    (4, "a4", 4, "b4"),
]


def test_full_outer_basic(runner):
    assert runner.execute(FULL_BASIC).rows == FULL_EXPECT


def test_full_outer_distributed(dist):
    assert dist.execute(FULL_BASIC).rows == FULL_EXPECT


def test_full_outer_null_keys_never_match(runner):
    rows = runner.execute("""
        SELECT a.v, b.w FROM
         (VALUES (1, 'a1'), (cast(null as integer), 'an')) a(x, v)
         FULL OUTER JOIN
         (VALUES (1, 'b1'), (cast(null as integer), 'bn')) b(x, w)
         ON a.x = b.x
        ORDER BY a.v NULLS LAST, b.w NULLS LAST
    """).rows
    assert rows == [("a1", "b1"), ("an", None), (None, "bn")]


def test_full_outer_many_to_many(runner):
    rows = runner.execute("""
        SELECT a.v, b.w FROM
         (VALUES (1, 'a1'), (1, 'a2'), (5, 'a5')) a(x, v)
         FULL OUTER JOIN
         (VALUES (1, 'b1'), (1, 'b2'), (7, 'b7')) b(x, w)
         ON a.x = b.x
        ORDER BY a.v NULLS LAST, b.w NULLS LAST
    """).rows
    assert rows == [
        ("a1", "b1"), ("a1", "b2"), ("a2", "b1"), ("a2", "b2"),
        ("a5", None), (None, "b7"),
    ]


def test_full_outer_aggregate_over_tpch(runner):
    # every order has a customer, but not every customer has orders: the
    # unmatched-customer tail must survive the FULL join
    rows = runner.execute("""
        SELECT count(o.o_orderkey), count(*) FROM
        orders o FULL OUTER JOIN customer c ON o.o_custkey = c.c_custkey
    """).rows
    n_orders = runner.execute("SELECT count(*) FROM orders").rows[0][0]
    n_cust_without = runner.execute("""
        SELECT count(*) FROM customer c WHERE NOT EXISTS
         (SELECT 1 FROM orders o WHERE o.o_custkey = c.c_custkey)
    """).rows[0][0]
    assert rows[0][0] == n_orders
    assert rows[0][1] == n_orders + n_cust_without


def test_three_key_join(runner):
    rows = runner.execute("""
        SELECT a.v, b.w FROM
         (VALUES (9223372036854775806, 2.5, 1, 10),
                 (1, -0.0, 2, 20),
                 (5, 3.25, 3, 30)) a(x, y, z, v)
         JOIN (VALUES (9223372036854775806, 2.5, 1, 'hit1'),
                      (1, 0.0, 2, 'hit2'),
                      (5, 3.5, 3, 'miss')) b(x, y, z, w)
         ON a.x = b.x AND a.y = b.y AND a.z = b.z
        ORDER BY a.v
    """).rows
    assert rows == [(10, "hit1"), (20, "hit2")]


def test_wide_key_join_no_32bit_pack(runner):
    # both key columns span > 32 bits: the old shifted pack would collide
    rows = runner.execute("""
        SELECT a.v, b.w FROM
         (VALUES (4294967296123, 8589934592456, 1)) a(x, y, v)
         JOIN (VALUES (4294967296123, 8589934592456, 'hit'),
                      (4294967296123, 8589934592457, 'miss')) b(x, y, w)
         ON a.x = b.x AND a.y = b.y
    """).rows
    assert rows == [(1, "hit")]


def test_full_outer_spilled_build(runner):
    """Force the build side through the host-partition spill path."""
    from presto_tpu.exec.runner import LocalRunner
    r = LocalRunner(catalogs=runner.session.catalogs,
                    rows_per_batch=1 << 12)
    r.session.properties["query_max_memory"] = 200_000
    r.session.properties["spill_partitions"] = 4
    got = r.execute("""
        SELECT count(o.o_orderkey), count(*) FROM
        orders o FULL OUTER JOIN customer c ON o.o_custkey = c.c_custkey
    """).rows
    want = runner.execute("""
        SELECT count(o.o_orderkey), count(*) FROM
        orders o FULL OUTER JOIN customer c ON o.o_custkey = c.c_custkey
    """).rows
    assert got == want
    assert r.session.last_memory_stats is not None


def test_skewed_many_to_many_join(runner):
    """One key with multiplicity far above SKEW_MATCH_LIMIT must not
    explode expand_join's capacity; the executor chunks the build."""
    n = 300   # > SKEW_MATCH_LIMIT
    vals = ", ".join(f"(1, {i})" for i in range(n)) + ", (2, 9000)"
    rows = runner.execute(f"""
        SELECT a.x, count(*), sum(b.i) FROM
         (VALUES (1), (1), (2), (3)) a(x)
         JOIN (VALUES {vals}) b(x, i) ON a.x = b.x
        GROUP BY a.x ORDER BY a.x
    """).rows
    assert rows == [(1, 2 * n, 2 * sum(range(n))), (2, 1, 9000)]


def test_skewed_left_join_unmatched_once(runner):
    n = 200
    vals = ", ".join(f"(1, {i})" for i in range(n))
    rows = runner.execute(f"""
        SELECT a.x, count(b.i) FROM
         (VALUES (1), (5)) a(x)
         LEFT JOIN (VALUES {vals}) b(x, i) ON a.x = b.x
        GROUP BY a.x ORDER BY a.x
    """).rows
    assert rows == [(1, n), (5, 0)]

"""Stats-bounded dense grouping: dense-vs-sort parity, planner gating,
selectivity-first fused chains.

The dense composite-code path (ops/aggregation.py dense_group_plan +
_ScatterReducers over ops/scatter_agg.py digit scatters) must be
RESULT-IDENTICAL to the sort-segment path for every key shape the
planner can route to it — NULL keys, negative keys, keys sitting exactly
on their stats bounds, overflow-adjacent 64-bit sums — because the
dispatch is a pure performance decision (the reference's
BigintGroupByHash dense-array mode has the same contract)."""
import numpy as np
import pytest

import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, Schema
from presto_tpu.obs.metrics import REGISTRY
from presto_tpu.ops.aggregation import (
    AggSpec, dense_group_plan, dense_path_selected, grouped_aggregate,
)


def _metric(name: str) -> float:
    for m in REGISTRY.snapshot():
        if m["name"] == name:
            return float(m.get("value", 0.0))
    return 0.0


def _batch(n, keys, vals, null_frac=0.0, seed=0):
    """Batch of integer key columns + one BIGINT value column."""
    rng = np.random.default_rng(seed)
    fields = [(f"k{i}", T.BIGINT) for i in range(len(keys))] + [
        ("v", T.BIGINT)]
    schema = Schema(fields)
    b = Batch.from_arrays(schema, list(keys) + [vals], num_rows=n)
    if null_frac:
        cap = b.capacity
        cols = list(b.columns)
        for i in range(len(keys)):
            nulls = jnp.asarray(np.pad(rng.random(n) >= null_frac,
                                       (0, cap - n)))
            cols[i] = Column(T.BIGINT, cols[i].data,
                             cols[i].validity & nulls, None)
        b = Batch(schema, cols, b.row_mask)
    return b


def _rows(batch):
    def key(t):
        return tuple((v is None, v) for v in t)
    return sorted([tuple(r) for r in batch.to_pylist()], key=key)


def _assert_rows_equal(a, b):
    assert len(a) == len(b), (len(a), len(b))
    for ra, rb in zip(a, b):
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) <= 1e-9 * max(1.0, abs(y)), (ra, rb)
            else:
                assert x == y, (ra, rb)


def _aggs(vi):
    """The standard agg battery over value column index ``vi``."""
    return [
        AggSpec("sum", vi, T.BIGINT, "s"),
        AggSpec("count", vi, T.BIGINT, "c"),
        AggSpec("count_star", None, T.BIGINT, "cs"),
        AggSpec("min", vi, T.BIGINT, "mn"),
        AggSpec("max", vi, T.BIGINT, "mx"),
        AggSpec("avg", vi, T.DOUBLE, "a"),
    ]


AGGS = _aggs(2)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_dense_sort_parity_random(seed):
    rng = np.random.default_rng(seed)
    n = 4000
    k1 = rng.integers(-7, 25, n)           # negative keys
    k2 = rng.integers(50, 90, n)
    vals = rng.integers(-(1 << 40), 1 << 40, n)
    b = _batch(n, [k1, k2], vals, null_frac=0.15, seed=seed)
    kb = ((-7, 24), (50, 89))
    assert dense_path_selected(b, [0, 1], AGGS, key_bounds=kb)
    dense = grouped_aggregate(b, [0, 1], AGGS, "single", key_bounds=kb)
    plain = grouped_aggregate(b, [0, 1], AGGS, "single")
    _assert_rows_equal(_rows(dense), _rows(plain))


def test_dense_sort_parity_bound_edges():
    """Keys exactly at lo and hi must land in real slots, not clamp."""
    lo, hi = -100, 100
    k = np.array([lo, lo, hi, hi, 0, lo, hi, 3])
    v = np.array([1, 2, 3, 4, 5, 6, 7, 8], dtype=np.int64)
    b = _batch(len(k), [k], v)
    kb = ((lo, hi),)
    dense = grouped_aggregate(b, [0], _aggs(1), "single", key_bounds=kb)
    plain = grouped_aggregate(b, [0], _aggs(1), "single")
    _assert_rows_equal(_rows(dense), _rows(plain))


def test_dense_sort_parity_overflow_adjacent_sums():
    """Sums whose digits span the full 62-bit budget stay exact through
    the i32 digit scatters (structural exactness, not probabilistic)."""
    big = (1 << 61) - 12345
    k = np.array([1, 1, 2, 2, 3])
    v = np.array([big, 7, -big, -13, big], dtype=np.int64)
    b = _batch(len(k), [k], v)
    kb = ((1, 3),)
    dense = grouped_aggregate(b, [0], _aggs(1), "single", key_bounds=kb)
    plain = grouped_aggregate(b, [0], _aggs(1), "single")
    _assert_rows_equal(_rows(dense), _rows(plain))


def test_dense_partial_merge_final_parity():
    """partial -> merge -> final over the dense path must agree with the
    single-pass sort path (the AggSpillBuffer pipeline shape)."""
    rng = np.random.default_rng(7)
    n = 3000
    k1 = rng.integers(0, 40, n)
    k2 = rng.integers(-3, 3, n)
    vals = rng.integers(-(1 << 30), 1 << 30, n)
    b1 = _batch(1500, [k1[:1500], k2[:1500]], vals[:1500], null_frac=0.1)
    b2 = _batch(n - 1500, [k1[1500:], k2[1500:]], vals[1500:],
                null_frac=0.1, seed=1)
    kb = ((0, 39), (-3, 2))
    p1 = grouped_aggregate(b1, [0, 1], AGGS, "partial", key_bounds=kb)
    p2 = grouped_aggregate(b2, [0, 1], AGGS, "partial", key_bounds=kb)
    from presto_tpu.batch import concat_batches
    merged = grouped_aggregate(concat_batches([p1, p2]), [0, 1], AGGS,
                               "merge", key_bounds=kb)
    out = grouped_aggregate(merged, [0, 1], AGGS, "final", key_bounds=kb)
    from presto_tpu.batch import concat_batches as cc
    raw = cc([b1, b2])
    plain = grouped_aggregate(raw, [0, 1], AGGS, "single")
    _assert_rows_equal(_rows(out), _rows(plain))


def test_dense_mixed_radix_with_dict_and_bool_keys():
    """Bounded ints compose with dictionary and boolean components in one
    mixed-radix code (the q27 ROLLUP shape: dict keys + $group_id)."""
    n = 1000
    rng = np.random.default_rng(3)
    gid = rng.integers(0, 3, n)
    code = rng.integers(0, 4, n).astype(np.int32)
    flag = rng.integers(0, 2, n).astype(bool)
    vals = rng.integers(0, 1000, n)
    schema = Schema([("gid", T.BIGINT), ("s", T.varchar(2)),
                     ("b", T.BOOLEAN), ("v", T.BIGINT)])
    b = Batch.from_arrays(schema, [gid, code, flag, vals],
                          dictionaries=[None, ("aa", "bb", "cc", "dd"),
                                        None, None], num_rows=n)
    kb = ((0, 2), None, None)
    plan = dense_group_plan(b, [0, 1, 2], b.capacity, kb)
    assert plan is not None and plan.scatter
    dense = grouped_aggregate(b, [0, 1, 2], AGGS[:1] + AGGS[2:3], "single",
                              key_bounds=kb)
    plain = grouped_aggregate(b, [0, 1, 2], AGGS[:1] + AGGS[2:3], "single")
    _assert_rows_equal(_rows(dense), _rows(plain))


def test_dense_plan_gates():
    n = 100
    k = np.arange(n)
    b = _batch(n, [k], k)
    # unbounded integer key: no plan
    assert dense_group_plan(b, [0], b.capacity, None) is None
    # domain wider than the capacity: no plan
    assert dense_group_plan(b, [0], b.capacity,
                            ((0, 10_000_000),)) is None
    # inverted bounds: no plan
    assert dense_group_plan(b, [0], b.capacity, ((5, 4),)) is None
    # small bounded domain: broadcast reducers, not scatter
    p = dense_group_plan(b, [0], b.capacity, ((0, 99),))
    assert p is not None and p.scatter


def test_bounds_violation_flags():
    from presto_tpu.errors import STATS_BOUND_VIOLATION
    from presto_tpu.ops.jitcache import key_bounds_violation_jit
    k = np.array([1, 2, 3, 999])          # 999 breaks the promised hi=10
    b = _batch(len(k), [k], k)
    code = int(key_bounds_violation_jit(b, (0,), ((1, 10),)))
    assert code == STATS_BOUND_VIOLATION
    ok = int(key_bounds_violation_jit(b, (0,), ((1, 999),)))
    assert ok == 0


# ---------------------------------------------------------------------------
# Planner gate + executor dispatch (the q55 shape)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ds_runner():
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.exec.runner import LocalRunner
    catalogs = CatalogManager()
    # sf 0.05: big enough that store_sales is the largest estimated
    # leaf (the greedy join order anchors on it), small enough for CPU
    catalogs.register("tpcds", TpcdsConnector(sf=0.05))
    return LocalRunner(catalogs=catalogs, catalog="tpcds",
                       rows_per_batch=1 << 16)


def test_planner_attaches_bounds_q55_shape(ds_runner):
    """The real q55 text: the brand aggregation's integer key gets its
    stats bound attached (i_brand_id generated in [1, 1000])."""
    q55 = """
    select i_brand_id brand_id, i_brand brand,
           sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 28 and d_moy = 11 and d_year = 1999
    group by i_brand, i_brand_id
    order by ext_price desc, i_brand_id
    limit 100
    """
    txt = "\n".join(r[0] for r in ds_runner.execute("explain " + q55).rows)
    assert "bounds=[?, 1..1000]" in txt


def test_multikey_bounded_group_takes_dense_path(ds_runner):
    """Multi-key GROUP BY whose keys all carry stats bounds: EXPLAIN
    shows the bounds and execution selects the dense grouping kernel
    (trace-level assertion via the obs metrics registry)."""
    sql = """
    select ss_store_sk, ss_quantity, sum(ss_ticket_number) t,
           count(*) c
    from store_sales
    group by ss_store_sk, ss_quantity
    """
    txt = "\n".join(r[0] for r in ds_runner.execute(
        "explain " + sql).rows)
    assert "bounds=[1..12, 1..100]" in txt
    before = _metric("agg_dense_path_selected_total")
    rows = ds_runner.execute(sql).rows
    assert rows
    after = _metric("agg_dense_path_selected_total")
    assert after > before
    # parity against the sort path (stats-bounded grouping disabled)
    plain = ds_runner.execute(
        sql, properties={"stats_bounded_grouping": False}).rows
    assert sorted(rows) == sorted(plain)


def test_rollup_group_id_gets_bounds(ds_runner):
    """ROLLUP's $group_id carries its exact [0, nsets) bound from the
    GroupIdNode stats rule — the q27 grouping-sets shape."""
    sql = """
    select ss_store_sk, ss_quantity, count(*) c
    from store_sales
    group by rollup (ss_store_sk, ss_quantity)
    """
    txt = "\n".join(r[0] for r in ds_runner.execute(
        "explain " + sql).rows)
    assert "0..2" in txt
    rows = ds_runner.execute(sql).rows
    plain = ds_runner.execute(
        sql, properties={"stats_bounded_grouping": False}).rows
    def key(r):
        return tuple((v is None, v) for v in r)
    assert sorted(rows, key=key) == sorted(plain, key=key)


# ---------------------------------------------------------------------------
# Selectivity-first fused chains (the q27 shape)
# ---------------------------------------------------------------------------

_Q27ISH = """
select i_item_id, s_state, avg(ss_quantity) agg1
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College' and d_year = 2002
group by i_item_id, s_state
order by i_item_id, s_state
limit 50
"""


def test_join_order_is_selectivity_first(ds_runner):
    """The greedy join order puts the most selective dimension
    (customer_demographics: 1/70 of the fact survives) at the BOTTOM of
    the star chain, ahead of smaller-but-unselective dimensions."""
    plan = ds_runner.plan(_Q27ISH)
    from presto_tpu.planner.plan import JoinNode, TableScanNode

    def join_chain_tables(node):
        """Build-side scan tables of the join chain, bottom-up."""
        out = []

        def walk(n):
            for c in n.children:
                walk(c)
            if isinstance(n, JoinNode):
                scan = n.right
                while scan.children:
                    scan = scan.children[0]
                if isinstance(scan, TableScanNode):
                    out.append(scan.table.table)
        walk(plan.root)
        return out

    tables = join_chain_tables(plan.root)
    assert tables.index("customer_demographics") < tables.index("store")
    assert tables.index("customer_demographics") < tables.index("item")


def test_fused_chain_gather_lane_reduction(ds_runner):
    """q27-shaped star chain: the head program's pre-gather masks plus
    windowed compaction shrink the lanes entering the tail's payload
    gathers (obs metrics assert the reduction)."""
    props = {"fused_compact_floor": 1, "fused_compact_window": 2}
    before_src = _metric("fused_source_lanes_total")
    before_tail = _metric("fused_tail_lanes_total")
    rows = ds_runner.execute(_Q27ISH, properties=props).rows
    src = _metric("fused_source_lanes_total") - before_src
    tail = _metric("fused_tail_lanes_total") - before_tail
    assert src > 0, "query did not take the fused-chain path"
    # the cd filter keeps ~1/70 of the fact; compaction must shrink the
    # tail lanes well below the source lanes
    assert tail < src / 2, (src, tail)
    # and the fused path must agree with the generic per-operator path
    plain = ds_runner.execute(_Q27ISH,
                              properties={"fused_pipeline": False}).rows
    assert rows == plain


# ---------------------------------------------------------------------------
# Microbenchmark (slow): dense scatter vs sort-segment at 2^20 x 3 keys
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_dense_beats_sort_microbench():
    """The acceptance microbench: on a 2^20-row batch with a 3-key
    bounded composite domain, the dense i32 scatter path beats the
    multi-operand lax.sort sort-segment path (steady state, compiles
    excluded — the persistent compile cache absorbs them on both
    paths)."""
    import time

    import jax

    from presto_tpu.ops.jitcache import grouped_aggregate_jit

    rng = np.random.default_rng(11)
    n = 1 << 20
    k1 = rng.integers(0, 1000, n)
    k2 = rng.integers(0, 40, n)
    k3 = rng.integers(0, 3, n)
    vals = rng.integers(-(1 << 40), 1 << 40, n)
    schema = Schema([("k1", T.BIGINT), ("k2", T.BIGINT),
                     ("k3", T.BIGINT), ("v", T.BIGINT)])
    b = Batch.from_arrays(schema, [k1, k2, k3, vals], num_rows=n)
    aggs = [AggSpec("sum", 3, T.BIGINT, "s"),
            AggSpec("count_star", None, T.BIGINT, "c")]
    kb = ((0, 999), (0, 39), (0, 2))
    assert dense_path_selected(b, [0, 1, 2], aggs, key_bounds=kb)

    def run(key_bounds):
        out = grouped_aggregate_jit(b, [0, 1, 2], aggs, "partial",
                                    key_bounds=key_bounds)
        jax.block_until_ready(out.columns[0].data)
        return out

    def best_of(fn, reps=3):
        fn()                               # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t_dense = best_of(lambda: run(kb))
    t_sort = best_of(lambda: run(None))
    # parity on the way through
    f_dense = grouped_aggregate(run(kb), [0, 1, 2], aggs, "final",
                                key_bounds=kb)
    f_sort = grouped_aggregate(run(None), [0, 1, 2], aggs, "final")
    _assert_rows_equal(_rows(f_dense), _rows(f_sort))
    assert t_dense < t_sort, (t_dense, t_sort)

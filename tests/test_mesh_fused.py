"""Fused SPMD exchange (PR 15): one compiled program per stage.

The tentpole contract: with ``mesh_fused_exchange`` on (the default),
repartition fuses into the producer's shard_map program (compute +
bucket-count + ship is ONE dispatch ending in device collectives),
stats-bounded aggregation stages batch their rounds into a single
``lax.fori_loop`` dispatch over donated shard buffers, and the host
fetches control scalars once per stage instead of once per round.
``mesh_fused_exchange=off`` is the escape hatch back to the per-round
host control plane — and the oracle these tests compare against:
fused and unfused must be row-exact across NULL-heavy, skewed and
empty-shard inputs, including a forced mid-query re-split.
"""
import jax
import jax.numpy as jnp
import pytest

from presto_tpu.exec.runner import LocalRunner
from presto_tpu.obs.metrics import REGISTRY

from test_mesh_default import _check_parity

SF = 0.005

ON = {"mesh_execution": "on"}

#: the fused-vs-unfused sweep: each shape stresses one failure mode of
#: a fused exchange — NULL groups crossing shards, skewed bucket loads,
#: shards that receive zero rows after partitioning
SHAPES = [
    ("null-heavy", "select n_name, count(c_custkey), sum(c_acctbal) "
                   "from nation left join customer "
                   "on n_nationkey = c_nationkey and c_acctbal < 0 "
                   "group by 1 order by 1"),
    ("skewed", "select o_orderstatus, count(*), sum(o_totalprice), "
               "min(o_orderdate) from orders group by 1 order by 1"),
    ("empty-shard", "select c_mktsegment, count(*) from customer "
                    "where c_custkey < 5 group by 1 order by 1"),
]


def _metric(name: str) -> float:
    return REGISTRY.value(name)


@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=SF, rows_per_batch=1 << 11)


@pytest.fixture(scope="module")
def small_runner():
    # small batches -> many chunks per stage: the shape where the
    # per-round dispatch tax is visible at suite scale
    return LocalRunner(tpch_sf=SF, rows_per_batch=1 << 9)


def _fused_vs_unfused(runner, sql, n, extra=None):
    base = {**ON, "mesh_devices": n, **(extra or {})}
    want = runner.execute(
        sql, properties={**base, "mesh_fused_exchange": False})
    got = runner.execute(
        sql, properties={**base, "mesh_fused_exchange": True})
    _check_parity(want, got, "order by" in sql.lower())
    return got


@pytest.mark.parametrize("name,sql", SHAPES, ids=[s[0] for s in SHAPES])
@pytest.mark.parametrize("n", [2, 4])
def test_fused_parity(runner, name, sql, n):
    _fused_vs_unfused(runner, sql, n)


@pytest.mark.slow
@pytest.mark.parametrize("name,sql", SHAPES, ids=[s[0] for s in SHAPES])
@pytest.mark.parametrize("n", [1, 8])
def test_fused_parity_edge_widths(runner, name, sql, n):
    """n=1 (fused loop with no real exchange) and the full 8-wide mesh
    ride the slow tier — same contract, pricier compiles."""
    _fused_vs_unfused(runner, sql, n)


def test_fused_parity_small_loop_rounds(runner):
    """mesh_fused_loop_rounds=2 forces multi-wave draining: the second
    wave re-enters with the carried (donated) state batch, the shape
    the single-wave tests never exercise."""
    _fused_vs_unfused(runner, SHAPES[1][1], 2,
                      extra={"mesh_fused_loop_rounds": 2})


def test_fused_parity_under_forced_resplit(runner, monkeypatch):
    """A mid-query adaptive re-split is the fused path's rarer
    loop-exit-and-rebuild branch: with the skew threshold forced low, a
    partitioned join re-splits its bucket assignment while the fused
    probe stream is in flight, the build side re-ships under the new
    epoch, and fused still matches unfused row-for-row."""
    from presto_tpu.exec import distributed as D
    monkeypatch.setattr(D, "_skew_ratio", lambda: 1.01)
    sql = ("select c_name, sum(o_totalprice) from customer join orders "
           "on c_custkey = o_custkey group by 1 order by 2 desc, 1 "
           "limit 5")
    before = _metric("mesh_repartition_resplit_total")
    _fused_vs_unfused(runner, sql, 2,
                      extra={"broadcast_join_row_limit": 1})
    assert _metric("mesh_repartition_resplit_total") > before


def test_fused_slashes_host_dispatches(small_runner):
    """The dispatch-tax claim at suite scale: the same grouped
    aggregation costs at most half the host dispatches fused vs
    unfused (the bench pin MULTICHIP_r08 carries the >= 3x evidence at
    bench scale; in-suite the guard is a conservative 2x). Warm runs
    are compared so plan/compile effects cancel."""
    sql = SHAPES[1][1]
    base = {**ON, "mesh_devices": 4}
    small_runner.execute(
        sql, properties={**base, "mesh_fused_exchange": False})
    b0 = _metric("mesh_dispatches_total")
    small_runner.execute(
        sql, properties={**base, "mesh_fused_exchange": False})
    unfused = _metric("mesh_dispatches_total") - b0
    small_runner.execute(sql, properties=base)
    b1 = _metric("mesh_dispatches_total")
    small_runner.execute(sql, properties=base)
    fused = _metric("mesh_dispatches_total") - b1
    assert fused > 0
    assert fused * 2 <= unfused, (fused, unfused)


def test_fused_wave_donates_carried_state(small_runner, monkeypatch):
    """The carried state batch of a multi-wave fused drain is DONATED:
    the executor builds the wave program with donate_argnums on the
    carry position, so round N's output aliases round N-1's buffers
    instead of churning HBM."""
    from presto_tpu.exec.distributed import DistributedExecutor
    donated = []
    orig = DistributedExecutor._smap

    def spy(self, fn, n_in, *args, **kwargs):
        if kwargs.get("donate"):
            donated.append(tuple(kwargs["donate"]))
        return orig(self, fn, n_in, *args, **kwargs)

    monkeypatch.setattr(DistributedExecutor, "_smap", spy)
    small_runner.execute(SHAPES[1][1],
                         properties={**ON, "mesh_devices": 2,
                                     "mesh_fused_loop_rounds": 2})
    assert (0,) in donated


def test_donated_buffer_is_invalidated():
    """Donation semantics the fused loops rely on, pinned at the JAX
    level: a donated input is deleted on dispatch (reuse raises), and
    the compiled program reports the aliased bytes — if either stops
    holding, the carry-donation above silently degrades to a copy."""
    from presto_tpu.ops.jitcache import _TimedEntry
    entry = _TimedEntry(
        "test:donate",
        jax.jit(lambda a, b: (a + b, a - b), donate_argnums=(0,)),
        key=("test_donate",), donate=(0,))
    assert entry.donate == (0,)
    x = jnp.arange(1 << 10, dtype=jnp.float32)
    y = jnp.ones(1 << 10, dtype=jnp.float32)
    out, _ = entry(x, y)
    out.block_until_ready()
    assert x.is_deleted()
    with pytest.raises(RuntimeError):
        _ = x + 1.0
    lowered = jax.jit(
        lambda a, b: (a + b, a - b), donate_argnums=(0,)
    ).lower(y, y).compile()
    mem = lowered.memory_analysis()
    if mem is not None and hasattr(mem, "alias_size_in_bytes"):
        assert mem.alias_size_in_bytes >= y.nbytes

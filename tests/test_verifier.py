"""Verifier: control-vs-test comparison harness (reference
presto-verifier/.../Validator.java:68)."""
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.verifier import Verifier


def test_match_and_mismatch():
    control = LocalRunner(tpch_sf=0.001)
    same = LocalRunner(tpch_sf=0.001)
    bigger = LocalRunner(tpch_sf=0.01)
    v = Verifier(control, same)
    results = v.run([
        "select count(*) from nation",
        "select n_regionkey, count(*) from nation group by 1 order by 1",
        "select sum(l_extendedprice * l_discount) from lineitem",
    ])
    assert [r.status for r in results] == ["MATCH"] * 3
    assert all(r.control_ms > 0 and r.test_ms > 0 for r in results)
    # row-content mismatch (different scale factor)
    bad = Verifier(control, bigger).verify_one(
        "select count(*) from lineitem")
    assert bad.status == "MISMATCH" and "row 0" in bad.detail
    # order-insensitive: reversed ORDER BY still matches
    v2 = Verifier(control, same)
    a = v2.verify_one("select n_name from nation order by 1")
    assert a.status == "MATCH"


def test_failures_classified():
    control = LocalRunner(tpch_sf=0.001)

    class Broken:
        def execute(self, sql):
            raise RuntimeError("boom")

    assert Verifier(Broken(), control).verify_one(
        "select 1").status == "CONTROL_FAILED"
    r = Verifier(control, Broken()).verify_one("select 1")
    assert r.status == "TEST_FAILED" and "boom" in r.detail

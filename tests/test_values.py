"""VALUES as a query body / inline table (reference sql/tree/Values.java,
SqlBase.g4 inlineTable) with derived-table column aliases."""
import datetime

import pytest


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.001)


def test_bare_values(runner):
    assert runner.execute("values 1, 2, 3").rows == [(1,), (2,), (3,)]


def test_values_rows(runner):
    assert runner.execute("values (1, 'a'), (2, 'b')").rows \
        == [(1, "a"), (2, "b")]


def test_values_as_relation_with_aliases(runner):
    rows = runner.execute(
        "select name from (values (1, 'a'), (2, 'b')) as t(id, name) "
        "where id = 2").rows
    assert rows == [("b",)]


def test_values_order_limit(runner):
    rows = runner.execute("values 3, 1, 2 order by 1 limit 2").rows
    assert rows == [(1,), (2,)]


def test_values_join(runner):
    rows = runner.execute(
        "select n.n_name, v.tag from nation n "
        "join (values (0, 'zero'), (1, 'one')) v(k, tag) "
        "on n.n_nationkey = v.k order by 1").rows
    assert rows == [("ALGERIA", "zero"), ("ARGENTINA", "one")]


def test_values_types_unify(runner):
    rows = runner.execute("values (1, null), (null, 'x')").rows
    assert rows == [(1, None), (None, "x")]


def test_values_dates(runner):
    rows = runner.execute("values date '2020-01-01'").rows
    assert rows == [(datetime.date(2020, 1, 1),)]


def test_values_union(runner):
    rows = runner.execute(
        "select * from (values 1) union all "
        "select * from (values 2) order by 1").rows
    assert rows == [(1,), (2,)]


def test_values_arity_mismatch(runner):
    from presto_tpu.sql.analyzer import AnalysisError
    with pytest.raises(AnalysisError, match="arity"):
        runner.execute("values (1, 2), (3)")


def test_values_incompatible_types(runner):
    from presto_tpu.sql.analyzer import AnalysisError
    with pytest.raises(AnalysisError, match="incompatible"):
        runner.execute("values (1), ('x')")


def test_values_constant_expressions(runner):
    assert runner.execute("values (1+1), (10/2)").rows == [(2,), (5,)]
    assert runner.execute("values upper('ab') || 'c'").rows == [("ABc",)]


def test_values_date_timestamp_coercion(runner):
    rows = runner.execute(
        "values (date '2020-01-01'), "
        "(timestamp '2020-01-02 03:00:00')").rows
    assert rows == [(datetime.datetime(2020, 1, 1),),
                    (datetime.datetime(2020, 1, 2, 3, 0),)]


def test_values_arrays(runner):
    rows = runner.execute(
        "select x[2] from (values (array[1,2,3]), (array[4,5,6])) t(x)").rows
    assert rows == [(2,), (5,)]
    rows = runner.execute(
        "select sum(e) from (values (array[1,2])) t(x), "
        "unnest(t.x) u(e)").rows
    assert rows == [(3,)]


def test_values_ctas(runner):
    runner.execute("create table memory.default.vals_t as "
                   "select * from (values (1, 'x'), (2, 'y')) t(a, b)")
    assert runner.execute(
        "select b from memory.default.vals_t where a = 2").rows == [("y",)]

"""Pallas scan kernels (interpret mode on the CPU mesh) and the
sorted-run segment-sum fast path they power.

Reference role: these kernels are the hot-loop replacement for the
reference's hash-aggregation inner loops (reference
presto-main/.../operator/MultiChannelGroupByHash.java) on hardware where
the "hash table" is sort + segmented reduction — see
presto_tpu/ops/pallas_scan.py for the measured rationale.
"""
import numpy as np
import pytest

import presto_tpu.ops.pallas_scan as ps


def test_cumsum_i32_matches_numpy():
    import jax.numpy as jnp
    rng = np.random.default_rng(2)
    for n in (1, 100, ps.TILE, ps.TILE + 1, 3 * ps.TILE + 17):
        x = rng.integers(-1000, 1000, n).astype(np.int32)
        got = np.asarray(ps.cumsum_i32(jnp.asarray(x), interpret=True))
        assert np.array_equal(got, np.cumsum(x).astype(np.int32)), n


def test_cumsum_i32_wraps_mod_2_32():
    import jax.numpy as jnp
    x = np.full(1000, 2 ** 30, dtype=np.int32)
    got = np.asarray(ps.cumsum_i32(jnp.asarray(x), interpret=True))
    want = np.cumsum(x.astype(np.int64)).astype(np.uint64) % (1 << 32)
    assert np.array_equal(got.astype(np.uint64) % (1 << 32), want)


def _sorted_run_case(rng, n_groups, n_rows, lo=-10**17, hi=10**17):
    sizes = rng.multinomial(n_rows, np.ones(n_groups) / n_groups)
    gid = np.repeat(np.arange(n_groups), sizes)
    vals = rng.integers(lo, hi, n_rows)
    starts = np.zeros(n_groups, dtype=np.int32)
    starts[1:] = np.cumsum(sizes)[:-1]
    # absent groups (size 0) must point one past the end per the
    # kernel contract; multinomial keeps all >0 with high probability,
    # so force a couple of empties
    return gid, vals.astype(np.int64), starts, sizes


def test_segment_sum_sorted_i64_exact():
    import jax.numpy as jnp
    rng = np.random.default_rng(3)
    gid, vals, starts, sizes = _sorted_run_case(rng, 64, 5000)
    got = np.asarray(ps.segment_sum_sorted_i64(
        jnp.asarray(vals), jnp.asarray(starts), 64,
        max_rows_per_group=5000, interpret=True))
    want = np.zeros(64, dtype=np.int64)
    np.add.at(want, gid, vals)
    assert np.array_equal(got, want)


def test_segment_sum_sorted_trailing_and_absent_groups():
    import jax.numpy as jnp
    # groups [0,0,1] then dead rows (zero-valued), groups 2..3 absent
    vals = jnp.asarray([5, 7, 11, 0, 0], dtype=jnp.int64)
    starts = jnp.asarray([0, 2, 5, 5], dtype=jnp.int32)
    got = np.asarray(ps.segment_sum_sorted_i64(
        vals, starts, 4, max_rows_per_group=5, interpret=True))
    assert got[0] == 12 and got[1] == 11


def test_segment_count_sorted():
    import jax.numpy as jnp
    live = jnp.asarray([True, True, False, True, False])
    starts = jnp.asarray([0, 2, 5], dtype=jnp.int32)
    got = np.asarray(ps.segment_count_sorted(live, starts, 3,
                                             interpret=True))
    assert got[0] == 2 and got[1] == 1


def test_engine_grouped_agg_scan_path_matches_scatter_path():
    """Force the scan paths through a real grouped aggregation and
    compare with the default scatter path: i64 sums are bit-identical;
    f64 sums agree to summation-order tolerance."""
    from presto_tpu.exec.runner import LocalRunner
    r = LocalRunner(tpch_sf=0.01)
    q = ("select l_orderkey, count(*), sum(l_linenumber), "
         "sum(l_extendedprice) from lineitem group by 1 order by 1 "
         "limit 500")
    plain = r.execute(q).rows
    ps.FORCE_SCAN_PATHS = True
    try:
        forced = r.execute(q).rows
    finally:
        ps.FORCE_SCAN_PATHS = False
    assert len(plain) == len(forced)
    for a, b in zip(plain, forced):
        assert a[:3] == b[:3]
        assert b[3] == pytest.approx(a[3], rel=1e-12)

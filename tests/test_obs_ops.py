"""Operations plane: Prometheus exposition, query history,
straggler/skew detection, node federation, metric-name lint.

Covers the PR-3 layer end to end: /v1/metrics on a live WorkerServer
and on the coordinator protocol server (round-tripped through the tiny
text-format parser), histogram buckets + derived p50/p95/p99,
TaskRegistry eviction, history capture across local and ClusterRunner
paths (including a failed query), straggler detection with an
artificially delayed worker task, and the system.runtime
{nodes,completed_queries,operator_stats} tables over plain SQL.
"""
import json
import os
import sys
import time
import urllib.request

import pytest

from presto_tpu.exec.runner import LocalRunner
from presto_tpu.obs.exposition import parse_exposition, render_exposition
from presto_tpu.obs.history import HISTORY
from presto_tpu.obs.log import LOG
from presto_tpu.obs.metrics import (
    REGISTRY, TASKS, MetricsRegistry, TaskRegistry,
)


def _counter(name: str) -> float:
    return REGISTRY.counter(name).value


# -- histogram buckets + quantiles -------------------------------------------

def test_histogram_buckets_and_quantiles():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds")
    for i in range(1, 101):
        h.observe(i / 100.0)          # 0.01 .. 1.00 uniform
    st = h.state()
    assert st["count"] == 100 and st["min"] == 0.01 and st["max"] == 1.0
    # buckets cumulative and monotone; +Inf bucket equals count
    cums = [c for _, c in st["buckets"]]
    assert cums == sorted(cums) and cums[-1] == 100
    assert st["buckets"][-1][0] == float("inf")
    # bucket-interpolated quantiles of a uniform 0.01..1.0 sample
    assert st["quantiles"][0.5] == pytest.approx(0.5, abs=0.05)
    assert st["quantiles"][0.95] == pytest.approx(0.95, abs=0.05)
    assert st["quantiles"][0.99] == pytest.approx(0.99, abs=0.05)
    assert h.quantile(0.5) == pytest.approx(0.5, abs=0.05)
    # snapshot rows carry the derived quantiles
    rows = {r["name"]: r["value"] for r in reg.snapshot()}
    assert rows["h_seconds.count"] == 100
    for q in ("p50", "p95", "p99"):
        assert f"h_seconds.{q}" in rows


def test_empty_histogram_has_no_quantiles():
    reg = MetricsRegistry()
    h = reg.histogram("e_seconds")
    assert h.quantile(0.5) is None
    rows = {r["name"] for r in reg.snapshot()}
    assert "e_seconds.count" in rows and "e_seconds.p50" not in rows


# -- exposition round-trip ---------------------------------------------------

def test_exposition_round_trip():
    reg = MetricsRegistry()
    reg.counter("c_total").inc(3)
    reg.counter("op_total.scan").inc(2)      # dotted name -> label
    reg.gauge("g_bytes").set(5)
    for v in (0.01, 0.2, 3.0):
        reg.histogram("h_seconds").observe(v)
    text = render_exposition(reg)
    assert text.rstrip().endswith("# EOF")
    samples, types = parse_exposition(text)
    assert types["c_total"] == "counter"
    assert types["op_total"] == "counter"
    assert types["g_bytes"] == "gauge"
    assert types["h_seconds"] == "histogram"
    assert types["h_seconds_quantile"] == "gauge"
    assert samples[("c_total", ())] == 3
    assert samples[("op_total", (("key", "scan"),))] == 2
    assert samples[("g_bytes", ())] == 5
    assert samples[("h_seconds_count", ())] == 3
    assert samples[("h_seconds_sum", ())] == pytest.approx(3.21)
    assert samples[("h_seconds_bucket", (("le", "+Inf"),))] == 3
    # cumulative buckets are monotone in le order
    buckets = sorted(
        ((dict(lbl)["le"], v) for (n, lbl), v in samples.items()
         if n == "h_seconds_bucket"), key=lambda kv: float(kv[0]))
    counts = [v for _, v in buckets]
    assert counts == sorted(counts)
    assert any(n == "h_seconds_quantile" and ("quantile", "0.95") in lbl
               for (n, lbl), _ in samples.items())


def test_parse_exposition_rejects_garbage():
    with pytest.raises(ValueError):
        parse_exposition("this is not a metric line\n")


# -- task registry eviction --------------------------------------------------

def test_task_registry_evicts_oldest_finished_first():
    before = _counter("task_registry_evicted_total")
    reg = TaskRegistry(max_tasks=3)
    reg.update("t1", state="FINISHED")
    reg.update("t2", state="RUNNING")
    reg.update("t3", state="RUNNING")
    reg.update("t4", state="RUNNING")   # over cap: t1 (terminal) goes
    ids = {t["task_id"] for t in reg.snapshot()}
    assert ids == {"t2", "t3", "t4"}
    reg.update("t5", state="RUNNING")   # all live: oldest (t2) goes
    ids = {t["task_id"] for t in reg.snapshot()}
    assert ids == {"t3", "t4", "t5"}
    assert _counter("task_registry_evicted_total") == before + 2


# -- engine integration (local) ----------------------------------------------

@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=0.001)


def test_metrics_table_carries_quantiles(runner):
    runner.execute("select count(*) from nation")
    res = runner.execute(
        "select name, value from system.runtime.metrics "
        "where name = 'query_seconds.p95'")
    assert len(res.rows) == 1
    assert res.rows[0][1] > 0


def test_history_local_success_and_failure(runner):
    runner.execute("select 41 + 1")
    with pytest.raises(Exception):
        runner.execute("select nope_col from nation")
    res = runner.execute(
        "select query_id, state, error, rows, mode from "
        "system.runtime.completed_queries "
        "where query = 'select 41 + 1'")
    assert res.rows
    qid, state, error, rows, mode = res.rows[-1]
    assert state == "FINISHED" and error is None
    assert rows == 1 and mode == "local"
    res = runner.execute(
        "select state, error from system.runtime.completed_queries "
        "where query = 'select nope_col from nation'")
    assert res.rows and res.rows[-1][0] == "FAILED"
    assert res.rows[-1][1]               # error text populated
    # operator_stats rows exist for the succeeded query
    res = runner.execute(
        "select operator, batches from system.runtime.operator_stats "
        f"where query_id = '{qid}'")
    assert res.rows
    assert all(b >= 0 for _, b in res.rows)
    # the record itself carries cpu/peak-memory accounting
    rec = next(r for r in HISTORY.snapshot()
               if r.get("query") == "select 41 + 1")
    assert rec["cpu_ms"] >= 0 and rec["plan_summary"]


def test_history_jsonl_sink_and_slow_query_log(runner, tmp_path):
    sink = tmp_path / "history.jsonl"
    logf = tmp_path / "engine.log"
    old_sink, old_thr = HISTORY.sink_path, HISTORY.slow_threshold_s
    HISTORY.configure(sink_path=str(sink), slow_threshold_s=0.0)
    LOG.configure(path=str(logf))
    try:
        runner.execute("select 'jsonl-sink-marker'")
    finally:
        HISTORY.sink_path, HISTORY.slow_threshold_s = old_sink, old_thr
        LOG.configure()
    recs = [json.loads(line) for line in
            sink.read_text().strip().splitlines()]
    assert any("jsonl-sink-marker" in r["query"] for r in recs)
    events = [json.loads(line) for line in
              logf.read_text().strip().splitlines()]
    slow = [e for e in events if e["event"] == "slow_query"]
    assert any("jsonl-sink-marker" in e.get("query", "") for e in slow)
    done = [e for e in events if e["event"] == "query_completed"]
    assert done and done[-1]["state"] == "FINISHED"


def test_explain_analyze_skew_section(runner):
    res = runner.execute("explain analyze select count(*) from lineitem")
    text = "\n".join(r[0] for r in res.rows)
    # lineitem scans with scan_threads=2 -> 2 splits -> skew section
    assert "Skew (splits per table):" in text
    assert "lineitem" in text.split("Skew (splits per table):")[1]


def test_format_skew_summary_flags_straggler():
    from presto_tpu.exec.stats import StatsCollector
    st = StatsCollector()
    st.record_split("t", 0, 0.0, 0.020, 4)
    st.record_split("t", 1, 0.0, 0.025, 4)
    st.record_split("t", 2, 0.0, 0.500, 4)   # 20x the median of others
    from presto_tpu.planner.printer import format_skew_summary
    out = format_skew_summary(st)
    assert "STRAGGLER" in out and "[2]" in out
    # balanced splits: no straggler flag
    st2 = StatsCollector()
    for i in range(3):
        st2.record_split("t", i, 0.0, 0.020, 4)
    assert "STRAGGLER" not in format_skew_summary(st2)


# -- cluster integration -----------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.server.worker import WorkerServer
    workers = [WorkerServer(tpch_sf=0.001) for _ in range(3)]
    for w in workers:
        w.start()
    urls = [f"http://127.0.0.1:{w.port}" for w in workers]
    runner = ClusterRunner(urls, tpch_sf=0.001, heartbeat=False)
    yield runner, workers
    for w in workers:
        w.stop()


def test_worker_metrics_endpoint(cluster):
    runner, workers = cluster
    runner.execute("select count(*) from nation")
    url = f"http://127.0.0.1:{workers[0].port}/v1/metrics"
    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()
    samples, types = parse_exposition(text)      # round-trip parse
    assert samples and types
    assert samples[("cluster_queries_total", ())] >= 1
    assert types["query_seconds"] == "histogram"
    assert text.rstrip().endswith("# EOF")


def test_coordinator_metrics_endpoint(cluster, runner):
    from presto_tpu.server.protocol import PrestoTpuServer
    crunner, _ = cluster
    crunner.execute("select count(*) from region")
    srv = PrestoTpuServer(runner)
    srv.start()
    try:
        url = f"http://127.0.0.1:{srv.port}/v1/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            samples, types = parse_exposition(resp.read().decode())
    finally:
        srv.stop()
    # federated node-labeled series from the worker heartbeats
    ups = [lbl for (n, lbl), v in samples.items() if n == "node_up"]
    assert len(ups) >= 3
    assert types["node_heartbeat_age_seconds"] == "gauge"


def test_cluster_history_and_nodes_tables(cluster):
    runner, workers = cluster
    marker = ("select l_returnflag, count(*) from lineitem "
              "group by l_returnflag order by l_returnflag")
    res = runner.execute(marker)
    assert len(res.rows) == 3
    # completed_queries has the cluster query, queryable over plain SQL
    res = runner.local.execute(
        "select query_id, state, mode from "
        f"system.runtime.completed_queries where query = '{marker}'")
    assert res.rows and res.rows[-1][1] == "FINISHED"
    assert res.rows[-1][2] == "cluster"
    qid = res.rows[-1][0]
    assert qid.startswith("cq_")
    # per-task operator stats rode the history record
    res = runner.local.execute(
        "select operator, rows from system.runtime.operator_stats "
        f"where query_id = '{qid}'")
    assert res.rows
    # nodes table lists every worker with a fresh heartbeat age
    res = runner.local.execute(
        "select node_id, state, heartbeat_age_s, coordinator "
        "from system.runtime.nodes")
    by_id = {r[0]: r for r in res.rows}
    for w in workers:
        assert w.node_id in by_id, by_id
        _, state, age, coord = by_id[w.node_id]
        assert state == "ACTIVE" and age < 30.0 and not coord
    assert by_id["coordinator"][3]
    # cluster queries appear in system.runtime.queries too
    res = runner.local.execute(
        "select state from system.runtime.queries "
        f"where query = '{marker}'")
    assert res.rows and res.rows[-1][0] == "FINISHED"


def test_straggler_detection_with_delayed_task(cluster, monkeypatch):
    from presto_tpu.server import worker as worker_mod
    runner, _ = cluster
    sql = "select count(*) from lineitem"
    runner.execute(sql)                  # warm compiles before timing

    orig = worker_mod._TaskExecutor._TableScanNode

    def delayed(self, node):
        # partition 0 of the scan stage straggles; the others get a
        # small floor so the stage median clears the detector's noise
        # floor deterministically
        time.sleep(1.2 if self.partition == 0 else 0.05)
        return orig(self, node)

    monkeypatch.setattr(worker_mod._TaskExecutor, "_TableScanNode",
                        delayed)
    before = _counter("straggler_detected_total")
    res = runner.execute(sql)
    assert res.rows[0][0] > 0
    assert _counter("straggler_detected_total") >= before + 1
    flagged = [t for t in TASKS.snapshot() if t.get("straggler")]
    assert flagged
    assert any(t["task_id"].endswith(".0") for t in flagged)
    # flagged rows visible over plain SQL
    res = runner.local.execute(
        "select task_id from system.runtime.tasks "
        "where straggler = true")
    assert res.rows


def test_stage_monitor_skew_detection():
    from presto_tpu.exec.cluster import StageMonitor
    before = _counter("skewed_stage_total")
    mon = StageMonitor("cq_skewtest")
    statuses = [
        {"taskId": "cq_skewtest.0.0", "state": "FINISHED",
         "elapsedMs": 100.0, "rowsOut": 5000, "bytesOut": 10},
        {"taskId": "cq_skewtest.0.1", "state": "FINISHED",
         "elapsedMs": 100.0, "rowsOut": 100, "bytesOut": 10},
        {"taskId": "cq_skewtest.0.2", "state": "FINISHED",
         "elapsedMs": 100.0, "rowsOut": 100, "bytesOut": 10},
    ]
    summary = mon.finalize(statuses)
    assert _counter("skewed_stage_total") == before + 1
    assert summary["skewed_stages"] and 0 in summary["skewed_stages"]
    assert summary["progress"][0] == 100.0
    # balanced stage: no flag, and finalize is idempotent per stage
    mon2 = StageMonitor("cq_noskew")
    balanced = [dict(s, taskId=f"cq_noskew.0.{i}", rowsOut=1000)
                for i, s in enumerate(statuses)]
    assert not mon2.finalize(balanced)["skewed_stages"]
    assert _counter("skewed_stage_total") == before + 1


def test_cluster_failed_query_lands_in_history(cluster):
    from presto_tpu.exec.cluster import QueryFailedError
    runner, _ = cluster
    sql = ("select sum(l_orderkey % (l_orderkey - l_orderkey)) "
           "from lineitem")
    with pytest.raises(QueryFailedError):
        runner.execute(sql)
    rec = next(r for r in reversed(HISTORY.snapshot())
               if r.get("query") == sql)
    assert rec["state"] == "FAILED" and rec["mode"] == "cluster"
    assert rec["error"]


# -- metric-name lint (CI wiring) --------------------------------------------

def test_check_metric_names_passes_on_source(capsys):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        import check_metric_names
    finally:
        sys.path.pop(0)
    assert check_metric_names.main(
        [os.path.join(repo, "presto_tpu")]) == 0


def test_check_metric_names_flags_bad_names(tmp_path, capsys):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        import check_metric_names
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "REGISTRY.counter('CamelCase_total').inc()\n"
        "REGISTRY.counter('no_unit_suffix').inc()\n"
        "REGISTRY.gauge('dup_total').set(1)\n"
        "REGISTRY.counter('dup_total').inc()\n")
    assert check_metric_names.main([str(tmp_path)]) == 1
    err = capsys.readouterr().err
    assert "snake_case" in err and "unit suffix" in err
    assert "dup_total" in err

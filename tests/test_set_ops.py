"""INTERSECT / EXCEPT (lowered to union-all + marker aggregation, the
reference's ImplementIntersectAsUnion.java / ImplementExceptAsUnion.java
rewrite)."""
import pytest


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.01)


@pytest.fixture(scope="module")
def dist(runner):
    from presto_tpu.exec.distributed import DistributedRunner
    return DistributedRunner(catalogs=runner.session.catalogs,
                             n_devices=8, rows_per_batch=1 << 12)


def test_intersect(runner):
    rows = runner.execute(
        "SELECT * FROM (VALUES 1,2,3,3) INTERSECT "
        "SELECT * FROM (VALUES 2,3,4) ORDER BY 1").rows
    assert rows == [(2,), (3,)]


def test_except(runner):
    rows = runner.execute(
        "SELECT * FROM (VALUES 1,2,3,3) EXCEPT "
        "SELECT * FROM (VALUES 2,4) ORDER BY 1").rows
    assert rows == [(1,), (3,)]


def test_except_multi_column(runner):
    rows = runner.execute(
        "SELECT * FROM (VALUES (1,'a'),(2,'b')) EXCEPT "
        "SELECT * FROM (VALUES (2,'b'),(3,'c'))").rows
    assert rows == [(1, "a")]


def test_intersect_null_equality(runner):
    # set-op semantics treat NULLs as equal (IS NOT DISTINCT), unlike =
    rows = runner.execute(
        "SELECT * FROM (VALUES 1, cast(null as integer)) INTERSECT "
        "SELECT * FROM (VALUES cast(null as integer), 2)").rows
    assert rows == [(None,)]


def test_intersect_binds_tighter_than_union(runner):
    rows = runner.execute(
        "SELECT * FROM (VALUES 1,2) UNION SELECT * FROM (VALUES 3,5) "
        "INTERSECT SELECT * FROM (VALUES 3) ORDER BY 1").rows
    assert rows == [(1,), (2,), (3,)]


def test_except_left_assoc_with_union(runner):
    # A UNION B EXCEPT C == (A UNION B) EXCEPT C
    rows = runner.execute(
        "SELECT * FROM (VALUES 1,2) UNION SELECT * FROM (VALUES 3) "
        "EXCEPT SELECT * FROM (VALUES 2) ORDER BY 1").rows
    assert rows == [(1,), (3,)]


def test_intersect_over_tpch(runner):
    got = runner.execute(
        "SELECT c_nationkey FROM customer INTERSECT "
        "SELECT s_nationkey FROM supplier ORDER BY 1").rows
    want = runner.execute(
        "SELECT DISTINCT c_nationkey FROM customer "
        "WHERE c_nationkey IN (SELECT s_nationkey FROM supplier) "
        "ORDER BY 1").rows
    assert got == want


def test_except_distributed(dist):
    rows = dist.execute(
        "SELECT * FROM (VALUES 1,2,3,3) EXCEPT "
        "SELECT * FROM (VALUES 2,4) ORDER BY 1").rows
    assert rows == [(1,), (3,)]


def test_intersect_all_rejected(runner):
    from presto_tpu.errors import QueryError
    from presto_tpu.sql.analyzer import AnalysisError
    with pytest.raises((AnalysisError, QueryError, NotImplementedError)):
        runner.execute("SELECT * FROM (VALUES 1) INTERSECT ALL "
                       "SELECT * FROM (VALUES 1)")

"""Red fixture: expr/params misuse inside jitted code (tracing checker).

The parameter-generic plan cache (serving/template.py) keeps literal
values OUT of compile keys; a kernel that reads a Param's build-time
value (``.bound``) or branches on its dispatch-scope traced value
un-does that — one binding's value bakes into (or specializes) the
executable every other binding shares.
"""
import jax
import jax.numpy as jnp

from presto_tpu.expr.params import consult, traced_val

SOME_PARAM = object()           # stands in for a captured ir.Param


@jax.jit
def bakes_build_time_value(xs):
    # param-bound-read: .bound is the value the TEMPLATE was built
    # against, not this query's binding
    return xs + SOME_PARAM.bound


@jax.jit
def consults_under_trace(xs):
    # param-bound-read: consult() is planner-only (records guards)
    return xs * consult(SOME_PARAM)


@jax.jit
def branches_on_dispatch_value(xs):
    v = traced_val(SOME_PARAM, 4)
    if v.data > 0:              # tracer-branch: traced_val is traced
        return xs
    return -xs


@jax.jit
def dispatch_scope_used_correctly(xs):
    # clean negative: the live binding flows as a traced operand into
    # data-parallel ops — no host decision, no build-time read
    v = traced_val(SOME_PARAM, 4)
    return jnp.where(v.data > 0, xs, -xs)

"""Red fixture: jax.jit entry points that bypass ops/jitcache."""
import functools

import jax


def make_kernel(scale):
    return jax.jit(lambda b: b * scale)       # raw-jit: bare call


@functools.partial(jax.jit, static_argnums=(1,))
def scaled(x, k):                             # raw-jit: partial decorator
    return x * k


def sync_without_span(x):
    y = jax.device_get(x)                     # unbracketed-sync
    x.block_until_ready()                     # unbracketed-sync
    return y


def sync_with_span(x, TRACER):
    with TRACER.span("device-sync", what="fixture"):
        return jax.device_get(x)              # properly bracketed — ok

"""Red fixture for the cache-contract checker (tools/analyze/caches.py).

A miniature cache module committing every protocol sin the checker
exists to catch — tests/test_analyze.py asserts each rule fires on this
file (with a synthetic CacheSpec) and NEVER on the live tree.
"""
import threading
from collections import OrderedDict


class BadCache:
    """Violates: plain lock, no version in key, no insert-time version
    recheck, no epoch veto, unbounded residency."""

    def __init__(self):
        self._entries = OrderedDict()
        self._epoch = 0
        # cache-plain-lock: invisible to the runtime lock validator
        self._lock = threading.Lock()

    @staticmethod
    def key(conn, catalog, table, columns, version, rows_per_batch=0):
        # cache-key-missing-version: `version` is a parameter but never
        # folded into the key — a connector write leaves stale entries
        # reachable under the same key
        return (id(conn), catalog, table, tuple(columns))

    def get(self, key):
        with self._lock:
            return self._entries.get(key)

    def put(self, key, value, epoch=None):
        # cache-missing-version-recheck: no data_version re-read under
        # the lock; cache-missing-epoch-veto: `epoch` accepted and
        # ignored; cache-unbounded: no pool reserve, no popitem cap
        with self._lock:
            self._entries[key] = value
            return True


def deps_of(plan, session):
    """Dep builder that stamps nothing (cache-missing-deps): entries
    record no data_version, so hits can never notice a write."""
    return [(None, "catalog", "table", 0)]


def cached_value(cache, stmt, session):
    # cache-epoch-after-deps: deps are snapshotted (build_plan) BEFORE
    # the write epoch is captured — a write landing between the two
    # stamps pre-write deps on a post-write epoch
    plan = build_plan(stmt, session)
    deps = deps_of(plan, session)
    epoch = cache.epoch()
    cache.put(("k",), (plan, deps), epoch=epoch)
    return plan


def build_plan(stmt, session):
    return ("plan", stmt)


# cache-missing-invalidation-hook: no spi.on_data_change registration
# anywhere in this module — connector writes are never seen eagerly.


def notify_data_change(conn, table):
    pass


class BadConnector:
    """connector-write-no-notify: versioned (defines data_version) but
    append/drop_table mutate without notifying; create_table is the
    clean negative (reaches notify through a helper chain)."""

    def __init__(self):
        self._version = 0
        self.tables = {}

    def data_version(self, table):
        return self._version

    def _bump(self, table):
        self._version += 1
        self._note(table)

    def _note(self, table):
        notify_data_change(self, table)

    def create_table(self, name, schema):
        self.tables[name] = []
        self._bump(name)                 # OK: two-hop helper chain

    def append(self, name, batch):
        self.tables[name].append(batch)  # BAD: silent write

    def drop_table(self, name):
        del self.tables[name]            # BAD: silent write

"""Red fixture: every trace-safety sin in one file. NEVER imported —
tests/test_analyze.py asserts tools/analyze/tracing.py flags each."""
import random
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def branch_on_tracer(x):
    if x > 0:                      # tracer-branch: host `if` on arg
        return x + 1
    return x - 1


def loop_on_tracer(x):
    total = x * 2                  # taint propagates through assignment
    while total < 10:              # tracer-branch: host `while`
        total = total + 1
    return total


loop_jit = jax.jit(loop_on_tracer)


@jax.jit
def concretize(x):
    return float(x) + x.item() + bool(x)   # tracer-branch x3


@jax.jit
def frozen_random(x):
    # nondeterminism: evaluated once at trace time, constant thereafter
    return x + time.time() + random.random() + np.random.rand()


@jax.jit
def static_uses_are_fine(x, flag):
    # none of these may be flagged: structure reads are static
    if x is None:
        return jnp.zeros(())
    if len(x.shape) > 1:
        return x.sum()
    if x.dtype == jnp.int32:
        return x * 2
    return x

"""Red fixture: registry-consistency violations (session props +
failpoint sites) for tools/analyze/registries.py."""


def read_props(session, bool_property, FAILPOINTS):
    a = session.properties.get("definitely_not_a_declared_prop", 1)
    b = bool_property(session, "another_undeclared_prop", True)
    FAILPOINTS.hit("not.a.registered.site")
    return a, b

"""Red fixture: undeclared environment-variable reads (registries
checker, env-var rules)."""
import os

# unknown-env-var: enforced prefixes, never declared in config.ENV_VARS
KNOB = os.environ.get("PRESTO_TPU_NOT_A_REAL_KNOB", "0")
TYPO = os.getenv("BENCH_TYPO_KNOB")
FORCED = os.environ["PRESTO_TPU_ALSO_UNDECLARED"]
os.environ.setdefault("BENCH_SETDEFAULT_UNDECLARED", "1")

# clean negatives: a declared engine var, and a foreign var outside
# the enforced prefixes
DECLARED = os.environ.get("PRESTO_TPU_LOCKCHECK")
FOREIGN = os.environ.get("SOME_OTHER_PROJECTS_VAR")

"""Red fixture: lock-discipline violations for tools/analyze/locks.py."""
import threading

SHARED = {}


class A:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def ab(self):
        with self._la:
            with self._lb:        # edge la -> lb
                pass

    def ba(self):
        with self._lb:
            with self._la:        # edge lb -> la: cycle
                pass

    def unlocked_write(self):
        SHARED["k"] = 1           # unlocked-global-write

    def locked_write_is_fine(self):
        with self._la:
            SHARED["k"] = 2


class Looper:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.wait(1.0):
            pass

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()          # unjoined-thread: no join anywhere


def fire_and_forget():
    threading.Thread(target=print, daemon=True).start()   # unjoined


def string_join_does_not_count(names):
    t = threading.Thread(target=print, daemon=True)       # unjoined:
    t.start()                                             # str.join on
    return ", ".join(names)                               # the next line
                                                          # must not mask it


def looped_join_counts(n):
    ts = [threading.Thread(target=print, daemon=True) for _ in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=1.0)                               # ok

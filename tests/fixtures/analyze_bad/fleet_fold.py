"""Red fixture for the distributed broadcast-fold clauses in
tools/analyze/caches.py (fleet_findings).

Every fold here violates the contract on purpose:

- ``fold_bump`` stores the dedupe high-water seq BEFORE the audited
  spi.notify_data_change call → fleet-fold-seq-order.
- ``fold_silent`` never reaches notify_data_change at all
  → fleet-fold-unaudited.
- ``_nudge`` pokes a cache's invalidate()/note_write() directly from
  the fleet module → fleet-fold-bypass (twice).
"""


class BadFleetMember:
    def __init__(self, spi, cache):
        self.spi = spi
        self.cache = cache
        self._seen = {}
        self._lock = None

    def fold_bump(self, doc):
        key = (doc["origin"], doc["connectorId"], doc["table"])
        seq = doc["seq"]
        if self._seen.get(key, -1) >= seq:
            return False
        # WRONG: delivery is recorded before the caches hear about
        # the write — a crash between these two lines loses the bump.
        self._seen[key] = seq
        conn = self.spi.catalogs.get(doc["connectorId"])
        self.spi.notify_data_change(conn, doc["table"])
        return True

    def fold_silent(self, doc):
        # WRONG: swallows the bump without the audited notify path.
        self._seen[(doc["origin"], doc["table"])] = doc["seq"]
        return True

    def _nudge(self, table):
        # WRONG: bypasses spi.notify_data_change entirely.
        self.cache.note_write(table)
        self.cache.invalidate(table)

"""Cluster memory manager: heartbeat memory payloads + biggest-query
kill under cluster-wide pressure (reference
memory/ClusterMemoryManager.java, TotalReservationLowMemoryKiller.java).
"""
import threading
import time

import pytest

# tier-1 budget: excluded from `pytest -m 'not slow'` — boots a multi-worker cluster per test
# (see tools/check_tier1_time.py; ~39s)
pytestmark = pytest.mark.slow

from presto_tpu.exec.cluster import (
    ClusterMemoryManager, ClusterRunner, QueryFailedError,
)
from presto_tpu.server.worker import WorkerServer

SF = 0.01


@pytest.fixture(scope="module")
def cluster():
    workers = [WorkerServer(tpch_sf=SF) for _ in range(2)]
    for w in workers:
        w.start()
    urls = [f"http://127.0.0.1:{w.port}" for w in workers]
    runner = ClusterRunner(urls, tpch_sf=SF, heartbeat=False)
    yield runner, workers
    for w in workers:
        w.stop()


def test_heartbeat_reports_query_memory(cluster):
    runner, workers = cluster
    seen = {}

    def snoop():
        for _ in range(400):
            for url in runner.worker_urls:
                try:
                    info = runner._request(f"{url}/v1/info")
                except Exception:
                    continue
                for q, b in info.get("queryMemory", {}).items():
                    seen[q] = max(seen.get(q, 0), b)
            time.sleep(0.01)

    t = threading.Thread(target=snoop, daemon=True)
    t.start()
    # under load (xdist peers) the snoop thread can get starved past a
    # single short query's lifetime — retry the query until a heartbeat
    # with live reservations was observed
    for _ in range(5):
        runner.execute(
            "select l_orderkey, count(*) c from lineitem "
            "group by 1 order by c desc limit 5")
        time.sleep(0.1)
        if max(list(seen.values()) or [0]) > 0:
            break
    # snapshot: the snoop thread keeps inserting while we assert
    peak = max(list(seen.values()) or [0])
    assert seen, "no queryMemory payload observed during execution"
    assert peak > 0


def test_kill_biggest_query_under_pressure(cluster):
    runner, workers = cluster
    # tiny cluster limit: the first poll that sees any reservation kills
    # the (single) running query
    mm = ClusterMemoryManager(runner, limit_bytes=1, interval_s=0.05)
    mm.start()
    try:
        with pytest.raises(QueryFailedError):
            for _ in range(20):   # retry loop: must die within budget
                runner.execute(
                    "select l_partkey, count(*), sum(l_extendedprice) "
                    "from lineitem group by 1")
    finally:
        mm.stop()
    assert mm.killed, "memory manager never killed a query"


def test_enforce_picks_largest(cluster):
    runner, _ = cluster
    mm = ClusterMemoryManager(runner, limit_bytes=100)
    mm.enforce({"cq_1": 60, "cq_2": 80})
    assert list(mm.killed) == ["cq_2"]
    # below the limit: no further kills
    mm.enforce({"cq_1": 60})
    assert list(mm.killed) == ["cq_2"]

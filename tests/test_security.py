"""Security (password auth + catalog access control) and query events
(reference server/security/, security/AccessControlManager.java,
eventlistener/EventListenerManager.java)."""
import base64
import json
import urllib.error
import urllib.request

import pytest

from presto_tpu.exec.runner import LocalRunner
from presto_tpu.server.security import (
    AccessControl, AccessDeniedError, PasswordAuthenticator,
)


def test_password_authenticator():
    auth = PasswordAuthenticator({"alice": "secret"})
    assert auth.authenticate("alice", "secret")
    assert not auth.authenticate("alice", "wrong")
    assert not auth.authenticate("bob", "secret")


def test_access_control_rules():
    ac = AccessControl({"catalogs": [
        {"user": "admin", "catalog": ".*", "allow": True},
        {"catalog": "system", "allow": False},
        {"allow": True}]})
    assert ac.can_access_catalog("admin", "system")
    assert not ac.can_access_catalog("jane", "system")
    assert ac.can_access_catalog("jane", "tpch")
    assert ac.filter_catalogs("jane", ["tpch", "system"]) == ["tpch"]


def test_runner_enforces_catalog_rules():
    r = LocalRunner(tpch_sf=0.001)
    r.access_control = AccessControl({"catalogs": [
        {"user": "admin", "allow": True},
        {"catalog": "tpch", "allow": True},
        {"allow": False}]})
    assert r.execute("select count(*) from nation",
                     user="jane").rows == [(25,)]
    with pytest.raises(AccessDeniedError):
        r.execute("select * from system.default.catalogs", user="jane")
    rows = r.execute("select * from system.default.catalogs",
                     user="admin").rows
    assert ("tpch",) in [tuple(x) for x in rows]
    # SHOW CATALOGS is filtered, not failed
    shown = [x[0] for x in r.execute("show catalogs", user="jane").rows]
    assert shown == ["tpch"]
    with pytest.raises(AccessDeniedError):
        r.execute("create table memory.default.t as select 1 a",
                  user="jane")


def test_ctas_insert_source_is_secured():
    """INSERT INTO allowed-catalog SELECT FROM denied-catalog must fail:
    the source query plans against the secured session too."""
    r = LocalRunner(tpch_sf=0.001)
    r.access_control = AccessControl({"catalogs": [
        {"catalog": "memory", "allow": True},
        {"allow": False}]})
    with pytest.raises(AccessDeniedError):
        r.execute("create table memory.default.steal as "
                  "select * from tpch.default.nation", user="bob")


def test_per_user_transactions():
    """One user's BEGIN must not scope (or roll back) another user's
    autocommit writes."""
    r = LocalRunner(tpch_sf=0.001)
    r.execute("start transaction", user="alice")
    r.execute("create table memory.default.bobt as select 1 a",
              user="bob")
    r.execute("rollback", user="alice")
    assert r.execute("select count(*) from memory.default.bobt",
                     user="bob").rows == [(1,)]


def test_server_basic_auth():
    from presto_tpu.server.protocol import PrestoTpuServer
    srv = PrestoTpuServer(
        runner=LocalRunner(tpch_sf=0.001),
        authenticator=PasswordAuthenticator({"alice": "pw"}))
    srv.start()
    url = f"http://127.0.0.1:{srv.port}/v1/statement"
    req = urllib.request.Request(url, data=b"select 1", method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=10)
    assert e.value.code == 401
    assert "Basic" in e.value.headers.get("WWW-Authenticate", "")
    cred = base64.b64encode(b"alice:pw").decode()
    req = urllib.request.Request(url, data=b"select 1", method="POST",
                                 headers={"Authorization":
                                          f"Basic {cred}"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        doc = json.loads(resp.read())
    # authenticated statements serve: either the classic paging doc or
    # the single-round-trip inline page (fast statements)
    assert "nextUri" in doc or doc.get("data") == [[1]]
    # every endpoint is guarded, not just POST
    with pytest.raises(urllib.error.HTTPError) as e2:
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/resourceGroup", timeout=10)
    assert e2.value.code == 401
    srv.stop()


def test_query_completed_events():
    r = LocalRunner(tpch_sf=0.001)
    seen = []
    r.events.register(seen.append)
    r.execute("select count(*) from region", user="jane")
    with pytest.raises(Exception):
        r.execute("select nope from region")
    assert len(seen) == 2
    ok, bad = seen
    assert ok.state == "FINISHED" and ok.user == "jane"
    assert ok.elapsed_ms > 0 and "region" in ok.query
    assert bad.state == "FAILED" and bad.error


def test_broken_listener_does_not_break_queries():
    r = LocalRunner(tpch_sf=0.001)
    r.events.register(lambda e: 1 / 0)
    assert r.execute("select 1").rows == [(1,)]


# -- roles, grants, JWT (reference spi/security/RoleGrant + GrantInfo,
# -- server/security/jwt JsonWebTokenAuthenticator) --------------------------


def test_roles_and_grants_sql_surface():
    r = LocalRunner(tpch_sf=0.001)
    r.execute("create role analyst")
    assert ("analyst",) in r.execute("show roles").rows
    r.execute("grant analyst to user alice")
    r.execute("grant select on nation to analyst")
    grants = r.execute("show grants on nation").rows
    assert ("analyst", "tpch", "nation", "SELECT") in grants
    r.execute("revoke select on nation from analyst")
    assert r.execute("show grants on nation").rows == []
    r.execute("drop role analyst")
    assert ("analyst",) not in r.execute("show roles").rows


def test_table_privilege_enforcement():
    """With enforcement on, SELECT needs a grant (direct or via role);
    admin bypasses; management statements are admin-gated."""
    from presto_tpu.server.security import AccessDeniedError
    r = LocalRunner(tpch_sf=0.001)
    r.roles.enforce = True
    r.roles.user_roles["boss"] = {"admin"}
    # admin can read anything and manage roles
    assert r.execute("select count(*) from region", user="boss").rows
    r.execute("create role readers", user="boss")
    r.execute("grant readers to user carol", user="boss")
    r.execute("grant select on region to readers", user="boss")
    # carol reads through the role; region only
    assert r.execute("select count(*) from region", user="carol").rows
    with pytest.raises(AccessDeniedError):
        r.execute("select count(*) from nation", user="carol")
    # non-admins cannot manage
    with pytest.raises(AccessDeniedError):
        r.execute("create role hackers", user="carol")
    # write path needs INSERT
    with pytest.raises(AccessDeniedError):
        r.execute("create table memory.default.t1 as "
                  "select * from region", user="carol")
    r.execute("grant insert on memory.default.t1 to carol",
              user="boss")
    r.execute("grant select on region to carol", user="boss")
    r.execute("create table memory.t1 as select * from region",
              user="carol")


def test_jwt_authenticator_unit():
    import time
    from presto_tpu.server.security import JwtAuthenticator
    tok = JwtAuthenticator.issue("s3cret", "dave",
                                 exp=time.time() + 60)
    auth = JwtAuthenticator("s3cret")
    assert auth.authenticate(tok) == "dave"
    assert auth.authenticate(tok + "x") is None
    assert JwtAuthenticator("other").authenticate(tok) is None
    expired = JwtAuthenticator.issue("s3cret", "dave",
                                     exp=time.time() - 1)
    assert auth.authenticate(expired) is None
    aud = JwtAuthenticator.issue("s3cret", "dave", aud="presto")
    assert JwtAuthenticator("s3cret", "presto").authenticate(aud) == "dave"
    assert JwtAuthenticator("s3cret", "nope").authenticate(aud) is None


def test_jwt_bearer_against_statement_server():
    """End-to-end: the statement server accepts Bearer tokens and runs
    the query as the token's subject; bad tokens get 401."""
    import json
    import time
    import urllib.error
    import urllib.request

    from presto_tpu.server.protocol import StatementServer
    from presto_tpu.server.security import JwtAuthenticator

    srv = StatementServer(LocalRunner(tpch_sf=0.001),
                          jwt_authenticator=JwtAuthenticator("k3y"))
    srv.start()
    try:
        tok = JwtAuthenticator.issue("k3y", "erin",
                                     exp=time.time() + 60)
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/statement",
            data=b"select 42",
            headers={"Authorization": f"Bearer {tok}"})
        doc = json.loads(urllib.request.urlopen(req).read())
        while "data" not in doc and "nextUri" in doc:
            nxt = urllib.request.Request(
                doc["nextUri"],
                headers={"Authorization": f"Bearer {tok}"})
            doc = json.loads(urllib.request.urlopen(nxt).read())
        assert doc["data"] == [[42]]
        bad = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/statement",
            data=b"select 1",
            headers={"Authorization": "Bearer nope"})
        try:
            urllib.request.urlopen(bad)
            assert False, "expected 401"
        except urllib.error.HTTPError as e:
            assert e.code == 401
    finally:
        srv.stop()

"""Security (password auth + catalog access control) and query events
(reference server/security/, security/AccessControlManager.java,
eventlistener/EventListenerManager.java)."""
import base64
import json
import urllib.error
import urllib.request

import pytest

from presto_tpu.exec.runner import LocalRunner
from presto_tpu.server.security import (
    AccessControl, AccessDeniedError, PasswordAuthenticator,
)


def test_password_authenticator():
    auth = PasswordAuthenticator({"alice": "secret"})
    assert auth.authenticate("alice", "secret")
    assert not auth.authenticate("alice", "wrong")
    assert not auth.authenticate("bob", "secret")


def test_access_control_rules():
    ac = AccessControl({"catalogs": [
        {"user": "admin", "catalog": ".*", "allow": True},
        {"catalog": "system", "allow": False},
        {"allow": True}]})
    assert ac.can_access_catalog("admin", "system")
    assert not ac.can_access_catalog("jane", "system")
    assert ac.can_access_catalog("jane", "tpch")
    assert ac.filter_catalogs("jane", ["tpch", "system"]) == ["tpch"]


def test_runner_enforces_catalog_rules():
    r = LocalRunner(tpch_sf=0.001)
    r.access_control = AccessControl({"catalogs": [
        {"user": "admin", "allow": True},
        {"catalog": "tpch", "allow": True},
        {"allow": False}]})
    assert r.execute("select count(*) from nation",
                     user="jane").rows == [(25,)]
    with pytest.raises(AccessDeniedError):
        r.execute("select * from system.default.catalogs", user="jane")
    rows = r.execute("select * from system.default.catalogs",
                     user="admin").rows
    assert ("tpch",) in [tuple(x) for x in rows]
    # SHOW CATALOGS is filtered, not failed
    shown = [x[0] for x in r.execute("show catalogs", user="jane").rows]
    assert shown == ["tpch"]
    with pytest.raises(AccessDeniedError):
        r.execute("create table memory.default.t as select 1 a",
                  user="jane")


def test_ctas_insert_source_is_secured():
    """INSERT INTO allowed-catalog SELECT FROM denied-catalog must fail:
    the source query plans against the secured session too."""
    r = LocalRunner(tpch_sf=0.001)
    r.access_control = AccessControl({"catalogs": [
        {"catalog": "memory", "allow": True},
        {"allow": False}]})
    with pytest.raises(AccessDeniedError):
        r.execute("create table memory.default.steal as "
                  "select * from tpch.default.nation", user="bob")


def test_per_user_transactions():
    """One user's BEGIN must not scope (or roll back) another user's
    autocommit writes."""
    r = LocalRunner(tpch_sf=0.001)
    r.execute("start transaction", user="alice")
    r.execute("create table memory.default.bobt as select 1 a",
              user="bob")
    r.execute("rollback", user="alice")
    assert r.execute("select count(*) from memory.default.bobt",
                     user="bob").rows == [(1,)]


def test_server_basic_auth():
    from presto_tpu.server.protocol import PrestoTpuServer
    srv = PrestoTpuServer(
        runner=LocalRunner(tpch_sf=0.001),
        authenticator=PasswordAuthenticator({"alice": "pw"}))
    srv.start()
    url = f"http://127.0.0.1:{srv.port}/v1/statement"
    req = urllib.request.Request(url, data=b"select 1", method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=10)
    assert e.value.code == 401
    assert "Basic" in e.value.headers.get("WWW-Authenticate", "")
    cred = base64.b64encode(b"alice:pw").decode()
    req = urllib.request.Request(url, data=b"select 1", method="POST",
                                 headers={"Authorization":
                                          f"Basic {cred}"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        doc = json.loads(resp.read())
    assert "nextUri" in doc
    # every endpoint is guarded, not just POST
    with pytest.raises(urllib.error.HTTPError) as e2:
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/resourceGroup", timeout=10)
    assert e2.value.code == 401
    srv.stop()


def test_query_completed_events():
    r = LocalRunner(tpch_sf=0.001)
    seen = []
    r.events.register(seen.append)
    r.execute("select count(*) from region", user="jane")
    with pytest.raises(Exception):
        r.execute("select nope from region")
    assert len(seen) == 2
    ok, bad = seen
    assert ok.state == "FINISHED" and ok.user == "jane"
    assert ok.elapsed_ms > 0 and "region" in ok.query
    assert bad.state == "FAILED" and bad.error


def test_broken_listener_does_not_break_queries():
    r = LocalRunner(tpch_sf=0.001)
    r.events.register(lambda e: 1 / 0)
    assert r.execute("select 1").rows == [(1,)]

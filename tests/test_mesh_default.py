"""Mesh-native execution by default (PR 12): auto-selection + parity.

The tentpole contract: with more than one device visible, the RUNNER
entry points (LocalRunner.execute / ClusterRunner.execute — never a
direct DistributedExecutor call) place SQL on the SPMD mesh by default
(`mesh_execution=auto`), with row-exact parity against the
single-device path and `mesh_execution=off` as the escape hatch. The
harness pins the environment default off (tests/conftest.py) so only
these suites pay shard_map compiles; every test here opts back in per
query through the session-property overlay, which is exactly the
production surface.
"""
import os
import subprocess
import sys

import jax
import pytest

from presto_tpu.exec.runner import LocalRunner
from presto_tpu.obs.metrics import REGISTRY

SF = 0.005
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

AUTO = {"mesh_execution": "auto"}
OFF = {"mesh_execution": "off"}

#: the parity sweep shapes: joins, grouped aggs, top-n, semi joins,
#: NULL-heavy inputs (outer-join NULL extension + NULL-aware anti join)
SWEEP = [
    ("grouped-agg", "select o_orderstatus, count(*), sum(o_totalprice) "
                    "from orders group by 1 order by 1"),
    ("join-agg-topn", "select c_name, sum(o_totalprice) from customer "
                      "join orders on c_custkey = o_custkey "
                      "group by 1 order by 2 desc, 1 limit 3"),
    ("semi", "select count(*) from orders where o_custkey in "
             "(select c_custkey from customer where c_acctbal > 0)"),
    ("null-left-join", "select s_name, n_name from supplier left join "
                       "nation on s_nationkey = n_nationkey "
                       "and n_regionkey < 2 order by 1, 2 limit 8"),
    ("null-anti", "select count(*) from orders where o_custkey not in "
                  "(select case when c_acctbal < 0 then null "
                  "else c_custkey end from customer)"),
    ("distinct", "select distinct c_mktsegment from customer "
                 "order by 1"),
]


def _metric(name: str) -> float:
    return REGISTRY.value(name)


@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=SF, rows_per_batch=1 << 11)


def _norm(rows):
    out = []
    for r in rows:
        out.append(tuple(v.item() if hasattr(v, "item") else v
                         for v in r))
    return out


def _check_parity(want, got, ordered):
    w, g = _norm(want.rows), _norm(got.rows)
    if not ordered:
        w, g = sorted(w, key=repr), sorted(g, key=repr)
    assert len(g) == len(w)
    for gr, wr in zip(g, w):
        for gv, wv in zip(gr, wr):
            if isinstance(gv, float):
                assert gv == pytest.approx(wv, rel=1e-6, abs=1e-9)
            else:
                assert gv == wv, (gr, wr)


def _parity(runner, sql, props_on, extra=None):
    props_off = {**OFF, **(extra or {})}
    props_on = {**props_on, **(extra or {})}
    want = runner.execute(sql, properties=props_off)
    got = runner.execute(sql, properties=props_on)
    _check_parity(want, got, "order by" in sql.lower())
    return got


def test_auto_selects_mesh_and_matches(runner):
    """The default: >1 device -> SQL lands on the mesh (observable as
    mesh_path_selected_total) with rows matching the local path."""
    before = _metric("mesh_path_selected_total")
    _parity(runner, SWEEP[0][1], {**AUTO, "mesh_devices": 2})
    assert _metric("mesh_path_selected_total") == before + 1


def test_off_escape_hatch_stays_local(runner):
    before = _metric("mesh_path_selected_total")
    res = runner.execute(SWEEP[0][1], properties=dict(OFF))
    assert res.rows
    assert _metric("mesh_path_selected_total") == before


def test_mesh_devices_one_stays_local(runner):
    """mesh_devices=1 under auto means a 1-chip 'mesh' — the router
    keeps the plain single-device executor."""
    before = _metric("mesh_path_selected_total")
    res = runner.execute(SWEEP[0][1],
                         properties={**AUTO, "mesh_devices": 1})
    assert res.rows
    assert _metric("mesh_path_selected_total") == before


@pytest.mark.parametrize("name,sql", SWEEP[1:3],
                         ids=[t[0] for t in SWEEP[1:3]])
def test_parity_n2(runner, name, sql):
    _parity(runner, sql, {**AUTO, "mesh_devices": 2})


@pytest.mark.slow
@pytest.mark.parametrize("n", [1, 2, 8])
@pytest.mark.parametrize("name,sql", SWEEP, ids=[t[0] for t in SWEEP])
def test_parity_sweep(runner, n, name, sql):
    """The full sweep: every shape at n_devices in {1, 2, 8} through
    the runner entry point. n=1 exercises the router's 1-chip
    degenerate (local path); n>1 the real SPMD substrate."""
    _parity(runner, sql, {**AUTO, "mesh_devices": n})


def test_system_catalog_stays_local(runner):
    """Metadata queries gain nothing from SPMD: auto never meshes
    them."""
    before = _metric("mesh_path_selected_total")
    res = runner.execute(
        "select name from system.runtime.metrics limit 1",
        properties=dict(AUTO))
    assert res.rows is not None
    assert _metric("mesh_path_selected_total") == before


def test_mesh_stays_device_resident(runner, monkeypatch):
    """Transfer guard: a warm mesh query's intermediates never
    round-trip the host. Two teeth: the host staging fallback
    (_stage_parts) must not run — warm scans replay device-resident
    out of the scan cache and compose shards device-to-device — and
    the bytes fetched via jax.device_get stay at control-scalar scale
    (exchange quotas, error flags, result rows), independent of table
    size."""
    from presto_tpu.exec.distributed import DistributedExecutor
    sql = SWEEP[0][1]
    props = {**AUTO, "mesh_devices": 2}
    runner.execute(sql, properties=props)       # cold: compile + cache

    def no_host_staging(self, *a, **k):
        raise AssertionError("mesh scan staged through the host")

    monkeypatch.setattr(DistributedExecutor, "_stage_parts",
                        no_host_staging)
    fetched = []
    real = jax.device_get

    def counting(x):
        out = real(x)
        import numpy as np
        for leaf in jax.tree_util.tree_leaves(out):
            try:
                fetched.append(int(np.asarray(leaf).nbytes))
            except Exception:
                pass
        return out

    monkeypatch.setattr(jax, "device_get", counting)
    got = runner.execute(sql, properties=props)
    assert got.rows
    assert sum(fetched) < 64 * 1024, sum(fetched)


def test_scan_cache_serves_mesh(runner):
    """PR 4's device scan cache backs the mesh scan: a repeated mesh
    query replays decoded splits instead of re-decoding."""
    sql = "select count(*), sum(c_acctbal) from customer"
    props = {**AUTO, "mesh_devices": 2}
    runner.execute(sql, properties=props)
    before = _metric("scan_cache_hit_total")
    runner.execute(sql, properties=props)
    assert _metric("scan_cache_hit_total") > before


def test_adaptive_resplit_keeps_parity(runner, monkeypatch):
    """StageMonitor's skew verdict in action: with the threshold forced
    low, a partitioned join re-splits hot buckets mid-query (metric
    fires) and rows stay exact — the build side re-ships under the new
    assignment before the next probe batch."""
    from presto_tpu.exec import distributed as D
    monkeypatch.setattr(D, "_skew_ratio", lambda: 1.01)
    sql = ("select c_name, sum(o_totalprice) from customer join orders "
           "on c_custkey = o_custkey group by 1 order by 2 desc, 1 "
           "limit 5")
    before = _metric("mesh_repartition_resplit_total")
    _parity(runner, sql, {**AUTO, "mesh_devices": 2},
            extra={"broadcast_join_row_limit": 1})
    assert _metric("mesh_repartition_resplit_total") > before


def test_partition_map_rebalance_unit():
    """The greedy re-balancer itself: a hot bucket moves to the idle
    shard; a single hot KEY (one bucket) cannot improve and never
    flips; changes cap at MAX_CHANGES."""
    import numpy as np

    from presto_tpu.exec.distributed import _PartitionMap
    pm = _PartitionMap(2, ratio=1.5)
    counts = np.zeros((2, pm.buckets), dtype=np.int64)
    # buckets 0 and 2 both map to shard 0 initially (b % n): pile rows
    # on them so shard 0 holds ~all rows, then expect a re-split
    counts[0, 0] = 1000
    counts[0, 2] = 900
    counts[0, 1] = 10
    pm.observe(counts)
    assert pm.epoch == 1
    loads = [0, 0]
    for b, d in enumerate(pm.assign):
        loads[d] += int(pm._totals[b])
    assert max(loads) < 1900        # the two hot buckets split shards

    one_key = _PartitionMap(2, ratio=1.5)
    hot = np.zeros((2, one_key.buckets), dtype=np.int64)
    hot[0, 0] = 10_000              # one hot bucket: nothing to split
    one_key.observe(hot)
    assert one_key.epoch == 0

    capped = _PartitionMap(2, ratio=1.01)
    capped.changes = capped.MAX_CHANGES
    capped.observe(counts)
    assert capped.epoch == 0


def test_cluster_workerless_rides_mesh(runner):
    """A worker-less multi-chip ClusterRunner executes on the mesh
    (auto) instead of failing with no schedulable nodes."""
    from presto_tpu.exec.cluster import ClusterRunner
    cr = ClusterRunner(worker_urls=[], catalogs=runner.session.catalogs,
                       heartbeat=False)
    before = _metric("mesh_path_selected_total")
    got = cr.execute("select count(*) from nation",
                     properties={**AUTO, "mesh_devices": 2})
    assert _norm(got.rows) == [(25,)]
    assert _metric("mesh_path_selected_total") == before + 1


def test_distributed_runner_surface(runner):
    """DistributedRunner.execute surface parity: properties validate
    through the registry, user lands in the history record, a pre-set
    cancel event interrupts."""
    import threading

    from presto_tpu.config import SessionPropertyError
    from presto_tpu.errors import QueryCancelledError
    from presto_tpu.exec.distributed import DistributedRunner
    from presto_tpu.obs.history import HISTORY
    dr = DistributedRunner(catalogs=runner.session.catalogs,
                           n_devices=2, rows_per_batch=1 << 11)
    res = dr.execute("select count(*) from nation",
                     properties={"dense_grouping": True}, user="audit")
    assert _norm(res.rows) == [(25,)]
    rec = [h for h in HISTORY.snapshot() if h.get("mode") == "spmd"][-1]
    assert rec["user"] == "audit"
    with pytest.raises(SessionPropertyError):
        dr.execute("select count(*) from nation",
                   properties={"not_a_property": 1})
    ev = threading.Event()
    ev.set()
    with pytest.raises(QueryCancelledError):
        dr.execute("select count(*) from region", cancel_event=ev)


def test_mesh_execution_property_validates():
    from presto_tpu.config import (SessionPropertyError,
                                   validate_session_property)
    assert validate_session_property("mesh_execution", "AUTO") == "auto"
    assert validate_session_property("mesh_devices", "4") == 4
    with pytest.raises(SessionPropertyError):
        validate_session_property("mesh_execution", "sideways")


def test_mesh_stages_recipe():
    """The fragmenter's mesh-stage pass: a join+agg plan cuts into
    scan-shard / hash / single stages with the exchanges named."""
    from presto_tpu.planner.fragmenter import plan_mesh_stages
    r = LocalRunner(tpch_sf=0.001)
    plan = r.plan("select c_name, count(*) from customer join orders "
                  "on c_custkey = o_custkey group by 1")
    mp = plan_mesh_stages(plan.root)
    assert mp.supported
    kinds = [s.kind for s in mp.stages]
    assert kinds[-1] == "single"
    assert "scan-shard" in kinds
    exchanges = {s.exchange for s in mp.stages}
    assert "partition" in exchanges or "broadcast" in exchanges
    # partition exchanges feeding an agg/join consumer are marked as
    # fused into the consumer's shard_map program; everything else is
    # not (the root stage in particular has no exchange to fuse)
    for s in mp.stages:
        if s.fused:
            assert s.exchange == "partition"
    if "partition" in exchanges:
        assert any(s.fused for s in mp.stages)


def test_per_chip_billing(runner):
    """A mesh quantum bills every chip it occupies: the chip-quanta
    counter advances by the mesh width per quantum, and group device
    seconds grow accordingly (PR 8 tenants share the mesh fairly)."""
    before = _metric("scheduler_chip_quanta_total")
    bq = _metric("scheduler_quanta_total")
    runner.execute(SWEEP[0][1], properties={**AUTO, "mesh_devices": 2})
    dq = _metric("scheduler_quanta_total") - bq
    dchip = _metric("scheduler_chip_quanta_total") - before
    assert dq > 0 and dchip == 2 * dq


def test_multichip_gate_smoke():
    """check_bench_regression --kind multichip --smoke: the committed
    MULTICHIP_r*.json pin parses, passes against itself, and a
    degraded copy fails — the tier-1 guard that the mesh-scaling gate
    cannot rot."""
    out = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "tools", "check_bench_regression.py"),
         "--kind", "multichip", "--smoke"],
        capture_output=True, text=True, cwd=_REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert '"verdict": "pass"' in out.stdout

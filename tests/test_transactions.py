"""Transactions over the memory catalog (reference
transaction/InMemoryTransactionManager.java)."""
import pytest

from presto_tpu.exec.runner import LocalRunner
from presto_tpu.transaction import TransactionError


@pytest.fixture()
def runner():
    return LocalRunner(tpch_sf=0.001)


def _count(runner, table):
    return runner.execute(
        f"select count(*) from memory.default.{table}").rows[0][0]


def test_commit_keeps_writes(runner):
    runner.execute("start transaction")
    runner.execute("create table memory.default.t as "
                   "select n_nationkey k from nation")
    runner.execute("insert into memory.default.t "
                   "select r_regionkey from region")
    assert _count(runner, "t") == 30      # read-your-writes inside tx
    runner.execute("commit")
    assert _count(runner, "t") == 30


def test_rollback_restores_snapshot(runner):
    runner.execute("create table memory.default.base as "
                   "select r_regionkey k from region")
    runner.execute("start transaction")
    runner.execute("insert into memory.default.base "
                   "select n_nationkey from nation")
    runner.execute("create table memory.default.scratch as "
                   "select 1 x")
    assert _count(runner, "base") == 30
    runner.execute("rollback")
    assert _count(runner, "base") == 5    # insert undone
    with pytest.raises(Exception):
        _count(runner, "scratch")         # create undone


def test_drop_rolled_back(runner):
    runner.execute("create table memory.default.keep as select 1 x")
    runner.execute("start transaction")
    runner.execute("drop table memory.default.keep")
    runner.execute("rollback")
    assert _count(runner, "keep") == 1


def test_read_only_rejects_writes(runner):
    runner.execute("start transaction read only")
    with pytest.raises(TransactionError, match="read-only"):
        runner.execute("create table memory.default.x as select 1 a")
    runner.execute("rollback")


def test_isolation_level_parses(runner):
    res = runner.execute(
        "start transaction isolation level serializable, read write")
    assert res.rows[0][0].startswith("tx_")
    runner.execute("commit")
    runner.execute("start transaction isolation level repeatable read")
    runner.execute("commit")


def test_nested_begin_rejected(runner):
    runner.execute("start transaction")
    with pytest.raises(TransactionError, match="already in progress"):
        runner.execute("start transaction")
    runner.execute("rollback")


def test_commit_without_tx_rejected(runner):
    with pytest.raises(TransactionError, match="no transaction"):
        runner.execute("commit")


def test_autocommit_unaffected(runner):
    runner.execute("create table memory.default.ac as select 1 a")
    assert _count(runner, "ac") == 1

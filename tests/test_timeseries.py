"""Time-series health plane: the windowed metrics sampler
(obs/timeseries.py) — counter rates, windowed histogram quantiles,
retention rings, the range/reduce API, the /v1/metrics/history doc,
the system.runtime.timeseries table feed, and the sampler's own
overhead budget.

Everything runs against a private MetricsRegistry + TimeSeriesStore
with synthetic ``now`` values, so windows are deterministic — no
sleeps, no wall-clock flake.
"""
import threading

import pytest

from presto_tpu.obs.metrics import MetricsRegistry
from presto_tpu.obs.timeseries import (
    DEFAULT_RETENTION_POINTS, DEFAULT_SAMPLE_INTERVAL_S,
    TimeSeriesStore, _per_bucket, _window_pair,
)


def _store(retention: int = 64, interval: float = 1.0):
    reg = MetricsRegistry()
    ts = TimeSeriesStore(registry=reg)
    ts.configure(sample_interval_s=interval, retention_points=retention)
    return reg, ts


# -- primitives ---------------------------------------------------------------

def test_per_bucket_from_cumulative():
    assert _per_bucket((0, 3, 3, 10)) == [0, 3, 0, 7]
    assert _per_bucket(()) == []


def test_window_pair_needs_two_distinct_samples():
    assert _window_pair([], 60.0, 100.0) is None
    assert _window_pair([(99.0, 1.0)], 60.0, 100.0) is None
    # two points inside the window: earliest is the baseline
    base, end = _window_pair([(50.0, 1.0), (99.0, 5.0)], 60.0, 100.0)
    assert base == (50.0, 1.0) and end == (99.0, 5.0)
    # a point at/before now-window becomes the baseline instead
    base, end = _window_pair(
        [(30.0, 1.0), (50.0, 2.0), (99.0, 5.0)], 50.0, 100.0)
    assert base == (50.0, 2.0) and end == (99.0, 5.0)


# -- counters + gauges --------------------------------------------------------

def test_counter_windowed_rate():
    reg, ts = _store()
    c = reg.counter("req_total")
    for i in range(11):
        c.inc(6)                      # 6/sample at 1s spacing
        ts.sample(now=100.0 + i)
    assert ts.rate("req_total", 10.0, now=110.0) == pytest.approx(6.0)
    # the range API agrees with the dedicated accessor
    assert ts.range("req_total", 10.0, reduce="rate",
                    now=110.0) == pytest.approx(6.0)
    # outside any data: None, not garbage
    assert ts.rate("req_total", 10.0, now=500.0) is None
    assert ts.rate("nope_total", 10.0, now=110.0) is None


def test_gauge_reducers_and_unknown_reducer():
    reg, ts = _store()
    g = reg.gauge("depth")
    for i, v in enumerate((1.0, 5.0, 3.0)):
        g.set(v)
        ts.sample(now=100.0 + i)
    assert ts.range("depth", 60.0, reduce="max", now=102.0) == 5.0
    assert ts.range("depth", 60.0, reduce="avg",
                    now=102.0) == pytest.approx(3.0)
    assert ts.range("depth", 60.0, reduce="sum",
                    now=102.0) == pytest.approx(9.0)
    with pytest.raises(ValueError):
        ts.range("depth", 60.0, reduce="median", now=102.0)


def test_registry_reset_mid_run_yields_none_not_negative():
    reg, ts = _store()
    c = reg.counter("req_total")
    c.inc(100)
    ts.sample(now=100.0)
    ts.sample(now=101.0)
    reg.reset()                       # counter back to 0 in place
    reg.counter("req_total").inc(1)
    ts.sample(now=102.0)
    # the window spanning the reset has a negative delta — reported as
    # "unknown", never as a negative rate
    assert ts.rate("req_total", 10.0, now=102.0) is None


# -- windowed histogram quantiles ---------------------------------------------

def test_windowed_quantile_diverges_from_lifetime():
    """A latency spike AFTER a long quiet history: the lifetime p95
    still reads fast, the 5m-windowed p95 reads the spike — the whole
    reason the plane exists."""
    reg, ts = _store()
    h = reg.histogram("lat_seconds")
    for _ in range(10_000):
        h.observe(0.01)               # long fast history
    ts.sample(now=100.0)
    for _ in range(100):
        h.observe(1.0)                # recent spike (1% of lifetime)
    ts.sample(now=160.0)
    lifetime_p95 = h.quantile(0.95)
    windowed = ts.window_quantile("lat_seconds", 120.0, 0.95,
                                  now=160.0)
    assert lifetime_p95 == pytest.approx(0.01, abs=0.01)
    assert windowed is not None and windowed > 0.5
    # window with only the quiet prefix: no second sample, None
    assert ts.window_quantile("lat_seconds", 120.0, 0.95,
                              now=100.0) is None


def test_window_counts_are_cumulative_deltas():
    reg, ts = _store()
    h = reg.histogram("q_seconds")
    h.observe(0.01)
    ts.sample(now=10.0)
    for _ in range(3):
        h.observe(0.01)
    h.observe(50.0)
    ts.sample(now=20.0)
    dc, dsum, cum, bounds = ts.window_counts("q_seconds", 60.0,
                                             now=20.0)
    assert dc == 4
    assert dsum == pytest.approx(3 * 0.01 + 50.0)
    # cumulative within the window: every 0.01 obs is ≤ every bound,
    # the 50s obs only lands at/above the 60s bound
    assert cum[bounds.index(0.025)] == 3
    assert cum[bounds.index(60.0)] == 4
    assert list(cum) == sorted(cum)


def test_quantile_rows_for_metrics_table():
    reg, ts = _store()
    h = reg.histogram("query_seconds")
    for _ in range(10):
        h.observe(0.2)
    ts.sample(now=100.0)
    for _ in range(90):
        h.observe(0.2)
    ts.sample(now=200.0)
    rows = dict(ts.window_quantile_rows(window=300.0, now=200.0))
    for tag in ("p50_5m", "p95_5m", "p99_5m"):
        assert f"query_seconds.{tag}" in rows
        assert rows[f"query_seconds.{tag}"] == pytest.approx(0.2,
                                                             abs=0.15)


# -- retention ----------------------------------------------------------------

def test_retention_ring_is_bounded():
    reg, ts = _store(retention=16)
    g = reg.gauge("depth")
    for i in range(10_000):           # a long run: 625x the ring
        g.set(float(i))
        ts.sample(now=float(i))
    pts = ts.points("depth")
    assert len(pts) == 16
    assert pts[-1] == (9999.0, 9999.0)
    assert pts[0][0] == 9984.0        # oldest retained, not oldest ever


def test_configure_shrinks_existing_rings():
    reg, ts = _store(retention=32)
    g = reg.gauge("depth")
    for i in range(32):
        g.set(float(i))
        ts.sample(now=float(i))
    ts.configure(retention_points=4)
    assert ts.retention_points == 4
    assert len(ts.points("depth")) == 4
    assert ts.points("depth")[-1][1] == 31.0


# -- federated points + sampler lifecycle -------------------------------------

def test_record_federated_point():
    _, ts = _store()
    ts.record("node_active_tasks.w1", 3.0, now=50.0)
    ts.record("node_active_tasks.w1", 5.0, now=51.0)
    assert ts.kind("node_active_tasks.w1") == "gauge"
    assert ts.range("node_active_tasks.w1", 60.0, reduce="max",
                    now=51.0) == 5.0


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_TIMESERIES", "off")
    reg = MetricsRegistry()
    ts = TimeSeriesStore(registry=reg)
    assert ts.ensure_started() is False
    ts.stop()


def test_sampler_thread_runs_and_stops():
    reg, ts = _store(interval=0.05)
    reg.counter("beat_total").inc()
    assert ts.ensure_started() is True
    assert ts.ensure_started() is True    # idempotent
    deadline = threading.Event()
    for _ in range(100):
        if len(ts.points("beat_total")) >= 2:
            break
        deadline.wait(0.05)
    ts.stop()
    assert len(ts.points("beat_total")) >= 2
    # the sampler meters itself on the sampled registry
    assert reg.counter("timeseries_samples_total").value >= 2


def test_sampler_overhead_under_one_percent():
    """The plane must be free: average sample() cost over a registry
    of realistic size stays under 1% of the default 5s cadence."""
    import time as _time

    reg, ts = _store()
    for i in range(40):
        reg.counter(f"c{i}_total").inc(i)
        reg.gauge(f"g{i}_bytes").set(i)
    for i in range(20):
        h = reg.histogram(f"h{i}_seconds")
        for j in range(50):
            h.observe(0.001 * (j + 1))
    rounds = 200
    t0 = _time.perf_counter()
    for i in range(rounds):
        ts.sample(now=float(i))
    per_sample = (_time.perf_counter() - t0) / rounds
    assert per_sample < 0.01 * DEFAULT_SAMPLE_INTERVAL_S, \
        f"sample() cost {per_sample * 1e3:.2f}ms"


# -- history doc (the /v1/metrics/history payload) ----------------------------

def test_history_doc_contract():
    import time as _time

    reg, ts = _store()
    c = reg.counter("req_total")
    # the doc's window ends at the wall clock (it serves live HTTP
    # requests), so anchor the synthetic samples just behind it
    t0 = _time.time() - 4.0
    for i in range(5):
        c.inc(10)
        ts.sample(now=t0 + i)

    code, doc = ts.history_doc("")
    assert code == 400 and "series" in doc

    code, doc = ts.history_doc("name=unknown_total")
    assert code == 404

    code, doc = ts.history_doc("name=req_total&window=60")
    assert code == 200
    assert doc["name"] == "req_total" and doc["kind"] == "counter"
    assert doc["window_s"] == 60.0
    # counters plot as per-interval rates: 5 samples -> 4 points
    assert len(doc["points"]) == 4
    assert all(len(p) == 2 for p in doc["points"])
    assert doc["points"][-1][1] == pytest.approx(10.0)

    code, doc = ts.history_doc("name=req_total&window=60&reduce=rate")
    assert code == 200 and doc["reduce"] == "rate"
    assert doc["reduced"] == pytest.approx(10.0)

    code, doc = ts.history_doc("name=req_total&window=banana")
    assert code == 400


def test_rows_feed_for_system_table():
    reg, ts = _store()
    c = reg.counter("req_total")
    g = reg.gauge("depth")
    h = reg.histogram("lat_seconds")
    for i in range(4):
        c.inc(5)
        g.set(float(i))
        h.observe(0.1)
        ts.sample(now=100.0 + i)
    rows = ts.rows(now=103.0)
    by_name = {}
    for name, kind, t, value in rows:
        by_name.setdefault(name, []).append((kind, t, value))
    # gauges verbatim, counters as per-interval rates, histograms as
    # rate + derived windowed quantiles
    assert [v for _, _, v in by_name["depth"]] == [0.0, 1.0, 2.0, 3.0]
    assert all(k == "counter" for k, _, _ in by_name["req_total.rate"])
    assert by_name["req_total.rate"][-1][2] == pytest.approx(5.0)
    assert "lat_seconds.rate" in by_name
    assert "lat_seconds.p95" in by_name
    ts_sorted = sorted(rows, key=lambda r: (r[0], r[2]))
    assert ts_sorted == rows


# -- exposition: windowed gauges ----------------------------------------------

def test_exposition_carries_windowed_quantile_gauges():
    """/v1/metrics grows `<family>_p95_5m`-style gauges for every
    histogram the GLOBAL store has windowed data on (and only when
    rendering the global registry — private registries stay clean)."""
    import time as _time

    from presto_tpu.obs.exposition import render_exposition
    from presto_tpu.obs.timeseries import TIMESERIES

    TIMESERIES.reset()
    try:
        h = TIMESERIES.registry.histogram("expo_win_seconds")
        t0 = _time.time() - 2.0
        h.observe(0.2)
        TIMESERIES.sample(now=t0)
        for _ in range(50):
            h.observe(0.2)
        TIMESERIES.sample(now=t0 + 1.0)
        text = render_exposition(TIMESERIES.registry)
        for tag in ("p50_5m", "p95_5m", "p99_5m"):
            assert f"expo_win_seconds_{tag}" in text
        # a private registry never leaks the global store's series
        other = MetricsRegistry()
        other.counter("lonely_total").inc()
        assert "expo_win_seconds_p95_5m" not in render_exposition(other)
    finally:
        TIMESERIES.reset()


# -- the /v1/metrics/history HTTP route ---------------------------------------

def test_history_endpoint_on_worker_and_coordinator():
    """Both servers expose the windowed-history doc; the route must
    win over the /v1/metrics prefix match and (on the coordinator)
    skip auth like the exposition endpoint does."""
    import json as _json
    import urllib.error
    import urllib.request

    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.obs.timeseries import TIMESERIES
    from presto_tpu.server.protocol import PrestoTpuServer
    from presto_tpu.server.worker import WorkerServer

    TIMESERIES.reset()
    TIMESERIES.registry.counter("history_ep_total").inc(5)
    TIMESERIES.sample()
    TIMESERIES.registry.counter("history_ep_total").inc(5)
    TIMESERIES.sample()

    def get(base, qs):
        with urllib.request.urlopen(
                f"{base}/v1/metrics/history{qs}", timeout=10) as r:
            return _json.loads(r.read().decode())

    w = WorkerServer(tpch_sf=0.001)
    w.start()
    srv = PrestoTpuServer(LocalRunner(tpch_sf=0.001))
    srv.start()
    try:
        for base in (f"http://127.0.0.1:{w.port}",
                     f"http://127.0.0.1:{srv.port}"):
            doc = get(base, "?name=history_ep_total&window=300")
            assert doc["kind"] == "counter" and doc["points"]
            # plain /v1/metrics still serves the exposition
            with urllib.request.urlopen(f"{base}/v1/metrics",
                                        timeout=10) as r:
                assert b"history_ep_total" in r.read()
            with pytest.raises(urllib.error.HTTPError) as ei:
                get(base, "")
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                get(base, "?name=nope_total")
            assert ei.value.code == 404
    finally:
        srv.stop()
        w.stop()
        TIMESERIES.stop()
        TIMESERIES.reset()


# -- defaults sanity ----------------------------------------------------------

def test_defaults_match_documented_config():
    assert DEFAULT_SAMPLE_INTERVAL_S == 5.0
    assert DEFAULT_RETENTION_POINTS == 360   # 30 min at 5s

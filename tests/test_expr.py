import datetime
from decimal import Decimal

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch
from presto_tpu.expr import (
    Form, cast, compile_filter, compile_projection, input_ref, lit, call,
)
from presto_tpu.expr.ir import special


def _batch():
    return Batch.from_pydict({
        "a": (T.BIGINT, [1, 2, 3, None, 5]),
        "b": (T.DOUBLE, [10.0, 20.0, None, 40.0, 50.0]),
        "s": (T.VARCHAR, ["MAIL", "SHIP", "AIR", "MAIL", None]),
        "d": (T.DATE, ["1994-01-01", "1994-06-15", "1995-01-01", "1993-12-31", "1994-02-28"]),
        "p": (T.decimal(12, 2), ["1.00", "2.50", "3.75", "4.00", None]),
    })


def test_arith_projection():
    b = _batch()
    a = input_ref(0, T.BIGINT)
    bb = input_ref(1, T.DOUBLE)
    exprs = [
        call("add", T.BIGINT, a, lit(10, T.BIGINT)),
        call("multiply", T.DOUBLE, bb, lit(2.0, T.DOUBLE)),
    ]
    fn = compile_projection(exprs, ["x", "y"], b.schema)
    out = fn(b)
    rows = out.to_pylist()
    assert [r[0] for r in rows] == [11, 12, 13, None, 15]
    assert [r[1] for r in rows] == [20.0, 40.0, None, 80.0, 100.0]


def test_decimal_arith():
    b = _batch()
    p = input_ref(4, T.decimal(12, 2))
    # p * 2.5 (decimal) -> scale 3
    e = call("multiply", T.decimal(15, 3), p, lit("2.5", T.decimal(3, 1)))
    out = compile_projection([e], ["x"], b.schema)(b)
    vals = [r[0] for r in out.to_pylist()]
    assert vals[0] == Decimal("2.500")
    assert vals[2] == Decimal("9.375")
    assert vals[4] is None


def test_filter_three_valued_logic():
    b = _batch()
    a = input_ref(0, T.BIGINT)
    # WHERE a > 1 AND b < 45  -- row2 has b NULL -> dropped
    pred = special(
        Form.AND, T.BOOLEAN,
        call("gt", T.BOOLEAN, a, lit(1, T.BIGINT)),
        call("lt", T.BOOLEAN, input_ref(1, T.DOUBLE), lit(45.0, T.DOUBLE)),
    )
    out = compile_filter(pred, b.schema)(b)
    rows = out.to_pylist()
    assert [r[0] for r in rows] == [2]


def test_or_null_semantics():
    b = Batch.from_pydict({"x": (T.BOOLEAN, [True, False, None])})
    pred = special(
        Form.OR, T.BOOLEAN,
        input_ref(0, T.BOOLEAN),
        lit(None, T.BOOLEAN),
    )
    out = compile_filter(pred, b.schema)(b)
    # TRUE OR NULL = TRUE; FALSE OR NULL = NULL; NULL OR NULL = NULL
    assert len(out.to_pylist()) == 1


def test_string_predicates():
    b = _batch()
    s = input_ref(2, T.VARCHAR)
    in_pred = special(
        Form.IN, T.BOOLEAN, s,
        lit("MAIL", T.VARCHAR), lit("SHIP", T.VARCHAR),
    )
    out = compile_filter(in_pred, b.schema)(b)
    assert [r[0] for r in out.to_pylist()] == [1, 2, None]

    like = call("like", T.BOOLEAN, s, lit("%AI%", T.VARCHAR))
    out2 = compile_filter(like, b.schema)(b)
    assert sorted(r[2] for r in out2.to_pylist()) == ["AIR", "MAIL", "MAIL"]


def test_string_transform_and_compare():
    b = _batch()
    s = input_ref(2, T.VARCHAR)
    lower = call("lower", T.VARCHAR, s)
    out = compile_projection([lower], ["l"], b.schema)(b)
    assert [r[0] for r in out.to_pylist()] == ["mail", "ship", "air", "mail", None]

    ltp = call("lt", T.BOOLEAN, s, lit("MAIL", T.VARCHAR))
    out2 = compile_filter(ltp, b.schema)(b)
    assert [r[2] for r in out2.to_pylist()] == ["AIR"]


def test_date_functions():
    b = _batch()
    d = input_ref(3, T.DATE)
    y = call("year", T.BIGINT, d)
    m = call("month", T.BIGINT, d)
    out = compile_projection([y, m], ["y", "m"], b.schema)(b)
    rows = out.to_pylist()
    assert [r[0] for r in rows] == [1994, 1994, 1995, 1993, 1994]
    assert [r[1] for r in rows] == [1, 6, 1, 12, 2]


def test_date_between():
    b = _batch()
    d = input_ref(3, T.DATE)
    pred = special(
        Form.BETWEEN, T.BOOLEAN, d,
        lit("1994-01-01", T.DATE), lit("1994-12-31", T.DATE),
    )
    out = compile_filter(pred, b.schema)(b)
    assert len(out.to_pylist()) == 3


def test_date_add_months_clamps():
    b = Batch.from_pydict({"d": (T.DATE, ["2000-01-31", "2000-02-29"])})
    e = call("date_add_months", T.DATE, input_ref(0, T.DATE), lit(1, T.INTEGER))
    out = compile_projection([e], ["d2"], b.schema)(b)
    assert [r[0] for r in out.to_pylist()] == [
        datetime.date(2000, 2, 29), datetime.date(2000, 3, 29)]


def test_case_switch():
    b = _batch()
    s = input_ref(2, T.VARCHAR)
    e = special(
        Form.SWITCH, T.BIGINT,
        call("eq", T.BOOLEAN, s, lit("MAIL", T.VARCHAR)), lit(1, T.BIGINT),
        call("eq", T.BOOLEAN, s, lit("SHIP", T.VARCHAR)), lit(2, T.BIGINT),
        lit(0, T.BIGINT),
    )
    out = compile_projection([e], ["c"], b.schema)(b)
    assert [r[0] for r in out.to_pylist()] == [1, 2, 0, 1, 0]


def test_coalesce_and_is_null():
    b = _batch()
    a = input_ref(0, T.BIGINT)
    e = special(Form.COALESCE, T.BIGINT, a, lit(-1, T.BIGINT))
    out = compile_projection([e], ["c"], b.schema)(b)
    assert [r[0] for r in out.to_pylist()] == [1, 2, 3, -1, 5]

    isn = special(Form.IS_NULL, T.BOOLEAN, a)
    out2 = compile_projection([isn], ["n"], b.schema)(b)
    assert [r[0] for r in out2.to_pylist()] == [False, False, False, True, False]


def test_cast_decimal_double():
    b = _batch()
    p = input_ref(4, T.decimal(12, 2))
    e = cast(p, T.DOUBLE)
    out = compile_projection([e], ["x"], b.schema)(b)
    assert [r[0] for r in out.to_pylist()] == [1.0, 2.5, 3.75, 4.0, None]

    e2 = cast(input_ref(1, T.DOUBLE), T.BIGINT)
    out2 = compile_projection([e2], ["x"], b.schema)(b)
    assert [r[0] for r in out2.to_pylist()] == [10, 20, None, 40, 50]


def test_division_by_zero_is_null():
    b = Batch.from_pydict({
        "x": (T.BIGINT, [10, 7]),
        "y": (T.BIGINT, [0, 2]),
    })
    e = call("divide", T.BIGINT, input_ref(0, T.BIGINT), input_ref(1, T.BIGINT))
    out = compile_projection([e], ["q"], b.schema)(b)
    assert [r[0] for r in out.to_pylist()] == [None, 3]


def test_q6_style_predicate():
    """TPC-H Q6 shape: date range + discount between + quantity bound."""
    b = Batch.from_pydict({
        "shipdate": (T.DATE, ["1994-03-01", "1993-05-05", "1994-11-30"]),
        "discount": (T.DOUBLE, [0.06, 0.06, 0.01]),
        "quantity": (T.DOUBLE, [10.0, 10.0, 30.0]),
        "extendedprice": (T.DOUBLE, [100.0, 200.0, 300.0]),
    })
    pred = special(
        Form.AND, T.BOOLEAN,
        call("ge", T.BOOLEAN, input_ref(0, T.DATE), lit("1994-01-01", T.DATE)),
        call("lt", T.BOOLEAN, input_ref(0, T.DATE), lit("1995-01-01", T.DATE)),
        special(Form.BETWEEN, T.BOOLEAN, input_ref(1, T.DOUBLE),
                lit(0.05, T.DOUBLE), lit(0.07, T.DOUBLE)),
        call("lt", T.BOOLEAN, input_ref(2, T.DOUBLE), lit(24.0, T.DOUBLE)),
    )
    out = compile_filter(pred, b.schema)(b)
    assert [r[3] for r in out.to_pylist()] == [100.0]

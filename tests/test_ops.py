from decimal import Decimal

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, concat_batches
from presto_tpu.ops import (
    AggSpec, SortKey, global_aggregate, grouped_aggregate, limit,
    lookup_join, semi_join_mask, sort_batch, top_n,
)


def test_grouped_sum_count():
    b = Batch.from_pydict({
        "k": (T.VARCHAR, ["a", "b", "a", "b", "a", None]),
        "v": (T.BIGINT, [1, 2, 3, None, 5, 7]),
    })
    out = grouped_aggregate(
        b, [0],
        [AggSpec("sum", 1, T.BIGINT, "s"),
         AggSpec("count", 1, T.BIGINT, "c"),
         AggSpec("count_star", None, T.BIGINT, "cs")],
    )
    rows = sorted(out.to_pylist(), key=lambda r: (r[0] is None, r[0]))
    assert rows == [("a", 9, 3, 3), ("b", 2, 1, 2), (None, 7, 1, 1)]


def test_grouped_min_max_avg():
    b = Batch.from_pydict({
        "k": (T.BIGINT, [1, 1, 2, 2, 2]),
        "v": (T.DOUBLE, [4.0, 2.0, 10.0, None, 20.0]),
    })
    out = grouped_aggregate(
        b, [0],
        [AggSpec("min", 1, T.DOUBLE, "mn"),
         AggSpec("max", 1, T.DOUBLE, "mx"),
         AggSpec("avg", 1, T.DOUBLE, "av")],
    )
    rows = sorted(out.to_pylist())
    assert rows == [(1, 2.0, 4.0, 3.0), (2, 10.0, 20.0, 15.0)]


def test_partial_final_equals_single():
    b1 = Batch.from_pydict({
        "k": (T.BIGINT, [1, 2, 1]),
        "v": (T.BIGINT, [10, 20, 30]),
    })
    b2 = Batch.from_pydict({
        "k": (T.BIGINT, [2, 3]),
        "v": (T.BIGINT, [40, None]),
    })
    aggs = [AggSpec("sum", 1, T.BIGINT, "s"), AggSpec("avg", 1, T.DOUBLE, "a")]
    p1 = grouped_aggregate(b1, [0], aggs, mode="partial")
    p2 = grouped_aggregate(b2, [0], aggs, mode="partial")
    merged = concat_batches([p1, p2])
    out = grouped_aggregate(merged, [0], aggs, mode="final")
    rows = sorted(out.to_pylist(), key=lambda r: r[0])
    assert rows == [(1, 40, 20.0), (2, 60, 30.0), (3, None, None)]

    single = grouped_aggregate(concat_batches([b1, b2]), [0], aggs)
    assert sorted(single.to_pylist(), key=lambda r: r[0]) == rows


def test_global_aggregate():
    b = Batch.from_pydict({"v": (T.BIGINT, [5, None, 7])})
    out = global_aggregate(b, [
        AggSpec("sum", 0, T.BIGINT, "s"),
        AggSpec("count", 0, T.BIGINT, "c"),
        AggSpec("min", 0, T.BIGINT, "mn"),
    ])
    assert out.to_pylist() == [(12, 2, 5)]


def test_global_aggregate_empty_input():
    b = Batch.from_pydict({"v": (T.BIGINT, [])})
    out = global_aggregate(b, [
        AggSpec("sum", 0, T.BIGINT, "s"),
        AggSpec("count", 0, T.BIGINT, "c"),
    ])
    # SQL: sum over empty = NULL, count = 0
    assert out.to_pylist() == [(None, 0)]


def test_grouped_decimal_sum_avg():
    b = Batch.from_pydict({
        "k": (T.BIGINT, [1, 1, 1]),
        "v": (T.decimal(10, 2), ["1.00", "2.00", "2.01"]),
    })
    out = grouped_aggregate(
        b, [0],
        [AggSpec("sum", 1, T.decimal(18, 2), "s"),
         AggSpec("avg", 1, T.decimal(10, 2), "a")],
    )
    assert out.to_pylist() == [(1, Decimal("5.01"), Decimal("1.67"))]


def test_sort_multi_key_null_ordering():
    b = Batch.from_pydict({
        "a": (T.BIGINT, [2, 1, 2, None, 1]),
        "b": (T.DOUBLE, [1.0, 9.0, 0.5, 3.0, None]),
    })
    out = sort_batch(b, [SortKey(0, ascending=True), SortKey(1, ascending=False)])
    rows = out.to_pylist()
    # a asc nulls last; within a, b desc nulls first
    assert rows == [(1, None), (1, 9.0), (2, 1.0), (2, 0.5), (None, 3.0)]


def test_sort_string_key():
    b = Batch.from_pydict({"s": (T.VARCHAR, ["pear", "apple", "fig"])})
    out = sort_batch(b, [SortKey(0)])
    assert [r[0] for r in out.to_pylist()] == ["apple", "fig", "pear"]


def test_top_n_and_limit():
    b = Batch.from_pydict({"v": (T.BIGINT, [5, 3, 9, 1, 7])})
    out = top_n(b, [SortKey(0, ascending=False)], 2)
    assert [r[0] for r in out.to_pylist()] == [9, 7]
    out2 = limit(b, 3)
    assert [r[0] for r in out2.to_pylist()] == [5, 3, 9]


def test_lookup_join_inner_left():
    orders = Batch.from_pydict({
        "okey": (T.BIGINT, [10, 20, 30]),
        "cust": (T.VARCHAR, ["alice", "bob", "carol"]),
    })
    lineitem = Batch.from_pydict({
        "okey": (T.BIGINT, [20, 10, 99, 20, None]),
        "qty": (T.BIGINT, [1, 2, 3, 4, 5]),
    })
    out = lookup_join(lineitem, orders, [0], [0], [1], ["cust"], "inner")
    rows = out.to_pylist()
    assert rows == [(20, 1, "bob"), (10, 2, "alice"), (20, 4, "bob")]

    out2 = lookup_join(lineitem, orders, [0], [0], [1], ["cust"], "left")
    rows2 = out2.to_pylist()
    assert rows2 == [
        (20, 1, "bob"), (10, 2, "alice"), (99, 3, None), (20, 4, "bob"),
        (None, 5, None),
    ]


def test_two_column_join_key():
    build = Batch.from_pydict({
        "a": (T.INTEGER, [1, 1, 2]),
        "b": (T.INTEGER, [10, 20, 10]),
        "val": (T.BIGINT, [100, 200, 300]),
    })
    probe = Batch.from_pydict({
        "a": (T.INTEGER, [1, 2, 1]),
        "b": (T.INTEGER, [20, 10, 99]),
    })
    out = lookup_join(probe, build, [0, 1], [0, 1], [2], ["val"], "inner")
    assert out.to_pylist() == [(1, 20, 200), (2, 10, 300)]


def test_semi_join_mask():
    probe = Batch.from_pydict({"k": (T.BIGINT, [1, 2, 3, None])})
    build = Batch.from_pydict({"k": (T.BIGINT, [2, 3])})
    mask = semi_join_mask(probe, build, [0], [0])
    assert list(np.asarray(mask))[:4] == [False, True, True, False]

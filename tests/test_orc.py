"""ORC reader + connector tests; pyarrow writes the oracle files.

The reference tests its ORC reader against files written by Hive/its own
writer (reference presto-orc/src/test/.../AbstractTestOrcReader.java);
here pyarrow.orc is the independent writer and python-side oracle, while
the reader under test is the from-scratch implementation in
presto_tpu/formats/.
"""
import datetime
import math

import numpy as np
import pyarrow as pa
import pyarrow.orc as pa_orc
import pytest

from presto_tpu.connectors.orc import OrcConnector
from presto_tpu.connectors.spi import CatalogManager, TableHandle
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.formats.orc import OrcReader
from presto_tpu.formats.orc_rle import decode_rle_v2_numpy

N = 10_000


@pytest.fixture(scope="module")
def orc_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("orc_tables")
    rng = np.random.RandomState(7)
    t = pa.table({
        "k": pa.array(np.arange(N)),
        "small": pa.array(rng.randint(-128, 128, N), type=pa.int32()),
        "big": pa.array(rng.randint(-10**14, 10**14, N)),
        "price": pa.array(np.round(rng.uniform(0, 1e4, N), 2)),
        "flag": pa.array(rng.choice(["A", "N", "R"], N)),
        "day": pa.array([datetime.date(1995, 1, 1)
                         + datetime.timedelta(days=int(d))
                         for d in rng.randint(0, 2000, N)]),
        "maybe": pa.array([None if i % 11 == 0 else float(i)
                           for i in range(N)]),
    })
    (root / "events").mkdir()
    # two files -> two splits
    pa_orc.write_table(t.slice(0, N // 2),
                       str(root / "events" / "part0.orc"),
                       compression="zlib")
    pa_orc.write_table(t.slice(N // 2),
                       str(root / "events" / "part1.orc"),
                       compression="uncompressed")
    return root, t


@pytest.fixture(scope="module")
def runner(orc_dir):
    root, _ = orc_dir
    catalogs = CatalogManager()
    catalogs.register("hive", OrcConnector(str(root)))
    from presto_tpu.connectors.tpch import TpchConnector
    catalogs.register("tpch", TpchConnector(sf=0.001))
    return LocalRunner(catalogs=catalogs, catalog="hive")


def test_reader_roundtrip(orc_dir):
    root, t = orc_dir
    r = OrcReader(str(root / "events" / "part0.orc"))
    got = [row for b in r.batches() for row in b.to_pylist()]
    want = t.slice(0, N // 2).to_pylist()
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w["k"] and g[1] == w["small"] and g[2] == w["big"]
        assert (g[3] is None) == (w["price"] is None)
        assert math.isclose(g[3], w["price"], abs_tol=1e-9)
        assert g[4] == w["flag"] and g[5] == w["day"]
        assert (g[6] is None) == (w["maybe"] is None)
        if g[6] is not None:
            assert math.isclose(g[6], w["maybe"], abs_tol=1e-9)


# tier-1 budget: single worst seconds-per-dot test in the suite (~297s
# of call time, 41% of the round-8 tier-1 wall per
# tools/check_tier1_time.py); the rest of the ORC ring stays in tier-1
@pytest.mark.slow
def test_sql_over_orc(runner, orc_dir):
    _, t = orc_dir
    res = runner.execute("select count(*), sum(big), min(k), max(k) "
                         "from events")
    want_sum = sum(v for v in t["big"].to_pylist())
    assert res.rows[0] == (N, want_sum, 0, N - 1)


@pytest.mark.slow   # 296s call on the tier-1 host (35% of the whole
#                     suite, check_tier1_time r7): grouped agg over the
#                     ORC table compiles a one-off kernel set; the fast
#                     ORC coverage (scan/pushdown/nulls/types) stays
def test_sql_filter_group(runner, orc_dir):
    _, t = orc_dir
    res = runner.execute(
        "select flag, count(*) c from events where price < 5000 "
        "group by flag order by flag")
    flags = t["flag"].to_pylist()
    prices = t["price"].to_pylist()
    want = {}
    for f, p in zip(flags, prices):
        if p < 5000:
            want[f] = want.get(f, 0) + 1
    assert [(r[0], r[1]) for r in res.rows] == sorted(want.items())


def test_nulls_over_orc(runner):
    res = runner.execute(
        "select count(*), count(maybe) from events")
    assert res.rows[0][0] == N
    assert res.rows[0][1] == N - len([i for i in range(N) if i % 11 == 0])


def test_join_orc_with_tpch(runner):
    res = runner.execute(
        "select count(*) from events, tpch.default.region "
        "where small = r_regionkey")
    direct = runner.execute(
        "select count(*) from events where small between 0 and 4")
    assert res.rows[0][0] == direct.rows[0][0]


def test_show_tables(runner):
    res = runner.execute("show tables")
    assert ("events",) in [tuple(r) for r in res.rows]


def test_rle_v2_device_vs_numpy(orc_dir):
    """Device expansion matches the NumPy oracle on real streams."""
    root, _ = orc_dir
    from presto_tpu.formats.orc_meta import parse_stripe_footer
    from presto_tpu.formats.orc_rle import decode_rle_v2_device
    r = OrcReader(str(root / "events" / "part1.orc"))
    stripe = r.tail.stripes[0]
    body = r._read_range(
        stripe.offset,
        stripe.index_length + stripe.data_length + stripe.footer_length)
    footer = parse_stripe_footer(
        body[stripe.index_length + stripe.data_length:],
        r.tail.compression)
    checked = 0
    for c in r.columns:
        if c.orc_kind not in ("long", "int", "date"):
            continue
        streams = r._streams(footer, body, c.orc_id)
        if "data" not in streams or "present" in streams:
            continue
        n = stripe.num_rows
        want = decode_rle_v2_numpy(streams["data"], n, signed=True)
        got = np.asarray(decode_rle_v2_device(streams["data"], n,
                                              signed=True))[:n]
        np.testing.assert_array_equal(got, want)
        checked += 1
    assert checked >= 2


def test_outliers_and_tinyint(tmp_path):
    """Outlier-heavy integers (the PATCHED_BASE shape) and signed
    tinyint round-trip exactly."""
    rng = np.random.RandomState(11)
    n = 5000
    vals = rng.randint(0, 512, n)
    vals[rng.choice(n, 25, replace=False)] = 10**13   # outliers
    tiny = (rng.randint(-128, 128, n)).astype(np.int8)
    t = pa.table({"v": pa.array(vals), "t": pa.array(tiny)})
    pa_orc.write_table(t, str(tmp_path / "o.orc"),
                       compression="uncompressed")
    r = OrcReader(str(tmp_path / "o.orc"))
    got = [row for b in r.batches() for row in b.to_pylist()]
    for (gv, gt), wv, wt in zip(got, vals, tiny):
        assert gv == wv and gt == int(wt)


def test_stripe_pruning(tmp_path):
    """Sorted data + per-stripe stats: a tight filter decodes only the
    matching stripes (and the engine pushes the bounds automatically)."""
    n = 400_000
    rng = np.random.RandomState(5)
    t = pa.table({
        "k": pa.array(np.arange(n)),
        "pad": pa.array(rng.randint(-10**15, 10**15, n)),
    })
    (tmp_path / "seq").mkdir()
    pa_orc.write_table(t, str(tmp_path / "seq" / "a.orc"),
                       compression="uncompressed",
                       stripe_size=256 * 1024)
    r = OrcReader(str(tmp_path / "seq" / "a.orc"))
    assert len(r.tail.stripes) > 2
    assert len(r.tail.stripe_stats) == len(r.tail.stripes)
    # direct reader-level pruning
    pruned = list(r.batches(["k"], min_max={"k": (0, 10)}))
    assert 0 < len(pruned) < len(r.tail.stripes)
    # engine-level: optimizer attaches bounds, scan rows shrink
    catalogs = CatalogManager()
    catalogs.register("hive", OrcConnector(str(tmp_path)))
    runner = LocalRunner(catalogs=catalogs, catalog="hive")
    res = runner.execute("select count(*), min(k), max(k) from seq "
                         "where k between 100 and 200")
    assert res.rows[0] == (101, 100, 200)
    ana = runner.execute("explain analyze select count(*) from seq "
                         "where k between 100 and 200")
    text = "\n".join(row[0] for row in ana.rows)
    import re as _re
    m = _re.search(r"TableScan\[hive.*?(\d[\d,]*) rows", text)
    assert m, text
    scanned = int(m.group(1).replace(",", ""))
    assert scanned < n  # pruned stripes never decoded


def test_one_sided_pushdown_large_values(tmp_path):
    """A one-sided filter (k >= lo) must not prune stripes whose values
    exceed any finite sentinel: unbounded sides travel as None, not a
    fake +/-2^62 bound."""
    n = 200_000
    big = (1 << 62) + 17   # above the old sentinel
    t = pa.table({"k": pa.array(np.concatenate([
        np.arange(n, dtype=np.int64),              # small stripe(s)
        np.arange(n, dtype=np.int64) + big,        # huge stripe(s)
    ]))})
    (tmp_path / "huge").mkdir()
    pa_orc.write_table(t, str(tmp_path / "huge" / "a.orc"),
                       compression="uncompressed",
                       stripe_size=256 * 1024)
    catalogs = CatalogManager()
    catalogs.register("hive", OrcConnector(str(tmp_path)))
    runner = LocalRunner(catalogs=catalogs, catalog="hive")
    res = runner.execute("select count(*) c from huge where k >= 10")
    assert res.rows[0][0] == 2 * n - 10


Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from {table}
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
"""


def test_q6_over_orc(tmp_path):
    """BASELINE config 5 shape: TPC-H Q6 over ORC lineitem with on-device
    decode, identical to the generator-connector answer (reference
    presto-benchmark/HandTpchQuery6.java over presto-orc)."""
    import jax.numpy as jnp
    from presto_tpu.connectors.tpch import TpchConnector, tpch_schema

    sf = 0.01
    conn = TpchConnector(sf=sf)
    cols = ["l_shipdate", "l_discount", "l_quantity", "l_extendedprice"]
    th = TableHandle("tpch", "default", "lineitem")
    (tmp_path / "lineitem").mkdir()
    epoch = datetime.date(1970, 1, 1)
    for i, split in enumerate(conn.split_manager.splits(th, 2)):
        arrays = {c: [] for c in cols}
        for b in conn.page_source(split, cols).batches():
            live = np.asarray(b.row_mask)
            for c, col in zip(cols, b.columns):
                arrays[c].append(np.asarray(col.data)[live])
        t = pa.table({
            "l_shipdate": pa.array(
                [epoch + datetime.timedelta(days=int(d))
                 for d in np.concatenate(arrays["l_shipdate"])]),
            "l_discount": pa.array(np.concatenate(arrays["l_discount"])),
            "l_quantity": pa.array(np.concatenate(arrays["l_quantity"])),
            "l_extendedprice": pa.array(
                np.concatenate(arrays["l_extendedprice"])),
        })
        pa_orc.write_table(t, str(tmp_path / "lineitem" / f"p{i}.orc"),
                           compression="zlib")

    catalogs = CatalogManager()
    catalogs.register("hive", OrcConnector(str(tmp_path)))
    catalogs.register("tpch", conn)
    r = LocalRunner(catalogs=catalogs, catalog="hive")
    got = r.execute(Q6.format(table="lineitem")).rows[0][0]
    want = r.execute(Q6.format(table="tpch.default.lineitem")).rows[0][0]
    assert got == pytest.approx(want, rel=1e-12)
    assert got > 0


def test_multi_stripe(tmp_path):
    n = 300_000
    rng = np.random.RandomState(1)
    vals = rng.randint(-10**15, 10**15, n)   # incompressible: real stripes
    t = pa.table({"v": pa.array(vals),
                  "w": pa.array(np.arange(n) % 97)})
    pa_orc.write_table(t, str(tmp_path / "ms.orc"), compression="zlib",
                       stripe_size=256 * 1024)
    r = OrcReader(str(tmp_path / "ms.orc"))
    assert len(r.tail.stripes) > 1
    total = 0
    checksum = 0
    for b in r.batches(["v"]):
        arr = np.asarray(b.columns[0].data)[np.asarray(b.row_mask)]
        total += len(arr)
        checksum += int(arr.sum())
    assert total == n
    assert checksum == int(vals.sum())

"""System connector: engine metadata via SQL (reference
presto-main/.../connector/system/ + connector/informationschema/)."""
import pytest

from presto_tpu.exec.runner import LocalRunner


@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=0.001)


def test_catalogs(runner):
    res = runner.execute(
        "select catalog_name from system.default.catalogs "
        "order by catalog_name")
    names = [r[0] for r in res.rows]
    assert {"tpch", "tpcds", "memory", "system"} <= set(names)


def test_tables_and_columns(runner):
    res = runner.execute(
        "select table_name from system.default.tables "
        "where table_catalog = 'tpch' order by table_name")
    assert ("lineitem",) in [tuple(r) for r in res.rows]
    res = runner.execute(
        "select column_name, data_type from system.default.columns "
        "where table_catalog = 'tpch' and table_name = 'nation' "
        "order by ordinal")
    assert res.rows[0][0] == "n_nationkey"
    assert res.rows[0][1] == "bigint"


def test_query_log(runner):
    runner.execute("select 42")
    res = runner.execute(
        "select query_id, state, query from system.default.queries")
    states = {r[2]: r[1] for r in res.rows}
    assert states.get("select 42") == "FINISHED"
    # the in-flight query shows as RUNNING
    assert any(s == "RUNNING" for s in states.values())


def test_query_log_failures(runner):
    with pytest.raises(Exception):
        runner.execute("select nope from nation")
    res = runner.execute(
        "select state from system.default.queries "
        "where query = 'select nope from nation'")
    assert res.rows and res.rows[0][0] == "FAILED"


def test_joins_against_system(runner):
    res = runner.execute("""
        select c.table_name, count(*) n
        from system.default.columns c
        where c.table_catalog = 'tpch'
        group by c.table_name order by c.table_name""")
    by_table = dict((r[0], r[1]) for r in res.rows)
    assert by_table["nation"] == 4
    assert by_table["lineitem"] == 16

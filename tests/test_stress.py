"""Concurrency stress: threaded-scan fault injection, early-LIMIT
abandonment, concurrent statements, worker death mid-query.

The round-2 regression lived exactly here (a threaded-scan refactor no
test executed); the reference covers this surface with failing page
sources in operator tests and the TaskExecutor simulator ring
(presto-main/src/test/.../execution/executor/simulator/).
"""
import threading
import time

import pytest

# tier-1 budget: excluded from `pytest -m 'not slow'` — stress shapes compile minutes of kernels for 5 tests
# (see tools/check_tier1_time.py; ~128s)
pytestmark = pytest.mark.slow

from presto_tpu.connectors.spi import (
    CatalogManager, PageSource, Split, TableHandle,
)
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.errors import QueryError


class FlakyConnector:
    """Delegates to tpch; injects sleeps/failures per split index
    (the failing-page-source stub of reference operator tests)."""

    name = "flaky"

    def __init__(self, inner, fail_splits=(), slow_splits=(),
                 delay_s: float = 0.05):
        self._inner = inner
        self.fail_splits = set(fail_splits)
        self.slow_splits = set(slow_splits)
        self.delay_s = delay_s
        self.started = []

    @property
    def metadata(self):
        return self._inner.metadata

    @property
    def split_manager(self):
        return self._inner.split_manager

    def page_source(self, split: Split, columns, pushdown=None,
                    rows_per_batch=1 << 17):
        inner = self._inner.page_source(split, columns,
                                        pushdown=pushdown,
                                        rows_per_batch=rows_per_batch)
        idx = len(self.started)
        self.started.append(split)
        conn = self

        class _Source(PageSource):
            def batches(self):
                if idx in conn.slow_splits:
                    time.sleep(conn.delay_s)
                if idx in conn.fail_splits:
                    raise IOError(f"injected failure on split {idx}")
                yield from inner.batches()

        return _Source()


def _flaky_runner(**kw):
    inner = TpchConnector(sf=0.01)
    flaky = FlakyConnector(inner, **kw)
    catalogs = CatalogManager()
    catalogs.register("tpch", flaky)
    r = LocalRunner(catalogs=catalogs, catalog="tpch",
                    rows_per_batch=1 << 12)
    r.session.properties["scan_threads"] = 4
    return r, flaky


def test_failing_split_fails_query_not_hangs():
    r, _ = _flaky_runner(fail_splits=(2,))
    t0 = time.perf_counter()
    with pytest.raises(Exception) as ei:
        r.execute("select count(*) from lineitem")
    assert "injected failure" in str(ei.value)
    assert time.perf_counter() - t0 < 60


def test_failing_split_does_not_poison_runner():
    r, flaky = _flaky_runner(fail_splits=(1,))
    with pytest.raises(Exception):
        r.execute("select count(*) from lineitem")
    flaky.fail_splits = set()
    got = r.execute("select count(*) from lineitem").rows[0][0]
    assert got > 0


def test_early_limit_abandons_scan():
    r, flaky = _flaky_runner(slow_splits=tuple(range(2, 64)),
                             delay_s=0.2)
    t0 = time.perf_counter()
    rows = r.execute("select l_orderkey from lineitem limit 5").rows
    assert len(rows) == 5
    # with ~60 slow splits a full scan would take >> this bound; LIMIT
    # must abandon the remaining splits
    assert time.perf_counter() - t0 < 30


def test_concurrent_statements_one_runner():
    r = LocalRunner(tpch_sf=0.01, rows_per_batch=1 << 12)
    r.execute("select 1")
    errors = []
    results = {}

    def go(i):
        try:
            if i % 3 == 0:
                rows = r.execute(
                    "select count(*) from lineitem").rows
            elif i % 3 == 1:
                rows = r.execute(
                    "select l_returnflag, count(*) from lineitem "
                    "group by 1 order by 1").rows
            else:
                rows = r.execute(
                    "select count(*) from orders o join customer c "
                    "on o.o_custkey = c.c_custkey").rows
            results[i] = rows
        except Exception as e:   # noqa: BLE001
            errors.append((i, e))

    threads = [threading.Thread(target=go, args=(i,)) for i in range(9)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    # all runs of the same statement agree
    for base in range(3):
        vals = [results[i] for i in range(9) if i % 3 == base]
        assert all(v == vals[0] for v in vals)


def test_worker_death_mid_query_fails_fast():
    from presto_tpu.exec.cluster import ClusterRunner, QueryFailedError
    from presto_tpu.server.worker import WorkerServer
    workers = [WorkerServer(tpch_sf=0.01) for _ in range(2)]
    for w in workers:
        w.start()
    urls = [f"http://127.0.0.1:{w.port}" for w in workers]
    runner = ClusterRunner(urls, tpch_sf=0.01, heartbeat=False)
    try:
        # warm the path
        assert runner.execute("select count(*) from nation").rows
        killer = threading.Timer(0.2, workers[1].stop)
        killer.start()
        t0 = time.perf_counter()
        with pytest.raises(QueryFailedError):
            for _ in range(50):
                runner.execute(
                    "select l_partkey, count(*) from lineitem "
                    "group by 1 order by 2 desc limit 3")
        # bounded by the exchange retry budget (the reference's
        # RequestErrorTracker keeps retrying ~5min before declaring the
        # task lost) — fail-fast, not hang-forever, is the contract
        assert time.perf_counter() - t0 < 320
    finally:
        for w in workers:
            try:
                w.stop()
            except Exception:
                pass

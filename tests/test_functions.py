"""Widened scalar-function surface (reference operator/scalar/*.java:
MathFunctions, BitwiseFunctions, StringFunctions, JoniRegexpFunctions,
JsonFunctions, UrlFunctions, DateTimeFunctions).

String/regex/JSON/URL functions evaluate host-side over the static
dictionary vocab and bake into the kernel as gather tables — asserted here
end-to-end through the SQL surface.
"""
import datetime
import math

import pytest


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.001)


def one(runner, sql):
    rows = runner.execute("select " + sql).rows
    assert len(rows) == 1
    return rows[0]


def test_math(runner):
    r = one(runner, "sin(0e0), log2(8e0), log10(1000e0), cbrt(27e0), "
                    "atan2(1e0, 1e0), log(2e0, 32e0)")
    assert r[0] == 0.0 and r[1] == 3.0 and abs(r[2] - 3.0) < 1e-12
    assert abs(r[3] - 3.0) < 1e-12
    assert abs(r[4] - math.pi / 4) < 1e-12 and abs(r[5] - 5.0) < 1e-12


def test_sign_trunc_bucket(runner):
    r = one(runner, "sign(-5), sign(0), truncate(3.9e0), truncate(-3.9e0), "
                    "width_bucket(5e0, 0e0, 10e0, 10)")
    assert r == (-1, 0, 3.0, -3.0, 6)


def test_nan_infinity(runner):
    r = one(runner, "is_nan(nan()), is_finite(1e0), is_infinite(infinity()), "
                    "is_nan(1e0)")
    assert r == (True, True, True, False)


def test_greatest_least(runner):
    assert one(runner, "greatest(1, 5, 3), least(2, 5, 3)") == (5, 2)
    assert one(runner, "greatest(1, null, 3)") == (None,)


def test_pi_e(runner):
    r = one(runner, "pi(), e()")
    assert abs(r[0] - math.pi) < 1e-12 and abs(r[1] - math.e) < 1e-12


def test_bitwise(runner):
    r = one(runner, "bitwise_and(12, 10), bitwise_or(12, 10), "
                    "bitwise_xor(12, 10), bitwise_not(0), bit_count(255), "
                    "bitwise_left_shift(1, 4), "
                    "bitwise_arithmetic_shift_right(-8, 1)")
    assert r == (8, 14, 6, -1, 8, 16, -4)


def test_string_functions(runner):
    r = one(runner, "replace('banana', 'an', 'x'), reverse('abc'), "
                    "lpad('7', 3, '0'), rpad('ab', 5, '-'), "
                    "ltrim('  x '), rtrim(' x  '), "
                    "split_part('a:b:c', ':', 2), strpos('hello', 'll'), "
                    "strpos('hello', 'z'), codepoint('A')")
    assert r == ("bxxa", "cba", "007", "ab---", "x ", " x", "b", 3, 0, 65)


def test_string_functions_on_column(runner):
    rows = runner.execute(
        "select n_name, reverse(n_name), strpos(n_name, 'AN') "
        "from nation where n_nationkey in (0, 3)").rows
    for name, rev, pos in rows:
        assert rev == name[::-1]
        assert pos == name.find("AN") + 1


def test_levenshtein(runner):
    rows = runner.execute(
        "select n_name, levenshtein_distance(n_name, 'ALGERIA') "
        "from nation where n_nationkey < 3").rows
    import difflib
    for name, d in rows:
        if name == "ALGERIA":
            assert d == 0
        else:
            assert d > 0


def test_regexp(runner):
    r = one(runner, "regexp_like('algeria', 'a.g'), "
                    "regexp_extract('x123y', '[0-9]+'), "
                    "regexp_extract('ab-cd', '(\\w+)-(\\w+)', 2), "
                    "regexp_replace('a1b2', '[0-9]', '#')")
    assert r == (True, "123", "cd", "a#b#")


def test_regexp_extract_no_match_is_null(runner):
    r = one(runner, "regexp_extract('abc', '[0-9]+'), "
                    "regexp_extract('abc', '[0-9]+') is null")
    assert r == (None, True)


def test_regexp_replace_literal_dollar(runner):
    r = one(runner, "regexp_replace('9.99', '^', 'US$'), "
                    "regexp_replace('ab-cd', '(\\w+)-(\\w+)', '$2.$1')")
    assert r == ("US$9.99", "cd.ab")


def test_truncate_scale(runner):
    r = one(runner, "truncate(123.456e0, 2), truncate(-123.456e0, 1)")
    assert r == (123.45, -123.4)


def test_json_extract_dedupes_codes(runner):
    # equal extracted values must share one dictionary code: GROUP BY
    # over the extraction must merge them
    runner.execute("create table memory.default.js as select * from "
                   "(values ('{\"a\": 1, \"z\": 9}'), ('{\"a\": 1}'), "
                   "('{\"a\": 2}')) as t(doc)")
    rows = runner.execute(
        "select json_extract_scalar(doc, '$.a') v, count(*) "
        "from memory.default.js group by 1 order by 1").rows
    assert rows == [("1", 2), ("2", 1)]


def test_json(runner):
    r = one(runner, "json_extract_scalar('{\"a\": {\"b\": [1, 5]}}', "
                    "'$.a.b[1]'), "
                    "json_extract_scalar('{\"x\": true}', '$.x'), "
                    "json_extract_scalar('{\"x\": 1}', '$.missing')")
    assert r == ("5", "true", None)


def test_url(runner):
    r = one(runner, "url_extract_host('https://x.io:8080/p?q=1#f'), "
                    "url_extract_protocol('https://x.io/'), "
                    "url_extract_path('https://x.io/a/b'), "
                    "url_extract_query('https://x.io/p?q=1'), "
                    "url_extract_port('https://x.io:8080/')")
    assert r == ("x.io", "https", "/a/b", "q=1", 8080)


def test_day_functions(runner):
    # 2026-07-30 is a Thursday, day 211 of the year
    r = one(runner, "day_of_week(date '2026-07-30'), "
                    "day_of_year(date '2026-07-30'), "
                    "extract(dow from date '2026-07-30')")
    assert r == (4, 211, 4)


def test_iso_week(runner):
    # ISO-8601 edges: 2026-01-01 (Thursday) is week 1 of 2026;
    # 2027-01-01 (Friday) is week 53 of 2026; 2024-12-30 is week 1 of 2025
    r = one(runner, "week(date '2026-01-01'), year_of_week(date '2026-01-01'), "
                    "week(date '2027-01-01'), year_of_week(date '2027-01-01'), "
                    "week(date '2024-12-30'), year_of_week(date '2024-12-30')")
    assert r == (1, 2026, 53, 2026, 1, 2025)


def test_iso_week_vs_python(runner):
    dates = ["2020-01-01", "2021-01-01", "2022-12-31", "2023-01-02",
             "2024-02-29", "2025-12-29"]
    for d in dates:
        w, yw = one(runner, f"week(date '{d}'), year_of_week(date '{d}')")
        iso = datetime.date.fromisoformat(d).isocalendar()
        assert (yw, w) == (iso[0], iso[1]), d


def test_time_parts(runner):
    r = one(runner, "hour(timestamp '2026-07-30 13:45:56'), "
                    "minute(timestamp '2026-07-30 13:45:56'), "
                    "second(timestamp '2026-07-30 13:45:56'), "
                    "millisecond(timestamp '2026-07-30 13:45:56.250')")
    assert r == (13, 45, 56, 250)


def test_date_trunc(runner):
    r = one(runner, "date_trunc('month', date '2026-07-30'), "
                    "date_trunc('quarter', date '2026-07-30'), "
                    "date_trunc('year', date '2026-07-30'), "
                    "date_trunc('week', date '2026-07-30')")
    assert r == (datetime.date(2026, 7, 1), datetime.date(2026, 7, 1),
                 datetime.date(2026, 1, 1), datetime.date(2026, 7, 27))


def test_date_trunc_timestamp(runner):
    r = one(runner, "date_trunc('hour', timestamp '2026-07-30 13:45:56'), "
                    "date_trunc('day', timestamp '2026-07-30 13:45:56')")
    assert r == (datetime.datetime(2026, 7, 30, 13, 0),
                 datetime.datetime(2026, 7, 30, 0, 0))


def test_date_diff(runner):
    r = one(runner, "date_diff('day', date '2026-01-01', date '2026-07-30'), "
                    "date_diff('week', date '2026-01-01', date '2026-01-15'), "
                    "date_diff('month', date '2026-01-31', date '2026-02-28'), "
                    "date_diff('month', date '2026-01-15', date '2026-03-15'), "
                    "date_diff('year', date '2020-06-01', date '2026-05-31')")
    assert r == (210, 2, 0, 2, 5)


def test_date_diff_negative(runner):
    r = one(runner, "date_diff('day', date '2026-07-30', date '2026-01-01'), "
                    "date_diff('month', date '2026-03-15', date '2026-01-20')")
    assert r == (-210, -1)


def test_date_add(runner):
    r = one(runner, "date_add('month', 1, date '2026-01-31'), "
                    "date_add('day', -1, date '2026-01-01'), "
                    "date_add('hour', 25, timestamp '2026-07-30 00:30:00')")
    assert r == (datetime.date(2026, 2, 28), datetime.date(2025, 12, 31),
                 datetime.datetime(2026, 7, 31, 1, 30))


def test_last_day_of_month(runner):
    r = one(runner, "last_day_of_month(date '2026-02-01'), "
                    "last_day_of_month(date '2024-02-11')")
    assert r == (datetime.date(2026, 2, 28), datetime.date(2024, 2, 29))


def test_unixtime(runner):
    r = one(runner, "to_unixtime(timestamp '1970-01-02 00:00:00'), "
                    "from_unixtime(86400e0)")
    assert r == (86400.0, datetime.datetime(1970, 1, 2))


def test_functions_over_table_scan(runner):
    # device-path sanity: vectorized over a real column
    rows = runner.execute(
        "select o_orderdate, day_of_week(o_orderdate), week(o_orderdate) "
        "from orders limit 50").rows
    for d, dow, wk in rows:
        iso = d.isocalendar()
        assert dow == iso[2] and wk == iso[1]


def test_string_function_additions(runner):
    rows = runner.execute(
        "select ends_with('hello', 'llo'), ends_with('hello', 'x'), "
        "translate('abcde', 'bd', 'XY'), translate('abc', 'b', ''), "
        "hamming_distance('karolin', 'kathrin'), "
        "day_of_month(date '2024-03-07')").rows
    assert rows == [(True, False, "aXcYe", "ac", 3, 7)]

"""External recorded-answer checks.

Expected values are transcribed from the reference's product-test
fixtures (reference presto-product-tests/src/main/resources/sql-tests/
testcases/tpch_connector/*.result — recorded outputs of Presto itself
over the airlift dbgen tpch connector), plus TPC-spec-fixed table
contents. They check our TPC-H connector against something OUTSIDE this
repo's own code.

Known divergence (documented): our generator is not dbgen
bit-compatible (connectors/tpch.py:16) — per-order line counts draw from
a different RNG stream, so tiny lineitem is 60472 vs dbgen's 60175.
Spec-pinned tables (nation/region) and count formulas for the fixed-
cardinality tables must match exactly.
"""
import pytest


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.01)


# reference countXxxTiny.result values (dbgen tiny = SF 0.01)
FIXED_COUNTS = {
    "customer": 1500,
    "orders": 15000,
    "part": 2000,
    "partsupp": 8000,
    "supplier": 100,
    "nation": 25,
    "region": 5,
}


@pytest.mark.parametrize("table,want", sorted(FIXED_COUNTS.items()))
def test_tiny_counts_match_reference(runner, table, want):
    got = runner.execute(f"select count(*) from {table}").rows[0][0]
    assert got == want


def test_nation_contents_match_reference(runner):
    # reference selectFromNationTiny.result (spec-fixed table)
    got = runner.execute(
        "select n_nationkey, n_name, n_regionkey from nation "
        "order by n_nationkey").rows
    want_head = [
        (0, "ALGERIA", 0), (1, "ARGENTINA", 1), (2, "BRAZIL", 1),
        (3, "CANADA", 1), (4, "EGYPT", 4), (5, "ETHIOPIA", 0),
        (6, "FRANCE", 3),
    ]
    assert got[:7] == want_head
    assert len(got) == 25


def test_region_contents(runner):
    got = runner.execute(
        "select r_regionkey, r_name from region order by 1").rows
    assert got == [(0, "AFRICA"), (1, "AMERICA"), (2, "ASIA"),
                   (3, "EUROPE"), (4, "MIDDLE EAST")]

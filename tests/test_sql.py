"""End-to-end SQL tests against a SQLite oracle.

Ring-2 of the test strategy (SURVEY.md §4): the full
parse->plan->optimize->execute path in-process, results checked against an
independent engine — the role H2 plays for the reference
(presto-tests/.../H2QueryRunner.java).
"""
import datetime
import math
import sqlite3
from decimal import Decimal

import pytest

from presto_tpu.connectors.spi import TableHandle
from presto_tpu.connectors.tpch import TABLES, TpchConnector, tpch_schema
from presto_tpu.exec.runner import LocalRunner

from tpch_queries import Q as TPCH_QUERIES

SF = 0.01


@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=SF)


@pytest.fixture(scope="module")
def oracle(runner):
    """SQLite loaded with the same generated TPC-H data."""
    conn = sqlite3.connect(":memory:")
    try:
        conn.execute("select floor(1.5)")
    except sqlite3.OperationalError:
        # sqlite built without SQLITE_ENABLE_MATH_FUNCTIONS (the default
        # before 3.35 and in many distro builds): supply the oracle's
        # floor() in Python so the feature test compares, not crashes
        import math
        conn.create_function(
            "floor", 1,
            lambda v: math.floor(v) if v is not None else None)
    tpch = runner.session.catalogs.get("tpch")
    for t in TABLES:
        schema = tpch_schema(t)
        cols = ", ".join(schema.names)
        conn.execute(f"create table {t} ({cols})")
        placeholders = ", ".join("?" * len(schema))
        th = TableHandle("tpch", "default", t)
        for split in tpch.split_manager.splits(th, 1):
            for b in tpch.page_source(split, schema.names).batches():
                rows = [tuple(_sql_val(v) for v in r) for r in b.to_pylist()]
                conn.executemany(
                    f"insert into {t} values ({placeholders})", rows)
    conn.commit()
    return conn


def _sql_val(v):
    if hasattr(v, "item"):      # numpy scalar -> python (sqlite stores
        v = v.item()            # np.int64 as a BLOB otherwise)
    if isinstance(v, datetime.date):
        return v.isoformat()
    if isinstance(v, Decimal):
        return float(v)
    return v


def _norm(rows, has_order):
    out = []
    for r in rows:
        nr = []
        for v in r:
            v = _sql_val(v)
            if isinstance(v, float):
                v = round(v, 4)
            if hasattr(v, "item"):
                v = v.item()
                if isinstance(v, float):
                    v = round(v, 4)
            nr.append(v)
        out.append(tuple(nr))
    return out if has_order else sorted(out, key=repr)


def compare(runner, oracle, sql, oracle_sql=None, rel=1e-9):
    got = runner.execute(sql)
    want = oracle.execute(oracle_sql or sql).fetchall()
    has_order = "order by" in sql.lower()
    g = _norm(got.rows, has_order)
    w = _norm(want, has_order)
    assert len(g) == len(w), f"{len(g)} rows vs oracle {len(w)}"
    for gr, wr in zip(g, w):
        assert len(gr) == len(wr)
        for gv, wv in zip(gr, wr):
            if isinstance(gv, float) and isinstance(wv, (int, float)):
                assert gv == pytest.approx(wv, rel=rel, abs=1e-9), (gr, wr)
            else:
                assert gv == wv, (gr, wr)


# q21 alone costs 137s on the tier-1 host (16% of the whole suite,
# check_tier1_time r7: quadruple-correlated EXISTS/NOT EXISTS compiles
# a one-off kernel set) — it runs with the slow tier; the other 21
# TPC-H queries keep oracle coverage in tier-1
_TPCH_PARAMS = [
    pytest.param(*t, marks=pytest.mark.slow) if t[0] == "q21" else t
    for t in TPCH_QUERIES
]


@pytest.mark.parametrize(
    "name,sql,oracle_sql", _TPCH_PARAMS, ids=[t[0] for t in TPCH_QUERIES])
def test_tpch(runner, oracle, name, sql, oracle_sql):
    compare(runner, oracle, sql, oracle_sql, rel=1e-6)


# -- generic SQL feature coverage (AbstractTestQueries role) -----------------

FEATURES = [
    "select 1 + 2 * 3 as x",
    "select count(*) from orders",
    "select count(o_orderkey), min(o_totalprice), max(o_totalprice) from orders",
    "select o_orderstatus, count(*) from orders group by o_orderstatus order by 1",
    "select * from region order by r_regionkey",
    "select r.r_name, n.n_name from region r join nation n on n.n_regionkey = r.r_regionkey order by 1, 2",
    "select n_name from nation where n_regionkey in (1, 2) order by n_name",
    "select n_name from nation where n_name like 'A%' order by 1",
    "select n_name from nation where n_name not like '%A%' order by 1",
    "select o_orderkey from orders where o_orderkey between 5 and 10 order by 1",
    "select coalesce(null, 42) as x",
    "select nullif(1, 1) as a, nullif(1, 2) as b",
    "select abs(-5) a, length('hello') b, upper('abc') c, substr('hello', 2, 3) d",
    "select case o_orderstatus when 'F' then 'f' when 'O' then 'o' else 'x' end s, count(*) from orders group by 1 order by 1",
    "select cast(floor(o_totalprice) as integer) from orders order by o_orderkey limit 5",
    "select distinct c_mktsegment from customer order by 1",
    "select c_mktsegment, count(*) c from customer group by c_mktsegment having count(*) > 10 order by c",
    "select s_name from supplier where s_suppkey in (select ps_suppkey from partsupp where ps_availqty > 9990) order by 1",
    "select count(*) from orders where o_custkey not in (select c_custkey from customer where c_mktsegment = 'BUILDING')",
    "select n_name from nation union select r_name from region order by 1",
    "select n_regionkey from nation union all select r_regionkey from region order by 1 limit 5",
    "select o_orderpriority, sum(o_totalprice) from orders group by o_orderpriority order by 2 desc limit 3",
    "select count(*) from lineitem where l_shipdate is not null",
    "select o_orderkey, o_totalprice from orders order by o_totalprice desc limit 7",
    "select o_orderdate, count(*) from orders where o_orderdate < date '1992-03-01' group by o_orderdate order by 1",
    "select count(*) from (select o_custkey k from orders where o_totalprice > 200000) t join customer on c_custkey = k",
    "select max(o_orderdate) from orders",
    "select s_name, n_name from supplier left join nation on s_nationkey = n_nationkey and n_regionkey = 0 order by s_name limit 5",
    # many-to-many joins (expansion path)
    "select count(*) from nation n join customer c on n.n_nationkey = c.c_nationkey",
    "select n_name, count(o_orderkey) from nation left join customer on n_nationkey = c_nationkey left join orders on c_custkey = o_custkey group by n_name order by 1",
    "select count(*), sum(l1.l_quantity) from lineitem l1 join lineitem l2 on l1.l_orderkey = l2.l_orderkey where l1.l_linenumber = 1 and l2.l_linenumber = 2",
]


@pytest.mark.parametrize("sql", FEATURES, ids=range(len(FEATURES)))
def test_features(runner, oracle, sql):
    osql = sql.replace("date '", "'")     # sqlite: ISO strings compare fine
    compare(runner, oracle, sql, osql)


def test_explain_and_session(runner):
    res = runner.execute("explain select count(*) from orders")
    assert any("Aggregate" in r[0] for r in res.rows)
    runner.execute("set session broadcast_join_row_limit = 10")
    assert runner.session.properties["broadcast_join_row_limit"] == 10
    runner.execute("reset session broadcast_join_row_limit")
    assert "broadcast_join_row_limit" not in runner.session.properties
    res = runner.execute("show tables")
    assert ("lineitem",) in res.rows


def test_date_semantics(runner, oracle):
    compare(
        runner, oracle,
        "select extract(year from o_orderdate) y, count(*) c from orders "
        "group by 1 order by 1",
        "select cast(substr(o_orderdate,1,4) as integer) y, count(*) c "
        "from orders group by 1 order by 1")


def test_distinct_aggregates(runner, oracle):
    """Single, mixed, and multi-argument DISTINCT aggregates (the
    MarkDistinct mask-channel lowering)."""
    compare(runner, oracle,
            "select count(distinct o_custkey) from orders")
    compare(runner, oracle,
            "select o_orderstatus, count(distinct o_custkey) c, "
            "count(*) n, sum(o_totalprice) s from orders "
            "group by 1 order by 1")
    compare(runner, oracle,
            "select count(distinct l_suppkey), count(distinct l_partkey),"
            " count(*) from lineitem")
    compare(runner, oracle,
            "select l_returnflag, sum(distinct l_quantity) sq, "
            "avg(l_quantity) a from lineitem group by 1 order by 1")
    compare(runner, oracle,
            "select o_orderpriority, count(distinct o_orderstatus) "
            "from orders group by 1 order by 1")


def test_approx_distinct(runner, oracle):
    """Global approx_distinct runs the bounded HLL sketch: within a few
    standard errors (2.3% default) of the exact count, deterministically
    (stateless hashing)."""
    got = runner.execute(
        "select approx_distinct(o_custkey) from orders").rows
    want = oracle.execute(
        "select count(distinct o_custkey) from orders").fetchall()
    assert abs(int(got[0][0]) - want[0][0]) <= max(0.1 * want[0][0], 2)


def test_variance_large_mean(runner, oracle):
    """Central-moment states must not cancel catastrophically: shifting
    the data by 1e15 must leave stddev (nearly) unchanged.  The naive
    sum/sum-of-squares state returns ~2x (or 0) here."""
    res = runner.execute(
        "select stddev(l_quantity + 1000000000000000.0) a, "
        "stddev(l_quantity) b from lineitem")
    a, b = res.rows[0]
    assert a == pytest.approx(b, rel=1e-3)
    res = runner.execute(
        "select l_returnflag, stddev(l_quantity + 1000000000000000.0) a, "
        "stddev(l_quantity) b from lineitem group by 1 order by 1")
    for _, a, b in res.rows:
        assert a == pytest.approx(b, rel=1e-3)


def test_variance_family(runner, oracle):
    """stddev/variance vs numpy (SQLite has no stddev built in)."""
    import numpy as np
    res = runner.execute(
        "select l_returnflag, count(*) n, var_samp(l_quantity) vs, "
        "var_pop(l_quantity) vp, stddev(l_quantity) ss, "
        "stddev_pop(l_quantity) sp from lineitem "
        "group by l_returnflag order by l_returnflag")
    raw = oracle.execute(
        "select l_returnflag, l_quantity from lineitem").fetchall()
    by_flag = {}
    for f, q in raw:
        by_flag.setdefault(f, []).append(q)
    for flag, n, vs, vp, ss, sp in res.rows:
        a = np.asarray(by_flag[flag], dtype=float)
        assert n == len(a)
        assert vs == pytest.approx(a.var(ddof=1), rel=1e-9)
        assert vp == pytest.approx(a.var(), rel=1e-9)
        assert ss == pytest.approx(a.std(ddof=1), rel=1e-9)
        assert sp == pytest.approx(a.std(), rel=1e-9)


def test_bool_and_or(runner, oracle):
    compare(runner, oracle, """
        select o_orderstatus, count(*) from orders
        group by o_orderstatus order by o_orderstatus""")
    res = runner.execute("""
        select o_orderpriority,
               bool_and(o_totalprice > 1000) ba,
               bool_or(o_totalprice > 400000) bo
        from orders group by o_orderpriority order by o_orderpriority""")
    want = {}
    for pri, price in oracle.execute(
            "select o_orderpriority, o_totalprice from orders"):
        a, o = want.setdefault(pri, [True, False])
        want[pri] = [a and price > 1000, o or price > 400000]
    for pri, ba, bo in res.rows:
        assert [bool(ba), bool(bo)] == want[pri]


def test_global_variance(runner, oracle):
    import numpy as np
    res = runner.execute(
        "select stddev(l_extendedprice), var_pop(l_discount) "
        "from lineitem")
    vals = oracle.execute(
        "select l_extendedprice, l_discount from lineitem").fetchall()
    p = np.asarray([v[0] for v in vals])
    d = np.asarray([v[1] for v in vals])
    assert res.rows[0][0] == pytest.approx(p.std(ddof=1), rel=1e-9)
    assert res.rows[0][1] == pytest.approx(d.var(), rel=1e-9)


def test_arbitrary(runner, oracle):
    res = runner.execute(
        "select n_regionkey, arbitrary(n_name) a, any_value(n_name) v "
        "from nation group by n_regionkey order by n_regionkey")
    names = {}
    for rk, nm in oracle.execute(
            "select n_regionkey, n_name from nation"):
        names.setdefault(rk, set()).add(nm)
    for rk, a, v in res.rows:
        assert a in names[rk] and v in names[rk]


def test_min_max_varchar(runner, oracle):
    """Lexicographic min/max over dictionary columns, grouped + global
    (codes are appearance-ordered, so raw-code reduction would be
    wrong)."""
    compare(runner, oracle, """
        select n_regionkey, min(n_name) mn, max(n_name) mx
        from nation group by n_regionkey order by n_regionkey""")
    compare(runner, oracle,
            "select min(p_type), max(p_container) from part")

"""Views and prepared statements (reference sql/tree/CreateView.java,
Prepare.java, Execute.java, ParameterRewriter.java; view expansion in
StatementAnalyzer)."""
import pytest


@pytest.fixture()
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.001)


def test_create_select_drop_view(runner):
    runner.execute("create view v1 as select n_name, n_regionkey "
                   "from nation where n_nationkey < 5")
    assert runner.execute("select count(*) from v1").rows == [(5,)]
    rows = runner.execute(
        "select v1.n_name from v1 join region "
        "on v1.n_regionkey = region.r_regionkey "
        "where region.r_name = 'AMERICA' order by 1").rows
    assert [r[0] for r in rows] == ["ARGENTINA", "BRAZIL", "CANADA"]
    runner.execute("drop view v1")
    with pytest.raises(Exception):
        runner.execute("select * from v1")


def test_view_over_view(runner):
    runner.execute("create view a_nations as "
                   "select * from nation where n_name like 'A%'")
    runner.execute("create view al_nations as "
                   "select * from a_nations where n_name like 'AL%'")
    assert runner.execute(
        "select n_name from al_nations").rows == [("ALGERIA",)]


def test_or_replace(runner):
    runner.execute("create view v as select 1 as x")
    with pytest.raises(ValueError, match="already exists"):
        runner.execute("create view v as select 2 as x")
    runner.execute("create or replace view v as select 2 as x")
    assert runner.execute("select x from v").rows == [(2,)]


def test_drop_view_if_exists(runner):
    runner.execute("drop view if exists nope")
    with pytest.raises(ValueError, match="does not exist"):
        runner.execute("drop view nope")


def test_broken_view_fails_at_create(runner):
    with pytest.raises(Exception):
        runner.execute("create view bad as select no_such_col from nation")


def test_view_shows_in_show_tables(runner):
    runner.execute("create view zzz_view as select 1 as x")
    names = [r[0] for r in runner.execute("show tables").rows]
    assert "zzz_view" in names


def test_prepare_execute(runner):
    runner.execute("prepare q1 from "
                   "select n_name from nation where n_nationkey = ?")
    assert runner.execute("execute q1 using 3").rows == [("CANADA",)]
    assert runner.execute("execute q1 using 4").rows == [("EGYPT",)]


def test_prepare_multiple_params(runner):
    runner.execute("prepare q2 from select n_name from nation "
                   "where n_nationkey = ? or n_name = ? order by 1")
    assert runner.execute("execute q2 using 3, 'PERU'").rows \
        == [("CANADA",), ("PERU",)]


def test_prepare_no_params(runner):
    runner.execute("prepare q3 from select count(*) from region")
    assert runner.execute("execute q3").rows == [(5,)]


def test_describe_input_output(runner):
    runner.execute("prepare q4 from select n_name, n_nationkey + ? as k "
                   "from nation where n_regionkey = ?")
    rows = runner.execute("describe input q4").rows
    assert len(rows) == 2
    out = runner.execute("describe output q4").rows
    assert [r[0] for r in out] == ["n_name", "k"]


def test_deallocate(runner):
    runner.execute("prepare q5 from select 1")
    runner.execute("deallocate prepare q5")
    with pytest.raises(ValueError, match="not found"):
        runner.execute("execute q5")


def test_too_few_parameters(runner):
    runner.execute("prepare q6 from "
                   "select * from nation where n_nationkey = ?")
    with pytest.raises(ValueError, match="parameters"):
        runner.execute("execute q6")


def test_unbound_parameter_rejected(runner):
    from presto_tpu.sql.analyzer import AnalysisError
    with pytest.raises(AnalysisError, match="unbound"):
        runner.execute("select * from nation where n_nationkey = ?")


def test_view_not_captured_by_outer_cte(runner):
    runner.execute("create view vcnt as select count(*) as c from nation")
    rows = runner.execute(
        "with nation as (select 1 as x) select * from vcnt").rows
    assert rows == [(25,)]


def test_view_cannot_shadow_table(runner):
    with pytest.raises(ValueError, match="shadow"):
        runner.execute("create view nation as select 1 as x")


def test_prepare_of_execute_rejected(runner):
    with pytest.raises(ValueError, match="cannot prepare"):
        runner.execute("prepare p from execute p")


def test_describe_view(runner):
    runner.execute("create view dv as select n_name, n_nationkey + 1 as k "
                   "from nation")
    rows = runner.execute("describe dv").rows
    assert [r[0] for r in rows] == ["n_name", "k"]


def test_too_many_parameters(runner):
    runner.execute("prepare q7 from select ? as a")
    with pytest.raises(ValueError, match="expected 1 but found 3"):
        runner.execute("execute q7 using 1, 2, 3")


def test_or_replace_table_rejected(runner):
    from presto_tpu.sql.lexer import SqlSyntaxError
    with pytest.raises(SqlSyntaxError, match="OR REPLACE"):
        runner.execute("create or replace table memory.default.t "
                       "as select 1 as x")


def test_prepare_insert(runner):
    runner.execute("create table memory.default.pt as select 1 as x")
    runner.execute("prepare ins from "
                   "insert into memory.default.pt select ?")
    runner.execute("execute ins using 42")
    assert runner.execute(
        "select sum(x) from memory.default.pt").rows == [(43,)]

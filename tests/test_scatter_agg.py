"""Direct-address (scatter) grouped aggregation vs a NumPy oracle.

The scatter path must produce bit-identical results to a straightforward
host implementation for every supported aggregate, including NULL keys,
NULL inputs, dead rows, negative values, and out-of-span keys (ring-1
operator tests, the role of the reference's TestHashAggregationOperator
against expected pages)."""
import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, Schema
from presto_tpu.ops.aggregation import AggSpec
from presto_tpu.ops.scatter_agg import (
    grouped_aggregate_direct, segment_sum_exact, supported_direct,
)

import jax.numpy as jnp


def _batch(keys, key_valid, vals, val_valid, mask, vtype=T.BIGINT):
    n = len(keys)
    schema = Schema([("k", T.BIGINT), ("v", vtype)])
    dt = vtype.storage_dtype
    cols = [
        Column(T.BIGINT, jnp.asarray(keys, dtype=jnp.int64),
               jnp.asarray(key_valid, dtype=bool), None),
        Column(vtype, jnp.asarray(vals, dtype=dt),
               jnp.asarray(val_valid, dtype=bool), None),
    ]
    return Batch(schema, cols, jnp.asarray(mask, dtype=bool))


def test_segment_sum_exact_matches_int64():
    rng = np.random.default_rng(7)
    n, nseg = 4096, 64
    seg = rng.integers(0, nseg, size=n)
    vals = rng.integers(0, 1 << 37, size=n)
    got = np.asarray(segment_sum_exact(
        jnp.asarray(vals), jnp.asarray(seg.astype(np.int32)), nseg,
        max_rows_per_segment=n, value_bits=37))
    want = np.zeros(nseg, dtype=np.int64)
    np.add.at(want, seg, vals)
    assert (got == want).all()


def test_segment_sum_exact_wide_values_many_digits():
    rng = np.random.default_rng(8)
    n, nseg = 1024, 8
    seg = rng.integers(0, nseg, size=n)
    vals = rng.integers(0, 1 << 52, size=n)
    got = np.asarray(segment_sum_exact(
        jnp.asarray(vals), jnp.asarray(seg.astype(np.int32)), nseg,
        max_rows_per_segment=n, value_bits=52))
    want = np.zeros(nseg, dtype=np.int64)
    np.add.at(want, seg, vals)
    assert (got == want).all()


def _oracle(keys, key_valid, vals, val_valid, mask, fn):
    groups = {}
    for k, kv, v, vv, m in zip(keys, key_valid, vals, val_valid, mask):
        if not m:
            continue
        gk = int(k) if kv else None
        groups.setdefault(gk, []).append(int(v) if vv else None)
    out = {}
    for gk, items in groups.items():
        live = [x for x in items if x is not None]
        if fn == "count_star":
            out[gk] = len(items)
        elif fn == "count":
            out[gk] = len(live)
        elif fn == "sum":
            out[gk] = sum(live) if live else None
        elif fn == "avg":
            out[gk] = sum(live) / len(live) if live else None
        elif fn == "min":
            out[gk] = min(live) if live else None
        elif fn == "max":
            out[gk] = max(live) if live else None
    return out


@pytest.mark.parametrize("fn,outtype", [
    ("sum", T.BIGINT), ("count", T.BIGINT), ("count_star", T.BIGINT),
    ("min", T.BIGINT), ("max", T.BIGINT), ("avg", T.DOUBLE),
])
def test_direct_single_matches_oracle(fn, outtype):
    rng = np.random.default_rng(11)
    n, lo, span = 512, 5, 37
    keys = rng.integers(lo, lo + span, size=n)
    key_valid = rng.uniform(size=n) > 0.1
    vals = rng.integers(-1000, 1000, size=n)
    val_valid = rng.uniform(size=n) > 0.15
    mask = rng.uniform(size=n) > 0.2
    b = _batch(keys, key_valid, vals, val_valid, mask)
    aggs = [AggSpec(fn, None if fn == "count_star" else 1, outtype, "a")]
    out = grouped_aggregate_direct(b, 0, lo, span, aggs, mode="single")
    rows = {r[0]: r[1] for r in out.to_pylist()}
    want = _oracle(keys, key_valid, vals, val_valid, mask, fn)
    assert set(rows) == set(want), (sorted(rows), sorted(want))
    for gk, wv in want.items():
        gv = rows[gk]
        if wv is None:
            assert gv is None, (gk, gv)
        elif fn == "avg":
            assert abs(gv - wv) < 1e-9, (gk, gv, wv)
        else:
            assert gv == wv, (gk, gv, wv)


def test_direct_partial_merges_through_sort_path_final():
    """Partial states from the scatter path must merge with the sort
    path's final step (states are ordinary columns — the exchange
    contract)."""
    from presto_tpu.batch import concat_batches
    from presto_tpu.ops.aggregation import grouped_aggregate

    rng = np.random.default_rng(13)
    lo, span = 0, 16
    parts = []
    all_rows = []
    for chunk in range(3):
        n = 128
        keys = rng.integers(lo, lo + span, size=n)
        vals = rng.integers(0, 10_000, size=n)
        mask = rng.uniform(size=n) > 0.1
        all_rows += [(int(k), int(v)) for k, v, m
                     in zip(keys, vals, mask) if m]
        b = _batch(keys, np.ones(n, bool), vals, np.ones(n, bool), mask)
        parts.append(grouped_aggregate_direct(
            b, 0, lo, span,
            [AggSpec("sum", 1, T.BIGINT, "s"),
             AggSpec("avg", 1, T.DOUBLE, "m")],
            mode="partial", nonnegative=True))
    merged = grouped_aggregate(
        concat_batches(parts), [0],
        [AggSpec("sum", 1, T.BIGINT, "s"),
         AggSpec("avg", 1, T.DOUBLE, "m")], mode="final")
    got = {r[0]: (r[1], r[2]) for r in merged.to_pylist()}
    want_sum = {}
    want_cnt = {}
    for k, v in all_rows:
        want_sum[k] = want_sum.get(k, 0) + v
        want_cnt[k] = want_cnt.get(k, 0) + 1
    assert set(got) == set(want_sum)
    for k in want_sum:
        assert got[k][0] == want_sum[k]
        assert abs(got[k][1] - want_sum[k] / want_cnt[k]) < 1e-9


def test_direct_null_key_group_and_out_of_span():
    keys = [3, 3, None, None, 99]     # 99 out of span -> trash slot
    n = len(keys)
    b = _batch([k if k is not None else 0 for k in keys],
               [k is not None for k in keys],
               [10, 20, 5, 7, 1000], np.ones(n, bool), np.ones(n, bool))
    out = grouped_aggregate_direct(
        b, 0, 0, 10, [AggSpec("sum", 1, T.BIGINT, "s")], mode="single")
    rows = {r[0]: r[1] for r in out.to_pylist()}
    assert rows == {3: 30, None: 12}


def test_supported_direct():
    n = 4
    b = _batch([1] * n, np.ones(n, bool), [1] * n, np.ones(n, bool),
               np.ones(n, bool))
    assert supported_direct([AggSpec("sum", 1, T.BIGINT, "s")], b)
    assert supported_direct([AggSpec("count_star", None, T.BIGINT, "c")], b)
    fb = _batch([1] * n, np.ones(n, bool), [1.5] * n, np.ones(n, bool),
                np.ones(n, bool), vtype=T.DOUBLE)
    assert not supported_direct([AggSpec("sum", 1, T.DOUBLE, "s")], fb)
    assert not supported_direct(
        [AggSpec("var_samp", 1, T.DOUBLE, "v")], b)

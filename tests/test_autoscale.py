"""The elasticity control loop (ISSUE 20): rule-registry parity with
the reference watcher, and the controller's stability machinery —
hysteresis, cooldown, bounded steps, the PAGE-never-scale-down
invariant re-checked at apply time, drain-never-kill scale-down,
launch-before-drain node replacement, and coordinator-tier routing for
admission-bound groups. Every controller test drives ``evaluate`` tick
by tick with injected signals and a fake provider — no sockets, no
sleeps."""
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from presto_tpu.exec import autoscale
from presto_tpu.exec.autoscale import (AutoscaleController,
                                       AutoscalePolicy, NodeHandle,
                                       NodeProvider, decide,
                                       demo_signals)
from presto_tpu.obs.signals import (CacheSignals, ClusterSignals,
                                    GroupSignals, NodeSignals)


# -- the watcher is a shim over THE rule registry -----------------------------

def test_watcher_is_a_shim_over_the_controller_rules():
    """tools/autoscale_watch.py must re-export the controller's rule
    registry — same function objects, so the reference watcher and the
    control loop cannot drift."""
    import autoscale_watch as watch
    assert watch.decide is autoscale.decide
    assert watch.demo_signals is autoscale.demo_signals
    assert watch.RULES is autoscale.RULES


def test_rules_registry_covers_every_action():
    assert sorted(autoscale.RULES) == [
        "grow_cache", "replace_node", "scale_coordinator",
        "scale_down", "scale_up"]


def test_demo_signals_decision_contract():
    """The synthetic busy cluster fires every classic rule exactly as
    the watcher's ``--demo`` mode documents (same fixture the signals
    feed's contract test pins)."""
    decisions = decide(demo_signals())
    by_action = {}
    for d in decisions:
        by_action.setdefault(d["action"], []).append(d["target"])
    assert by_action["scale_up"] == ["serving.dash", "serving.adhoc"]
    assert by_action["scale_down"] == ["batch"]
    assert by_action["replace_node"] == ["w1"]
    assert by_action["grow_cache"] == ["scan_cache"]
    # the paging group may never be recommended down
    assert "serving.adhoc" not in by_action["scale_down"]


# -- controller fixtures ------------------------------------------------------

class FakeProvider(NodeProvider):
    """Ledger provider: every controller call is recorded, drains can
    be forced to fail, nothing real happens."""

    def __init__(self, n: int = 1):
        self._seq = 0
        self._handles = []
        self.calls = []
        self.drain_ok = True
        for _ in range(n):
            self.launch()
            self.calls.clear()

    def launch(self):
        self._seq += 1
        h = NodeHandle(f"w{self._seq}",
                       f"http://127.0.0.1:{7000 + self._seq}")
        self._handles.append(h)
        self.calls.append(("launch", h.node_id))
        return h

    def nodes(self):
        return list(self._handles)

    def drain(self, handle, timeout_s: float = 30.0):
        self.calls.append(("drain", handle.node_id))
        if self.drain_ok:
            self._handles.remove(handle)
        return self.drain_ok

    def terminate(self, handle):
        self.calls.append(("terminate", handle.node_id))
        if handle in self._handles:
            self._handles.remove(handle)


def _signals(groups=(), nodes=(), caches=None):
    return ClusterSignals(ts=0.0, groups=tuple(groups),
                          nodes=tuple(nodes),
                          caches=caches or CacheSignals())


def _busy(group="serving", queued=40, running=8, limit=8,
          alert="OK"):
    return GroupSignals(group=group, state="FULL", running=running,
                        queued=queued, hard_concurrency_limit=limit,
                        alert_state=alert)


def _idle(group="batch", alert="OK"):
    return GroupSignals(group=group, state="CAN_RUN", running=0,
                        queued=0, hard_concurrency_limit=16,
                        error_budget_remaining=1.0, alert_state=alert)


def _controller(provider, **policy):
    policy.setdefault("confirm_evals", 2)
    policy.setdefault("cooldown_s", 30.0)
    return AutoscaleController(provider,
                               AutoscalePolicy(**policy),
                               signals_fn=lambda: _signals())


# -- hysteresis / cooldown / bounds -------------------------------------------

def test_hysteresis_one_snapshot_moves_nothing():
    prov = FakeProvider(n=1)
    ctl = _controller(prov, confirm_evals=3)
    # busy node so scale_up fires; three confirmations required
    sig = _signals(groups=[_busy()],
                   nodes=[NodeSignals("w1", "active", 1.0, 4)])
    for tick in range(2):
        rep = ctl.evaluate(signals=sig, now=float(tick))
        assert rep["applied"] == []
        assert rep["blocked"][0]["blocked"] == "hysteresis"
        assert prov.calls == []
    rep = ctl.evaluate(signals=sig, now=2.0)
    assert [a["action"] for a in rep["applied"]] == ["scale_up"]
    assert ("launch", "w2") in prov.calls


def test_streak_resets_when_recommendation_stops():
    prov = FakeProvider(n=1)
    ctl = _controller(prov, confirm_evals=2)
    busy = _signals(groups=[_busy()])
    calm = _signals(groups=[_busy(queued=0, running=1)])
    ctl.evaluate(signals=busy, now=0.0)       # streak 1
    ctl.evaluate(signals=calm, now=1.0)       # streak wiped
    rep = ctl.evaluate(signals=busy, now=2.0)  # streak back to 1
    assert rep["applied"] == []
    assert prov.calls == []


def test_cooldown_spaces_applied_actions():
    prov = FakeProvider(n=1)
    ctl = _controller(prov, confirm_evals=1, cooldown_s=30.0,
                      max_workers=8)
    sig = _signals(groups=[_busy()])
    assert ctl.evaluate(signals=sig, now=0.0)["applied"]
    rep = ctl.evaluate(signals=sig, now=5.0)
    assert rep["applied"] == []
    assert rep["blocked"][0]["blocked"] == "cooldown"
    # past the cooldown the same confirmed decision applies again
    assert ctl.evaluate(signals=sig, now=31.0)["applied"]


def test_bounds_clamp_scale_up_and_down():
    prov = FakeProvider(n=2)
    ctl = _controller(prov, confirm_evals=1, cooldown_s=0.0,
                      min_workers=2, max_workers=2)
    up = _signals(groups=[_busy()])
    rep = ctl.evaluate(signals=up, now=0.0)
    assert rep["blocked"][0]["blocked"] == "bounds"
    down = _signals(groups=[_idle()])
    rep = ctl.evaluate(signals=down, now=1.0)
    assert rep["blocked"][0]["blocked"] == "bounds"
    assert prov.calls == []
    assert len(prov.nodes()) == 2


# -- the invariants -----------------------------------------------------------

def test_page_anywhere_holds_every_scale_down():
    """While ANY group pages, the cluster never shrinks — even a group
    the rules judged idle (the PR 16 invariant, re-checked at apply
    time, not just in the rules)."""
    prov = FakeProvider(n=3)
    ctl = _controller(prov, confirm_evals=1, cooldown_s=0.0)
    sig = _signals(groups=[_idle("batch"),
                           _busy("dash", alert="PAGE")])
    for tick in range(3):
        rep = ctl.evaluate(signals=sig, now=float(tick))
        down = [b for b in rep["blocked"]
                if b["action"] == "scale_down"]
        assert down and down[0]["blocked"] == "page-held"
    assert ("drain", "w1") not in prov.calls
    assert ("drain", "w2") not in prov.calls
    assert len(prov.nodes()) >= 3


def test_scale_down_is_always_a_drain_never_a_kill():
    prov = FakeProvider(n=3)
    ctl = _controller(prov, confirm_evals=1, cooldown_s=0.0)
    rep = ctl.evaluate(signals=_signals(groups=[_idle()]), now=0.0)
    assert [a["action"] for a in rep["applied"]] == ["scale_down"]
    kinds = {c[0] for c in prov.calls}
    assert kinds == {"drain"}, prov.calls


def test_stuck_drain_blocks_instead_of_escalating():
    """A drain that never confirms leaves the node serving — blocked
    as drain-failed, retried next tick, NEVER terminated."""
    prov = FakeProvider(n=3)
    prov.drain_ok = False
    ctl = _controller(prov, confirm_evals=1, cooldown_s=0.0)
    rep = ctl.evaluate(signals=_signals(groups=[_idle()]), now=0.0)
    assert rep["applied"] == []
    assert rep["blocked"][0]["blocked"] == "drain-failed"
    assert ("terminate", "w1") not in prov.calls
    assert len(prov.nodes()) == 3


def test_replace_node_launches_capacity_first():
    prov = FakeProvider(n=2)
    ctl = _controller(prov, confirm_evals=1, cooldown_s=0.0,
                      max_workers=8)
    sig = _signals(nodes=[NodeSignals("w1", "active", 120.0, 0)])
    rep = ctl.evaluate(signals=sig, now=0.0)
    assert [a["action"] for a in rep["applied"]] == ["replace_node"]
    # the replacement launched BEFORE the stale node drained out
    assert prov.calls.index(("launch", "w3")) \
        < prov.calls.index(("drain", "w1"))


def test_replace_node_terminates_only_a_corpse():
    prov = FakeProvider(n=2)
    prov.drain_ok = False
    ctl = _controller(prov, confirm_evals=1, cooldown_s=0.0)
    sig = _signals(nodes=[NodeSignals("w1", "active", 120.0, 0)])
    ctl.evaluate(signals=sig, now=0.0)
    # too dead to drain -> terminate IS the right tool (replacement of
    # a corpse, not scale-down)
    assert ("terminate", "w1") in prov.calls


def test_victim_selection_prefers_idle_nodes():
    prov = FakeProvider(n=3)
    ctl = _controller(prov, confirm_evals=1, cooldown_s=0.0)
    sig = _signals(groups=[_idle()],
                   nodes=[NodeSignals("w1", "active", 1.0, 5),
                          NodeSignals("w2", "active", 1.0, 0),
                          NodeSignals("w3", "active", 1.0, 2)])
    ctl.evaluate(signals=sig, now=0.0)
    assert ("drain", "w2") in prov.calls
    assert ("drain", "w1") not in prov.calls


# -- coordinator-tier routing -------------------------------------------------

def _admission_bound():
    return _signals(
        groups=[_busy(queued=40, running=8, limit=8)],
        nodes=[NodeSignals("w1", "active", 1.0, 0),
               NodeSignals("w2", "active", 1.0, 1)])


def test_admission_bound_routes_to_coordinator_scaler():
    class Scaler:
        reasons = []

        def scale_up(self, reason):
            self.reasons.append(reason)
            return True

    prov = FakeProvider(n=2)
    scaler = Scaler()
    ctl = AutoscaleController(prov, AutoscalePolicy(
        confirm_evals=1, cooldown_s=0.0, max_workers=2),
        signals_fn=lambda: _signals(), coordinator_scaler=scaler)
    rep = ctl.evaluate(signals=_admission_bound(), now=0.0)
    applied = {a["action"] for a in rep["applied"]}
    assert "scale_coordinator" in applied
    assert scaler.reasons and "admission-bound" in scaler.reasons[0]


def test_admission_bound_without_scaler_blocks():
    prov = FakeProvider(n=2)
    ctl = _controller(prov, confirm_evals=1, cooldown_s=0.0,
                      max_workers=2)
    rep = ctl.evaluate(signals=_admission_bound(), now=0.0)
    blocked = {b["action"]: b["blocked"] for b in rep["blocked"]}
    assert blocked["scale_coordinator"] == "no-scaler"


# -- observability ------------------------------------------------------------

def test_status_surface_reports_policy_and_streaks():
    prov = FakeProvider(n=1)
    ctl = _controller(prov, confirm_evals=3)
    ctl.evaluate(signals=_signals(groups=[_busy()]), now=0.0)
    st = ctl.status()
    assert st["running"] is False
    assert st["policy"]["confirmEvals"] == 3
    assert st["streaks"].get("scale_up:serving") == 1
    assert st["workers"][0]["nodeId"] == "w1"


def test_controller_actions_are_counted():
    from presto_tpu.obs.metrics import REGISTRY
    prov = FakeProvider(n=1)
    ctl = _controller(prov, confirm_evals=1, cooldown_s=0.0,
                      max_workers=8)
    before = REGISTRY.counter("autoscale_actions_total.scale_up").value
    ctl.evaluate(signals=_signals(groups=[_busy()]), now=0.0)
    after = REGISTRY.counter("autoscale_actions_total.scale_up").value
    assert after == before + 1

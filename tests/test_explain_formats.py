"""EXPLAIN (TYPE ...) / (FORMAT ...) variants and the web UI endpoints
(reference sql/planner/planprinter/: PlanPrinter, JsonRenderer,
GraphvizPrinter, IoPlanPrinter; webapp query console)."""
import json

import pytest


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.001)


Q = ("select l_returnflag, count(*) from lineitem, orders "
     "where l_orderkey = o_orderkey group by 1 order by 1")


def text_of(runner, sql):
    return "\n".join(r[0] for r in runner.execute(sql).rows)


def test_explain_distributed(runner):
    text = text_of(runner, f"explain (type distributed) {Q}")
    assert "Fragment 0" in text and "Fragment" in text
    assert "RemoteSource" in text
    assert "partition" in text or "single" in text


def test_explain_validate(runner):
    assert runner.execute(f"explain (type validate) {Q}").rows == [(True,)]
    with pytest.raises(Exception):
        runner.execute("explain (type validate) select nope from nation")


def test_explain_io(runner):
    doc = json.loads(text_of(runner, f"explain (type io) {Q}"))
    tables = {t["table"] for t in doc["inputTableColumnInfos"]}
    assert tables == {"lineitem", "orders"}


def test_explain_json(runner):
    doc = json.loads(text_of(runner, f"explain (format json) {Q}"))
    assert doc["name"] == "Output"
    assert doc["children"]

    def names(n):
        yield n["name"]
        for c in n["children"]:
            yield from names(c)
    assert "TableScan" in set(names(doc))


def test_explain_graphviz(runner):
    text = text_of(runner, f"explain (format graphviz) {Q}")
    assert text.startswith("digraph") and "->" in text


def test_explain_analyze_rejects_options(runner):
    with pytest.raises(ValueError, match="ANALYZE"):
        runner.execute("explain (type distributed) analyze select 1")
    with pytest.raises(ValueError, match="ANALYZE"):
        runner.execute("explain (format json) analyze select 1")


def test_explain_distributed_includes_init_plans(runner):
    text = text_of(runner, "explain (type distributed) "
                           "select n_name, (select max(r_regionkey) "
                           "from region) mx from nation")
    assert "InitPlan" in text and "region" in text


def test_explain_default_unchanged(runner):
    text = text_of(runner, f"explain {Q}")
    assert "Output" in text and "TableScan" in text


def test_ui_endpoints(runner):
    import urllib.request

    from presto_tpu.server.protocol import PrestoTpuServer
    srv = PrestoTpuServer(runner=runner)
    srv.start()
    try:
        runner.execute("select 1")
        base = f"http://127.0.0.1:{srv.port}"
        qs = json.loads(urllib.request.urlopen(base + "/v1/query").read())
        assert qs and {"queryId", "state", "query",
                       "elapsedMs"} <= set(qs[0])
        html = urllib.request.urlopen(base + "/ui").read().decode()
        assert "presto-tpu" in html and "/v1/query" in html
    finally:
        srv.stop()

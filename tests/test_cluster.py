"""Multi-host task runtime: fragments over HTTP workers vs LocalRunner.

Ring-3-style coverage of the DCN path (reference
presto-tests/.../DistributedQueryRunner.java boots N in-process servers
and runs the generic query suites against them): real WorkerServers on
real sockets, the full coordinator scheduling path, page wire format,
token/ack output buffers, heartbeat failure detection, and graceful
shutdown."""
import sys

import pytest

# tier-1 budget: excluded from `pytest -m 'not slow'` — boots multi-worker HTTP clusters per fixture
# (see tools/check_tier1_time.py; ~152s)
pytestmark = pytest.mark.slow

sys.path.insert(0, ".")
from tpch_queries import Q as TPCH_QUERIES  # noqa: E402

from presto_tpu.exec.cluster import (  # noqa: E402
    ClusterRunner, HeartbeatFailureDetector, QueryFailedError,
)
from presto_tpu.exec.runner import LocalRunner  # noqa: E402
from presto_tpu.server.worker import WorkerServer  # noqa: E402

SF = 0.01


@pytest.fixture(scope="module")
def cluster():
    workers = [WorkerServer(tpch_sf=SF) for _ in range(2)]
    for w in workers:
        w.start()
    urls = [f"http://127.0.0.1:{w.port}" for w in workers]
    runner = ClusterRunner(urls, tpch_sf=SF, heartbeat=False)
    yield runner, workers
    for w in workers:
        w.stop()


def check(runner: ClusterRunner, sql: str, rel=1e-6):
    want = runner.local.execute(sql).rows
    got = runner.execute(sql).rows
    assert len(got) == len(want), (sql, len(got), len(want))
    for gr, wr in zip(got, want):
        for gv, wv in zip(gr, wr):
            if isinstance(wv, float):
                assert gv == pytest.approx(wv, rel=rel, abs=1e-9), (gr, wr)
            else:
                assert gv == wv, (gr, wr)


CLUSTER_TPCH = [t for t in TPCH_QUERIES
                if t[0] in ("q1", "q3", "q4", "q5", "q6", "q12", "q13",
                            "q14", "q19")]


@pytest.mark.parametrize("name,sql,_o", CLUSTER_TPCH,
                         ids=[t[0] for t in CLUSTER_TPCH])
def test_tpch_cluster(cluster, name, sql, _o):
    runner, _ = cluster
    check(runner, sql)


BASICS = [
    "select count(*) from lineitem",
    "select o_orderstatus, count(*), sum(o_totalprice) from orders "
    "group by 1 order by 1",
    "select distinct l_shipmode from lineitem order by 1",
    "select o_orderkey, o_totalprice from orders "
    "order by o_totalprice desc limit 5",
    "select n_name from nation union select r_name from region "
    "order by 1 limit 8",
    "select o_custkey, row_number() over (partition by o_custkey "
    "order by o_orderkey) rn from orders order by 1, 2 limit 20",
    "select max(o_totalprice) from orders where o_totalprice < "
    "(select avg(o_totalprice) from orders)",
    "select o_orderpriority, count(*) from orders where exists "
    "(select 1 from lineitem where l_orderkey = o_orderkey) "
    "group by 1 order by 1",
    "select stddev(l_quantity), var_pop(l_extendedprice) from lineitem",
    "select o_orderstatus, count(distinct o_custkey) c, count(*) n "
    "from orders group by 1 order by 1",
]


@pytest.mark.parametrize("sql", BASICS, ids=range(len(BASICS)))
def test_basics_cluster(cluster, sql):
    runner, _ = cluster
    check(runner, sql)


def test_partitioned_join_cluster(cluster):
    """Force repartitioned joins (no broadcast): both sides hash-exchange
    by join key into a fixed stage."""
    runner, _ = cluster
    runner.session.properties["broadcast_join_row_limit"] = 0
    try:
        check(runner, "select c_mktsegment, count(*) c, "
                      "sum(o_totalprice) s from customer, orders "
                      "where c_custkey = o_custkey group by 1 order by 1")
        check(runner, TPCH_QUERIES[[t[0] for t in TPCH_QUERIES]
                                   .index("q3")][1])
    finally:
        del runner.session.properties["broadcast_join_row_limit"]


def test_task_failure_surfaces(cluster):
    """A task hitting a runtime error reports FAILED and poisons its
    result buffer (reference TaskStateMachine -> failed task status)."""
    import json
    import time
    import urllib.error
    import urllib.request
    from presto_tpu.connectors.spi import TableHandle
    from presto_tpu.planner.codec import encode
    from presto_tpu.planner.plan import TableScanNode
    from presto_tpu.sql.analyzer import Field
    from presto_tpu import types as T
    _, workers = cluster
    url = f"http://127.0.0.1:{workers[0].port}"
    bad = TableScanNode(catalog="tpch",
                        table=TableHandle("tpch", "t", "nope"),
                        columns=("x",),
                        fields=(Field("x", T.BIGINT),))
    doc = {"fragment": encode(bad),
           "output": {"kind": "single", "n_buffers": 1},
           "splits": [encode(__import__(
               "presto_tpu.connectors.spi", fromlist=["Split"]
           ).Split(TableHandle("tpch", "t", "nope"), (0, 1)))]}
    req = urllib.request.Request(f"{url}/v1/task/failing.0.0",
                                 method="PUT",
                                 data=json.dumps(doc).encode())
    with urllib.request.urlopen(req, timeout=10) as resp:
        json.loads(resp.read())
    deadline = time.time() + 20
    state = None
    while time.time() < deadline:
        with urllib.request.urlopen(f"{url}/v1/task/failing.0.0",
                                    timeout=5) as resp:
            st = json.loads(resp.read())
        state = st["state"]
        if state == "FAILED":
            assert "nope" in (st["error"] or "")
            break
        time.sleep(0.2)
    assert state == "FAILED"
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"{url}/v1/task/failing.0.0/results/0/0", timeout=5)


def test_failure_detector_excludes_dead_worker(cluster):
    runner, workers = cluster
    dead_url = "http://127.0.0.1:1"   # nothing listens there
    det = HeartbeatFailureDetector(
        [f"http://127.0.0.1:{workers[0].port}", dead_url],
        max_consecutive=1)
    assert det.ping(det.urls[0])
    assert not det.ping(dead_url)
    det.failures[dead_url] = 1
    assert det.active() == [det.urls[0]]


def test_no_active_workers_fails_fast():
    runner = ClusterRunner(["http://127.0.0.1:1"], tpch_sf=SF,
                           heartbeat=False)
    runner.detector.failures["http://127.0.0.1:1"] = 99
    with pytest.raises(QueryFailedError, match="no active workers"):
        runner.execute("select count(*) from nation")


def test_graceful_shutdown_drains():
    import json
    import time
    import urllib.request
    w = WorkerServer(tpch_sf=SF)
    w.start()
    url = f"http://127.0.0.1:{w.port}"
    runner = ClusterRunner([url], tpch_sf=SF, heartbeat=False)
    assert runner.execute("select count(*) from nation").rows == [(25,)]
    req = urllib.request.Request(f"{url}/v1/info/state", method="PUT",
                                 data=json.dumps("SHUTTING_DOWN").encode())
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert json.loads(resp.read())["state"] == "SHUTTING_DOWN"
    # new tasks are refused while draining
    with pytest.raises(Exception):
        runner.execute("select count(*) from region")
    # the server stops once active tasks drain
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(f"{url}/v1/info", timeout=2)
            time.sleep(0.2)
        except Exception:
            break
    else:
        pytest.fail("worker did not stop after drain")


def test_fragmenter_shapes():
    """Q3 with forced partitioned joins: scans feed hash exchanges, the
    aggregation splits into partial+final, the root is single."""
    from presto_tpu.planner.fragmenter import fragment_plan
    from presto_tpu.planner.plan import AggregationNode, RemoteSourceNode
    lr = LocalRunner(tpch_sf=SF)
    lr.session.properties["broadcast_join_row_limit"] = 0
    sql = [t[1] for t in TPCH_QUERIES if t[0] == "q3"][0]
    fp = fragment_plan(lr.plan(sql).root)
    kinds = [f.partitioning for f in fp.fragments]
    assert kinds.count("source") == 3          # lineitem, orders, customer
    assert kinds[-1] == "single"
    steps = [n.step for f in fp.fragments
             for n in _walk(f.root) if isinstance(n, AggregationNode)]
    assert sorted(steps) == ["final", "partial"]
    outs = {f.output.kind for f in fp.fragments if f.output}
    assert "partition" in outs
    # every RemoteSourceNode references an existing upstream fragment
    ids = {f.id for f in fp.fragments}
    for f in fp.fragments:
        for n in _walk(f.root):
            if isinstance(n, RemoteSourceNode):
                assert set(n.fragment_ids) <= ids


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


def test_page_serde_roundtrip():
    import datetime
    from presto_tpu import types as T
    from presto_tpu.batch import Batch
    from presto_tpu.exec.pages import deserialize_page, serialize_page
    b = Batch.from_pydict({
        "a": (T.BIGINT, [1, None, 3]),
        "s": (T.VARCHAR, ["x", None, "yy"]),
        "d": (T.DOUBLE, [1.5, -0.0, None]),
        "b": (T.BOOLEAN, [True, None, False]),
        "dt": (T.DATE, [datetime.date(1994, 1, 1), None,
                        datetime.date(2000, 2, 29)]),
        "dec": (T.DecimalType(10, 2), ["3.14", "-2.50", None]),
    })
    assert deserialize_page(serialize_page(b)).to_pylist() == b.to_pylist()
    assert deserialize_page(
        serialize_page(b, compress=False)).to_pylist() == b.to_pylist()


def test_plan_codec_roundtrip():
    import json
    from presto_tpu.planner.codec import decode, encode
    lr = LocalRunner(tpch_sf=SF)
    for sql in [
        "select l_returnflag, sum(l_quantity) from lineitem "
        "where l_shipdate >= date '1994-01-01' group by 1 order by 1",
        "select o_orderkey, n_name from orders, customer, nation "
        "where o_custkey = c_custkey and c_nationkey = n_nationkey "
        "limit 5",
        "select r_name, (select count(*) from nation) c from region",
    ]:
        plan = lr.plan(sql)
        assert decode(json.loads(json.dumps(encode(plan.root)))) \
            == plan.root


def test_request_retries_transient_failures(monkeypatch):
    """One transient socket error must not fail the query: _request
    retries with backoff (reference server/remotetask/
    RequestErrorTracker.java)."""
    import urllib.error

    from presto_tpu.exec.cluster import ClusterRunner

    calls = {"n": 0}

    class _Resp:
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

        def read(self):
            return b'{"ok": true}'

    def flaky_open(req, timeout=None):
        calls["n"] += 1
        if calls["n"] < 3:
            raise urllib.error.URLError("connection refused")
        return _Resp()

    runner = ClusterRunner.__new__(ClusterRunner)  # no workers needed
    monkeypatch.setattr("urllib.request.urlopen", flaky_open)
    monkeypatch.setattr(ClusterRunner, "REQUEST_BACKOFF_S", 0.001)
    out = runner._request("http://127.0.0.1:1/v1/task/x")
    assert out == {"ok": True} and calls["n"] == 3


def test_request_gives_up_after_budget(monkeypatch):
    import urllib.error

    import pytest as _pytest

    from presto_tpu.exec.cluster import ClusterRunner, QueryFailedError

    def always_down(req, timeout=None):
        raise urllib.error.URLError("connection refused")

    runner = ClusterRunner.__new__(ClusterRunner)
    monkeypatch.setattr("urllib.request.urlopen", always_down)
    monkeypatch.setattr(ClusterRunner, "REQUEST_BACKOFF_S", 0.001)
    with _pytest.raises(QueryFailedError, match="after 5 attempts"):
        runner._request("http://127.0.0.1:1/v1/task/x")

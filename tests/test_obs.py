"""Observability: span tracing, metrics registry, system.runtime SQL,
Chrome-trace export.

Covers the obs/ subsystem end to end: span nesting + distributed
stitching across a real ClusterRunner (coordinator + worker spans share
one trace with consistent query/stage/task ids), metrics counters after
TPC-H-shaped runs, the system.runtime.{queries,tasks,metrics} tables,
and Chrome-trace JSON schema validity.
"""
import json

import pytest

from presto_tpu.exec.runner import LocalRunner
from presto_tpu.obs.metrics import REGISTRY, TASKS, MetricsRegistry, \
    attach_event_listeners
from presto_tpu.obs.trace import NOOP_SPAN, TRACER, Tracer, chrome_trace, \
    write_chrome_trace


@pytest.fixture
def tracing():
    """Enable the global tracer for one test, restore after."""
    was = TRACER.enabled
    TRACER.enable(True)
    yield TRACER
    TRACER.enable(was)


# -- tracer core -------------------------------------------------------------

def test_disabled_tracer_is_noop():
    t = Tracer(node="t0")
    assert t.enabled is False
    s = t.span("anything", x=1)
    assert s is NOOP_SPAN
    with s:
        pass
    assert t.export() == []
    assert t.context() is None


def test_span_nesting_and_context():
    t = Tracer(node="t1")
    t.enable(True)
    with t.span("query", query_id="q1") as q:
        ctx = t.context()
        assert ctx == {"traceId": q.trace_id, "spanId": q.span_id}
        with t.span("plan"):
            pass
        with t.span("stage", stage_id=0) as st:
            assert st.parent_id == q.span_id
    spans = t.export()
    assert [s["name"] for s in spans] == ["plan", "stage", "query"]
    by_name = {s["name"]: s for s in spans}
    assert by_name["plan"]["parentId"] == by_name["query"]["spanId"]
    assert len({s["traceId"] for s in spans}) == 1
    assert all(s["end"] >= s["start"] for s in spans)


def test_task_span_stitches_wire_context():
    t = Tracer(node="t2")
    t.enable(True)
    with t.span("query") as q:
        ctx = t.context()
    with t.task_span(ctx, "task", task_id="q.0.0"):
        pass
    spans = {s["name"]: s for s in t.export()}
    assert spans["task"]["traceId"] == q.trace_id
    assert spans["task"]["parentId"] == q.span_id


def test_import_spans_dedupes():
    t = Tracer(node="t3")
    t.enable(True)
    with t.span("a"):
        pass
    spans = t.export()
    assert t.import_spans(spans) == 0          # already present
    foreign = dict(spans[0], spanId="other.1", name="b")
    assert t.import_spans([foreign]) == 1
    assert len(t.export()) == 2


def test_wrap_iter_records_batches():
    t = Tracer(node="t4")
    t.enable(True)
    out = list(t.wrap_iter("op:Scan", iter([1, 2, 3])))
    assert out == [1, 2, 3]
    (span,) = t.export()
    assert span["name"] == "op:Scan"
    assert span["attrs"]["batches"] == 3


# -- metrics registry --------------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("c_total").inc()
    reg.counter("c_total").inc(2)
    reg.gauge("g").max_update(5)
    reg.gauge("g").max_update(3)          # high-water keeps 5
    reg.histogram("h").observe(1.0)
    reg.histogram("h").observe(3.0)
    rows = {r["name"]: r for r in reg.snapshot()}
    assert rows["c_total"]["value"] == 3
    assert rows["g"]["value"] == 5
    assert rows["h.count"]["value"] == 2
    assert rows["h.sum"]["value"] == 4.0
    assert rows["h.min"]["value"] == 1.0
    assert rows["h.max"]["value"] == 3.0


def test_event_listener_sink():
    from presto_tpu.events import (EventListenerManager,
                                   SplitCompletedEvent, completed_event)
    import time as _t
    reg = MetricsRegistry()
    ev = EventListenerManager()
    attach_event_listeners(ev, reg)
    ev.query_completed(completed_event(
        "q1", "select 1", "u", "FINISHED", _t.perf_counter()))
    ev.split_completed(SplitCompletedEvent("q1", "t", 0, 1.5, 4))
    rows = {r["name"]: r["value"] for r in reg.snapshot()}
    assert rows["queries_finished_total"] == 1
    assert rows["splits_completed_total"] == 1
    assert rows["split_batches_total"] == 4


# -- engine integration ------------------------------------------------------

@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=0.001)


def test_metrics_after_query(runner):
    before = {r["name"]: r["value"] for r in REGISTRY.snapshot()}
    runner.execute(
        "select l_returnflag, sum(l_quantity) from lineitem "
        "group by l_returnflag")
    after = {r["name"]: r["value"] for r in REGISTRY.snapshot()}
    assert after["queries_started_total"] > \
        before.get("queries_started_total", 0)
    assert after["queries_finished_total"] > \
        before.get("queries_finished_total", 0)
    assert after.get("operator_batches_total.tablescan", 0) > \
        before.get("operator_batches_total.tablescan", 0)
    assert after.get("scheduler_quanta_total", 0) > \
        before.get("scheduler_quanta_total", 0)


def test_system_runtime_queries_group_by_state(runner):
    runner.execute("select 1")
    res = runner.execute(
        "select state, count(*) from system.runtime.queries "
        "group by state")
    states = {r[0]: r[1] for r in res.rows}
    assert states.get("FINISHED", 0) >= 1
    assert "RUNNING" in states              # the in-flight query itself


def test_system_runtime_queries_user_and_error(runner):
    runner.execute("select 2", user="alice")
    with pytest.raises(Exception):
        runner.execute("select nope from nation", user="bob")
    res = runner.execute(
        "select query, user, error from system.runtime.queries")
    by_query = {r[0]: (r[1], r[2]) for r in res.rows}
    assert by_query["select 2"][0] == "alice"
    assert by_query["select nope from nation"][0] == "bob"
    assert by_query["select nope from nation"][1]   # error populated


def test_system_runtime_metrics_table(runner):
    runner.execute("select count(*) from nation")
    res = runner.execute(
        "select name, kind, value from system.runtime.metrics "
        "where name = 'queries_started_total'")
    assert len(res.rows) == 1
    name, kind, value = res.rows[0]
    assert kind == "counter" and value >= 1


def test_system_table_count_star_matches_count_col(runner):
    """count(*) over a system table prunes every column, leaving the
    connector page source with nothing to ship — the batch must still
    carry the row count. Regression: count(*) returned 0 while
    count(col) was correct."""
    runner.execute("select count(*) from nation")     # populate metrics
    for table, col in [("system.runtime.metrics", "name"),
                       ("system.runtime.mesh_rounds", "query_id")]:
        star = runner.execute(
            f"select count(*) from {table}").rows[0][0]
        by_col = runner.execute(
            f"select count({col}) from {table}").rows[0][0]
        assert star == by_col, (table, star, by_col)
        if table == "system.runtime.metrics":
            assert star > 0


def test_query_span_tree(runner, tracing):
    runner.execute("select count(*) from nation")
    spans = TRACER.export()
    queries = [s for s in spans if s["name"] == "query"]
    assert queries, "query span missing"
    q = queries[-1]
    tree = [s for s in spans if s["traceId"] == q["traceId"]]
    names = {s["name"] for s in tree}
    assert "plan" in names
    assert any(n.startswith("op:") for n in names)
    ids = {s["spanId"] for s in tree}
    assert all(s["parentId"] in ids for s in tree
               if s["parentId"] is not None)


def test_explain_analyze_trace_section(runner, tracing):
    res = runner.execute("explain analyze select count(*) from nation")
    text = "\n".join(r[0] for r in res.rows)
    assert "Trace (spans by name):" in text
    assert "op:" in text


def test_explain_analyze_no_trace_section_when_disabled(runner):
    assert not TRACER.enabled
    res = runner.execute("explain analyze select count(*) from nation")
    text = "\n".join(r[0] for r in res.rows)
    assert "Trace (spans by name):" not in text


# -- distributed stitching ---------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.server.worker import WorkerServer
    workers = [WorkerServer(tpch_sf=0.001) for _ in range(2)]
    for w in workers:
        w.start()
    urls = [f"http://127.0.0.1:{w.port}" for w in workers]
    runner = ClusterRunner(urls, tpch_sf=0.001, heartbeat=False)
    yield runner, workers
    for w in workers:
        w.stop()


def test_distributed_trace_stitches(cluster, tracing):
    runner, workers = cluster
    res = runner.execute(
        "select l_returnflag, count(*), sum(l_quantity) from lineitem "
        "group by l_returnflag order by l_returnflag")
    assert len(res.rows) == 3
    spans = TRACER.export()
    q = [s for s in spans if s["name"] == "query"][-1]
    tree = [s for s in spans if s["traceId"] == q["traceId"]]
    qid = q["attrs"]["query_id"]
    stages = [s for s in tree if s["name"] == "stage"]
    tasks = [s for s in tree if s["name"] == "task"]
    assert stages and tasks
    # consistent ids: every stage/task span carries the query id, task
    # ids embed it, and parent links resolve within the trace
    assert all(s["attrs"]["query_id"] == qid for s in stages + tasks)
    assert all(s["attrs"]["task_id"].startswith(qid + ".")
               for s in tasks)
    stage_ids = {s["attrs"]["stage_id"] for s in stages}
    assert {t["attrs"]["stage_id"] for t in tasks} <= stage_ids
    ids = {s["spanId"] for s in tree}
    assert all(s["parentId"] in ids for s in tree
               if s["parentId"] is not None)
    # both workers contributed spans
    nodes = {t["attrs"]["node_id"] for t in tasks}
    assert len(nodes) == 2
    # worker-side operator spans rode along (in-process workers share
    # the ring; cross-process they arrive via the span harvest)
    assert any(s["name"].startswith("op:") for s in tree)


def test_system_runtime_tasks_after_cluster_query(cluster):
    runner, _ = cluster
    runner.execute("select count(*) from nation")
    rows = TASKS.snapshot()
    assert rows, "task registry empty after cluster query"
    assert all(t["state"] in ("PLANNED", "RUNNING", "FINISHED",
                              "FAILED", "ABORTED") for t in rows)
    res = runner.local.execute(
        "select task_id, query_id, state from system.runtime.tasks "
        "where state = 'FINISHED'")
    assert res.rows
    tid, qid, _ = res.rows[0]
    assert tid.startswith(qid + ".")


# -- Chrome-trace export -----------------------------------------------------

def test_chrome_trace_schema(tmp_path, tracing):
    TRACER.clear()
    with TRACER.span("query", query_id="qx") as q:
        with TRACER.span("op:Scan"):
            pass
    path = write_chrome_trace(
        str(tmp_path / "trace.json"), TRACER.export(q.trace_id))
    doc = json.load(open(path))
    assert isinstance(doc["traceEvents"], list)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    ms = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(xs) == 2 and ms
    for e in xs:
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["args"]["traceId"] == q.trace_id
    # parent/child linkage preserved in args
    by_name = {e["name"]: e for e in xs}
    assert by_name["op:Scan"]["args"]["parentId"] == \
        by_name["query"]["args"]["spanId"]


def test_chrome_trace_empty():
    assert chrome_trace([]) == {"traceEvents": [],
                                "displayTimeUnit": "ms"}


def test_cli_trace_out(tmp_path):
    from presto_tpu.cli import main
    out = tmp_path / "cli_trace.json"
    rc = main(["--execute", "select count(*) from nation",
               "--sf", "0.001", "--trace-out", str(out)])
    try:
        assert rc == 0
        doc = json.load(open(out))
        names = {e["name"] for e in doc["traceEvents"]}
        assert "query" in names
    finally:
        TRACER.enable(False)

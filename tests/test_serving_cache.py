"""ISSUE 13: parameter-generic plan templates + versioned result cache.

Covers the serving-cache stack end to end: template fingerprinting and
binding (one plan + one warm executable set across a fleet of
bindings), optimizer guards with per-binding fallback, the
result/subplan cache's hit / partial (append-only incremental
maintenance) / invalidation / veto semantics, admission-slot release on
the hit fast path, and the cross-session parse-cache regression.
"""
import tempfile

import pytest

from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.orc import OrcConnector
from presto_tpu.connectors.spi import CatalogManager
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.obs.metrics import REGISTRY

TPROPS = {"plan_template_cache": True}
RPROPS = {"result_cache": True}


def _metric(name: str) -> float:
    for m in REGISTRY.snapshot():
        if m["name"] == name:
            return m["value"]
    return 0.0


@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=0.01)


@pytest.fixture()
def file_runner():
    tmp = tempfile.mkdtemp()
    cats = CatalogManager()
    cats.register("tpch", TpchConnector(sf=0.01))
    cats.register("memory", MemoryConnector())
    cats.register("orc", OrcConnector(tmp))
    return LocalRunner(catalogs=cats, catalog="tpch")


# -- plan templates -----------------------------------------------------------

def test_template_parity_across_bindings(runner):
    """Row-exact parity: the same statement shape with different
    literals returns identical rows under the template cache, serving
    N bindings from ONE optimized plan."""
    sqls = ["select count(*), sum(l_extendedprice) from lineitem "
            f"where l_quantity > {q}" for q in (10, 20, 30)]
    cold = [runner.execute(s).rows for s in sqls]
    h0, m0 = (_metric("plan_template_cache_hit_total"),
              _metric("plan_template_cache_miss_total"))
    warm = [runner.execute(s, properties=TPROPS).rows for s in sqls]
    assert warm == cold
    assert _metric("plan_template_cache_miss_total") - m0 == 1
    assert _metric("plan_template_cache_hit_total") - h0 == 2


def test_template_shares_compiled_kernels(runner):
    """The whole point: a new binding re-dispatches the SAME traced
    executable — the expression compile cache must not grow."""
    from presto_tpu.expr.compiler import _DEFAULT
    sql = ("select l_returnflag, count(*) from lineitem "
           "where l_discount between 0.0%d and 0.08 "
           "group by l_returnflag order by l_returnflag")
    cold = [runner.execute(sql % d).rows for d in (1, 2, 3)]
    runner.execute(sql % 1, properties=TPROPS)       # template build
    before = len(_DEFAULT._cache)
    warm = [runner.execute(sql % d, properties=TPROPS).rows
            for d in (1, 2, 3)]
    assert warm == cold
    assert len(_DEFAULT._cache) == before


def test_execute_fleet_parity(runner):
    """EXECUTE with different bindings rides one template."""
    runner.execute("prepare fleet_q from select count(*) from lineitem "
                   "where l_quantity > ?")
    h0 = _metric("plan_template_cache_hit_total")
    got = [runner.execute(f"execute fleet_q using {q}",
                          properties=TPROPS).rows for q in (5, 15, 25)]
    want = [runner.execute(
        f"select count(*) from lineitem where l_quantity > {q}").rows
        for q in (5, 15, 25)]
    assert got == want
    assert _metric("plan_template_cache_hit_total") - h0 >= 2


def test_guard_fallback_on_flipped_pushdown_literal(runner):
    """A DATE range literal feeds scan-pushdown bounds: the template
    records an equality guard, a binding that flips it falls back to
    the per-binding fingerprint — with correct rows either way."""
    s1 = ("select count(*) from lineitem "
          "where l_shipdate <= date '1998-09-02'")
    s2 = ("select count(*) from lineitem "
          "where l_shipdate <= date '1997-09-02'")
    c1, c2 = runner.execute(s1).rows, runner.execute(s2).rows
    assert runner.execute(s1, properties=TPROPS).rows == c1
    g0 = _metric("plan_template_cache_guard_fallback_total")
    # same binding again: guard holds, template serves
    assert runner.execute(s1, properties=TPROPS).rows == c1
    assert _metric("plan_template_cache_guard_fallback_total") == g0
    # flipped binding: guard miss -> per-binding fallback, right rows
    assert runner.execute(s2, properties=TPROPS).rows == c2
    assert _metric("plan_template_cache_guard_fallback_total") == g0 + 1


def test_template_plans_keep_pushdown_quality(runner):
    """The guarded consult keeps literal-derived scan pushdown on the
    template plan (the bound would vanish if Params were opaque to
    the pushdown extractor)."""
    from presto_tpu.serving.template import parameterize
    from presto_tpu.serving.plancache import parse_cached
    from presto_tpu.planner.optimizer import optimize
    from presto_tpu.planner.planner import plan_query
    from presto_tpu.planner.plan import TableScanNode
    stmt = parse_cached("select count(*) from lineitem "
                        "where l_shipdate <= date '1998-09-02'")
    _t, marked, values = parameterize(stmt)
    assert values                      # the date hole-punched
    plan = optimize(plan_query(marked, runner.session), runner.session)

    def scans(n):
        if isinstance(n, TableScanNode):
            yield n
        for c in n.children:
            yield from scans(c)
    [scan] = list(scans(plan.root))
    assert any(name == "l_shipdate" and hi is not None
               for name, _lo, hi in scan.pushdown)


def test_template_mix_of_kinds(runner):
    """BIGINT / DOUBLE / short-DECIMAL / DATE literals parameterize;
    kind is part of the key so 5 and 5.0 never share a template."""
    from presto_tpu.serving.template import parameterize
    from presto_tpu.serving.plancache import parse_cached
    a = parameterize(parse_cached(
        "select 1 from lineitem where l_quantity > 5"))
    b = parameterize(parse_cached(
        "select 1 from lineitem where l_quantity > 5.0"))
    assert a[0] != b[0]                # different template ASTs
    assert a[2] == {0: 5} and b[2] == {0: 5.0}
    # LIMIT counts and GROUP BY ordinals never hole-punch
    t, _m, v = parameterize(parse_cached(
        "select l_returnflag, count(*) from lineitem "
        "group by 1 order by 1 limit 3"))
    assert v == {}


def test_parse_cache_does_not_leak_across_sessions():
    """ISSUE 13 satellite: parse_cached keys on TEXT only; resolution
    happens at plan time, so two sessions with different default
    catalog/schema share the parsed AST but NOT the plan — the
    fingerprint (which folds catalog/schema/connector identities in)
    is what separates them."""
    from presto_tpu.serving.plancache import PlanCache, parse_cached
    from presto_tpu.batch import Batch, Schema
    from presto_tpu import types as T

    def mem_runner(vals):
        cats = CatalogManager()
        mem = MemoryConnector()
        cats.register("memory", mem)
        cats.register("tpch", TpchConnector(sf=0.001))
        r = LocalRunner(catalogs=cats, catalog="memory")
        schema = Schema([("x", T.BIGINT)])
        mem.create_table("t", schema)
        mem.append("t", Batch.from_pydict({"x": (T.BIGINT, vals)}))
        return r

    r1, r2 = mem_runner([1, 2, 3]), mem_runner([10, 20])
    sql = "select sum(x) s from t"
    # one parsed AST object serves both sessions
    assert parse_cached(sql) is parse_cached(sql)
    stmt = parse_cached(sql)
    assert PlanCache.fingerprint(stmt, r1.session) != \
        PlanCache.fingerprint(stmt, r2.session)
    # and (with every cache enabled) each session sees its own table
    props = {**TPROPS, **RPROPS}
    assert r1.execute(sql, properties=props).rows == [(6,)]
    assert r2.execute(sql, properties=props).rows == [(30,)]
    assert r1.execute(sql, properties=props).rows == [(6,)]


# -- result cache -------------------------------------------------------------

def test_result_cache_hit_and_write_invalidation(file_runner):
    """Eager invalidation rides spi.notify_data_change for memory,
    sqlite and filebase writes — the same path the plan cache uses."""
    import os
    from presto_tpu.connectors.sqlite import SqliteConnector
    r = file_runner
    tmp = tempfile.mkdtemp()
    r.session.catalogs.register(
        "sqlite", SqliteConnector(os.path.join(tmp, "db.sqlite")))
    cases = [
        ("memory", "select count(*) c, sum(q) s from memory.t"),
        ("sqlite", "select count(*) c, sum(q) s from sqlite.t"),
        ("orc", "select count(*) c, sum(q) s from orc.t"),
    ]
    for cat, _ in cases:
        r.execute(f"create table {cat}.t as select l_orderkey k, "
                  "l_quantity q from lineitem where l_orderkey < 100")
    for cat, sql in cases:
        h0 = _metric("result_cache_hit_total")
        a = r.execute(sql, properties=RPROPS).rows
        b = r.execute(sql, properties=RPROPS).rows
        assert a == b
        assert _metric("result_cache_hit_total") == h0 + 1
        i0 = _metric("result_cache_invalidated_total")
        r.execute(f"insert into {cat}.t select l_orderkey k, "
                  "l_quantity q from lineitem "
                  "where l_orderkey between 100 and 150")
        if cat != "orc":
            # filebase appends stay resident for incremental
            # maintenance; the others must drop eagerly, BEFORE the
            # next lookup
            assert _metric("result_cache_invalidated_total") > i0
        c = r.execute(sql, properties=RPROPS).rows
        assert c == r.execute(sql).rows
        assert c != a                  # the write is visible


def test_result_cache_mid_execution_write_vetoes_insert(file_runner):
    """The write-epoch TOCTOU contract: a connector write notifying
    while the query runs must veto the insert (the stored rows could
    straddle versions)."""
    from presto_tpu.connectors import spi
    r = file_runner
    r.execute("create table memory.v as select l_orderkey k from "
              "lineitem where l_orderkey < 50")
    mem = r.session.catalogs.get("memory")
    sql = "select count(*) from memory.v"

    fired = []
    orig = MemoryConnector.page_source

    def chaotic(self, split, columns, pushdown=None,
                rows_per_batch=1 << 17):
        if not fired:
            fired.append(1)
            spi.notify_data_change(mem, "unrelated")  # mid-run write
        return orig(self, split, columns, pushdown, rows_per_batch)

    MemoryConnector.page_source = chaotic
    try:
        m0 = _metric("result_cache_miss_total")
        r.execute(sql, properties=RPROPS)
        # vetoed: the very next execution is a miss again
        r.execute(sql, properties=RPROPS)
        assert _metric("result_cache_miss_total") == m0 + 2
    finally:
        MemoryConnector.page_source = orig
    # clean run now inserts and hits
    r.execute(sql, properties=RPROPS)
    h0 = _metric("result_cache_hit_total")
    r.execute(sql, properties=RPROPS)
    assert _metric("result_cache_hit_total") == h0 + 1


def test_result_cache_epoch_api_veto():
    from presto_tpu.serving.resultcache import RESULTS
    from presto_tpu.exec.local import QueryResult
    epoch = RESULTS.epoch()
    RESULTS.note_write()
    ok = RESULTS.put(b"k-veto", QueryResult(["a"], [], [(1,)]),
                     deps=[], epoch=epoch)
    assert not ok


def test_incremental_partial_maintenance(file_runner):
    """Append-only filebase growth: only the changed splits recompute;
    the merged result is row-exact vs a cold run, for grouped AND
    global distributive aggregations; rewrites fall back to a miss."""
    r = file_runner
    r.execute("create table orc.inc as select l_orderkey k, "
              "l_quantity q, l_returnflag flag from lineitem "
              "where l_orderkey < 500")
    grouped = ("select flag, count(*) c, sum(q) sq, max(k) mk "
               "from orc.inc group by flag order by flag")
    glob = "select count(*), sum(q), min(k) from orc.inc where q > 10"
    r.execute(grouped, properties=RPROPS)
    r.execute(glob, properties=RPROPS)
    p0 = _metric("result_cache_partial_total")
    r.execute("insert into orc.inc select l_orderkey k, l_quantity q, "
              "l_returnflag flag from lineitem "
              "where l_orderkey between 500 and 1000")
    assert r.execute(grouped, properties=RPROPS).rows == \
        r.execute(grouped).rows
    assert r.execute(glob, properties=RPROPS).rows == \
        r.execute(glob).rows
    assert _metric("result_cache_partial_total") == p0 + 2
    # the re-stamped entry serves plain hits afterwards
    h0 = _metric("result_cache_hit_total")
    r.execute(grouped, properties=RPROPS)
    assert _metric("result_cache_hit_total") == h0 + 1
    # rewrite (drop + recreate): old files gone -> full miss, not merge
    r.execute("drop table orc.inc")
    r.execute("create table orc.inc as select l_orderkey k, "
              "l_quantity q, l_returnflag flag from lineitem "
              "where l_orderkey < 300")
    p1 = _metric("result_cache_partial_total")
    assert r.execute(grouped, properties=RPROPS).rows == \
        r.execute(grouped).rows
    assert _metric("result_cache_partial_total") == p1


def test_concurrent_partial_hits_never_double_apply(file_runner):
    """Two lookups racing on the same appended entry each merge the
    delta into the LOOKUP-TIME snapshot; the second re-stamp is
    rejected (base_deps compare), so the delta can never double-count
    — the 100-client repeated-mix race."""
    from presto_tpu.serving import resultcache as RC
    from presto_tpu.serving.plancache import bound_fingerprint, \
        parse_cached
    r = file_runner
    r.execute("create table orc.race as select l_orderkey k, "
              "l_quantity q from lineitem where l_orderkey < 400")
    sql = "select count(*) c, sum(q) sq from orc.race"
    r.execute(sql, properties=RPROPS)           # insert entry
    r.execute("insert into orc.race select l_orderkey k, l_quantity q "
              "from lineitem where l_orderkey between 400 and 800")
    stmt = parse_cached(sql)
    import dataclasses as dc
    session = dc.replace(r.session, properties={**r.session.properties,
                                                **RPROPS})
    key = bound_fingerprint(stmt, session)
    out1, ph1 = RC.RESULTS.get(key)
    out2, ph2 = RC.RESULTS.get(key)
    assert out1 == out2 == "partial"
    # first racer completes normally
    restrict = RC.split_predicate(session, ph1.spec, ph1.new_files)
    d1 = RC.subplan_result(ph1.plan, ph1.spec, session, 1 << 17,
                           split_restrict=restrict)
    m1 = RC.merge_subplan_rows(ph1.spec, ph1.base_subplan, d1)
    o1 = RC.replay_suffix(ph1.plan, ph1.spec, m1, session, 1 << 17)
    assert RC.RESULTS.update(ph1, o1, m1)
    # second racer merged against ITS OWN snapshot: identical rows,
    # and its re-stamp is rejected
    m2 = RC.merge_subplan_rows(ph2.spec, ph2.base_subplan, d1)
    o2 = RC.replay_suffix(ph2.plan, ph2.spec, m2, session, 1 << 17)
    assert sorted(o2.rows) == sorted(o1.rows)
    assert not RC.RESULTS.update(ph2, o2, m2)
    # and the surviving entry matches a cold run
    assert r.execute(sql, properties=RPROPS).rows == \
        r.execute(sql).rows


def test_result_cache_stores_materialized_plans(file_runner):
    """With templates + result cache combined, the CACHED plan must be
    binding-free: a later query for the same bound key can take the
    template guard-fallback path (no binding scope), and the partial
    delta/suffix replay re-executes the stored plan there."""
    import dataclasses as dc
    from presto_tpu.expr.params import has_params
    from presto_tpu.serving import resultcache as RC
    from presto_tpu.serving.plancache import bound_fingerprint, \
        parse_cached
    r = file_runner
    r.execute("create table orc.mat as select l_orderkey k, "
              "l_quantity q from lineitem where l_orderkey < 200")
    props = {**TPROPS, **RPROPS}
    sql = "select count(*) c, sum(q) s from orc.mat where q > 5"
    r.execute(sql, properties=props)
    session = dc.replace(r.session,
                         properties={**r.session.properties, **props})
    key = bound_fingerprint(parse_cached(sql), session)
    outcome, entry = RC.RESULTS.get(key)
    assert outcome == "hit"
    assert entry.spec is not None          # incremental-eligible
    assert not has_params(entry.plan)      # materialized for replay


def test_result_cache_eviction_under_limit(file_runner):
    from presto_tpu.serving.resultcache import RESULTS
    r = file_runner
    r.execute("create table memory.ev as select l_orderkey k from "
              "lineitem where l_orderkey < 200")
    old_limit = RESULTS.pool.limit
    try:
        # order-robust: the cache is process-global, so size the limit
        # from a MEASURED entry footprint instead of a fixed byte count
        # (a fixed 8 KiB fails in isolation where 5 small entries fit,
        # and put() silently rejects any entry larger than the limit)
        RESULTS.clear()
        r.execute("select count(*) from memory.ev where k > 0",
                  properties=RPROPS)
        size0 = RESULTS.pool.reserved
        assert size0 > 0
        limit = int(size0 * 2.5)        # room for 2 entries, never 3
        RESULTS.set_limit(limit)
        e0 = _metric("result_cache_evicted_total")
        for lo in (50, 100, 150):
            r.execute(f"select count(*) from memory.ev where k > {lo}",
                      properties=RPROPS)
        assert RESULTS.pool.reserved <= limit
        assert _metric("result_cache_evicted_total") > e0
        assert len(RESULTS) <= 2
    finally:
        RESULTS.set_limit(old_limit)


def test_explain_analyze_result_cache_line(file_runner):
    r = file_runner
    r.execute("create table memory.t as select l_orderkey k from "
              "lineitem where l_orderkey < 100")
    sql = "select count(*) from memory.t"
    r.execute(sql, properties=RPROPS)
    out = r.execute("explain analyze " + sql, properties=RPROPS)
    text = "\n".join(row[0] for row in out.rows)
    assert "Result cache:" in text
    assert "cached" in text


def test_result_cache_hit_releases_admission_slot_and_ctx():
    """ISSUE 13 satellite: the result-cache-hit fast path must release
    the resource-group slot AND the serving context (group memory back
    to zero) exactly like a cold run — extends PR 8's leak test."""
    from presto_tpu.server.protocol import PrestoTpuServer
    srv = PrestoTpuServer(
        LocalRunner(tpch_sf=0.001),
        resource_groups={
            "rootGroups": [{"name": "g", "hardConcurrencyLimit": 2,
                            "softMemoryLimit": 1 << 30}],
            "selectors": [{"group": "g"}]})
    try:
        srv.runner.session.properties["result_cache"] = True
        h0 = _metric("result_cache_hit_total")
        for _ in range(2):
            q = srv.create_query(
                "select count(*) from lineitem", {})
            assert q.done.wait(timeout=30)
            assert q.state == "FINISHED"
        assert _metric("result_cache_hit_total") == h0 + 1
        info = srv.resource_groups.info()[0]
        assert info["numRunning"] == 0 and info["numQueued"] == 0
        assert info["memoryReservedBytes"] == 0
    finally:
        srv.stop()


def test_cluster_template_and_result_cache_parity():
    """Row-exact parity on the ClusterRunner path: template-cached
    plans materialize bindings before fragmenting, result-cache hits
    serve stored rows, and a connector write invalidates them."""
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.server.worker import WorkerServer
    workers = [WorkerServer(tpch_sf=0.001) for _ in range(2)]
    for w in workers:
        w.start()
    urls = [f"http://127.0.0.1:{w.port}" for w in workers]
    runner = ClusterRunner(urls, tpch_sf=0.001, heartbeat=False)
    try:
        props = {**TPROPS, **RPROPS}
        sql = ("select n_regionkey, count(*) c from nation "
               "where n_nationkey > %d group by n_regionkey "
               "order by n_regionkey")
        cold = [runner.execute(sql % k).rows for k in (3, 7)]
        warm = [runner.execute(sql % k, properties=props).rows
                for k in (3, 7)]
        assert warm == cold
        h0 = _metric("result_cache_hit_total")
        again = [runner.execute(sql % k, properties=props).rows
                 for k in (3, 7)]
        assert again == cold
        assert _metric("result_cache_hit_total") == h0 + 2
    finally:
        for w in workers:
            w.stop()


def test_serving_cache_suite_lock_graph_clean():
    """End-of-suite assertion (ISSUE 15): the template/result cache
    locks are `checked_lock`s, so everything this module exercised —
    template builds, result-cache hits/partials, cluster parity —
    recorded real acquisition edges; the observed graph must hold no
    cycle, no jit dispatch under a lock, and no guarded-field
    violation. Defined last: pytest runs in definition order."""
    from presto_tpu._devtools import lockcheck
    assert lockcheck.ENABLED
    assert lockcheck.GRAPH.check() == [], lockcheck.GRAPH.check()

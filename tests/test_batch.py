import datetime
from decimal import Decimal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.batch import Batch, Schema, bucket_capacity, concat_batches


def test_bucket_capacity():
    assert bucket_capacity(1) == 128
    assert bucket_capacity(128) == 128
    assert bucket_capacity(129) == 256
    assert bucket_capacity(100_000) == 131072


def test_type_parse_roundtrip():
    for s in ["bigint", "integer", "double", "boolean", "date",
              "decimal(12,2)", "varchar(25)", "char(1)", "varchar"]:
        t = T.parse_type(s)
        assert T.parse_type(t.display()) == t


def test_decimal_storage():
    d = T.decimal(12, 2)
    assert d.to_storage("1.005") == 101  # round half up
    assert d.to_storage(3) == 300
    assert d.from_storage(12345) == Decimal("123.45")


def test_date_storage():
    assert T.DATE.to_storage("1970-01-02") == 1
    assert T.DATE.from_storage(0) == datetime.date(1970, 1, 1)
    assert T.DATE.to_storage(datetime.date(1994, 1, 1)) == 8766


def test_batch_pydict_roundtrip():
    b = Batch.from_pydict({
        "a": (T.BIGINT, [1, 2, None, 4]),
        "b": (T.DOUBLE, [1.5, None, 3.5, 4.5]),
        "s": (T.varchar(10), ["x", "y", "x", None]),
        "d": (T.DATE, ["1994-01-01", None, "1995-06-15", "1992-02-02"]),
    })
    assert b.capacity == 128
    assert b.host_count() == 4
    rows = b.to_pylist()
    assert rows[0] == (1, 1.5, "x", datetime.date(1994, 1, 1))
    assert rows[1][1] is None
    assert rows[2][2] == "x"
    assert rows[3][2] is None


def test_batch_is_pytree():
    b = Batch.from_pydict({"a": (T.BIGINT, [1, 2, 3])})

    @jax.jit
    def double(batch):
        col = batch.column("a")
        new = type(col)(col.type, col.data * 2, col.validity, col.dictionary)
        return batch.with_columns(batch.schema, [new])

    out = double(b)
    assert [r[0] for r in out.to_pylist()] == [2, 4, 6]


def test_compact():
    b = Batch.from_pydict({"a": (T.BIGINT, [10, 20, 30, 40, 50])})
    # kill rows 1 and 3
    mask = np.asarray(b.row_mask).copy()
    mask[1] = False
    mask[3] = False
    b2 = Batch(b.schema, b.columns, jnp.asarray(mask))
    c = b2.compact()
    assert c.host_count() == 3
    assert [r[0] for r in c.to_pylist()] == [10, 30, 50]


def test_concat_unifies_dictionaries():
    b1 = Batch.from_pydict({"s": (T.VARCHAR, ["a", "b"])}, capacity=128)
    b2 = Batch.from_pydict({"s": (T.VARCHAR, ["b", "c", None])}, capacity=128)
    out = concat_batches([b1, b2])
    vals = [r[0] for r in out.to_pylist()]
    assert vals == ["a", "b", "b", "c", None]
    assert out.column("s").dictionary == ("a", "b", "c")


def test_select():
    b = Batch.from_pydict({
        "a": (T.BIGINT, [1]), "b": (T.DOUBLE, [2.0]), "c": (T.INTEGER, [3]),
    })
    s = b.select(["c", "a"])
    assert s.schema.names == ["c", "a"]
    assert s.to_pylist() == [(3, 1)]

"""SQLite connector: a real external store behind the SPI.

Mirrors the reference's JDBC-connector test shape (reference
presto-base-jdbc + presto-mysql tests run the shared suites against a
real foreign database): CTAS engine data INTO sqlite, read it back
through the engine, check filter pushdown reaches sqlite's SQL, and
verify joins across catalogs work.
"""
import os

import pytest

from presto_tpu.connectors.spi import CatalogManager, TableHandle
from presto_tpu.connectors.sqlite import SqliteConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.runner import LocalRunner


@pytest.fixture(scope="module")
def runner(tmp_path_factory):
    db = str(tmp_path_factory.mktemp("sqlite") / "store.db")
    cat = CatalogManager()
    cat.register("tpch", TpchConnector(sf=0.01))
    cat.register("sq", SqliteConnector(db))
    r = LocalRunner(catalogs=cat, catalog="tpch")
    # CTAS a TPC-H subset INTO sqlite through the engine's write path
    r.execute("create table sq.default.nation2 as select * from nation")
    r.execute("""create table sq.default.orders2 as
                 select o_orderkey, o_custkey, o_totalprice, o_orderdate
                 from orders where o_orderkey < 1000""")
    return r


def test_metadata_discovery(runner):
    conn = runner.session.catalogs.get("sq")
    tables = conn.metadata.list_tables()
    assert "nation2" in tables and "orders2" in tables
    schema = conn.metadata.table_schema(
        TableHandle("sq", "default", "orders2"))
    assert "o_orderkey" in schema.names


def test_roundtrip_matches_source(runner):
    want = runner.execute(
        "select n_nationkey, n_name from nation order by 1").rows
    got = runner.execute(
        "select n_nationkey, n_name from sq.default.nation2 order by 1"
    ).rows
    assert [(int(a), str(b)) for a, b in got] \
        == [(int(a), str(b)) for a, b in want]


def test_filter_pushdown_reaches_sqlite(runner):
    """The planner's bound tuples must render into sqlite's WHERE
    clause (reference JdbcMetadata.applyFilter -> QueryBuilder)."""
    conn = runner.session.catalogs.get("sq")
    split = conn.split_manager.splits(
        TableHandle("sq", "default", "orders2"), 1)[0]
    src = conn.page_source(split, ["o_orderkey", "o_totalprice"],
                           pushdown=(("o_orderkey", 10, 500),))
    assert '"o_orderkey" >= ?' in src._sql
    assert '"o_orderkey" <= ?' in src._sql
    n = sum(b.host_count() for b in src.batches())
    full = conn.page_source(split, ["o_orderkey"], pushdown=None)
    n_full = sum(b.host_count() for b in full.batches())
    assert 0 < n < n_full


def test_pushdown_in_explain(runner):
    out = runner.execute(
        "explain select o_totalprice from sq.default.orders2 "
        "where o_orderkey between 10 and 500")
    text = "\n".join(r[0] for r in out.rows)
    assert "sq.default.orders2" in text


def test_engine_filters_through_connector(runner):
    got = runner.execute(
        """select count(*), sum(o_totalprice) from sq.default.orders2
           where o_orderkey between 10 and 500""").rows
    want = runner.execute(
        """select count(*), sum(o_totalprice) from orders
           where o_orderkey between 10 and 500 and o_orderkey < 1000"""
    ).rows
    assert int(got[0][0]) == int(want[0][0])
    assert float(got[0][1]) == pytest.approx(float(want[0][1]), rel=1e-9)


def test_cross_catalog_join(runner):
    got = runner.execute(
        """select r_name, count(*) from sq.default.nation2
           join tpch.default.region on n_regionkey = r_regionkey
           group by r_name order by r_name""").rows
    assert len(got) == 5 and all(int(c) == 5 for _, c in got)


def test_stats_feed_optimizer(runner):
    conn = runner.session.catalogs.get("sq")
    stats = conn.metadata.table_stats(
        TableHandle("sq", "default", "nation2"))
    assert stats.row_count == 25
    cs = stats.columns["n_nationkey"]
    assert cs.distinct_count == 25 and cs.min_value == 0


def test_plugin_factory_loads_from_properties(tmp_path):
    from presto_tpu.config import CONNECTOR_FACTORIES
    db = str(tmp_path / "p.db")
    conn = CONNECTOR_FACTORIES["sqlite"]({"sqlite.path": db})
    conn.create_table("t", __import__(
        "presto_tpu.batch", fromlist=["Schema"]).Schema(
            [("a", __import__("presto_tpu", fromlist=["types"])
              .types.BIGINT)]))
    assert conn.metadata.list_tables() == ["t"]

"""Memory accounting + host-DRAM spill under a tiny budget.

Queries that exercise the spillable operators (join build, hash agg,
distinct, order-by) must produce ORACLE-IDENTICAL results with a budget
small enough to force every buffer to host DRAM — the TPU reshape of the
reference's spill tests (reference
presto-main/src/test/java/io/prestosql/operator/TestHashJoinOperator.java
spill variants, TestHashAggregationOperator spill cases).
"""
import pytest

from test_sql import compare, oracle, runner  # noqa: F401 (fixtures)

from presto_tpu.exec.runner import LocalRunner

# small enough that even SF 0.01 state spills, large enough for one chunk
BUDGET = 200_000

SPILL_QUERIES = [
    # hash agg over many groups
    "select l_orderkey, sum(l_quantity) q, count(*) c from lineitem group by l_orderkey order by l_orderkey limit 50",
    # join with a large build side (orders) — partitioned spill probe
    "select o_orderpriority, count(*) c from orders, lineitem where l_orderkey = o_orderkey group by o_orderpriority order by o_orderpriority",
    # left join survives partitioned probing
    "select count(*) c, count(o_orderkey) co from customer left join orders on c_custkey = o_custkey",
    # distinct
    "select count(*) c from (select distinct l_suppkey, l_returnflag from lineitem) t",
    # full sort (no LIMIT: TopN is bounded and never spills) with a
    # descending string key exercising host-side rank ordering
    "select o_orderstatus, o_orderkey from orders order by o_orderstatus desc, o_orderkey",
    # string group keys: spill partitioning must hash dictionary VALUES,
    # not per-chunk codes, or one group finalizes in two partitions
    "select l_returnflag, l_linestatus, count(*) c, sum(l_quantity) q from lineitem group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus",
]


@pytest.fixture(scope="module")
def spill_runner(runner):
    r = LocalRunner(catalogs=runner.session.catalogs,
                    rows_per_batch=1 << 12)
    r.session.properties["query_max_memory"] = BUDGET
    r.session.properties["spill_partitions"] = 4
    # pin the sort-segment grouping path: the dense composite-code path
    # (stats-bounded grouping) shrinks partial states to the key domain's
    # bucket, and these queries then never hit the budget — but the
    # SPILL machinery is what this module tests
    r.session.properties["dense_grouping"] = False
    return r


@pytest.mark.parametrize("sql", SPILL_QUERIES, ids=range(len(SPILL_QUERIES)))
def test_spill_matches_oracle(spill_runner, oracle, sql):
    compare(spill_runner, oracle, sql, rel=1e-9)
    stats = spill_runner.session.last_memory_stats
    assert stats is not None
    assert stats.peak_bytes <= BUDGET, stats
    assert stats.spilled_bytes > 0, f"no spill happened: {stats}"


def test_no_spill_when_unlimited(runner, oracle):
    sql = SPILL_QUERIES[0]
    compare(runner, oracle, sql, rel=1e-9)
    stats = runner.session.last_memory_stats
    assert stats is not None and stats.spilled_bytes == 0


@pytest.fixture(scope="module")
def disk_runner(runner, tmp_path_factory):
    """Tiny device budget AND tiny host budget: every spillable buffer
    flushes through to the disk tier (reference FileSingleStreamSpiller)."""
    r = LocalRunner(catalogs=runner.session.catalogs,
                    rows_per_batch=1 << 12)
    r.session.properties["query_max_memory"] = BUDGET
    r.session.properties["spill_partitions"] = 4
    r.session.properties["spill_to_disk_bytes"] = 50_000
    r.session.properties["spill_path"] = str(
        tmp_path_factory.mktemp("spill"))
    # see spill_runner: keep the sort-segment path so states stay big
    # enough to hit the budget
    r.session.properties["dense_grouping"] = False
    return r


@pytest.mark.parametrize("sql", SPILL_QUERIES, ids=range(len(SPILL_QUERIES)))
def test_disk_spill_matches_oracle(disk_runner, oracle, sql):
    compare(disk_runner, oracle, sql, rel=1e-9)
    stats = disk_runner.session.last_memory_stats
    assert stats.peak_bytes <= BUDGET, stats
    assert stats.disk_spilled_bytes > 0, f"no disk spill: {stats}"


def test_disk_spill_files_cleaned_up(disk_runner, oracle):
    import os
    spill_dir = disk_runner.session.properties["spill_path"]
    compare(disk_runner, oracle, SPILL_QUERIES[1], rel=1e-9)
    assert os.listdir(spill_dir) == []

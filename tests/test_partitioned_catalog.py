"""Partitioned file catalog over ORC/Parquet + the ORC writer.

Reference: presto-hive/.../HiveMetadata.java (CTAS + partitioned_by),
BackgroundHiveSplitLoader.java:262 (partition dirs -> splits),
HivePartitionManager partition pruning, presto-orc/.../writer/
(OrcWriter). The write path routes rows into key=value directories; the
read path appends partition columns per split and prunes partitions on
pushdown bounds before any file IO.
"""
import os

import numpy as np
import pytest

# tier-1 budget: excluded from `pytest -m 'not slow'` — CTAS + ORC round-trips are IO/compile heavy
# (see tools/check_tier1_time.py; ~51s)
pytestmark = pytest.mark.slow


def test_grouped_execution_partition_wise_join(orc_runner):
    """Grouped (lifespan) execution: a join of two tables co-partitioned
    on the join key runs one bucket at a time (reference
    execution/Lifespan.java:26 + scheduler/group/LifespanScheduler.java),
    bounding peak query memory at O(bucket) instead of O(table)."""
    n_per = 6000
    rows_a = ", ".join(f"({i}, {i % 3})" for i in range(n_per * 3))
    rows_b = ", ".join(f"({i}, {i % 3}, {i * 2})"
                       for i in range(n_per * 3))
    orc_runner.execute(
        "CREATE TABLE ga WITH (partitioned_by = ARRAY['p']) AS "
        f"SELECT * FROM (VALUES {rows_a}) t(id, p)")
    orc_runner.execute(
        "CREATE TABLE gb WITH (partitioned_by = ARRAY['p']) AS "
        f"SELECT * FROM (VALUES {rows_b}) t(id, p, v)")
    q = ("SELECT count(*), sum(gb.v) FROM ga "
         "JOIN gb ON ga.id = gb.id AND ga.p = gb.p")
    grouped = orc_runner.execute(q).rows
    peak_grouped = orc_runner.session.last_memory_stats.peak_bytes
    plain = orc_runner.execute(
        q, properties={"grouped_execution": "false"}).rows
    peak_plain = orc_runner.session.last_memory_stats.peak_bytes
    assert grouped == plain == [(n_per * 3, sum(i * 2
                                                for i in range(n_per * 3)))]
    # bucket-serial processing drains one partition's build at a time:
    # its tracked peak must be well under the all-partitions peak
    assert peak_grouped < peak_plain, (peak_grouped, peak_plain)


def test_grouped_execution_skips_non_copartitioned(orc_runner):
    """Joins whose keys don't cover the partition keys keep the normal
    all-at-once path (and stay correct)."""
    orc_runner.execute(
        "CREATE TABLE na WITH (partitioned_by = ARRAY['p']) AS "
        "SELECT * FROM (VALUES (1, 0), (2, 1), (3, 0)) t(id, p)")
    orc_runner.execute(
        "CREATE TABLE nb AS SELECT * FROM "
        "(VALUES (1, 10), (2, 20), (4, 40)) t(id, v)")
    got = orc_runner.execute(
        "SELECT ga.id, nb.v FROM na ga JOIN nb ON ga.id = nb.id "
        "ORDER BY 1").rows
    assert got == [(1, 10), (2, 20)]


@pytest.fixture()
def orc_runner(tmp_path):
    from presto_tpu.connectors.orc import OrcConnector
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec.runner import LocalRunner
    catalogs = CatalogManager()
    catalogs.register("orc", OrcConnector(str(tmp_path)))
    catalogs.register("tpch", TpchConnector(sf=0.01))
    return LocalRunner(catalogs=catalogs, catalog="orc")


def test_orc_writer_roundtrip_pyarrow(tmp_path):
    """Conformance: files we write must be readable by an independent
    ORC implementation (pyarrow), nulls and stats included."""
    import pyarrow.orc as po

    from presto_tpu import types as T
    from presto_tpu.batch import Batch
    from presto_tpu.formats.orc_writer import write_orc

    b = Batch.from_pydict({
        "k": (T.BIGINT, [1, 2, None, 2 ** 40, -2 ** 40]),
        "d": (T.DOUBLE, [1.5, None, 3.25, -0.5, 2.0]),
        "s": (T.VARCHAR, ["aa", "bb", None, "dd", "aa"]),
        "flag": (T.BOOLEAN, [True, False, True, None, False]),
        "dt": (T.DATE, [18000, 18001, 18002, None, 18004]),
    })
    path = str(tmp_path / "t.orc")
    assert write_orc(path, b.schema, [b]) == 5
    t = po.ORCFile(path).read()
    assert t.to_pydict()["k"] == [1, 2, None, 2 ** 40, -2 ** 40]
    assert t.to_pydict()["s"] == ["aa", "bb", None, "dd", "aa"]
    assert t.to_pydict()["flag"] == [True, False, True, None, False]


def test_orc_writer_multi_stripe_stats(tmp_path):
    from presto_tpu import types as T
    from presto_tpu.batch import Batch
    from presto_tpu.formats.orc import OrcReader
    from presto_tpu.formats.orc_writer import write_orc

    vals = list(range(5000))
    b = Batch.from_pydict({"k": (T.BIGINT, vals)})
    path = str(tmp_path / "m.orc")
    write_orc(path, b.schema, [b], stripe_rows=1000)
    r = OrcReader(path)
    assert len(r.tail.stripes) == 5
    assert r.tail.int_stats[1].min == 0
    assert r.tail.int_stats[1].max == 4999
    # stripe stats enable stripe pruning: ask for a range in stripe 3
    got = [row for batch in r.batches(["k"], {"k": (3100, 3200)})
           for row in batch.to_pylist()]
    flat = [v for (v,) in got]
    assert set(range(3100, 3201)) <= set(flat)
    assert len(flat) == 1000           # exactly one stripe survived


def test_ctas_partitioned_orc_roundtrip(orc_runner):
    n = orc_runner.execute(
        "CREATE TABLE sales WITH (partitioned_by = ARRAY['region']) AS "
        "SELECT * FROM (VALUES (1, 10.5, 1), (2, 20.5, 1), (3, 30.5, 2),"
        " (4, 40.5, 2), (5, 50.5, 3)) t(id, amt, region)").rows
    assert n == [(5,)]
    got = orc_runner.execute(
        "SELECT region, count(*), sum(amt) FROM sales "
        "GROUP BY region ORDER BY region").rows
    assert [(r[0], r[1], round(float(r[2]), 1)) for r in got] == [
        (1, 2, 31.0), (2, 2, 71.0), (3, 1, 50.5)]
    # files live in key=value dirs
    root = orc_runner.session.catalogs.get("orc").root
    assert os.path.isdir(os.path.join(root, "sales", "region=1"))


def test_partition_pruning_skips_file_io(orc_runner):
    orc_runner.execute(
        "CREATE TABLE pt WITH (partitioned_by = ARRAY['p']) AS "
        "SELECT * FROM (VALUES (1, 1), (2, 2), (3, 3)) t(v, p)")
    conn = orc_runner.session.catalogs.get("orc")
    opened = []
    orig = conn.make_page_source

    def spy(path, columns, pushdown):
        opened.append(path)
        return orig(path, columns, pushdown)

    conn.make_page_source = spy
    try:
        rows = orc_runner.execute(
            "SELECT v FROM pt WHERE p = 2").rows
    finally:
        conn.make_page_source = orig
    assert rows == [(2,)]
    # only the p=2 partition's file was opened
    assert len(opened) == 1 and "p=2" in opened[0]


def test_ctas_partitioned_from_tpch(orc_runner):
    """SF0.01 lineitem partitioned by returnflag: every row survives the
    round trip and partition pruning serves flag-filtered queries."""
    orc_runner.execute(
        "CREATE TABLE li WITH (partitioned_by = ARRAY['l_returnflag']) "
        "AS SELECT l_orderkey, l_quantity, l_returnflag FROM "
        "tpch.tiny.lineitem")
    want = orc_runner.execute(
        "SELECT l_returnflag, count(*), sum(l_quantity) FROM "
        "tpch.tiny.lineitem GROUP BY 1 ORDER BY 1").rows
    got = orc_runner.execute(
        "SELECT l_returnflag, count(*), sum(l_quantity) FROM li "
        "GROUP BY 1 ORDER BY 1").rows
    assert [(a, b, round(float(c), 2)) for a, b, c in got] == \
        [(a, b, round(float(c), 2)) for a, b, c in want]


def test_insert_into_partitioned(orc_runner):
    orc_runner.execute(
        "CREATE TABLE ins WITH (partitioned_by = ARRAY['p']) AS "
        "SELECT * FROM (VALUES (1, 1)) t(v, p)")
    orc_runner.execute(
        "INSERT INTO ins SELECT * FROM (VALUES (2, 1), (3, 9)) t(v, p)")
    rows = orc_runner.execute(
        "SELECT p, v FROM ins ORDER BY p, v").rows
    assert rows == [(1, 1), (1, 2), (9, 3)]


def test_parquet_ctas(tmp_path):
    from presto_tpu.connectors.parquet import ParquetConnector
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.exec.runner import LocalRunner
    catalogs = CatalogManager()
    catalogs.register("pq", ParquetConnector(str(tmp_path)))
    r = LocalRunner(catalogs=catalogs, catalog="pq")
    r.execute("CREATE TABLE t AS SELECT * FROM "
              "(VALUES (1, 'x'), (2, 'y')) v(a, b)")
    assert r.execute("SELECT a, b FROM t ORDER BY a").rows == [
        (1, "x"), (2, "y")]

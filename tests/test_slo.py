"""SLO plane: burn-rate math against hand-computed fixtures, the
OK/WARN/PAGE state machine's hysteresis, per-group objective parsing,
the alert-rule registry, the signals feed + its reference consumer,
and the end-to-end chaos drill — a failpoint-injected latency spike
firing WARN then PAGE and recovering, visible through
``system.runtime.alerts`` over plain SQL.

Burn windows and evaluation instants are synthetic throughout
(``now=`` everywhere), so window arithmetic is deterministic.
"""
import dataclasses

import pytest

from presto_tpu.obs.metrics import REGISTRY, MetricsRegistry
from presto_tpu.obs.slo import (
    ALERT_RULES, CLEAR_AFTER, EXIT_FRACTION, PAGE_ENTER_BURN,
    WARN_ENTER_BURN, SLO, SloObjective, SloTracker, _AlertState,
    alert_rule, burn_rate, objectives_from_spec,
)
from presto_tpu.obs.timeseries import TIMESERIES, TimeSeriesStore


def _tracker():
    reg = MetricsRegistry()
    ts = TimeSeriesStore(registry=reg)
    return reg, ts, SloTracker(store=ts)


# -- burn-rate math (hand fixtures) -------------------------------------------

def test_burn_rate_formula():
    # 5% errors against a 95% objective: burning exactly at plan
    assert burn_rate(0.05, 0.95) == pytest.approx(1.0)
    # 2% errors against 99%: double plan
    assert burn_rate(0.02, 0.99) == pytest.approx(2.0)
    assert burn_rate(0.0, 0.99) == 0.0


def test_latency_error_fraction_hand_fixture():
    """10 good (1s) + 10 bad (3s) observations against a 2s target:
    the 2s threshold snaps UP to the bucket ladder's 2.5s bound, the
    error fraction is exactly 0.5, and at target 0.95 that is a 10x
    burn — the PAGE threshold."""
    reg, ts, tr = _tracker()
    h = reg.histogram("serving_latency_seconds.g")
    ts.sample(now=100.0)
    for _ in range(10):
        h.observe(1.0)
    for _ in range(10):
        h.observe(3.0)
    ts.sample(now=110.0)
    obj = SloObjective(group="g", objective="latency", target=0.95,
                       threshold_s=2.0)
    frac = tr._error_fraction(obj, 300.0, now=110.0)
    assert frac == pytest.approx(0.5)
    burns = tr.burns(obj, now=110.0)
    assert burns[300.0] == pytest.approx(10.0)
    assert burns[3600.0] == pytest.approx(10.0)


def test_latency_threshold_above_ladder_never_errors():
    reg, ts, tr = _tracker()
    h = reg.histogram("serving_latency_seconds.g")
    ts.sample(now=100.0)
    for _ in range(5):
        h.observe(10.0)
    ts.sample(now=110.0)
    obj = SloObjective(group="g", objective="latency", target=0.95,
                       threshold_s=500.0)   # beyond the 120s top bound
    assert tr._error_fraction(obj, 300.0, now=110.0) == 0.0


def test_availability_error_fraction_hand_fixture():
    """100 requests, 2 errors over the window against a 99% target:
    error fraction 0.02, burn 2.0 — the WARN threshold."""
    reg, ts, tr = _tracker()
    req = reg.counter("serving_requests_total.g")
    err = reg.counter("serving_errors_total.g")
    ts.sample(now=100.0)
    req.inc(100)
    err.inc(2)
    ts.sample(now=110.0)
    obj = SloObjective(group="g", objective="availability",
                       target=0.99)
    assert tr._error_fraction(obj, 300.0,
                              now=110.0) == pytest.approx(0.02)
    assert tr.burns(obj, now=110.0)[300.0] == pytest.approx(2.0)


def test_no_traffic_means_no_burn_data():
    _, ts, tr = _tracker()
    ts.sample(now=100.0)
    ts.sample(now=110.0)
    obj = SloObjective(group="g", objective="availability",
                       target=0.99)
    assert tr._error_fraction(obj, 300.0, now=110.0) is None


# -- objective parsing --------------------------------------------------------

def test_objectives_from_spec_normalized_block():
    objs = objectives_from_spec("serving.dash", {
        "latencyObjective": 0.95, "latencyTargetMs": 500.0,
        "availabilityObjective": 0.99, "windows": [60.0, 600.0]})
    by_kind = {o.objective: o for o in objs}
    lat = by_kind["latency"]
    assert lat.group == "serving.dash" and lat.target == 0.95
    assert lat.threshold_s == pytest.approx(0.5)
    assert lat.windows == (60.0, 600.0)
    assert lat.rule == "latency_burn"
    avail = by_kind["availability"]
    assert avail.target == 0.99 and avail.rule == "availability_burn"
    assert objectives_from_spec("g", None) == []


def test_group_config_slo_validation():
    from presto_tpu.server.resource_groups import _parse_slo
    ok = _parse_slo({"latencyTargetMs": 250, "latencyObjective": 0.9})
    assert ok == {"latencyObjective": 0.9, "latencyTargetMs": 250.0}
    assert _parse_slo(None) is None
    with pytest.raises(ValueError):
        _parse_slo({"latencyObjective": 0.9})       # no target ms
    with pytest.raises(ValueError):
        _parse_slo({"availabilityObjective": 1.5})  # out of (0,1)
    with pytest.raises(ValueError):
        _parse_slo({})                              # no objective
    with pytest.raises(ValueError):
        _parse_slo({"availabilityObjective": 0.99,
                    "windows": [0.0]})              # bad window
    with pytest.raises(ValueError):
        _parse_slo("latency<1s")                    # not an object


def test_alert_rule_registry():
    import tools.slo_report as slo_report

    assert alert_rule("latency_burn") == "latency_burn"
    with pytest.raises(ValueError):
        alert_rule("typo_burn")
    # the gate's literal copies cannot drift from the engine's
    assert tuple(sorted(ALERT_RULES)) == tuple(sorted(slo_report.RULES))
    assert slo_report.STATES == ("OK", "WARN", "PAGE")
    assert slo_report.OBJECTIVES == ("latency", "availability")


def test_slo_block_multi_coordinator_validation():
    """ISSUE 19: the merged fleet slo block — a valid two-coordinator
    block passes; untagged rows, a fleet of one, and p95 coverage
    missing for a coordinator all fail."""
    from tools.slo_report import validate_slo_block

    def block(tag=True, coords=2):
        extra = {"coordinator": "coord-0"} if tag else {}
        obj = {"group": "serving.dash", "objective": "latency",
               "target": 0.95, "threshold_ms": 2000.0, "state": "OK",
               "burn_short": 0.0, "burn_long": 0.0,
               "budget_remaining": 1.0, **extra}
        pt = {"t": 1.0, "group": "serving.dash",
              "objective": "latency", "state": "OK", "burn": 0.0,
              "p95_ms": 10.0, **extra}
        return {"coordinators": coords, "sample_interval_s": 0.2,
                "objectives": [obj], "alerts": [], "timeline": [pt]}

    def verdict(blk):
        return validate_slo_block({"m": {"metric": "m", "slo": blk}})

    assert verdict(block())["ok"]
    assert not verdict(block(tag=False))["ok"]      # untagged rows
    assert not verdict(block(coords=1))["ok"]       # fleet of one
    # the p95 coverage check is per coordinator: a latency objective
    # on coord-0 is NOT covered by a timeline point from coord-1
    drifted = block()
    drifted["timeline"][0]["coordinator"] = "coord-1"
    v = verdict(drifted)
    assert not v["ok"]
    assert any("coord-0" in x["detail"] for x in v["violations"])
    # and the single-coordinator (r03) form still validates untagged
    legacy = block(tag=False)
    legacy.pop("coordinators")
    assert verdict(legacy)["ok"]


# -- state machine hysteresis -------------------------------------------------

def _step_seq(burns, start="OK"):
    st = _AlertState(0.0)
    st.state = start
    out = []
    for b in burns:
        new = SloTracker._step(st, b)
        if new != st.state:
            st.state = new
            st.ok_streak = 0
        out.append(st.state)
    return out


def test_state_machine_escalates_immediately():
    assert _step_seq([0.5, 3.0, 12.0]) == ["OK", "WARN", "PAGE"]
    assert _step_seq([15.0]) == ["PAGE"]          # straight to PAGE


def test_state_machine_does_not_flap_at_the_threshold():
    """Burn oscillating just below the WARN entry threshold (but above
    the exit threshold, entry x 0.5) must NOT clear the alert."""
    assert WARN_ENTER_BURN * EXIT_FRACTION == pytest.approx(1.0)
    seq = _step_seq([3.0, 1.9, 1.1, 1.9, 1.1, 1.9], start="OK")
    assert seq == ["WARN"] * 6                    # held, no flapping


def test_state_machine_clears_after_consecutive_quiet_evals():
    assert CLEAR_AFTER == 2
    # one quiet eval is not enough; a burp resets the streak
    seq = _step_seq([3.0, 0.5, 1.5, 0.5, 0.5])
    assert seq == ["WARN", "WARN", "WARN", "WARN", "OK"]
    # PAGE exits against its own (higher) threshold: 10 x 0.5 = 5;
    # a burn still in WARN territory steps DOWN to WARN, not to OK
    seq = _step_seq([12.0, 4.0, 4.0])
    assert seq == ["PAGE", "PAGE", "WARN"]
    seq = _step_seq([12.0, 0.5, 0.5])
    assert seq == ["PAGE", "PAGE", "OK"]


def test_window_without_data_holds_alert_down():
    """A huge burn in one window but no data in the other must not
    page — no escalation without evidence in EVERY window."""
    reg, ts, tr = _tracker()
    h = reg.histogram("serving_latency_seconds.g")
    ts.sample(now=100.0)
    for _ in range(10):
        h.observe(50.0)                 # everything over threshold
    ts.sample(now=110.0)
    obj = SloObjective(group="g", objective="latency", target=0.95,
                       threshold_s=0.1, windows=(5.0, 3600.0))
    tr.objectives = lambda: [obj]       # bypass live-manager walk
    # the 5s window at now=110 has baseline 100 (at/before 105) and
    # end 110 -> burn 20; at now=200 the short window's baseline and
    # end collapse to the same sample -> no data -> held OK
    burns = tr.burns(obj, now=200.0)
    assert burns[3600.0] == pytest.approx(20.0)
    assert burns[5.0] is None
    tr.evaluate(now=200.0)
    assert tr.state_of("g", "latency") == "OK"


# -- the signals feed + its reference consumer --------------------------------

def test_cluster_signals_snapshot_is_frozen():
    from presto_tpu.obs.signals import cluster_signals

    snap = cluster_signals(now=1000.0)
    assert snap.ts == 1000.0
    with pytest.raises(dataclasses.FrozenInstanceError):
        snap.ts = 0.0
    assert snap.group("no.such.group") is None
    assert snap.node("no-such-node") is None
    # cache limits come from the live serving caches
    assert snap.caches.scan_cache_limit_bytes >= 0
    assert 0.0 <= snap.caches.plan_cache_pressure <= 1.0


def test_autoscale_watcher_consumes_the_feed():
    """The demo consumer (tools/autoscale_watch.py) exercises every
    rule against a synthetic snapshot — the feed's contract test."""
    import tools.autoscale_watch as watch

    decisions = watch.decide(watch.demo_signals())
    by_action = {}
    for d in decisions:
        by_action.setdefault(d["action"], []).append(d["target"])
    assert by_action["scale_up"] == ["serving.dash", "serving.adhoc"]
    assert by_action["scale_down"] == ["batch"]
    assert by_action["replace_node"] == ["w1"]
    assert by_action["grow_cache"] == ["scan_cache"]
    # every decision carries the signal values that justified it
    assert all("reason" in d and "signals" in d for d in decisions)
    # a paging group is never scaled down, even when idle
    paged = watch.demo_signals().group("serving.adhoc")
    assert paged.alert_state == "PAGE"
    assert "serving.adhoc" not in by_action["scale_down"]


# -- end to end: failpoint latency spike through the whole plane --------------

@pytest.fixture
def health_plane():
    """The process-global plane (protocol records into REGISTRY; the
    system tables read TIMESERIES/SLO), reset around the test. An
    earlier test's server may have left the wall-clock sampler thread
    running and the tracker installed as a sample listener — stop the
    thread and drop listeners so only this test's synthetic clock and
    explicit evaluate() calls drive the plane (srv.start() re-installs
    for later tests)."""
    TIMESERIES.stop()
    TIMESERIES.reset(keep_listeners=False)
    SLO.reset()
    yield
    TIMESERIES.reset(keep_listeners=False)
    SLO.reset()


def test_failpoint_latency_spike_pages_and_recovers(health_plane):
    """The chaos drill from docs/observability.md: a latency failpoint
    on ``protocol.serve`` drives the group's burn through WARN then
    PAGE; clearing it recovers to OK after the hysteresis streak —
    and the whole story is queryable via system.runtime.{slo,alerts,
    timeseries}."""
    from presto_tpu.exec.failpoints import FAILPOINTS
    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.server.protocol import PrestoTpuServer

    runner = LocalRunner(tpch_sf=0.001)
    srv = PrestoTpuServer(runner, resource_groups={
        "rootGroups": [{"name": "sloe2e", "hardConcurrencyLimit": 4,
                        "slo": {"latencyTargetMs": 100.0,
                                "latencyObjective": 0.95,
                                "availabilityObjective": 0.99,
                                "windows": [5.0, 10.0]}}],
        "selectors": [{"group": "sloe2e"}]})
    sql = "select count(*) from nation"

    def run(n):
        for _ in range(n):
            q = srv.create_query(sql, {})
            q.done.wait(timeout=60)
            assert q.state == "FINISHED", q.error

    try:
        run(2)                        # compile outside any window
        TIMESERIES.sample(now=100.0)

        # phase 1: one slow request among eight fast -> ~2.2x burn
        FAILPOINTS.configure("protocol.serve", action="sleep",
                             sleep_s=0.3, times=1)
        run(9)
        TIMESERIES.sample(now=101.0)
        transitions = SLO.evaluate(now=101.0)
        assert [(t["from"], t["to"]) for t in transitions] == \
            [("OK", "WARN")]
        assert transitions[0]["rule"] == "latency_burn"

        # phase 2: every request slow; both windows see only the bad
        # interval -> 20x burn -> PAGE
        FAILPOINTS.configure("protocol.serve", action="sleep",
                             sleep_s=0.3, times=None)
        run(4)
        FAILPOINTS.clear("protocol.serve")
        TIMESERIES.sample(now=115.0)
        transitions = SLO.evaluate(now=115.0)
        assert [(t["from"], t["to"]) for t in transitions] == \
            [("WARN", "PAGE")]
        assert SLO.state_of("sloe2e", "latency") == "PAGE"

        # recovery: fast traffic only; burn 0 but hysteresis holds the
        # page for CLEAR_AFTER consecutive quiet evaluations
        run(6)
        TIMESERIES.sample(now=130.0)
        assert SLO.evaluate(now=130.0) == []      # streak 1: held
        assert SLO.state_of("sloe2e", "latency") == "PAGE"
        TIMESERIES.sample(now=131.0)
        transitions = SLO.evaluate(now=131.0)
        assert [(t["from"], t["to"]) for t in transitions] == \
            [("PAGE", "OK")]

        # availability never fired (every request FINISHED)
        assert SLO.state_of("sloe2e", "availability") == "OK"

        # the whole story over plain SQL
        res = runner.execute(
            "select from_state, to_state, rule from "
            "system.runtime.alerts")
        lat = [(f, t) for f, t, r in res.rows if r == "latency_burn"]
        assert lat == [("OK", "WARN"), ("WARN", "PAGE"),
                       ("PAGE", "OK")]

        res = runner.execute(
            "select objective, state, budget_remaining from "
            "system.runtime.slo where group_path = 'sloe2e'")
        states = {o: (s, b) for o, s, b in res.rows}
        assert states["latency"][0] == "OK"
        assert states["availability"] == ("OK", 1.0)

        res = runner.execute(
            "select name, kind from system.runtime.timeseries "
            "where name = 'serving_latency_seconds.sloe2e.p95'")
        assert res.rows and res.rows[0][1] == "histogram"

        # the metrics table stamps one clock read per snapshot
        res = runner.execute(
            "select sampled_at from system.runtime.metrics limit 3")
        stamps = {r[0] for r in res.rows}
        assert len(stamps) == 1 and stamps.pop() > 0
    finally:
        FAILPOINTS.clear("protocol.serve")
        srv.stop()


def test_evaluate_sets_burn_gauges_and_history(health_plane):
    """Gauges + the history ring (the bench slo block's feed) update
    on every evaluation pass."""
    reg, ts, tr = _tracker()
    req = reg.counter("serving_requests_total.g")
    err = reg.counter("serving_errors_total.g")
    ts.sample(now=100.0)
    req.inc(100)
    err.inc(3)
    ts.sample(now=110.0)
    obj = SloObjective(group="g", objective="availability",
                       target=0.99, windows=(300.0, 3600.0))
    tr.objectives = lambda: [obj]
    tr.evaluate(now=110.0)
    # note: gauges land on the GLOBAL registry (the exposition path),
    # keyed by group:objective:window
    g = REGISTRY.gauge("slo_burn_rate_ratio.g:availability:300s")
    assert g.value == pytest.approx(3.0)
    budget = REGISTRY.gauge("slo_error_budget_remaining_ratio."
                            "g:availability")
    assert budget.value == pytest.approx(0.0)    # 1 - 3.0, clamped
    hist = tr.history()
    assert hist and hist[-1]["group"] == "g"
    assert hist[-1]["burn"]["300"] == pytest.approx(3.0)
    assert hist[-1]["state"] == "WARN"

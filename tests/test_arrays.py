"""ARRAY/MAP types, UNNEST, and higher-order functions.

The padded dense representation (reference spi/block/ArrayBlock.java
offsets+values, re-designed as [cap, L] tiles + lengths — types.py
ArrayType) and the array function surface (reference
operator/scalar/Array*.java, UnnestOperator.java,
LambdaBytecodeGenerator.java).
"""
import pytest


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.001)


def one(runner, sql):
    rows = runner.execute("select " + sql).rows
    assert len(rows) == 1
    return rows[0]


def test_array_literal_roundtrip(runner):
    assert one(runner, "array[1, 2, 3]") == ([1, 2, 3],)
    assert one(runner, "array['a', 'b']") == (["a", "b"],)
    assert one(runner, "array[1, null, 3]") == ([1, None, 3],)


def test_subscript(runner):
    assert one(runner, "array[10, 20, 30][2]") == (20,)


def test_subscript_out_of_bounds_errors(runner):
    from presto_tpu.errors import QueryError
    with pytest.raises(QueryError, match="INVALID_FUNCTION_ARGUMENT"):
        runner.execute("select array[1, 2][5]")


def test_element_at(runner):
    r = one(runner, "element_at(array[10, 20, 30], 2), "
                    "element_at(array[10, 20, 30], -1), "
                    "element_at(array[10, 20], 5)")
    assert r == (20, 30, None)


def test_cardinality_contains_position(runner):
    r = one(runner, "cardinality(array[1, 2, 3]), "
                    "contains(array[1, 2], 2), contains(array['x'], 'z'), "
                    "array_position(array[5, 6, 7], 6), "
                    "array_position(array[5], 9)")
    assert r == (3, True, False, 2, 0)


def test_min_max_sort_distinct(runner):
    r = one(runner, "array_max(array[3, 1, 2]), array_min(array[3, 1, 2]), "
                    "array_min(array['b', 'a']), "
                    "array_sort(array[3, 1, 2]), "
                    "array_distinct(array[1, 2, 1, 3, 2])")
    assert r == (3, 1, "a", [1, 2, 3], [1, 2, 3])


def test_array_min_null_element(runner):
    assert one(runner, "array_min(array[1, null, 3])") == (None,)


def test_concat_operator(runner):
    assert one(runner, "array[1, 2] || array[3]") == ([1, 2, 3],)
    assert one(runner, "array['a'] || array['b', 'a']") == (["a", "b", "a"],)


def test_repeat_sequence(runner):
    r = one(runner, "repeat(7, 3), sequence(1, 4), sequence(5, 1, -2)")
    assert r == ([7, 7, 7], [1, 2, 3, 4], [5, 3, 1])


def test_split(runner):
    assert one(runner, "split('a,b,c', ',')") == (["a", "b", "c"],)
    assert one(runner, "split('a:b:c', ':', 2)") == (["a", "b:c"],)


def test_transform(runner):
    assert one(runner, "transform(array[1, 2, 3], x -> x * 10)") \
        == ([10, 20, 30],)
    assert one(runner, "transform(array['a', 'b'], s -> upper(s))") \
        == (["A", "B"],)


def test_transform_capture(runner):
    rows = runner.execute(
        "select transform(array[1, 2], x -> x + n_regionkey) "
        "from nation where n_nationkey = 1").rows
    assert rows == [([2, 3],)]


def test_filter_lambda(runner):
    assert one(runner, "filter(array[1, -2, 3, -4], x -> x > 0)") \
        == ([1, 3],)


def test_reduce(runner):
    assert one(runner, "reduce(array[1, 2, 3, 4], 0, "
                       "(s, x) -> s + x, s -> s)") == (10,)
    assert one(runner, "reduce(array[2, 3], 1, (s, x) -> s * x, "
                       "s -> s * 10)") == (60,)


def test_match_functions(runner):
    r = one(runner, "any_match(array[1, 2], x -> x > 1), "
                    "all_match(array[1, 2], x -> x > 0), "
                    "none_match(array[1, 2], x -> x > 5)")
    assert r == (True, True, True)


def test_map_functions(runner):
    r = one(runner, "map(array['a', 'b'], array[1, 2])['b'], "
                    "element_at(map(array[1, 2], array['x', 'y']), 3), "
                    "cardinality(map(array['a'], array[1]))")
    assert r == (2, None, 1)
    r = one(runner, "map_keys(map(array['a', 'b'], array[1, 2])), "
                    "map_values(map(array['a', 'b'], array[1, 2]))")
    assert r == (["a", "b"], [1, 2])


def test_map_to_pylist(runner):
    assert one(runner, "map(array['k'], array[9])") == ({"k": 9},)


def test_unnest_standalone(runner):
    rows = runner.execute(
        "select x, o from unnest(array[10, 20, 30]) "
        "with ordinality as t(x, o)").rows
    assert rows == [(10, 1), (20, 2), (30, 3)]


def test_unnest_lateral(runner):
    rows = runner.execute(
        "select n_name, x from nation, "
        "unnest(array[n_nationkey, n_regionkey]) as u(x) "
        "where n_nationkey = 1").rows
    assert rows == [("ARGENTINA", 1), ("ARGENTINA", 1)]


def test_unnest_aggregate(runner):
    want = runner.execute(
        "select sum(n_nationkey) + sum(n_regionkey) from nation").rows
    got = runner.execute(
        "select sum(x) from nation, "
        "unnest(array[n_nationkey, n_regionkey]) as u(x)").rows
    assert got == want


def test_unnest_group_by(runner):
    rows = runner.execute(
        "select x, count(*) from nation, "
        "unnest(array[n_regionkey, n_regionkey]) as u(x) "
        "group by 1 order by 1").rows
    assert all(c == 10 for _, c in rows) and len(rows) == 5


def test_array_in_where(runner):
    rows = runner.execute(
        "select n_name from nation "
        "where contains(array[1, 3], n_nationkey) order by 1").rows
    assert [r[0] for r in rows] == ["ARGENTINA", "CANADA"]


def test_array_agg_on_split_column(runner):
    rows = runner.execute(
        "select cardinality(split(n_name, 'A')) from nation "
        "where n_nationkey = 0").rows
    assert rows == [(3,)]     # ALGERIA -> ['', 'LGERI', '']


def test_null_array(runner):
    assert one(runner, "cardinality(cast(null as array(bigint)))") == (None,)


def test_nested_transform_filter(runner):
    assert one(runner, "transform(filter(array[1, 2, 3, 4], x -> x % 2 = 0), "
                       "y -> y * y)") == ([4, 16],)


def test_nested_lambda_outer_param(runner):
    # inner lambda referencing the OUTER lambda's parameter
    assert one(runner, "filter(array[1, 2, 3], "
                       "x -> any_match(array[10, 20], y -> y = x * 10))") \
        == ([1, 2],)


def test_contains_null_three_valued(runner):
    r = one(runner, "contains(array[1, null], 2), "
                    "contains(array[1, null], 1), "
                    "contains(array[1, 2], 3)")
    assert r == (None, True, False)


def test_variadic_array_concat(runner):
    assert one(runner, "concat(array[1], array[2], array[3])") \
        == ([1, 2, 3],)


def test_map_duplicate_keys_error(runner):
    from presto_tpu.errors import QueryError
    with pytest.raises(QueryError, match="INVALID_FUNCTION_ARGUMENT"):
        runner.execute("select map(array[1, 1], array[10, 20])")


def test_element_at_index_zero_errors(runner):
    from presto_tpu.errors import QueryError
    with pytest.raises(QueryError, match="INVALID_FUNCTION_ARGUMENT"):
        runner.execute("select element_at(array[1, 2], 0)")


def test_distributed_unnest():
    from presto_tpu.exec.distributed import DistributedRunner
    d = DistributedRunner(tpch_sf=0.001, n_devices=8)
    rows = d.execute(
        "select sum(x) from nation, "
        "unnest(array[n_nationkey, n_regionkey]) as u(x)").rows
    assert rows == [(350,)]

"""Device profiling & cost attribution plane (obs/profiler.py).

Covers: executable introspection (compile seconds, cost/memory
analysis, invocation + device-time ledger) through real jit-cache
entries and the `system.runtime.executables` SQL surface; per-operator
device-time attribution and the EXPLAIN ANALYZE Executables/Verdict
sections; HBM gauge sampling with a fake device (XLA:CPU has no
memory_stats); Chrome-trace merge round-trip with device tracks;
history-sink rotation; and the bench regression gate's smoke mode
(tier-1 keeps the gate itself from rotting).
"""
import gzip
import json
import os
import subprocess
import sys

import pytest

from presto_tpu import types as T
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.obs import profiler
from presto_tpu.obs.metrics import REGISTRY, MetricsRegistry
from presto_tpu.obs.profiler import (
    EXECUTABLES, cost_verdict, hbm_totals, merge_chrome_traces,
    operator_scope, profiled, sample_hbm, write_merged_trace,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")


@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=0.01)


def _sql(runner, sql, **kw):
    return runner.execute(sql, **kw).rows


# -- executable registry ------------------------------------------------------

def test_jit_entry_registers_executable():
    import jax.numpy as jnp

    from presto_tpu.batch import Batch
    from presto_tpu.ops.jitcache import compact_jit
    b = Batch.from_pydict({"x": (T.BIGINT, list(range(10)))})
    compact_jit(b, 16)
    rows = {(e["name"], e["static_key"]): e
            for e in EXECUTABLES.snapshot(analyze=False)}
    rec = rows.get(("compact", "(16,)"))
    assert rec is not None
    assert rec["compiles"] >= 1
    assert rec["invocations"] >= 1
    assert rec["compile_seconds"] > 0.0
    del jnp  # imported for parity with sibling tests


def test_executable_cost_and_memory_analysis():
    from presto_tpu.batch import Batch
    from presto_tpu.ops.jitcache import pad_capacity_jit
    b = Batch.from_pydict({"x": (T.BIGINT, list(range(7)))})
    pad_capacity_jit(b, 32)
    rec = next(e for e in EXECUTABLES.snapshot(analyze=True)
               if e["name"] == "pad_capacity")
    # XLA:CPU supports both introspection APIs (conftest pins the
    # backend); bytes move through a pad, flops may legitimately be 0
    assert rec["bytes_accessed"] is not None
    assert rec["bytes_accessed"] > 0
    assert rec["arg_bytes"] is not None and rec["arg_bytes"] > 0
    assert rec["output_bytes"] is not None and rec["output_bytes"] > 0


def test_registry_is_bounded():
    reg = profiler.ExecutableRegistry(max_records=3)
    for i in range(6):
        reg.register("k", (i,))
    assert len(reg.snapshot(analyze=False)) == 3


def test_profiled_call_attributes_to_operator():
    """The contextvar plumbing end to end: a profiled dispatch charges
    the executable AND the operator scope's stats collector."""
    from presto_tpu.batch import Batch
    from presto_tpu.exec.stats import StatsCollector
    from presto_tpu.ops.jitcache import pad_capacity_jit
    b = Batch.from_pydict({"x": (T.BIGINT, list(range(5)))})
    stats = StatsCollector()
    node = object()
    # compile outside the profile context: the first (compiling) call
    # is charged as compile time, never as device time
    pad_capacity_jit(b, 64)
    with profiled(True), operator_scope(stats, node):
        pad_capacity_jit(b, 64)
    dev = stats.device_for(node)
    assert dev is not None
    assert dev["device_time_s"] > 0.0
    assert stats.by_node[node].device_time_s == dev["device_time_s"]
    used = stats.executables_used()
    assert used and used[0]["name"] == "pad_capacity"
    assert used[0]["invocations"] == 1


def test_profile_off_is_off():
    from presto_tpu.batch import Batch
    from presto_tpu.exec.stats import StatsCollector
    from presto_tpu.ops.jitcache import pad_capacity_jit
    b = Batch.from_pydict({"x": (T.BIGINT, list(range(5)))})
    stats = StatsCollector()
    node = object()
    with operator_scope(stats, node):   # no profiled()
        pad_capacity_jit(b, 128)
    assert stats.device_for(node) is None
    assert stats.executables_used() == []


# -- SQL + EXPLAIN ANALYZE surfaces ------------------------------------------

def test_explain_analyze_shows_device_columns_and_verdict(runner):
    rows = _sql(runner, """
        explain analyze
        select o_orderpriority, count(*)
          from orders join lineitem on l_orderkey = o_orderkey
         where l_quantity < 24 group by o_orderpriority""")
    text = "\n".join(r[0] for r in rows)
    assert "[device " in text
    assert "FLOP" in text
    assert "Executables (this query, by device time):" in text
    assert "Verdict: " in text
    assert ("input-bound" in text or "compute-bound" in text
            or "balanced" in text)
    # the join node row (not just the aggregate) carries device truth
    join_line = next(ln for ln in text.split("\n") if "- Join[" in ln)
    assert "[device " in join_line


def test_executables_sql_queryable(runner):
    _sql(runner, "select count(*) from lineitem where l_quantity < 5")
    rows = _sql(runner, """
        select name, compiles, compile_seconds, invocations,
               device_time_s, flops, bytes_accessed, arg_bytes
          from system.runtime.executables
         where invocations > 0 order by compile_seconds desc""")
    assert rows
    names = {r[0] for r in rows}
    assert "global_aggregate" in names or "grouped_aggregate" in names
    top = rows[0]
    assert top[2] > 0.0             # compile_seconds
    assert top[3] >= 1              # invocations
    # at least one executable has cost analysis populated
    assert any(r[5] is not None and r[5] > 0 for r in rows)


def test_operator_stats_history_device_columns(runner):
    _sql(runner,
         "select count(*) from orders where o_custkey > 100",
         properties={"profile": True})
    rows = _sql(runner, """
        select query_id, operator, device_time_s, flops, hbm_bytes
          from system.runtime.operator_stats""")
    assert rows
    # the profiled query charged device time to at least one operator
    assert any(r[2] > 0.0 for r in rows)
    assert any(r[3] > 0.0 for r in rows)


def test_cost_verdict_classification():
    from presto_tpu.connectors.spi import TableHandle
    from presto_tpu.exec.stats import NodeStats, StatsCollector
    from presto_tpu.planner.plan import TableScanNode

    compute_node = object()
    stats = StatsCollector()
    stats.by_node[compute_node] = NodeStats(wall_s=0.1,
                                            device_time_s=1.0)
    v = cost_verdict(stats)
    assert v["verdict"] == "compute-bound"
    assert v["compute_s"] == 1.0

    scan = TableScanNode(fields=(), catalog="tpch",
                         table=TableHandle("tpch", "default", "t"),
                         columns=())
    stats2 = StatsCollector()
    stats2.prefetch_stall_s = 1.0
    stats2.by_node[scan] = NodeStats(wall_s=2.0)      # decode wall
    stats2.by_node[compute_node] = NodeStats(device_time_s=0.5)
    v2 = cost_verdict(stats2)
    assert v2["verdict"] == "input-bound"
    assert v2["input_s"] == pytest.approx(3.0)

    assert cost_verdict(StatsCollector()) is None     # nothing profiled


# -- HBM telemetry ------------------------------------------------------------

class _FakeDevice:
    platform = "tpu"
    id = 0

    def __init__(self, in_use=1 << 30, peak=2 << 30):
        self._in_use, self._peak = in_use, peak

    def memory_stats(self):
        return {"bytes_in_use": self._in_use,
                "peak_bytes_in_use": self._peak,
                "bytes_limit": 16 << 30}


def test_sample_hbm_fake_device_gauges():
    reg = MetricsRegistry()
    docs = sample_hbm([_FakeDevice()], registry=reg)
    assert docs == [{"device": "tpu0", "device_id": 0,
                     "bytes_in_use": 1 << 30,
                     "peak_bytes_in_use": 2 << 30,
                     "bytes_limit": 16 << 30}]
    assert reg.gauge("hbm_in_use_bytes.tpu0").value == float(1 << 30)
    assert reg.gauge("hbm_peak_bytes.tpu0").value == float(2 << 30)


def test_sample_hbm_statless_backend_is_empty():
    class _Cpu:
        platform, id = "cpu", 0

        def memory_stats(self):
            return None
    reg = MetricsRegistry()
    assert sample_hbm([_Cpu()], registry=reg) == []
    totals = hbm_totals([_Cpu()], registry=reg)
    assert totals == {"bytesInUse": 0, "peakBytes": 0, "devices": 0}


def test_worker_info_and_nodes_federation():
    """Heartbeat payload carries the HBM sample; the coordinator's
    federator folds it into system.runtime.nodes and the node-labeled
    scrape series."""
    from presto_tpu.obs.exposition import (
        parse_exposition, render_exposition,
    )
    from presto_tpu.obs.metrics import NodeRegistry
    nodes = NodeRegistry()
    nodes.update("w1", state="ACTIVE", hbm_in_use_bytes=123,
                 hbm_peak_bytes=456)
    nodes.update("w2", state="ACTIVE")   # never reported an HBM sample
    text = render_exposition(registry=MetricsRegistry(), nodes=nodes)
    samples, types = parse_exposition(text)
    assert samples[("node_hbm_in_use_bytes", (("node", "w1"),))] == 123.0
    assert samples[("node_hbm_peak_bytes", (("node", "w1"),))] == 456.0
    assert ("node_hbm_in_use_bytes", (("node", "w2"),)) not in samples
    assert types["node_hbm_in_use_bytes"] == "gauge"


def test_nodes_table_has_hbm_columns(runner):
    rows = _sql(runner, """
        select node_id, hbm_in_use_bytes, hbm_peak_bytes
          from system.runtime.nodes""")
    assert rows
    for _, in_use, peak in rows:
        assert in_use >= 0 and peak >= 0   # CPU backend: zeros


# -- Chrome-trace merge (--profile-out) ---------------------------------------

def test_merge_device_trace_roundtrip(tmp_path):
    from presto_tpu.obs.trace import Tracer
    t = Tracer(node="merge-test")
    t.enable(True)
    with t.span("query", query_id="q1"):
        with t.span("op:Join"):
            pass
    # a fake jax.profiler output tree with a gzipped Chrome trace
    sess = tmp_path / "plugins" / "profile" / "2026_08_03_00_00_00"
    sess.mkdir(parents=True)
    device_events = [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "name": "fusion.123", "pid": 1, "tid": 1,
         "ts": 100.0, "dur": 42.0, "cat": "kernel"},
    ]
    with gzip.open(sess / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": device_events}, f)

    out = tmp_path / "merged_trace.json"
    write_merged_trace(str(out), t.export(), str(tmp_path))
    with open(out) as f:
        merged = json.load(f)
    names = [e.get("name") for e in merged["traceEvents"]]
    assert "op:Join" in names and "query" in names     # host spans
    assert "fusion.123" in names                       # device track
    host_pids = {e["pid"] for e in merged["traceEvents"]
                 if e.get("name") in ("op:Join", "query")}
    dev_pids = {e["pid"] for e in merged["traceEvents"]
                if e.get("name") == "fusion.123"}
    assert host_pids.isdisjoint(dev_pids)   # remapped, no collision


def test_merge_ignores_stale_profile_sessions(tmp_path):
    """A reused --profile-out DIR accumulates one plugins/profile/<ts>
    subdir per run; only the NEWEST session's kernels may be merged."""
    for i, (ts, name) in enumerate((("2026_08_03_00_00_00", "old.kern"),
                                    ("2026_08_03_01_00_00", "new.kern"))):
        sess = tmp_path / "plugins" / "profile" / ts
        sess.mkdir(parents=True)
        p = sess / "host.trace.json"
        with open(p, "w") as f:
            json.dump({"traceEvents": [
                {"ph": "X", "name": name, "pid": 1, "tid": 1,
                 "ts": 0.0, "dur": 1.0}]}, f)
        os.utime(p, (1000.0 + i, 1000.0 + i))
    out = tmp_path / "merged.json"
    write_merged_trace(str(out), [], str(tmp_path))
    with open(out) as f:
        names = [e.get("name") for e in json.load(f)["traceEvents"]]
    assert "new.kern" in names and "old.kern" not in names


def test_registry_evicts_coldest_and_readmits():
    """The cap drops the least-invoked record, and a dropped record's
    live _TimedEntry readmits it on the next dispatch — hot kernels can
    never go permanently invisible (counts survive)."""
    from presto_tpu.obs.profiler import ExecutableRegistry
    reg = ExecutableRegistry(max_records=2)
    hot = reg.register("hot", (1,))
    hot.invocations = 50
    cold = reg.register("cold", (2,))
    reg.register("newcomer", (3,))          # evicts "cold", not "hot"
    names = {r["name"] for r in reg.snapshot(analyze=False)}
    assert names == {"hot", "newcomer"}
    assert cold.evicted and not hot.evicted
    cold.invocations = 7
    reg.readmit(cold)                        # what a dispatch would do
    assert not cold.evicted
    rows = {r["name"]: r for r in reg.snapshot(analyze=False)}
    assert rows["cold"]["invocations"] == 7  # ledger survived eviction
    assert "hot" in rows
    reg.reset()                              # reset keeps the contract
    assert cold.evicted and hot.evicted


def test_merge_survives_missing_device_trace(tmp_path):
    # mesh flights from earlier suites would legitimately add their
    # "mesh rounds" track to the merge — drain the process-global log
    # so the missing-device-trace contract is what's measured
    from presto_tpu.obs.flight import FLIGHTS
    FLIGHTS.clear()
    out = tmp_path / "merged.json"
    write_merged_trace(str(out), [], str(tmp_path / "nowhere"))
    with open(out) as f:
        assert json.load(f)["traceEvents"] == []


def test_merge_chrome_traces_pure():
    host = {"traceEvents": [{"ph": "X", "name": "h", "pid": 1,
                             "tid": 1, "ts": 0, "dur": 1}],
            "displayTimeUnit": "ms"}
    merged = merge_chrome_traces(host, [
        {"ph": "X", "name": "d", "pid": 1, "tid": 1, "ts": 0, "dur": 1}])
    assert len(merged["traceEvents"]) == 2
    pids = [e["pid"] for e in merged["traceEvents"]]
    assert len(set(pids)) == 2
    assert merged["displayTimeUnit"] == "ms"


# -- jit compile histogram (satellite) ----------------------------------------

def test_jit_compile_seconds_histogram():
    import jax
    import jax.numpy as jnp

    from presto_tpu.obs.metrics import Histogram
    from presto_tpu.ops.jitcache import _TimedEntry
    h = REGISTRY.histogram("jit_compile_seconds")
    assert isinstance(h, Histogram)
    # a fresh entry guarantees a first-call compile regardless of what
    # the rest of the (single-process) suite compiled before
    entry = _TimedEntry("hist_test_kernel", jax.jit(lambda x: x + 1))
    before = h.count
    entry(jnp.arange(4))
    assert h.count >= before + 1
    # the scrape-compatible running sum is still a counter
    assert REGISTRY.counter("jit_compile_seconds_total").value > 0.0


# -- history rotation (satellite) ---------------------------------------------

def test_history_sink_rotation(tmp_path):
    from presto_tpu.obs.history import QueryHistory
    sink = tmp_path / "history.jsonl"
    h = QueryHistory(max_records=10)
    h.configure(sink_path=str(sink), max_sink_bytes=400)
    dropped = REGISTRY.counter("history_records_dropped_total")
    before = dropped.value
    for i in range(40):
        h.add({"query_id": f"q{i:04d}", "state": "FINISHED",
               "query": "select 1", "elapsed_ms": 1.0})
    assert sink.exists() or (tmp_path / "history.jsonl.1").exists()
    assert (tmp_path / "history.jsonl.1").exists()
    # >= 2 rotations happened at this cap, so the first generation's
    # records were dropped and counted
    assert dropped.value > before
    # every surviving line is valid JSON
    for p in (sink, tmp_path / "history.jsonl.1"):
        if p.exists():
            for line in p.read_text().splitlines():
                json.loads(line)


def test_history_sink_unbounded_when_disabled(tmp_path):
    from presto_tpu.obs.history import QueryHistory
    sink = tmp_path / "h.jsonl"
    h = QueryHistory()
    h.configure(sink_path=str(sink), max_sink_bytes=0)   # 0 = unbounded
    for i in range(50):
        h.add({"query_id": f"q{i}", "pad": "x" * 64})
    assert not (tmp_path / "h.jsonl.1").exists()
    assert len(sink.read_text().splitlines()) == 50


# -- regression gate (satellite: --smoke runs inside tier-1) ------------------

def test_check_bench_regression_smoke():
    out = subprocess.run(
        [sys.executable,
         os.path.join(_TOOLS, "check_bench_regression.py"), "--smoke"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    verdict = json.loads(out.stdout)
    assert verdict["verdict"] == "pass"
    assert verdict["self_comparison"] == "pass"
    assert verdict["degraded_comparison"] == "fail"


def test_check_bench_regression_catches_drop(tmp_path):
    baseline = {"metric": "m_q1_x", "value": 100, "vs_baseline": 10.0,
                "sub_metrics": [
                    {"metric": "m_q3_x", "value": 50,
                     "vs_baseline": 2.0}]}
    run = {"metric": "m_q1_x", "value": 100, "vs_baseline": 10.0,
           "sub_metrics": [
               {"metric": "m_q3_x", "value": 20, "vs_baseline": 0.8}]}
    bp, rp = tmp_path / "base.json", tmp_path / "run.json"
    bp.write_text(json.dumps(baseline))
    rp.write_text(json.dumps(run))
    tool = os.path.join(_TOOLS, "check_bench_regression.py")
    out = subprocess.run(
        [sys.executable, tool, "--baseline", str(bp), "--run", str(rp)],
        capture_output=True, text=True)
    assert out.returncode == 1
    verdict = json.loads(out.stdout)
    assert verdict["failed"] == ["m_q3_x"]
    # a generous per-query tolerance lets the same run pass
    out2 = subprocess.run(
        [sys.executable, tool, "--baseline", str(bp), "--run", str(rp),
         "--tolerance-for", "q3=70"],
        capture_output=True, text=True)
    assert out2.returncode == 0, out2.stdout


def test_check_bench_regression_log_mode(tmp_path):
    """A captured stdout log (noise + several summary lines) parses to
    the LAST summary."""
    lines = [
        "[bench] q6 starting",
        json.dumps({"metric": "m_q1_x", "vs_baseline": 1.0,
                    "sub_metrics": []}),
        json.dumps({"metric": "m_q1_x", "vs_baseline": 10.0,
                    "sub_metrics": [{"metric": "m_q6_x",
                                     "vs_baseline": 5.0}]}),
    ]
    rp = tmp_path / "log.txt"
    rp.write_text("\n".join(lines))
    bp = tmp_path / "base.json"
    bp.write_text(lines[-1])
    out = subprocess.run(
        [sys.executable,
         os.path.join(_TOOLS, "check_bench_regression.py"),
         "--baseline", str(bp), "--run", str(rp)],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout


# -- doc drift (satellite) ----------------------------------------------------

def test_metric_doc_drift_check_green():
    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "check_metric_names.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_metric_doc_drift_catches_unknown_doc_name(tmp_path):
    doc = tmp_path / "observability.md"
    doc.write_text("The doc names `totally_fake_metric_total` only.\n")
    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "check_metric_names.py"),
         "--docs", str(doc)],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "totally_fake_metric_total" in out.stderr
    # the reverse direction fires too: real families are undocumented
    # in this stub doc
    assert "not documented" in out.stderr

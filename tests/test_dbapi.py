"""DB-API 2.0 interface and CLI output formats (reference presto-jdbc
PrestoConnection/PrestoResultSet; presto-cli OutputFormat)."""
import json

import pytest

from presto_tpu import dbapi


@pytest.fixture(scope="module")
def server():
    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.server.protocol import PrestoTpuServer
    srv = PrestoTpuServer(runner=LocalRunner(tpch_sf=0.001))
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture()
def conn(server):
    c = dbapi.connect(port=server.port, catalog="tpch", schema="default")
    yield c
    c.close()


def test_module_globals():
    assert dbapi.apilevel == "2.0"
    assert dbapi.paramstyle == "qmark"


def test_basic_query(conn):
    cur = conn.cursor()
    cur.execute("select n_name, n_nationkey from nation "
                "where n_nationkey < 3 order by 2")
    assert cur.rowcount == 3
    assert [d[0] for d in cur.description] == ["n_name", "n_nationkey"]
    assert cur.fetchone() == ("ALGERIA", 0)
    assert cur.fetchmany(1) == [("ARGENTINA", 1)]
    assert cur.fetchall() == [("BRAZIL", 2)]
    assert cur.fetchone() is None


def test_cursor_iteration(conn):
    cur = conn.cursor()
    cur.execute("select n_nationkey from nation order by 1 limit 4")
    assert [r[0] for r in cur] == [0, 1, 2, 3]


def test_qmark_parameters(conn):
    cur = conn.cursor()
    cur.execute("select n_name from nation where n_nationkey = ?", (3,))
    assert cur.fetchall() == [("CANADA",)]
    cur.execute("select n_name from nation where n_name = ?", ("PERU",))
    assert cur.fetchall() == [("PERU",)]


def test_string_escaping(conn):
    cur = conn.cursor()
    cur.execute("select ? as v", ("it's",))
    assert cur.fetchall() == [("it's",)]


def test_question_mark_in_string_literal(conn):
    cur = conn.cursor()
    cur.execute("select '?' as q, ? as v", (7,))
    assert cur.fetchall() == [("?", 7)]


def test_parameter_count_mismatch(conn):
    cur = conn.cursor()
    with pytest.raises(dbapi.ProgrammingError):
        cur.execute("select ? as v", (1, 2))
    with pytest.raises(dbapi.ProgrammingError):
        cur.execute("select ?, ? ", (1,))


def test_date_parameter(conn):
    import datetime
    cur = conn.cursor()
    cur.execute("select ? < date '2021-01-01'",
                (datetime.date(2020, 5, 5),))
    assert cur.fetchall() == [(True,)]


def test_error_maps_to_database_error(conn):
    cur = conn.cursor()
    with pytest.raises(dbapi.DatabaseError):
        cur.execute("select no_such from nation")


def test_closed_cursor_rejects(conn):
    cur = conn.cursor()
    cur.close()
    with pytest.raises(dbapi.InterfaceError):
        cur.execute("select 1")


def test_context_managers(server):
    with dbapi.connect(port=server.port, catalog="tpch") as c:
        with c.cursor() as cur:
            cur.execute("select count(*) from region")
            assert cur.fetchone() == (5,)


def test_placeholder_in_comment_ignored(conn):
    cur = conn.cursor()
    cur.execute("select ? as v -- trailing comment?", (5,))
    assert cur.fetchall() == [(5,)]
    cur.execute("select ? as v /* block ? comment */", (6,))
    assert cur.fetchall() == [(6,)]


def test_escaped_quote_in_string(conn):
    cur = conn.cursor()
    cur.execute("select 'it''s' as s, ? as v", (1,))
    assert cur.fetchall() == [("it's", 1)]


def test_empty_params_with_placeholder_rejected(conn):
    cur = conn.cursor()
    with pytest.raises(dbapi.ProgrammingError):
        cur.execute("select ? as v", ())


def test_commit_without_transaction_ok(conn):
    conn.commit()
    conn.rollback()


# -- CLI output formats ------------------------------------------------------

COLS = [("a", "bigint"), ("b", "varchar")]
ROWS = [(1, "x,y"), (2, None)]


def test_format_csv():
    from presto_tpu.cli import format_rows
    out = format_rows(COLS, ROWS, "CSV")
    assert out == '"1","x,y"\n"2",'
    assert format_rows(COLS, ROWS, "CSV_HEADER").startswith('"a","b"\n')


def test_format_tsv():
    from presto_tpu.cli import format_rows
    assert format_rows(COLS, [(1, "a\tb")], "TSV") == "1\ta\\tb"


def test_format_json():
    from presto_tpu.cli import format_rows
    lines = format_rows(COLS, ROWS, "JSON").split("\n")
    assert json.loads(lines[0]) == {"a": 1, "b": "x,y"}
    assert json.loads(lines[1]) == {"a": 2, "b": None}


def test_cli_execute_csv(server, capsys):
    from presto_tpu.cli import main
    rc = main(["--server", f"http://127.0.0.1:{server.port}",
               "--catalog", "tpch", "--output-format", "CSV_HEADER",
               "-e", "select n_nationkey from nation order by 1 limit 2"])
    assert rc == 0
    out = capsys.readouterr().out.strip().split("\n")
    assert out == ['"n_nationkey"', '"0"', '"1"']

"""Residual (ON-clause) predicates on LEFT/FULL OUTER joins vs the
SQLite oracle: the filter gates matches but never drops probe rows, and
a FULL join's unmatched-build tail counts only residual-surviving
matches (reference operator/LookupJoinOperator.java +
sql/gen/JoinFilterFunctionCompiler.java)."""
import sqlite3

import pytest

# tier-1 budget: excluded from `pytest -m 'not slow'` — residual-join kernels compile-bound
# (see tools/check_tier1_time.py; ~42s)
pytestmark = pytest.mark.slow

from test_sql import compare, oracle, runner  # noqa: F401 (fixtures)

QUERIES = [
    # LEFT join, unique build, residual over both sides
    """select c_custkey, o_orderkey from customer
       left join orders on c_custkey = o_custkey
                       and o_totalprice > 150000
       order by c_custkey, o_orderkey""",
    # LEFT join residual referencing only the probe side
    """select c_custkey, count(o_orderkey) from customer
       left join orders on c_custkey = o_custkey and c_acctbal > 0
       group by c_custkey order by c_custkey""",
    # LEFT join, multi-match build (orders per cust), arithmetic residual
    """select o_orderkey, l_linenumber from orders
       left join lineitem on o_orderkey = l_orderkey
                         and l_quantity * 2 > 60
       order by o_orderkey, l_linenumber""",
    # FULL join with residual: both null-extension sides must honor it
    """select n_name, s_name from nation
       full outer join supplier on n_nationkey = s_nationkey
                               and s_acctbal > 4000
       order by n_name nulls last, s_name nulls last""",
    # residual that is never true: LEFT degenerates to all-null payload
    """select c_custkey, o_orderkey from customer
       left join orders on c_custkey = o_custkey and 1 = 0
       order by c_custkey limit 50""",
]


@pytest.mark.parametrize("sql", QUERIES, ids=range(len(QUERIES)))
def test_outer_residual_matches_oracle(runner, oracle, sql):
    if "full outer" in sql and sqlite3.sqlite_version_info < (3, 39):
        # the ORACLE can't check this one: sqlite grew FULL OUTER JOIN
        # in 3.39 (the engine side is covered by
        # test_outer_residual_distributed and test_full_outer.py)
        pytest.skip("oracle sqlite < 3.39 lacks FULL OUTER JOIN")
    compare(runner, oracle, sql, rel=1e-9)


def test_outer_residual_distributed(runner):
    from presto_tpu.exec.distributed import DistributedRunner
    dist = DistributedRunner(catalogs=runner.session.catalogs,
                             n_devices=8, rows_per_batch=1 << 12)
    for sql in (QUERIES[0], QUERIES[3]):
        want = runner.execute(sql).rows
        got = dist.execute(sql).rows
        assert got == want


def test_outer_residual_under_spill(runner):
    """Partitioned (spilled-build) probing keeps outer+residual
    semantics: each probe row hashes to one partition."""
    from presto_tpu.exec.runner import LocalRunner
    r = LocalRunner(catalogs=runner.session.catalogs,
                    rows_per_batch=1 << 12)
    r.session.properties["query_max_memory"] = 200_000
    r.session.properties["spill_partitions"] = 4
    sql = """select o_orderkey, count(l_linenumber) c from orders
             left join lineitem on o_orderkey = l_orderkey
                               and l_quantity > 25
             group by o_orderkey order by o_orderkey limit 100"""
    want = runner.execute(sql).rows
    got = r.execute(sql).rows
    assert got == want
    stats = r.session.last_memory_stats
    assert stats.spilled_bytes > 0

"""Iterative rule engine + rule catalog unit tests, each asserted with
the plan-pattern DSL (reference sql/planner/assertions/PlanMatchPattern
.java + per-rule tests under sql/planner/iterative/rule/test/)."""
import pytest

from presto_tpu import types as T
from presto_tpu.expr import ir
from presto_tpu.planner.plan import (
    DistinctNode, FilterNode, LimitNode, ProjectNode, SortKeySpec,
    SortNode, TopNNode, UnionNode, ValuesNode,
)
from presto_tpu.planner.rules import (
    Pattern, iterative_optimize, pattern,
)
from presto_tpu.sql.analyzer import Field


def f(name="x", t=T.BIGINT):
    return Field(name, t)


def values(n_rows=3):
    return ValuesNode(fields=(f(),), rows=tuple((i,) for i in range(n_rows)))


def assert_plan(node, pat: Pattern):
    """PlanMatchPattern.assertPlan analogue: the pattern must match the
    node chain from the root."""
    assert pat.matches(node), f"plan {node!r} does not match {pat!r}"


def test_merge_limits():
    plan = LimitNode(child=LimitNode(child=values(), count=2), count=5)
    out = iterative_optimize(plan)
    assert_plan(out, pattern(ValuesNode,
                             where=lambda v: len(v.rows) == 2))


def test_merge_limit_with_sort_to_topn():
    plan = LimitNode(
        child=SortNode(child=values(), keys=(SortKeySpec(0, True, None),)),
        count=2)
    out = iterative_optimize(plan)
    assert_plan(out, pattern(
        TopNNode, where=lambda n: n.count == 2,
        child=pattern(ValuesNode)))


def test_limit_zero_becomes_empty_values():
    plan = LimitNode(child=values(), count=0)
    out = iterative_optimize(plan)
    assert_plan(out, pattern(ValuesNode,
                             where=lambda v: len(v.rows) == 0))


def test_merge_filters():
    p = ir.call("gt", T.BOOLEAN, ir.input_ref(0, T.BIGINT),
                ir.lit(1, T.BIGINT))
    q = ir.call("lt", T.BOOLEAN, ir.input_ref(0, T.BIGINT),
                ir.lit(5, T.BIGINT))
    plan = FilterNode(child=FilterNode(child=values(), predicate=q),
                      predicate=p)
    out = iterative_optimize(plan)
    assert_plan(out, pattern(FilterNode, child=pattern(ValuesNode)))


def test_remove_true_filter():
    plan = FilterNode(child=values(), predicate=ir.lit(True, T.BOOLEAN))
    out = iterative_optimize(plan)
    assert_plan(out, pattern(ValuesNode,
                             where=lambda v: len(v.rows) == 3))


def test_false_filter_becomes_empty():
    plan = FilterNode(child=values(), predicate=ir.lit(False, T.BOOLEAN))
    out = iterative_optimize(plan)
    assert_plan(out, pattern(ValuesNode,
                             where=lambda v: len(v.rows) == 0))


def test_push_limit_through_project():
    proj = ProjectNode(child=values(),
                       exprs=(ir.input_ref(0, T.BIGINT),),
                       fields=(f("y"),))
    out = iterative_optimize(LimitNode(child=proj, count=2))
    # limit reached the values leaf through the projection
    assert_plan(out, pattern(
        ProjectNode, child=pattern(ValuesNode,
                                   where=lambda v: len(v.rows) == 2)))


def test_push_limit_through_union():
    u = UnionNode(children_=(values(5), values(5)), fields=(f(),),
                  distinct=False)
    out = iterative_optimize(LimitNode(child=u, count=2))
    assert isinstance(out, LimitNode)
    union = out.child
    assert isinstance(union, UnionNode)
    for c in union.children:
        assert isinstance(c, ValuesNode) and len(c.rows) == 2


def test_identity_projection_removed():
    proj = ProjectNode(child=values(),
                       exprs=(ir.input_ref(0, T.BIGINT),),
                       fields=(f("x"),))
    out = iterative_optimize(proj)
    assert_plan(out, pattern(ValuesNode))


def test_inline_projections():
    inner = ProjectNode(
        child=values(),
        exprs=(ir.call("add", T.BIGINT, ir.input_ref(0, T.BIGINT),
                       ir.lit(1, T.BIGINT)),),
        fields=(f("a"),))
    outer = ProjectNode(
        child=inner,
        exprs=(ir.call("mul", T.BIGINT, ir.input_ref(0, T.BIGINT),
                       ir.lit(2, T.BIGINT)),),
        fields=(f("b"),))
    out = iterative_optimize(outer)
    assert_plan(out, pattern(ProjectNode, child=pattern(ValuesNode)))
    # composed expression: (x + 1) * 2
    e = out.exprs[0]
    assert isinstance(e, ir.Call) and e.name == "mul"
    assert isinstance(e.args[0], ir.Call) and e.args[0].name == "add"


def test_push_filter_through_project():
    proj = ProjectNode(child=values(),
                       exprs=(ir.input_ref(0, T.BIGINT),),
                       fields=(f("y"),))
    pred = ir.call("gt", T.BOOLEAN, ir.input_ref(0, T.BIGINT),
                   ir.lit(0, T.BIGINT))
    out = iterative_optimize(FilterNode(child=proj, predicate=pred))
    # the renaming projection stays; the filter moved below it
    assert_plan(out, pattern(
        ProjectNode,
        child=pattern(FilterNode, child=pattern(ValuesNode))))


def test_distinct_over_distinct():
    plan = DistinctNode(child=DistinctNode(child=values()))
    out = iterative_optimize(plan)
    assert_plan(out, pattern(DistinctNode, child=pattern(ValuesNode)))


def test_end_to_end_queries_unchanged():
    """Existing query results are unchanged with the rule engine on."""
    from presto_tpu.exec.runner import LocalRunner
    r = LocalRunner(tpch_sf=0.01)
    rows = r.execute(
        "select l_returnflag, count(*) from ("
        "  select * from lineitem where l_quantity > 0 limit 1000"
        ") t group by 1 order by 1").rows
    assert sum(c for _, c in rows) == 1000
    rows2 = r.execute(
        "select * from (select 1 x union all select 2) t "
        "order by x limit 1").rows
    assert rows2 == [(1,)]


# -- eager aggregation (partial agg pushed through a join) -------------------

def _q55ish_runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(catalog="tpcds", tpch_sf=0.01)


def test_push_partial_agg_through_join_plan_shape():
    """Agg(Project*(Join)) with probe-side aggregate inputs splits into
    final-over-join-over-partial (reference
    iterative/rule/PushPartialAggregationThroughJoin.java)."""
    from presto_tpu.planner.plan import AggregationNode, JoinNode

    r = _q55ish_runner()
    plan = r.plan("""
        select i_brand_id, sum(ss_ext_sales_price) p
        from store_sales, item
        where ss_item_sk = i_item_sk group by i_brand_id""")

    steps = []

    def walk(n):
        if isinstance(n, AggregationNode):
            steps.append(n.step)
        for c in n.children:
            walk(c)
    walk(plan.root)
    assert steps == ["final", "partial"], steps

    # the partial must sit BELOW the join, the final ABOVE it
    def find(n, cls, out):
        if isinstance(n, cls):
            out.append(n)
        for c in n.children:
            find(c, cls, out)
    joins = []
    find(plan.root, JoinNode, joins)
    aggs_below = []
    find(joins[0], AggregationNode, aggs_below)
    assert [a.step for a in aggs_below] == ["partial"]


def test_push_partial_agg_build_side_keys_collapse_to_join_key():
    """Group keys that are bare build-side columns do not widen the
    pushed grouping: the partial groups by the probe join key alone."""
    from presto_tpu.planner.plan import AggregationNode

    from presto_tpu.exec.runner import LocalRunner
    r = LocalRunner(tpch_sf=0.01)
    plan = r.plan("""
        select c_custkey, c_name, c_address, c_phone, c_acctbal,
               sum(o_totalprice)
        from orders, customer where o_custkey = c_custkey
        group by 1, 2, 3, 4, 5""")
    partials = []

    def walk(n):
        if isinstance(n, AggregationNode) and n.step == "partial":
            partials.append(n)
        for c in n.children:
            walk(c)
    walk(plan.root)
    assert len(partials) == 1
    assert len(partials[0].group_indices) == 1


def test_push_partial_agg_declines_wide_keys():
    """>4 pushed grouping keys (probe-side) would hit the variadic-sort
    compile wall; the rewrite declines and keeps a single-step agg."""
    from presto_tpu.planner.plan import AggregationNode

    from presto_tpu.exec.runner import LocalRunner
    r = LocalRunner(tpch_sf=0.01)
    plan = r.plan("""
        select o_orderpriority, o_orderstatus, o_clerk, o_shippriority,
               o_orderdate, sum(o_totalprice)
        from orders, customer where o_custkey = c_custkey
        group by 1, 2, 3, 4, 5""")
    steps = []

    def walk(n):
        if isinstance(n, AggregationNode):
            steps.append(n.step)
        for c in n.children:
            walk(c)
    walk(plan.root)
    assert steps == ["single"], steps


def test_push_partial_agg_results_match_unpushed():
    """The rewrite must not change results: compare against the same
    query with the rewrite disabled via session property."""
    r = _q55ish_runner()
    sql = """
        select i_brand_id, sum(ss_ext_sales_price) p, count(*) c,
               min(ss_quantity) q
        from store_sales, item
        where ss_item_sk = i_item_sk and i_manager_id < 40
        group by i_brand_id order by i_brand_id"""
    pushed = r.execute(sql).rows
    plain = r.execute(
        sql, properties={
            "push_partial_aggregation_through_join": "false"}).rows
    assert len(pushed) == len(plain) and len(pushed) > 0
    for a, b in zip(pushed, plain):
        assert a[0] == b[0] and a[2] == b[2] and a[3] == b[3]
        assert abs(a[1] - b[1]) <= 1e-9 * max(abs(b[1]), 1.0)


def test_push_partial_agg_cardinality_gate():
    """When statistics prove the pushed partial cannot shrink its input
    (near-unique grouping keys), the rewrite declines; grouping by the
    join key itself still pushes (the q3 shape)."""
    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.planner.plan import AggregationNode

    r = LocalRunner(tpch_sf=0.01)

    def steps(plan):
        out = []

        def walk(n):
            if isinstance(n, AggregationNode):
                out.append(n.step)
            for c in n.children:
                walk(c)
        walk(plan.root)
        return out

    bad = r.plan("select l_orderkey, sum(l_quantity) from lineitem, "
                 "orders where l_partkey = o_custkey group by 1")
    assert steps(bad) == ["single"], steps(bad)
    good = r.plan("select l_orderkey, sum(l_quantity) from lineitem, "
                  "orders where l_orderkey = o_orderkey group by 1")
    assert steps(good) == ["final", "partial"], steps(good)

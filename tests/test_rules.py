"""Iterative rule engine + rule catalog unit tests, each asserted with
the plan-pattern DSL (reference sql/planner/assertions/PlanMatchPattern
.java + per-rule tests under sql/planner/iterative/rule/test/)."""
import pytest

from presto_tpu import types as T
from presto_tpu.expr import ir
from presto_tpu.planner.plan import (
    DistinctNode, FilterNode, LimitNode, ProjectNode, SortKeySpec,
    SortNode, TopNNode, UnionNode, ValuesNode,
)
from presto_tpu.planner.rules import (
    Pattern, iterative_optimize, pattern,
)
from presto_tpu.sql.analyzer import Field


def f(name="x", t=T.BIGINT):
    return Field(name, t)


def values(n_rows=3):
    return ValuesNode(fields=(f(),), rows=tuple((i,) for i in range(n_rows)))


def assert_plan(node, pat: Pattern):
    """PlanMatchPattern.assertPlan analogue: the pattern must match the
    node chain from the root."""
    assert pat.matches(node), f"plan {node!r} does not match {pat!r}"


def test_merge_limits():
    plan = LimitNode(child=LimitNode(child=values(), count=2), count=5)
    out = iterative_optimize(plan)
    assert_plan(out, pattern(ValuesNode,
                             where=lambda v: len(v.rows) == 2))


def test_merge_limit_with_sort_to_topn():
    plan = LimitNode(
        child=SortNode(child=values(), keys=(SortKeySpec(0, True, None),)),
        count=2)
    out = iterative_optimize(plan)
    assert_plan(out, pattern(
        TopNNode, where=lambda n: n.count == 2,
        child=pattern(ValuesNode)))


def test_limit_zero_becomes_empty_values():
    plan = LimitNode(child=values(), count=0)
    out = iterative_optimize(plan)
    assert_plan(out, pattern(ValuesNode,
                             where=lambda v: len(v.rows) == 0))


def test_merge_filters():
    p = ir.call("gt", T.BOOLEAN, ir.input_ref(0, T.BIGINT),
                ir.lit(1, T.BIGINT))
    q = ir.call("lt", T.BOOLEAN, ir.input_ref(0, T.BIGINT),
                ir.lit(5, T.BIGINT))
    plan = FilterNode(child=FilterNode(child=values(), predicate=q),
                      predicate=p)
    out = iterative_optimize(plan)
    assert_plan(out, pattern(FilterNode, child=pattern(ValuesNode)))


def test_remove_true_filter():
    plan = FilterNode(child=values(), predicate=ir.lit(True, T.BOOLEAN))
    out = iterative_optimize(plan)
    assert_plan(out, pattern(ValuesNode,
                             where=lambda v: len(v.rows) == 3))


def test_false_filter_becomes_empty():
    plan = FilterNode(child=values(), predicate=ir.lit(False, T.BOOLEAN))
    out = iterative_optimize(plan)
    assert_plan(out, pattern(ValuesNode,
                             where=lambda v: len(v.rows) == 0))


def test_push_limit_through_project():
    proj = ProjectNode(child=values(),
                       exprs=(ir.input_ref(0, T.BIGINT),),
                       fields=(f("y"),))
    out = iterative_optimize(LimitNode(child=proj, count=2))
    # limit reached the values leaf through the projection
    assert_plan(out, pattern(
        ProjectNode, child=pattern(ValuesNode,
                                   where=lambda v: len(v.rows) == 2)))


def test_push_limit_through_union():
    u = UnionNode(children_=(values(5), values(5)), fields=(f(),),
                  distinct=False)
    out = iterative_optimize(LimitNode(child=u, count=2))
    assert isinstance(out, LimitNode)
    union = out.child
    assert isinstance(union, UnionNode)
    for c in union.children:
        assert isinstance(c, ValuesNode) and len(c.rows) == 2


def test_identity_projection_removed():
    proj = ProjectNode(child=values(),
                       exprs=(ir.input_ref(0, T.BIGINT),),
                       fields=(f("x"),))
    out = iterative_optimize(proj)
    assert_plan(out, pattern(ValuesNode))


def test_inline_projections():
    inner = ProjectNode(
        child=values(),
        exprs=(ir.call("add", T.BIGINT, ir.input_ref(0, T.BIGINT),
                       ir.lit(1, T.BIGINT)),),
        fields=(f("a"),))
    outer = ProjectNode(
        child=inner,
        exprs=(ir.call("mul", T.BIGINT, ir.input_ref(0, T.BIGINT),
                       ir.lit(2, T.BIGINT)),),
        fields=(f("b"),))
    out = iterative_optimize(outer)
    assert_plan(out, pattern(ProjectNode, child=pattern(ValuesNode)))
    # composed expression: (x + 1) * 2
    e = out.exprs[0]
    assert isinstance(e, ir.Call) and e.name == "mul"
    assert isinstance(e.args[0], ir.Call) and e.args[0].name == "add"


def test_push_filter_through_project():
    proj = ProjectNode(child=values(),
                       exprs=(ir.input_ref(0, T.BIGINT),),
                       fields=(f("y"),))
    pred = ir.call("gt", T.BOOLEAN, ir.input_ref(0, T.BIGINT),
                   ir.lit(0, T.BIGINT))
    out = iterative_optimize(FilterNode(child=proj, predicate=pred))
    # the renaming projection stays; the filter moved below it
    assert_plan(out, pattern(
        ProjectNode,
        child=pattern(FilterNode, child=pattern(ValuesNode))))


def test_distinct_over_distinct():
    plan = DistinctNode(child=DistinctNode(child=values()))
    out = iterative_optimize(plan)
    assert_plan(out, pattern(DistinctNode, child=pattern(ValuesNode)))


def test_end_to_end_queries_unchanged():
    """Existing query results are unchanged with the rule engine on."""
    from presto_tpu.exec.runner import LocalRunner
    r = LocalRunner(tpch_sf=0.01)
    rows = r.execute(
        "select l_returnflag, count(*) from ("
        "  select * from lineitem where l_quantity > 0 limit 1000"
        ") t group by 1 order by 1").rows
    assert sum(c for _, c in rows) == 1000
    rows2 = r.execute(
        "select * from (select 1 x union all select 2) t "
        "order by x limit 1").rows
    assert rows2 == [(1,)]

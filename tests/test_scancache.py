"""Device-resident scan cache + prefetching scan pipeline
(exec/scancache.py): warm-hit parity, write invalidation, eviction
under a small memory limit, prefetcher shutdown hygiene, ragged-split
capacity padding, and the observability surfaces.
"""
import threading
import time

import pytest

from presto_tpu import types as T
from presto_tpu.batch import Batch, Schema
from presto_tpu.connectors.spi import (
    CatalogManager, Connector, ConnectorMetadata, ConnectorSplitManager,
    PageSource, Split, TableHandle,
)
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec import scancache
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.exec.scancache import CACHE, ScanCache, ScanOptions
from presto_tpu.obs.metrics import REGISTRY

SF = 0.01


def _counter(name: str) -> float:
    return REGISTRY.counter(name).value


@pytest.fixture(autouse=True)
def fresh_cache():
    """Deterministic cache state per test; the process-wide limit is
    restored afterwards so other modules see the default."""
    CACHE.clear()
    yield
    CACHE.clear()
    CACHE.set_limit(scancache.DEFAULT_CACHE_BYTES)


@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=SF)


# -- correctness: warm hits, escape hatch, invalidation ----------------------

def test_warm_hit_parity(runner):
    q = ("select l_returnflag, count(*), sum(l_extendedprice) "
         "from lineitem group by l_returnflag order by 1")
    cold = runner.execute(q).rows
    h0 = _counter("scan_cache_hit_total")
    warm = runner.execute(q).rows
    assert warm == cold
    assert _counter("scan_cache_hit_total") > h0
    # scan_cache=false escape hatch: same results, no cache traffic
    h1 = _counter("scan_cache_hit_total")
    m1 = _counter("scan_cache_miss_total")
    off = runner.execute(q, properties={"scan_cache": False}).rows
    assert off == cold
    assert _counter("scan_cache_hit_total") == h1
    assert _counter("scan_cache_miss_total") == m1


class _CountingConnector:
    """Delegate that counts page_source calls (decode work)."""

    def __init__(self, inner):
        self._inner = inner
        self.name = inner.name
        self.page_sources = 0

    @property
    def metadata(self):
        return self._inner.metadata

    @property
    def split_manager(self):
        return self._inner.split_manager

    def data_version(self, table):
        return self._inner.data_version(table)

    def page_source(self, split, columns, pushdown=None,
                    rows_per_batch=1 << 17):
        self.page_sources += 1
        return self._inner.page_source(split, columns, pushdown=pushdown,
                                       rows_per_batch=rows_per_batch)


def test_warm_run_skips_decode():
    counting = _CountingConnector(TpchConnector(sf=SF))
    catalogs = CatalogManager()
    catalogs.register("tpch", counting)
    r = LocalRunner(catalogs=catalogs)
    q = "select count(*), sum(o_totalprice) from orders"
    cold = r.execute(q).rows
    n_cold = counting.page_sources
    assert n_cold > 0
    warm = r.execute(q).rows
    assert warm == cold
    assert counting.page_sources == n_cold  # zero new decodes


def test_invalidation_on_insert(runner):
    runner.execute("drop table if exists memory.sc_inval")
    runner.execute("create table memory.sc_inval as "
                   "select n_nationkey, n_name from nation")
    q = "select count(*) from memory.sc_inval"
    assert runner.execute(q).rows == [(25,)]
    assert runner.execute(q).rows == [(25,)]          # warm hit
    runner.execute("insert into memory.sc_inval "
                   "select n_nationkey + 100, n_name from nation")
    # the write invalidated the cached split: new rows are visible
    assert runner.execute(q).rows == [(50,)]
    runner.execute("drop table memory.sc_inval")


def test_invalidation_on_sqlite_write(tmp_path):
    import sqlite3
    path = str(tmp_path / "sc.db")
    db = sqlite3.connect(path)
    db.execute("create table t (a INTEGER)")
    db.executemany("insert into t values (?)", [(i,) for i in range(10)])
    db.commit()
    from presto_tpu.connectors.sqlite import SqliteConnector
    conn = SqliteConnector(path)
    catalogs = CatalogManager()
    catalogs.register("db", conn)
    r = LocalRunner(catalogs=catalogs, catalog="db")
    q = "select count(*) from t"
    assert r.execute(q).rows[0][0] == 10
    assert r.execute(q).rows[0][0] == 10              # warm hit
    # a write THROUGH the connector invalidates (same path as its
    # TableStats cache)
    r.execute("insert into t select a + 10 from t")
    assert r.execute(q).rows[0][0] == 20


# -- eviction under a small limit --------------------------------------------

class _Obj:
    pass


def _mini_batch(n=64):
    return Batch.from_pydict({"x": (T.BIGINT, list(range(n)))})


def test_eviction_under_small_limit():
    b = _mini_batch()
    from presto_tpu.memory import batch_device_bytes
    nbytes = batch_device_bytes(b)
    cache = ScanCache(limit_bytes=int(nbytes * 2.5))  # fits two entries
    conn = _Obj()
    th = TableHandle("c", "s", "t")
    evicted0 = _counter("scan_cache_evicted_bytes_total")
    keys = [ScanCache.key(conn, "c", Split(th, (i,)), ("x",), None, 0)
            for i in range(3)]
    for k in keys:
        assert cache.put(k, conn, [b])
    # third insert evicted the LRU (first) entry
    assert len(cache) == 2
    assert cache.resident_bytes <= cache.pool.limit
    assert _counter("scan_cache_evicted_bytes_total") >= evicted0 + nbytes
    assert cache.get(keys[0], conn) is None           # evicted
    assert cache.get(keys[2], conn) is not None
    # an entry that can never fit is refused outright
    big = ScanCache(limit_bytes=nbytes // 2)
    assert not big.put(keys[0], conn, [b])
    assert len(big) == 0


def test_put_refused_after_version_bump():
    """A write landing while a scan decodes must not let the scan park
    a stale (unreachable) entry under the pre-write version."""
    b = _mini_batch()
    cache = ScanCache(limit_bytes=1 << 20)

    class _Versioned:
        v = 1

        def data_version(self, table):
            return self.v

    conn = _Versioned()
    th = TableHandle("c", "s", "t")
    key = ScanCache.key(conn, "c", Split(th, (0,)), ("x",), None,
                        conn.data_version("t"))
    conn.v = 2            # concurrent write bumped the version
    assert not cache.put(key, conn, [b])
    assert len(cache) == 0 and cache.resident_bytes == 0


def test_shrinking_limit_evicts():
    b = _mini_batch()
    from presto_tpu.memory import batch_device_bytes
    nbytes = batch_device_bytes(b)
    cache = ScanCache(limit_bytes=nbytes * 4)
    conn = _Obj()
    th = TableHandle("c", "s", "t")
    for i in range(3):
        cache.put(ScanCache.key(conn, "c", Split(th, (i,)), ("x",),
                                None, 0), conn, [b])
    assert len(cache) == 3
    cache.set_limit(nbytes)
    assert len(cache) == 1
    assert cache.resident_bytes <= nbytes


# -- prefetcher ---------------------------------------------------------------

def _scan_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("scan-prefetch")]


def _assert_no_scan_threads():
    deadline = time.time() + 5.0
    while _scan_threads() and time.time() < deadline:
        time.sleep(0.02)
    assert not _scan_threads()


def test_prefetcher_shutdown_clean(runner):
    # full drain
    runner.execute("select count(*) from lineitem")
    _assert_no_scan_threads()
    # early abandonment (LIMIT satisfied before the scan finishes)
    runner.execute("select l_orderkey from lineitem limit 3",
                   properties={"scan_threads": 2, "scan_cache": False})
    _assert_no_scan_threads()


class _SlowSource(PageSource):
    def __init__(self, batches, delay_s):
        self._batches = batches
        self._delay = delay_s

    def batches(self):
        for b in self._batches:
            time.sleep(self._delay)
            yield b


class _SlowMeta(ConnectorMetadata):
    def __init__(self, schema):
        self._schema = schema

    def list_tables(self, schema=None):
        return ["slow"]

    def table_schema(self, table):
        return self._schema


class _SlowSplits(ConnectorSplitManager):
    def __init__(self, n):
        self.n = n

    def splits(self, table, desired=1):
        return [Split(table, (i,)) for i in range(self.n)]


class _SlowConnector(Connector):
    """Fixed table, n splits, ``delay_s`` of fake decode per batch."""

    name = "slow"

    def __init__(self, n_splits=4, delay_s=0.05):
        self._batch = Batch.from_pydict(
            {"x": (T.BIGINT, list(range(128)))})
        self._meta = _SlowMeta(self._batch.schema)
        self._splits = _SlowSplits(n_splits)
        self.delay_s = delay_s

    @property
    def metadata(self):
        return self._meta

    @property
    def split_manager(self):
        return self._splits

    def data_version(self, table):
        return 0

    def page_source(self, split, columns, pushdown=None,
                    rows_per_batch=1 << 17):
        return _SlowSource([self._batch.select(list(columns))],
                           self.delay_s)


def test_warm_measurably_faster_than_cold():
    """The committed warm-vs-cold check: a decode-bound scan's re-run
    must not pay the decode again (device-resident replay)."""
    catalogs = CatalogManager()
    catalogs.register("slow", _SlowConnector(n_splits=4, delay_s=0.1))
    r = LocalRunner(catalogs=catalogs, catalog="slow")
    q = "select count(*) from slow"
    t0 = time.perf_counter()
    cold = r.execute(q).rows
    cold_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    warm = r.execute(q).rows
    warm_s = time.perf_counter() - t1
    assert warm == cold == [(512,)]
    assert cold_s >= 0.2          # 4 splits x 0.1s over 2 threads
    assert warm_s < cold_s * 0.5  # warm replay skips the decode wall


def test_prefetch_overlaps_decode():
    """With prefetch ON, 2 workers overlap split decodes; serially the
    same scan pays the full decode sum."""
    conn = _SlowConnector(n_splits=4, delay_s=0.1)
    th = TableHandle("slow", "default", "slow")
    splits = conn.split_manager.splits(th, 4)

    def drain(opts):
        t0 = time.perf_counter()
        n = sum(b.host_count()
                for b in scancache.scan_splits(
                    conn, "slow", ["x"], splits, lambda: None, 1 << 17,
                    opts))
        return n, time.perf_counter() - t0

    n1, serial_s = drain(ScanOptions(cache=False, prefetch=False))
    n2, overlap_s = drain(ScanOptions(cache=False, prefetch=True,
                                      threads=4, depth=2))
    assert n1 == n2 == 512
    assert serial_s >= 0.4
    assert overlap_s < serial_s * 0.75


# -- ragged-split padding -----------------------------------------------------

def test_ragged_final_chunk_padded():
    conn = TpchConnector(sf=SF)
    th = TableHandle("tpch", "default", "orders")
    splits = conn.split_manager.splits(th, 1)
    # rows_per_batch deliberately NOT a power of two: full chunks bucket
    # to 16384; the residual would bucket smaller without padding
    rpb = 10_000
    padded = list(scancache.scan_splits(
        conn, "tpch", ["o_orderkey"], splits, lambda: None, rpb,
        ScanOptions(cache=False, prefetch=False, pad=True)))
    assert len(padded) > 1
    assert len({b.capacity for b in padded}) == 1     # one bucket, one
    #                                                   executable
    raw = list(scancache.scan_splits(
        conn, "tpch", ["o_orderkey"], splits, lambda: None, rpb,
        ScanOptions(cache=False, prefetch=False, pad=False)))
    assert raw[-1].capacity < raw[0].capacity          # ragged without
    assert sum(b.host_count() for b in padded) == \
        sum(b.host_count() for b in raw)               # same live rows


# -- observability ------------------------------------------------------------

def test_metrics_surfaces(runner):
    runner.execute("select count(*) from region")
    runner.execute("select count(*) from region")
    rows = runner.execute(
        "select name, value from system.runtime.metrics "
        "where name like 'scan_cache%'").rows
    names = {r[0] for r in rows}
    assert {"scan_cache_hit_total", "scan_cache_miss_total",
            "scan_cache_evicted_bytes_total",
            "scan_cache_resident_bytes"} <= names
    by_name = {r[0]: r[1] for r in rows}
    assert by_name["scan_cache_resident_bytes"] > 0
    from presto_tpu.obs.exposition import render_exposition
    text = render_exposition(REGISTRY)
    assert "scan_cache_hit_total" in text
    assert "scan_prefetch_stall_seconds" in text


def test_explain_analyze_scan_cache_line(runner):
    runner.execute("select count(*) from supplier")
    out = runner.execute("explain analyze select count(*) from supplier")
    text = "\n".join(r[0] for r in out.rows)
    assert "Scan cache:" in text
    assert "hit" in text.split("Scan cache:")[1]

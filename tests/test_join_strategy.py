"""Stats-driven join strategy selection + the Pallas probe kernel.

The direct-address paths (single-key measured, multi-key planner-keyed,
and the Pallas probe kernel over either) must be RESULT-IDENTICAL to
the sorted-lookup path for every key shape the planner can route to
them — NULL keys, negative keys, keys sitting exactly on their stats
bounds, out-of-domain probe keys, composite key tuples, duplicate
(expansion) builds — because the dispatch is a pure performance
decision. Bounds that LIE (a live build key outside the planner's
promise) must fail the query with STATS_BOUND_VIOLATION, never drop
matches (the dense-grouping contract applied to joins)."""
import numpy as np
import pytest

import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, Schema
from presto_tpu.obs.metrics import REGISTRY
from presto_tpu.ops import join as J
from presto_tpu.ops import pallas_join as PJ


def _metric(name: str) -> float:
    for m in REGISTRY.snapshot():
        if m["name"] == name:
            return float(m.get("value", 0.0))
    return 0.0


def _rows(batch):
    def key(t):
        return tuple((v is None, str(type(v)), v) for v in t)
    return sorted([tuple(r) for r in batch.to_pylist()], key=key)


def _with_nulls(b: Batch, col: int, null_rows) -> Batch:
    cols = list(b.columns)
    mask = np.ones(b.capacity, dtype=bool)
    mask[list(null_rows)] = False
    c = cols[col]
    cols[col] = Column(c.type, c.data,
                       c.validity & jnp.asarray(mask), c.dictionary)
    return Batch(b.schema, cols, b.row_mask)


def _build(keys1, keys2, vals):
    return Batch.from_pydict({
        "k1": (T.BIGINT, keys1), "k2": (T.BIGINT, keys2),
        "v": (T.BIGINT, vals)})


# ---------------------------------------------------------------------------
# kernel parity: keyed direct vs sorted
# ---------------------------------------------------------------------------

def test_direct_keyed_vs_sorted_parity_random():
    rng = np.random.default_rng(7)
    n, m = 300, 500
    b1 = rng.integers(-20, 20, n).tolist()
    b2 = rng.integers(5, 12, n).tolist()
    build = _build(b1, b2, list(range(n)))
    build = _with_nulls(build, 0, [3, 50])
    probe = Batch.from_pydict({
        "p1": (T.BIGINT, rng.integers(-25, 25, m).tolist()),
        "p2": (T.BIGINT, rng.integers(3, 14, m).tolist()),
        "x": (T.BIGINT, list(range(m)))})
    probe = _with_nulls(probe, 1, [0, 7, 100])
    bounds = ((-20, 19), (5, 11))
    los, sizes, K = J.direct_keyed_plan(bounds)
    keyed = J.prepare_direct_keyed(build, [0, 1], los, sizes, K)
    sortp = J.prepare_build(build, [0, 1])
    # duplicates exist -> expansion join; parity across both tables
    for jt in ("inner", "left"):
        a = J.expand_join(probe, build, [0, 1], [0, 1], [2], ["v"], jt,
                          8, prepared=keyed)
        c = J.expand_join(probe, build, [0, 1], [0, 1], [2], ["v"], jt,
                          8, prepared=sortp)
        assert _rows(a) == _rows(c), jt
    assert int(J.max_multiplicity(keyed)) == int(J.max_multiplicity(sortp))
    for neg in (False, True):
        ma = J.semi_join_mask(probe, build, [0, 1], [0, 1], neg, False,
                              prepared=keyed)
        mc = J.semi_join_mask(probe, build, [0, 1], [0, 1], neg, False,
                              prepared=sortp)
        assert bool(jnp.all(ma == mc)), neg


def test_direct_keyed_bound_edges_and_out_of_domain():
    """Keys exactly on lo/hi match; probe keys outside the promised
    domain (which provably cannot match an in-bounds build) miss."""
    build = Batch.from_pydict({
        "k": (T.BIGINT, [-5, 0, 7]), "v": (T.BIGINT, [1, 2, 3])})
    los, sizes, K = J.direct_keyed_plan(((-5, 7),))
    keyed = J.prepare_direct_keyed(build, [0], los, sizes, K)
    probe = Batch.from_pydict({
        "p": (T.BIGINT, [-5, 7, -6, 8, 0, None])})
    out = J.lookup_join(probe, build, [0], [0], [1], ["v"], "inner",
                        prepared=keyed)
    assert _rows(out) == [(-5, 1), (0, 2), (7, 3)]
    left = J.lookup_join(probe, build, [0], [0], [1], ["v"], "left",
                         prepared=keyed)
    assert len(_rows(left)) == 6


def test_direct_keyed_plan_gates():
    assert J.direct_keyed_plan(()) is None
    assert J.direct_keyed_plan((None,)) is None
    assert J.direct_keyed_plan(((5, 4),)) is None          # empty span
    big = 1 << 20
    assert J.direct_keyed_plan(((0, big), (0, big))) is None  # product
    plan = J.direct_keyed_plan(((0, 9), (0, 9)))
    assert plan == ((0, 0), (10, 10), 100)


# ---------------------------------------------------------------------------
# Pallas probe kernel parity (interpret mode on the CPU mesh)
# ---------------------------------------------------------------------------

@pytest.fixture
def force_pallas(monkeypatch):
    monkeypatch.setattr(PJ, "FORCE_PALLAS_PROBE", True)
    monkeypatch.setitem(PJ._STATE, "broken", False)


def test_pallas_lookup_parity_dtypes(force_pallas):
    """Row-exact against the XLA path across the payload dtype zoo:
    64-bit ints, doubles (digit planes), 32-bit ints, bools, dictionary
    strings, decimal128 limb pairs."""
    import decimal
    n = 40
    rng = np.random.default_rng(3)
    build = Batch.from_pydict({
        "k": (T.BIGINT, list(range(1, n + 1))),
        "big": (T.BIGINT, rng.integers(-2**52, 2**52, n).tolist()),
        "dbl": (T.DOUBLE, (rng.standard_normal(n) * 1e9).tolist()),
        "i": (T.INTEGER, rng.integers(-100, 100, n).tolist()),
        "b": (T.BOOLEAN, (rng.random(n) < 0.5).tolist()),
        "s": (T.VARCHAR, [f"s{i % 7}" for i in range(n)]),
        "dec": (T.decimal(30, 2),
                [decimal.Decimal(int(v)) * 1000000 +
                 decimal.Decimal(int(w)) / 100
                 for v, w in zip(rng.integers(-2**52, 2**52, n),
                                 rng.integers(0, 10**4, n))]),
    })
    build = _with_nulls(build, 1, [2, 5])
    build = _with_nulls(build, 6, [4])
    probe = Batch.from_pydict({
        "p": (T.BIGINT, rng.integers(-3, n + 4, 64).tolist())})
    prep = J.prepare_direct(build, [0], 1, 64)
    payload = [1, 2, 3, 4, 5, 6]
    names = ["big", "dbl", "i", "b", "s", "dec"]
    for jt in ("inner", "left"):
        a = PJ.lookup_join_direct(probe, build, [0], [0], payload,
                                  names, jt, prep)
        c = J.lookup_join(probe, build, [0], [0], payload, names, jt,
                          prepared=prep)
        assert _rows(a) == _rows(c), jt


def test_pallas_supports_join_gate():
    build = Batch.from_pydict({
        "k": (T.BIGINT, list(range(1, 200))),
        "v": (T.BIGINT, list(range(199)))})
    sortp = J.prepare_build(build, [0])
    assert not PJ.supports_join(sortp, build, [1])   # not direct
    prep = J.prepare_direct(build, [0], 1, 256)
    PJ._STATE["broken"] = True
    try:
        assert not PJ.kernel_enabled()
    finally:
        PJ._STATE["broken"] = False


def test_pallas_engine_parity_and_breaker(force_pallas, monkeypatch):
    """The 3-way tpch star chain runs the fused pipeline through the
    kernel; flipping the session property (and tripping the breaker)
    both land on the identical rows."""
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec.runner import LocalRunner
    catalogs = CatalogManager()
    catalogs.register("tpch", TpchConnector(sf=0.01))
    r = LocalRunner(catalogs=catalogs, catalog="tpch",
                    rows_per_batch=1 << 14)
    q = ("select n_name, count(*) c from orders "
         "join customer on o_custkey = c_custkey "
         "join nation on c_nationkey = n_nationkey "
         "group by n_name order by n_name")
    before = _metric("join_strategy_selected_total.direct.replicated")
    on = r.execute(q).rows
    after = _metric("join_strategy_selected_total.direct.replicated")
    assert after > before
    off = r.execute(q, properties={"join_pallas_probe": False}).rows
    assert on == off
    assert _metric("join_pallas_fallback_total") == 0.0


def test_pallas_breaker_falls_back(monkeypatch):
    """A kernel that fails to lower costs one fallback count, never a
    query: dispatch transparently re-runs on XLA and the breaker stays
    tripped for later dispatches."""
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec.runner import LocalRunner
    monkeypatch.setattr(PJ, "FORCE_PALLAS_PROBE", False)
    monkeypatch.setitem(PJ._STATE, "broken", False)
    # backend reports capable, kernel explodes at dispatch
    monkeypatch.setattr(PJ, "kernel_enabled", lambda: True)

    def boom(*a, **k):
        raise RuntimeError("mosaic lowering failed")
    monkeypatch.setattr(
        "presto_tpu.ops.jitcache.lookup_join_pallas_jit", boom)
    monkeypatch.setattr(
        "presto_tpu.exec.local.lookup_join_pallas_jit", boom)
    catalogs = CatalogManager()
    catalogs.register("tpch", TpchConnector(sf=0.002))
    r = LocalRunner(catalogs=catalogs, catalog="tpch",
                    rows_per_batch=1 << 13)
    before = _metric("join_pallas_fallback_total")
    rows = r.execute(
        "select count(*) from orders join customer "
        "on o_custkey = c_custkey where c_nationkey = 3",
        properties={"fused_pipeline": False}).rows
    assert rows[0][0] > 0
    assert _metric("join_pallas_fallback_total") >= before + 1
    assert PJ._STATE["broken"]
    PJ._STATE["broken"] = False


# ---------------------------------------------------------------------------
# planner: strategy attaches from stats, flips when stats change
# ---------------------------------------------------------------------------

def _find(node, cls):
    from presto_tpu.planner.plan import PlanNode
    out = []

    def walk(n):
        if isinstance(n, cls):
            out.append(n)
        for c in n.children:
            walk(c)
    walk(node)
    return out


@pytest.fixture(scope="module")
def tpch_runner():
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec.runner import LocalRunner
    catalogs = CatalogManager()
    catalogs.register("tpch", TpchConnector(sf=0.01))
    return LocalRunner(catalogs=catalogs, catalog="tpch",
                       rows_per_batch=1 << 14)


def test_planner_attaches_join_bounds(tpch_runner):
    from presto_tpu.planner.plan import JoinNode
    plan = tpch_runner.plan(
        "select c_name, n_name from customer "
        "join nation on c_nationkey = n_nationkey")
    joins = _find(plan.root, JoinNode)
    assert joins and joins[0].key_bounds == ((0, 24),)
    ex = tpch_runner.execute(
        "explain select c_name, n_name from customer "
        "join nation on c_nationkey = n_nationkey").rows
    text = "\n".join(r[0] for r in ex)
    assert "direct bounds=[0..24]" in text


def test_planner_bounds_flip_with_stats(tpch_runner, monkeypatch):
    """Same SQL, stats withdrawn -> the strategy flips to sorted (no
    key_bounds); join_dense_path=false pins the old behavior too."""
    from presto_tpu.connectors.spi import TableStats
    from presto_tpu.planner.plan import JoinNode
    sql = ("select c_name, n_name from customer "
           "join nation on c_nationkey = n_nationkey")
    conn = tpch_runner.session.catalogs.get("tpch")
    meta = conn.metadata
    real = meta.table_stats

    def no_bounds(table):
        st = real(table)
        if table.table == "nation":
            return TableStats(row_count=st.row_count, columns={},
                              primary_key=st.primary_key)
        return st
    monkeypatch.setattr(type(meta), "table_stats",
                        lambda self, t: no_bounds(t))
    try:
        plan = tpch_runner.plan(sql)
    finally:
        monkeypatch.undo()
    joins = _find(plan.root, JoinNode)
    assert joins and joins[0].key_bounds == ()
    # session escape hatch
    old = dict(tpch_runner.session.properties)
    tpch_runner.session.properties["join_dense_path"] = False
    try:
        plan2 = tpch_runner.plan(sql)
    finally:
        tpch_runner.session.properties.clear()
        tpch_runner.session.properties.update(old)
    assert _find(plan2.root, JoinNode)[0].key_bounds == ()


def test_semi_distribution_from_stats(tpch_runner):
    """Semi joins stop broadcasting membership everywhere: a filtering
    set estimated above broadcast_join_row_limit partitions; NULL-aware
    anti joins always replicate (global NULL semantics)."""
    from presto_tpu.planner.plan import SemiJoinNode
    sql = ("select count(*) from orders where o_custkey in "
           "(select c_custkey from customer)")
    plan = tpch_runner.plan(sql)
    semis = _find(plan.root, SemiJoinNode)
    assert semis and semis[0].distribution == "replicated"
    old = dict(tpch_runner.session.properties)
    tpch_runner.session.properties["broadcast_join_row_limit"] = 100
    try:
        plan2 = tpch_runner.plan(sql)
        semis2 = _find(plan2.root, SemiJoinNode)
        assert semis2 and semis2[0].distribution == "partitioned"
        anti = tpch_runner.plan(
            "select count(*) from orders where o_custkey not in "
            "(select c_custkey from customer)")
        asemis = _find(anti.root, SemiJoinNode)
        assert asemis and asemis[0].negated
        assert asemis[0].distribution == "replicated"
    finally:
        tpch_runner.session.properties.clear()
        tpch_runner.session.properties.update(old)


def test_semi_partitioned_row_parity(tpch_runner):
    """Forcing the partitioned semi distribution returns the identical
    rows (the fragmenter/mesh path composes per-partition verdicts)."""
    sql = ("select count(*) from orders where o_custkey in "
           "(select c_custkey from customer where c_nationkey < 5)")
    a = tpch_runner.execute(sql).rows
    b = tpch_runner.execute(
        sql, properties={"broadcast_join_row_limit": 10}).rows
    assert a == b


# ---------------------------------------------------------------------------
# bounds that lie -> STATS_BOUND_VIOLATION through the error channel
# ---------------------------------------------------------------------------

def test_join_bound_violation_fails_query():
    from presto_tpu.connectors.spi import (CatalogManager, ColumnStats,
                                           TableStats)
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.errors import QueryError
    from presto_tpu.exec.runner import LocalRunner
    conn = MemoryConnector()
    catalogs = CatalogManager()
    catalogs.register("memory", conn)
    r = LocalRunner(catalogs=catalogs, catalog="memory")
    r.execute("create table memory.default.dim as select * from "
              "(values (1, 'a'), (2, 'b'), (99, 'z')) t(k, name)")
    r.execute("create table memory.default.fact as select * from "
              "(values (1, 10), (2, 20), (99, 30)) t(fk, v)")

    lying = {
        "dim": TableStats(
            row_count=3.0,
            columns={"k": ColumnStats(3, 0.0, 1, 5)},  # 99 violates
            primary_key=("k",)),
        "fact": TableStats(row_count=3.0, columns={}),
    }
    meta = conn.metadata
    monkeypatch_stats = lambda self, t: lying.get(
        t.table, TableStats(row_count=3.0))
    orig = type(meta).table_stats
    type(meta).table_stats = monkeypatch_stats
    try:
        plan = r.plan("select v, name from memory.default.fact "
                      "join memory.default.dim on fk = k")
        from presto_tpu.planner.plan import JoinNode
        joins = _find(plan.root, JoinNode)
        assert joins and joins[0].key_bounds == ((1, 5),)
        with pytest.raises(QueryError) as ei:
            r.execute("select v, name from memory.default.fact "
                      "join memory.default.dim on fk = k")
        assert ei.value.name == "STATS_BOUND_VIOLATION"
        # honest bounds: same query with the real (empty) stats runs.
        # plan_cache=false: the cached plan still carries the lying
        # bounds (stats changes don't bump connector data versions)
        type(meta).table_stats = orig
        rows = r.execute("select v, name from memory.default.fact "
                         "join memory.default.dim on fk = k",
                         properties={"plan_cache": False}).rows
        assert sorted(rows) == [(10, 'a'), (20, 'b'), (30, 'z')]
    finally:
        type(meta).table_stats = orig


# ---------------------------------------------------------------------------
# observability: EXPLAIN ANALYZE strategy rows
# ---------------------------------------------------------------------------

def test_explain_analyze_shows_strategy(tpch_runner):
    ex = tpch_runner.execute(
        "explain analyze select c_name, n_name from customer "
        "join nation on c_nationkey = n_nationkey").rows
    text = "\n".join(r[0] for r in ex)
    assert "[strategy direct/replicated]" in text

import numpy as np
import pytest

from presto_tpu.connectors.spi import TableHandle
from presto_tpu.connectors.tpch import (
    TpchConnector, _lines_per_order, tpch_schema, TABLES,
)


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=0.001)  # tiny: 1500 orders, ~6000 lineitems


def _scan(conn, table, columns, desired_splits=1, rows_per_batch=1 << 17):
    th = TableHandle("tpch", "tiny", table)
    out = []
    for split in conn.split_manager.splits(th, desired_splits):
        src = conn.page_source(split, columns, rows_per_batch=rows_per_batch)
        out.extend(b.to_pylist() for b in src.batches())
    return [r for rows in out for r in rows]


def test_all_tables_scan(conn):
    for t in TABLES:
        cols = tpch_schema(t).names[:3]
        rows = _scan(conn, t, cols)
        assert len(rows) > 0, t


def test_row_counts(conn):
    assert len(_scan(conn, "orders", ["o_orderkey"])) == 1500
    assert len(_scan(conn, "customer", ["c_custkey"])) == 150
    assert len(_scan(conn, "nation", ["n_nationkey"])) == 25
    assert len(_scan(conn, "region", ["r_regionkey"])) == 5
    n_li = len(_scan(conn, "lineitem", ["l_orderkey"]))
    assert 4000 < n_li < 8000  # ~4 lines/order


def test_determinism_across_splits(conn):
    one = _scan(conn, "orders", ["o_orderkey", "o_custkey", "o_orderdate"], 1)
    four = _scan(conn, "orders", ["o_orderkey", "o_custkey", "o_orderdate"], 4)
    assert sorted(one) == sorted(four)


def test_lineitem_split_determinism(conn):
    cols = ["l_orderkey", "l_linenumber", "l_extendedprice", "l_shipdate"]
    one = _scan(conn, "lineitem", cols, 1)
    three = _scan(conn, "lineitem", cols, 3, rows_per_batch=512)
    assert sorted(one) == sorted(three)


def test_referential_integrity(conn):
    custkeys = {r[0] for r in _scan(conn, "customer", ["c_custkey"])}
    orders = _scan(conn, "orders", ["o_custkey"])
    assert all(r[0] in custkeys for r in orders)

    partkeys = {r[0] for r in _scan(conn, "part", ["p_partkey"])}
    suppkeys = {r[0] for r in _scan(conn, "supplier", ["s_suppkey"])}
    li = _scan(conn, "lineitem", ["l_partkey", "l_suppkey"])
    assert all(r[0] in partkeys for r in li)
    assert all(r[1] in suppkeys for r in li)

    ps = _scan(conn, "partsupp", ["ps_partkey", "ps_suppkey"])
    assert all(r[0] in partkeys and r[1] in suppkeys for r in ps)


def test_extendedprice_consistency(conn):
    # l_extendedprice == l_quantity * p_retailprice(l_partkey)
    prices = dict(
        (r[0], r[1]) for r in _scan(conn, "part", ["p_partkey", "p_retailprice"]))
    li = _scan(conn, "lineitem", ["l_partkey", "l_quantity", "l_extendedprice"])
    for pk, qty, ext in li[:500]:
        assert abs(ext - qty * prices[pk]) < 1e-6


def test_date_ranges_and_enums(conn):
    import datetime

    rows = _scan(conn, "lineitem", ["l_shipdate", "l_returnflag", "l_linestatus",
                                    "l_shipmode", "l_discount"])
    for d, rf, ls, mode, disc in rows[:1000]:
        assert datetime.date(1992, 1, 2) <= d <= datetime.date(1999, 1, 1)
        assert rf in ("A", "N", "R")
        assert ls in ("O", "F")
        assert 0.0 <= disc <= 0.10
    # Q6-ish selectivity sanity: discount in [0.05,0.07] ~ 3/11 of rows
    frac = sum(1 for r in rows if 0.05 <= r[4] <= 0.07) / len(rows)
    assert 0.15 < frac < 0.40


def test_stable_dictionaries_across_batches(conn):
    th = TableHandle("tpch", "tiny", "lineitem")
    split = conn.split_manager.splits(th, 1)[0]
    src = conn.page_source(split, ["l_returnflag", "l_shipmode"],
                           rows_per_batch=512)
    dicts = set()
    for b in src.batches():
        dicts.add((b.column("l_returnflag").dictionary,
                   b.column("l_shipmode").dictionary))
    assert len(dicts) == 1  # stable vocab -> one compiled kernel


def test_stats(conn):
    th = TableHandle("tpch", "tiny", "orders")
    st = conn.metadata.table_stats(th)
    assert st.row_count == 1500
    assert st.columns["o_orderkey"].max_value == 1500

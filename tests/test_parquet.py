"""Parquet reader: thrift-compact footer, device hybrid decode, pruning.

Oracle: pyarrow writes the fixture files (the industry-standard writer),
our reader (reference presto-parquet role) decodes them; our own writer
round-trips as a second fixture source.
"""
import datetime
import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from presto_tpu import types as T
from presto_tpu.batch import Schema
from presto_tpu.formats.parquet import ParquetReader, write_parquet


@pytest.fixture(scope="module")
def fixture_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("parquet")
    table = pa.table({
        "a": pa.array([1, 2, None, 4], type=pa.int64()),
        "b": pa.array(["x", "y", "x", None]),
        "c": pa.array([1.5, None, 2.5, 3.5], type=pa.float64()),
        "d": pa.array([datetime.date(2020, 1, 1), None,
                       datetime.date(2021, 2, 3),
                       datetime.date(2022, 3, 4)]),
        "t": pa.array([datetime.datetime(2020, 1, 1, 12, 30), None,
                       datetime.datetime(2021, 1, 1),
                       datetime.datetime(2022, 5, 6, 7, 8, 9)],
                      type=pa.timestamp("us")),
    })
    pq.write_table(table, str(d / "small.parquet"), compression="NONE",
                   version="1.0")
    pq.write_table(table, str(d / "gz.parquet"), compression="GZIP",
                   use_dictionary=False, version="1.0")
    rng = np.random.RandomState(7)
    n = 50_000
    big = pa.table({
        "k": pa.array(rng.randint(0, 100, n), type=pa.int64()),
        "v": pa.array(rng.rand(n)),
        "s": pa.array([f"tag{int(i)}" for i in rng.randint(0, 50, n)]),
    })
    pq.write_table(big, str(d / "big.parquet"), compression="NONE",
                   row_group_size=16_384, version="1.0")
    return d


def rows_of(path, cols):
    out = []
    for b in ParquetReader(str(path)).batches(cols):
        out.extend(b.to_pylist())
    return out


def test_schema_mapping(fixture_dir):
    r = ParquetReader(str(fixture_dir / "small.parquet"))
    got = {f.name: f.type.display() for f in r.schema.fields}
    assert got == {"a": "bigint", "b": "varchar", "c": "double",
                   "d": "date", "t": "timestamp"}


def test_pyarrow_dictionary_pages(fixture_dir):
    rows = rows_of(fixture_dir / "small.parquet",
                   ["a", "b", "c", "d", "t"])
    assert rows[0] == (1, "x", 1.5, datetime.date(2020, 1, 1),
                       datetime.datetime(2020, 1, 1, 12, 30))
    assert rows[1][1] == "y" and rows[1][2] is None and rows[1][3] is None
    assert rows[3][1] is None


def test_gzip_plain_pages(fixture_dir):
    rows = rows_of(fixture_dir / "gz.parquet", ["a", "b", "c"])
    assert [r[0] for r in rows] == [1, 2, None, 4]
    assert [r[1] for r in rows] == ["x", "y", "x", None]


def test_big_file_matches_pyarrow(fixture_dir):
    path = fixture_dir / "big.parquet"
    want = pq.read_table(str(path)).to_pydict()
    rows = rows_of(path, ["k", "v", "s"])
    assert len(rows) == len(want["k"])
    got_k = [r[0] for r in rows]
    got_s = [r[2] for r in rows]
    assert got_k == want["k"]
    assert got_s == want["s"]
    np.testing.assert_allclose([r[1] for r in rows], want["v"])


def test_row_group_pruning(fixture_dir):
    r = ParquetReader(str(fixture_dir / "big.parquet"))
    assert len(r.row_groups) > 1
    # impossible bound prunes every group
    batches = list(r.batches(["k"], pushdown=[("k", 1000, None)]))
    assert batches == []
    total = sum(b.host_count()
                for b in r.batches(["k"], pushdown=[("k", 0, 99)]))
    assert total == r.num_rows


def test_multipage_dictionary_with_nulls(tmp_path):
    # pages where n_present < page size: per-page index arrays must not
    # carry bucket padding into the dense value stream
    n = 20_000
    vals = [f"tag{i % 37}" if i % 7 else None for i in range(n)]
    t = pa.table({"s": pa.array(vals)})
    p = str(tmp_path / "mp.parquet")
    pq.write_table(t, p, compression="NONE", version="1.0",
                   data_page_size=1024)
    rows = rows_of(p, ["s"])
    assert [r[0] for r in rows] == vals


def test_multipage_plain_strings(tmp_path):
    # PLAIN (no dictionary) strings spanning pages share one chunk vocab
    n = 5_000
    vals = [f"val{i}" for i in range(n)]
    t = pa.table({"s": pa.array(vals)})
    p = str(tmp_path / "plain.parquet")
    pq.write_table(t, p, compression="NONE", version="1.0",
                   use_dictionary=False, data_page_size=1024)
    rows = rows_of(p, ["s"])
    assert [r[0] for r in rows] == vals


def test_nanosecond_timestamps_logical_only(tmp_path):
    # version 2.6 writes logicalType (field 10) with no converted_type
    ts = [datetime.datetime(2020, 1, 1, 12, 0, 0, 123456),
          datetime.datetime(2021, 6, 5, 4, 3, 2, 999000)]
    t = pa.table({"t": pa.array(ts, type=pa.timestamp("ns"))})
    p = str(tmp_path / "ns.parquet")
    pq.write_table(t, p, compression="NONE", version="2.6")
    r = ParquetReader(p)
    assert r.schema.fields[0].type.display() == "timestamp"
    rows = rows_of(p, ["t"])
    assert [r[0] for r in rows] == ts


def test_all_null_column(tmp_path):
    p = str(tmp_path / "nulls.parquet")
    pq.write_table(pa.table({"a": pa.array([None] * 3, type=pa.int64()),
                             "b": pa.array([1, 2, 3])}),
                   p, compression="NONE", version="1.0")
    rows = rows_of(p, ["a", "b"])
    assert rows == [(None, 1), (None, 2), (None, 3)]


def test_empty_table_dir_is_unknown_table(tmp_path):
    import os

    from presto_tpu.connectors.parquet import ParquetConnector
    os.mkdir(tmp_path / "emptytab")
    conn = ParquetConnector(str(tmp_path))
    from presto_tpu.connectors.spi import TableHandle
    with pytest.raises(KeyError, match="emptytab"):
        conn.metadata.table_schema(TableHandle("pq", "d", "emptytab"))
    assert conn.metadata.list_tables() == []


def test_own_writer_roundtrip(tmp_path):
    p = str(tmp_path / "own.parquet")
    schema = Schema([("a", T.BIGINT), ("b", T.VARCHAR), ("e", T.BOOLEAN)])
    write_parquet(p, schema, [
        [10, None, 30], ["aa", "bb", "aa"], [True, False, None]])
    rows = rows_of(p, ["a", "b", "e"])
    assert rows == [(10, "aa", True), (None, "bb", False),
                    (30, "aa", None)]


def test_own_writer_readable_by_pyarrow(tmp_path):
    p = str(tmp_path / "own2.parquet")
    schema = Schema([("a", T.BIGINT), ("b", T.VARCHAR)])
    write_parquet(p, schema, [[1, 2, None], ["x", None, "z"]])
    t = pq.read_table(p)
    assert t.to_pydict() == {"a": [1, 2, None], "b": ["x", None, "z"]}


def test_sql_over_parquet(fixture_dir):
    from presto_tpu.connectors.parquet import ParquetConnector
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.exec.runner import LocalRunner
    catalogs = CatalogManager()
    catalogs.register("pq", ParquetConnector(str(fixture_dir)))
    r = LocalRunner(catalogs=catalogs, catalog="pq")
    assert r.execute("show tables").rows == [("big",), ("gz",), ("small",)]
    rows = r.execute(
        "select k, count(*), sum(v) from big group by 1 "
        "order by 2 desc, 1 limit 3").rows
    want = pq.read_table(str(fixture_dir / "big.parquet")).to_pydict()
    import collections
    cnt = collections.Counter(want["k"])
    sums = collections.defaultdict(float)
    for k, v in zip(want["k"], want["v"]):
        sums[k] += v
    expect = sorted(cnt.items(), key=lambda kv: (-kv[1], kv[0]))[:3]
    for (gk, gc, gs), (wk, wc) in zip(rows, expect):
        assert (gk, gc) == (wk, wc)
        assert abs(gs - sums[wk]) < 1e-6
    # predicate pushdown prunes row groups at the scan
    n = r.execute("select count(*) from big where k > 1000").rows
    assert n == [(0,)]

"""Config-file system + discovery/announce membership.

Reference: airlift bootstrap @Config binding over etc/config.properties,
StaticCatalogStore over etc/catalog/*.properties (PrestoServer.java:86),
and DiscoveryNodeManager.java:68 (workers join by announcing; vanished
workers age out)."""
import time

import pytest


def _write_etc(tmp_path, catalog_props):
    etc = tmp_path / "etc"
    (etc / "catalog").mkdir(parents=True)
    (etc / "config.properties").write_text(
        "node.id = test-node\n"
        "coordinator=true\n"
        "# a comment\n"
        "session.catalog = tiny\n"
        "session.schema = t\n"
        "session.scan_threads = 3\n")
    for name, props in catalog_props.items():
        (etc / "catalog" / f"{name}.properties").write_text(props)
    return str(etc)


def test_load_catalogs_and_config(tmp_path):
    from presto_tpu.config import load_catalogs, load_node_config
    etc = _write_etc(tmp_path, {
        "tiny": "connector.name=tpch\ntpch.scale-factor=0.01\n",
        "mem": "connector.name=memory\n",
    })
    cfg = load_node_config(etc)
    assert cfg.node_id == "test-node" and cfg.coordinator
    assert cfg.catalog == "tiny"
    assert cfg.session_defaults["scan_threads"] == "3"
    catalogs = load_catalogs(etc)
    assert set(catalogs.names()) >= {"tiny", "mem", "system"}
    assert abs(catalogs.get("tiny").sf - 0.01) < 1e-12


def test_catalog_file_errors(tmp_path):
    from presto_tpu.config import load_catalogs
    etc = _write_etc(tmp_path, {"bad": "no_connector_name=1\n"})
    with pytest.raises(ValueError):
        load_catalogs(etc)


def test_orc_catalog_from_properties(tmp_path):
    from presto_tpu.config import load_catalogs
    (tmp_path / "wh").mkdir()
    etc = _write_etc(tmp_path, {
        "warehouse": f"connector.name=orc\norc.root={tmp_path}/wh\n"})
    catalogs = load_catalogs(etc)
    assert catalogs.get("warehouse").root == f"{tmp_path}/wh"


def test_query_via_config_loaded_runner(tmp_path):
    from presto_tpu.config import load_catalogs, load_node_config
    from presto_tpu.exec.runner import LocalRunner
    etc = _write_etc(tmp_path, {
        "tiny": "connector.name=tpch\ntpch.scale-factor=0.01\n"})
    cfg = load_node_config(etc)
    r = LocalRunner(catalogs=load_catalogs(etc), catalog=cfg.catalog,
                    schema=cfg.schema)
    r.session.properties.update(cfg.session_defaults)
    assert r.execute("select count(*) from nation").rows == [(25,)]


def test_announce_and_ttl():
    from presto_tpu.exec.discovery import DiscoveryNodeManager
    d = DiscoveryNodeManager(ttl_s=0.2)
    d.announce("w1", "http://h1:1")
    d.announce("w2", "http://h2:2")
    assert d.active_urls() == ["http://h1:1", "http://h2:2"]
    time.sleep(0.3)
    d.announce("w2", "http://h2:2")
    assert d.active_urls() == ["http://h2:2"]
    infos = {n["nodeId"]: n for n in d.nodes()}
    assert infos["w1"]["active"] is False
    assert infos["w2"]["active"] is True


def test_worker_announces_to_statement_server():
    """End-to-end: a worker joins a coordinator by announcement and a
    discovery-fed ClusterRunner schedules on it."""
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.server.protocol import PrestoTpuServer
    from presto_tpu.server.worker import WorkerServer

    srv = PrestoTpuServer()
    srv.start() if hasattr(srv, "start") else srv._thread.start()
    worker = WorkerServer(tpch_sf=0.01)
    worker.start()
    try:
        worker.start_announcing(f"http://127.0.0.1:{srv.port}",
                                interval_s=0.5)
        deadline = time.time() + 10
        while not srv.discovery.active_urls() and time.time() < deadline:
            time.sleep(0.05)
        urls = srv.discovery.active_urls()
        assert urls == [f"http://127.0.0.1:{worker.port}"]
        runner = ClusterRunner(discovery=srv.discovery, tpch_sf=0.01,
                               heartbeat=False)
        assert runner.execute(
            "select count(*) from nation").rows == [(25,)]
    finally:
        worker.stop()
        srv.httpd.shutdown()


def test_server_from_etc(tmp_path):
    """Full coordinator bootstrap from a config directory: catalogs,
    session defaults, resource groups (PrestoServer.run analogue)."""
    import json
    import urllib.request

    etc = _write_etc(tmp_path, {
        "tiny": "connector.name=tpch\ntpch.scale-factor=0.01\n"})
    (tmp_path / "etc" / "resource-groups.json").write_text(json.dumps({
        "rootGroups": [{"name": "global", "hardConcurrencyLimit": 4,
                        "maxQueued": 10}],
        "selectors": [{"group": "global"}],
    }))
    from presto_tpu.config import server_from_etc
    srv, cfg = server_from_etc(str(etc))
    srv.start()
    try:
        body = "select count(*) from nation".encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/statement", data=body,
            method="POST", headers={"X-Presto-User": "t"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            doc = json.loads(resp.read())
        while doc.get("nextUri"):
            with urllib.request.urlopen(doc["nextUri"],
                                        timeout=30) as resp:
                nxt = json.loads(resp.read())
            doc = {**nxt, "data": doc.get("data") or nxt.get("data")}
        assert doc.get("data") == [[25]]
    finally:
        srv.httpd.shutdown()

"""ISSUE 19: coordinator-fleet cache coherence.

Two statement servers ("coordinators") in one process, each over its
OWN LocalRunner and OWN CatalogManager, sharing one writable sqlite
catalog file — the in-process stand-in for a multi-process fleet (the
subprocess version runs in bench.py's fleet mode and the chaos drill).
Connector identity keeps the stand-in honest: each coordinator's
caches stamp deps against its own connector OBJECT, so a write through
A can only reach B's template/result entries via the fleet bump
broadcast -> ``fold_bump`` -> ``spi.notify_data_change`` path, exactly
like separate processes.

Covers the three coherence contracts:

- a write through coordinator A invalidates B's template + result
  entries BEFORE B's next hit (eager remote invalidation, observed via
  the invalidation counters and row-exact reads);
- with broadcasts dropped (the ``fleet.broadcast`` failpoint), B still
  serves row-exact results — the hit-time ``data_version``
  revalidation backstop (sqlite's PRAGMA data_version sees foreign
  commits);
- the remote-bump-vs-local-insert race, interleaving-explored: a bump
  folding between B's epoch capture and its cache insert must veto the
  insert (the epoch-before-deps contract holds across the wire).
"""
import os
import sqlite3
import tempfile

import pytest

from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.spi import CatalogManager
from presto_tpu.connectors.sqlite import SqliteConnector
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.obs.metrics import REGISTRY
from presto_tpu.serving.fleet import FleetMember

CACHE_PROPS = {"plan_template_cache": True, "result_cache": True}


def _metric(name: str) -> float:
    for m in REGISTRY.snapshot():
        if m["name"] == name:
            return m["value"]
    return 0.0


def _make_runner(db_path: str) -> LocalRunner:
    cats = CatalogManager()
    cats.register("memory", MemoryConnector())
    cats.register("fleetdb", SqliteConnector(db_path))
    r = LocalRunner(catalogs=cats, catalog="fleetdb")
    r.session.properties.update(CACHE_PROPS)
    return r


@pytest.fixture()
def fleet_pair():
    """Two HTTP coordinators, fleet-enabled, over one sqlite file."""
    from presto_tpu.server.protocol import PrestoTpuServer
    db = os.path.join(tempfile.mkdtemp(prefix="fleet_test_"),
                      "shared.db")
    servers = []
    for i in range(2):
        srv = PrestoTpuServer(_make_runner(db))
        srv.start()
        servers.append(srv)
    urls = [f"http://127.0.0.1:{s.port}" for s in servers]
    for i, srv in enumerate(servers):
        srv.enable_fleet(f"coord-{i}",
                         peers=[u for j, u in enumerate(urls) if j != i],
                         heartbeat_s=5.0)
    try:
        yield servers, urls, db
    finally:
        for srv in servers:
            try:
                srv.kill()
            except Exception:
                pass


def _client(url):
    from presto_tpu.client import StatementClient
    return StatementClient(url, user="fleet-test")


def test_remote_write_invalidates_before_next_hit(fleet_pair):
    """Write through A -> B's template + result entries drop eagerly
    (the broadcast fold), before B's next lookup — and B's re-read is
    row-exact against an uncached run."""
    servers, urls, _db = fleet_pair
    a, b = _client(urls[0]), _client(urls[1])
    a.execute("create table fleetdb.default.t1 as select 1 as x")
    sql = ("select count(*) as c, sum(x) as s "
           "from fleetdb.default.t1 where x < 100")
    h0 = _metric("result_cache_hit_total")
    r1 = b.execute(sql).rows
    r2 = b.execute(sql).rows
    assert r1 == r2 == [[1, 1]]
    assert _metric("result_cache_hit_total") == h0 + 1
    # a second binding of the same template (different literal) is a
    # template hit — B now holds template AND result entries
    th0 = _metric("plan_template_cache_hit_total")
    b.execute("select count(*) as c, sum(x) as s "
              "from fleetdb.default.t1 where x < 200")
    assert _metric("plan_template_cache_hit_total") > th0

    ri0 = _metric("result_cache_invalidated_total")
    ti0 = _metric("plan_template_cache_invalidated_total")
    f0 = _metric("fleet_bump_fold_total")
    a.execute("insert into fleetdb.default.t1 select 2 as x")
    # the bump POST rides A's write synchronously; B folded it through
    # spi.notify_data_change before A's statement even finished
    assert _metric("fleet_bump_fold_total") > f0
    assert _metric("result_cache_invalidated_total") > ri0
    assert _metric("plan_template_cache_invalidated_total") > ti0
    # B serves the post-write truth — and it is a rebuild, not a hit
    h1 = _metric("result_cache_hit_total")
    assert b.execute(sql).rows == [[2, 3]]
    assert _metric("result_cache_hit_total") == h1


def test_dropped_broadcast_still_serves_correct_rows(fleet_pair):
    """The fail-safe backstop: with every broadcast dropped at the
    ``fleet.broadcast`` failpoint, B never hears about A's write — but
    its hit-time data_version revalidation (sqlite PRAGMA data_version
    moves on foreign commits) refuses the stale entry and recomputes
    row-exact results."""
    from presto_tpu.exec.failpoints import FAILPOINTS
    servers, urls, _db = fleet_pair
    a, b = _client(urls[0]), _client(urls[1])
    a.execute("create table fleetdb.default.t2 as select 10 as x")
    sql = "select count(*) as c, sum(x) as s from fleetdb.default.t2"
    assert b.execute(sql).rows == [[1, 10]]
    h0 = _metric("result_cache_hit_total")
    assert b.execute(sql).rows == [[1, 10]]
    assert _metric("result_cache_hit_total") == h0 + 1

    FAILPOINTS.configure("fleet.broadcast", action="error",
                         message="chaos: broadcast dropped")
    try:
        d0 = _metric("fleet_bump_dropped_total")
        f0 = _metric("fleet_bump_fold_total")
        a.execute("insert into fleetdb.default.t2 select 20 as x")
        assert _metric("fleet_bump_dropped_total") > d0
        assert _metric("fleet_bump_fold_total") == f0   # B never told
        # B's cached entry survived (no eager invalidation) — the
        # lookup itself must notice the drifted data_version
        assert b.execute(sql).rows == [[2, 30]]
    finally:
        FAILPOINTS.clear("fleet.broadcast")
    # and once broadcasts flow again, coherence is eager once more
    f1 = _metric("fleet_bump_fold_total")
    a.execute("insert into fleetdb.default.t2 select 30 as x")
    assert _metric("fleet_bump_fold_total") > f1
    assert b.execute(sql).rows == [[3, 60]]


def test_fold_is_deduped_and_catalog_checked():
    """fold_bump unit seams: per-origin monotonic dedupe, unknown
    catalogs counted and ignored, own-origin bumps refused."""
    db = os.path.join(tempfile.mkdtemp(prefix="fleet_fold_"), "f.db")
    cats = CatalogManager()
    cats.register("fleetdb", SqliteConnector(db))
    m = FleetMember("coord-b", "http://127.0.0.1:0", catalogs=cats)
    doc = {"origin": "coord-a", "seq": 1, "connectorId": "fleetdb",
           "table": "t"}
    assert m.fold_bump(dict(doc)) is True
    s0 = _metric("fleet_bump_stale_total")
    assert m.fold_bump(dict(doc)) is False          # replayed seq
    assert _metric("fleet_bump_stale_total") == s0 + 1
    assert m.fold_bump(dict(doc, seq=2)) is True    # monotonic advance
    u0 = _metric("fleet_bump_unknown_catalog_total")
    assert m.fold_bump(dict(doc, seq=3,
                            connectorId="nosuch")) is False
    assert _metric("fleet_bump_unknown_catalog_total") == u0 + 1
    assert m.fold_bump(dict(doc, origin="coord-b", seq=9)) is False


def test_remote_bump_vs_local_insert_interleaving():
    """The cross-the-wire epoch-before-deps race, systematically
    explored: coordinator B runs a cacheable SELECT while a remote
    write (raw sqlite commit, then ``fold_bump``) lands at every
    schedulable seam. No interleaving may leave a stale entry — the
    fold's notify bumps the write epoch, and an insert whose epoch
    predates it is vetoed."""
    from presto_tpu._devtools.interleave import (explore,
                                                 failpoints_as_points,
                                                 point)

    def make():
        db = os.path.join(tempfile.mkdtemp(prefix="fleet_race_"),
                          "race.db")
        r = _make_runner(db)
        member = FleetMember("coord-b", "http://127.0.0.1:0",
                             catalogs=r.session.catalogs)
        r.execute("create table fleetdb.default.rt as select 1 as x")
        sql = ("select count(*) as c, sum(x) as s "
               "from fleetdb.default.rt")

        def reader():
            r.execute(sql, properties=CACHE_PROPS)

        def remote_writer():
            point("remote.write")
            raw = sqlite3.connect(db)
            raw.execute("insert into rt values (2)")
            raw.commit()
            raw.close()
            point("remote.bump")
            member.fold_bump({"origin": "coord-a", "seq": 1,
                              "connectorId": "fleetdb",
                              "table": "rt"})

        def check():
            got = r.execute(sql, properties=CACHE_PROPS).rows
            want = r.execute(sql).rows
            if got != want:
                return f"stale cached rows {got} vs truth {want}"
            return None

        return [reader, remote_writer], check

    with failpoints_as_points(["plancache.plan", "resultcache.stamp"]):
        ex = explore(make, max_schedules=48, preemption_bound=2)
    assert ex.schedules, "explorer executed no schedules"
    ex.assert_clean()


def test_explicit_deregister_beats_the_staleness_grace():
    """ISSUE 20 scale-down race: a DRAINED coordinator must leave the
    survivor's peer list and federated counts the moment its final
    ``leaving`` heartbeat folds — not ``staleness_grace_s`` later, and
    never declared lost. A KILLED coordinator (no leaving heartbeat)
    keeps holding its admission share until the grace expires, then is
    declared lost exactly once. With the grace set huge, only the
    explicit path can possibly clear state — the regression this pins:
    an autoscaler that drains and instantly relaunches must never see
    the old member's ghost counts bind admission against the new one."""
    survivor = FleetMember("coord-0", "http://127.0.0.1:9100",
                           staleness_grace_s=3600.0)
    lost0 = _metric("coordinator_lost_total")

    def hb(origin, url, leaving=False):
        return {"origin": origin, "url": url, "leaving": leaving,
                "groups": {"serving": {"running": 7, "memory": 0}}}

    # two members join: their counts bind admission, urls enter peering
    survivor.fold_heartbeat(hb("coord-drain", "http://127.0.0.1:9101"))
    survivor.fold_heartbeat(hb("coord-kill", "http://127.0.0.1:9102"))
    assert survivor.remote_running("serving") == 14
    assert set(survivor.peers()) == {"http://127.0.0.1:9101",
                                     "http://127.0.0.1:9102"}

    # clean drain: one leaving heartbeat clears EVERYTHING now
    survivor.fold_heartbeat(hb("coord-drain", "http://127.0.0.1:9101",
                               leaving=True))
    st = survivor.status()
    assert survivor.remote_running("serving") == 7
    assert st["peers"] == ["http://127.0.0.1:9102"]
    assert "coord-drain" not in st["remote"]
    assert st["lost"] == []                      # a drain is NOT a loss
    assert _metric("coordinator_lost_total") == lost0

    # killed member: counts persist inside the grace...
    survivor._sweep_lost()
    assert survivor.remote_running("serving") == 7
    assert "coord-kill" in survivor.status()["remote"]
    # ...and only expiring the grace declares the loss (once)
    survivor.staleness_grace_s = 0.0
    survivor._sweep_lost()
    survivor._sweep_lost()
    st = survivor.status()
    assert st["lost"] == ["coord-kill"]
    assert survivor.remote_running("serving") == 0
    assert _metric("coordinator_lost_total") == lost0 + 1

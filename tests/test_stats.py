"""Stats calculus tests: connector statistics drive plan decisions.

Mirrors the reference's cost-framework behavior tests (reference
cost/FilterStatsCalculator.java, cost/JoinStatsRule.java,
iterative/rule/DetermineJoinDistributionType.java): changing ONLY the
table statistics must flip broadcast<->partitioned distribution and
enable/disable the eager-aggregation push.
"""
import dataclasses

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.spi import (
    CatalogManager, ColumnStats, Connector, ConnectorMetadata,
    ConnectorSplitManager, Split, TableHandle, TableStats,
)
from presto_tpu.expr import ir
from presto_tpu.planner.optimizer import optimize
from presto_tpu.planner.plan import JoinNode, TableScanNode
from presto_tpu.planner.planner import Session, plan_query
from presto_tpu.planner.stats import StatsCalculator
from presto_tpu.sql.parser import parse_statement


class _Meta(ConnectorMetadata):
    def __init__(self, tables, stats):
        self._tables = tables          # name -> [(col, type)]
        self._stats = stats            # name -> TableStats

    def list_tables(self, schema=None):
        return list(self._tables)

    def table_schema(self, table):
        from presto_tpu.batch import Schema
        return Schema(self._tables[table.table])

    def table_stats(self, table):
        return self._stats.get(table.table, TableStats())


class _FakeConnector(Connector):
    def __init__(self, tables, stats):
        self.name = "fake"
        self._meta = _Meta(tables, stats)

    @property
    def metadata(self):
        return self._meta

    @property
    def split_manager(self):
        return ConnectorSplitManager()


def _session(stats):
    tables = {
        "fact": [("f_key", T.BIGINT), ("f_val", T.DOUBLE),
                 ("f_ts", T.BIGINT)],
        "dim": [("d_key", T.BIGINT), ("d_name", T.VARCHAR)],
    }
    cat = CatalogManager()
    cat.register("fake", _FakeConnector(tables, stats))
    return Session(catalogs=cat, catalog="fake", schema="default")


def _stats(dim_rows, fact_rows=1_000_000, key_ndv=None):
    # f_key NDV stays consistent: a foreign key repeats, so its NDV can
    # never exceed (here: half) the fact row count
    fk_ndv = key_ndv if key_ndv is not None \
        else min(dim_rows, fact_rows // 2)
    return {
        "fact": TableStats(row_count=fact_rows, columns={
            "f_key": ColumnStats(distinct_count=fk_ndv,
                                 min_value=0, max_value=1_000_000),
            "f_ts": ColumnStats(distinct_count=1000, min_value=0,
                                max_value=1000),
        }),
        "dim": TableStats(row_count=dim_rows, columns={
            "d_key": ColumnStats(distinct_count=dim_rows, min_value=0,
                                 max_value=dim_rows)},
            primary_key=("d_key",)),
    }


def _plan(sql, session):
    return optimize(plan_query(parse_statement(sql), session),
                    session).root


def _find(node, typ):
    out = []

    def walk(n):
        if isinstance(n, typ):
            out.append(n)
        for c in n.children:
            walk(c)
    walk(node)
    return out


JOIN_SQL = """select d_name, sum(f_val) from fact
              join dim on f_key = d_key group by d_name"""


def test_small_dim_broadcasts():
    session = _session(_stats(dim_rows=1000))
    joins = _find(_plan(JOIN_SQL, session), JoinNode)
    assert joins and joins[0].distribution == "replicated"


def test_large_dim_partitions():
    session = _session(_stats(dim_rows=50_000_000))
    joins = _find(_plan(JOIN_SQL, session), JoinNode)
    assert joins and joins[0].distribution == "partitioned"


def test_filter_selectivity_flips_distribution():
    """The SAME table sizes: a selective range filter on the build side
    (estimated through column min/max) shrinks it under the broadcast
    threshold."""
    big = _stats(dim_rows=10_000_000)
    big["dim"] = dataclasses.replace(
        big["dim"], columns={
            "d_key": ColumnStats(distinct_count=10_000_000, min_value=0,
                                 max_value=10_000_000)})
    session = _session(big)
    sql = """select d_name, sum(f_val) from fact
             join (select * from dim where d_key < 1000) d
             on f_key = d_key group by d_name"""
    joins = _find(_plan(sql, session), JoinNode)
    assert joins and joins[0].distribution == "replicated"
    # without the filter the same dim stays partitioned
    joins2 = _find(_plan(JOIN_SQL, session), JoinNode)
    assert joins2 and joins2[0].distribution == "partitioned"


def test_filter_range_selectivity_rows():
    """Range predicates estimate by range overlap, not a fixed factor."""
    session = _session(_stats(dim_rows=1000))
    calc = StatsCalculator(session)
    sql = "select f_val from fact where f_ts < 100"
    root = _plan(sql, session)
    scans = _find(root, TableScanNode)
    assert scans
    # pushdown bakes the bound into the scan estimate, or a FilterNode
    # survives — either way the estimate must reflect ~10% selectivity
    est = calc.rows(root)
    assert est == pytest.approx(100_000, rel=0.5)


def test_eager_agg_gate_follows_stats():
    """High grouping-key NDV (no reduction below the join) disables the
    partial-agg push; low NDV enables it (reference
    PushPartialAggregationThroughJoin's stats gate)."""
    from presto_tpu.planner.plan import AggregationNode

    def agg_below_join(key_ndv):
        session = _session(_stats(dim_rows=1000, key_ndv=key_ndv))
        sql = """select f_key, sum(f_val) from fact
                 join dim on f_key = d_key group by f_key"""
        root = _plan(sql, session)
        aggs = _find(root, AggregationNode)
        joins = _find(root, JoinNode)
        assert joins
        return any(_find(joins[0].left, AggregationNode) for _ in [0]) \
            and bool(_find(joins[0].left, AggregationNode))

    assert agg_below_join(key_ndv=1000)          # 1000x reduction: push
    assert not agg_below_join(key_ndv=900_000)   # no reduction: keep

"""Distributed operators must not stage batches through the host.

The exchange contract (SURVEY.md §2d): all data movement between shards
rides XLA collectives over the mesh; the host sees only deliberate sizing
scalars (explicit jax.device_get) and the final client result. Wrapping
execution in jax.transfer_guard_device_to_host("disallow") rejects any
IMPLICIT device-to-host transfer — the first half of every host bounce —
which pins down round 4's sort/top-n/window/unnest/broadcast-build paths
gathering whole batches into numpy (reference contract: exchange-only
data movement, operator/ExchangeClient.java:55). Host-to-device stays
unguarded: eager jnp ops legitimately ship Python scalar constants.
"""
import jax
import pytest

# tier-1 budget: excluded from `pytest -m 'not slow'` — transfer-guard mesh runs are compile-bound
# (see tools/check_tier1_time.py; ~77s)
pytestmark = pytest.mark.slow

from presto_tpu.exec.distributed import DistributedRunner
from presto_tpu.exec.runner import LocalRunner

SF = 0.01

#: join + top-n + sort + window + unnest + semi-join shapes — one per
#: operator family the round-4 review flagged as host-bouncing
GUARDED_QUERIES = [
    # broadcast-build join + group-by + top-n
    """select o_orderpriority, count(*) c from orders
       join lineitem on o_orderkey = l_orderkey
       group by o_orderpriority order by c desc limit 3""",
    # distributed sort (range exchange)
    """select l_orderkey, l_extendedprice from lineitem
       where l_quantity > 49 order by l_extendedprice desc, l_orderkey""",
    # window over partitions (hash exchange) and global window
    """select o_custkey, rank() over (partition by o_custkey
       order by o_totalprice desc) r from orders where o_custkey < 100""",
    """select o_orderkey, sum(o_totalprice) over (order by o_orderkey)
       from orders where o_orderkey < 64""",
    # unnest
    """select u from unnest(sequence(1, 5)) as t(u)""",
    # semi join
    """select count(*) from orders where o_orderkey in
       (select l_orderkey from lineitem where l_quantity > 49)""",
]


@pytest.fixture(scope="module")
def local():
    return LocalRunner(tpch_sf=SF)


@pytest.fixture(scope="module")
def dist(local):
    return DistributedRunner(catalogs=local.session.catalogs,
                             rows_per_batch=1 << 13)


@pytest.mark.parametrize("sql", GUARDED_QUERIES)
def test_no_implicit_host_transfers(local, dist, sql):
    want = sorted(map(repr, local.execute(sql).rows))
    with jax.transfer_guard_device_to_host("disallow"):
        got = dist.execute(sql)
    assert sorted(map(repr, got.rows)) == want

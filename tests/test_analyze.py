"""Tier-1 gate for the static-analysis plane (tools/analyze/) and its
paired runtime pieces.

Three layers:

- the live tree is GREEN: ``python -m tools.analyze`` semantics (all
  three checker families + baseline) produce zero unsuppressed
  findings and zero stale suppressions;
- each checker family CATCHES its seeded red fixtures under
  tests/fixtures/analyze_bad/ — these tests fail if a checker is
  disabled or its detection rots;
- the registry contracts hold at runtime too: SET SESSION rejects
  unknown/mistyped properties, failpoint specs reject unregistered
  sites, and the lock-order validator (_devtools/lockcheck.py) records
  real edges and flags real inversions.
"""
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analyze_bad")
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analyze import (CHECKERS, caches, locks, registries, run,  # noqa: E402
                           run_changed, tracing)
from tools.analyze.base import Finding, apply_baseline, load_baseline  # noqa: E402


def _rules(findings):
    return {f.rule for f in findings}


def _fixture(name):
    return os.path.join(FIXTURES, name)


# -- the live tree is green --------------------------------------------------

def test_live_tree_has_no_unsuppressed_findings():
    findings, _suppressed, stale = run(root=REPO)
    assert not findings, "\n" + "\n".join(f.render() for f in findings)
    assert not stale, f"stale baseline suppressions: {stale}"


def test_cli_main_exits_zero():
    from tools.analyze.__main__ import main
    assert main([]) == 0


def test_every_checker_family_registered():
    assert set(CHECKERS) == {"tracing", "locks", "registries", "caches"}


def test_analyzer_full_scan_stays_fast():
    # the analyzer polices the tree from inside tier-1; its own cost is
    # budgeted (tools/check_tier1_time.py --analyzer-budget polices the
    # module totals, this pins the core scan itself)
    import time
    t0 = time.monotonic()
    run(root=REPO)
    assert time.monotonic() - t0 < 30.0


# -- red fixtures: tracing ---------------------------------------------------

def test_tracing_catches_tracer_branches():
    fs = tracing.check_paths([_fixture("tracer_branch.py")], REPO)
    by_sym = {(f.rule, f.line) for f in fs}
    assert ("tracer-branch", 13) in by_sym          # if x > 0
    assert ("tracer-branch", 20) in by_sym          # while (via taint)
    concretize = [f for f in fs if f.rule == "tracer-branch"
                  and f.line == 30]
    kinds = {f.message.split("(")[0].split()[0] for f in concretize}
    assert {"float", "bool", ".item"} <= kinds
    assert sum(f.rule == "nondeterminism" for f in fs) == 3


def test_tracing_static_structure_reads_not_flagged():
    fs = tracing.check_paths([_fixture("tracer_branch.py")], REPO)
    assert not [f for f in fs
                if f.symbol.startswith("static_uses_are_fine")
                and f.rule != "raw-jit"]


def test_tracing_catches_raw_jit_and_unbracketed_sync():
    fs = tracing.check_paths([_fixture("raw_jit.py")], REPO)
    raw = [f for f in fs if f.rule == "raw-jit"]
    assert {f.line for f in raw} == {8, 11}
    sync = [f for f in fs if f.rule == "unbracketed-sync"]
    assert {f.line for f in sync} == {17, 18}       # 24 is spanned


def test_tracing_jitcache_itself_is_exempt():
    path = os.path.join(REPO, "presto_tpu", "ops", "jitcache.py")
    fs = tracing.check_paths([path], REPO)
    assert not [f for f in fs if f.rule == "raw-jit"]


# -- red fixtures: locks -----------------------------------------------------

def test_locks_catches_inversion_cycle():
    fs = locks.check_paths([_fixture("lock_inversion.py")], REPO)
    cycles = [f for f in fs if f.rule == "lock-cycle"]
    assert len(cycles) == 1
    assert "_la" in cycles[0].message and "_lb" in cycles[0].message


def test_locks_catches_unjoined_threads():
    fs = locks.check_paths([_fixture("lock_inversion.py")], REPO)
    unjoined = [f for f in fs if f.rule == "unjoined-thread"]
    # the Looper attr thread, the anonymous fire-and-forget, and the
    # local masked by a str.join; the looped t.join() case is clean
    assert {f.line for f in unjoined} == {33, 47, 51}


def test_locks_catches_unlocked_global_write():
    fs = locks.check_paths([_fixture("lock_inversion.py")], REPO)
    writes = [f for f in fs if f.rule == "unlocked-global-write"]
    assert [f.line for f in writes] == [23]         # line 27 is locked


# -- red fixtures: registries ------------------------------------------------

def test_registries_catches_unknown_session_props():
    fs = registries.session_prop_findings(
        REPO, scan_paths=[_fixture("unknown_session_prop.py")],
        doc_path="/nonexistent")
    unknown = {f.symbol for f in fs if f.rule == "unknown-session-prop"}
    assert unknown == {"definitely_not_a_declared_prop",
                       "another_undeclared_prop"}


def test_registries_catches_unknown_failpoint_site():
    fs = registries.failpoint_findings(
        REPO, scan_paths=[_fixture("unknown_session_prop.py")],
        doc_path="/nonexistent")
    assert {f.symbol for f in fs
            if f.rule == "unknown-failpoint-site"} \
        == {"not.a.registered.site"}


def test_registries_metric_rules_still_fire():
    # the folded-in check_metric_names rules (shim covers the CLI; this
    # pins the library path)
    import tempfile
    import textwrap
    with tempfile.TemporaryDirectory() as td:
        with open(os.path.join(td, "bad.py"), "w") as f:
            f.write(textwrap.dedent("""\
                REGISTRY.counter('CamelCase_total').inc()
                REGISTRY.counter('no_unit_suffix').inc()
                REGISTRY.gauge('dup_total').set(1)
                REGISTRY.counter('dup_total').inc()
            """))
        fs = registries.metric_findings([td], REPO, doc_path=None)
    assert _rules(fs) == {"bad-metric-name", "metric-type-conflict"}


# -- red fixtures: tracing/params (ISSUE 15 satellite) -----------------------

def test_tracing_catches_param_bound_read():
    fs = tracing.check_paths([_fixture("param_branch.py")], REPO)
    bound = [f for f in fs if f.rule == "param-bound-read"]
    assert {f.symbol.split(".")[-1] for f in bound} == {"bound",
                                                       "consult"}
    # traced_val results are tainted: branching on one is a
    # tracer-branch even though no jit parameter is involved
    assert any(f.rule == "tracer-branch"
               and f.symbol.startswith("branches_on_dispatch_value")
               for f in fs)


def test_tracing_dispatch_scope_use_is_clean():
    fs = tracing.check_paths([_fixture("param_branch.py")], REPO)
    assert not [f for f in fs
                if f.symbol.startswith("dispatch_scope_used_correctly")
                and f.rule != "raw-jit"]


# -- red fixtures: caches (ISSUE 15 tentpole) --------------------------------

_BAD_CACHE_SPEC = caches.CacheSpec(
    name="badcache",
    module="tests/fixtures/analyze_bad/cache_contract.py",
    cache_class="BadCache",
    versions="key",
    key_fn="key",
    key_version_param="version",
    version_recheck_in=("put",),
    epoch_veto_in=("put",),
    orchestrations={"cached_value": ("build_plan",)},
    invalidation_hook=True,
    bounded_in=("put",),
)

_BAD_DEPS_SPEC = caches.CacheSpec(
    name="baddeps",
    module="tests/fixtures/analyze_bad/cache_contract.py",
    cache_class=None,
    lock_attrs=("_lock",),
    versions="deps",
    deps_fns=("deps_of",),
    revalidate_fns=("deps_of",),
    invalidation_hook=False,
)


def test_caches_catches_every_contract_violation():
    fs = caches.check_specs([_BAD_CACHE_SPEC, _BAD_DEPS_SPEC], REPO)
    assert _rules(fs) == {
        "cache-plain-lock", "cache-key-missing-version",
        "cache-missing-version-recheck", "cache-missing-epoch-veto",
        "cache-epoch-after-deps", "cache-missing-invalidation-hook",
        "cache-unbounded", "cache-missing-deps"}


def test_caches_catches_silent_connector_writes():
    fs = caches.connector_findings(
        REPO, scan_paths=[_fixture("cache_contract.py")])
    bad = {f.symbol for f in fs
           if f.rule == "connector-write-no-notify"}
    # create_table reaches notify through a two-hop helper chain and
    # must NOT be flagged; the silent writes must
    assert bad == {"BadConnector.append", "BadConnector.drop_table"}


def test_caches_undeclared_cache_rule_fires():
    # with an empty registry, every live cache-shaped class is flagged
    fs = caches._undeclared_findings(REPO, specs=())
    names = {f.symbol for f in fs if f.rule == "undeclared-cache"}
    assert {"ScanCache", "PlanCache", "ResultCache",
            "IdentMemo"} <= names
    # and with the real registry, none are
    assert caches._undeclared_findings(REPO, caches.SPECS) == []


def test_caches_live_tree_contracts_hold():
    assert caches.check(REPO) == []


# -- red fixtures: distributed broadcast-fold clauses (ISSUE 19) -------------

def test_caches_catches_fleet_fold_violations():
    fs = caches.fleet_findings(
        REPO, module="tests/fixtures/analyze_bad/fleet_fold.py",
        fold_fns=("fold_bump", "fold_silent"))
    assert _rules(fs) == {"fleet-fold-bypass", "fleet-fold-seq-order",
                          "fleet-fold-unaudited"}
    # the direct cache pokes are flagged individually
    bypass = [f for f in fs if f.rule == "fleet-fold-bypass"]
    assert sorted(f.symbol for f in bypass) == [
        "self.cache.invalidate", "self.cache.note_write"]
    # fold_bump stores the dedupe seq before notify; fold_silent
    # never notifies at all
    assert {f.symbol for f in fs if f.rule == "fleet-fold-seq-order"} \
        == {"fleet.fold_bump"}
    assert {f.symbol for f in fs if f.rule == "fleet-fold-unaudited"} \
        == {"fleet.fold_silent"}


def test_caches_fleet_fold_live_tree_clean():
    assert caches.fleet_findings(REPO) == []


def test_caches_fleet_module_registered():
    # the contract is only worth anything if it points at a real file
    assert os.path.isfile(os.path.join(REPO, caches.FLEET_MODULE))
    mod = caches._Mod(os.path.join(REPO, caches.FLEET_MODULE),
                      caches.FLEET_MODULE)
    for name in caches.FLEET_FOLD_FNS:
        assert mod.fn(name) is not None


# -- red fixtures: env-var registry (ISSUE 15 satellite) ---------------------

def test_registries_catches_undeclared_env_vars():
    fs = registries.env_var_findings(
        REPO, scan_paths=[_fixture("env_var.py")],
        doc_path="/nonexistent", two_way=False)
    assert {f.symbol for f in fs} == {
        "PRESTO_TPU_NOT_A_REAL_KNOB", "BENCH_TYPO_KNOB",
        "PRESTO_TPU_ALSO_UNDECLARED", "BENCH_SETDEFAULT_UNDECLARED"}
    assert _rules(fs) == {"unknown-env-var"}


def test_registries_env_vars_round_trip_on_live_tree():
    assert registries.env_var_findings(REPO) == []


def test_registries_env_var_doc_drift_detected(tmp_path):
    doc = tmp_path / "doc.md"
    doc.write_text("## Environment-variable registry\n\n"
                   "| variable | description |\n|---|---|\n"
                   "| `PRESTO_TPU_LOCKCHECK` | real |\n"
                   "| `PRESTO_TPU_IMAGINARY` | drifted |\n")
    fs = registries.env_var_findings(REPO, doc_path=str(doc))
    drift = {f.symbol for f in fs if f.rule == "env-var-doc-drift"}
    assert "PRESTO_TPU_IMAGINARY" in drift        # documented, unknown
    assert "PRESTO_TPU_LOG" in drift              # declared, undocumented


# -- CLI modes (ISSUE 15 satellite) ------------------------------------------

def test_cli_json_format_shape():
    import io
    import json as _json
    from contextlib import redirect_stdout
    from tools.analyze.__main__ import main
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["--format", "json"])
    doc = _json.loads(buf.getvalue())
    assert rc == 0 and doc["ok"] is True
    assert doc["mode"] == "full"
    assert doc["findings"] == [] and doc["stale_suppressions"] == []


def test_run_changed_scopes_to_given_files():
    # a changed file in the tracing scope is scanned; stale detection
    # is skipped by contract
    findings, _sup, stale = run_changed(
        ["presto_tpu/exec/fused.py", "presto_tpu/serving/plancache.py"],
        root=REPO)
    assert findings == [] and stale == []


def test_run_changed_inherited_spec_alone_is_clean():
    # the templates spec delegates lock/dep/veto clauses to plancache;
    # a delta containing ONLY template.py must not re-check them
    # against template.py (regression: false cache-plain-lock)
    findings, _sup, _st = run_changed(
        ["presto_tpu/serving/template.py"], root=REPO)
    assert findings == []


def test_run_changed_config_keys_scoped_to_their_files():
    # scancache reads scan_threads/scan_prefetch_depth off a session
    # OPTIONS dict via props.get — not config keys; the fast path must
    # not widen config_key_findings past its full-scan file set
    findings, _sup, _st = run_changed(
        ["presto_tpu/exec/scancache.py"], root=REPO)
    assert findings == []


def test_run_changed_runs_undeclared_cache_sweep():
    # the sweep accepts explicit paths (the fast-mode wiring) and still
    # catches a cache-shaped class missing from the registry
    fs = caches._undeclared_findings(
        REPO, specs=(), scan_paths=[_fixture("cache_contract.py")])
    assert {f.symbol for f in fs
            if f.rule == "undeclared-cache"} == {"BadCache"}


def test_run_changed_falls_back_on_global_inputs():
    # touching a declaring input (config.py) escalates to the full
    # two-way scan — which is green on the live tree
    findings, _sup, stale = run_changed(
        ["presto_tpu/config.py"], root=REPO)
    assert findings == [] and stale == []


def test_check_tier1_time_analyzer_budget(tmp_path):
    import subprocess
    log = tmp_path / "t1.log"
    log.write_text(
        "12.00s call  tests/test_analyze.py::test_x\n"
        "9.00s call   tests/test_interleave.py::test_y\n"
        "1.00s call   tests/test_sql.py::test_z\n")
    ok = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_tier1_time.py"),
         str(log), "--analyzer-budget", "30"],
        capture_output=True, text=True)
    assert ok.returncode == 0 and "ANALYZER" in ok.stdout
    over = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_tier1_time.py"),
         str(log), "--analyzer-budget", "15"],
        capture_output=True, text=True)
    assert over.returncode == 1
    assert "ANALYZER OVER BUDGET" in over.stderr


# -- baseline machinery ------------------------------------------------------

def test_baseline_suppresses_and_goes_stale(tmp_path):
    f1 = Finding("tracing", "raw-jit", "a.py", 3, "f", "m")
    f2 = Finding("locks", "lock-cycle", "b.py", 9, "g", "m")
    bl_path = tmp_path / "baseline.json"
    bl_path.write_text(json.dumps({"suppressions": [
        {"id": f1.ident, "reason": "accepted"},
        {"id": "tracing:raw-jit:gone.py:old", "reason": "fixed long ago"},
    ]}))
    baseline = load_baseline(str(bl_path))
    keep, dropped, stale = apply_baseline([f1, f2], baseline)
    assert keep == [f2]
    assert dropped == [f1]
    assert stale == ["tracing:raw-jit:gone.py:old"]


# -- runtime: SET SESSION validation -----------------------------------------

@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.01)


def test_set_session_unknown_property_raises(runner):
    from presto_tpu.config import SessionPropertyError
    with pytest.raises(SessionPropertyError, match="unknown session"):
        runner.execute("set session not_a_real_property = 1")
    assert "not_a_real_property" not in runner.session.properties


def test_set_session_type_mismatch_raises(runner):
    from presto_tpu.config import SessionPropertyError
    with pytest.raises(SessionPropertyError, match="expects a integer"):
        runner.execute("set session scan_threads = 'many'")
    with pytest.raises(SessionPropertyError, match="expects a boolean"):
        runner.execute("set session dense_grouping = 7")


def test_set_session_coerces_and_latches(runner):
    try:
        runner.execute("set session dense_grouping = 'false'")
        assert runner.session.properties["dense_grouping"] is False
        runner.execute("set session scan_threads = '3'")
        assert runner.session.properties["scan_threads"] == 3
        runner.execute("set session retry_policy = 'query'")
        assert runner.session.properties["retry_policy"] == "QUERY"
    finally:
        for k in ("dense_grouping", "scan_threads", "retry_policy"):
            runner.session.properties.pop(k, None)


def test_session_defaults_from_config_validated(tmp_path):
    from presto_tpu.config import NodeConfig, SessionPropertyError, \
        validate_session_property
    cfg = NodeConfig({"session.scan_threads": "4"})
    assert validate_session_property(
        "scan_threads", cfg.session_defaults["scan_threads"]) == 4
    with pytest.raises(SessionPropertyError):
        validate_session_property("scan_threads", "lots")
    with pytest.raises(SessionPropertyError):
        validate_session_property("no_such_default", "1")


def test_every_declared_property_documents_itself():
    from presto_tpu.config import SESSION_PROPERTIES
    for sp in SESSION_PROPERTIES.values():
        assert sp.doc.strip(), f"{sp.name} has no doc line"
        assert sp.type in ("boolean", "integer", "double", "varchar",
                           "duration"), sp.name


# -- runtime: failpoint site validation --------------------------------------

def test_failpoint_unknown_site_rejected_at_parse_time():
    from presto_tpu.exec.failpoints import FAILPOINTS
    with pytest.raises(ValueError, match="unknown failpoint site"):
        FAILPOINTS.configure("scan.decoed")      # typo'd site
    with pytest.raises(ValueError, match="unknown failpoint site"):
        FAILPOINTS.configure_from_spec("no.such.site=error")
    # a real site still arms (and disarms) fine
    FAILPOINTS.configure("scan.decode", action="sleep", sleep_s=0.0,
                         times=0)
    FAILPOINTS.clear("scan.decode")


def test_failpoint_unit_registries_can_use_synthetic_sites():
    # rule-machinery unit tests build private registries with no site
    # table — those must keep accepting arbitrary names
    from presto_tpu.exec.failpoints import FailpointRegistry
    reg = FailpointRegistry()
    reg.configure("synthetic.site", times=1)
    with pytest.raises(Exception):
        reg.hit("synthetic.site")


# -- runtime: lock-order validator -------------------------------------------

def test_lockcheck_enabled_under_pytest():
    from presto_tpu._devtools import lockcheck
    assert lockcheck.ENABLED
    lk = lockcheck.checked_lock("test.analyze.probe")
    assert type(lk).__name__ == "_CheckedLock"


def test_lockcheck_records_cycle():
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    a, b = g.lock("A"), g.lock("B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    out = g.check()
    assert any("cycle" in v for v in out)


def test_lockcheck_consistent_order_is_clean():
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    a, b, c = g.lock("A"), g.lock("B"), g.lock("C")
    for _ in range(3):
        with a:
            with b:
                with c:
                    pass
    assert g.check() == []


def test_lockcheck_flags_dispatch_under_lock():
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    a = g.lock("A")
    with a:
        g.note_dispatch("kernel")
    out = g.check()
    assert any("jit dispatch" in v and "kernel" in v for v in out)
    g.reset()
    assert g.check() == []


def test_lockcheck_rlock_reentry_balances():
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    r = g.rlock("R")
    with r:
        with r:
            pass
    assert g.held() == []
    assert g.check() == []


def test_lockcheck_condition_wait_releases_stack():
    import threading
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    lk = g.lock("CV")
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            cv.wait(timeout=5)
            hits.append(tuple(g.held()))

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.1)
    with cv:
        cv.notify()
    t.join(timeout=5)
    assert not t.is_alive()
    assert hits and hits[0] == ("CV",)
    assert g.check() == []


# -- the engine's own locks feed the process graph ---------------------------

def test_engine_locks_recorded_and_clean(runner):
    from presto_tpu._devtools import lockcheck
    runner.execute("select count(*) from nation")
    assert lockcheck.GRAPH.check() == [], lockcheck.GRAPH.check()

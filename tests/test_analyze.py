"""Tier-1 gate for the static-analysis plane (tools/analyze/) and its
paired runtime pieces.

Three layers:

- the live tree is GREEN: ``python -m tools.analyze`` semantics (all
  three checker families + baseline) produce zero unsuppressed
  findings and zero stale suppressions;
- each checker family CATCHES its seeded red fixtures under
  tests/fixtures/analyze_bad/ — these tests fail if a checker is
  disabled or its detection rots;
- the registry contracts hold at runtime too: SET SESSION rejects
  unknown/mistyped properties, failpoint specs reject unregistered
  sites, and the lock-order validator (_devtools/lockcheck.py) records
  real edges and flags real inversions.
"""
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analyze_bad")
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analyze import CHECKERS, locks, registries, run, tracing  # noqa: E402
from tools.analyze.base import Finding, apply_baseline, load_baseline  # noqa: E402


def _rules(findings):
    return {f.rule for f in findings}


def _fixture(name):
    return os.path.join(FIXTURES, name)


# -- the live tree is green --------------------------------------------------

def test_live_tree_has_no_unsuppressed_findings():
    findings, _suppressed, stale = run(root=REPO)
    assert not findings, "\n" + "\n".join(f.render() for f in findings)
    assert not stale, f"stale baseline suppressions: {stale}"


def test_cli_main_exits_zero():
    from tools.analyze.__main__ import main
    assert main([]) == 0


def test_every_checker_family_registered():
    assert set(CHECKERS) == {"tracing", "locks", "registries"}


# -- red fixtures: tracing ---------------------------------------------------

def test_tracing_catches_tracer_branches():
    fs = tracing.check_paths([_fixture("tracer_branch.py")], REPO)
    by_sym = {(f.rule, f.line) for f in fs}
    assert ("tracer-branch", 13) in by_sym          # if x > 0
    assert ("tracer-branch", 20) in by_sym          # while (via taint)
    concretize = [f for f in fs if f.rule == "tracer-branch"
                  and f.line == 30]
    kinds = {f.message.split("(")[0].split()[0] for f in concretize}
    assert {"float", "bool", ".item"} <= kinds
    assert sum(f.rule == "nondeterminism" for f in fs) == 3


def test_tracing_static_structure_reads_not_flagged():
    fs = tracing.check_paths([_fixture("tracer_branch.py")], REPO)
    assert not [f for f in fs
                if f.symbol.startswith("static_uses_are_fine")
                and f.rule != "raw-jit"]


def test_tracing_catches_raw_jit_and_unbracketed_sync():
    fs = tracing.check_paths([_fixture("raw_jit.py")], REPO)
    raw = [f for f in fs if f.rule == "raw-jit"]
    assert {f.line for f in raw} == {8, 11}
    sync = [f for f in fs if f.rule == "unbracketed-sync"]
    assert {f.line for f in sync} == {17, 18}       # 24 is spanned


def test_tracing_jitcache_itself_is_exempt():
    path = os.path.join(REPO, "presto_tpu", "ops", "jitcache.py")
    fs = tracing.check_paths([path], REPO)
    assert not [f for f in fs if f.rule == "raw-jit"]


# -- red fixtures: locks -----------------------------------------------------

def test_locks_catches_inversion_cycle():
    fs = locks.check_paths([_fixture("lock_inversion.py")], REPO)
    cycles = [f for f in fs if f.rule == "lock-cycle"]
    assert len(cycles) == 1
    assert "_la" in cycles[0].message and "_lb" in cycles[0].message


def test_locks_catches_unjoined_threads():
    fs = locks.check_paths([_fixture("lock_inversion.py")], REPO)
    unjoined = [f for f in fs if f.rule == "unjoined-thread"]
    # the Looper attr thread, the anonymous fire-and-forget, and the
    # local masked by a str.join; the looped t.join() case is clean
    assert {f.line for f in unjoined} == {33, 47, 51}


def test_locks_catches_unlocked_global_write():
    fs = locks.check_paths([_fixture("lock_inversion.py")], REPO)
    writes = [f for f in fs if f.rule == "unlocked-global-write"]
    assert [f.line for f in writes] == [23]         # line 27 is locked


# -- red fixtures: registries ------------------------------------------------

def test_registries_catches_unknown_session_props():
    fs = registries.session_prop_findings(
        REPO, scan_paths=[_fixture("unknown_session_prop.py")],
        doc_path="/nonexistent")
    unknown = {f.symbol for f in fs if f.rule == "unknown-session-prop"}
    assert unknown == {"definitely_not_a_declared_prop",
                       "another_undeclared_prop"}


def test_registries_catches_unknown_failpoint_site():
    fs = registries.failpoint_findings(
        REPO, scan_paths=[_fixture("unknown_session_prop.py")],
        doc_path="/nonexistent")
    assert {f.symbol for f in fs
            if f.rule == "unknown-failpoint-site"} \
        == {"not.a.registered.site"}


def test_registries_metric_rules_still_fire():
    # the folded-in check_metric_names rules (shim covers the CLI; this
    # pins the library path)
    import tempfile
    import textwrap
    with tempfile.TemporaryDirectory() as td:
        with open(os.path.join(td, "bad.py"), "w") as f:
            f.write(textwrap.dedent("""\
                REGISTRY.counter('CamelCase_total').inc()
                REGISTRY.counter('no_unit_suffix').inc()
                REGISTRY.gauge('dup_total').set(1)
                REGISTRY.counter('dup_total').inc()
            """))
        fs = registries.metric_findings([td], REPO, doc_path=None)
    assert _rules(fs) == {"bad-metric-name", "metric-type-conflict"}


# -- baseline machinery ------------------------------------------------------

def test_baseline_suppresses_and_goes_stale(tmp_path):
    f1 = Finding("tracing", "raw-jit", "a.py", 3, "f", "m")
    f2 = Finding("locks", "lock-cycle", "b.py", 9, "g", "m")
    bl_path = tmp_path / "baseline.json"
    bl_path.write_text(json.dumps({"suppressions": [
        {"id": f1.ident, "reason": "accepted"},
        {"id": "tracing:raw-jit:gone.py:old", "reason": "fixed long ago"},
    ]}))
    baseline = load_baseline(str(bl_path))
    keep, dropped, stale = apply_baseline([f1, f2], baseline)
    assert keep == [f2]
    assert dropped == [f1]
    assert stale == ["tracing:raw-jit:gone.py:old"]


# -- runtime: SET SESSION validation -----------------------------------------

@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.01)


def test_set_session_unknown_property_raises(runner):
    from presto_tpu.config import SessionPropertyError
    with pytest.raises(SessionPropertyError, match="unknown session"):
        runner.execute("set session not_a_real_property = 1")
    assert "not_a_real_property" not in runner.session.properties


def test_set_session_type_mismatch_raises(runner):
    from presto_tpu.config import SessionPropertyError
    with pytest.raises(SessionPropertyError, match="expects a integer"):
        runner.execute("set session scan_threads = 'many'")
    with pytest.raises(SessionPropertyError, match="expects a boolean"):
        runner.execute("set session dense_grouping = 7")


def test_set_session_coerces_and_latches(runner):
    try:
        runner.execute("set session dense_grouping = 'false'")
        assert runner.session.properties["dense_grouping"] is False
        runner.execute("set session scan_threads = '3'")
        assert runner.session.properties["scan_threads"] == 3
        runner.execute("set session retry_policy = 'query'")
        assert runner.session.properties["retry_policy"] == "QUERY"
    finally:
        for k in ("dense_grouping", "scan_threads", "retry_policy"):
            runner.session.properties.pop(k, None)


def test_session_defaults_from_config_validated(tmp_path):
    from presto_tpu.config import NodeConfig, SessionPropertyError, \
        validate_session_property
    cfg = NodeConfig({"session.scan_threads": "4"})
    assert validate_session_property(
        "scan_threads", cfg.session_defaults["scan_threads"]) == 4
    with pytest.raises(SessionPropertyError):
        validate_session_property("scan_threads", "lots")
    with pytest.raises(SessionPropertyError):
        validate_session_property("no_such_default", "1")


def test_every_declared_property_documents_itself():
    from presto_tpu.config import SESSION_PROPERTIES
    for sp in SESSION_PROPERTIES.values():
        assert sp.doc.strip(), f"{sp.name} has no doc line"
        assert sp.type in ("boolean", "integer", "double", "varchar",
                           "duration"), sp.name


# -- runtime: failpoint site validation --------------------------------------

def test_failpoint_unknown_site_rejected_at_parse_time():
    from presto_tpu.exec.failpoints import FAILPOINTS
    with pytest.raises(ValueError, match="unknown failpoint site"):
        FAILPOINTS.configure("scan.decoed")      # typo'd site
    with pytest.raises(ValueError, match="unknown failpoint site"):
        FAILPOINTS.configure_from_spec("no.such.site=error")
    # a real site still arms (and disarms) fine
    FAILPOINTS.configure("scan.decode", action="sleep", sleep_s=0.0,
                         times=0)
    FAILPOINTS.clear("scan.decode")


def test_failpoint_unit_registries_can_use_synthetic_sites():
    # rule-machinery unit tests build private registries with no site
    # table — those must keep accepting arbitrary names
    from presto_tpu.exec.failpoints import FailpointRegistry
    reg = FailpointRegistry()
    reg.configure("synthetic.site", times=1)
    with pytest.raises(Exception):
        reg.hit("synthetic.site")


# -- runtime: lock-order validator -------------------------------------------

def test_lockcheck_enabled_under_pytest():
    from presto_tpu._devtools import lockcheck
    assert lockcheck.ENABLED
    lk = lockcheck.checked_lock("test.analyze.probe")
    assert type(lk).__name__ == "_CheckedLock"


def test_lockcheck_records_cycle():
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    a, b = g.lock("A"), g.lock("B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    out = g.check()
    assert any("cycle" in v for v in out)


def test_lockcheck_consistent_order_is_clean():
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    a, b, c = g.lock("A"), g.lock("B"), g.lock("C")
    for _ in range(3):
        with a:
            with b:
                with c:
                    pass
    assert g.check() == []


def test_lockcheck_flags_dispatch_under_lock():
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    a = g.lock("A")
    with a:
        g.note_dispatch("kernel")
    out = g.check()
    assert any("jit dispatch" in v and "kernel" in v for v in out)
    g.reset()
    assert g.check() == []


def test_lockcheck_rlock_reentry_balances():
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    r = g.rlock("R")
    with r:
        with r:
            pass
    assert g.held() == []
    assert g.check() == []


def test_lockcheck_condition_wait_releases_stack():
    import threading
    from presto_tpu._devtools.lockcheck import LockGraph
    g = LockGraph()
    lk = g.lock("CV")
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            cv.wait(timeout=5)
            hits.append(tuple(g.held()))

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.1)
    with cv:
        cv.notify()
    t.join(timeout=5)
    assert not t.is_alive()
    assert hits and hits[0] == ("CV",)
    assert g.check() == []


# -- the engine's own locks feed the process graph ---------------------------

def test_engine_locks_recorded_and_clean(runner):
    from presto_tpu._devtools import lockcheck
    runner.execute("select count(*) from nation")
    assert lockcheck.GRAPH.check() == [], lockcheck.GRAPH.check()

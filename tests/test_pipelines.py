"""Hand-built TPC-H pipelines (Q6/Q1/Q3 shapes) vs a pandas oracle.

The functional spec for these pipelines is Presto's hand-built benchmark
pipelines (reference presto-benchmark/.../HandTpchQuery6.java,
HandTpchQuery1.java) — scan -> filter -> project -> aggregate (->join/topN).
"""
import datetime

import pandas as pd
import pytest

from presto_tpu import types as T
from presto_tpu.batch import Batch
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec import (
    AggregationOperator, FilterProjectOperator, HashBuildOperator,
    LimitOperator, LookupJoinOperator, OrderByOperator, TableScanOperator,
    TopNOperator, ValuesOperator, run_pipeline,
)
from presto_tpu.expr import Form, call, input_ref, lit
from presto_tpu.expr.ir import special
from presto_tpu.ops import AggSpec, SortKey
from presto_tpu.connectors.tpch import tpch_schema

SF = 0.005


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(sf=SF)


def _df(conn, table, columns):
    th = TableHandle("tpch", "t", table)
    rows = []
    for split in conn.split_manager.splits(th, 1):
        for b in conn.page_source(split, columns).batches():
            rows.extend(b.to_pylist())
    return pd.DataFrame(rows, columns=columns)


def _scan_ops(conn, table, columns, rows_per_batch=1 << 14):
    th = TableHandle("tpch", "t", table)
    splits = conn.split_manager.splits(th, 1)
    return TableScanOperator(conn, splits[0], columns, rows_per_batch)


def test_q6(conn):
    cols = ["l_shipdate", "l_discount", "l_quantity", "l_extendedprice"]
    schema = tpch_schema("lineitem").select(cols)
    pred = special(
        Form.AND, T.BOOLEAN,
        call("ge", T.BOOLEAN, input_ref(0, T.DATE), lit("1994-01-01", T.DATE)),
        call("lt", T.BOOLEAN, input_ref(0, T.DATE), lit("1995-01-01", T.DATE)),
        special(Form.BETWEEN, T.BOOLEAN, input_ref(1, T.DOUBLE),
                lit(0.05, T.DOUBLE), lit(0.07, T.DOUBLE)),
        call("lt", T.BOOLEAN, input_ref(2, T.DOUBLE), lit(24.0, T.DOUBLE)),
    )
    proj = [call("multiply", T.DOUBLE, input_ref(3, T.DOUBLE), input_ref(1, T.DOUBLE))]
    from presto_tpu.batch import Schema
    out = run_pipeline([
        _scan_ops(conn, "lineitem", cols),
        FilterProjectOperator(schema, pred, proj, ["rev"]),
        AggregationOperator(Schema([("rev", T.DOUBLE)]), [],
                            [AggSpec("sum", 0, T.DOUBLE, "revenue")]),
    ])
    assert len(out) == 1
    got = out[0].to_pylist()[0][0]

    df = _df(conn, "lineitem", cols)
    d0, d1 = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    m = ((df.l_shipdate >= d0) & (df.l_shipdate < d1)
         & (df.l_discount >= 0.05) & (df.l_discount <= 0.07)
         & (df.l_quantity < 24))
    want = (df.l_extendedprice[m] * df.l_discount[m]).sum()
    assert got == pytest.approx(want, rel=1e-12)


def test_q1(conn):
    cols = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate"]
    schema = tpch_schema("lineitem").select(cols)
    cutoff = "1998-09-02"
    pred = call("le", T.BOOLEAN, input_ref(6, T.DATE), lit(cutoff, T.DATE))
    one = lit(1.0, T.DOUBLE)
    disc_price = call("multiply", T.DOUBLE, input_ref(3, T.DOUBLE),
                      call("subtract", T.DOUBLE, one, input_ref(4, T.DOUBLE)))
    charge = call("multiply", T.DOUBLE, disc_price,
                  call("add", T.DOUBLE, one, input_ref(5, T.DOUBLE)))
    proj = [input_ref(0, T.varchar(1)), input_ref(1, T.varchar(1)),
            input_ref(2, T.DOUBLE), input_ref(3, T.DOUBLE), disc_price, charge,
            input_ref(4, T.DOUBLE)]
    names = ["rf", "ls", "qty", "price", "disc_price", "charge", "disc"]
    from presto_tpu.batch import Schema
    mid = Schema([(n, T.varchar(1)) if i < 2 else (n, T.DOUBLE)
                  for i, n in enumerate(names)])
    aggs = [
        AggSpec("sum", 2, T.DOUBLE, "sum_qty"),
        AggSpec("sum", 3, T.DOUBLE, "sum_base"),
        AggSpec("sum", 4, T.DOUBLE, "sum_disc_price"),
        AggSpec("sum", 5, T.DOUBLE, "sum_charge"),
        AggSpec("avg", 2, T.DOUBLE, "avg_qty"),
        AggSpec("avg", 3, T.DOUBLE, "avg_price"),
        AggSpec("avg", 6, T.DOUBLE, "avg_disc"),
        AggSpec("count_star", None, T.BIGINT, "count_order"),
    ]
    out = run_pipeline([
        _scan_ops(conn, "lineitem", cols, rows_per_batch=1 << 13),
        FilterProjectOperator(schema, pred, proj, names),
        AggregationOperator(mid, [0, 1], aggs),
        OrderByOperator([SortKey(0), SortKey(1)]),
    ])
    rows = [r for b in out for r in b.to_pylist()]

    df = _df(conn, "lineitem", cols)
    df = df[df.l_shipdate <= datetime.date(1998, 9, 2)].copy()
    df["disc_price"] = df.l_extendedprice * (1 - df.l_discount)
    df["charge"] = df.disc_price * (1 + df.l_tax)
    g = df.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    ).reset_index().sort_values(["l_returnflag", "l_linestatus"])
    want = [tuple(r) for r in g.itertuples(index=False)]
    assert len(rows) == len(want)
    for got_r, want_r in zip(rows, want):
        assert got_r[0] == want_r[0] and got_r[1] == want_r[1]
        for a, b in zip(got_r[2:], want_r[2:]):
            assert a == pytest.approx(b, rel=1e-9)


def test_q3(conn):
    cutoff = "1995-03-15"
    # stage 1: customers in BUILDING segment -> build (custkey)
    ccols = ["c_custkey", "c_mktsegment"]
    cschema = tpch_schema("customer").select(ccols)
    cust_out = run_pipeline([
        _scan_ops(conn, "customer", ccols),
        FilterProjectOperator(
            cschema,
            call("eq", T.BOOLEAN, input_ref(1, T.varchar(10)),
                 lit("BUILDING", T.varchar(10)))),
    ])
    cust_build = HashBuildOperator()
    for b in cust_out:
        cust_build.add_input(b)
    cust_build.finish()

    # stage 2: orders before cutoff, semi-joined to customers -> build
    ocols = ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]
    oschema = tpch_schema("orders").select(ocols)
    orders_out = run_pipeline([
        _scan_ops(conn, "orders", ocols),
        FilterProjectOperator(
            oschema,
            call("lt", T.BOOLEAN, input_ref(2, T.DATE), lit(cutoff, T.DATE))),
        LookupJoinOperator(cust_build, [1], [0], [], [], "inner"),
    ])
    orders_build = HashBuildOperator()
    for b in orders_out:
        orders_build.add_input(b)
    orders_build.finish()

    # stage 3: lineitem after cutoff -> join orders -> agg -> topN
    lcols = ["l_orderkey", "l_shipdate", "l_extendedprice", "l_discount"]
    lschema = tpch_schema("lineitem").select(lcols)
    from presto_tpu.batch import Schema
    joined_schema = Schema([
        ("l_orderkey", T.BIGINT), ("rev", T.DOUBLE),
        ("o_orderdate", T.DATE), ("o_shippriority", T.INTEGER),
    ])
    rev = call("multiply", T.DOUBLE, input_ref(2, T.DOUBLE),
               call("subtract", T.DOUBLE, lit(1.0, T.DOUBLE),
                    input_ref(3, T.DOUBLE)))
    out = run_pipeline([
        _scan_ops(conn, "lineitem", lcols, rows_per_batch=1 << 13),
        FilterProjectOperator(
            lschema,
            call("gt", T.BOOLEAN, input_ref(1, T.DATE), lit(cutoff, T.DATE))),
        LookupJoinOperator(orders_build, [0], [0], [2, 3],
                           ["o_orderdate", "o_shippriority"], "inner"),
        FilterProjectOperator(
            Schema(list(zip(lschema.names, lschema.types))
                   + [("o_orderdate", T.DATE), ("o_shippriority", T.INTEGER)]),
            None,
            [input_ref(0, T.BIGINT), rev, input_ref(4, T.DATE),
             input_ref(5, T.INTEGER)],
            ["l_orderkey", "rev", "o_orderdate", "o_shippriority"]),
        AggregationOperator(joined_schema, [0, 2, 3],
                            [AggSpec("sum", 1, T.DOUBLE, "revenue")]),
        TopNOperator([SortKey(3, ascending=False), SortKey(1)], 10),
    ])
    rows = [r for b in out for r in b.to_pylist()]
    # agg output layout: [l_orderkey, o_orderdate, o_shippriority, revenue]

    # oracle
    cust = _df(conn, "customer", ccols)
    orders = _df(conn, "orders", ocols)
    li = _df(conn, "lineitem", lcols)
    cutoff_d = datetime.date(1995, 3, 15)
    cust = cust[cust.c_mktsegment == "BUILDING"]
    orders = orders[(orders.o_orderdate < cutoff_d)
                    & orders.o_custkey.isin(cust.c_custkey)]
    li = li[li.l_shipdate > cutoff_d]
    j = li.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    g = (j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["rev"]
         .sum().reset_index())
    g = g.sort_values(["rev", "o_orderdate"], ascending=[False, True]).head(10)
    want = [(int(r.l_orderkey), r.o_orderdate, int(r.o_shippriority),
             pytest.approx(r.rev, rel=1e-9)) for r in g.itertuples(index=False)]
    assert rows == want

"""Serving plane: plan cache, group-weighted device scheduling, group
memory accounting, shared-scan batching, queued timeouts, and the
32-query concurrency stress test (ISSUE 9 acceptance)."""
import threading
import time

import pytest

from presto_tpu.exec.runner import LocalRunner
from presto_tpu.serving.plancache import PLANS, PlanCache


@pytest.fixture()
def runner():
    r = LocalRunner(tpch_sf=0.001)
    yield r


def _metric(name: str) -> float:
    from presto_tpu.obs.metrics import REGISTRY
    for m in REGISTRY.snapshot():
        if m["name"] == name:
            return float(m["value"])
    return 0.0


# -- plan cache ---------------------------------------------------------------

def test_plan_cache_repeated_statement_hits(runner):
    sql = "select count(*) from nation where n_regionkey = 1"
    h0, m0 = _metric("plan_cache_hit_total"), _metric("plan_cache_miss_total")
    first = runner.execute(sql).rows
    second = runner.execute(sql).rows
    assert first == second
    assert _metric("plan_cache_miss_total") == m0 + 1
    assert _metric("plan_cache_hit_total") == h0 + 1


def test_plan_cache_execute_skips_replan(runner):
    runner.execute("prepare dash from "
                   "select count(*) from orders where o_totalprice > ?")
    h0 = _metric("plan_cache_hit_total")
    a = runner.execute("execute dash using 1000").rows
    b = runner.execute("execute dash using 1000").rows
    assert a == b
    # the second EXECUTE of identical arguments rides the cached plan
    assert _metric("plan_cache_hit_total") == h0 + 1
    # different arguments are a different fingerprint: re-planned under
    # the new binding, never served the other binding's plan
    assert runner.execute("execute dash using 999999999").rows == [(0,)]


def test_plan_cache_invalidated_by_write(runner):
    runner.execute("create table memory.t1 as select 1 as x")
    sql = "select count(*) from memory.t1"
    assert runner.execute(sql).rows == [(1,)]
    i0 = _metric("plan_cache_invalidated_total")
    runner.execute("insert into memory.t1 select 2")
    # the write invalidated the cached plan (eager hook) and the re-run
    # sees the new row — never a stale plan over stale stats
    assert runner.execute(sql).rows == [(2,)]
    assert _metric("plan_cache_invalidated_total") >= i0 + 1


def test_plan_cache_property_sensitivity(runner):
    """A session-property overlay is part of the fingerprint: toggling
    an optimizer gate must not serve the other variant's plan."""
    sql = "select count(*) from lineitem where l_quantity > 20"
    base = runner.execute(sql).rows
    off = runner.execute(sql,
                         properties={"dense_grouping": False}).rows
    assert base == off


def test_plan_cache_disabled_by_session_prop(runner):
    sql = "select count(*) from region"
    h0 = _metric("plan_cache_hit_total")
    m0 = _metric("plan_cache_miss_total")
    runner.execute(sql, properties={"plan_cache": False})
    runner.execute(sql, properties={"plan_cache": False})
    assert _metric("plan_cache_hit_total") == h0
    assert _metric("plan_cache_miss_total") == m0


def test_plan_cache_uncacheable_system_tables(runner):
    """system.runtime tables have no data version: never cached."""
    sql = "select count(*) from system.runtime.metrics"
    runner.execute(sql)
    h0 = _metric("plan_cache_hit_total")
    runner.execute(sql)
    assert _metric("plan_cache_hit_total") == h0


def test_plan_cache_lru_eviction():
    pc = PlanCache(capacity=2)

    class _Plan:
        def __init__(self):
            self.root = type("N", (), {"children": ()})()
            self.init_plans = []

    class _Sess:
        class catalogs:
            @staticmethod
            def get(name):
                raise AssertionError("no scans, no deps")
    for i in range(3):
        # dep-free plans (no scans) cache unconditionally
        assert pc.put(bytes([i]), _Plan(), _Sess())
    assert len(pc) == 2
    assert pc.get(bytes([0])) is None      # oldest evicted
    assert pc.get(bytes([2])) is not None


# -- group-weighted fair device scheduling ------------------------------------

def test_group_weighted_quanta_ratio():
    """ISSUE 9 acceptance: under saturation a 2-weight group receives
    >= 1.5x the device quanta of a 1-weight group."""
    from presto_tpu.exec.taskexec import DeviceScheduler

    sched = DeviceScheduler()
    stop = threading.Event()
    counts = {"heavy": 0, "light": 0}
    lock = threading.Lock()

    def worker(group: str, weight: int) -> None:
        h = sched.task(name=f"{group}-t", group=group, weight=weight)
        try:
            while not stop.is_set():
                sched.run_quantum(h, lambda: time.sleep(0.002))
                with lock:
                    counts[group] += 1
        finally:
            h.close()

    threads = [threading.Thread(target=worker, args=("heavy", 2)),
               threading.Thread(target=worker, args=("heavy", 2)),
               threading.Thread(target=worker, args=("light", 1)),
               threading.Thread(target=worker, args=("light", 1))]
    for t in threads:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    assert counts["light"] > 0, counts
    ratio = counts["heavy"] / counts["light"]
    assert ratio >= 1.5, counts
    shares = sched.group_shares()
    assert shares["heavy"]["device_seconds"] > \
        shares["light"]["device_seconds"]


def test_group_share_registry_bounded():
    """Idle shares beyond the cap evict: restart-per-tenant churn must
    not grow the scheduler's ledger (or the snapshot denominator)
    forever."""
    from presto_tpu.exec.taskexec import _MAX_SHARES, DeviceScheduler

    sched = DeviceScheduler()
    for i in range(_MAX_SHARES + 50):
        sched.task(name=f"t{i}", group=f"rg{i}/g").close()
    live = sched.task(name="live", group="keep/g")
    assert len(sched.group_shares()) <= _MAX_SHARES + 1
    assert "keep/g" in sched.group_shares()   # active share survives
    live.close()


def test_group_share_idle_return_clamp():
    """A group returning from idle competes from the active floor — it
    cannot replay its idle period as debt and monopolize the device."""
    from presto_tpu.exec.taskexec import DeviceScheduler

    sched = DeviceScheduler()
    a = sched.task(name="a", group="ga", weight=1)
    for _ in range(20):
        sched.run_quantum(a, lambda: time.sleep(0.001))
    # group gb was idle the whole time; its share starts at ga's vtime
    b = sched.task(name="b", group="gb", weight=1)
    shares = sched.group_shares()
    assert shares["gb"]["vtime"] >= shares["ga"]["vtime"] * 0.99
    a.close()
    b.close()


# -- group memory accounting --------------------------------------------------

def _group_manager(**leaf):
    from presto_tpu.server.resource_groups import ResourceGroupManager
    return ResourceGroupManager({
        "rootGroups": [{"name": "g", "hardConcurrencyLimit": 8,
                        "maxQueued": 100, **leaf}],
        "selectors": [{"group": "g"}]})


def test_group_memory_charges_and_refunds():
    from presto_tpu.serving.groups import QueryServingContext
    m = _group_manager(softMemoryLimit=1000)
    adm = m.submit()
    ctx = QueryServingContext(adm.group)
    ctx.charge(600)
    assert adm.group.memory_reserved == 600
    assert not adm.group.over_soft_memory()
    ctx.charge(600)
    assert adm.group.over_soft_memory()
    # over the soft limit the group queues new work
    adm2 = m.submit()
    assert not adm2.granted
    # refund wakes the dispatcher: the queued query is admitted
    ctx.close()
    assert adm.group.memory_reserved == 0
    assert adm2.granted
    adm2.release()
    adm.release()


def test_group_hard_memory_limit_kills_requester():
    from presto_tpu.memory import MemoryLimitExceeded, QueryMemoryPool
    from presto_tpu.serving.groups import QueryServingContext
    m = _group_manager(hardMemoryLimit=1 << 20)
    adm = m.submit()
    ctx = QueryServingContext(adm.group)
    pool = QueryMemoryPool(group=ctx)
    opctx = pool.context("op")
    pool.reserve(1 << 19, opctx)
    with pytest.raises(MemoryLimitExceeded) as ei:
        pool.reserve(1 << 20, opctx)
    assert "resource group" in str(ei.value)
    # the failed reservation left both ledgers consistent
    assert pool.reserved == 1 << 19
    assert adm.group.memory_reserved == 1 << 19
    opctx.close()
    assert adm.group.memory_reserved == 0
    ctx.close()
    adm.release()


def test_group_memory_via_protocol_query():
    """End to end: a protocol query's pool reservations land on the
    admitting group and return to zero afterwards."""
    from presto_tpu.server.protocol import PrestoTpuServer

    srv = PrestoTpuServer(LocalRunner(tpch_sf=0.001))
    try:
        q = srv.create_query(
            "select l_returnflag, sum(l_quantity) from lineitem "
            "group by l_returnflag", {})
        q.done.wait(timeout=30)
        assert q.state == "FINISHED"
        root = srv.resource_groups.roots["global"]
        assert root.memory_reserved == 0
        assert root.running == 0
    finally:
        srv.stop()


# -- admission: leak regression + queued timeout ------------------------------

def test_failed_query_releases_admission_slot():
    """ISSUE 9 satellite: a query that fails during planning/execution
    must release its resource-group slot on every exit path."""
    from presto_tpu.server.protocol import PrestoTpuServer

    srv = PrestoTpuServer(LocalRunner(tpch_sf=0.001))
    try:
        q = srv.create_query("select bogus_column from nation", {})
        q.done.wait(timeout=30)
        assert q.state == "FAILED"
        info = srv.resource_groups.info()[0]
        assert info["numRunning"] == 0 and info["numQueued"] == 0
        # and the next query is admitted normally
        q2 = srv.create_query("select 1", {})
        q2.done.wait(timeout=30)
        assert q2.state == "FINISHED"
    finally:
        srv.stop()


def test_query_queued_timeout():
    """A query stuck in the admission queue past its deadline fails
    with a distinct QUERY_QUEUED_TIMEOUT verdict (and frees its queue
    slot), instead of waiting forever."""
    from presto_tpu.server.protocol import PrestoTpuServer

    class SlowRunner:
        def __init__(self):
            self.gate = threading.Event()
            self.started = threading.Event()
            from presto_tpu.exec.local import QueryResult
            self._result = QueryResult(["x"], [], [(1,)])

        def execute(self, sql, properties=None, user="",
                    cancel_event=None):
            if sql == "slow":
                self.started.set()
                self.gate.wait(20)
            return self._result

    runner = SlowRunner()
    srv = PrestoTpuServer(runner=runner)   # serial default group
    try:
        q1 = srv.create_query("slow", {})
        # producers run on a shared pool: without this rendezvous q2
        # can win the serial slot before q1 is admitted (and FINISH
        # instead of timing out) — wait until q1 actually holds it
        assert runner.started.wait(10)
        q2 = srv.create_query("fast", {"query_queued_timeout": "0.3s"})
        q2.done.wait(timeout=10)
        assert q2.state == "FAILED"
        assert q2.error["errorName"] == "QUERY_QUEUED_TIMEOUT"
        info = srv.resource_groups.info()[0]
        assert info["numQueued"] == 0
        runner.gate.set()
        q1.done.wait(timeout=10)
        assert q1.state == "FINISHED"
        assert info["numRunning"] in (0, 1)  # q1 may still be draining
    finally:
        runner.gate.set()
        srv.stop()


def test_group_config_queued_timeout():
    from presto_tpu.server.resource_groups import ResourceGroupManager
    m = ResourceGroupManager({
        "rootGroups": [{"name": "g", "hardConcurrencyLimit": 1,
                        "queryQueuedTimeout": "250ms"}],
        "selectors": [{"group": "g"}]})
    a = m.submit()
    b = m.submit()
    assert b.queued_timeout_s() == pytest.approx(0.25)
    # session override wins over the group config
    assert b.queued_timeout_s("2s") == pytest.approx(2.0)
    b.release()
    a.release()


# -- shared-scan batching -----------------------------------------------------

def test_shared_scan_single_decode():
    """N concurrent misses on one split ride ONE decode: the connector
    sees one page_source call, every query gets full results."""
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.connectors.tpch import TpchConnector

    class CountingConnector:
        def __init__(self, inner):
            self._inner = inner
            self.name = inner.name
            self.decodes_by_split = {}
            self._lock = threading.Lock()
            self._gate = threading.Event()

        @property
        def metadata(self):
            return self._inner.metadata

        @property
        def split_manager(self):
            return self._inner.split_manager

        def data_version(self, table):
            return self._inner.data_version(table)

        def page_source(self, split, columns, pushdown=None,
                        rows_per_batch=1 << 17):
            with self._lock:
                key = (split.table.table, split.info)
                self.decodes_by_split[key] = \
                    self.decodes_by_split.get(key, 0) + 1
            inner = self._inner.page_source(
                split, columns, pushdown=pushdown,
                rows_per_batch=rows_per_batch)
            gate = self._gate

            class _PS:
                def batches(self):
                    for b in inner.batches():
                        # slow decode: attached queries must wait on
                        # this in-flight decode, not start their own
                        gate.wait(0.05)
                        yield b
            return _PS()

    conn = CountingConnector(TpchConnector(sf=0.001))
    catalogs = CatalogManager()
    catalogs.register("tpch", conn)
    runner = LocalRunner(catalogs=catalogs)
    sql = "select count(*), sum(o_totalprice) from orders"
    a0 = _metric("scan_shared_attach_total")

    results, errors = [], []

    def go():
        try:
            results.append(runner.execute(
                sql, properties={"plan_cache": False}).rows)
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=go) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert len(results) == 4
    assert all(r == results[0] for r in results)
    # exactly ONE decode per split across all 4 queries; the rest
    # attached to the in-flight decode or replayed the inserted entry
    # (both are shared-work wins; what must not happen is 4x decodes)
    assert conn.decodes_by_split, "no scans observed"
    assert all(n == 1 for n in conn.decodes_by_split.values()), \
        conn.decodes_by_split
    assert _metric("scan_shared_attach_total") >= a0


def test_shared_scan_owner_failure_recovers():
    """If the owning decode dies, attached queries retry and succeed."""
    from presto_tpu.exec.scancache import CACHE

    key = ("synthetic-inflight-key",)
    fl, owner = CACHE.join_inflight(key)
    assert owner
    got = []

    def waiter():
        rec, own = CACHE.join_inflight(key)
        assert not own
        rec.event.wait(5)
        got.append(rec.batches)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    CACHE.finish_inflight(key, None)    # owner failed
    t.join(timeout=5)
    assert got == [None]                # waiter told to retry
    # registry is clean: the next joiner becomes owner again
    fl2, owner2 = CACHE.join_inflight(key)
    assert owner2
    CACHE.finish_inflight(key, None)


# -- serving regression gate --------------------------------------------------

def test_serving_regression_gate_smoke(capsys):
    """ISSUE 9 satellite: the bench gate also covers the committed
    SERVING_r*.json — self-comparison passes, a degraded copy fails."""
    from tools.check_bench_regression import main
    assert main(["--kind", "serving", "--smoke"]) == 0
    import json
    doc = json.loads(capsys.readouterr().out)
    assert doc["verdict"] == "pass"
    assert doc["self_comparison"] == "pass"
    assert doc["degraded_comparison"] == "fail"
    assert any("qps" in m for m in doc["metrics"])
    # ISSUE 13: the r02+ pins carry the template/result hit-rate keys —
    # the gate must cover them (a halved hit rate fails the degraded
    # comparison above)
    assert any("template_hit_rate" in m for m in doc["metrics"])
    assert any("result_hit_rate" in m for m in doc["metrics"])
    # ISSUE 18: r03+ pins carry the health plane's slo block — smoke
    # schema-validates it through tools/slo_report.py (objectives,
    # burn timeline with windowed p95, alert transitions)
    assert doc["slo"]["ok"], doc["slo"]["violations"]
    assert doc["slo"]["blocks"] == 1
    # ISSUE 19: r04+ pins carry the fleet block — smoke validates its
    # invariants (balanced per-coordinator qps summing to the
    # aggregate, zero failed queries through the kill drill, row-exact
    # cross-coordinator coherence)
    assert doc["fleet"]["ok"], doc["fleet"]["violations"]
    assert doc["fleet"]["blocks"] == 1


def test_serving_gate_latency_metrics_invert():
    """p95 latency regresses by going UP: the gate inverts *_ms
    ratios, so a doubled latency fails and a halved one passes."""
    from tools.check_bench_regression import compare
    base = {"serving_qps": {"metric": "serving_qps", "value": 100.0},
            "serving_p95_latency_ms": {
                "metric": "serving_p95_latency_ms", "value": 50.0}}
    slower = {"serving_qps": {"metric": "serving_qps", "value": 100.0},
              "serving_p95_latency_ms": {
                  "metric": "serving_p95_latency_ms", "value": 100.0}}
    faster = {"serving_qps": {"metric": "serving_qps", "value": 100.0},
              "serving_p95_latency_ms": {
                  "metric": "serving_p95_latency_ms", "value": 25.0}}
    assert compare(base, slower)["verdict"] == "fail"
    assert compare(base, faster)["verdict"] == "pass"


def _good_fleet_block():
    """A fleet block shaped exactly like bench_serving_fleet's."""
    return {
        "coordinators": 3,
        "workers": 1,
        "per_coordinator_qps": {"coord-0": 300.0, "coord-1": 310.0,
                                "coord-2": 290.0},
        "aggregate_qps": 900.0,
        "client_failovers": 0,
        "coherence": {"bump_fold_delta": 1.0,
                      "remote_invalidation_observed": True,
                      "xcoord_result_cache_hits": 1,
                      "rows_before": [[1, 1]], "rows_after": [[2, 3]],
                      "row_exact": True},
        "kill": {"killed": "coord-2", "queries": 128,
                 "failed_queries": 0, "client_failovers": 3,
                 "client_retries": 3, "coordinator_lost_total": 2.0,
                 "survivor_lost_view": ["coord-2"]},
    }


def test_fleet_gate_invariants():
    """ISSUE 19: the serving gate's fleet block — the good block
    passes, a pin without one passes vacuously, and every violated
    invariant (too-small fleet, idle member, aggregate drift, missing
    coherence proof, failed kill drill) fails."""
    import copy

    from tools.check_bench_regression import _fleet_gate

    flat = {"serving_qps": {"metric": "serving_qps", "value": 900.0,
                            "fleet": _good_fleet_block()}}
    v = _fleet_gate(flat)
    assert v["ok"] and v["blocks"] == 1, v
    vac = _fleet_gate({"m": {"metric": "m", "value": 1.0}})
    assert vac["ok"] and vac["blocks"] == 0

    mutations = [
        lambda fl: fl.update(coordinators=2),
        lambda fl: fl["per_coordinator_qps"].update({"coord-1": 0.0}),
        lambda fl: fl["per_coordinator_qps"].pop("coord-1"),
        lambda fl: fl.update(aggregate_qps=2000.0),
        lambda fl: fl["coherence"].update(
            remote_invalidation_observed=False),
        lambda fl: fl["coherence"].update(row_exact=False),
        lambda fl: fl["coherence"].update(xcoord_result_cache_hits=0),
        lambda fl: fl.pop("coherence"),
        lambda fl: fl["kill"].update(failed_queries=2),
        lambda fl: fl["kill"].update(coordinator_lost_total=0.0),
        lambda fl: fl["kill"].update(survivor_lost_view=[]),
        lambda fl: fl.pop("kill"),
    ]
    for mut in mutations:
        f = copy.deepcopy(flat)
        mut(f["serving_qps"]["fleet"])
        assert not _fleet_gate(f)["ok"], mut


# -- cluster path through admission + plan cache (ISSUE 10 satellite) ---------

def test_cluster_runner_through_admission_and_plan_cache():
    """The statement server fronts a ClusterRunner with the SAME
    resource-group admission, serving handoff, and compiled-plan cache
    that LocalRunner deployments get: repeated statements skip
    parse/plan/optimize, the admitting group's slot frees on every
    exit path, and the query's device quanta bill the group's
    scheduler share on the (in-process) workers."""
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.server.protocol import (
        PrestoTpuServer, _runner_accepts_serving,
    )
    from presto_tpu.server.worker import WorkerServer

    workers = [WorkerServer(tpch_sf=0.001) for _ in range(2)]
    for w in workers:
        w.start()
    urls = [f"http://127.0.0.1:{w.port}" for w in workers]
    runner = ClusterRunner(urls, tpch_sf=0.001, heartbeat=False)
    assert _runner_accepts_serving(runner)
    srv = PrestoTpuServer(runner=runner, resource_groups={
        "rootGroups": [{"name": "fleet", "hardConcurrencyLimit": 2,
                        "schedulingWeight": 3}],
        "selectors": [{"group": "fleet"}]})
    try:
        sql = ("select n_regionkey, count(*) c from nation "
               "group by n_regionkey order by n_regionkey")
        h0 = _metric("plan_cache_hit_total")
        q1 = srv.create_query(sql, {}, user="alice")
        q1.done.wait(timeout=60)
        assert q1.state == "FINISHED", q1.error
        q2 = srv.create_query(sql, {}, user="alice")
        q2.done.wait(timeout=60)
        assert q2.state == "FINISHED", q2.error
        # the repeated statement rode the compiled-plan cache on the
        # CLUSTER path
        assert _metric("plan_cache_hit_total") >= h0 + 1
        # admission accounting drained on every exit path
        info = srv.resource_groups.info()[0]
        assert info["numRunning"] == 0 and info["numQueued"] == 0
        # the admitting group's stride share exists on the device
        # scheduler (the worker-side serving handoff landed)
        from presto_tpu.exec.taskexec import GLOBAL
        assert any(k.endswith("/fleet")
                   for k in GLOBAL.group_shares()), \
            GLOBAL.group_shares().keys()
        # per-query session property overlays reach the cluster
        # session (a bad value fails the statement, a good one binds)
        q3 = srv.create_query(sql, {"retry_policy": "BOGUS"})
        q3.done.wait(timeout=60)
        assert q3.state == "FAILED"
        q4 = srv.create_query(sql, {"retry_policy": "NONE"})
        q4.done.wait(timeout=60)
        assert q4.state == "FINISHED", q4.error
    finally:
        srv.stop()
        for w in workers:
            try:
                w.stop()
            except Exception:
                pass


# -- concurrency stress test --------------------------------------------------

def test_concurrent_stress_parity_and_fairness():
    """ISSUE 9 satellite: ~32 mixed queries (repeated + distinct, two
    groups) concurrently against one server == serial results, with
    plan-cache hits observed and a clean lock-order graph."""
    from presto_tpu.client import StatementClient
    from presto_tpu.server.protocol import PrestoTpuServer

    runner = LocalRunner(tpch_sf=0.001)
    srv = PrestoTpuServer(runner, resource_groups={
        "rootGroups": [
            {"name": "root", "hardConcurrencyLimit": 8,
             "maxQueued": 1000,
             "subGroups": [
                 {"name": "etl", "hardConcurrencyLimit": 8,
                  "schedulingWeight": 2},
                 {"name": "adhoc", "hardConcurrencyLimit": 8,
                  "schedulingWeight": 1}]}],
        "selectors": [{"user": "etl-.*", "group": "root.etl"},
                      {"group": "root.adhoc"}]})
    srv.start()
    statements = [
        "select count(*) from lineitem where l_quantity > 25",
        "select l_returnflag, count(*) from lineitem "
        "group by l_returnflag order by l_returnflag",
        "select count(*) from orders where o_totalprice > 1000",
        "select n_name from nation order by n_name limit 3",
        "select r_name, count(*) from region group by r_name "
        "order by r_name",
        "select max(o_orderdate) from orders",
        "select count(distinct l_suppkey) from lineitem",
        "select sum(l_extendedprice * (1 - l_discount)) from lineitem "
        "where l_shipdate > date '1995-01-01'",
    ]
    try:
        # serial oracle (one execution per distinct statement)
        serial = {}
        oracle = StatementClient(f"http://127.0.0.1:{srv.port}",
                                 user="oracle")
        for s in statements:
            serial[s] = oracle.execute(s).rows
        h0 = _metric("plan_cache_hit_total")

        results, errors = {}, []
        lock = threading.Lock()

        def client(ci: int) -> None:
            user = f"etl-{ci}" if ci % 2 == 0 else f"adhoc-{ci}"
            cl = StatementClient(f"http://127.0.0.1:{srv.port}",
                                 user=user)
            sql = statements[ci % len(statements)]
            try:
                rows = cl.execute(sql).rows
                with lock:
                    results.setdefault(sql, []).append(rows)
            except Exception as e:
                errors.append(f"{ci}: {e}")

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        # row-exact parity with serial execution, for every client
        total = 0
        for sql, outs in results.items():
            for rows in outs:
                assert rows == serial[sql], sql
                total += 1
        assert total == 32
        # repeated statements rode the plan cache
        assert _metric("plan_cache_hit_total") > h0
        # both groups ran work and drained clean
        info = srv.resource_groups.info()[0]
        assert info["numRunning"] == 0 and info["numQueued"] == 0
        rows = runner.execute(
            "select \"group\", running, queued from "
            "system.runtime.resource_groups").rows
        groups = {r[0] for r in rows}
        assert {"root", "root.etl", "root.adhoc"} <= groups
        # no lock-discipline violations under full concurrency
        from presto_tpu._devtools import lockcheck
        assert lockcheck.ENABLED
        assert lockcheck.GRAPH.check() == [], lockcheck.GRAPH.check()
    finally:
        srv.stop()


def test_serving_suite_lock_graph_clean():
    """End-of-suite assertion (ISSUE 15): the serving plane's locks —
    plan cache, producer pool, query page/state, resource-group
    manager/memory, group registry — are `checked_lock`s, so every
    edge this module's admission/scheduling/batching stress recorded is
    in the process graph; it must hold no cycle, no jit dispatch under
    a lock, and no guarded-field violation. Defined last: pytest runs
    in definition order."""
    from presto_tpu._devtools import lockcheck
    assert lockcheck.ENABLED
    assert lockcheck.GRAPH.check() == [], lockcheck.GRAPH.check()

"""Dynamic filtering: build-side bounds prune the probe scan at runtime
(reference sql/DynamicFilters.java + dynamic filter collection; v319
collects build-side values and filters probe scans)."""
import re

import numpy as np
import pyarrow as pa
import pyarrow.orc as pa_orc
import pytest

# tier-1 budget: excluded from `pytest -m 'not slow'` — per-test cluster + scan pruning paths
# (see tools/check_tier1_time.py; ~25s)
pytestmark = pytest.mark.slow

from presto_tpu.connectors.orc import OrcConnector
from presto_tpu.connectors.spi import CatalogManager
from presto_tpu.exec.runner import LocalRunner


@pytest.fixture(scope="module")
def runner(tmp_path_factory):
    root = tmp_path_factory.mktemp("orcdf")
    n = 400_000
    (root / "seq").mkdir()
    pa_orc.write_table(
        pa.table({"k": pa.array(np.arange(n, dtype=np.int64)),
                  "v": pa.array(np.arange(n, dtype=np.int64) * 3)}),
        str(root / "seq" / "a.orc"),
        compression="uncompressed", stripe_size=256 * 1024)
    from presto_tpu.connectors.memory import MemoryConnector
    catalogs = CatalogManager()
    catalogs.register("hive", OrcConnector(str(root)))
    catalogs.register("memory", MemoryConnector())
    r = LocalRunner(catalogs=catalogs, catalog="hive")
    r.execute("create table memory.default.keys as "
              "select cast(100 as bigint) k union all select 150 "
              "union all select 199")
    return r


def _scan_rows(runner, sql: str) -> int:
    # scan_cache off for the measurement: a warm cache (left by an
    # earlier test in module order) serves the full decoded scan under
    # the static-pushdown fallback key — correct results, but the
    # EXPLAIN ANALYZE row count would show the replayed superset
    # instead of what dynamic-filter stripe pruning actually decodes
    ana = runner.execute(f"explain analyze {sql}",
                         properties={"scan_cache": False})
    text = "\n".join(row[0] for row in ana.rows)
    m = re.search(r"TableScan\[hive.*?(\d[\d,]*) rows", text)
    assert m, text
    return int(m.group(1).replace(",", ""))


JOIN = ("select count(*) c, sum(s.v) sv from seq s, "
        "memory.default.keys t where s.k = t.k")


def test_results_match_with_and_without(runner):
    runner.session.properties["enable_dynamic_filtering"] = False
    want = runner.execute(JOIN).rows
    runner.session.properties["enable_dynamic_filtering"] = True
    got = runner.execute(JOIN).rows
    assert got == want == [(3, (100 + 150 + 199) * 3)]


def test_probe_scan_pruned(runner):
    """The build side covers keys 100..199, so only the first ORC stripe
    survives stats pruning — the probe scan reads far fewer rows."""
    runner.session.properties["enable_dynamic_filtering"] = True
    pruned = _scan_rows(runner, JOIN)
    runner.session.properties["enable_dynamic_filtering"] = False
    full = _scan_rows(runner, JOIN)
    assert full == 400_000
    assert pruned < full / 2, (pruned, full)


def test_shared_probe_subtree_not_pruned(runner):
    """A scan replayed for two consumers must not inherit one join's
    bounds; results stay correct."""
    runner.session.properties["enable_dynamic_filtering"] = True
    res = runner.execute(
        "select (select count(*) from seq), count(*) from seq s, "
        "memory.default.keys t where s.k = t.k")
    assert res.rows == [(400_000, 3)]

"""EXPLAIN / EXPLAIN ANALYZE surface.

Reference parity: planprinter text plans + ExplainAnalyzeOperator runtime
stats (reference sql/planner/planprinter/PlanPrinter.java,
operator/ExplainAnalyzeOperator.java)."""
import re

import pytest

from presto_tpu.exec.runner import LocalRunner


@pytest.fixture(scope="module")
def runner():
    return LocalRunner(tpch_sf=0.01)


def test_explain_shows_plan(runner):
    res = runner.execute("explain select count(*) from nation")
    text = "\n".join(r[0] for r in res.rows)
    assert "TableScan[tpch.default.nation]" in text
    assert "Aggregate" in text
    assert "ms" not in text          # no runtime stats without ANALYZE


def test_explain_analyze_shows_stats(runner):
    res = runner.execute(
        "explain analyze select n_regionkey, count(*) from nation "
        "group by n_regionkey")
    text = "\n".join(r[0] for r in res.rows)
    # per-operator wall/self/rows annotations
    assert re.search(r"TableScan\[tpch.default.nation\].*"
                     r"\[self [\d,.]+ms, wall [\d,.]+ms, 25 rows", text)
    assert re.search(r"Aggregate.*5 rows", text)
    assert re.search(r"Total: [\d,]+ms \(planning [\d,]+ms\)", text)


def test_explain_analyze_join_rows(runner):
    res = runner.execute(
        "explain analyze select count(*) from nation, region "
        "where n_regionkey = r_regionkey")
    text = "\n".join(r[0] for r in res.rows)
    assert re.search(r"Join\[inner.*25 rows", text)

"""Fault-tolerant cluster execution (exec/cluster.py retry layer,
exec/failpoints.py harness, server/worker.py buffer/exchange failure
semantics).

Unit coverage of each recovery building block, plus targeted
integration over a small real-socket cluster: drain-aware scheduling,
query-deadline abort propagation (DELETE /v1/query frees the task
registry and leaves a FAILED history record), exchange failure
attribution, and scan-cache insert-on-abort safety. The end-to-end
recovery scenarios (task retry, worker death, speculative wins,
retry_policy=NONE fail-fast) live in tools/chaos_smoke.py, driven by
tests/test_chaos.py."""
import json
import threading
import time
import types as _pytypes
import urllib.error
import urllib.request

import pytest

from presto_tpu.exec.cluster import (
    ClusterRunner, QueryFailedError, _retry_policy, parse_duration_s,
)
from presto_tpu.exec.failpoints import (
    FAILPOINTS, FailpointError, FailpointRegistry,
)
from presto_tpu.obs.metrics import REGISTRY
from presto_tpu.server.worker import (
    ExchangeClient, ExchangeFailedError, OutputBuffer, WorkerServer,
)

SF = 0.01


@pytest.fixture(autouse=True)
def clean_failpoints():
    """The registry is process-wide: no rule may leak across tests."""
    FAILPOINTS.clear()
    yield
    FAILPOINTS.clear()


@pytest.fixture(scope="module")
def cluster():
    workers = [WorkerServer(tpch_sf=SF) for _ in range(2)]
    for w in workers:
        w.start()
    urls = [f"http://127.0.0.1:{w.port}" for w in workers]
    runner = ClusterRunner(urls, tpch_sf=SF, heartbeat=False)
    yield runner, workers
    for w in workers:
        try:
            w.stop()
        except Exception:
            pass


def _counter(name: str) -> float:
    return REGISTRY.counter(name).value


# -- failpoint harness -------------------------------------------------------

def test_failpoint_times_and_skip():
    fp = FailpointRegistry()
    fp.configure("a.b", times=2, skip=1, message="boom")
    fired = []
    for i in range(5):
        try:
            fp.hit("a.b")
            fired.append(False)
        except FailpointError:
            fired.append(True)
    # hits 2 and 3 trigger: skip the first, then times=2, then disarmed
    assert fired == [False, True, True, False, False]
    assert fp.hits("a.b") == 5 and fp.triggers("a.b") == 2


def test_failpoint_unlimited_times():
    fp = FailpointRegistry()
    fp.configure("a.b", times=None)
    for _ in range(3):
        with pytest.raises(FailpointError):
            fp.hit("a.b")


def test_failpoint_match_targets_key():
    fp = FailpointRegistry()
    fp.configure("site", match=r"\.0\.0@", times=None)
    fp.hit("site", key="cq_1.0.1@worker-a")      # no match, no trigger
    with pytest.raises(FailpointError):
        fp.hit("site", key="cq_1.0.0@worker-a")
    # non-matching keys don't consume the hit counter
    assert fp.triggers("site") == 1 and fp.hits("site") == 1


def test_failpoint_probability_replayable():
    """Same seed + same hit sequence = bit-identical trigger sequence
    (the determinism contract that makes chaos runs replayable)."""
    def run():
        fp = FailpointRegistry()
        fp.configure("p", probability=0.3, seed=42, times=None)
        out = []
        for _ in range(64):
            try:
                fp.hit("p")
                out.append(0)
            except FailpointError:
                out.append(1)
        return out
    a, b = run(), run()
    assert a == b and 0 < sum(a) < 64


def test_failpoint_sleep_and_callback():
    fp = FailpointRegistry()
    fp.configure("s", action="sleep", sleep_s=0.05)
    t0 = time.monotonic()
    fp.hit("s")
    assert time.monotonic() - t0 >= 0.05
    seen = {}
    fp.configure("cb", action="callback",
                 callback=lambda key, **ctx: seen.update(key=key, **ctx))
    fp.hit("cb", key="k1", task_id="t9")
    assert seen == {"key": "k1", "task_id": "t9"}
    with pytest.raises(ValueError):
        fp.configure("cb2", action="callback")     # callback= required


def test_failpoint_spec_grammar():
    fp = FailpointRegistry()
    fp.configure_from_spec(
        "w.run=error:boom,times:2,skip:1;"
        "x.pull=sleep:0.01,prob:0.5,seed:7,match:a$;"
        "y.z=error,times:inf")
    fp.hit("w.run")                               # skipped
    with pytest.raises(FailpointError, match="boom"):
        fp.hit("w.run")
    for _ in range(3):                            # times:inf
        with pytest.raises(FailpointError):
            fp.hit("y.z")
    for bad in ("noequals", "a.b=callback", "a.b=explode",
                "a.b=error,frequency:2"):
        with pytest.raises(ValueError):
            FailpointRegistry().configure_from_spec(bad)


# -- session property parsing ------------------------------------------------

def test_parse_duration():
    assert parse_duration_s(None) is None and parse_duration_s("") is None
    assert parse_duration_s("500ms") == pytest.approx(0.5)
    assert parse_duration_s("30s") == 30.0
    assert parse_duration_s("5m") == 300.0
    assert parse_duration_s("2h") == 7200.0
    assert parse_duration_s("12.5") == 12.5 and parse_duration_s(3) == 3.0
    with pytest.raises(ValueError):
        parse_duration_s("fast")


def test_retry_policy_validation():
    ses = _pytypes.SimpleNamespace(properties={})
    assert _retry_policy(ses) == "TASK"            # default
    for p in ("task", "QUERY", "none"):
        ses.properties["retry_policy"] = p
        assert _retry_policy(ses) == p.upper()
    ses.properties["retry_policy"] = "ALWAYS"
    with pytest.raises(ValueError, match="retry_policy"):
        _retry_policy(ses)


def test_bad_session_value_leaves_no_phantom_query(cluster):
    """A bad retry_policy/query_max_run_time now fails at SET SESSION
    time (config.SESSION_PROPERTIES validation) — and even a bad value
    injected directly into the session dict still raises before the
    RUNNING log entry is appended, so there is never a forever-RUNNING
    phantom row in system.runtime.queries."""
    runner, _ = cluster
    for prop, bad in (("retry_policy", "ALWAYS"),
                      ("query_max_run_time", "soon")):
        # the SQL path rejects the value up front...
        with pytest.raises(ValueError):
            runner.execute(f"set session {prop} = '{bad}'")
        assert prop not in runner.session.properties
        # ...and the belt-and-braces execution-time check still guards
        # values that bypass SET SESSION (direct dict writes)
        runner.session.properties[prop] = bad
        try:
            with pytest.raises(ValueError):
                runner.execute("select count(*) from nation")
        finally:
            runner.session.properties.pop(prop, None)
    assert not [e for e in runner.local.query_log
                if e.state == "RUNNING"]


# -- output buffer retry semantics ------------------------------------------

def test_output_buffer_retain_rereads_from_zero():
    """retain=True (retry_policy=TASK): acked pages survive so a
    re-created consumer attempt replays the buffer from token 0."""
    buf = OutputBuffer(1, retain=True)
    buf.add(0, b"p0")
    buf.add(0, b"p1")
    buf.finish()
    pages, token, _ = buf.get(0, 0, 0.1)
    assert pages == [b"p0", b"p1"]
    # ack everything, then a NEW attempt re-reads the full stream
    again, _, complete = buf.get(0, token, 0.1)
    assert complete and again == []
    replay, token, _ = buf.get(0, 0, 0.1)
    assert replay == [b"p0", b"p1"]
    assert buf.get(0, token, 0.1)[2] is True


def test_output_buffer_default_drops_acked():
    buf = OutputBuffer(1)
    buf.add(0, b"p0")
    pages, token, _ = buf.get(0, 0, 0.1)
    assert pages == [b"p0"]
    buf.get(0, token, 0.0)                        # ack drops it
    pages, _, _ = buf.get(0, 0, 0.0)
    assert pages == []


def test_output_buffer_first_failure_wins():
    """An abort racing (or following) the real error must not clobber
    the diagnostic a late poller needs."""
    buf = OutputBuffer(1)
    buf.fail("ValueError: the real cause")
    buf.fail("task aborted")
    with pytest.raises(RuntimeError, match="the real cause"):
        buf.get(0, 0, 0.1)


# -- exchange failure attribution -------------------------------------------

def test_exchange_transport_failure_names_upstream():
    """A dead upstream worker surfaces ExchangeFailedError with the
    source task id after fail_fast_s — not a 300s generic timeout."""
    client = ExchangeClient(
        ["http://127.0.0.1:9/v1/task/cq_9.1.0"], 0, fail_fast_s=0.4)
    t0 = time.monotonic()
    with pytest.raises(ExchangeFailedError) as ei:
        list(client.batches())
    assert time.monotonic() - t0 < 10.0
    assert ei.value.task_id == "cq_9.1.0"
    assert "cq_9.1.0" in str(ei.value)


def test_exchange_http_error_names_upstream(cluster):
    """An upstream that ANSWERS with an error (task gone) fails the
    pull immediately with the upstream task id embedded."""
    _, workers = cluster
    url = f"http://127.0.0.1:{workers[0].port}/v1/task/cq_9.2.0"
    client = ExchangeClient([url], 0)
    with pytest.raises(ExchangeFailedError) as ei:
        list(client.batches())
    assert ei.value.task_id == "cq_9.2.0"
    assert "HTTP 404" in str(ei.value)


def test_exchange_pull_failpoint():
    FAILPOINTS.configure("exchange.pull", message="chaos drop")
    client = ExchangeClient(
        ["http://127.0.0.1:9/v1/task/cq_9.3.0"], 0, fail_fast_s=30.0)
    with pytest.raises(ExchangeFailedError, match="chaos drop"):
        list(client.batches())


def test_exchange_wait_is_cancellable():
    """A DELETE-aborted task blocked on its upstreams must wake on the
    cancel event, not after the transport window."""
    from presto_tpu.errors import QueryCancelledError
    cancel = threading.Event()
    client = ExchangeClient(
        ["http://127.0.0.1:9/v1/task/cq_9.4.0"], 0,
        fail_fast_s=60.0, cancel_event=cancel)
    threading.Timer(0.3, cancel.set).start()
    t0 = time.monotonic()
    with pytest.raises(QueryCancelledError):
        list(client.batches())
    assert time.monotonic() - t0 < 5.0
    client.stop.set()


# -- drain-aware scheduling --------------------------------------------------

def test_discovery_tracks_announced_state():
    from presto_tpu.exec.discovery import DiscoveryNodeManager
    dm = DiscoveryNodeManager()
    dm.announce("n1", "http://a:1")
    dm.announce("n2", "http://b:2", state="SHUTTING_DOWN")
    assert dm.states() == {"http://a:1": "ACTIVE",
                           "http://b:2": "SHUTTING_DOWN"}
    # draining nodes still announce (their buffers stay reachable)
    assert dm.active_urls() == ["http://a:1", "http://b:2"]
    assert [n["state"] for n in dm.nodes()] == ["ACTIVE",
                                                "SHUTTING_DOWN"]


def test_draining_worker_gets_no_new_tasks(cluster):
    """A SHUTTING_DOWN node leaves the schedulable set (reference
    NodeScheduler + GracefulShutdownHandler) but queries still run on
    the survivors."""
    runner, workers = cluster
    w_drain = workers[1]
    url_drain = f"http://127.0.0.1:{w_drain.port}"
    drained0 = _counter("node_drained_total")
    w_drain.shutting_down = True       # /v1/info now reports the drain
    try:
        assert runner._schedulable_workers() == \
            [f"http://127.0.0.1:{workers[0].port}"]
        assert _counter("node_drained_total") == drained0 + 1
        before = len(w_drain.tasks) + len(w_drain.done)
        res = runner.execute(
            "select count(*), sum(n_regionkey) from nation")
        assert res.rows == [(25, 50)]
        assert len(w_drain.tasks) + len(w_drain.done) == before
    finally:
        w_drain.shutting_down = False
    assert url_drain in runner._schedulable_workers()


def test_all_draining_fails_fast(cluster):
    runner, workers = cluster
    for w in workers:
        w.shutting_down = True
    try:
        with pytest.raises(QueryFailedError, match="draining"):
            runner.execute("select count(*) from region")
    finally:
        for w in workers:
            w.shutting_down = False


# -- abort propagation (DELETE /v1/query) ------------------------------------

def _put_sleeping_task(worker, task_id: str, sleep_s: float) -> str:
    """PUT a real single-fragment task that stalls in a failpoint."""
    from presto_tpu.planner.codec import encode
    from presto_tpu.exec.runner import LocalRunner
    FAILPOINTS.configure("worker.task_run", action="sleep",
                         sleep_s=sleep_s, match=task_id.split(".")[0])
    lr = LocalRunner(tpch_sf=SF)
    plan = lr.plan("select count(*) from nation")
    url = f"http://127.0.0.1:{worker.port}"
    doc = {"fragment": encode(plan.root),
           "output": {"kind": "single", "n_buffers": 1},
           "splits": [], "sources": {}}
    req = urllib.request.Request(f"{url}/v1/task/{task_id}",
                                 method="PUT",
                                 data=json.dumps(doc).encode())
    with urllib.request.urlopen(req, timeout=10):
        pass
    return url


def test_query_delete_frees_tasks_and_tombstones(cluster):
    """DELETE /v1/query/{id} aborts every task of the query, frees the
    task-registry entries, and late status/result polls still see the
    terminal verdict (persisted failure state, not a 404/empty page)."""
    _, workers = cluster
    qid, tid = "qabort", "qabort.0.0"
    url = _put_sleeping_task(workers[0], tid, sleep_s=8.0)
    req = urllib.request.Request(f"{url}/v1/query/{qid}",
                                 method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert json.loads(resp.read())["aborted_tasks"] == 1
    assert tid not in workers[0].tasks            # registry freed
    with urllib.request.urlopen(f"{url}/v1/task/{tid}",
                                timeout=5) as resp:
        tomb = json.loads(resp.read())
    assert tomb["state"] == "ABORTED"
    # late results poll: the real verdict, not an empty page
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{url}/v1/task/{tid}/results/0/0",
                               timeout=5)
    assert ei.value.code == 500
    assert "aborted" in json.loads(ei.value.read())["error"]


def test_deadline_aborts_query_and_records_history(cluster):
    """query_max_run_time: the coordinator aborts the whole query
    (DELETE /v1/query on every worker), the error names the deadline,
    workers keep no registry entries, and the history record is FAILED
    with the retry column present."""
    runner, workers = cluster
    FAILPOINTS.configure("worker.task_run", action="sleep",
                         sleep_s=6.0, times=None)
    runner.session.properties["query_max_run_time"] = "300ms"
    try:
        with pytest.raises(QueryFailedError,
                           match="query_max_run_time"):
            runner.execute("select count(*) from orders")
    finally:
        del runner.session.properties["query_max_run_time"]
        FAILPOINTS.clear()
    for w in workers:
        assert not any(t.state in ("PLANNED", "RUNNING")
                       and not t._abort.is_set()
                       for t in w.tasks.values())
    res = runner.local.execute(
        "select state, error, retries from "
        "system.runtime.completed_queries where mode = 'cluster' "
        "order by create_time")
    assert res.rows, "no cluster history record"
    state, error, retries = res.rows[-1]
    assert state == "FAILED" and "query_max_run_time" in error
    assert retries == 0
    # let the injected sleeps drain before the next test queries
    deadline = time.time() + 12
    while time.time() < deadline and any(
            t.state in ("PLANNED", "RUNNING")
            for w in workers for t in list(w.tasks.values())):
        time.sleep(0.2)


# -- explain analyze surface -------------------------------------------------

def test_format_retry_summary():
    from presto_tpu.planner.printer import format_retry_summary
    assert format_retry_summary({"retries": 0, "events": []}) == ""
    text = format_retry_summary({
        "policy": "TASK", "retries": 1, "speculative_launched": 1,
        "speculative_won": 1,
        "events": [
            {"kind": "task_retry", "task": "cq.1.0.a1", "attempt": 1,
             "from": "http://a", "to": "http://b", "reason": "boom"},
            {"kind": "speculative_launched", "task": "cq.2.0.a1",
             "straggler": "cq.2.0", "worker": "http://b"},
            {"kind": "speculative_won", "task": "cq.2.0.a1",
             "worker": "http://b"},
        ]})
    assert "1 task retry" in text and "1 speculative launched" in text
    assert "cq.1.0.a1" in text and "straggler cq.2.0" in text


def test_cluster_explain_analyze_includes_retries(cluster):
    runner, _ = cluster
    FAILPOINTS.configure("worker.task_run", action="error",
                         message="explain chaos", times=1)
    res = runner.execute("explain analyze select count(*) from nation")
    text = "\n".join(r[0] for r in res.rows)
    assert "Cluster:" in text
    assert "Fault tolerance [TASK]: 1 task retry" in text
    # the per-event detail line names the replaced attempt
    assert "\n  retry cq_" in text


# -- scan-cache safety under retries ----------------------------------------

def test_scancache_no_insert_on_aborted_scan():
    """A scan that dies mid-decode must never put() a partial column
    set: the next (clean) run must MISS and decode fresh, not hit a
    truncated resident entry."""
    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.exec.scancache import CACHE
    CACHE.clear()
    lr = LocalRunner(tpch_sf=SF)
    # serial scan (no background prefetch): the injected failure kills
    # the FIRST split before anything can complete, so a moved insert
    # counter can only mean a partial entry leaked into the cache
    lr.session.properties["scan_prefetch"] = False
    q = ("select l_returnflag, count(*) c from lineitem "
         "group by 1 order by 1")
    inserts0 = _counter("scan_cache_insert_total")
    FAILPOINTS.configure("scan.decode", message="chaos mid-decode",
                         match=r"\.lineitem\.")
    with pytest.raises(Exception, match="chaos mid-decode"):
        lr.execute(q)
    assert _counter("scan_cache_insert_total") == inserts0, \
        "aborted scan inserted a partial column set"
    FAILPOINTS.clear()
    hits0 = _counter("scan_cache_hit_total")
    want = lr.execute(q, properties={"scan_cache": False}).rows
    assert _counter("scan_cache_hit_total") == hits0
    got = lr.execute(q).rows                      # clean run: cold miss
    assert got == want
    assert _counter("scan_cache_insert_total") > inserts0
    assert lr.execute(q).rows == want             # warm hit parity
    assert _counter("scan_cache_hit_total") > hits0
    CACHE.clear()


# -- coordinator drain -------------------------------------------------------

def test_lifecycle_put_requires_auth():
    """PUT /v1/info/state needs the same credentials as statements: an
    unauthenticated peer must not be able to drain the server."""
    import base64
    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.server.protocol import PrestoTpuServer
    from presto_tpu.server.security import PasswordAuthenticator
    srv = PrestoTpuServer(
        runner=LocalRunner(tpch_sf=0.001),
        authenticator=PasswordAuthenticator({"alice": "pw"}))
    srv.start()
    url = f"http://127.0.0.1:{srv.port}/v1/info/state"
    body = json.dumps("SHUTTING_DOWN").encode()
    try:
        req = urllib.request.Request(url, method="PUT", data=body)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 401
        assert srv.shutting_down is False
        cred = base64.b64encode(b"alice:pw").decode()
        req = urllib.request.Request(
            url, method="PUT", data=body,
            headers={"Authorization": f"Basic {cred}"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert json.loads(resp.read())["state"] == "SHUTTING_DOWN"
        assert srv.shutting_down is True
    finally:
        try:
            srv.stop()
        except Exception:
            pass


def test_coordinator_drain_refuses_new_statements():
    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.server import PrestoTpuServer
    srv = PrestoTpuServer(LocalRunner(tpch_sf=0.001))
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(f"{base}/v1/info",
                                    timeout=5) as resp:
            info = json.loads(resp.read())
        assert info["state"] == "ACTIVE"
        srv.shutting_down = True                  # drain window open
        with urllib.request.urlopen(f"{base}/v1/info",
                                    timeout=5) as resp:
            assert json.loads(resp.read())["state"] == "SHUTTING_DOWN"
        req = urllib.request.Request(
            f"{base}/v1/statement", method="POST",
            data=b"select 1", headers={"X-Presto-User": "t"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 503
        # the PUT lifecycle endpoint drains and then stops the server
        req = urllib.request.Request(
            f"{base}/v1/info/state", method="PUT",
            data=json.dumps("SHUTTING_DOWN").encode())
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert json.loads(resp.read())["state"] == "SHUTTING_DOWN"
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                urllib.request.urlopen(f"{base}/v1/info", timeout=2)
                time.sleep(0.1)
            except Exception:
                break
        else:
            pytest.fail("coordinator did not stop after drain")
    finally:
        try:
            srv.stop()
        except Exception:
            pass

"""Spooled exchange (exec/spool.py) building blocks in isolation:
page-addressed store round-trips, checksum corruption detection,
disk accounting + per-query GC, failpoint sites, spool-backed
OutputBuffer replay, the ExchangeClient spool fallback, the worker
drain fast-exit, and the jittered retry backoff (ISSUE 10)."""
import json
import os
import threading
import time
import urllib.request

import pytest

from presto_tpu.exec.failpoints import FAILPOINTS
from presto_tpu.exec.spool import (
    LocalDiskSpoolStore, SpoolCorruptionError, SpoolFullError,
)

SF = 0.001


@pytest.fixture(autouse=True)
def clean_failpoints():
    FAILPOINTS.clear()
    yield
    FAILPOINTS.clear()


@pytest.fixture()
def store(tmp_path):
    return LocalDiskSpoolStore(directory=str(tmp_path))


def _fill(store, qid="q1", tid="q1.0.0", n_buffers=2):
    w = store.writer(qid, tid, n_buffers)
    w.append(0, 0, b"page-zero")
    w.append(0, 1, b"page-one")
    w.append(1, 0, b"other-buffer")
    w.finish([2, 1])
    return w


# -- store round-trips --------------------------------------------------------

def test_write_read_roundtrip(store):
    _fill(store)
    assert store.finished_tokens("q1", "q1.0.0") == [2, 1]
    pages, nxt = store.read_pages("q1", "q1.0.0", 0, 0)
    assert pages == [b"page-zero", b"page-one"] and nxt == 2
    # resume mid-stream: token addressing, not offsets
    pages, nxt = store.read_pages("q1", "q1.0.0", 0, 1)
    assert pages == [b"page-one"] and nxt == 2
    pages, nxt = store.read_pages("q1", "q1.0.0", 1, 0)
    assert pages == [b"other-buffer"] and nxt == 1


def test_unfinished_task_has_no_marker(store):
    w = store.writer("q1", "q1.0.0", 1)
    w.append(0, 0, b"partial")
    assert store.finished_tokens("q1", "q1.0.0") is None
    # pages written so far are still readable (live fallback path)
    pages, nxt = store.read_pages("q1", "q1.0.0", 0, 0)
    assert pages == [b"partial"] and nxt == 1
    w.abandon()
    assert store.read_pages("q1", "q1.0.0", 0, 0)[0] == []


def test_partial_trailing_frame_ignored(store, tmp_path):
    _fill(store, n_buffers=1)
    path = store._page_path("q1", "q1.0.0", 0)
    with open(path, "ab") as f:
        f.write(b"\x05\x00\x00\x00")      # torn frame header
    pages, nxt = store.read_pages("q1", "q1.0.0", 0, 0)
    assert len(pages) == 2 and nxt == 2   # the torn tail is invisible


def test_checksum_detects_on_disk_corruption(store):
    from presto_tpu.obs.metrics import REGISTRY
    _fill(store, n_buffers=1)
    path = store._page_path("q1", "q1.0.0", 0)
    data = bytearray(open(path, "rb").read())
    data[12] ^= 0xFF                      # flip a payload byte
    with open(path, "wb") as f:
        f.write(bytes(data))
    before = REGISTRY.counter("spool_corruption_total").value
    with pytest.raises(SpoolCorruptionError):
        store.read_pages("q1", "q1.0.0", 0, 0)
    assert REGISTRY.counter("spool_corruption_total").value \
        == before + 1


def test_release_query_gc_and_accounting(store):
    _fill(store, qid="qa", tid="qa.0.0")
    _fill(store, qid="qb", tid="qb.0.0")
    assert store.query_dirs() == ["qa", "qb"]
    used = store.usage()["bytes"]
    assert used > 0
    freed = store.release_query("qa")
    assert freed > 0
    assert store.query_dirs() == ["qb"]
    assert store.usage()["bytes"] == used - freed
    # idempotent: coordinator AND workers may each release
    assert store.release_query("qa") == 0
    store.release_query("qb")
    assert store.query_dirs() == [] and store.usage()["bytes"] == 0


def test_max_bytes_refuses_writes(tmp_path):
    small = LocalDiskSpoolStore(directory=str(tmp_path), max_bytes=64)
    w = small.writer("q1", "q1.0.0", 1)
    with pytest.raises(SpoolFullError):
        w.append(0, 0, b"x" * 128)
    # released space becomes writable again
    small.release_query("q1")
    big = LocalDiskSpoolStore(directory=str(tmp_path),
                              max_bytes=1 << 20)
    big.writer("q2", "q2.0.0", 1).append(0, 0, b"x" * 128)


def test_failpoint_spool_write_fails_append(store):
    FAILPOINTS.configure("spool.write", action="error",
                         message="chaos: spool write")
    w = store.writer("q1", "q1.0.0", 1)
    with pytest.raises(Exception, match="chaos: spool write"):
        w.append(0, 0, b"page")


def test_failpoint_spool_corrupt_plants_detectable_corruption(store):
    FAILPOINTS.configure("spool.corrupt", action="error", times=1)
    w = store.writer("q1", "q1.0.0", 1)
    w.append(0, 0, b"page-zero")          # corrupted on disk
    w.append(0, 1, b"page-one")           # clean (times=1)
    w.finish([2])
    with pytest.raises(SpoolCorruptionError):
        store.read_pages("q1", "q1.0.0", 0, 0)
    # later tokens remain readable
    pages, _ = store.read_pages("q1", "q1.0.0", 0, 1)
    assert pages == [b"page-one"]


# -- OutputBuffer spool replay ------------------------------------------------

def test_output_buffer_replays_acked_pages_from_spool(store):
    from presto_tpu.server.worker import OutputBuffer
    w = store.writer("q1", "q1.1.0", 1)
    buf = OutputBuffer(1, spool=w)
    buf.add(0, b"p0")
    buf.add(0, b"p1")
    # first consumer generation reads + acks everything
    pages, nxt, complete = buf.get(0, 0, 0.1)
    assert pages == [b"p0", b"p1"] and nxt == 2
    pages, nxt, complete = buf.get(0, 2, 0.1)   # ack drops memory
    assert pages == [] and not complete
    assert all(not q for q in buf.pages)        # RAM is bounded
    # a re-created consumer re-reads from token 0: spool replay
    pages, nxt, complete = buf.get(0, 0, 0.1)
    assert pages == [b"p0", b"p1"] and nxt == 2
    buf.finish()
    assert buf.get(0, 2, 0.1)[2] is True


def test_output_buffer_drained_semantics(store):
    from presto_tpu.server.worker import OutputBuffer
    spooled = OutputBuffer(1, spool=store.writer("q1", "q1.1.0", 1))
    spooled.add(0, b"p0")
    assert not spooled.drained()          # still running
    spooled.finish()
    assert spooled.drained()              # unread pages live in spool
    retained = OutputBuffer(1, retain=True)
    retained.add(0, b"p0")
    retained.finish()
    assert not retained.drained()         # only THIS process can serve


# -- ExchangeClient fallback --------------------------------------------------

def test_exchange_client_falls_back_to_spool(store, monkeypatch):
    """A consumer whose upstream worker is GONE drains the committed
    attempt from the spool — no retry window, no upstream re-run."""
    import presto_tpu.exec.spool as spool_mod
    from presto_tpu.batch import Batch, Schema
    from presto_tpu import types as T
    from presto_tpu.exec.pages import serialize_page
    from presto_tpu.obs.metrics import REGISTRY
    from presto_tpu.server.worker import ExchangeClient
    monkeypatch.setattr(spool_mod, "SPOOL", store)
    schema = Schema([("x", T.BIGINT)])
    import numpy as np
    batch = Batch.from_arrays(schema, [np.arange(4, dtype=np.int64)],
                              [np.ones(4, dtype=bool)], [None],
                              num_rows=4)
    w = store.writer("qx", "qx.0.0", 1)
    w.append(0, 0, serialize_page(batch))
    w.finish([1])
    before = REGISTRY.counter("exchange_spool_fallback_total").value
    # port 1 refuses instantly: first transport error -> spool drain
    client = ExchangeClient(["http://127.0.0.1:1/v1/task/qx.0.0"], 0,
                            fail_fast_s=5.0)
    got = [b.to_pylist() for b in client.batches()]
    assert got == [[(0,), (1,), (2,), (3,)]]
    assert REGISTRY.counter("exchange_spool_fallback_total").value \
        == before + 1


def test_exchange_client_spool_corruption_names_upstream(store,
                                                         monkeypatch):
    import presto_tpu.exec.spool as spool_mod
    from presto_tpu.server.worker import (
        ExchangeClient, ExchangeFailedError,
    )
    monkeypatch.setattr(spool_mod, "SPOOL", store)
    FAILPOINTS.configure("spool.corrupt", action="error", times=1)
    w = store.writer("qy", "qy.0.0", 1)
    w.append(0, 0, b"not-a-real-page")
    w.finish([1])
    client = ExchangeClient(["http://127.0.0.1:1/v1/task/qy.0.0"], 0,
                            fail_fast_s=5.0)
    with pytest.raises(ExchangeFailedError) as ei:
        list(client.batches())
    assert ei.value.task_id == "qy.0.0"   # the retry layer's pointer
    assert "spool replay" in str(ei.value)


# -- worker drain fast-exit ---------------------------------------------------

def test_drain_exits_without_waiting_for_consumers(tmp_path,
                                                   monkeypatch):
    """A draining worker whose finished task holds consumed-but-
    unfinished output EXITS within its grace; the slow consumer then
    completes from the durable spool."""
    import presto_tpu.exec.spool as spool_mod
    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.planner.codec import encode
    from presto_tpu.server.worker import ExchangeClient, WorkerServer
    store = LocalDiskSpoolStore(directory=str(tmp_path))
    monkeypatch.setattr(spool_mod, "SPOOL", store)
    worker = WorkerServer(tpch_sf=SF, drain_grace_s=2.0)
    worker.start()
    try:
        lr = LocalRunner(tpch_sf=SF)
        plan = lr.plan("select n_regionkey, count(*) c from nation "
                       "group by n_regionkey")
        from presto_tpu.planner.plan import TableScanNode

        def walk(n):
            yield n
            for c in n.children:
                yield from walk(c)
        scan = next(n for n in walk(plan.root)
                    if isinstance(n, TableScanNode))
        conn = lr.session.catalogs.get("tpch")
        splits = conn.split_manager.splits(scan.table, 1)
        doc = {"fragment": encode(plan.root),
               "output": {"kind": "single", "n_buffers": 1,
                          "spool": True},
               "splits": [encode(s) for s in splits], "sources": {}}
        url = f"http://127.0.0.1:{worker.port}"
        req = urllib.request.Request(f"{url}/v1/task/qd.0.0",
                                     method="PUT",
                                     data=json.dumps(doc).encode())
        with urllib.request.urlopen(req, timeout=10):
            pass
        deadline = time.time() + 20
        while worker.tasks["qd.0.0"].state != "FINISHED":
            assert time.time() < deadline
            time.sleep(0.05)
        # NO consumer has pulled a single page; drain must still exit
        t0 = time.monotonic()
        worker.begin_shutdown()
        while time.monotonic() - t0 < 5.0:
            try:
                with urllib.request.urlopen(f"{url}/v1/info",
                                            timeout=1):
                    pass
            except Exception:
                break
            time.sleep(0.05)
        exit_s = time.monotonic() - t0
        assert exit_s < 4.0, \
            f"drained worker lingered {exit_s:.1f}s"
        # the consumer that shows up AFTER the exit drains the spool
        client = ExchangeClient([f"{url}/v1/task/qd.0.0"], 0,
                                fail_fast_s=5.0)
        rows = [r for b in client.batches() for r in b.to_pylist()]
        assert len(rows) == 5             # nation has 5 region keys
    finally:
        try:
            worker.stop()
        except Exception:
            pass


# -- retry backoff jitter -----------------------------------------------------

def test_backoff_jitter_spreads_retries():
    from presto_tpu.server.worker import jittered
    samples = {jittered(1.0) for _ in range(64)}
    assert all(0.5 <= s <= 1.5 for s in samples)
    assert len(samples) > 32              # not deterministic


# -- config wiring ------------------------------------------------------------

def test_node_config_spool_keys(tmp_path):
    from presto_tpu.config import NodeConfig, parse_properties
    etc = tmp_path / "config.properties"
    etc.write_text("spool.dir=/var/spool/presto\n"
                   "spool.max-bytes=1073741824\n")
    cfg = NodeConfig(parse_properties(str(etc)))
    assert cfg.spool_dir == "/var/spool/presto"
    assert cfg.spool_max_bytes == 1 << 30


def test_spool_store_configure(tmp_path):
    st = LocalDiskSpoolStore()
    st.configure(directory=str(tmp_path / "sp"), max_bytes=123)
    assert st.max_bytes == 123
    assert st.directory == str(tmp_path / "sp")


def test_spool_session_property_registered():
    from presto_tpu.config import validate_session_property
    assert validate_session_property("spool_exchange", "false") is False
    with pytest.raises(Exception):
        validate_session_property("spool_exchang", True)


# -- the object-store backend (ISSUE 20) --------------------------------------

@pytest.fixture()
def obj(tmp_path):
    from presto_tpu.exec.spool import ObjectSpoolStore
    return ObjectSpoolStore(directory=str(tmp_path / "bucket"))


def _counter(name: str) -> float:
    from presto_tpu.obs.metrics import REGISTRY
    return REGISTRY.counter(name).value


def test_object_store_roundtrip_and_manifest_commit(obj):
    """Pages upload as content-addressed blobs immediately; the
    attempt becomes visible to OTHER processes only when the manifest
    commits — but the owning process reads its uncommitted pages
    through the live index the whole time."""
    w = obj.writer("q1", "q1.0.0", 2)
    w.append(0, 0, b"page-zero")
    w.append(0, 1, b"page-one")
    w.append(1, 0, b"other-buffer")
    # uncommitted: no completion marker, live index still serves
    assert obj.finished_tokens("q1", "q1.0.0") is None
    pages, nxt = obj.read_pages("q1", "q1.0.0", 0, 0)
    assert pages == [b"page-zero", b"page-one"] and nxt == 2
    w.finish([2, 1])
    assert obj.finished_tokens("q1", "q1.0.0") == [2, 1]
    # token addressing, mid-stream resume
    pages, nxt = obj.read_pages("q1", "q1.0.0", 0, 1)
    assert pages == [b"page-one"] and nxt == 2
    pages, nxt = obj.read_pages("q1", "q1.0.0", 1, 0)
    assert pages == [b"other-buffer"] and nxt == 1


def test_object_store_survives_scale_to_zero(obj, tmp_path):
    """A committed attempt is readable by a PROCESS THAT NEVER WROTE
    IT (fresh store over the same bucket): every worker that produced
    the data can be gone — the scale-to-zero contract."""
    from presto_tpu.exec.spool import ObjectSpoolStore
    w = obj.writer("q1", "q1.0.0", 1)
    w.append(0, 0, b"durable-page")
    w.finish([1])
    fresh = ObjectSpoolStore(directory=str(tmp_path / "bucket"))
    assert fresh.finished_tokens("q1", "q1.0.0") == [1]
    pages, nxt = fresh.read_pages("q1", "q1.0.0", 0, 0)
    assert pages == [b"durable-page"] and nxt == 1


def test_object_store_content_addressed_dedup(obj):
    """Identical payloads (broadcast pages fanned to every consumer
    buffer) store ONE blob: dedup counted, bytes charged once."""
    dedup0 = _counter("spool_object_dedup_total")
    w = obj.writer("q1", "q1.0.0", 3)
    payload = b"broadcast-page" * 16
    for buf in range(3):
        w.append(buf, 0, payload)
    w.finish([1, 1, 1])
    assert _counter("spool_object_dedup_total") == dedup0 + 2
    blob_dir = os.path.join(obj.directory, "q1", "blobs")
    assert len(os.listdir(blob_dir)) == 1
    # accounting charges the blob once plus the manifest — never the
    # 3x a per-reference charge would cost
    assert obj.usage()["bytes"] < 3 * len(payload)
    for buf in range(3):
        pages, _ = obj.read_pages("q1", "q1.0.0", buf, 0)
        assert pages == [payload]


def test_object_torn_manifest_is_uncommitted_not_corrupt(obj):
    """A torn/garbled manifest upload is an UNCOMMITTED attempt —
    readers keep their normal retry semantics, nothing raises."""
    w = obj.writer("q1", "q1.0.0", 1)
    w.append(0, 0, b"page")
    path = obj._manifest_path("q1", "q1.0.0", create=True)
    with open(path, "wb") as f:
        f.write(b'{"tok')                 # torn mid-upload
    assert obj.finished_tokens("q1", "q1.0.0") is None
    with open(path, "wb") as f:
        f.write(b'{"no_tokens_key": 1}')  # garbled
    assert obj.finished_tokens("q1", "q1.0.0") is None


def test_object_corruption_is_attributed_to_the_page(obj):
    """The planted-corruption contract carries over from the disk
    backend: digest/crc are of the CLEAN page, so the read side names
    the exact page that failed its checksum."""
    FAILPOINTS.configure("spool.corrupt", action="error", times=1)
    w = obj.writer("q1", "q1.0.0", 1)
    w.append(0, 0, b"page-to-corrupt")
    w.finish([1])
    before = _counter("spool_corruption_total")
    with pytest.raises(SpoolCorruptionError, match=r"b0/t0"):
        obj.read_pages("q1", "q1.0.0", 0, 0)
    assert _counter("spool_corruption_total") == before + 1


def test_object_missing_blob_is_corruption(obj):
    """A manifest referencing a vanished blob is a damaged copy, not
    a retryable miss — the consumer must re-run the producer."""
    w = obj.writer("q1", "q1.0.0", 1)
    w.append(0, 0, b"page")
    w.finish([1])
    import hashlib
    digest = hashlib.sha256(b"page").hexdigest()[:32]
    os.unlink(obj._blob_path("q1", digest))
    with pytest.raises(SpoolCorruptionError, match="unreadable"):
        obj.read_pages("q1", "q1.0.0", 0, 0)


def test_object_release_query_gc_zero_orphans(obj):
    w = obj.writer("qa", "qa.0.0", 1)
    w.append(0, 0, b"qa-page")
    w.finish([1])
    w = obj.writer("qb", "qb.0.0", 1)
    w.append(0, 0, b"qb-page")
    w.finish([1])
    assert obj.query_dirs() == ["qa", "qb"]
    used = obj.usage()["bytes"]
    freed = obj.release_query("qa")
    assert freed > 0
    assert obj.query_dirs() == ["qb"]
    assert obj.usage()["bytes"] == used - freed
    assert obj.release_query("qa") == 0          # idempotent
    obj.release_query("qb")
    assert obj.query_dirs() == []
    assert obj.usage()["bytes"] == 0             # zero orphans


def test_object_abandon_respects_shared_blob_refcounts(obj):
    """Two attempts of one query share a dedup'd blob: abandoning one
    keeps the blob for the survivor; abandoning both deletes it."""
    shared = b"shared-payload" * 8
    w1 = obj.writer("q1", "q1.0.0", 1)
    w1.append(0, 0, shared)
    w2 = obj.writer("q1", "q1.0.1", 1)
    w2.append(0, 0, shared)
    import hashlib
    blob = obj._blob_path(
        "q1", hashlib.sha256(shared).hexdigest()[:32])
    w1.abandon()
    assert os.path.exists(blob)                  # w2 still references
    pages, _ = obj.read_pages("q1", "q1.0.1", 0, 0)
    assert pages == [shared]
    w2.abandon()
    assert not os.path.exists(blob)
    assert obj.usage()["bytes"] == 0


def test_object_max_bytes_refuses_puts(tmp_path):
    from presto_tpu.exec.spool import ObjectSpoolStore
    small = ObjectSpoolStore(directory=str(tmp_path / "b"),
                             max_bytes=64)
    w = small.writer("q1", "q1.0.0", 1)
    with pytest.raises(SpoolFullError):
        w.append(0, 0, b"x" * 128)
    small.release_query("q1")
    w = small.writer("q2", "q2.0.0", 1)
    w.append(0, 0, b"x" * 32)                    # freed space reusable


def test_object_failpoints_cover_both_directions(obj):
    from presto_tpu.exec.failpoints import FailpointError
    FAILPOINTS.configure("spool.object_put", action="error", times=1,
                         message="chaos: object put")
    w = obj.writer("q1", "q1.0.0", 1)
    with pytest.raises(FailpointError, match="object put"):
        w.append(0, 0, b"page")
    FAILPOINTS.clear()
    w.append(0, 0, b"page")
    w.finish([1])
    FAILPOINTS.configure("spool.object_get", action="error", times=1,
                         message="chaos: object get")
    with pytest.raises(FailpointError, match="object get"):
        obj.read_pages("q1", "q1.0.0", 0, 0)


def test_object_latency_bandwidth_model(tmp_path):
    """The modeled round trip really costs wall time (latency +
    size/bandwidth) and lands in the RTT histogram."""
    from presto_tpu.exec.spool import ObjectSpoolStore
    st = ObjectSpoolStore(directory=str(tmp_path / "b"),
                          get_latency_s=0.05,
                          bandwidth_bytes_per_s=1e6)
    w = st.writer("q1", "q1.0.0", 1)
    w.append(0, 0, b"x" * 100_000)
    w.finish([1])
    st._manifests.clear()                 # force the wire path
    t0 = time.monotonic()
    pages, _ = st.read_pages("q1", "q1.0.0", 0, 0)
    dt = time.monotonic() - t0
    assert pages == [b"x" * 100_000]
    # one manifest get + one 100kB blob get: >= 2x latency + 0.1s
    assert dt >= 0.15, f"modeled RTT not paid ({dt:.3f}s)"


def test_facade_backend_switch_and_config(tmp_path):
    from presto_tpu.exec.spool import SwitchableSpoolStore
    sw = SwitchableSpoolStore()
    sw.configure(directory=str(tmp_path / "local"),
                 object_dir=str(tmp_path / "bucket"),
                 backend="object", object_put_latency_s=0.0,
                 object_get_latency_s=0.0, object_bandwidth_mbps=0.0)
    assert sw.backend == "object"
    w = sw.writer("q1", "q1.0.0", 1)
    w.append(0, 0, b"page")
    w.finish([1])
    assert sw.finished_tokens("q1", "q1.0.0") == [1]
    assert (tmp_path / "bucket" / "q1").is_dir()
    with pytest.raises(ValueError, match="local or object"):
        sw.configure(backend="s3")
    sw.configure(backend="local")
    assert sw.backend == "local"


# -- speculative reads: replay vs live, both outcomes -------------------------

def _committed_page_store(store, qid, tid):
    import numpy as np
    from presto_tpu import types as T
    from presto_tpu.batch import Batch, Schema
    from presto_tpu.exec.pages import serialize_page
    schema = Schema([("x", T.BIGINT)])
    batch = Batch.from_arrays(schema, [np.arange(4, dtype=np.int64)],
                              [np.ones(4, dtype=bool)], [None],
                              num_rows=4)
    page = serialize_page(batch)
    w = store.writer(qid, tid, 1)
    w.append(0, 0, page)
    w.finish([1])
    return page


def test_speculative_replay_wins_when_live_stays_dead(tmp_path,
                                                      monkeypatch):
    """Producer truly gone (port refuses, the spec_live failpoint
    keeps the resumed pull dead): the object-store replay wins the
    race and the consumer gets every row."""
    import presto_tpu.exec.spool as spool_mod
    from presto_tpu.exec.spool import ObjectSpoolStore
    from presto_tpu.server.worker import ExchangeClient
    store = ObjectSpoolStore(directory=str(tmp_path / "bucket"))
    monkeypatch.setattr(spool_mod, "SPOOL", store)
    _committed_page_store(store, "qs", "qs.0.0")
    FAILPOINTS.configure("exchange.spec_live", action="error",
                         message="chaos: live pull down")
    reads0 = _counter("exchange_speculative_read_total")
    won0 = _counter("exchange_speculative_replay_won_total")
    client = ExchangeClient(["http://127.0.0.1:1/v1/task/qs.0.0"], 0,
                            fail_fast_s=5.0)
    got = [b.to_pylist() for b in client.batches()]
    assert got == [[(0,), (1,), (2,), (3,)]]
    assert _counter("exchange_speculative_read_total") == reads0 + 1
    assert _counter("exchange_speculative_replay_won_total") == won0 + 1


def test_speculative_live_wins_when_replay_is_slow(tmp_path,
                                                   monkeypatch):
    """Producer merely restarting: the live pull completes while the
    object-store replay is still paying its modeled round trips — the
    live arm wins and the replay is cancelled."""
    import http.server
    import presto_tpu.exec.spool as spool_mod
    from presto_tpu.exec.spool import ObjectSpoolStore
    from presto_tpu.server.worker import ExchangeClient, frame_pages
    store = ObjectSpoolStore(directory=str(tmp_path / "bucket"))
    monkeypatch.setattr(spool_mod, "SPOOL", store)
    page = _committed_page_store(store, "ql", "ql.0.0")

    class Upstream(http.server.BaseHTTPRequestHandler):
        def do_GET(self):              # noqa: N802 (stdlib casing)
            body = frame_pages([page])
            self.send_response(200)
            self.send_header("X-Buffer-Complete", "true")
            self.send_header("X-Next-Token", "1")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Upstream)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = (f"http://127.0.0.1:{httpd.server_address[1]}"
               "/v1/task/ql.0.0")
        FAILPOINTS.configure("exchange.spec_replay", action="sleep",
                             sleep_s=1.5)
        won0 = _counter("exchange_speculative_live_won_total")
        client = ExchangeClient([url], 0, fail_fast_s=5.0)
        assert client._race_spool(url, "ql.0.0", 0) is True
        assert _counter("exchange_speculative_live_won_total") \
            == won0 + 1
        assert client.queue.get_nowait() == page
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_speculative_disabled_session_property_drains_serially(
        tmp_path, monkeypatch):
    """``speculative_spool_reads=false`` falls back to the plain
    serial spool drain — no race, no speculative counters."""
    import presto_tpu.exec.spool as spool_mod
    from presto_tpu.exec.spool import ObjectSpoolStore
    from presto_tpu.server.worker import ExchangeClient
    store = ObjectSpoolStore(directory=str(tmp_path / "bucket"))
    monkeypatch.setattr(spool_mod, "SPOOL", store)
    page = _committed_page_store(store, "qn", "qn.0.0")
    reads0 = _counter("exchange_speculative_read_total")
    client = ExchangeClient(["http://127.0.0.1:1/v1/task/qn.0.0"], 0,
                            fail_fast_s=5.0, speculative=False)
    assert client._race_spool("http://127.0.0.1:1/v1/task/qn.0.0",
                              "qn.0.0", 0) is True
    assert _counter("exchange_speculative_read_total") == reads0
    assert client.queue.get_nowait() == page

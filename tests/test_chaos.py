"""Tier-1 chaos smoke: every cluster recovery + spooled-exchange path
under seeded failpoints, with row-exact parity against the fault-free
run.

Thin pytest wrapper over tools/chaos_smoke.py (also runnable directly
from the CLI) — an elastic discovery-fed in-process cluster survives
one injected task failure, one exchange drop, one 30s straggler
(speculative win), a worker death, a worker killed AFTER spooling its
output (replayed, NOT re-run), an on-disk spool-page corruption
(checksum -> retry from upstream), a fresh worker joining mid-query
(re-created tasks land on it), and a mid-read drain (the worker exits
within its grace; the consumer finishes from the spool);
``retry_policy=NONE`` still fails fast. Recovery is asserted
observable through ``system.runtime.metrics`` and the query-history
``retries`` column inside the tool itself, and the spool directory
must end the run with zero orphaned per-query directories."""
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))


def test_chaos_smoke():
    import chaos_smoke
    summary = chaos_smoke.run_chaos(sf=0.01)
    assert summary["ok"] is True
    scenarios = summary["scenarios"]
    assert scenarios["task_failure"]["task_retries"] >= 1
    assert scenarios["exchange_drop"]["task_retries"] >= 1
    assert scenarios["straggler"]["speculative_won"] >= 1
    assert scenarios["worker_death"]["task_retries"] >= 1
    assert "retry_none" in scenarios
    # spooled exchange + elastic membership (ISSUE 10)
    assert scenarios["spool_replay"]["spool_replays"] >= 1
    assert scenarios["spool_replay"]["spool_fallbacks"] >= 1
    assert scenarios["spool_corrupt"]["corruptions"] >= 1
    assert scenarios["spool_corrupt"]["task_retries"] >= 1
    assert scenarios["worker_join"]["landed_on_joiner"] >= 1
    assert scenarios["drain_exit"]["task_retries"] == 0
    assert scenarios["drain_exit"]["spool_fallbacks"] >= 1
    # the recovery-time summary feeds the ELASTIC_r* gate
    assert summary["elastic"]["value"] > 0


def test_fleet_coordinator_kill():
    """ISSUE 19: kill 1 of 3 coordinators mid-run over one shared
    worker pool — zero failed queries (FleetClient re-dispatches),
    survivors drop the dead coordinator's federated resource-group
    counts after the staleness grace, and the loss is observable as
    ``coordinator_lost_total`` through plain SQL."""
    import chaos_smoke
    summary = chaos_smoke.run_fleet_chaos(sf=0.01)
    assert summary["ok"] is True
    kill = summary["scenarios"]["coordinator_kill"]
    assert kill["failed"] == 0
    assert kill["queries"] >= 6
    assert kill["failovers"] >= 1
    assert kill["coordinator_lost_total"] >= 1.0
    assert kill["survivor_lost_view"] == ["coord-2"]


def test_elastic_regression_gate_smoke(capsys):
    """The elastic recovery-time gate's self-consistency: the pinned
    ELASTIC_r*.json passes against itself and a degraded (slower)
    copy fails — same contract as the BENCH/SERVING gates."""
    import check_bench_regression as gate
    rc = gate.main(["--kind", "elastic", "--smoke"])
    out = capsys.readouterr().out
    assert rc == 0, out
    import json
    verdict = json.loads(out)
    assert verdict["verdict"] == "pass"
    assert "elastic_recovery_ms" in verdict["metrics"]
    # ramp gate (ELASTIC_r02 on): the pinned round must carry a
    # schema-valid 1 -> N -> 1 load-ramp block, so a bad re-pin
    # cannot be committed
    assert verdict["ramp"]["ok"] is True
    assert verdict["ramp"]["blocks"] >= 1


def test_lock_discipline_clean_after_chaos():
    """After the full chaos run (retries, speculation, drain, worker
    death) the runtime lock-order validator saw every engine lock edge
    the cluster plane takes under stress: the acquisition graph must be
    acyclic and no dispatch may have run under a lock."""
    from presto_tpu._devtools import lockcheck
    assert lockcheck.ENABLED
    assert lockcheck.GRAPH.check() == [], lockcheck.GRAPH.check()


def test_chaos_spec_with_unknown_site_fails_fast():
    """A typo'd chaos spec must raise at parse time — a config that
    injects nothing would 'pass' every recovery scenario it was meant
    to exercise."""
    import pytest
    from presto_tpu.exec.failpoints import FAILPOINTS
    with pytest.raises(ValueError, match="unknown failpoint site"):
        FAILPOINTS.configure_from_spec("worker.task_ruin=error")

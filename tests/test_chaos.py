"""Tier-1 chaos smoke: every cluster recovery path under seeded
failpoints, with row-exact parity against the fault-free run.

Thin pytest wrapper over tools/chaos_smoke.py (also runnable directly
from the CLI) — a 3-worker in-process cluster survives one injected
task failure, one exchange drop, one 15s straggler (speculative win),
and one worker death; ``retry_policy=NONE`` still fails fast. Recovery
is asserted observable through ``system.runtime.metrics`` and the
query-history ``retries`` column inside the tool itself."""
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))


def test_chaos_smoke():
    import chaos_smoke
    summary = chaos_smoke.run_chaos(sf=0.01)
    assert summary["ok"] is True
    scenarios = summary["scenarios"]
    assert scenarios["task_failure"]["task_retries"] >= 1
    assert scenarios["exchange_drop"]["task_retries"] >= 1
    assert scenarios["straggler"]["speculative_won"] >= 1
    assert scenarios["worker_death"]["task_retries"] >= 1
    assert "retry_none" in scenarios


def test_lock_discipline_clean_after_chaos():
    """After the full chaos run (retries, speculation, drain, worker
    death) the runtime lock-order validator saw every engine lock edge
    the cluster plane takes under stress: the acquisition graph must be
    acyclic and no dispatch may have run under a lock."""
    from presto_tpu._devtools import lockcheck
    assert lockcheck.ENABLED
    assert lockcheck.GRAPH.check() == [], lockcheck.GRAPH.check()


def test_chaos_spec_with_unknown_site_fails_fast():
    """A typo'd chaos spec must raise at parse time — a config that
    injects nothing would 'pass' every recovery scenario it was meant
    to exercise."""
    import pytest
    from presto_tpu.exec.failpoints import FAILPOINTS
    with pytest.raises(ValueError, match="unknown failpoint site"):
        FAILPOINTS.configure_from_spec("worker.task_ruin=error")

"""Memory connector: CTAS / INSERT / DROP / scans (presto-memory role)."""
import pytest

from presto_tpu.exec.runner import LocalRunner


@pytest.fixture()
def runner():
    return LocalRunner(tpch_sf=0.002)


def test_ctas_and_query(runner):
    res = runner.execute(
        "create table memory.default.big_orders as "
        "select o_orderkey, o_totalprice from orders "
        "where o_totalprice > 200000")
    n = res.rows[0][0]
    assert n > 0
    res = runner.execute("select count(*) from memory.default.big_orders")
    assert res.rows[0][0] == n
    res = runner.execute(
        "select max(o_totalprice) from memory.default.big_orders")
    want = runner.execute(
        "select max(o_totalprice) from orders where o_totalprice > 200000")
    assert res.rows == want.rows


def test_insert_appends(runner):
    runner.execute("create table memory.default.t as select 1 as x")
    runner.execute("insert into memory.default.t select 2 as x")
    runner.execute("insert into memory.default.t select x + 10 from memory.default.t")
    res = runner.execute("select x from memory.default.t order by x")
    assert [r[0] for r in res.rows] == [1, 2, 11, 12]


def test_drop(runner):
    runner.execute("create table memory.default.d as select 1 as x")
    runner.execute("drop table memory.default.d")
    with pytest.raises(KeyError):
        runner.execute("select * from memory.default.d")
    runner.execute("drop table if exists memory.default.d")


def test_ctas_strings_and_joins(runner):
    runner.execute(
        "create table memory.default.nr as "
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey")
    res = runner.execute(
        "select r_name, count(*) c from memory.default.nr "
        "group by r_name order by r_name")
    assert len(res.rows) == 5
    assert sum(r[1] for r in res.rows) == 25


def test_show_tables_includes_memory(runner):
    runner.execute("create table memory.default.vis as select 1 as x")
    conn = runner.session.catalogs.get("memory")
    assert "vis" in conn.metadata.list_tables()

"""Plugin loading: an external module provides a connector and a scalar
function with ZERO engine edits (reference spi/Plugin.java:33-78 +
server/PluginManager.java:121 loadPlugins; the test plugin plays the
role of presto-example-http)."""
import os
import textwrap

import pytest


PLUGIN_SOURCE = '''
import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.batch import Batch, Schema
from presto_tpu.connectors.spi import (
    Connector, ConnectorMetadata, ConnectorSplitManager, PageSource,
    Split, TableHandle, TableStats,
)
from presto_tpu.expr.functions import Val
from presto_tpu.plugin import Plugin


class _Meta(ConnectorMetadata):
    def list_tables(self):
        return ["numbers"]

    def table_schema(self, table):
        return Schema([("n", T.BIGINT), ("squared", T.BIGINT)])

    def table_stats(self, table):
        return TableStats(row_count=100.0)


class _Splits(ConnectorSplitManager):
    def splits(self, table, desired=1):
        return [Split(table, (0, 100))]


class _PS(PageSource):
    def __init__(self, split, columns, rows):
        self.columns = columns
        self.rows = rows

    def batches(self):
        import numpy as np
        n = np.arange(1, self.rows + 1, dtype=np.int64)
        cols = {"n": (T.BIGINT, n), "squared": (T.BIGINT, n * n)}
        data = {c: cols[c][1].tolist() for c in self.columns}
        yield Batch.from_pydict(
            {c: (cols[c][0], data[c]) for c in self.columns})


class NumbersConnector(Connector):
    name = "numbers"

    def __init__(self):
        self._meta = _Meta()
        self._splits = _Splits()

    @property
    def metadata(self):
        return self._meta

    @property
    def split_manager(self):
        return self._splits

    def page_source(self, split, columns, pushdown=None,
                    rows_per_batch=1 << 17):
        return _PS(split, list(columns), split.info[1])


def _double_it(args, out_type):
    (a,) = args
    return Val(a.data * 2, a.valid, out_type)


class NumbersPlugin(Plugin):
    def get_connector_factories(self):
        return [("numbers", lambda props: NumbersConnector())]

    def get_scalar_functions(self):
        return [("double_it", _double_it, lambda arg_types: arg_types[0])]


PLUGIN = NumbersPlugin()
'''


@pytest.fixture()
def etc_with_plugin(tmp_path):
    plug_dir = tmp_path / "plugin"
    plug_dir.mkdir()
    (plug_dir / "numbers_plugin.py").write_text(PLUGIN_SOURCE)
    etc = tmp_path / "etc"
    (etc / "catalog").mkdir(parents=True)
    (etc / "config.properties").write_text(textwrap.dedent(f"""
        coordinator=true
        http-server.http.port=0
        plugin.dir={plug_dir}
    """))
    (etc / "catalog" / "nums.properties").write_text(
        "connector.name=numbers\n")
    (etc / "catalog" / "tiny.properties").write_text(
        "connector.name=tpch\ntpch.scale-factor=0.001\n")
    return str(etc)


def test_plugin_connector_and_function(etc_with_plugin):
    from presto_tpu.config import load_catalogs, load_node_config
    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.plugin import load_plugins_from_config

    cfg = load_node_config(etc_with_plugin)
    plugins = load_plugins_from_config(cfg.props)
    assert len(plugins) == 1
    catalogs = load_catalogs(etc_with_plugin)
    assert "nums" in catalogs.names()
    runner = LocalRunner(catalogs=catalogs, catalog="nums")
    rows = runner.execute(
        "select n, squared, double_it(n) d from nums.default.numbers "
        "where n <= 3 order by n").rows
    assert [tuple(int(v) for v in r) for r in rows] == [
        (1, 1, 2), (2, 4, 4), (3, 9, 6)]
    # the plugin function composes with builtins and the oracle engine
    total = runner.execute(
        "select sum(double_it(n)) from nums.default.numbers").rows
    assert int(total[0][0]) == 2 * 100 * 101 // 2


def test_plugin_via_server_boot(etc_with_plugin):
    from presto_tpu.config import server_from_etc

    srv, cfg = server_from_etc(etc_with_plugin)
    try:
        srv.start()
        from presto_tpu.client import StatementClient
        c = StatementClient(f"http://127.0.0.1:{srv.port}")
        res = c.execute("select double_it(squared) from "
                        "nums.default.numbers where n = 5")
        assert res.rows[0][0] == 50
    finally:
        srv.stop()


def test_plugin_module_without_contract_rejected(tmp_path):
    from presto_tpu.plugin import PluginManager
    mod = tmp_path / "empty_mod.py"
    mod.write_text("x = 1\n")
    import sys
    sys.path.insert(0, str(tmp_path))
    try:
        with pytest.raises(ValueError, match="exposes no plugin"):
            PluginManager().load_module("empty_mod")
    finally:
        sys.path.remove(str(tmp_path))

"""Window functions vs the SQLite oracle (sqlite3 >= 3.25 has windows)."""
import pytest

from test_sql import compare, oracle, runner  # noqa: F401 (fixtures)

WINDOW_QUERIES = [
    "select o_custkey, o_orderkey, row_number() over (partition by o_custkey order by o_orderkey) rn from orders order by o_custkey, o_orderkey limit 50",
    "select n_regionkey, n_name, rank() over (partition by n_regionkey order by n_name) r from nation order by n_regionkey, n_name",
    "select n_regionkey, n_name, dense_rank() over (order by n_regionkey) d from nation order by n_regionkey, n_name",
    "select o_orderkey, sum(o_totalprice) over (partition by o_custkey) s from orders order by o_orderkey limit 30",
    "select o_orderkey, sum(o_totalprice) over (partition by o_custkey order by o_orderkey) run from orders order by o_orderkey limit 30",
    "select o_orderkey, count(*) over (partition by o_orderstatus) c from orders order by o_orderkey limit 20",
    "select n_name, lag(n_name, 1) over (order by n_name) prev from nation order by n_name",
    "select n_name, lead(n_name, 2) over (partition by n_regionkey order by n_name) nx from nation order by n_regionkey, n_name",
    "select n_name, first_value(n_name) over (partition by n_regionkey order by n_name) f from nation order by n_regionkey, n_name",
    "select o_custkey, avg(o_totalprice) over (partition by o_custkey) a from orders order by o_custkey, o_orderkey limit 25",
    "select n_regionkey, n_name, percent_rank() over (partition by n_regionkey order by n_name) p from nation order by n_regionkey, n_name",
    "select n_regionkey, n_name, cume_dist() over (partition by n_regionkey order by n_name) p from nation order by n_regionkey, n_name",
    "select n_name, ntile(3) over (order by n_name) t from nation order by n_name",
    "select o_orderkey, min(o_totalprice) over (partition by o_orderstatus order by o_orderkey) m from orders order by o_orderkey limit 25",
    # ROWS vs RANGE frames: order key with ties (o_orderstatus) makes them differ
    "select o_orderkey, sum(o_totalprice) over (partition by o_custkey order by o_orderstatus rows between unbounded preceding and current row) s from orders order by o_orderkey limit 30",
    "select o_orderkey, count(*) over (partition by o_custkey order by o_orderstatus range between unbounded preceding and current row) c from orders order by o_orderkey limit 30",
    "select o_orderkey, last_value(o_orderstatus) over (partition by o_custkey order by o_totalprice rows unbounded preceding) lv from orders order by o_orderkey limit 30",
    # min/max over strings must compare lexicographically, not by code order
    "select n_regionkey, max(n_name) over (partition by n_regionkey order by n_nationkey) m from nation order by n_regionkey, n_nationkey",
    # explicit ROWS frame with no window ORDER BY still runs row-by-row
    # (which row gets which count is order-dependent, so sort by the count)
    "select count(*) over (rows between unbounded preceding and current row) c from nation order by c",
]


@pytest.mark.parametrize("sql", WINDOW_QUERIES, ids=range(len(WINDOW_QUERIES)))
def test_window(runner, oracle, sql):
    compare(runner, oracle, sql, rel=1e-9)


def _window_distributed(runner, queries):
    from presto_tpu.exec.distributed import DistributedRunner
    dist = DistributedRunner(catalogs=runner.session.catalogs,
                             rows_per_batch=1 << 13)
    for sql in queries:
        want = runner.execute(sql)
        got = dist.execute(sql)
        w = [tuple(round(v, 6) if isinstance(v, float) else v for v in r)
             for r in want.rows]
        g = [tuple(round(float(v), 6) if hasattr(v, "item") and
                   isinstance(v.item(), float) else
                   (v.item() if hasattr(v, "item") else v) for v in r)
             for r in got.rows]
        w2 = [tuple(v.item() if hasattr(v, "item") else v for v in r)
              for r in want.rows]
        assert len(g) == len(w2)


def test_window_distributed(runner):
    # tier-1 smoke: two shapes through the distributed exchange; the
    # remaining sweep rides the slow lane (tier-1 wall budget)
    _window_distributed(runner, WINDOW_QUERIES[:2])


@pytest.mark.slow
def test_window_distributed_sweep(runner):
    _window_distributed(runner, WINDOW_QUERIES[2:6])


# -- explicit frames (reference operator/window/FrameInfo.java) --------------

FRAME_QUERIES = [
    # ROWS offsets: moving sums / averages
    "select o_orderkey, sum(o_totalprice) over (partition by o_custkey order by o_orderkey rows between 2 preceding and current row) s from orders order by o_orderkey limit 40",
    "select o_orderkey, avg(o_totalprice) over (order by o_orderkey rows between 1 preceding and 1 following) a from orders order by o_orderkey limit 40",
    "select o_orderkey, sum(o_totalprice) over (order by o_orderkey rows between current row and 3 following) s from orders order by o_orderkey limit 40",
    "select o_orderkey, count(*) over (partition by o_orderstatus order by o_orderkey rows between 5 preceding and 2 preceding) c from orders order by o_orderkey limit 40",
    "select o_orderkey, sum(o_totalprice) over (order by o_orderkey rows between current row and unbounded following) s from orders order by o_orderkey limit 40",
    # min/max over arbitrary frames (sparse-table range queries)
    "select o_orderkey, min(o_totalprice) over (order by o_orderkey rows between 3 preceding and 1 following) m from orders order by o_orderkey limit 40",
    "select o_orderkey, max(o_totalprice) over (partition by o_orderstatus order by o_orderkey rows between 2 preceding and 2 following) m from orders order by o_orderkey limit 40",
    # value functions over explicit frames
    "select o_orderkey, first_value(o_totalprice) over (order by o_orderkey rows between 2 preceding and 1 preceding) f from orders order by o_orderkey limit 40",
    "select o_orderkey, last_value(o_totalprice) over (order by o_orderkey rows between 1 following and 3 following) l from orders order by o_orderkey limit 40",
    "select o_orderkey, nth_value(o_totalprice, 2) over (order by o_orderkey rows between 2 preceding and 2 following) n from orders order by o_orderkey limit 40",
    # RANGE with value offsets (single numeric order key)
    "select o_orderkey, count(*) over (order by o_orderkey range between 3 preceding and current row) c from orders order by o_orderkey limit 40",
    "select n_nationkey, sum(n_regionkey) over (order by n_nationkey range between 2 preceding and 2 following) s from nation order by n_nationkey",
    "select o_custkey, count(*) over (order by o_custkey range between 10 preceding and 5 preceding) c from orders order by o_orderkey limit 40",
    # RANGE offsets over a key with duplicates (peer handling)
    "select o_orderkey, o_custkey, sum(o_totalprice) over (order by o_custkey range between 5 preceding and current row) s from orders order by o_orderkey limit 40",
    # descending order with RANGE offsets
    "select o_orderkey, count(*) over (order by o_orderkey desc range between 3 preceding and current row) c from orders order by o_orderkey limit 40",
    # UNBOUNDED FOLLOWING ends
    "select o_orderkey, sum(o_totalprice) over (partition by o_orderstatus order by o_orderkey rows between 1 preceding and unbounded following) s from orders order by o_orderkey limit 40",
    # frame wider than the partition clips to it
    "select n_name, count(*) over (partition by n_regionkey order by n_nationkey rows between 100 preceding and 100 following) c from nation order by n_nationkey",
]


@pytest.mark.parametrize("sql", FRAME_QUERIES, ids=range(len(FRAME_QUERIES)))
def test_window_frames(runner, oracle, sql):
    compare(runner, oracle, sql, rel=1e-9)


def test_window_frames_distributed(runner):
    from presto_tpu.exec.distributed import DistributedRunner
    dist = DistributedRunner(catalogs=runner.session.catalogs,
                             n_devices=8, rows_per_batch=1 << 12)
    for sql in (FRAME_QUERIES[0], FRAME_QUERIES[11]):
        want = runner.execute(sql).rows
        got = dist.execute(sql).rows
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g[0] == w[0]
            # cumsum-difference vs per-shard summation: same frame sums
            # up to float association
            assert abs(float(g[1]) - float(w[1])) \
                <= 1e-9 * max(abs(float(w[1])), 1.0)


def test_window_frame_validation():
    import pytest as _pytest

    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.sql.lexer import SqlSyntaxError
    r = LocalRunner(tpch_sf=0.001)
    with _pytest.raises(SqlSyntaxError):
        r.execute("select sum(n_regionkey) over (order by n_name rows "
                  "between unbounded following and current row) from nation")
    with _pytest.raises(SqlSyntaxError):
        r.execute("select sum(n_regionkey) over (order by n_name rows "
                  "between current row and 2 preceding) from nation")
    with _pytest.raises(Exception, match="one ORDER BY"):
        r.execute("select sum(n_regionkey) over (order by n_name, "
                  "n_nationkey range between 2 preceding and current row)"
                  " from nation")

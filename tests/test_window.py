"""Window functions vs the SQLite oracle (sqlite3 >= 3.25 has windows)."""
import pytest

from test_sql import compare, oracle, runner  # noqa: F401 (fixtures)

WINDOW_QUERIES = [
    "select o_custkey, o_orderkey, row_number() over (partition by o_custkey order by o_orderkey) rn from orders order by o_custkey, o_orderkey limit 50",
    "select n_regionkey, n_name, rank() over (partition by n_regionkey order by n_name) r from nation order by n_regionkey, n_name",
    "select n_regionkey, n_name, dense_rank() over (order by n_regionkey) d from nation order by n_regionkey, n_name",
    "select o_orderkey, sum(o_totalprice) over (partition by o_custkey) s from orders order by o_orderkey limit 30",
    "select o_orderkey, sum(o_totalprice) over (partition by o_custkey order by o_orderkey) run from orders order by o_orderkey limit 30",
    "select o_orderkey, count(*) over (partition by o_orderstatus) c from orders order by o_orderkey limit 20",
    "select n_name, lag(n_name, 1) over (order by n_name) prev from nation order by n_name",
    "select n_name, lead(n_name, 2) over (partition by n_regionkey order by n_name) nx from nation order by n_regionkey, n_name",
    "select n_name, first_value(n_name) over (partition by n_regionkey order by n_name) f from nation order by n_regionkey, n_name",
    "select o_custkey, avg(o_totalprice) over (partition by o_custkey) a from orders order by o_custkey, o_orderkey limit 25",
    "select n_regionkey, n_name, percent_rank() over (partition by n_regionkey order by n_name) p from nation order by n_regionkey, n_name",
    "select n_regionkey, n_name, cume_dist() over (partition by n_regionkey order by n_name) p from nation order by n_regionkey, n_name",
    "select n_name, ntile(3) over (order by n_name) t from nation order by n_name",
    "select o_orderkey, min(o_totalprice) over (partition by o_orderstatus order by o_orderkey) m from orders order by o_orderkey limit 25",
    # ROWS vs RANGE frames: order key with ties (o_orderstatus) makes them differ
    "select o_orderkey, sum(o_totalprice) over (partition by o_custkey order by o_orderstatus rows between unbounded preceding and current row) s from orders order by o_orderkey limit 30",
    "select o_orderkey, count(*) over (partition by o_custkey order by o_orderstatus range between unbounded preceding and current row) c from orders order by o_orderkey limit 30",
    "select o_orderkey, last_value(o_orderstatus) over (partition by o_custkey order by o_totalprice rows unbounded preceding) lv from orders order by o_orderkey limit 30",
    # min/max over strings must compare lexicographically, not by code order
    "select n_regionkey, max(n_name) over (partition by n_regionkey order by n_nationkey) m from nation order by n_regionkey, n_nationkey",
    # explicit ROWS frame with no window ORDER BY still runs row-by-row
    # (which row gets which count is order-dependent, so sort by the count)
    "select count(*) over (rows between unbounded preceding and current row) c from nation order by c",
]


@pytest.mark.parametrize("sql", WINDOW_QUERIES, ids=range(len(WINDOW_QUERIES)))
def test_window(runner, oracle, sql):
    compare(runner, oracle, sql, rel=1e-9)


def test_window_distributed(runner):
    from presto_tpu.exec.distributed import DistributedRunner
    dist = DistributedRunner(catalogs=runner.session.catalogs,
                             rows_per_batch=1 << 13)
    for sql in WINDOW_QUERIES[:6]:
        want = runner.execute(sql)
        got = dist.execute(sql)
        w = [tuple(round(v, 6) if isinstance(v, float) else v for v in r)
             for r in want.rows]
        g = [tuple(round(float(v), 6) if hasattr(v, "item") and
                   isinstance(v.item(), float) else
                   (v.item() if hasattr(v, "item") else v) for v in r)
             for r in got.rows]
        w2 = [tuple(v.item() if hasattr(v, "item") else v for v in r)
              for r in want.rows]
        assert len(g) == len(w2)

"""Row-level error semantics: DIVISION_BY_ZERO, TRY, short-circuits.

Mirrors the reference's error behavior (reference
presto-spi/.../spi/StandardErrorCode.java, operator/scalar/TryFunction.java,
sql/gen/AndCodeGenerator short-circuit): integer/decimal division by zero
raises, double division follows IEEE, TRY() yields NULL, and branches that
are not taken never raise.
"""
import math

import pytest

from presto_tpu.errors import QueryError


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.001)


def q1(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0][0]


def test_integer_division_by_zero(runner):
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        runner.execute("select 1/0")


def test_modulus_by_zero(runner):
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        runner.execute("select 5 % 0")


def test_division_by_zero_in_where(runner):
    # the predicate evaluates 1/l_x for every scanned row
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        runner.execute(
            "select count(*) from lineitem "
            "where 1/(l_linenumber - l_linenumber) > 0")


def test_try_division_by_zero_is_null(runner):
    assert q1(runner, "select try(1/0)") is None


def test_try_passthrough(runner):
    assert q1(runner, "select try(6/2)") == 3


def test_double_division_ieee(runner):
    # Java/Presto DoubleOperators: x/0.0 = Infinity, no error
    assert math.isinf(q1(runner, "select 1e0/0e0"))
    assert math.isnan(q1(runner, "select 0e0/0e0"))


def test_and_short_circuit_suppresses_error(runner):
    n = q1(runner, "select count(*) from lineitem "
                   "where l_linenumber <> 0 and l_orderkey/l_linenumber > 0")
    assert n > 0


def test_or_short_circuit_suppresses_error(runner):
    n = q1(runner, "select count(*) from lineitem "
                   "where l_linenumber > 0 or 1/(l_linenumber*0) > 0")
    assert n > 0


def test_case_untaken_branch_no_error(runner):
    v = q1(runner, "select case when l_linenumber = 99 "
                   "then l_orderkey/(l_linenumber-l_linenumber) "
                   "else 1 end from lineitem limit 1")
    assert v == 1


def test_if_untaken_branch_no_error(runner):
    assert q1(runner, "select if(false, 1/0, 42)") == 42


def test_if_taken_branch_errors(runner):
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        runner.execute("select if(true, 1/0, 42)")


def test_coalesce_error_propagates(runner):
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        runner.execute("select coalesce(1/0, 7)")


def test_coalesce_of_try(runner):
    assert q1(runner, "select coalesce(try(1/0), 7)") == 7


def test_null_divisor_is_null_not_error(runner):
    # null arguments short-circuit the call (no evaluation, no error)
    assert q1(runner, "select 1/cast(null as bigint)") is None


def test_error_in_projection_over_table(runner):
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        runner.execute("select l_orderkey/(l_linenumber - l_linenumber) "
                       "from lineitem")


def test_decimal_division_by_zero(runner):
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        runner.execute("select cast(1 as decimal(10,2)) / "
                       "cast(0 as decimal(10,2))")


def test_insert_error_persists_nothing(runner):
    # a failing INSERT ... SELECT must not write partial rows
    runner.execute("create table memory.default.err_t as select 1 as x")
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        runner.execute("insert into memory.default.err_t "
                       "select l_linenumber/(l_linenumber-l_linenumber) "
                       "from lineitem")
    assert runner.execute(
        "select count(*) from memory.default.err_t").rows == [(1,)]


def test_join_residual_error(runner):
    # ON-clause residual errors raise like WHERE errors do
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        runner.execute(
            "select count(*) from lineitem l join orders o "
            "on l.l_orderkey = o.o_orderkey "
            "and l.l_partkey > o.o_orderkey / o.o_shippriority")


def test_distributed_division_by_zero():
    from presto_tpu.exec.distributed import DistributedRunner
    r = DistributedRunner(tpch_sf=0.001, n_devices=8)
    with pytest.raises(QueryError, match="DIVISION_BY_ZERO"):
        r.execute("select l_orderkey/(l_linenumber - l_linenumber) "
                  "from lineitem")

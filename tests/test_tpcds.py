"""TPC-DS end-to-end tests against a SQLite oracle (BASELINE config 4).

Same ring-2 strategy as test_sql.py: the engine and the oracle see the
identical generated data (connectors/tpcds.py); results must match.
q27/q55 are the BASELINE.md config-4 queries (reference
presto-benchto-benchmarks/.../sql/presto/tpcds/q27.sql, q55.sql); q27
exercises GROUP BY ROLLUP + GROUPING() (reference
sql/tree/GroupingSets.java, operator/GroupIdOperator.java).
"""
import sqlite3

import pytest

# tier-1 budget: excluded from `pytest -m 'not slow'` — executes the full TPC-DS query battery against the oracle
# (see tools/check_tier1_time.py; ~192s)
pytestmark = pytest.mark.slow

from presto_tpu.connectors.spi import CatalogManager, TableHandle
from presto_tpu.connectors.tpcds import TABLES, TpcdsConnector, tpcds_schema
from presto_tpu.exec.runner import LocalRunner

from test_sql import _norm, _sql_val

SF = 0.01


@pytest.fixture(scope="module")
def runner():
    catalogs = CatalogManager()
    catalogs.register("tpcds", TpcdsConnector(sf=SF))
    return LocalRunner(catalogs=catalogs, catalog="tpcds")


@pytest.fixture(scope="module")
def oracle(runner):
    conn = sqlite3.connect(":memory:")
    tpcds = runner.session.catalogs.get("tpcds")
    for t in TABLES:
        schema = tpcds_schema(t)
        cols = ", ".join(schema.names)
        conn.execute(f"create table {t} ({cols})")
        placeholders = ", ".join("?" * len(schema))
        th = TableHandle("tpcds", "default", t)
        for split in tpcds.split_manager.splits(th, 1):
            for b in tpcds.page_source(split, schema.names,
                                       rows_per_batch=1 << 17).batches():
                rows = [tuple(_sql_val(v) for v in r) for r in b.to_pylist()]
                conn.executemany(
                    f"insert into {t} values ({placeholders})", rows)
    # join-key indexes: SQLite's nested-loop planner needs them for the
    # star joins and the big OR-of-conjuncts queries (q13/q48) to run in
    # test time
    for t in TABLES:
        for col in tpcds_schema(t).names:
            if col.endswith("_sk"):
                conn.execute(
                    f"create index idx_{t}_{col} on {t} ({col})")
    conn.commit()
    return conn


def compare(runner, oracle, sql, oracle_sql=None):
    got = runner.execute(sql)
    want = oracle.execute(oracle_sql or sql).fetchall()
    has_order = "order by" in sql.lower()
    g = _norm(got.rows, has_order)
    w = _norm(want, has_order)
    assert g == w, f"engine={g[:5]}... oracle={w[:5]}..."
    return got


Q55 = """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand, i_brand_id
order by ext_price desc, i_brand_id
limit 100
"""

Q27 = """
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2002
  and s_state in ('TN', 'TN', 'TN', 'TN', 'TN', 'TN')
group by rollup (i_item_id, s_state)
order by i_item_id nulls last, s_state nulls last
limit 100
"""

# SQLite has no ROLLUP/GROUPING(): emulate with UNION ALL of the three
# grouping sets, exactly the relational form our planner lowers to.
Q27_ORACLE = """
with base as (
  select i_item_id, s_state, ss_quantity, ss_list_price,
         ss_coupon_amt, ss_sales_price
  from store_sales, customer_demographics, date_dim, store, item
  where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk
    and ss_cdemo_sk = cd_demo_sk
    and cd_gender = 'M'
    and cd_marital_status = 'S'
    and cd_education_status = 'College'
    and d_year = 2002
    and s_state in ('TN', 'TN', 'TN', 'TN', 'TN', 'TN')
)
select * from (
  select i_item_id, s_state, 0 g_state, avg(ss_quantity) agg1,
         avg(ss_list_price) agg2, avg(ss_coupon_amt) agg3,
         avg(ss_sales_price) agg4
  from base group by i_item_id, s_state
  union all
  select i_item_id, null, 1, avg(ss_quantity), avg(ss_list_price),
         avg(ss_coupon_amt), avg(ss_sales_price)
  from base group by i_item_id
  union all
  select null, null, 1, avg(ss_quantity), avg(ss_list_price),
         avg(ss_coupon_amt), avg(ss_sales_price)
  from base
)
order by i_item_id nulls last, s_state nulls last
limit 100
"""


def test_q55(runner, oracle):
    res = compare(runner, oracle, Q55)
    assert len(res.rows) > 0


def test_q27(runner, oracle):
    res = compare(runner, oracle, Q27, Q27_ORACLE)
    assert len(res.rows) > 0
    # the rollup must include per-(item,state), per-item, and grand rows
    g_states = {r[2] for r in res.rows}
    assert g_states == {0, 1}


def test_rollup_over_empty_input_emits_grand_total(runner):
    """The ROLLUP empty set owes its grand-total row even over empty
    input (reference AggregationNode.hasDefaultOutput): one row with
    NULL keys and count 0 — synthesized by the executor now that the
    empty set rides the single GroupId pipeline instead of a separate
    global-aggregation branch."""
    rows = runner.execute(
        "select d_year, count(*), sum(d_date_sk) from date_dim "
        "where d_date_sk < 0 group by rollup(d_year)").rows
    assert rows == [(None, 0, None)]


def test_rollup_single_pipeline(runner):
    """The plan for ROLLUP contains exactly ONE aggregation pipeline —
    no Union re-executing the input for the grand-total set."""
    out = runner.execute(
        "explain select d_year, count(*) from date_dim "
        "group by rollup(d_year)")
    text = "\n".join(r[0] for r in out.rows)
    assert "Union" not in text
    assert text.count("TableScan") == 1


def test_scan_counts(runner, oracle):
    for t in TABLES:
        compare(runner, oracle, f"select count(*) from {t}")


def test_star_join_small(runner, oracle):
    compare(runner, oracle, """
        select d_year, count(*) n, sum(ss_net_paid) paid
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk
        group by d_year
        order by d_year
    """)


def test_cube(runner, oracle):
    compare(runner, oracle, """
        select d_year, d_qoy, count(*) n
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk and d_year between 1999 and 2000
        group by cube(d_year, d_qoy)
        order by d_year nulls last, d_qoy nulls last
    """, """
        with base as (
          select d_year, d_qoy from store_sales, date_dim
          where ss_sold_date_sk = d_date_sk and d_year between 1999 and 2000
        )
        select * from (
          select d_year, d_qoy, count(*) n from base group by d_year, d_qoy
          union all
          select d_year, null, count(*) from base group by d_year
          union all
          select null, d_qoy, count(*) from base group by d_qoy
          union all
          select null, null, count(*) from base
        )
        order by d_year nulls last, d_qoy nulls last
    """)


def test_grouping_sets(runner, oracle):
    compare(runner, oracle, """
        select s_state, s_store_name, count(*) n
        from store group by grouping sets ((s_state), (s_store_name), ())
        order by s_state nulls last, s_store_name nulls last, n
    """, """
        select * from (
          select s_state, null s_store_name, count(*) n
          from store group by s_state
          union all
          select null, s_store_name, count(*) from store group by s_store_name
          union all
          select null, null, count(*) from store
        )
        order by s_state nulls last, s_store_name nulls last, n
    """)


# -- the TPC-DS suite (adapted store-channel queries, tests/tpcds_queries.py)

from tpcds_queries import Q as TPCDS_QUERIES


@pytest.mark.parametrize(
    "name,sql,oracle_sql",
    TPCDS_QUERIES, ids=[t[0] for t in TPCDS_QUERIES])
def test_tpcds_query(runner, oracle, name, sql, oracle_sql):
    compare(runner, oracle, sql, oracle_sql)


def test_extension_tables_against_oracle():
    """The extension tables (catalog/web channels, returns, inventory,
    small dims) agree with a SQLite oracle over the same generated data
    (same contract as the base suite; reference AbstractTestQueries per
    connector)."""
    import sqlite3

    from presto_tpu.exec.runner import LocalRunner

    r = LocalRunner(catalog="tpcds", tpch_sf=0.001)
    conn = r.session.catalogs.get("tpcds")
    db = sqlite3.connect(":memory:")
    for table, cols in (
            ("catalog_sales", ["cs_item_sk", "cs_sold_date_sk",
                               "cs_quantity", "cs_ext_sales_price",
                               "cs_net_profit", "cs_order_number"]),
            ("web_sales", ["ws_item_sk", "ws_ext_sales_price",
                           "ws_web_site_sk", "ws_order_number"]),
            ("store_returns", ["sr_item_sk", "sr_return_amt",
                               "sr_ticket_number", "sr_return_quantity"]),
            ("inventory", ["inv_item_sk", "inv_warehouse_sk",
                           "inv_quantity_on_hand"]),
            ("warehouse", ["w_warehouse_sk", "w_warehouse_name",
                           "w_state"]),
            ("income_band", ["ib_income_band_sk", "ib_lower_bound",
                             "ib_upper_bound"])):
        from presto_tpu.connectors.spi import TableHandle
        th = TableHandle("tpcds", "default", table)
        rows = []
        for split in conn.split_manager.splits(th, 1):
            for b in conn.page_source(split, cols).batches():
                rows.extend(b.to_pylist())
        db.execute(f"create table {table} ({', '.join(cols)})")
        db.executemany(
            f"insert into {table} values ({', '.join('?' * len(cols))})",
            [tuple(v.item() if hasattr(v, "item") else v for v in row)
             for row in rows])
    db.commit()

    checks = [
        ("select count(*), sum(cs_quantity), round(sum(cs_ext_sales_price), 2) from catalog_sales",),
        ("select count(*) from catalog_sales cs join store_returns sr on cs_item_sk = sr_item_sk and cs_order_number = sr_ticket_number",),
        ("select w_state, sum(inv_quantity_on_hand) from inventory join warehouse on inv_warehouse_sk = w_warehouse_sk group by w_state order by 1",),
        ("select ib_income_band_sk from income_band where ib_lower_bound >= 20000 and ib_upper_bound <= 60000 order by 1",),
        ("select count(distinct ws_order_number) from web_sales where ws_ext_sales_price > 500",),
    ]
    for (sql,) in checks:
        got = [tuple(x.item() if hasattr(x, "item") else x for x in row)
               for row in r.execute(sql).rows]
        want = [tuple(row) for row in db.execute(sql).fetchall()]
        assert len(got) == len(want), (sql, got, want)
        for g, w in zip(got, want):
            for gv, wv in zip(g, w):
                if isinstance(gv, float):
                    assert abs(gv - wv) <= 1e-6 * max(abs(wv), 1.0), (sql, g, w)
                else:
                    assert gv == wv, (sql, g, w)

"""Long decimals (precision 19..38) as two-limb int128 columns.

The reference models decimal(38) over Int128 (reference
presto-spi/.../spi/type/DecimalType.java MAX_PRECISION = 38,
spi/block/Int128ArrayBlock.java, UnscaledDecimal128Arithmetic.java);
here the storage is an [capacity, 2] i64 limb tile with vector kernels
(presto_tpu/ops/int128.py). Every result checks against the Python
``decimal.Decimal`` oracle.
"""
import decimal
from decimal import Decimal

import numpy as np
import pytest


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.001)


@pytest.fixture(scope="module")
def dist():
    from presto_tpu.exec.distributed import DistributedRunner
    from presto_tpu.exec.runner import LocalRunner
    r = LocalRunner(tpch_sf=0.001)
    return DistributedRunner(catalogs=r.session.catalogs,
                             n_devices=8, rows_per_batch=1 << 10)


# -- kernel-level oracle ----------------------------------------------------

def _dec(pair):
    from presto_tpu.ops.int128 import int_of
    v = int_of(*pair)
    return v - 2 ** 128 if v >= 2 ** 127 else v


def test_int128_arith_oracle():
    import jax.numpy as jnp
    from presto_tpu.ops import int128 as I

    rng = np.random.default_rng(5)
    a_py = [int(rng.integers(-10 ** 18, 10 ** 18)) * 10 ** int(rng.integers(0, 19))
            + int(rng.integers(-10 ** 6, 10 ** 6)) for _ in range(300)]
    b_py = [int(rng.integers(-10 ** 18, 10 ** 18)) for _ in range(300)]
    a = jnp.asarray(I.np_limbs(a_py))
    b = jnp.asarray(I.np_limbs(b_py))
    s = np.asarray(I.add(a, b))
    d = np.asarray(I.sub(a, b))
    p, ovf = I.mul(a, b)
    p, ovf = np.asarray(p), np.asarray(ovf)
    lt = np.asarray(I.lt(a, b))
    for i in range(300):
        assert _dec(s[i]) == a_py[i] + b_py[i]
        assert _dec(d[i]) == a_py[i] - b_py[i]
        if abs(a_py[i] * b_py[i]) < 2 ** 127:
            assert not ovf[i] and _dec(p[i]) == a_py[i] * b_py[i], i
        assert bool(lt[i]) == (a_py[i] < b_py[i])


def test_int128_rescale_half_up():
    import jax.numpy as jnp
    from presto_tpu.ops import int128 as I

    vals = [123456789012345678901234567895, -123456789012345678901234567895,
            49, 50, -49, -50, 0]
    x = jnp.asarray(I.np_limbs(vals))
    down, _ = I.rescale(x, -2)
    with decimal.localcontext() as ctx:
        ctx.prec = 60
        for i, v in enumerate(vals):
            want = int(Decimal(v).scaleb(-2).quantize(
                0, rounding=decimal.ROUND_HALF_UP))
            assert _dec(np.asarray(down)[i]) == want, (v, want)
    up, ovf = I.rescale(x, 8)
    assert _dec(np.asarray(up)[0]) == vals[0] * 10 ** 8
    assert not bool(np.asarray(ovf)[0])


def test_int128_digit_sums_exact():
    import jax.numpy as jnp
    from presto_tpu.ops import int128 as I

    rng = np.random.default_rng(6)
    vals = [int(rng.integers(-10 ** 18, 10 ** 18)) * 10 ** 19 + 7
            for _ in range(5000)]
    planes = I.digit_sum_tiles(jnp.asarray(I.np_limbs(vals)))
    total = I.from_digit_sum_tiles(jnp.sum(planes, axis=0))
    assert _dec(np.asarray(total)) == sum(vals)


# -- data plane -------------------------------------------------------------

def test_long_decimal_column_roundtrip():
    from presto_tpu.batch import Batch
    from presto_tpu import types as T

    t = T.DecimalType(38, 10)
    vals = [Decimal("12345678901234567890.0123456789"), None,
            Decimal("-9999999999999999999999999999.9999999999"),
            Decimal("0.5")]
    b = Batch.from_pydict({"d": (t, vals)})
    assert b.columns[0].data.shape == (128, 2)
    out = [r[0] for r in b.to_pylist()]
    assert out[0] == vals[0] and out[1] is None
    assert out[2] == vals[2]
    assert out[3] == Decimal("0.5000000000")


def test_long_decimal_wire_roundtrip():
    from presto_tpu.batch import Batch
    from presto_tpu import types as T
    from presto_tpu.exec import pages

    t = T.DecimalType(30, 4)
    vals = [Decimal("12345678901234567890.1234"), None, Decimal("-7.5")]
    b = Batch.from_pydict({"d": (t, vals)})
    blob = pages.serialize_page(b)
    back = pages.deserialize_page(blob)
    assert [r[0] for r in back.to_pylist()] == [r[0] for r in b.to_pylist()]


# -- SQL surface ------------------------------------------------------------

def test_literals_and_arith(runner):
    rows = runner.execute(
        "select decimal '12345678901234567890.12345' + "
        "decimal '98765432109876543210.5', "
        "decimal '99999999999999999999' * decimal '1000000000000000000', "
        "decimal '12345678901234567890.5' - decimal '0.5'").rows
    assert rows[0][0] == Decimal("111111111011111111100.62345")
    assert rows[0][1] == Decimal("99999999999999999999000000000000000000")
    assert rows[0][2] == Decimal("12345678901234567890.0")


def test_division_and_rounding(runner):
    rows = runner.execute(
        "select cast('12345678901234567890.5' as decimal(38,2)) / 4, "
        "round(decimal '12345678901234567890.567', 1), "
        "floor(decimal '-12345678901234567890.5'), "
        "ceil(decimal '-12345678901234567890.5')").rows
    assert rows[0][0] == Decimal("3086419725308641972.63")
    assert rows[0][1] == Decimal("12345678901234567890.600")
    assert rows[0][2] == Decimal("-12345678901234567891.0")
    assert rows[0][3] == Decimal("-12345678901234567890.0")


def test_comparisons_and_abs(runner):
    rows = runner.execute(
        "select decimal '12345678901234567890' > "
        "decimal '12345678901234567889', "
        "abs(decimal '-123456789012345678901'), "
        "sign(decimal '-123456789012345678901')").rows
    assert bool(rows[0][0]) is True
    assert rows[0][1] == Decimal("123456789012345678901")
    assert rows[0][2] == Decimal("-1")


def test_casts(runner):
    rows = runner.execute(
        "select cast(decimal '123456789012345678901.5' as double), "
        "cast(decimal '123.45678901234567890123' as decimal(10,2)), "
        "cast(12345 as decimal(38,3)), "
        "cast(decimal '42.0000000000000000000009' as bigint)").rows
    assert rows[0][0] == pytest.approx(1.2345678901234568e20)
    assert rows[0][1] == Decimal("123.46")
    assert rows[0][2] == Decimal("12345.000")
    assert rows[0][3] == 42


def test_overflow_errors(runner):
    from presto_tpu.errors import QueryError
    with pytest.raises(QueryError):
        runner.execute(
            "select decimal '99999999999999999999999999999999999999' "
            "+ decimal '1'")
    with pytest.raises(QueryError):
        runner.execute(
            "select cast(decimal '12345678901234567890' as integer)")


def test_literal_over_38_digits_rejected(runner):
    from presto_tpu.sql.analyzer import AnalysisError
    with pytest.raises(AnalysisError):
        runner.execute(
            "select decimal '999999999999999999999999999999999999990'")


def test_null_propagation(runner):
    rows = runner.execute(
        "select cast(null as decimal(38,2)) + decimal '1.00', "
        "coalesce(cast(null as decimal(30,1)), decimal '7.5')").rows
    assert rows[0][0] is None
    assert rows[0][1] == Decimal("7.5")


# -- aggregation vs Decimal oracle ------------------------------------------

def test_sum_widens_to_38(runner):
    """sum(decimal(p,s)) is decimal(38,s): short-decimal columns whose
    sums overflow 18 digits are exact (reference
    DecimalSumAggregation)."""
    rows = runner.execute(
        "select sum(x), avg(x), min(x), max(x) from (values "
        "decimal '999999999999999.99', decimal '999999999999999.99', "
        "decimal '-0.01', cast(null as decimal(17,2))) t(x)").rows
    assert rows[0][0] == Decimal("1999999999999999.97")
    assert rows[0][1] == Decimal("666666666666666.66")   # half-up /3
    assert rows[0][2] == Decimal("-0.01")
    assert rows[0][3] == Decimal("999999999999999.99")


def test_grouped_long_decimal_aggs(runner):
    rows = runner.execute(
        "select k, sum(x), min(x), max(x) from (values "
        "(1, decimal '99999999999999999999999999999999.99'), "
        "(1, decimal '0.01'), "
        "(2, decimal '-99999999999999999999999999999999.99'), "
        "(2, cast(null as decimal(34,2)))) t(k, x) "
        "group by k order by k").rows
    assert rows[0][1] == Decimal("100000000000000000000000000000000.00")
    assert rows[0][2] == Decimal("0.01")
    assert rows[0][3] == Decimal("99999999999999999999999999999999.99")
    assert rows[1][1] == Decimal("-99999999999999999999999999999999.99")


def test_group_by_and_order_by_long_decimal_key(runner):
    rows = runner.execute(
        "select x, count(*) from (values decimal '12345678901234567890.5', "
        "decimal '12345678901234567890.5', decimal '-1.0', "
        "cast(null as decimal(21,1))) t(x) group by x order by x desc").rows
    # DESC with NULLS FIRST (Presto default for desc)
    assert rows[0][0] is None
    assert rows[1] == (Decimal("12345678901234567890.5"), 2)
    assert rows[2] == (Decimal("-1.0"), 1)


def test_distinct_long_decimal(runner):
    rows = runner.execute(
        "select distinct x from (values decimal '1.00', decimal '1.00', "
        "decimal '99999999999999999999.99') t(x) order by x").rows
    assert [r[0] for r in rows] == [Decimal("1.00"),
                                    Decimal("99999999999999999999.99")]


def test_distributed_decimal_sum(dist, runner):
    """Partial decimal(38) limb states merge across the mesh exchange
    exactly (digit-plane sums are associative integers)."""
    q = ("select k, sum(x) from (values "
         "(1, decimal '9999999999999999.99'), (2, decimal '0.01'), "
         "(1, decimal '9999999999999999.99'), (2, decimal '5.00'), "
         "(1, decimal '0.02')) t(k, x) group by k order by k")
    assert dist.execute(q).rows == runner.execute(q).rows


def test_long_decimal_join_key(runner):
    """Equi-joins on long-decimal keys: limbs become two lexicographic
    key operands (regression: the [n,2] tile crashed lax.sort)."""
    rows = runner.execute(
        "with t as (select * from (values decimal '12345678901234567890.5', "
        "decimal '-1.0', decimal '99999999999999999999999999.25') v(q)) "
        "select count(*) from t a join t b on a.q = b.q").rows
    assert rows == [(3,)]


def test_window_over_long_decimal_rejected(runner):
    """Window aggregates over decimal(>18) raise a clear analysis error
    instead of producing corrupt cumsums."""
    from presto_tpu.sql.analyzer import AnalysisError
    with pytest.raises(AnalysisError):
        runner.execute(
            "select sum(cast(x as decimal(38,2))) over () from "
            "(values decimal '1.00') t(x)")


def test_window_sum_short_decimal_still_exact(runner):
    """Window sums over short decimals keep the exact i64 path and
    correct per-partition results (regression: the decimal(38) agg
    output type leaked into window specs and corrupted results)."""
    rows = runner.execute(
        "select k, sum(x) over (partition by k) from (values "
        "(1, decimal '1.50'), (1, decimal '2.00'), (2, decimal '5.00')) "
        "t(k, x) order by k").rows
    assert rows == [(1, Decimal("3.50")), (1, Decimal("3.50")),
                    (2, Decimal("5.00"))]


def test_sum_overflow_raises(runner):
    """A 38-digit sum overflow raises NUMERIC_VALUE_OUT_OF_RANGE at
    decode instead of wrapping silently."""
    from presto_tpu.errors import QueryError
    with pytest.raises(QueryError):
        runner.execute(
            "select sum(x) from (values "
            "decimal '99999999999999999999999999999999999999', "
            "decimal '99999999999999999999999999999999999999') t(x)")


def test_round_digits_beyond_scale_is_identity(runner):
    rows = runner.execute(
        "select round(decimal '9999999999999999999999999999999999', 10), "
        "round(decimal '123456789012345678.12', 5)").rows
    assert rows[0][0] == Decimal("9999999999999999999999999999999999")
    assert rows[0][1] == Decimal("123456789012345678.12")


def test_oracle_random_sums(runner):
    """Random 25-digit decimals: engine sum == Python Decimal sum."""
    rng = np.random.default_rng(17)
    with decimal.localcontext() as ctx:
        ctx.prec = 60
        vals = [Decimal(int(rng.integers(-10 ** 15, 10 ** 15)))
                * Decimal(10) ** int(rng.integers(0, 10))
                + Decimal(int(rng.integers(0, 100))).scaleb(-2)
                for _ in range(97)]
        lits = ", ".join(f"decimal '{v}'" for v in vals)
        rows = runner.execute(
            f"select sum(x), min(x), max(x) from (values {lits}) t(x)").rows
        want_sum = sum(vals).quantize(Decimal("0.01"))
        assert rows[0][0] == want_sum, (rows[0][0], want_sum)
        assert rows[0][1] == min(vals).quantize(Decimal("0.01"))
        assert rows[0][2] == max(vals).quantize(Decimal("0.01"))


def test_wide_division_exact(runner):
    """General int128 division (float-estimate + exact correction,
    ops/int128.py divmod_abs) against python Decimal, including the
    small-divisor and small-value shapes that exposed the to_f64/
    from_f64 precision bugs."""
    import decimal as _d
    from decimal import ROUND_HALF_UP

    _d.getcontext().prec = 60
    cases = [
        ("12345678901234567890123456.78", "decimal(38,2)",
         "987654321098765.4", "decimal(16,1)"),
        ("99999999999999999999.99", "decimal(22,2)", "-3.7",
         "decimal(16,1)"),
        ("9955911909542365299945990106.63", "decimal(38,2)", "3.00",
         "decimal(18,2)"),
        ("0.04", "decimal(38,2)", "400000000000000000.0",
         "decimal(19,1)"),
    ]
    for a, ta, b, tb in cases:
        got = runner.execute(
            f"select cast('{a}' as {ta}) / cast('{b}' as {tb})"
        ).rows[0][0]
        scale = -got.as_tuple().exponent
        want = (Decimal(a) / Decimal(b)).quantize(
            Decimal(1).scaleb(-scale), rounding=ROUND_HALF_UP)
        assert got == want, (a, b, got, want)


def test_long_decimal_to_double_small_values(runner):
    """cast(decimal(38,s) as double) of SMALL magnitudes: the old
    to_f64 catastrophically cancelled (4.00 came back 0.0)."""
    rows = runner.execute(
        "select cast(cast('4.00' as decimal(38,2)) as double), "
        "cast(cast('-7.25' as decimal(20,2)) as double), "
        "cast(cast('0.01' as decimal(38,2)) as double)").rows
    assert rows[0] == (4.0, -7.25, 0.01)

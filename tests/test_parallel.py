"""Exchange collectives on the virtual 8-device CPU mesh.

Ring-3 analogue of Presto's multi-node-in-one-JVM tests (reference
presto-tests/.../DistributedQueryRunner.java:76): N shards in one process,
real collectives, results checked against the single-device path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
try:                                    # jax >= 0.6: top-level export,
    from jax import shard_map as _shard_map     # kwarg is check_vma
    _CHECK_KW = "check_vma"
except ImportError:                     # jax 0.4.x: experimental module,
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"             # same switch as exec/distributed
from jax.sharding import PartitionSpec as P


def shard_map(f, **kw):
    """Version-portable shard_map: call sites use the modern check_vma
    spelling; older jax gets it translated to check_rep."""
    if "check_vma" in kw and _CHECK_KW != "check_vma":
        kw[_CHECK_KW] = kw.pop("check_vma")
    return _shard_map(f, **kw)

from presto_tpu import types as T
from presto_tpu.batch import Batch
from presto_tpu.ops.aggregation import AggSpec, grouped_aggregate
from presto_tpu.parallel import (
    broadcast_batch, hash_partition_ids, make_mesh, repartition_by_hash,
    shard_batch,
)

N = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


def _batch(n=256, seed=0):
    rng = np.random.RandomState(seed)
    keys = rng.randint(0, 13, size=n).astype(np.int64)
    vals = rng.uniform(0, 100, size=n)
    return Batch.from_pydict({
        "k": (T.BIGINT, list(keys)),
        "v": (T.DOUBLE, list(vals)),
    })


def test_repartition_preserves_rows(mesh):
    b = _batch()
    sharded = shard_batch(b, mesh, "dp")

    def step(local):
        return repartition_by_hash(local, [0], "dp", N)

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                            out_specs=P("dp"), check_vma=False))(sharded)
    # every input row lands on exactly one shard
    assert int(jnp.sum(out.row_mask)) == b.host_count()
    got = sorted(out.to_pylist())
    want = sorted(b.to_pylist())
    assert got == want


def test_repartition_colocates_keys(mesh):
    b = _batch()
    sharded = shard_batch(b, mesh, "dp")

    def step(local):
        ex = repartition_by_hash(local, [0], "dp", N)
        # tag each live row with this shard's index
        me = jax.lax.axis_index("dp")
        tag = jnp.where(ex.row_mask, me, -1)
        return ex, tag

    ex, tags = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                                 out_specs=P("dp"), check_vma=False))(sharded)
    rows = ex.to_pylist()
    live = np.asarray(ex.row_mask)
    shard_of = np.asarray(tags)[live]
    key_shard = {}
    for (k, _v), s in zip(rows, shard_of):
        assert key_shard.setdefault(k, s) == s, f"key {k} split across shards"


def test_broadcast(mesh):
    b = _batch(64)
    sharded = shard_batch(b, mesh, "dp")

    def step(local):
        return broadcast_batch(local, "dp")

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                            out_specs=P("dp"), check_vma=False))(sharded)
    # each shard holds a full copy: N copies total
    assert int(jnp.sum(out.row_mask)) == N * b.host_count()


def test_distributed_grouped_agg_matches_local(mesh):
    b = _batch(512, seed=3)
    aggs = [AggSpec("sum", 1, T.DOUBLE, "s"),
            AggSpec("count_star", None, T.BIGINT, "c")]
    local_out = grouped_aggregate(b, [0], aggs, mode="single")
    want = sorted(local_out.to_pylist())

    sharded = shard_batch(b, mesh, "dp")

    def step(local):
        partial = grouped_aggregate(local, [0], aggs, mode="partial")
        ex = repartition_by_hash(partial, [0], "dp", N)
        return grouped_aggregate(ex, [0], aggs, mode="final")

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                            out_specs=P("dp"), check_vma=False))(sharded)
    got = sorted(out.to_pylist())
    assert len(got) == len(want)
    for (gk, gs, gc), (wk, ws, wc) in zip(got, want):
        assert gk == wk and gc == wc
        assert gs == pytest.approx(ws, rel=1e-12)


def test_partition_ids_in_range():
    b = _batch(128)
    pid = hash_partition_ids(b, [0], N)
    arr = np.asarray(pid)
    assert arr.min() >= 0 and arr.max() < N


def test_compact_repartition_matches_masked(mesh):
    """Quota-compacted exchange delivers the identical row multiset as the
    masked baseline, at ~C output capacity instead of n*C."""
    from presto_tpu.parallel.exchange import (
        partition_counts, repartition_by_hash_compact,
    )
    b = _batch(n=1024, seed=3)
    sharded = shard_batch(b, mesh, "dp")

    counts_fn = jax.jit(shard_map(
        lambda local: partition_counts(local, [0], N),
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False))
    quota = int(np.asarray(counts_fn(sharded)).max())
    # bucket up like the executor does
    from presto_tpu.batch import bucket_capacity
    quota = bucket_capacity(quota, minimum=1)

    def step(local):
        return repartition_by_hash_compact(local, [0], "dp", N, quota)

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                            out_specs=P("dp"), check_vma=False))(sharded)
    assert int(jnp.sum(out.row_mask)) == b.host_count()
    assert sorted(out.to_pylist()) == sorted(b.to_pylist())
    # volume: per-shard capacity n*quota, global n*n*quota << n*C
    masked_global_cap = N * b.capacity          # masked all_to_all output
    compact_global_cap = N * N * quota
    assert compact_global_cap < masked_global_cap


def test_compact_repartition_colocates_keys(mesh):
    from presto_tpu.parallel.exchange import repartition_by_hash_compact
    b = _batch(n=512, seed=7)
    sharded = shard_batch(b, mesh, "dp")

    def step(local):
        out = repartition_by_hash_compact(local, [0], "dp", N, 256)
        pid = hash_partition_ids(out, [0], N)
        ok = jnp.all(jnp.where(out.row_mask,
                               pid == jax.lax.axis_index("dp"), True))
        return out, ok[None]

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                           out_specs=(P("dp"), P("dp")), check_vma=False))
    out, ok = fn(sharded)
    assert bool(jnp.all(ok))
    assert int(jnp.sum(out.row_mask)) == b.host_count()

"""Exchange collectives on the virtual 8-device CPU mesh.

Ring-3 analogue of Presto's multi-node-in-one-JVM tests (reference
presto-tests/.../DistributedQueryRunner.java:76): N shards in one process,
real collectives, results checked against the single-device path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from presto_tpu import types as T
from presto_tpu.batch import Batch
from presto_tpu.ops.aggregation import AggSpec, grouped_aggregate
from presto_tpu.parallel import (
    broadcast_batch, hash_partition_ids, make_mesh, repartition_by_hash,
    shard_batch,
)

N = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


def _batch(n=256, seed=0):
    rng = np.random.RandomState(seed)
    keys = rng.randint(0, 13, size=n).astype(np.int64)
    vals = rng.uniform(0, 100, size=n)
    return Batch.from_pydict({
        "k": (T.BIGINT, list(keys)),
        "v": (T.DOUBLE, list(vals)),
    })


def test_repartition_preserves_rows(mesh):
    b = _batch()
    sharded = shard_batch(b, mesh, "dp")

    def step(local):
        return repartition_by_hash(local, [0], "dp", N)

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                            out_specs=P("dp"), check_vma=False))(sharded)
    # every input row lands on exactly one shard
    assert int(jnp.sum(out.row_mask)) == b.host_count()
    got = sorted(out.to_pylist())
    want = sorted(b.to_pylist())
    assert got == want


def test_repartition_colocates_keys(mesh):
    b = _batch()
    sharded = shard_batch(b, mesh, "dp")

    def step(local):
        ex = repartition_by_hash(local, [0], "dp", N)
        # tag each live row with this shard's index
        me = jax.lax.axis_index("dp")
        tag = jnp.where(ex.row_mask, me, -1)
        return ex, tag

    ex, tags = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                                 out_specs=P("dp"), check_vma=False))(sharded)
    rows = ex.to_pylist()
    live = np.asarray(ex.row_mask)
    shard_of = np.asarray(tags)[live]
    key_shard = {}
    for (k, _v), s in zip(rows, shard_of):
        assert key_shard.setdefault(k, s) == s, f"key {k} split across shards"


def test_broadcast(mesh):
    b = _batch(64)
    sharded = shard_batch(b, mesh, "dp")

    def step(local):
        return broadcast_batch(local, "dp")

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                            out_specs=P("dp"), check_vma=False))(sharded)
    # each shard holds a full copy: N copies total
    assert int(jnp.sum(out.row_mask)) == N * b.host_count()


def test_distributed_grouped_agg_matches_local(mesh):
    b = _batch(512, seed=3)
    aggs = [AggSpec("sum", 1, T.DOUBLE, "s"),
            AggSpec("count_star", None, T.BIGINT, "c")]
    local_out = grouped_aggregate(b, [0], aggs, mode="single")
    want = sorted(local_out.to_pylist())

    sharded = shard_batch(b, mesh, "dp")

    def step(local):
        partial = grouped_aggregate(local, [0], aggs, mode="partial")
        ex = repartition_by_hash(partial, [0], "dp", N)
        return grouped_aggregate(ex, [0], aggs, mode="final")

    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                            out_specs=P("dp"), check_vma=False))(sharded)
    got = sorted(out.to_pylist())
    assert len(got) == len(want)
    for (gk, gs, gc), (wk, ws, wc) in zip(got, want):
        assert gk == wk and gc == wc
        assert gs == pytest.approx(ws, rel=1e-12)


def test_partition_ids_in_range():
    b = _batch(128)
    pid = hash_partition_ids(b, [0], N)
    arr = np.asarray(pid)
    assert arr.min() >= 0 and arr.max() < N

"""TPC-DS suite on the 8-device mesh vs the local runner.

Ring-3 coverage for the star-join + grouping-sets shapes TPC-H lacks:
ROLLUP partial states crossing the hash exchange, replicated dimension
builds, and high-cardinality group-bys are exactly the distributed-agg
machinery the reference exercises per connector with its shared suites
(reference presto-tests/.../AbstractTestDistributedQueries + TPC-DS
benchto SQL). Parity with LocalRunner is the contract.
"""
import pytest

from presto_tpu.exec.distributed import DistributedRunner
from presto_tpu.exec.runner import LocalRunner

# minutes of shard_map compiles even with a warm persistent cache: out
# of the serial tier-1 time budget (run explicitly, or with xdist)
pytestmark = pytest.mark.slow

from tpcds_queries import Q as TPCDS_QUERIES
from test_distributed import _norm

SF = 0.01

#: every TPC-DS query the suite carries runs on the mesh (exclusions
#: would be bugs, not configuration)
DIST_QUERIES = list(TPCDS_QUERIES)


@pytest.fixture(scope="module")
def local():
    return LocalRunner(catalog="tpcds", tpch_sf=SF)


@pytest.fixture(scope="module")
def dist(local):
    return DistributedRunner(catalogs=local.session.catalogs,
                             catalog="tpcds", rows_per_batch=1 << 13)


@pytest.mark.parametrize(
    "name,sql,_o", DIST_QUERIES, ids=[t[0] for t in DIST_QUERIES])
def test_tpcds_distributed(local, dist, name, sql, _o):
    """Multiset comparison: several TPC-DS queries order by non-unique
    keys (e.g. q73's cnt desc, c_last_name), so tie order legitimately
    differs between executors; ORDER BY correctness itself is covered by
    the local-vs-SQLite-oracle ring."""
    want = _norm(local.execute(sql).rows, has_order=False)
    got = _norm(dist.execute(sql).rows, has_order=False)
    assert len(got) == len(want)
    for gr, wr in zip(got, want):
        for gv, wv in zip(gr, wr):
            if isinstance(gv, float):
                assert gv == pytest.approx(wv, rel=1e-6, abs=1e-9), (gr, wr)
            else:
                assert gv == wv, (gr, wr)

"""Deterministic interleaving tests (ISSUE 15 tentpole, dynamic half).

Three layers:

- the explorer itself: exhaustive schedule enumeration, preemption
  bounding, seeded sampling, deadlock detection through checked locks,
  failpoint-site glue, guarded-field fail-fast;
- the two historical cache races replayed as red/green pairs — the
  LIVE classes pass every schedule, and fixture-level copies with the
  fix mechanically reverted (a copied method minus the fix, NOT a git
  revert) fail deterministically:
    * PR 8: plan-cache write-epoch veto (a connector write landing
      between epoch capture and put must refuse the insert);
    * PR 12: result-cache partial-hit double-apply (concurrent partial
      hits must merge against their lookup-time snapshot and lose the
      re-stamp race);
- the PR 8 window exercised END-TO-END through the real
  serving/plancache.cached_plan path, scheduled via the declared
  `plancache.plan` failpoint site.
"""
import threading
import types
import weakref
from collections import OrderedDict

import pytest

from presto_tpu._devtools import interleave, lockcheck
from presto_tpu._devtools.interleave import explore, point, sample
from presto_tpu._devtools.lockcheck import (GuardedFieldError, LockGraph,
                                            checked_lock, guarded_by)


# -- explorer mechanics ------------------------------------------------------

def _lost_update_scenario():
    state = {"x": 0}

    def inc():
        v = state["x"]
        point("read")
        state["x"] = v + 1

    def check():
        return None if state["x"] == 2 else f"lost update: x={state['x']}"

    return [inc, inc], check


def test_explore_enumerates_all_schedules_and_finds_the_race():
    ex = explore(_lost_update_scenario)
    assert ex.exhausted
    # 2 threads x 2 segments each: C(4,2) = 6 interleavings
    assert len(ex.schedules) == 6
    assert len(ex.failures) == 4           # every overlapped schedule
    assert all("lost update" in s.error for s in ex.failures)


def test_explore_is_deterministic():
    a = explore(_lost_update_scenario)
    b = explore(_lost_update_scenario)
    assert [s.decisions for s in a.schedules] \
        == [s.decisions for s in b.schedules]
    assert [s.error for s in a.schedules] == [s.error for s in b.schedules]


def test_preemption_bound_prunes_but_keeps_a_failure():
    ex = explore(_lost_update_scenario, preemption_bound=1)
    assert len(ex.schedules) < 6
    assert ex.failures                     # the race needs 1 preemption


def test_sample_replays_bit_for_bit():
    a = sample(_lost_update_scenario, n=12, seed=7)
    b = sample(_lost_update_scenario, n=12, seed=7)
    assert [s.decisions for s in a.schedules] \
        == [s.decisions for s in b.schedules]
    assert sample(_lost_update_scenario, n=12, seed=8).schedules \
        != a.schedules


def test_max_schedules_reports_non_exhaustive():
    ex = explore(_lost_update_scenario, max_schedules=3)
    assert len(ex.schedules) == 3 and not ex.exhausted


def test_checked_lock_deadlock_is_a_finding_not_a_hang():
    def make():
        g = LockGraph()
        a, b = g.lock("IA"), g.lock("IB")

        def t1():
            with a:
                point("has-a")
                with b:
                    pass

        def t2():
            with b:
                point("has-b")
                with a:
                    pass

        return [t1, t2], None

    ex = explore(make)
    assert ex.deadlocks                     # AB/BA executed -> deadlock
    assert any("deadlock" in s.error for s in ex.failures)
    # well-ordered schedules (one thread finishes first) stay clean
    assert any(s.error is None for s in ex.schedules)


def test_locks_serialize_correctly_under_the_scheduler():
    # same increment race, but properly locked: every schedule clean
    def make():
        lk = checked_lock("interleave.serialize")
        state = {"x": 0}

        def inc():
            point("before")
            with lk:
                v = state["x"]
                state["x"] = v + 1

        def check():
            return None if state["x"] == 2 else f"x={state['x']}"

        return [inc, inc], check

    explore(make).assert_clean()


def test_failpoints_as_points_schedule_engine_sites():
    from presto_tpu.exec.failpoints import FailpointRegistry
    reg = FailpointRegistry()               # synthetic sites allowed
    hits = []

    def make():
        log = []

        def worker():
            reg.hit("synthetic.window", key="w")
            log.append("worked")

        def other():
            log.append("other")

        def check():
            hits.append(tuple(log))
            return None

        return [worker, other], check

    with interleave.failpoints_as_points(["synthetic.window"],
                                         registry=reg):
        ex = explore(make)
    ex.assert_clean()
    # the failpoint became a real scheduling point: both orders ran
    assert {h for h in hits} >= {("worked", "other"),
                                 ("other", "worked")}


def test_point_is_noop_outside_exploration():
    point("nobody-listening")               # must not raise or block


# -- guarded fields ----------------------------------------------------------

def test_guarded_field_fails_fast_without_lock():
    g = LockGraph()

    class Box:
        data = guarded_by("box.lock", graph=g)

        def __init__(self):
            self._lock = g.lock("box.lock")
            self.data = {}                  # first write: init, exempt

    b = Box()
    with b._lock:
        b.data["k"] = 1                     # guarded read under lock: ok
        assert b.data["k"] == 1
    with pytest.raises(GuardedFieldError):
        _ = b.data                          # read without the lock
    with pytest.raises(GuardedFieldError):
        b.data = {}                         # re-bind without the lock
    assert any("guarded field" in v for v in g.check())


def test_guarded_field_attr_form_resolves_per_instance():
    g = LockGraph()

    class Cache:
        entries = guarded_by(attr="_lock", graph=g)

        def __init__(self, name):
            self._lock = g.lock(name)
            self.entries = OrderedDict()

    a, b = Cache("cache.a"), Cache("cache.b")
    with a._lock:
        assert a.entries == OrderedDict()   # a's name satisfies a
        with pytest.raises(GuardedFieldError):
            _ = b.entries                   # but not b

    with b._lock:
        assert b.entries == OrderedDict()


def test_engine_caches_are_guard_annotated():
    from presto_tpu.exec.scancache import ScanCache
    from presto_tpu.serving.plancache import IdentMemo, PlanCache
    from presto_tpu.serving.resultcache import ResultCache
    for cls, fields in ((ScanCache, ("_entries", "_inflight")),
                        (PlanCache, ("_entries", "_epoch")),
                        (ResultCache, ("_entries", "_epoch")),
                        (IdentMemo, ("_entries",))):
        for f in fields:
            d = getattr(cls, f)
            assert type(d).__name__ == "_GuardedField", (cls, f)
            assert d.check is lockcheck.ENABLED


def test_engine_cache_guard_trips_on_unlocked_poke():
    from presto_tpu.serving.plancache import PlanCache
    c = PlanCache(lock_name="interleave.guardprobe")
    assert len(c) == 0                      # locked paths work
    with pytest.raises(GuardedFieldError):
        _ = c._entries                      # unlocked direct poke fails
    # scrub the recorded violation: it was deliberate, and the serving
    # suites assert a clean process graph
    with lockcheck.GRAPH._mu:
        lockcheck.GRAPH.violations[:] = [
            v for v in lockcheck.GRAPH.violations
            if "interleave.guardprobe" not in v]


# -- PR 8: plan-cache write-epoch race (fixture-level revert) ----------------

class _FakeConn:
    """data_version-bearing stand-in: bump() is 'a write landed'."""

    def __init__(self):
        self._v = 0

    def data_version(self, table):
        return self._v

    def bump(self):
        self._v += 1


def _mk_plan_caches():
    from presto_tpu.serving.plancache import PlanCache, _Entry

    class _Harness(PlanCache):
        """Real PlanCache over the fake connector's dep stamps."""

        def __init__(self, conn):
            super().__init__(lock_name="interleave.plancache")
            self._conn = conn

        def _plan_deps(self, plan, session):
            return [(weakref.ref(self._conn), "c", "t",
                     self._conn.data_version("t"))]

    class _NoVeto(_Harness):
        """PR 8 fix mechanically reverted: a fixture-level copy of
        PlanCache.put WITHOUT the epoch comparison (the pre-fix code
        shape — deps stamped post-plan validate a stale plan)."""

        def put(self, key, plan, session, epoch=None, payload=None):
            deps = self._plan_deps(plan, session)
            if deps is None:
                return False
            with self._lock:
                # (reverted) if epoch is not None and epoch != self._epoch:
                #     return False
                if key in self._entries:
                    return True
                self._entries[key] = _Entry(
                    payload if payload is not None else plan, deps)
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
                return True

    return _Harness, _NoVeto


def _plan_epoch_scenario(cache_cls):
    """One planner capturing its epoch then 'optimizing' (reading the
    connector's stats version) then inserting; one writer bumping the
    version mid-air. Invariant: a SERVED plan was never built against
    a version older than the data."""
    def make():
        conn = _FakeConn()
        cache = cache_cls(conn)
        key = b"q1"

        def planner():
            epoch = cache.epoch()
            point("epoch-captured")
            built_against = conn.data_version("t")   # optimizer stats
            point("planned")
            cache.put(key, {"built": built_against}, session=None,
                      epoch=epoch)

        def writer():
            point("about-to-write")
            conn.bump()
            cache.note_write()
            cache.invalidate(conn, "t")

        def check():
            served = cache.get(key)
            now = conn.data_version("t")
            if served is not None and served["built"] != now:
                return (f"stale plan served: built against "
                        f"v{served['built']}, data at v{now}")
            return None

        return [planner, writer], check

    return make


def test_plan_cache_epoch_veto_green_on_live_class():
    harness, _noveto = _mk_plan_caches()
    ex = explore(_plan_epoch_scenario(harness))
    assert ex.exhausted
    ex.assert_clean()


def test_plan_cache_epoch_race_red_when_fix_reverted():
    _harness, noveto = _mk_plan_caches()
    ex = explore(_plan_epoch_scenario(noveto))
    assert ex.failures, "reverting the epoch veto must reproduce PR 8"
    assert any("stale plan served" in s.error for s in ex.failures)
    # and the exact interleaving is the documented one: write lands
    # between epoch capture and put
    bad = ex.failures[0]
    labels = [lbl for _i, lbl in bad.trace]
    assert "planned" in labels and "about-to-write" in labels


# -- PR 12: result-cache partial-hit double-apply (fixture-level revert) ------

def _mk_result_caches():
    from presto_tpu.serving import resultcache as RC

    class _Fixed(RC.ResultCache):
        pass

    class _NoSnapshot(RC.ResultCache):
        """PR 12 fix mechanically reverted: update() is a fixture-level
        copy WITHOUT the base_deps compare, so a merge computed against
        a superseded base can re-stamp over a newer state."""

        def update(self, ph, result, subplan_rows):
            size = (RC._rows_bytes(result.rows)
                    + RC._rows_bytes(subplan_rows) + 1024)
            with self._lock:
                if ph.epoch != self._epoch:
                    return False
                e = self._entries.get(ph.key)
                if e is not ph.entry:
                    return False
                # (reverted) if e.deps != ph.base_deps: return False
                if size > self.pool.limit:
                    del self._entries[ph.key]
                    e.ctx.close()
                    return False
                e.rows = list(result.rows)
                e.subplan_rows = subplan_rows
                e.deps = list(ph.fresh_deps)
                self._account_locked(e, size)
                return True

    return _Fixed, _NoSnapshot


class _FileConn:
    """filebase-shaped version tokens: (seq, ((relpath, mtime), ...))."""

    def __init__(self):
        self.files = {"a.csv": 1.0}

    def data_version(self, table):
        return (0, tuple(sorted(self.files.items())))

    def add_file(self, name):
        self.files[name] = 2.0


def _res(rows):
    return types.SimpleNamespace(rows=rows, names=["g", "s"],
                                 types=["varchar", "bigint"])


def _partial_scenario(cache_cls, snapshot_base):
    """Two readers resolve a partial hit on one entry (base sum 10,
    append-only delta +5) and race the delta merge + re-stamp.
    ``snapshot_base=False`` additionally reverts the lookup-time
    snapshot (the second half of the PR 12 fix): the merge reads the
    LIVE entry rows at merge time. Invariant: the entry must end at
    15, never 20 (delta applied twice)."""
    from presto_tpu.serving import resultcache as RC

    def make():
        conn = _FileConn()
        rc = cache_cls()
        key = b"standing-query"
        spec = RC.IncrementalSpec(agg=None, dep_index=0, catalog="c",
                                  table="t", n_keys=1,
                                  agg_cols=((1, "sum"),))
        deps = [(weakref.ref(conn), "c", "t",
                 RC._freeze(conn.data_version("t")))]
        assert rc.put(key, _res([("g", 10)]), deps, rc.epoch(),
                      subplan_rows=[("g", 10)], spec=spec, plan=None)
        conn.add_file("b.csv")              # append-only drift: +5

        def reader():
            outcome, ph = rc.get(key)
            if outcome != "partial":
                return                      # lost the re-stamp race
            point("looked-up")
            base = (ph.base_subplan if snapshot_base
                    else ph.entry.subplan_rows)
            merged = RC.merge_subplan_rows(ph.spec, base, [("g", 5)])
            point("merged")
            rc.update(ph, _res(merged), merged)

        def check():
            # the closure keeps `conn` alive: entry deps are weakrefs,
            # and a collected connector reads as a dead dep (= miss)
            assert conn.files
            outcome, e = rc.get(key)
            if outcome != "hit":
                return f"entry lost: {outcome}"
            if list(e.rows) != [("g", 15)]:
                return (f"delta double-applied: {list(e.rows)} "
                        f"(base 10 + one delta of 5 must be 15)")
            return None

        return [reader, reader], check

    return make


def test_result_cache_partial_green_on_live_class():
    fixed, _nosnap = _mk_result_caches()
    ex = explore(_partial_scenario(fixed, snapshot_base=True))
    assert ex.exhausted
    ex.assert_clean()


def test_result_cache_double_apply_red_when_fix_reverted():
    _fixed, nosnap = _mk_result_caches()
    ex = explore(_partial_scenario(nosnap, snapshot_base=False))
    assert ex.failures, \
        "reverting the base-snapshot fix must reproduce PR 12"
    assert any("double-applied" in s.error for s in ex.failures)


# -- PR 8 window end-to-end through the real cached_plan path ----------------

@pytest.fixture(scope="module")
def plan_runner():
    from presto_tpu.exec.runner import LocalRunner
    r = LocalRunner(tpch_sf=0.01)
    r.execute("create table memory.ilv as select 1 as x")
    return r


def test_engine_cached_plan_epoch_window_via_failpoint(plan_runner):
    """The declared `plancache.plan` failpoint site turns the REAL
    cached_plan epoch window into a scheduling point: a memory-table
    write landing inside it must veto the insert (entry absent), a
    write before it must not stop caching, and a write after it must
    eagerly invalidate — all three interleavings, one exploration."""
    from presto_tpu.exec.failpoints import FAILPOINTS
    from presto_tpu.serving.plancache import (PLANS, PlanCache,
                                              parse_cached)
    r = plan_runner
    conn = r.session.catalogs.get("memory")
    sql = "select count(*) from memory.ilv"
    stmt = parse_cached(sql)
    key = PlanCache.fingerprint(stmt, r.session)
    holder = {}

    FAILPOINTS.configure(
        "plancache.plan", action="callback", times=None,
        callback=lambda key="", **kw: (holder["log"].append("window"),
                                       point("plancache.plan")))
    try:
        def make():
            from presto_tpu.serving.plancache import cached_plan
            PLANS.clear()
            log = holder["log"] = []

            def planner():
                plan = cached_plan(stmt, r.session)
                assert plan is not None     # veto never loses the query

            def writer():
                conn.append("ilv", conn.tables["ilv"][0])
                log.append("wrote")

            def check():
                cached = PLANS.get(key) is not None
                if "window" not in log:
                    return "warm hit: the per-run clear() didn't miss"
                if log.index("wrote") < log.index("window"):
                    # write fully preceded the epoch capture: the
                    # insert is clean and must have landed
                    return None if cached else \
                        "clean insert refused (veto misfired)"
                # write landed mid-window (veto) or after the insert
                # (eager invalidation): either way the entry must be
                # gone — a cached entry here is the PR 8 TOCTOU
                return ("stale plan cached despite a post-epoch write"
                        if cached else None)

            return [planner, writer], check

        ex = explore(make, max_schedules=16)
        ex.assert_clean()
        assert ex.exhausted
    finally:
        FAILPOINTS.clear("plancache.plan")


# -- the process lock graph stayed clean through all of the above ------------

def test_interleave_suite_leaves_lock_graph_clean():
    assert lockcheck.GRAPH.check() == [], lockcheck.GRAPH.check()

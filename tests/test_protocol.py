"""Statement protocol over real HTTP (ring-3: real server, real sockets).

The analogue of the reference's TestingPrestoServer-based protocol tests
(reference presto-tests/.../DistributedQueryRunner.java boots real HTTP
servers; presto-client/.../StatementClientV1.java:147,339 is the client
loop being exercised here)."""
import json
import urllib.request

import pytest

from presto_tpu.client import QueryFailed, StatementClient
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.server import PrestoTpuServer


@pytest.fixture(scope="module")
def server():
    srv = PrestoTpuServer(LocalRunner(tpch_sf=0.001))
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    return StatementClient(f"http://127.0.0.1:{server.port}")


def test_simple_query(server, client):
    res = client.execute("select n_name, n_regionkey from nation "
                         "order by n_name limit 3")
    assert [c[0] for c in res.columns] == ["n_name", "n_regionkey"]
    assert len(res.rows) == 3
    assert res.rows[0][0] == "ALGERIA"
    # results match the in-process runner
    direct = server.runner.execute(
        "select n_name, n_regionkey from nation order by n_name limit 3")
    assert [list(r) for r in res.rows] == \
        [[v if not hasattr(v, "item") else v.item() for v in r]
         for r in direct.rows]


def test_multi_page(server, client):
    res = client.execute("select l_orderkey from lineitem")
    direct = server.runner.execute("select count(*) from lineitem")
    assert len(res.rows) == direct.rows[0][0]


def test_error_surfaces_as_query_error(server, client):
    with pytest.raises(QueryFailed) as ei:
        client.execute("select bogus_column from nation")
    assert "bogus_column" in str(ei.value)


def test_session_roundtrip(server, client):
    client.execute("set session join_distribution_type = 'broadcast'")
    assert client.session_properties.get("join_distribution_type") \
        == "broadcast"
    # the override rides X-Presto-Session on later requests and is
    # restored server-side after each statement
    res = client.execute("show session")
    client.execute("reset session join_distribution_type")
    assert "join_distribution_type" not in client.session_properties


def test_raw_protocol_shape(server):
    """The wire documents look like the reference's QueryResults."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/v1/statement",
        data=b"select 1", method="POST",
        headers={"X-Presto-User": "test"})
    with urllib.request.urlopen(req) as resp:
        doc = json.loads(resp.read())
    assert set(doc) >= {"id", "infoUri", "nextUri", "stats"}
    with urllib.request.urlopen(doc["nextUri"]) as resp:
        doc2 = json.loads(resp.read())
    assert doc2["columns"][0]["type"] == "bigint"
    assert doc2["data"] == [[1]]


def test_cancel(server, client):
    doc = StatementClient(f"http://127.0.0.1:{server.port}")
    pages = doc.pages("select count(*) from lineitem")
    first = next(pages)
    req = urllib.request.Request(first["nextUri"], method="DELETE")
    urllib.request.urlopen(req)
    q = server.queries[first["id"]]
    assert q.state == "FAILED"

"""Statement protocol over real HTTP (ring-3: real server, real sockets).

The analogue of the reference's TestingPrestoServer-based protocol tests
(reference presto-tests/.../DistributedQueryRunner.java boots real HTTP
servers; presto-client/.../StatementClientV1.java:147,339 is the client
loop being exercised here)."""
import json
import urllib.request

import pytest

from presto_tpu.client import QueryFailed, StatementClient
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.server import PrestoTpuServer


@pytest.fixture(scope="module")
def server():
    srv = PrestoTpuServer(LocalRunner(tpch_sf=0.001))
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    return StatementClient(f"http://127.0.0.1:{server.port}")


def test_simple_query(server, client):
    res = client.execute("select n_name, n_regionkey from nation "
                         "order by n_name limit 3")
    assert [c[0] for c in res.columns] == ["n_name", "n_regionkey"]
    assert len(res.rows) == 3
    assert res.rows[0][0] == "ALGERIA"
    # results match the in-process runner
    direct = server.runner.execute(
        "select n_name, n_regionkey from nation order by n_name limit 3")
    assert [list(r) for r in res.rows] == \
        [[v if not hasattr(v, "item") else v.item() for v in r]
         for r in direct.rows]


def test_multi_page(server, client):
    res = client.execute("select l_orderkey from lineitem")
    direct = server.runner.execute("select count(*) from lineitem")
    assert len(res.rows) == direct.rows[0][0]


def test_error_surfaces_as_query_error(server, client):
    with pytest.raises(QueryFailed) as ei:
        client.execute("select bogus_column from nation")
    assert "bogus_column" in str(ei.value)


def test_session_roundtrip(server, client):
    # a DECLARED property (config.SESSION_PROPERTIES): SET SESSION now
    # validates against the registry, so the old undeclared
    # join_distribution_type would be rejected server-side
    client.execute("set session retry_policy = 'QUERY'")
    assert client.session_properties.get("retry_policy") == "QUERY"
    # the override rides X-Presto-Session on later requests and is
    # restored server-side after each statement
    res = client.execute("show session")
    client.execute("reset session retry_policy")
    assert "retry_policy" not in client.session_properties


def test_set_session_unknown_property_is_query_error(server, client):
    with pytest.raises(QueryFailed) as ei:
        client.execute("set session join_distribution_type = 'b'")
    assert "unknown session property" in str(ei.value)


def test_raw_protocol_shape(server):
    """The wire documents look like the reference's QueryResults. Fast
    statements may inline their page(s) into the POST response (the
    single-round-trip path); slower ones chain through nextUri — either
    way the data and column metadata arrive in QueryResults shape."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/v1/statement",
        data=b"select 1", method="POST",
        headers={"X-Presto-User": "test"})
    with urllib.request.urlopen(req) as resp:
        doc = json.loads(resp.read())
    assert set(doc) >= {"id", "infoUri", "stats"}
    while "data" not in doc:
        assert "nextUri" in doc
        with urllib.request.urlopen(doc["nextUri"]) as resp:
            doc = json.loads(resp.read())
    assert doc["columns"][0]["type"] == "bigint"
    assert doc["data"] == [[1]]


def test_cancel():
    """DELETE-cancel must interrupt a RUNNING query, not just mark state:
    the scan below is deterministically slow (>= 4s of per-batch delays),
    so the cancel always lands mid-execution, and the executor's per-batch
    cancel check (exec/local.py _check_cancel) must stop the producer
    thread long before the scan could finish (reference
    dispatcher/DispatchManager.java:134 cancel semantics)."""
    import time

    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.connectors.tpch import TpchConnector

    class _SlowConnector:
        def __init__(self, inner, delay_s):
            self._inner = inner
            self.name = inner.name
            self.delay_s = delay_s

        @property
        def metadata(self):
            return self._inner.metadata

        @property
        def split_manager(self):
            return self._inner.split_manager

        def page_source(self, split, columns, pushdown=None,
                        rows_per_batch=1 << 17):
            inner = self._inner.page_source(
                split, columns, pushdown=pushdown,
                rows_per_batch=rows_per_batch)
            delay = self.delay_s

            class _PS:
                def batches(self):
                    for b in inner.batches():
                        time.sleep(delay)
                        yield b
            return _PS()

    catalogs = CatalogManager()
    catalogs.register("tpch", _SlowConnector(TpchConnector(sf=0.001), 0.05))
    srv = PrestoTpuServer(LocalRunner(catalogs=catalogs,
                                      rows_per_batch=64))
    srv.start()
    try:
        doc = StatementClient(f"http://127.0.0.1:{srv.port}")
        pages = doc.pages("select count(*) from lineitem")
        first = next(pages)
        q = srv.queries[first["id"]]
        deadline = time.time() + 10
        while q.state == "QUEUED" and time.time() < deadline:
            time.sleep(0.01)
        assert q.state == "RUNNING"      # slow scan: cancel lands mid-run
        t0 = time.time()
        req = urllib.request.Request(first["nextUri"], method="DELETE")
        urllib.request.urlopen(req)
        assert q.state == "FAILED"
        assert q.error["errorName"] == "USER_CANCELED"
        # the producer must be interrupted promptly: the remaining scan
        # alone would take seconds of injected delay
        assert q.done.wait(timeout=3.0)
        assert time.time() - t0 < 3.0
        assert q.state == "FAILED"       # completion must not overwrite
    finally:
        srv.stop()


def test_query_detail_stats_endpoint():
    """GET /v1/query/{id} returns per-node wall/batches and split events
    (reference server/QueryResource.java + event/SplitMonitor.java)."""
    import json
    import urllib.request

    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.server.protocol import StatementServer

    srv = StatementServer(LocalRunner(tpch_sf=0.001))
    srv.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/statement",
            data=b"select count(*) from lineitem where l_quantity > 10")
        doc = json.loads(urllib.request.urlopen(req).read())
        while "nextUri" in doc:
            doc = json.loads(urllib.request.urlopen(doc["nextUri"]).read())
        qs = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/query").read())
        qid = qs[0]["queryId"]
        detail = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/v1/query/{qid}").read())
        assert detail["state"] == "FINISHED"
        names = [n["node"] for n in detail["nodes"]]
        assert "TableScan" in names
        scan = next(n for n in detail["nodes"] if n["node"] == "TableScan")
        assert scan["batches"] >= 1 and scan["wallMs"] >= 0
        assert detail["splits"] and detail["splits"][0]["table"] == "lineitem"
        missing = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/query/nope")
        try:
            urllib.request.urlopen(missing)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_split_completed_events():
    from presto_tpu.exec.runner import LocalRunner

    r = LocalRunner(tpch_sf=0.001)
    seen = []
    r.events.register_split_listener(seen.append)
    r.execute("select count(*) from orders")
    assert seen and seen[0].table == "orders" and seen[0].batches >= 1

"""Mesh flight recorder (obs/flight.py): per-round wall-clock
attribution for the SPMD exchange path.

The contract under test: every host-observable event on the mesh path
(dispatch, staging, control sync, re-split, repartition, prefetch
stall) lands in the active FlightRecorder as a timestamped round
record; `finish()` reconciles the round timeline against measured wall
into the six named buckets plus a per-shard critical path; and every
surface that re-renders the timeline — EXPLAIN ANALYZE's "Mesh rounds"
section, `system.runtime.mesh_rounds`, the completed-queries history
columns, the metric families — agrees row-exactly with the recorder.

The harness forces the mesh (`mesh_execution=on`) so n=1 also flies:
the single-shard flight is the degenerate baseline the attribution
must still reconcile. Warm runs (second execution, compiles cached)
are the measured ones — cold-run tracing/setup wall that happens
outside the instrumented sites is exactly the unattributed remainder
the recorder reports honestly instead of inventing.
"""
import json
import os
import re
import sys
import time

import pytest

from presto_tpu.exec.failpoints import FAILPOINTS
from presto_tpu.exec.runner import LocalRunner
from presto_tpu.obs import flight
from presto_tpu.obs.flight import (BUCKETS, FLIGHTS, KIND_BUCKET,
                                   FlightRecorder, chrome_events)
from presto_tpu.obs.metrics import REGISTRY

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SF = 0.005

#: the MULTICHIP q1sql shape (bench.py _TPCH_Q1): scan-heavy grouped
#: aggregation — the per-batch dispatch + partial-state exchange path
Q1 = ("select l_returnflag, l_linestatus, sum(l_quantity), "
      "sum(l_extendedprice), avg(l_discount), count(*) from lineitem "
      "where l_shipdate <= date '1998-09-02' "
      "group by l_returnflag, l_linestatus order by 1, 2")

#: the MULTICHIP q27 shape (bench.py _DS_Q27): 5-way star join +
#: ROLLUP partial states crossing the hash exchange
Q27 = ("select i_item_id, s_state, grouping(s_state) g_state, "
       "avg(ss_quantity) agg1, avg(ss_list_price) agg2, "
       "avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4 "
       "from store_sales, customer_demographics, date_dim, store, item "
       "where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
       "and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk "
       "and cd_gender = 'M' and cd_marital_status = 'S' "
       "and cd_education_status = 'College' and d_year = 2002 "
       "and s_state in ('TN', 'TN', 'TN', 'TN', 'TN', 'TN') "
       "group by rollup (i_item_id, s_state) "
       "order by i_item_id nulls last, s_state nulls last limit 100")


def _props(n, **extra):
    # "on" (not "auto") so the 1-device flight exists too — auto would
    # route n<2 to the single-device path with no recorder
    return {"mesh_execution": "on", "mesh_devices": n, **extra}


@pytest.fixture(scope="module")
def tpch():
    return LocalRunner(tpch_sf=SF, rows_per_batch=1 << 11)


@pytest.fixture(scope="module")
def tpcds():
    return LocalRunner(catalog="tpcds", tpch_sf=SF,
                       rows_per_batch=1 << 11)


def _fly(runner, sql, n, warm=True, **extra):
    """Execute on a forced n-device mesh and return (result, flight).
    ``warm`` pays one untimed run first so compiles are cached and the
    measured flight is the steady-state one (bench.py's warmup
    discipline)."""
    if warm:
        runner.execute(sql, properties=_props(n, **extra))
    before = FLIGHTS.snapshot()
    res = runner.execute(sql, properties=_props(n, **extra))
    after = FLIGHTS.snapshot()
    # identity, not length: the ring holds 32 flights, and a long
    # in-process suite run legitimately arrives here with it full
    assert after and (not before or after[-1] is not before[-1]), \
        "run did not produce a flight"
    return res, after[-1]


# -- attribution reconciliation (the acceptance criterion) --------------------

@pytest.mark.parametrize("n", [1, 2, 4])
def test_q1_reconciles_and_reports_dominant(tpch, n):
    _, fl = _fly(tpch, Q1, n)
    a = fl.attribution
    assert a is not None
    assert a["n_devices"] == n
    assert a["rounds"] > 0
    # buckets reconcile to >= 90% of measured wall on the warm run, OR
    # the unattributed remainder is bounded in ABSOLUTE terms: the
    # fused exchange + cross-query program cache cut q1's warm wall to
    # tens of milliseconds, where the recorder's few ms of per-record
    # host glue (batch iteration, python dispatch) is a large share of
    # a tiny number — the contract that matters is that the glue stays
    # small, not that it shrinks with the wall
    unattributed = a["wall_s"] * (100.0 - a["reconciled_pct"]) / 100.0
    assert a["reconciled_pct"] >= 90.0 or unattributed <= 0.25, a
    assert abs(sum(a["buckets"].values())
               - a["wall_s"] * a["reconciled_pct"] / 100.0) < 0.05 \
        or a["reconciled_pct"] == 100.0
    # dominant bucket reported per (query, n), and it is the max
    assert a["dominant_bucket"] in BUCKETS
    assert a["buckets"][a["dominant_bucket"]] == \
        max(a["buckets"].values())
    # critical path: one entry per shard, slowest shard is the argmax
    cp = a["critical_path"]
    assert len(cp["per_shard_s"]) == n
    assert cp["per_shard_s"][cp["slowest_shard"]] == \
        max(cp["per_shard_s"])
    # per-shard path never exceeds total bucketed wall (rounds gate
    # shards at most fully); each bucket is independently rounded to
    # 6 decimals, so the sum can trail the true wall by half an ULP
    # per bucket — the slack must cover that, not just float noise
    slack = (len(a["buckets"]) + 1) * 5e-7
    assert max(cp["per_shard_s"]) <= sum(a["buckets"].values()) + slack


@pytest.mark.slow
@pytest.mark.parametrize("n", [1, 2, 4])
def test_q27_reconciles_and_reports_dominant(tpcds, n):
    # the second MULTICHIP acceptance query: a 5-way join + rollup is
    # minutes of shard_map compiles across the n sweep, so this rides
    # the slow tier; the committed MULTICHIP_r07 pin carries the same
    # evidence (97.9/96.6% reconciled at n=2/4) inside tier-1 via the
    # gate smoke.  The fused exchange + program cache cut q27's warm
    # wall ~3x while the per-record host glue (a few ms of python
    # between ~600 records) stayed put, so the share-based floor moves:
    # the contract is 85% reconciled OR the unattributed remainder
    # bounded absolutely at a few ms per record.
    _, fl = _fly(tpcds, Q27, n)
    a = fl.attribution
    assert a["n_devices"] == n
    unattributed = a["wall_s"] * (100.0 - a["reconciled_pct"]) / 100.0
    assert a["reconciled_pct"] >= 85.0 or unattributed <= 3.0, a
    assert a["dominant_bucket"] in BUCKETS
    assert len(a["critical_path"]["per_shard_s"]) == n


# -- round counts vs the exchange's own accounting ----------------------------

#: hash-partitioned join (broadcast suppressed): the shape whose probe
#: stream still crosses the exchange every round — Q1's fused partial
#: states no longer repartition AT ALL, so the exchange-ledger
#: invariants need a join to stay live
QJOIN = ("select c_name, sum(o_totalprice) from customer "
         "join orders on c_custkey = o_custkey "
         "group by 1 order by 2 desc, 1 limit 5")
_QJOIN_PROPS = {"broadcast_join_row_limit": 1}


def test_round_counts_match_exchange_rounds(tpch):
    # pay compiles first
    tpch.execute(QJOIN, properties=_props(4, **_QJOIN_PROPS))
    ship0 = REGISTRY.value("exchange_repartitions_total")
    resplit0 = REGISTRY.value("mesh_repartition_resplit_total")
    _, fl = _fly(tpch, QJOIN, 4, warm=False, **_QJOIN_PROPS)
    shipped = REGISTRY.value("exchange_repartitions_total") - ship0
    resplits = REGISTRY.value("mesh_repartition_resplit_total") \
        - resplit0
    kinds = [r["kind"] for r in fl.records()]
    assert kinds.count("repartition") == int(shipped) > 0
    assert kinds.count("resplit") == int(resplits)
    # round indices are the record sequence, dense from 0
    assert [r["round"] for r in fl.records()] == \
        list(range(len(kinds)))
    # every kind maps onto a declared bucket
    assert all(KIND_BUCKET[k] in BUCKETS for k in kinds)


def test_fused_q1_has_no_exchange_rounds(tpch):
    """The tentpole, observable in the ledger: Q1's stats-bounded
    grouped aggregation rides the fused wave programs and the gathered
    finisher, so NO partial state crosses a repartition round."""
    tpch.execute(Q1, properties=_props(4))
    ship0 = REGISTRY.value("exchange_repartitions_total")
    _, fl = _fly(tpch, Q1, 4, warm=False)
    assert REGISTRY.value("exchange_repartitions_total") == ship0
    kinds = [r["kind"] for r in fl.records()]
    assert kinds.count("repartition") == 0
    assert kinds.count("dispatch") > 0
    # fused multi-round dispatches: device rounds outnumber host records
    a = fl.attribution
    assert a["device_rounds"] >= a["rounds"]


# -- EXPLAIN ANALYZE section vs system.runtime.mesh_rounds --------------------

def test_explain_analyze_matches_system_table(tpch):
    res = tpch.execute("explain analyze " + Q1, properties=_props(2))
    text = "\n".join(r[0] for r in res.rows)
    assert "Mesh rounds:" in text
    assert "Mesh verdict:" in text and "dominates" in text
    fl = FLIGHTS.last()
    m = re.search(r"Mesh rounds: (\d+) rounds on (\d+) devices", text)
    assert m and int(m.group(1)) == fl.attribution["rounds"]
    assert int(m.group(2)) == 2

    # the per-round table in the text, row-exact against the system
    # table (same renderer, obs/flight.round_rows — but prove it
    # end-to-end through SQL)
    rows = tpch.execute(
        "select round, stage, kind, bucket, rows, bytes, loads, rounds "
        "from system.runtime.mesh_rounds "
        f"where query_id = '{fl.query_id}'").rows
    assert len(rows) == fl.attribution["rounds"]
    printed = re.findall(
        r"^\s+(\d+)\s+(-?\d+)\s+(\w+)\s+(\w+)\s+[\d,.]+\s+(\d+)"
        r"\s+(\d+)\s*(\S*)\s+(\d+)\s*$", text, re.M)
    assert len(printed) == len(rows)
    for p, r in zip(printed, rows):
        assert (int(p[0]), int(p[1]), p[2], p[3]) == \
            (r[0], r[1], r[2], r[3])
        assert (int(p[4]), int(p[5])) == (r[4], r[5])
        assert p[6] == (r[6] or "")
        assert int(p[7]) == r[7]    # device rounds inside the dispatch


def test_completed_queries_carries_attribution(tpch):
    _, fl = _fly(tpch, Q1, 2, warm=False)
    # query ids restart per runner instance, so the process-global
    # history can hold same-named records from other suites' runners —
    # our run is the one whose bucket JSON matches the flight exactly
    rows = tpch.execute(
        "select mesh_rounds, mesh_dominant_bucket, mesh_overhead_ms, "
        "mesh_buckets from system.runtime.completed_queries "
        f"where query_id = '{fl.query_id}'").rows
    want = json.dumps(fl.attribution["buckets"], sort_keys=True)
    ours = [r for r in rows if r[3] == want]
    assert len(ours) == 1, rows
    rounds, dominant, overhead_ms, buckets_json = ours[0]
    assert rounds == fl.attribution["rounds"]
    assert dominant == fl.attribution["dominant_bucket"]
    assert overhead_ms == pytest.approx(
        fl.attribution["overhead_s"] * 1e3, abs=0.01)
    assert sorted(json.loads(buckets_json)) == sorted(BUCKETS)
    # non-mesh queries carry the zero/NULL tail, not stale data
    tpch.execute("select 17 * 3")
    rows = tpch.execute(
        "select mesh_rounds, mesh_dominant_bucket from "
        "system.runtime.completed_queries "
        "where query = 'select 17 * 3'").rows
    assert rows[-1][0] == 0 and rows[-1][1] is None


# -- failpoint-injected stall lands in the right bucket -----------------------

def test_injected_repartition_sleep_attributed(tpch):
    # Q1 no longer repartitions at all on the fused plane — the
    # failpoint needs a hash-partitioned join to fire
    _, green = _fly(tpch, QJOIN, 2, **_QJOIN_PROPS)
    # the sleep must dwarf run-to-run ship-wall noise (a warm
    # repartition round drifts by a few hundred ms under load), so the
    # delta assertion below stays deterministic
    FAILPOINTS.configure("mesh.repartition", action="sleep",
                         sleep_s=2.0, times=1)
    try:
        _, red = _fly(tpch, QJOIN, 2, warm=False, **_QJOIN_PROPS)
    finally:
        FAILPOINTS.clear("mesh.repartition")
    assert FAILPOINTS.triggers("mesh.repartition") == 0  # cleared
    g = green.attribution["buckets"]
    r = red.attribution["buckets"]
    # the injected 2s shows up in repartition — not smeared into
    # sync/stall/staging (red/green on the attribution)
    assert r["repartition"] - g["repartition"] >= 1.0, (g, r)
    for other in ("control_sync", "stall", "host_staging"):
        assert r[other] - g[other] < 1.0, (other, g, r)


# -- recording cost stays under 1% of query wall ------------------------------

def test_recorder_overhead_under_one_percent(tpch):
    _, fl = _fly(tpch, Q1, 2, warm=False)
    a = fl.attribution
    # microbench the per-record cost (no flaky A/B wall diffing): a
    # real query's round count times the measured per-record cost must
    # stay under 1% of its measured wall
    bench = FlightRecorder("overhead_bench", 4)
    n = 5000
    t0 = time.perf_counter()
    for i in range(n):
        bench.record("dispatch", stage=1, wall=1e-4, rows=10,
                     nbytes=100)
    per_record = (time.perf_counter() - t0) / n
    assert per_record * a["rounds"] < 0.01 * a["wall_s"], \
        (per_record, a["rounds"], a["wall_s"])
    # finish() is once per query and its cost is per-record (bucket
    # sums + histogram observes): scale the 5000-record measurement
    # down to the real query's round count, same as above
    t0 = time.perf_counter()
    bench.finish(1.0)
    per_record_finish = (time.perf_counter() - t0) / n
    assert per_record_finish * a["rounds"] < 0.01 * a["wall_s"], \
        (per_record_finish, a["rounds"], a["wall_s"])


# -- session property / metric families / cross-surface registries ------------

def test_mesh_flight_off_skips_recording(tpch):
    flights0 = REGISTRY.value("mesh_flight_queries_total")
    last0 = FLIGHTS.last()
    res = tpch.execute(Q1, properties=_props(2, mesh_flight=False))
    assert res.rows
    assert REGISTRY.value("mesh_flight_queries_total") == flights0
    assert FLIGHTS.last() is last0
    # and EXPLAIN ANALYZE shows no mesh section for the off run
    res = tpch.execute("explain analyze " + Q1,
                       properties=_props(2, mesh_flight=False))
    assert "Mesh rounds:" not in "\n".join(r[0] for r in res.rows)


def test_metric_families_populated(tpch):
    _fly(tpch, Q1, 2, warm=False)
    # Q1's fused plane finishes off an all-gather with ZERO exchange
    # rounds, so the repartition family needs a query that actually
    # ships a hash exchange
    _fly(tpch, QJOIN, 2, warm=False, **_QJOIN_PROPS)
    assert REGISTRY.value("mesh_flight_queries_total") > 0
    assert REGISTRY.value("mesh_rounds_total") > 0
    assert REGISTRY.value("mesh_round_seconds.count") > 0
    assert REGISTRY.value("mesh_attr_dispatch_overhead_seconds_total") \
        > 0
    assert REGISTRY.value("mesh_attr_repartition_seconds_total") > 0
    # overhead total = sum of non-compute buckets, monotonic
    assert REGISTRY.value("mesh_flight_overhead_seconds_total") > 0
    for b in BUCKETS:
        name = f"mesh_attr_{b}_seconds_total"
        assert REGISTRY.value(name, default=-1.0) >= 0.0, name


def test_buckets_agree_with_mesh_report_tool():
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import mesh_report
    finally:
        sys.path.pop(0)
    # the gate tool keeps its own literal (no engine import); it must
    # never drift from the recorder's bucket set
    assert tuple(mesh_report.BUCKETS) == tuple(BUCKETS)
    assert set(mesh_report.BUCKET_BUDGET_PCT) == \
        set(BUCKETS) - {"device_compute"}


def test_chrome_trace_track(tpch):
    _, fl = _fly(tpch, Q1, 2, warm=False)
    events = chrome_events(fl)
    names = [e for e in events if e["ph"] == "M"]
    slices = [e for e in events if e["ph"] == "X"]
    # one named thread per bucket + the process name
    assert len(names) == len(BUCKETS) + 1
    assert len(slices) == fl.attribution["rounds"]
    assert all(e["dur"] > 0 for e in slices)


def test_history_fields_shape():
    assert flight.history_fields(None) == {}
    a = {"rounds": 3, "dominant_bucket": "repartition",
         "overhead_s": 0.5,
         "buckets": {b: 0.0 for b in BUCKETS}}
    f = flight.history_fields(a)
    assert f["mesh_rounds"] == 3
    assert f["mesh_dominant_bucket"] == "repartition"
    assert f["mesh_overhead_ms"] == 500.0
    assert sorted(json.loads(f["mesh_buckets"])) == sorted(BUCKETS)

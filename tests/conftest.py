"""Test harness config: run everything on a virtual 8-device CPU mesh.

Mirrors Presto's ring-3 testing strategy (DistributedQueryRunner boots N
in-process servers, reference presto-tests/.../DistributedQueryRunner.java:76):
we get N devices in one process via XLA's host platform device count.

Note: this environment's sitecustomize registers a tunneled TPU backend and
sets jax_platforms directly in jax config (overriding the JAX_PLATFORMS env
var), so we must win the same way — config.update after importing jax, before
any backend is initialized. Tests must never touch the single-chip TPU
tunnel: it is slow, serialized, and not multi-device.
"""
import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Mesh-native execution defaults to AUTO with >1 device (PR 12) — and
# the 8 virtual devices above would put EVERY LocalRunner test on the
# SPMD path, paying shard_map compiles across the whole suite. Pin the
# harness to the single-device path; the mesh suites (test_mesh_default,
# test_distributed*) opt back in per query via the mesh_execution
# session property, which overrides this environment default.
os.environ.setdefault("PRESTO_TPU_MESH_EXECUTION", "off")

# Persistent XLA compile cache shared across test processes/runs: the
# suite's wall-clock is dominated by kernel compiles (lax.sort at 2^17
# costs tens of seconds per variant on XLA:CPU), and the same shapes
# recur run over run (reference discipline: LocalQueryRunner reuse,
# presto-main/.../testing/LocalQueryRunner.java:210).
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache_cpu"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

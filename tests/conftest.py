"""Test harness config: run everything on a virtual 8-device CPU mesh.

Mirrors Presto's ring-3 testing strategy (DistributedQueryRunner boots N
in-process servers, reference presto-tests/.../DistributedQueryRunner.java:76):
we get N devices in one process via XLA's host platform device count.

Note: this environment's sitecustomize registers a tunneled TPU backend and
sets jax_platforms directly in jax config (overriding the JAX_PLATFORMS env
var), so we must win the same way — config.update after importing jax, before
any backend is initialized. Tests must never touch the single-chip TPU
tunnel: it is slow, serialized, and not multi-device.
"""
import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

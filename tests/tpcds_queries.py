"""TPC-DS query texts (adapted from the public TPC-DS specification's
query templates with fixed parameter values), restricted to the store
sales channel and the column subset the generator produces — column
substitutions (e.g. i_category for i_class) are noted inline. Engine
results are validated against a SQLite oracle over the IDENTICAL
generated data, so adapted parameters stay self-consistent.

Each entry: (name, engine_sql, sqlite_sql_or_None).
"""

Q = []


def q(name, sql, sqlite_sql=None):
    Q.append((name, sql, sqlite_sql or sql))


q("q3", """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manufact_id = 128 and d_moy = 11
group by d_year, i_brand, i_brand_id
order by d_year, sum_agg desc, brand_id
limit 100
""")

q("q7", """
select i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id order by i_item_id limit 100
""")

q("q13", """
select avg(ss_quantity) q, avg(ss_ext_sales_price) e,
       avg(ss_wholesale_cost) w, sum(ss_wholesale_cost) sw
from store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ((ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M'
        and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
    or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'S' and cd_education_status = 'College'
        and ss_sales_price between 50.00 and 100.00
        and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('TX', 'OH', 'TX')
        and ss_net_profit between 100 and 200)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('OR', 'NC', 'KY')
        and ss_net_profit between 150 and 300))
""")

q("q19", """
select i_brand_id brand_id, i_brand brand, i_manufact_id,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 8 and d_moy = 11 and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand, i_brand_id, i_manufact_id
order by ext_price desc, i_brand, i_brand_id, i_manufact_id
limit 100
""")

q("q34", """
select c_last_name, c_first_name, c_salutation,
       c_preferred_cust_flag, ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (d_dom between 1 and 3 or d_dom between 25 and 28)
        and (hd_buy_potential = '>10000'
             or hd_buy_potential = 'Unknown')
        and hd_vehicle_count > 0
        and d_year in (1999, 2000, 2001)
        and s_county = 'Williamson County'
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk and cnt between 15 and 20
order by c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag desc, ss_ticket_number
""".replace("c_salutation", "c_customer_id"))

q("q42", """
select d_year, i_category_id, i_category,
       sum(ss_ext_sales_price) s
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_category_id, i_category
order by s desc, d_year, i_category_id, i_category
limit 100
""")

q("q43", """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price
                else null end) sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price
                else null end) mon_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price
                else null end) fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price
                else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
  and s_gmt_offset = -5 and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id, sun_sales, mon_sales
limit 100
""")

q("q48", """
select sum(ss_quantity) s
from store_sales, store, customer_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2000
  and ((cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
    or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'D'
        and cd_education_status = '2 yr Degree'
        and ss_sales_price between 50.00 and 100.00))
  and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('TX', 'NC', 'OH')
        and ss_net_profit between 0 and 2000)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('KY', 'GA', 'VA')
        and ss_net_profit between 150 and 3000))
""")

q("q52", """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_brand, i_brand_id
order by d_year, ext_price desc, brand_id
limit 100
""")

# i_class substituted with i_category (generator subset)
q("q53", """
select manufact_id, sum_sales,
       avg(sum_sales) over (partition by manufact_id) avg_quarterly_sales
from (select i_manufact_id manufact_id, d_qoy,
             sum(ss_sales_price) sum_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk and d_year = 2000
        and i_category in ('Books', 'Children', 'Electronics')
        and i_manager_id between 1 and 20
      group by i_manufact_id, d_qoy) t
order by manufact_id, sum_sales limit 100
""")

q("q55", """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 28 and d_moy = 11 and d_year = 1999
group by i_brand, i_brand_id
order by ext_price desc, i_brand_id
limit 100
""")

q("q65", """
select s_store_name, i_item_id, sc.revenue
from store, item,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk and d_year = 2001
      group by ss_store_sk, ss_item_sk) sc,
     (select ss_store_sk store_sk, avg(revenue) ave
      from (select ss_store_sk, ss_item_sk,
                   sum(ss_sales_price) revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk and d_year = 2001
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb
where sb.store_sk = sc.ss_store_sk
  and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_id, sc.revenue
limit 100
""")

q("q68", """
select c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_coupon_amt) extended_tax,
             sum(ss_list_price) list_price
      from store_sales, date_dim, store,
           household_demographics, customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and d_dom between 1 and 2
        and (hd_dep_count = 4 or hd_vehicle_count = 3)
        and d_year in (1999, 2000, 2001)
        and s_city in ('Midway', 'Fairview')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
""")

q("q73", """
select c_last_name, c_first_name, c_salutation,
       c_preferred_cust_flag, ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and d_dom between 1 and 2
        and (hd_buy_potential = '>10000'
             or hd_buy_potential = 'Unknown')
        and hd_vehicle_count > 0
        and d_year in (1999, 2000, 2001)
        and s_county = 'Williamson County'
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk and cnt between 1 and 5
order by cnt desc, c_last_name asc
""".replace("c_salutation", "c_customer_id"))

q("q79", """
select c_last_name, c_first_name,
       substr(s_city, 1, 30) city30, ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (hd_dep_count = 6 or hd_vehicle_count > 2)
        and d_dow = 1 and d_year in (1999, 2000, 2001)
        and s_number_employees between 200 and 295
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, city30, profit
limit 100
""".replace("d_dow = 1", "d_day_name = 'Monday'"))

q("q88", """
select *
from (select count(*) h8_30_to_9 from store_sales,
        household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk
        and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
        and t_hour = 8 and t_minute >= 30
        and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
             or (hd_dep_count = 2 and hd_vehicle_count <= 4))
        and s_store_name = 'ese') s1,
     (select count(*) h9_to_9_30 from store_sales,
        household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk
        and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
        and t_hour = 9 and t_minute < 30
        and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
             or (hd_dep_count = 2 and hd_vehicle_count <= 4))
        and s_store_name = 'ese') s2,
     (select count(*) h12_to_12_30 from store_sales,
        household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk
        and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
        and t_hour = 12 and t_minute < 30
        and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
             or (hd_dep_count = 2 and hd_vehicle_count <= 4))
        and s_store_name = 'ese') s3
""")

# i_class substituted with i_category; the window moved outside the
# grouped subquery (same plan the reference builds after its
# window-over-aggregation rewrite)
q("q89", """
select i_category, i_brand, s_store_name, s_company, d_moy, sum_sales,
       avg_monthly_sales
from (select i_category, i_brand, s_store_name, s_company, d_moy,
             sum_sales,
             avg(sum_sales) over (partition by i_category, i_brand,
                                  s_store_name) avg_monthly_sales
      from (select i_category, i_brand, s_store_name,
                   s_store_id s_company, d_moy,
                   sum(ss_sales_price) sum_sales
            from item, store_sales, date_dim, store
            where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
              and ss_store_sk = s_store_sk and d_year = 1999
              and i_category in ('Books', 'Electronics', 'Sports')
              and i_brand_id between 1 and 60
            group by i_category, i_brand, s_store_name, s_store_id,
                     d_moy) g) t
where avg_monthly_sales <> 0
  and abs(sum_sales - avg_monthly_sales) / avg_monthly_sales > 0.1
order by sum_sales - avg_monthly_sales, s_company, d_moy
limit 100
""")

q("q96", """
select count(*) c
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20 and t_minute >= 30 and hd_dep_count = 7
  and s_store_name = 'ese'
""")

# i_class substituted with i_category; ratio over category partitions
q("q98", """
select i_item_id, i_category, i_current_price, itemrevenue,
       itemrevenue * 100.0 / sum(itemrevenue)
           over (partition by i_category) revenueratio
from (select i_item_id, i_category, i_current_price,
             sum(ss_ext_sales_price) itemrevenue
      from store_sales, item, date_dim
      where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and i_category in ('Sports', 'Books', 'Home')
        and d_year = 1999 and d_moy = 2
      group by i_item_id, i_category, i_current_price) t
order by i_category, i_item_id
limit 100
""")

q("q26_store", """
select i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
  and cd_gender = 'F' and cd_marital_status = 'W'
  and cd_education_status = 'Primary'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 1998
group by i_item_id order by i_item_id limit 100
""")

q("q6_store", """
select ca_state state, count(*) cnt
from customer_address, customer, store_sales, date_dim, item
where ca_address_sk = c_current_addr_sk
  and c_customer_sk = ss_customer_sk
  and ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and d_year = 2001 and d_moy = 1
  and i_current_price > 1.2 *
      (select avg(j.i_current_price) from item j
       where j.i_category = i_category)
group by ca_state having count(*) >= 10
order by cnt, state limit 100
""")

q("q96_meal", """
select t_meal_time, count(*) c
from store_sales, time_dim
where ss_sold_time_sk = t_time_sk and t_meal_time <> ''
group by t_meal_time order by t_meal_time
""")

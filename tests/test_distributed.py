"""Distributed SQL execution on the virtual 8-device mesh vs LocalRunner.

Ring-3 of the test strategy (SURVEY.md §4): same queries, N shards of
SPMD programs with real collectives, results must match the single-device
path exactly.
"""
import pytest

from presto_tpu.exec.distributed import DistributedRunner
from presto_tpu.exec.runner import LocalRunner

from tpch_queries import Q as TPCH_QUERIES

# minutes of shard_map compiles even with a warm persistent cache: out
# of the serial tier-1 time budget (run explicitly, or with xdist)
pytestmark = pytest.mark.slow

SF = 0.01

#: every TPC-H query the suite carries runs on the mesh — parity with
#: the local runner is the contract (any exclusion is a bug, not a
#: configuration)
DIST_QUERIES = list(TPCH_QUERIES)


@pytest.fixture(scope="module")
def local():
    return LocalRunner(tpch_sf=SF)


@pytest.fixture(scope="module")
def dist(local):
    return DistributedRunner(catalogs=local.session.catalogs,
                             rows_per_batch=1 << 13)


def _norm(rows, has_order):
    out = []
    for r in rows:
        nr = []
        for v in r:
            if hasattr(v, "item"):
                v = v.item()
            if isinstance(v, float):
                v = round(v, 4)
            nr.append(v)
        out.append(tuple(nr))
    return out if has_order else sorted(out, key=repr)


def check(local, dist, sql, rel=1e-9):
    want = local.execute(sql)
    got = dist.execute(sql)
    has_order = "order by" in sql.lower()
    w = _norm(want.rows, has_order)
    g = _norm(got.rows, has_order)
    assert len(g) == len(w), f"{len(g)} rows vs local {len(w)}"
    for gr, wr in zip(g, w):
        for gv, wv in zip(gr, wr):
            if isinstance(gv, float):
                assert gv == pytest.approx(wv, rel=rel, abs=1e-9), (gr, wr)
            else:
                assert gv == wv, (gr, wr)


@pytest.mark.parametrize(
    "name,sql,_o", DIST_QUERIES, ids=[t[0] for t in DIST_QUERIES])
def test_tpch_distributed(local, dist, name, sql, _o):
    check(local, dist, sql, rel=1e-6)


BASICS = [
    "select count(*) from lineitem",
    "select o_orderstatus, count(*), sum(o_totalprice) from orders group by 1 order by 1",
    "select n_name from nation where n_regionkey = 2 order by 1",
    "select distinct c_mktsegment from customer order by 1",
    "select o_orderkey, o_totalprice from orders order by o_totalprice desc limit 5",
    "select count(*) from orders where o_custkey not in (select c_custkey from customer where c_acctbal < 0)",
    "select s_name, n_name from supplier left join nation on s_nationkey = n_nationkey order by 1 limit 4",
]


@pytest.mark.parametrize("sql", BASICS, ids=range(len(BASICS)))
def test_basics_distributed(local, dist, sql):
    check(local, dist, sql)

"""Distributed SQL execution on the virtual 8-device mesh vs LocalRunner.

Ring-3 of the test strategy (SURVEY.md §4): same queries, N shards of
SPMD programs with real collectives, results must match the single-device
path exactly.
"""
import pytest

from presto_tpu.exec.distributed import DistributedRunner
from presto_tpu.exec.runner import LocalRunner

from tpch_queries import Q as TPCH_QUERIES

# minutes of shard_map compiles even with a warm persistent cache: out
# of the serial tier-1 time budget (run explicitly, or with xdist)
pytestmark = pytest.mark.slow

SF = 0.01

#: every TPC-H query the suite carries runs on the mesh — parity with
#: the local runner is the contract (any exclusion is a bug, not a
#: configuration)
DIST_QUERIES = list(TPCH_QUERIES)


@pytest.fixture(scope="module")
def local():
    return LocalRunner(tpch_sf=SF)


@pytest.fixture(scope="module")
def dist(local):
    return DistributedRunner(catalogs=local.session.catalogs,
                             rows_per_batch=1 << 13)


def _norm(rows, has_order):
    out = []
    for r in rows:
        nr = []
        for v in r:
            if hasattr(v, "item"):
                v = v.item()
            if isinstance(v, float):
                v = round(v, 4)
            nr.append(v)
        out.append(tuple(nr))
    return out if has_order else sorted(out, key=repr)


def check(local, dist, sql, rel=1e-9):
    want = local.execute(sql)
    got = dist.execute(sql)
    has_order = "order by" in sql.lower()
    w = _norm(want.rows, has_order)
    g = _norm(got.rows, has_order)
    assert len(g) == len(w), f"{len(g)} rows vs local {len(w)}"
    for gr, wr in zip(g, w):
        for gv, wv in zip(gr, wr):
            if isinstance(gv, float):
                assert gv == pytest.approx(wv, rel=rel, abs=1e-9), (gr, wr)
            else:
                assert gv == wv, (gr, wr)


@pytest.mark.parametrize(
    "name,sql,_o", DIST_QUERIES, ids=[t[0] for t in DIST_QUERIES])
def test_tpch_distributed(local, dist, name, sql, _o):
    check(local, dist, sql, rel=1e-6)


BASICS = [
    "select count(*) from lineitem",
    "select o_orderstatus, count(*), sum(o_totalprice) from orders group by 1 order by 1",
    "select n_name from nation where n_regionkey = 2 order by 1",
    "select distinct c_mktsegment from customer order by 1",
    "select o_orderkey, o_totalprice from orders order by o_totalprice desc limit 5",
    "select count(*) from orders where o_custkey not in (select c_custkey from customer where c_acctbal < 0)",
    "select s_name, n_name from supplier left join nation on s_nationkey = n_nationkey order by 1 limit 4",
]


@pytest.mark.parametrize("sql", BASICS, ids=range(len(BASICS)))
def test_basics_distributed(local, dist, sql):
    check(local, dist, sql)


def _with_props(runner, props):
    import contextlib

    @contextlib.contextmanager
    def cm():
        old = dict(runner.session.properties)
        runner.session.properties.update(props)
        try:
            yield
        finally:
            runner.session.properties.clear()
            runner.session.properties.update(old)
    return cm()


def test_partitioned_semi_distribution_parity(local, dist):
    """Forcing the stats-driven partitioned semi distribution (round 8:
    membership no longer broadcasts everywhere) keeps mesh results
    row-exact — both sides hash by key, per-shard verdicts compose."""
    sql = ("select count(*) from orders where o_custkey in "
           "(select c_custkey from customer where c_nationkey < 7)")
    props = {"broadcast_join_row_limit": 10}
    with _with_props(local, props):
        want = local.execute(sql)
    with _with_props(dist, props):
        got = dist.execute(sql)
    assert want.rows == got.rows
    assert want.rows[0][0] > 0


def test_keyed_direct_join_mesh_parity(local, dist):
    """Planner key_bounds ride the mesh path: the per-shard build
    prepares a composite direct table once and every probe batch reuses
    it. join_dense_path=false must give identical rows."""
    sql = ("select n_name, count(*) from customer "
           "join nation on c_nationkey = n_nationkey "
           "group by n_name order by n_name")
    on = dist.execute(sql).rows
    with _with_props(dist, {"join_dense_path": False}):
        off = dist.execute(sql).rows
    assert on == off == local.execute(sql).rows

"""Sketch-style aggregates: approx_distinct and approx_percentile.

Global approx_distinct carries REAL bounded HLL register state
(ops/sketch.py) through partial -> exchange -> final, like the reference
(reference operator/aggregation/state/HyperLogLogState.java); grouped
approx_distinct keeps the exact mark-distinct lowering (unbounded group
counts would make the dense register tile unbounded; exact is within any
sketch's error bound). Global numeric approx_percentile likewise carries
bounded mergeable log-linear histogram state (ops/sketch.py qd_*,
relative value error <= 1/(2*QD_L); reference
state/DigestAndPercentileState.java); grouped and string forms drain
into an exact segmented-sort select, hash-partitioned by group key.
"""
import numpy as np
import pytest

#: documented bound of the quantile histogram (ops/sketch.py): midpoint
#: of a 1/QD_L-relative-width bin, plus integer-rounding slack
QD_REL = 1.0 / 64 + 1e-9


def within_qd(got, exact):
    if exact == 0:
        return abs(float(got)) <= 1e-12
    return abs(float(got) - float(exact)) <= QD_REL * abs(float(exact)) + 0.5


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.exec.runner import LocalRunner
    return LocalRunner(tpch_sf=0.01)


@pytest.fixture(scope="module")
def dist(runner):
    from presto_tpu.exec.distributed import DistributedRunner
    return DistributedRunner(catalogs=runner.session.catalogs,
                             n_devices=8, rows_per_batch=1 << 12)


def _numpy_lineitem(runner, cols):
    rows = runner.execute(
        f"select {', '.join(cols)} from lineitem").rows
    return [np.asarray(c) for c in zip(*rows)]


def nearest_rank(values, p):
    v = np.sort(values)
    if len(v) == 0:
        return None
    k = min(max(int(np.ceil(p * len(v))) - 1, 0), len(v) - 1)
    return v[k]


def test_global_approx_distinct_hll(runner):
    """Global approx_distinct runs the HLL sketch: estimates land within
    a few standard errors of the exact count (deterministic hashing, so
    the outcome is stable run to run)."""
    got = runner.execute(
        "select approx_distinct(l_orderkey), approx_distinct(l_returnflag) "
        "from lineitem").rows[0]
    want = runner.execute(
        "select count(distinct l_orderkey), count(distinct l_returnflag) "
        "from lineitem").rows[0]
    # default standard error 2.3%%: allow 4 sigma on the big count
    assert abs(got[0] - want[0]) <= max(0.1 * want[0], 2), (got, want)
    assert got[1] == want[1]     # 3 distinct values: exact in HLL range


def test_grouped_approx_distinct_stays_exact(runner):
    got = runner.execute(
        "select l_returnflag, approx_distinct(l_suppkey) from lineitem "
        "group by 1 order by 1").rows
    want = runner.execute(
        "select l_returnflag, count(distinct l_suppkey) from lineitem "
        "group by 1 order by 1").rows
    assert got == want


def test_approx_distinct_error_parameter(runner):
    """approx_distinct(x, e): a coarser budget shrinks the register
    vector; estimates stay within a few multiples of e."""
    want = runner.execute(
        "select count(distinct l_orderkey) from lineitem").rows[0][0]
    got = runner.execute(
        "select approx_distinct(l_orderkey, 0.26) from lineitem"
    ).rows[0][0]
    assert abs(got - want) <= 0.6 * want, (got, want)
    import pytest
    with pytest.raises(Exception):
        runner.execute(
            "select approx_distinct(l_orderkey, 0.5) from lineitem")


def test_global_approx_distinct_empty_and_null(runner):
    rows = runner.execute(
        "select approx_distinct(l_orderkey) from lineitem "
        "where l_orderkey < 0").rows
    assert rows == [(0,)]


def test_global_percentile(runner):
    """Global numeric percentiles run the bounded histogram sketch:
    within the documented relative-error bound of exact nearest-rank."""
    (qty,) = _numpy_lineitem(runner, ["l_quantity"])
    got = runner.execute(
        "select approx_percentile(l_quantity, 0.5), "
        "approx_percentile(l_quantity, 0.9), "
        "approx_percentile(l_quantity, 0.0), "
        "approx_percentile(l_quantity, 1.0) from lineitem").rows[0]
    for g, p in zip(got, (0.5, 0.9, 0.0, 1.0)):
        assert within_qd(g, nearest_rank(qty, p)), (p, g)


def test_grouped_percentile(runner):
    rf, price = _numpy_lineitem(runner, ["l_returnflag", "l_extendedprice"])
    got = runner.execute(
        "select l_returnflag, approx_percentile(l_extendedprice, 0.5), "
        "count(*) from lineitem group by 1 order by 1").rows
    assert len(got) == len(set(rf))
    for flag, med, cnt in got:
        sel = price[rf == flag]
        assert cnt == len(sel)
        assert float(med) == float(nearest_rank(sel, 0.5)), flag


def test_percentile_mixed_with_regular_aggs(runner):
    rf, price = _numpy_lineitem(runner, ["l_returnflag", "l_extendedprice"])
    got = runner.execute(
        "select l_returnflag, sum(l_extendedprice), "
        "approx_percentile(l_extendedprice, 0.25), avg(l_extendedprice) "
        "from lineitem group by 1 order by 1").rows
    for flag, s, q25, avg in got:
        sel = price[rf == flag]
        assert abs(float(s) - round(sel.sum(), 2)) < 1e-6 * abs(sel.sum())
        assert float(q25) == float(nearest_rank(sel, 0.25))
        assert abs(float(avg) - sel.mean()) < 1e-6 * abs(sel.mean())


def test_percentile_of_integers(runner):
    got = runner.execute(
        "select approx_percentile(l_linenumber, 0.5) from lineitem").rows
    assert isinstance(got[0][0], (int, np.integer))


def test_percentile_empty_input(runner):
    got = runner.execute(
        "select approx_percentile(l_quantity, 0.5) from lineitem "
        "where l_quantity < -1").rows
    assert got == [(None,)]


def test_percentile_nonconstant_p_rejected(runner):
    from presto_tpu.sql.analyzer import AnalysisError
    with pytest.raises(AnalysisError):
        runner.execute("select approx_percentile(l_quantity, l_discount) "
                       "from lineitem")


def test_percentile_varchar_lexicographic(runner):
    # dictionary codes are appearance-ordered; the kernel must sort by
    # lexicographic rank, not raw code
    names = sorted(r[0] for r in runner.execute(
        "select n_name from nation").rows)
    got = runner.execute(
        "select approx_percentile(n_name, 0.5) from nation").rows[0][0]
    k = max(int(np.ceil(0.5 * len(names))) - 1, 0)
    assert got == names[k]


def test_percentile_multiple_ps_share_input(runner):
    (qty,) = _numpy_lineitem(runner, ["l_quantity"])
    got = runner.execute(
        "select approx_percentile(l_quantity, 0.25), "
        "approx_percentile(l_quantity, 0.5), "
        "approx_percentile(l_quantity, 0.75) from lineitem").rows[0]
    for g, p in zip(got, (0.25, 0.5, 0.75)):
        assert within_qd(g, nearest_rank(qty, p))


def test_split_part_nonpositive_index_errors(runner):
    from presto_tpu.errors import QueryError
    with pytest.raises(QueryError):
        runner.execute("select split_part('a:b', ':', 0)")


def test_split_part_out_of_range_is_null(runner):
    assert runner.execute(
        "select split_part('a:b', ':', 5)").rows == [(None,)]


def test_distributed_percentile(runner, dist):
    want = runner.execute(
        "select l_returnflag, approx_percentile(l_extendedprice, 0.5) "
        "from lineitem group by 1 order by 1").rows
    got = dist.execute(
        "select l_returnflag, approx_percentile(l_extendedprice, 0.5) "
        "from lineitem group by 1 order by 1").rows
    assert [(a, float(b)) for a, b in got] \
        == [(a, float(b)) for a, b in want]


@pytest.mark.slow
def test_distributed_global_percentile(runner, dist):
    # global (ungrouped) sketch merge across shards; the grouped
    # distributed path stays tier-1 via test_distributed_percentile —
    # this single-row parity check costs ~45s of compile, slow lane
    want = runner.execute(
        "select approx_percentile(l_quantity, 0.9) from lineitem").rows
    got = dist.execute(
        "select approx_percentile(l_quantity, 0.9) from lineitem").rows
    assert float(got[0][0]) == float(want[0][0])


def test_distributed_approx_distinct(runner, dist):
    """Grouped approx_distinct (exact lowering) must survive the
    distributed exchange: mark-distinct repartitions by (group, value),
    so shards count disjoint value sets."""
    q = ("select l_returnflag, approx_distinct(l_suppkey) "
         "from lineitem group by 1 order by 1")
    assert dist.execute(q).rows == runner.execute(q).rows


def test_distributed_global_approx_distinct(runner, dist):
    """Global approx_distinct ships O(1) HLL register state through the
    mesh exchange (partial on every shard, merged at the single final):
    the distributed estimate must equal the local one bit-for-bit —
    register maxima are associative and hashing is deterministic."""
    q = "select approx_distinct(l_orderkey) from lineitem"
    assert dist.execute(q).rows == runner.execute(q).rows


def test_cluster_global_percentile_with_varchar_aggs():
    """Fragmenter-split global percentile: the FINAL node consumes state
    columns (varchar min/max state + qdigest tile); the executor must
    not re-evaluate the drain decision against that state layout
    (regression: raw-input indices pointing at a varchar state column
    misrouted the final step into the exact drain)."""
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.server.worker import WorkerServer

    workers = [WorkerServer(tpch_sf=0.01) for _ in range(2)]
    for w in workers:
        w.start()
    try:
        runner = ClusterRunner(
            [f"http://127.0.0.1:{w.port}" for w in workers],
            tpch_sf=0.01, heartbeat=False)
        sql = ("select max(l_shipmode), max(l_comment), "
               "approx_percentile(l_quantity, 0.5) from lineitem")
        got = runner.execute(sql).rows[0]
        want = runner.local.execute(sql).rows[0]
        assert got[:2] == want[:2]
        assert float(got[2]) == float(want[2])
    finally:
        for w in workers:
            w.stop()


def test_sketch_percentile_nan_sorts_last():
    """NaN bins into the top slot, matching the exact path's sort-last
    rank behavior (not the zero bin)."""
    import jax.numpy as jnp
    from presto_tpu.ops.sketch import QD_BINS, qd_bin, qd_update

    vals = jnp.asarray([float("nan"), 10.0, 20.0])
    assert int(qd_bin(vals)[0]) == QD_BINS - 1
    counts = qd_update(jnp.ones(3, bool), vals)
    from presto_tpu.ops.sketch import qd_estimate
    # nearest-rank k=ceil(0.5*3)=2 over [10, 20, NaN] -> 20, exactly
    # what the exact path's sort-NaN-last selection returns
    est, ok = qd_estimate(counts, 0.5)
    assert abs(float(est) - 20.0) <= 20.0 / 64 + 1e-9


def test_qdigest_state_is_fixed_size():
    """The percentile partial state is O(1) in input rows: one
    fixed-size histogram tile regardless of input size (the reference's
    bounded-memory contract, state/DigestAndPercentileState.java)."""
    from presto_tpu.batch import Batch
    from presto_tpu import types as T
    from presto_tpu.ops.aggregation import AggSpec, global_aggregate
    from presto_tpu.ops.sketch import QD_BINS
    from presto_tpu.types import QdigestStateType

    for n in (1 << 10, 1 << 14):
        b = Batch.from_pydict({"x": (T.DOUBLE,
                                     [float(i) for i in range(n)])})
        part = global_aggregate(
            b, [AggSpec("approx_percentile", 0, T.DOUBLE, "q", param=0.5)],
            mode="partial")
        (state_col,) = [c for c in part.columns
                        if isinstance(c.type, QdigestStateType)]
        assert state_col.data.shape == (128, QD_BINS)  # independent of n


def test_qdigest_partials_merge_exactly():
    """Chunked partial -> merge -> final equals one single pass: bin
    counts are integers, so merging is associative and exact."""
    from presto_tpu.batch import Batch, concat_batches
    from presto_tpu import types as T
    from presto_tpu.ops.aggregation import AggSpec, global_aggregate

    rng = np.random.default_rng(11)
    data = rng.lognormal(1.0, 1.5, 4096).tolist()
    aggs = [AggSpec("approx_percentile", 0, T.DOUBLE, "q", param=0.9)]
    whole = Batch.from_pydict({"x": (T.DOUBLE, data)})
    one = global_aggregate(global_aggregate(whole, aggs, mode="partial"),
                           aggs, mode="final")
    parts = [global_aggregate(
        Batch.from_pydict({"x": (T.DOUBLE, data[i::4])}), aggs,
        mode="partial") for i in range(4)]
    merged = global_aggregate(concat_batches(parts), aggs, mode="final")
    assert float(one.columns[0].data[0]) == float(merged.columns[0].data[0])
    assert within_qd(float(one.columns[0].data[0]),
                     nearest_rank(np.asarray(data), 0.9))


def test_hll_state_is_fixed_size():
    """The partial state is O(1) in input rows: one register vector per
    group regardless of input size (the reference's bounded-memory
    contract, state/HyperLogLogState.java)."""
    import jax.numpy as jnp
    from presto_tpu.batch import Batch
    from presto_tpu import types as T
    from presto_tpu.ops.aggregation import AggSpec, global_aggregate
    from presto_tpu.types import HllStateType

    for n in (1 << 10, 1 << 14):
        b = Batch.from_pydict({"x": (T.BIGINT, list(range(n)))})
        part = global_aggregate(
            b, [AggSpec("approx_distinct", 0, T.BIGINT, "d")],
            mode="partial")
        (state_col,) = [c for c in part.columns
                        if isinstance(c.type, HllStateType)]
        assert state_col.data.shape == (128, 2048)   # independent of n

"""Fair device scheduling across concurrent queries (the reference's
TaskExecutor / MultilevelSplitQueue role, execution/executor/
TaskExecutor.java:79, MultilevelSplitQueue.java:43)."""
import threading
import time

import pytest

from presto_tpu.exec.taskexec import DeviceScheduler, LEVEL_THRESHOLDS


def test_levels_by_cumulative_time():
    s = DeviceScheduler()
    h = s.task("t")
    assert h.level == 0
    h.device_seconds = 2.0
    assert h.level == 1
    h.device_seconds = 400.0
    assert h.level == len(LEVEL_THRESHOLDS) - 1


def test_low_usage_task_preempts_between_quanta():
    """A fresh task is granted the device ahead of a task that has
    accumulated more device time, at every quantum boundary."""
    s = DeviceScheduler()
    heavy = s.task("heavy")
    light = s.task("light")
    order = []
    stop = threading.Event()

    def heavy_loop():
        while not stop.is_set():
            s.run_quantum(heavy, lambda: (order.append("heavy"),
                                          time.sleep(0.02)))

    t = threading.Thread(target=heavy_loop, daemon=True)
    t.start()
    time.sleep(0.08)        # heavy accumulates usage
    for _ in range(5):
        s.run_quantum(light, lambda: order.append("light"))
    stop.set()
    t.join(timeout=5)
    # all 5 light quanta were granted while heavy kept requesting
    lights = [i for i, x in enumerate(order) if x == "light"]
    assert len(lights) == 5
    assert heavy.device_seconds > light.device_seconds
    # light never waited behind more than one heavy quantum: its grants
    # are consecutive-ish (no long heavy runs interleaved)
    gaps = [b - a for a, b in zip(lights, lights[1:])]
    assert max(gaps) <= 2


def test_concurrent_queries_interleave():
    """A short query against a busy runner completes while a long query
    is still executing (reference simulator-style check)."""
    from presto_tpu.exec.runner import LocalRunner
    runner = LocalRunner(tpch_sf=0.05, rows_per_batch=1 << 12)
    runner.execute("select 1")      # warm caches

    long_done = threading.Event()
    short_done_at = []
    long_done_at = []
    t0 = time.perf_counter()

    def long_query():
        runner.execute(
            "select l_suppkey, count(*), sum(l_extendedprice) "
            "from lineitem group by 1")
        long_done_at.append(time.perf_counter() - t0)
        long_done.set()

    def short_query():
        time.sleep(0.05)   # start after the long query is underway
        runner.execute("select count(*) from nation")
        short_done_at.append(time.perf_counter() - t0)

    tl = threading.Thread(target=long_query)
    ts = threading.Thread(target=short_query)
    tl.start()
    ts.start()
    tl.join(timeout=120)
    ts.join(timeout=120)
    assert short_done_at and long_done_at
    # the short query must not have been serialized behind the whole
    # long query
    assert short_done_at[0] <= long_done_at[0] + 0.5


def test_lock_discipline_clean_after_scheduler_exercise():
    """The fair scheduler's locks fed the runtime lock-order validator
    through every test above: no observed inversion cycles, and no jit
    dispatch ever ran under an engine lock (ISSUE 7 runtime checker)."""
    from presto_tpu._devtools import lockcheck
    assert lockcheck.ENABLED
    assert lockcheck.GRAPH.check() == [], lockcheck.GRAPH.check()


def test_stalled_releases_device_then_restores_bookkeeping():
    """Regression for the cross-worker exchange deadlock: a consumer
    blocked on remote pages inside its quantum must RELEASE the device
    (another query's quantum runs meanwhile), then re-acquire on exit
    with the nesting depth exactly restored — an unbalanced depth
    either wedges the scheduler or lets two quanta run at once."""
    from presto_tpu.obs.metrics import REGISTRY
    s = DeviceScheduler()
    a = s.task("stall-a")
    b = s.task("other-b")
    stalled_now = threading.Event()
    release = threading.Event()
    order = []

    def a_quantum():
        order.append("a-enter")
        with s.stalled(a):
            stalled_now.set()
            assert release.wait(timeout=5)
        order.append("a-resume")

    before = REGISTRY.counter("device_stall_release_total").value
    t = threading.Thread(
        target=lambda: s.run_quantum(a, a_quantum), daemon=True)
    t.start()
    assert stalled_now.wait(timeout=5)
    # the device is free while A waits on input: B's quantum runs NOW
    s.run_quantum(b, lambda: order.append("b-ran"))
    release.set()
    t.join(timeout=5)
    assert not t.is_alive()
    assert order == ["a-enter", "b-ran", "a-resume"]
    assert REGISTRY.counter(
        "device_stall_release_total").value == before + 1
    # bookkeeping balanced: scheduler idle, depth zero
    assert s._running is None
    assert s._running_depth == 0


def test_stalled_inside_nested_quantum_keeps_reentrancy():
    """stalled() gives back ONE nesting level. Inside a re-entrant
    (nested same-handle) quantum the outer level still holds the
    device, and the exit path must rebuild depth to exactly 2 before
    unwinding — off-by-one here frees the device while the outer
    quantum is mid-flight."""
    s = DeviceScheduler()
    a = s.task("nested")

    def inner():
        with s.stalled(a):
            # one level released, the outer one still held
            assert s._running is a
            assert s._running_depth == 1
        assert s._running_depth == 2

    def outer():
        s.run_quantum(a, inner)

    s.run_quantum(a, outer)
    assert s._running is None
    assert s._running_depth == 0


def test_stalled_without_held_quantum_is_a_noop():
    """Outside any quantum (fair_scheduling off, init paths) stalled()
    must not touch scheduler state or the release counter."""
    from presto_tpu.obs.metrics import REGISTRY
    s = DeviceScheduler()
    a = s.task("free")
    before = REGISTRY.counter("device_stall_release_total").value
    with s.stalled(a):
        pass
    with s.stalled(None):
        pass
    assert REGISTRY.counter(
        "device_stall_release_total").value == before
    assert s._running is None and s._running_depth == 0


def test_device_floor_pad_models_fixed_throughput(monkeypatch):
    """The modeled device-service floor pads a kernel chain up to the
    floor and never double-bills work that already took longer."""
    import presto_tpu.exec.taskexec as tx
    monkeypatch.setattr(tx, "_SERVICE_FLOOR_S", 0.05)
    t0 = time.perf_counter()
    tx.device_floor_pad(0.0)
    assert time.perf_counter() - t0 >= 0.045
    t0 = time.perf_counter()
    tx.device_floor_pad(10.0)         # chain already past the floor
    assert time.perf_counter() - t0 < 0.02
    monkeypatch.setattr(tx, "_SERVICE_FLOOR_S", 0.0)
    t0 = time.perf_counter()
    tx.device_floor_pad(0.0)          # disabled: free
    assert time.perf_counter() - t0 < 0.02

#!/usr/bin/env python
"""Tier-1 time-budget checker: per-module durations from pytest output.

The tier-1 verify command runs ``pytest -m 'not slow'`` under a hard
870 s timeout — when the suite creeps past it, the run is KILLED and
every not-yet-run module's passes are lost (round-6 baseline: rc=124 at
~69%). This tool makes the creep visible: feed it a pytest log produced
with ``--durations=0`` (or any log containing the `slowest durations`
section), and it aggregates test durations per module, prints them
sorted, and flags when the projected total busts the budget.

Usage:
    python -m pytest tests/ -q -m 'not slow' --durations=0 | tee /tmp/t1.log
    python tools/check_tier1_time.py /tmp/t1.log [--budget 870]

The per-test durations understate wall-clock (collection, fixtures and
compile time between tests are unattributed), so the budget check also
applies a configurable safety factor (default 1.3).
"""
from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict

# "12.34s call  tests/test_sql.py::test_features[3]" (also setup/teardown)
_DUR = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(?:call|setup|teardown)\s+"
    r"(?:.*[/\\])?tests[/\\](test_\w+)\.py::")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("log", help="pytest output containing --durations")
    ap.add_argument("--budget", type=float, default=870.0,
                    help="tier-1 timeout in seconds (default 870)")
    ap.add_argument("--safety", type=float, default=1.3,
                    help="factor for unattributed overhead (default 1.3)")
    ap.add_argument("--top", type=int, default=0,
                    help="print only the N slowest modules")
    ap.add_argument("--analyzer-budget", type=float, default=90.0,
                    help="cap (seconds) for the static-analysis plane's "
                         "own tier-1 cost — the analyzer modules "
                         "(default 90)")
    ap.add_argument("--analyzer-modules", default=
                    "test_analyze,test_interleave",
                    help="comma-separated modules charged against "
                         "--analyzer-budget")
    args = ap.parse_args(argv)

    per_module: dict = defaultdict(float)
    with open(args.log, errors="replace") as f:
        for line in f:
            m = _DUR.match(line)
            if m:
                per_module[m.group(2)] += float(m.group(1))
    if not per_module:
        print("no duration lines found — run pytest with --durations=0",
              file=sys.stderr)
        return 2

    total = sum(per_module.values())
    ranked = sorted(per_module.items(), key=lambda kv: -kv[1])
    if args.top:
        ranked = ranked[:args.top]
    width = max(len(k) for k, _ in ranked)
    for mod, s in ranked:
        share = 100.0 * s / total
        print(f"{mod:<{width}}  {s:8.1f}s  {share:5.1f}%")
    projected = total * args.safety
    print(f"{'TOTAL':<{width}}  {total:8.1f}s  (projected "
          f"~{projected:.0f}s with x{args.safety} overhead; "
          f"budget {args.budget:.0f}s)")
    rc = 0
    # the verification plane polices the tree, so it gets its own leash:
    # a checker or interleaving suite that quietly grows past its
    # budget is stealing wall-clock from the tests it exists to protect
    analyzer_mods = [m.strip() for m in args.analyzer_modules.split(",")
                     if m.strip()]
    analyzer_s = sum(per_module.get(m, 0.0) for m in analyzer_mods)
    print(f"{'ANALYZER':<{width}}  {analyzer_s:8.1f}s  "
          f"({'+'.join(analyzer_mods)}; budget "
          f"{args.analyzer_budget:.0f}s)")
    if analyzer_s > args.analyzer_budget:
        print(f"ANALYZER OVER BUDGET ({analyzer_s:.1f}s > "
              f"{args.analyzer_budget:.0f}s): trim the checker scope "
              f"or the interleaving schedule caps", file=sys.stderr)
        rc = 1
    if projected > args.budget:
        print(f"OVER BUDGET: mark the slowest modules @pytest.mark.slow "
              f"or split them", file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Perf-regression gate: diff a bench run against the latest committed
``BENCH_r*.json``.

Every round's numbers are committed as ``BENCH_rNN.json`` (the driver's
wrapper: ``{"parsed": {"metric", "value", "vs_baseline",
"sub_metrics": [...]}}``). This tool turns "did the join PR regress
q1?" from an eyeball diff into a machine verdict: it flattens the
headline + sub_metrics of both sides, compares each query's
``vs_baseline`` speedup (the machine-calibrated ratio against the
pinned NumPy proxy — BASELINE_PROXY.json pins the proxy seconds, so
the ratio is stable across rounds on one machine class) with a
per-query tolerance, and emits one JSON verdict plus a matching exit
code.

The same gate covers the SERVING summary (``SERVING_r*.json``, written
by ``SERVING_OUT=path python bench.py serving``): pass ``--kind
serving`` to diff QPS / p95 latency / warm-speedup against the latest
committed serving round. Latency metrics (``*_ms`` / ``*_latency_ms``)
are lower-is-better — the gate inverts their ratio automatically.
``--kind elastic`` gates the chaos recovery-time axis the same way
(``ELASTIC_r*.json``, written by ``python tools/chaos_smoke.py
--elastic-out``): per-scenario recovery milliseconds, all
lower-is-better. ``--kind multichip`` gates the mesh-scaling axis
(``MULTICHIP_r*.json``, written by ``MULTICHIP_OUT=path python
bench.py multichip``): per-query rows/s at each device count plus
scaling efficiency, all higher-is-better; rounds up to r05 pinned only
a dry-run exit code (the ``ok`` bool, kept in the summary for
back-compat) and are not comparable — the gate always discovers the
LATEST round, so they age out naturally. Multichip rounds from r07 on
also carry the flight recorder's per-query ``attribution`` block
(obs/flight.py); the gate schema-validates every block and enforces
the per-bucket overhead budgets declared in ``tools/mesh_report.py``,
so an exchange change that blows the control-sync or repartition
budget fails even when rows/s noise hides it. Pins without attribution
(r06 and older) pass the attribution gate vacuously.

Serving rounds from r03 on also carry the health plane's ``slo``
block (obs/slo.py via bench.py): declared per-group objectives, burn
rates, alert transitions, and the burn timeline with the windowed
p95. ``--kind serving`` schema-validates the block through
``tools/slo_report.py`` (smoke mode gates the pinned round, run mode
the candidate); pins without a block (r02 and older) pass vacuously.

Fleet serving rounds (r04 on, benched with ``SERVING_COORDINATORS``
>= 2) additionally carry a ``fleet`` block; the gate validates its
invariants — per-coordinator QPS present for every member and summing
to the aggregate, cross-coordinator cache coherence demonstrated
(remote invalidation observed, >= 1 cross-coordinator cache hit), and
a coordinator-kill drill with zero failed queries — through
:func:`_fleet_gate`. Pins without a fleet block (r03 and older, or a
single-coordinator rerun) pass that gate vacuously.

Elastic rounds (r02 on, produced by ``tools/chaos_smoke.py --ramp
--elastic-out``) carry a ``ramp`` block: the 1 -> N -> 1 load-ramp
bench over real subprocess workers. ``--kind elastic`` validates it
through :func:`_elastic_gate` — the ramp must really go 1 -> N -> 1,
every phase must run with ZERO failed queries, and peak-N QPS must be
>= 1.5x the 1-worker floor (elasticity that doesn't move throughput is
a no-op). Pins without a ramp block (r01) pass vacuously.

Usage:
    python tools/check_bench_regression.py --run bench_out.json
    python tools/check_bench_regression.py --run bench_out.json \
        --tolerance 10 --tolerance-for q55=25 --tolerance-for q3=15
    python tools/check_bench_regression.py --kind serving --run s.json
    python tools/check_bench_regression.py --smoke       # self-test
    python tools/check_bench_regression.py --kind serving --smoke

``--run`` accepts either bench.py's summary line (written via
``BENCH_OUT=path python bench.py``), a file whose LAST JSON line is
that summary (a captured stdout log), or a committed ``BENCH_r*.json``
wrapper. ``--smoke`` runs the gate's self-consistency check against
the latest committed round: the baseline must pass against itself, and
a synthetically halved copy must fail — the mode tier-1 runs so the
gate itself cannot rot.

Verdict JSON (stdout):
    {"verdict": "pass"|"fail", "baseline_file": ..., "checks": [
        {"metric", "baseline", "run", "ratio", "tolerance_pct", "ok"}],
     "missing": [...], "new": [...]}

Exit code 0 on pass, 1 on fail, 2 on usage/IO errors.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: default allowed relative drop in vs_baseline, percent. Generous
#: enough for machine noise on multi-second configs, tight enough that
#: a real regression (the 2x kind perf PRs cause) cannot hide.
DEFAULT_TOLERANCE_PCT = 10.0


def latest_bench_file(root: str = _REPO,
                      prefix: str = "BENCH") -> Optional[str]:
    """Highest-numbered <prefix>_r*.json — the pinned trajectory
    (``BENCH`` for per-query rounds, ``SERVING`` for the concurrent-
    throughput axis)."""
    best, best_n = None, -1
    for p in glob.glob(os.path.join(root, f"{prefix}_r*.json")):
        m = re.search(rf"{prefix}_r(\d+)\.json$", os.path.basename(p))
        if m and int(m.group(1)) > best_n:
            best, best_n = p, int(m.group(1))
    return best


def _lower_is_better(metric: str) -> bool:
    """Latency-flavoured metrics regress by going UP."""
    return metric.endswith("_ms") or metric.endswith("_latency_ms")


def _flatten(summary: Dict) -> Dict[str, Dict]:
    """Headline + sub_metrics -> {metric: record}."""
    out: Dict[str, Dict] = {}
    if not isinstance(summary, dict) or "metric" not in summary:
        return out
    head = {k: v for k, v in summary.items() if k != "sub_metrics"}
    out[head["metric"]] = head
    for sub in summary.get("sub_metrics") or ():
        if isinstance(sub, dict) and "metric" in sub:
            out[sub["metric"]] = sub
    return out


def load_summary(path: str) -> Dict[str, Dict]:
    """Metrics from a bench summary file: a BENCH_r wrapper (use its
    ``parsed``), a bare summary object, or a log whose last JSON line
    is the summary (bench.py re-emits the full summary after every
    config, so the last line always wins)."""
    with open(path) as f:
        text = f.read().strip()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict) and "parsed" in doc:
            doc = doc["parsed"]
        flat = _flatten(doc)
        if flat:
            return flat
    except ValueError:
        pass
    # log mode: last parseable JSON line
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            flat = _flatten(json.loads(line))
        except ValueError:
            continue
        if flat:
            return flat
    raise ValueError(f"{path}: no bench summary found")


def _score(rec: Dict) -> Optional[float]:
    """The comparable number: vs_baseline (machine-calibrated) when
    present, raw value otherwise."""
    v = rec.get("vs_baseline")
    if v is None:
        v = rec.get("value")
    return None if v is None else float(v)


def _tolerance_for(metric: str, default_pct: float,
                   overrides: Dict[str, float]) -> float:
    """Per-metric tolerance: exact metric name wins, then a short-name
    override (``q55=25`` matches ``tpcds_sf10_q55_rows_per_sec``)."""
    if metric in overrides:
        return overrides[metric]
    for short, pct in overrides.items():
        if f"_{short}_" in metric:
            return pct
    return default_pct


def compare(baseline: Dict[str, Dict], run: Dict[str, Dict],
            default_pct: float = DEFAULT_TOLERANCE_PCT,
            overrides: Optional[Dict[str, float]] = None,
            allow_missing: bool = False) -> Dict:
    """The gate: every baseline metric must be present in the run and
    within its tolerance. New run-only metrics are reported, never
    failed — adding a config must not break the gate."""
    overrides = overrides or {}
    checks: List[Dict] = []
    missing: List[str] = []
    for metric in sorted(baseline):
        b = _score(baseline[metric])
        if metric not in run:
            missing.append(metric)
            continue
        r = _score(run[metric])
        pct = _tolerance_for(metric, default_pct, overrides)
        if b is None or r is None or b <= 0:
            checks.append({"metric": metric, "baseline": b, "run": r,
                           "ratio": None, "tolerance_pct": pct,
                           "ok": True, "note": "not comparable"})
            continue
        # lower-is-better metrics (latency) invert: ratio stays
        # "1.0 = unchanged, < 1-tol = regressed" either way. A
        # nonpositive latency is malformed, not infinitely fast —
        # route it through the not-comparable path like other
        # malformed values rather than reporting ratio 0 "regressed".
        if _lower_is_better(metric):
            if r <= 0:
                checks.append({"metric": metric, "baseline": b,
                               "run": r, "ratio": None,
                               "tolerance_pct": pct, "ok": True,
                               "note": "not comparable"})
                continue
            ratio = b / r
        else:
            ratio = r / b
        ok = ratio >= 1.0 - pct / 100.0
        checks.append({"metric": metric, "baseline": b, "run": r,
                       "ratio": round(ratio, 4), "tolerance_pct": pct,
                       "ok": ok})
    new = sorted(set(run) - set(baseline))
    failed = [c["metric"] for c in checks if not c["ok"]]
    verdict = "pass"
    if failed or (missing and not allow_missing):
        verdict = "fail"
    return {"verdict": verdict, "checks": checks, "missing": missing,
            "new": new, "failed": failed}


def _attribution_gate(flat: Dict[str, Dict]) -> Dict:
    """Schema + per-bucket budget verdict for a multichip summary's
    flight-recorder attribution blocks. The budgets (and the
    validator) live in tools/mesh_report.py so the diff tool and this
    gate can never disagree about them."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from mesh_report import validate_attribution
    finally:
        sys.path.pop(0)
    return validate_attribution(flat)


def _slo_gate(flat: Dict[str, Dict]) -> Dict:
    """Schema verdict for a serving summary's SLO block (objectives,
    burn timeline, alert transitions). The schema (and the validator)
    live in tools/slo_report.py so the report tool and this gate can
    never disagree about it. Pins without a block (r02 and older)
    pass vacuously."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from slo_report import validate_slo_block
    finally:
        sys.path.pop(0)
    return validate_slo_block(flat)


def _fleet_gate(flat: Dict[str, Dict]) -> Dict:
    """Invariant verdict for the ``fleet`` block a multi-coordinator
    serving summary carries (SERVING_r04 on, ``SERVING_COORDINATORS``
    fleet mode): per-coordinator QPS that actually sums to the
    aggregate (no dead member hiding behind a fleet-wide number),
    cross-coordinator cache coherence demonstrated (a remote write
    observed invalidating, and at least one cross-coordinator
    result-cache hit pinned), and a clean coordinator-kill drill
    (ZERO failed queries, the loss observed). Pins without a fleet
    block (r03 and older, or single-coordinator reruns) pass
    vacuously."""
    violations: List[Dict] = []
    blocks = 0
    for metric in sorted(flat):
        fl = flat[metric].get("fleet")
        if fl is None:
            continue
        blocks += 1

        def bad(kind: str, detail: str, _m=metric) -> None:
            violations.append({"metric": _m, "kind": kind,
                               "detail": detail})

        if not isinstance(fl, dict):
            bad("schema", "fleet is not an object")
            continue
        n = fl.get("coordinators")
        if isinstance(n, bool) or not isinstance(n, int) or n < 3:
            bad("schema", "coordinators must be an int >= 3 (the "
                          "fleet claim needs a real fleet)")
            continue
        per = fl.get("per_coordinator_qps")
        if not isinstance(per, dict) or len(per) != n:
            bad("schema", f"per_coordinator_qps must name all {n} "
                          "coordinators")
        else:
            lazy = [c for c, q in sorted(per.items())
                    if not isinstance(q, (int, float))
                    or isinstance(q, bool) or q <= 0]
            if lazy:
                bad("balance", "coordinators with zero/invalid QPS: "
                              f"{', '.join(lazy)} — every member "
                              "must carry traffic")
            agg = fl.get("aggregate_qps")
            if not isinstance(agg, (int, float)) \
                    or isinstance(agg, bool) or agg <= 0:
                bad("schema", "aggregate_qps must be positive")
            elif not lazy:
                total = sum(per.values())
                if abs(total - agg) > 0.25 * agg:
                    bad("balance",
                        f"per-coordinator QPS sums to {total:g} but "
                        f"aggregate is {agg:g} (>25% apart) — the "
                        "aggregate is not the fleet's own traffic")
        coh = fl.get("coherence")
        if not isinstance(coh, dict):
            bad("coherence", "missing coherence block")
        else:
            if coh.get("remote_invalidation_observed") is not True:
                bad("coherence", "remote write was never observed "
                                 "invalidating a peer's caches")
            if coh.get("row_exact") is not True:
                bad("coherence", "post-write cross-coordinator read "
                                 "was not row-exact")
            hits = coh.get("xcoord_result_cache_hits")
            if not isinstance(hits, (int, float)) \
                    or isinstance(hits, bool) or hits < 1:
                bad("coherence", "needs >= 1 pinned cross-coordinator "
                                 "result-cache hit")
        kill = fl.get("kill")
        if not isinstance(kill, dict):
            bad("kill", "missing coordinator-kill block")
        else:
            if kill.get("failed_queries") != 0:
                bad("kill", f"{kill.get('failed_queries')!r} queries "
                            "failed across the coordinator kill "
                            "(must be 0)")
            lost = kill.get("coordinator_lost_total")
            if not isinstance(lost, (int, float)) \
                    or isinstance(lost, bool) or lost < 1:
                bad("kill", "coordinator_lost_total never reached 1 — "
                            "the loss was not observed")
            if not kill.get("killed") or \
                    kill.get("killed") not in (
                        kill.get("survivor_lost_view") or ()):
                bad("kill", "killed coordinator absent from the "
                            "survivor's lost view")
    return {"blocks": blocks, "violations": violations,
            "ok": not violations}


def _elastic_gate(flat: Dict[str, Dict]) -> Dict:
    """Invariant verdict for the ``ramp`` block an elastic summary
    carries (ELASTIC_r02 on, ``tools/chaos_smoke.py --ramp``): the
    worker pool must really ramp 1 -> N -> 1 (the scale-DOWN is part
    of the claim), every phase window must complete with ZERO failed
    queries, and peak-N QPS must be >= 1.5x the 1-worker floor —
    elasticity that doesn't move throughput is a no-op. Pins without
    a ramp block (r01) pass vacuously."""
    violations: List[Dict] = []
    blocks = 0
    for metric in sorted(flat):
        ramp = flat[metric].get("ramp")
        if ramp is None:
            continue
        blocks += 1

        def bad(kind: str, detail: str, _m=metric) -> None:
            violations.append({"metric": _m, "kind": kind,
                               "detail": detail})

        if not isinstance(ramp, dict):
            bad("schema", "ramp is not an object")
            continue
        phases = ramp.get("phases")
        if not isinstance(phases, list) or len(phases) < 3:
            bad("schema", "phases must be a list of >= 3 windows "
                          "(1 -> N -> 1)")
            continue
        rows_ok = all(isinstance(p, dict) for p in phases)
        if not rows_ok:
            bad("schema", "every phase must be an object")
            continue
        workers = [p.get("workers") for p in phases]
        if workers[0] != 1 or workers[-1] != 1:
            bad("shape", f"ramp must start and end at 1 worker, got "
                         f"{workers} — the scale-down is part of the "
                         "claim")
        if not any(isinstance(w, int) and w > 1 for w in workers):
            bad("shape", f"ramp never scaled above 1 worker: {workers}")
        failed = [p.get("failed") for p in phases]
        if any(f != 0 for f in failed):
            bad("failures", f"phases reported failed queries {failed} "
                            "(every window must be 0 — transitions "
                            "included)")
        for p in phases:
            q = p.get("qps")
            if not isinstance(q, (int, float)) or isinstance(q, bool) \
                    or q <= 0:
                bad("schema", f"phase {p.get('workers')!r} has "
                              "non-positive qps")
        ratio = ramp.get("peak_over_floor")
        if not isinstance(ratio, (int, float)) or isinstance(ratio, bool):
            bad("schema", "peak_over_floor missing")
        elif ratio < 1.5:
            bad("throughput", f"peak QPS is only {ratio}x the 1-worker "
                              "floor (need >= 1.5x) — the pool grew "
                              "but throughput didn't track it")
    return {"blocks": blocks, "violations": violations,
            "ok": not violations}


def smoke(baseline_path: str) -> Dict:
    """Self-consistency: the pinned round must pass against itself,
    and a halved copy must fail. Proves discovery, parsing, tolerance
    math, and verdict emission without running the engine."""
    baseline = load_summary(baseline_path)
    same = compare(baseline, baseline)

    def degrade(metric, rec):
        # latency metrics regress UP, everything else DOWN
        factor = 2.0 if _lower_is_better(metric) else 0.5
        out = {**rec, "value": (rec.get("value") or 0) * factor}
        if rec.get("vs_baseline") is not None:
            out["vs_baseline"] = rec["vs_baseline"] * factor
        return out

    degraded = {m: degrade(m, rec) for m, rec in baseline.items()}
    worse = compare(baseline, degraded)
    ok = same["verdict"] == "pass" and worse["verdict"] == "fail"
    return {"verdict": "pass" if ok else "fail", "mode": "smoke",
            "baseline_file": baseline_path,
            "self_comparison": same["verdict"],
            "degraded_comparison": worse["verdict"],
            "metrics": sorted(baseline)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff a bench run against the latest BENCH_r*.json")
    ap.add_argument("--run", default=None, metavar="FILE",
                    help="bench summary to check (BENCH_OUT file, "
                         "captured stdout log, or BENCH_r wrapper)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: latest BENCH_r*.json "
                         "in the repo root)")
    ap.add_argument("--tolerance", type=float,
                    default=DEFAULT_TOLERANCE_PCT, metavar="PCT",
                    help="default allowed vs_baseline drop, percent "
                         f"(default {DEFAULT_TOLERANCE_PCT:g})")
    ap.add_argument("--tolerance-for", action="append", default=[],
                    metavar="NAME=PCT",
                    help="per-query override; NAME is a full metric or "
                         "a short config name (q55=25). Repeatable")
    ap.add_argument("--allow-missing", action="store_true",
                    help="metrics the run skipped (bench budget) warn "
                         "instead of failing")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="also write the verdict JSON to this file")
    ap.add_argument("--smoke", action="store_true",
                    help="self-consistency mode (no engine run): "
                         "baseline-vs-itself must pass, a degraded "
                         "copy must fail")
    ap.add_argument("--kind",
                    choices=("bench", "serving", "elastic", "multichip"),
                    default="bench",
                    help="which pinned trajectory to gate: per-query "
                         "BENCH_r*.json (default), the concurrent-"
                         "throughput SERVING_r*.json, the chaos "
                         "recovery-time ELASTIC_r*.json "
                         "(tools/chaos_smoke.py --elastic-out), or the "
                         "mesh-scaling MULTICHIP_r*.json "
                         "(MULTICHIP_OUT=path python bench.py "
                         "multichip; rows/s and scaling-efficiency "
                         "metrics are higher-is-better, and the "
                         "legacy dry-run 'ok' bool rides along "
                         "untouched)")
    args = ap.parse_args(argv)

    prefix = {"serving": "SERVING",
              "elastic": "ELASTIC",
              "multichip": "MULTICHIP"}.get(args.kind, "BENCH")
    baseline_path = args.baseline or latest_bench_file(prefix=prefix)
    if baseline_path is None or not os.path.exists(baseline_path):
        print(json.dumps({"verdict": "error",
                          "error": f"no {prefix}_r*.json baseline "
                                   "found"}))
        return 2

    try:
        if args.smoke:
            verdict = smoke(baseline_path)
        else:
            if not args.run:
                print(json.dumps({"verdict": "error",
                                  "error": "--run FILE required "
                                           "(or --smoke)"}))
                return 2
            overrides: Dict[str, float] = {}
            for spec in args.tolerance_for:
                name, _, pct = spec.partition("=")
                overrides[name.strip()] = float(pct)
            verdict = compare(load_summary(baseline_path),
                              load_summary(args.run),
                              default_pct=args.tolerance,
                              overrides=overrides,
                              allow_missing=args.allow_missing)
            verdict["baseline_file"] = baseline_path
            verdict["run_file"] = args.run
    except (OSError, ValueError) as e:
        print(json.dumps({"verdict": "error", "error": str(e)}))
        return 2

    if args.kind == "multichip":
        # attribution gate: in smoke mode the pinned round itself must
        # satisfy schema + budgets (so a bad re-pin cannot be
        # committed); in run mode the candidate must
        target = baseline_path if args.smoke else args.run
        try:
            attr = _attribution_gate(load_summary(target))
        except (OSError, ValueError) as e:
            attr = {"blocks": 0, "ok": False, "violations": [
                {"metric": "*", "kind": "io", "detail": str(e)}]}
        verdict["attribution"] = attr
        if not attr["ok"]:
            verdict["verdict"] = "fail"

    if args.kind == "serving":
        # slo gate: in smoke mode the pinned round itself must carry a
        # schema-valid slo block (so a bad re-pin cannot be
        # committed); in run mode the candidate must
        target = baseline_path if args.smoke else args.run
        try:
            slo = _slo_gate(load_summary(target))
        except (OSError, ValueError) as e:
            slo = {"blocks": 0, "ok": False, "violations": [
                {"metric": "*", "kind": "io", "detail": str(e)}]}
        verdict["slo"] = slo
        if not slo["ok"]:
            verdict["verdict"] = "fail"
        # fleet gate (r04 on): same smoke-vs-run target as the slo
        # gate; pins without a fleet block pass vacuously
        try:
            fleet = _fleet_gate(load_summary(target))
        except (OSError, ValueError) as e:
            fleet = {"blocks": 0, "ok": False, "violations": [
                {"metric": "*", "kind": "io", "detail": str(e)}]}
        verdict["fleet"] = fleet
        if not fleet["ok"]:
            verdict["verdict"] = "fail"

    if args.kind == "elastic":
        # ramp gate (r02 on): smoke mode gates the pinned round (a bad
        # re-pin cannot be committed), run mode the candidate; pins
        # without a ramp block pass vacuously
        target = baseline_path if args.smoke else args.run
        try:
            ramp = _elastic_gate(load_summary(target))
        except (OSError, ValueError) as e:
            ramp = {"blocks": 0, "ok": False, "violations": [
                {"metric": "*", "kind": "io", "detail": str(e)}]}
        verdict["ramp"] = ramp
        if not ramp["ok"]:
            verdict["verdict"] = "fail"

    text = json.dumps(verdict, indent=2)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    return 0 if verdict["verdict"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())

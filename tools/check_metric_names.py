#!/usr/bin/env python
"""Metric-name lint: walk the source for ``counter(``/``gauge(``/
``histogram(`` call sites and fail on bad or conflicting names.

The metrics registry creates metrics on first use, so a typo'd or
re-typed name never errors at runtime — it silently forks a second
series. This tool makes the naming contract enforceable in CI (it runs
inside the tier-1 suite, tests/test_obs_ops.py, next to
tools/check_tier1_time.py's time budget):

- names must be ``snake_case`` (f-string call sites are checked on
  their literal parts; dotted suffixes like
  ``operator_batches_total.<kind>`` are label encodings and validated
  on the family before the first dot);
- the family must end in a unit suffix: ``_total``, ``_seconds`` or
  ``_bytes``;
- one family, one type: the same name registered as both a counter and
  a gauge (anywhere in the tree) is an error;
- **doc drift** (``docs/observability.md``): every metric family the
  doc names in backticks must exist in code (a registered family or an
  exposition-only series from ``obs/exposition.py``), and every family
  registered in code must be documented — renames and additions that
  forget the doc fail CI, not a reader.

Usage:
    python tools/check_metric_names.py [src_dir ...]   # default: presto_tpu/
    python tools/check_metric_names.py --docs PATH | --no-docs
"""
from __future__ import annotations

import argparse
import ast
import fnmatch
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

_KINDS = ("counter", "gauge", "histogram")
_SNAKE = re.compile(r"^[a-z][a-z0-9_]*(\*[a-z0-9_]*)*$")
_UNIT_SUFFIXES = ("_total", "_seconds", "_bytes")


def _name_pattern(arg: ast.expr) -> Optional[str]:
    """The metric-name argument as a string pattern: literal strings
    verbatim, f-strings with each interpolation collapsed to ``*``;
    None when the name is fully dynamic (a variable)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _check_name(pattern: str) -> Optional[str]:
    family = pattern.split(".", 1)[0]
    if not _SNAKE.match(family.replace("*", "x")):
        return f"{pattern!r}: family {family!r} is not snake_case"
    if not family.endswith(_UNIT_SUFFIXES):
        return (f"{pattern!r}: family {family!r} lacks a unit suffix "
                f"({'/'.join(_UNIT_SUFFIXES)})")
    return None


def scan_file(path: str) -> Tuple[List[Tuple[str, str, int]], List[str]]:
    """-> ([(pattern, kind, lineno)], [parse errors])."""
    with open(path, encoding="utf-8", errors="replace") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [], [f"{path}: {e}"]
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _KINDS and node.args):
            continue
        pattern = _name_pattern(node.args[0])
        if pattern is not None:
            out.append((pattern, node.func.attr, node.lineno))
    return out, []


#: doc tokens that look like a metric family (after stripping any
#: label/dotted suffix)
_DOC_FAMILY = re.compile(r"^[a-z][a-z0-9_]*_(?:total|seconds|bytes)$")

#: backticked doc tokens that share the unit-suffix shape but are SQL
#: column names, not metric families
_DOC_IGNORE = {"hbm_bytes", "peak_memory_bytes", "output_bytes",
               "arg_bytes", "temp_bytes", "generated_code_bytes",
               "mem_pool_peak_bytes"}


def exposition_families(path: str) -> Set[str]:
    """Literal sample families the Prometheus exposition constructs
    directly (``family("node_up", ...)`` in obs/exposition.py) — real
    scrape series that never pass through the registry, so the doc may
    name them without a counter()/gauge() call site existing."""
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return set()
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "family":
            pattern = _name_pattern(node.args[0])
            if pattern:
                out.add(pattern)
    return out


def doc_families(doc_path: str) -> Set[str]:
    """Backticked metric-family names in the doc: each `token` is
    stripped of label/series suffixes (``.``, ``{``, ``_bucket`` etc.
    stay — only families matching the unit-suffix shape count)."""
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    out: Set[str] = set()
    for token in re.findall(r"`([^`\n]+)`", text):
        fam = re.split(r"[.{\s(]", token.strip(), maxsplit=1)[0]
        if fam not in _DOC_IGNORE \
                and _DOC_FAMILY.match(fam.replace("*", "x")):
            out.add(fam)
    return out


def check_doc_drift(doc_path: str, code_families: Set[str],
                    expo_families: Set[str]) -> List[str]:
    """Two-way diff: doc names must exist in code (registered family or
    exposition series; f-string families compare by fnmatch), and every
    registered family must appear in the doc."""
    errors: List[str] = []
    known = code_families | expo_families
    documented = doc_families(doc_path)
    for fam in sorted(documented):
        if not any(fnmatch.fnmatch(fam, pat) or fam == pat
                   for pat in known):
            errors.append(f"{doc_path}: documents {fam!r} but no such "
                          "metric family is registered in code")
    for pat in sorted(code_families):
        if pat in documented:
            continue
        if any(fnmatch.fnmatch(fam, pat) for fam in documented):
            continue
        errors.append(f"metric family {pat!r} is registered in code "
                      f"but not documented in {doc_path}")
    return errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("src", nargs="*", default=None,
                    help="source directories (default: presto_tpu/ "
                         "next to this script's repo root)")
    ap.add_argument("--docs", default=None, metavar="PATH",
                    help="observability doc to drift-check (default: "
                         "docs/observability.md next to the repo root)")
    ap.add_argument("--no-docs", action="store_true",
                    help="skip the doc-drift check")
    args = ap.parse_args(argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = args.src or [os.path.join(repo, "presto_tpu")]

    errors: List[str] = []
    families: Dict[str, Tuple[str, str]] = {}   # family -> (kind, where)
    n_sites = 0
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__",)]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                sites, errs = scan_file(path)
                errors.extend(errs)
                for pattern, kind, lineno in sites:
                    n_sites += 1
                    where = f"{path}:{lineno}"
                    bad = _check_name(pattern)
                    if bad:
                        errors.append(f"{where}: {bad}")
                        continue
                    family = pattern.split(".", 1)[0]
                    prev = families.get(family)
                    if prev is not None and prev[0] != kind:
                        errors.append(
                            f"{where}: {family!r} registered as {kind} "
                            f"but as {prev[0]} at {prev[1]}")
                    elif prev is None:
                        families[family] = (kind, where)

    doc_path = args.docs or os.path.join(repo, "docs",
                                         "observability.md")
    if not args.no_docs and os.path.exists(doc_path):
        errors.extend(check_doc_drift(
            doc_path, set(families),
            exposition_families(os.path.join(
                repo, "presto_tpu", "obs", "exposition.py"))))

    if errors:
        for e in errors:
            print(e, file=sys.stderr)
        print(f"{len(errors)} metric-name error(s) across {n_sites} "
              f"call sites", file=sys.stderr)
        return 1
    print(f"ok: {n_sites} metric call sites, "
          f"{len(families)} families")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Metric-name lint — thin CLI shim over ``tools/analyze/registries.py``
(the one lint framework; this entry point survives for muscle memory
and the tier-1 wiring in tests/test_obs_ops.py).

Rules (enforced by the analyze package):

- metric families are ``snake_case`` with a unit suffix
  (``_total``/``_seconds``/``_bytes``, or ``_ratio`` for unitless
  0..1 fractions); dotted tails are label encodings validated on the
  family;
- one family, one type (a name can't be both counter and gauge);
- **doc drift**: every family in docs/observability.md exists in code
  (registry call site or exposition-only series), and every registered
  family is documented.

Usage:
    python tools/check_metric_names.py [src_dir ...]   # default: presto_tpu/
    python tools/check_metric_names.py --docs PATH | --no-docs
"""
from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analyze import registries  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("src", nargs="*", default=None,
                    help="source directories (default: presto_tpu/ "
                         "next to this script's repo root)")
    ap.add_argument("--docs", default=None, metavar="PATH",
                    help="observability doc to drift-check (default: "
                         "docs/observability.md next to the repo root)")
    ap.add_argument("--no-docs", action="store_true",
                    help="skip the doc-drift check")
    args = ap.parse_args(argv)
    # resolve user-given dirs against the CWD (walk_py would otherwise
    # anchor relative paths at the repo root and silently scan nothing)
    roots = [os.path.abspath(p) for p in args.src] if args.src \
        else [os.path.join(_REPO, "presto_tpu")]
    doc = None if args.no_docs else (
        args.docs or os.path.join(_REPO, "docs", "observability.md"))

    findings = registries.metric_findings(roots, _REPO, doc_path=doc)
    if findings:
        for f in findings:
            print(f.render(), file=sys.stderr)
        print(f"{len(findings)} metric-name error(s)", file=sys.stderr)
        return 1
    print("ok: metric naming, types and docs consistent "
          "(tools/analyze/registries.py)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

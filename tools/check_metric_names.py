#!/usr/bin/env python
"""Metric-name lint: walk the source for ``counter(``/``gauge(``/
``histogram(`` call sites and fail on bad or conflicting names.

The metrics registry creates metrics on first use, so a typo'd or
re-typed name never errors at runtime — it silently forks a second
series. This tool makes the naming contract enforceable in CI (it runs
inside the tier-1 suite, tests/test_obs_ops.py, next to
tools/check_tier1_time.py's time budget):

- names must be ``snake_case`` (f-string call sites are checked on
  their literal parts; dotted suffixes like
  ``operator_batches_total.<kind>`` are label encodings and validated
  on the family before the first dot);
- the family must end in a unit suffix: ``_total``, ``_seconds`` or
  ``_bytes``;
- one family, one type: the same name registered as both a counter and
  a gauge (anywhere in the tree) is an error.

Usage:
    python tools/check_metric_names.py [src_dir ...]   # default: presto_tpu/
"""
from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_KINDS = ("counter", "gauge", "histogram")
_SNAKE = re.compile(r"^[a-z][a-z0-9_]*(\*[a-z0-9_]*)*$")
_UNIT_SUFFIXES = ("_total", "_seconds", "_bytes")


def _name_pattern(arg: ast.expr) -> Optional[str]:
    """The metric-name argument as a string pattern: literal strings
    verbatim, f-strings with each interpolation collapsed to ``*``;
    None when the name is fully dynamic (a variable)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _check_name(pattern: str) -> Optional[str]:
    family = pattern.split(".", 1)[0]
    if not _SNAKE.match(family.replace("*", "x")):
        return f"{pattern!r}: family {family!r} is not snake_case"
    if not family.endswith(_UNIT_SUFFIXES):
        return (f"{pattern!r}: family {family!r} lacks a unit suffix "
                f"({'/'.join(_UNIT_SUFFIXES)})")
    return None


def scan_file(path: str) -> Tuple[List[Tuple[str, str, int]], List[str]]:
    """-> ([(pattern, kind, lineno)], [parse errors])."""
    with open(path, encoding="utf-8", errors="replace") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [], [f"{path}: {e}"]
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _KINDS and node.args):
            continue
        pattern = _name_pattern(node.args[0])
        if pattern is not None:
            out.append((pattern, node.func.attr, node.lineno))
    return out, []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("src", nargs="*", default=None,
                    help="source directories (default: presto_tpu/ "
                         "next to this script's repo root)")
    args = ap.parse_args(argv)
    roots = args.src or [os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "presto_tpu")]

    errors: List[str] = []
    families: Dict[str, Tuple[str, str]] = {}   # family -> (kind, where)
    n_sites = 0
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__",)]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                sites, errs = scan_file(path)
                errors.extend(errs)
                for pattern, kind, lineno in sites:
                    n_sites += 1
                    where = f"{path}:{lineno}"
                    bad = _check_name(pattern)
                    if bad:
                        errors.append(f"{where}: {bad}")
                        continue
                    family = pattern.split(".", 1)[0]
                    prev = families.get(family)
                    if prev is not None and prev[0] != kind:
                        errors.append(
                            f"{where}: {family!r} registered as {kind} "
                            f"but as {prev[0]} at {prev[1]}")
                    elif prev is None:
                        families[family] = (kind, where)

    if errors:
        for e in errors:
            print(e, file=sys.stderr)
        print(f"{len(errors)} metric-name error(s) across {n_sites} "
              f"call sites", file=sys.stderr)
        return 1
    print(f"ok: {n_sites} metric call sites, "
          f"{len(families)} families")
    return 0


if __name__ == "__main__":
    sys.exit(main())

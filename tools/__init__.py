"""Repo tooling (lint/CI helpers). A package so the static-analysis
plane runs as ``python -m tools.analyze``; the standalone scripts
(chaos_smoke, check_*) keep working as plain files."""

"""Lock-discipline checker: the static half of the thread-contract gate.

The engine runs five cooperating thread pools (scan prefetcher,
local-exchange producers, taskexec fair scheduler, cluster retry loop,
metrics/history sinks). Their lock discipline was previously enforced
by review comments; this checker extracts what the AST can prove and
the runtime validator (presto_tpu/_devtools/lockcheck.py) covers the
aliasing the AST can't see.

Rules:

- ``lock-cycle`` — the static lock-acquisition graph has a cycle. An
  edge A->B is recorded when lock B is acquired lexically inside a
  ``with A:`` block, or when a method known (same scanned file set) to
  acquire B is called under A. Lock identity is the ``checked_lock``
  name literal when present, else ``module.Class.attr``.
- ``unlocked-global-write`` — a store to module-level mutable state
  (``global X`` rebind, ``X[...] = ``, ``X.attr = `` on a module-level
  name) from inside a function with no lock held lexically. Reads are
  fine (single writes are atomic enough for metrics-ish reads); a
  racing WRITE is how registries lose entries.
- ``unjoined-thread`` — a ``threading.Thread(...)`` creation with no
  join on any path: a local thread whose enclosing function never
  calls ``.join``, or a ``self._thread`` whose class never joins it.
  Daemon threads that outlive their owner keep draining queues and
  touching registries through teardown — the flakes land in whichever
  test runs next.

Everything here is lexical and name-based by design: it runs in
milliseconds with zero imports, the committed baseline absorbs the
(reviewed) exceptions, and the runtime validator catches what slips
through.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .base import (Finding, add_parents, ancestors, dotted,
                   enclosing_symbol, parse_file, rel, str_const, walk_py)

CHECKER = "locks"

#: the threaded subsystems (ISSUE 7 tentpole scope) + exec/runner.py,
#: whose _state_lock the cluster plane acquires, + the serving caches
#: (ISSUE 15: they postdated the original scope and were invisible to
#: the static graph). serving/resultcache.py and server/protocol.py
#: stay runtime-validated only: the module-level self-locking RESULTS
#: object and the deliberately-daemon producer pool trip the crude
#: lexical rules here, while their checked locks feed the runtime
#: graph regardless.
SCOPE = ("presto_tpu/exec/scancache.py",
         "presto_tpu/exec/local_exchange.py",
         "presto_tpu/exec/taskexec.py",
         "presto_tpu/exec/cluster.py",
         "presto_tpu/exec/runner.py",
         "presto_tpu/obs/metrics.py",
         "presto_tpu/obs/history.py",
         "presto_tpu/serving/plancache.py",
         "presto_tpu/serving/template.py",
         "presto_tpu/serving/groups.py")

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock",
               "checked_lock", "checked_rlock"}
_THREAD_CTORS = {"threading.Thread", "Thread"}

#: method names shared with builtin containers — excluded from the
#: name-based call-through edge (a dict's .update under lock A must not
#: alias SomeRegistry.update's lock acquisitions)
_BUILTIN_METHODS = {"update", "get", "pop", "clear", "append", "add",
                    "extend", "remove", "setdefault", "keys", "values",
                    "items", "copy", "put", "insert", "discard"}


def _lock_name_from_ctor(call: ast.Call) -> Optional[str]:
    """checked_lock("name") -> its literal; plain ctor -> None (caller
    falls back to the attribute path)."""
    name = dotted(call.func) or ""
    if name.split(".")[-1] in ("checked_lock", "checked_rlock") \
            and call.args:
        return str_const(call.args[0])
    return None


class _ModuleScan:
    """Per-file lock/thread/shared-state facts."""

    def __init__(self, path: str, rpath: str):
        self.rpath = rpath
        self.module = os.path.splitext(os.path.basename(path))[0]
        self.tree = parse_file(path)
        #: 'Class.attr' (or bare 'attr' at module level) -> lock id
        self.lock_attrs: Dict[str, str] = {}
        #: method name -> set of lock ids its body acquires directly
        self.method_locks: Dict[str, Set[str]] = {}
        #: module-level assigned names (shared-state candidates)
        self.module_globals: Set[str] = set()
        if self.tree is not None:
            add_parents(self.tree)
            self._collect()

    # -- collection -----------------------------------------------------------
    def _enclosing_class(self, node: ast.AST) -> Optional[str]:
        for anc in ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc.name
        return None

    def _collect(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign):
                continue
            if isinstance(getattr(node, "parent", None), ast.Module):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_globals.add(tgt.id)
            if not isinstance(node.value, ast.Call):
                continue
            ctor = (dotted(node.value.func) or "").split(".")[-1]
            if ctor == "Condition" and node.value.args:
                # `self._cv = threading.Condition(self._lock)` — the
                # condition IS that lock; `with self._cv:` must resolve
                # to the wrapped lock's id (walk order guarantees the
                # lock's own assignment, earlier in __init__, was seen)
                lid = self.lock_id_of(node.value.args[0])
                if lid is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            cls = self._enclosing_class(tgt) or "?"
                            self.lock_attrs[f"{cls}.{tgt.attr}"] = lid
                        elif isinstance(tgt, ast.Name):
                            self.lock_attrs[tgt.id] = lid
                continue
            if ctor not in {"Lock", "RLock", "checked_lock",
                            "checked_rlock"}:
                continue
            lock_id = _lock_name_from_ctor(node.value)
            for tgt in node.targets:
                key = None
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    cls = self._enclosing_class(tgt) or "?"
                    key = f"{cls}.{tgt.attr}"
                elif isinstance(tgt, ast.Name):
                    key = tgt.id
                if key is not None:
                    self.lock_attrs[key] = (
                        lock_id or f"{self.module}.{key}")

    # -- lock-expression resolution ------------------------------------------
    def lock_id_of(self, expr: ast.expr) -> Optional[str]:
        """The lock id a ``with <expr>:`` (or ``<expr>.acquire()``)
        acquires, if <expr> names a known lock attribute."""
        d = dotted(expr)
        if d is None:
            return None
        if d.startswith("self."):
            attr = d[len("self."):]
            cls = self._enclosing_class(expr)
            return self.lock_attrs.get(f"{cls}.{attr}") \
                or self._any_class_lock(attr)
        return self.lock_attrs.get(d)

    def _any_class_lock(self, attr: str) -> Optional[str]:
        # `self._lock` used in a nested helper class we misattributed:
        # fall back to a unique attr match across classes
        hits = {v for k, v in self.lock_attrs.items()
                if k.split(".")[-1] == attr}
        return next(iter(hits)) if len(hits) == 1 else None


def _with_lock_items(scan: _ModuleScan, node: ast.With) -> List[str]:
    out = []
    for item in node.items:
        ctx = item.context_expr
        # `with self._lock:` / `with LOCK:` / `with self._cv:` (a
        # Condition built over an engine lock counts as that lock)
        lid = scan.lock_id_of(ctx)
        if lid is None and isinstance(ctx, ast.Call):
            lid = scan.lock_id_of(ctx.func) \
                if isinstance(ctx.func, ast.Attribute) else None
        if lid is not None:
            out.append(lid)
    return out


def _held_locks(scan: _ModuleScan, node: ast.AST) -> List[str]:
    """Lock ids of every enclosing ``with`` that acquires a known lock."""
    held: List[str] = []
    for anc in ancestors(node):
        if isinstance(anc, ast.With):
            held.extend(_with_lock_items(scan, anc))
    return held


def _collect_method_locks(scan: _ModuleScan) -> None:
    """method/function name -> lock ids acquired anywhere in its body
    (``with`` or ``.acquire()``)."""
    if scan.tree is None:
        return
    for node in ast.walk(scan.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        acquired: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.With):
                acquired.update(_with_lock_items(scan, sub))
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "acquire":
                lid = scan.lock_id_of(sub.func.value)
                if lid:
                    acquired.add(lid)
        if acquired:
            prev = scan.method_locks.setdefault(node.name, set())
            prev.update(acquired)


def _edges_for(scan: _ModuleScan,
               all_method_locks: Dict[str, Set[str]]
               ) -> Dict[Tuple[str, str], Tuple[str, int]]:
    """(held, acquired) -> (path, line) — direct nesting plus one level
    of call-through using the cross-file method->locks map."""
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    if scan.tree is None:
        return edges
    for node in ast.walk(scan.tree):
        if isinstance(node, ast.With):
            inner = _with_lock_items(scan, node)
            if not inner:
                continue
            held = _held_locks(scan, node)
            for h in held:
                for i in inner:
                    if h != i:
                        edges.setdefault((h, i),
                                         (scan.rpath, node.lineno))
        elif isinstance(node, ast.Call):
            held = _held_locks(scan, node)
            if not held:
                continue
            # a call made under a lock, to a method that acquires locks
            callee = None
            if isinstance(node.func, ast.Attribute):
                # skip computed receivers (``self._nodes[nid].update``
                # is a dict method, not our TaskRegistry.update) and
                # builtin-container method names — name-based matching
                # can't tell them apart; the runtime validator covers
                # real cross-object calls the AST misattributes
                if isinstance(node.func.value, (ast.Subscript, ast.Call)):
                    continue
                if node.func.attr in _BUILTIN_METHODS:
                    continue
                callee = node.func.attr
            elif isinstance(node.func, ast.Name):
                callee = node.func.id
            for lid in all_method_locks.get(callee or "", ()):
                for h in held:
                    if h != lid:
                        edges.setdefault((h, lid),
                                         (scan.rpath, node.lineno))
    return edges


def _find_cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]
                 ) -> List[List[str]]:
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    state: Dict[str, int] = {}
    path: List[str] = []
    cycles: List[List[str]] = []
    seen: Set[frozenset] = set()

    def visit(n: str) -> None:
        state[n] = 0
        path.append(n)
        for m in adj.get(n, ()):
            if state.get(m) == 0:
                cyc = path[path.index(m):] + [m]
                key = frozenset(cyc)
                if key not in seen:
                    seen.add(key)
                    cycles.append(cyc)
            elif m not in state:
                visit(m)
        path.pop()
        state[n] = 1

    for n in sorted(adj):
        if n not in state:
            visit(n)
    return cycles


# -- unjoined threads --------------------------------------------------------

def _thread_findings(scan: _ModuleScan) -> List[Finding]:
    out: List[Finding] = []
    if scan.tree is None:
        return out

    for node in ast.walk(scan.tree):
        if not (isinstance(node, ast.Call)
                and (dotted(node.func) or "").split(".")[-1] == "Thread"
                and (dotted(node.func) in _THREAD_CTORS)):
            continue
        parent = getattr(node, "parent", None)
        sym = enclosing_symbol(node)

        # `threading.Thread(...).start()` — never bound, never joined
        if isinstance(parent, ast.Attribute) and parent.attr == "start":
            out.append(Finding(
                CHECKER, "unjoined-thread", scan.rpath, node.lineno,
                f"{sym}.start",
                "Thread(...).start() is never bound — no close path "
                "can ever join it"))
            continue

        # find the name it's bound to (self.attr / local / list elem)
        attr = local = None
        for anc in ancestors(node):
            if isinstance(anc, ast.Assign):
                tgt = anc.targets[0]
                d = dotted(tgt)
                if d and d.startswith("self."):
                    attr = d[len("self."):]
                elif isinstance(tgt, ast.Name):
                    local = tgt.id
                break
            if isinstance(anc, (ast.FunctionDef, ast.ClassDef)):
                break

        if attr is not None:
            # joined anywhere in the file? (`self._thread.join(`)
            joined = \
                any(isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "join"
                    and (dotted(n.func.value) or "").endswith(attr)
                    for n in ast.walk(scan.tree))
            if not joined:
                out.append(Finding(
                    CHECKER, "unjoined-thread", scan.rpath, node.lineno,
                    f"{sym}.{attr}",
                    f"thread self.{attr} is started but no method ever "
                    f"joins it — stop/close paths must join so the "
                    f"loop can't touch shared state past teardown"))
        else:
            # local (or list-comprehended) thread: a `.join(` call on a
            # plain NAME in the same enclosing function counts — the
            # receiver must be a variable (`t.join()`, `w.join()` in a
            # loop over the thread list), so `", ".join(parts)` or
            # other non-thread joins can't mask a leaked thread
            fn = next((a for a in ancestors(node)
                       if isinstance(a, ast.FunctionDef)), None)
            haystack = fn if fn is not None else scan.tree
            joined = any(isinstance(n, ast.Call)
                         and isinstance(n.func, ast.Attribute)
                         and n.func.attr == "join"
                         and isinstance(n.func.value, ast.Name)
                         for n in ast.walk(haystack))
            if not joined:
                out.append(Finding(
                    CHECKER, "unjoined-thread", scan.rpath, node.lineno,
                    f"{sym}.{local or '<anon>'}",
                    f"thread {local or '<anonymous>'} created in "
                    f"{sym!r} has no join on any path"))
    return out


# -- unlocked shared writes --------------------------------------------------

def _global_write_findings(scan: _ModuleScan) -> List[Finding]:
    out: List[Finding] = []
    if scan.tree is None:
        return out
    #: module-level locks themselves aren't shared *state*
    skip = set(scan.module_globals) & set(scan.lock_attrs)

    for node in ast.walk(scan.tree):
        in_function = any(isinstance(a, ast.FunctionDef)
                          for a in ancestors(node))
        if not in_function:
            continue
        target: Optional[ast.expr] = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if isinstance(tgt, (ast.Subscript, ast.Attribute)):
                    base = tgt.value
                    d = dotted(base)
                    if d in scan.module_globals and d not in skip:
                        target = tgt
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("append", "add", "update",
                                       "setdefault", "pop", "clear",
                                       "extend", "remove"):
            d = dotted(node.func.value)
            if d in scan.module_globals and d not in skip:
                target = node.func
        if target is None:
            continue
        if _held_locks(scan, node):
            continue
        d = dotted(target.value if isinstance(
            target, (ast.Subscript, ast.Attribute)) else target) or "?"
        sym = enclosing_symbol(node)
        out.append(Finding(
            CHECKER, "unlocked-global-write", scan.rpath, node.lineno,
            f"{sym}.{d}",
            f"write to module-level {d!r} from {sym!r} with no lock "
            f"held — racing writes drop entries silently"))
    return out


# -- entry points ------------------------------------------------------------

def check_paths(paths: Sequence[str], root: str) -> List[Finding]:
    scans = [_ModuleScan(p, rel(p, root)) for p in paths]
    out: List[Finding] = []

    all_method_locks: Dict[str, Set[str]] = {}
    for s in scans:
        _collect_method_locks(s)
        for m, locks in s.method_locks.items():
            all_method_locks.setdefault(m, set()).update(locks)

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for s in scans:
        if s.tree is None:
            out.append(Finding(CHECKER, "parse-error", s.rpath, 1,
                               "<module>", "file does not parse"))
            continue
        for k, v in _edges_for(s, all_method_locks).items():
            edges.setdefault(k, v)
        out.extend(_thread_findings(s))
        out.extend(_global_write_findings(s))

    for cyc in _find_cycles(edges):
        where, line = edges.get((cyc[0], cyc[1]), ("<multiple>", 0))
        out.append(Finding(
            CHECKER, "lock-cycle", where, line,
            "->".join(sorted(set(cyc))),
            "lock-order cycle in the static acquisition graph: "
            + " -> ".join(cyc)))
    return out


def check(root: str, scope: Sequence[str] = SCOPE) -> List[Finding]:
    return check_paths(sorted(set(walk_py(root, scope))), root)
